"""Node-axis (TP) sharding of the live engine: the HBM-scaling path.

When the task table outgrows one chip (it dominates world memory:
``T = n_users * max_sends`` rows × ~17 columns), the per-task and per-user
arrays shard row-wise across the mesh and ONE world's population spans
every device — the capacity half of the ROADMAP north star (FogMQ's
internet-scale-broker regime, arXiv:1610.00620), vs. replica-DP
(:mod:`fognetsimpp_tpu.parallel.fleet`), which only multiplies
independent worlds.

Two implementations live here:

* **The explicit shard_map TP tick** (:func:`run_tp_sharded`) — the
  measured production path for the dense-broker family
  (:func:`fognetsimpp_tpu.core.engine.tp_ok`).  Each engine megaphase
  runs shard-local on a LOCAL world view (a spec with ``n_users = U/n``
  and locally sliced user/task/node rows; fog, broker, metrics and PRNG
  state replicated), with hand-placed broker↔fog collectives exactly
  where a global view is genuinely needed:

  - *spawn/connect*: zero collectives (full-width PRNG draws sliced per
    shard keep the reference bit pattern — ``engine._tp_user_draw``);
  - *dense broker decide*: zero collectives for the decision itself
    (the scalar winner is a pure function of the replicated broker
    view); one ``psum`` for the global per-topic fan-out counts;
  - *fog completions*: one ``psum``-combine per pass gathering the
    (MIPS, queue-entry-time) columns of the F global task ids the
    replicated fog state points at — each id is owned by exactly one
    shard, so masked-local-gather + psum IS the gather;
  - *fog arrivals*: the cross-device exchange — each shard's compacted
    arrival candidates ride a ring of ``lax.ppermute`` neighbor hops
    (N-1 steps; opt-in Pallas remote-DMA ring kernel,
    ``ops/pallas_kernels.ring_all_gather``) into a replicated global
    window, on which every shard runs the reference assignment/FIFO
    tail identically — so the replicated fog/queue state stays
    bit-coherent without locks; task-table writes land only on the
    owning shard (drop-scatter on out-of-shard rows).  Saturated-fog
    tail-drops are decided shard-local (one ``psum`` for the per-fog
    busy/count sums) and never occupy exchange slots.  A WINDOWED spec
    (``arrival_window=K < task_capacity``) switches the exchange to
    distributed top-K selection (:func:`ring_topk_merge`): each shard
    pre-selects its K best candidates in the engine's rotated global
    scan order, every hop merges the incoming neighbor window and
    truncates back to K, and the assembled window is bit-identical to
    compacting the full global candidate list — per-hop payload is
    O(K) packed slots instead of O(total candidates);
  - *counters*: ONE end-of-tick ``psum`` folds every shard-partial
    scalar (metrics deltas + broker message counters) into the
    replicated totals.

  Results are bit-identical to the single-device engine
  (tests/test_tp.py state-hash A/B), and ``tools/hloaudit`` proves the
  compiled tick contains exactly the collectives declared in
  :data:`DECLARED_COLLECTIVES` with the per-tick count pinned by
  ``tools/op_budget.py``.

* **The GSPMD fallback** (:func:`run_node_sharded` for worlds outside
  the TP family) — the original "unmodified engine under the SPMD
  partitioner" path: correct for every world the engine runs (windowed
  compaction, mobility, POOL fogs ...), but with XLA choosing the
  communication.  :func:`run_node_sharded` dispatches: TP-eligible
  specs take the explicit tick, the rest keep GSPMD.

Division of labour with the other axes: replica-DP is the *throughput*
path (zero collectives); this module is the *capacity* path (per-device
task memory = T / n_devices, paying the arrival exchange per tick).
"""
from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.engine import (
    TickBuf,
    TpCtx,
    _arrival_candidates,
    _compact,
    _compact_lane_width,
    _finalize_derived_acks,
    _per_fog,
    _phase_adverts,
    _phase_broker_dense,
    _phase_connect,
    _phase_periodic_adverts,
    _phase_spawn,
    _phase_spawn_multi,
    _STATIC_MAC_ERR,
    _ST_DONE,
    _ST_DROPPED,
    _ST_QUEUED,
    _ST_RUNNING,
    _ST_TASK_INFLIGHT,
    _svc_time,
    run,
    tp_ok,
    tp_reject_reason,
)
from ..dynspec import (
    DynSpec,
    apply_knobs,
    promote_default,
    registry_note,
    split_spec,
)
from ..net.mobility import MobilityBounds
from ..net.topology import LinkCache, NetParams, associate
from ..ops.queues import (
    NO_TASK,
    batched_enqueue,
    batched_pop,
    plan_arrivals,
    topk_merge_sorted,
)
from ..spec import WorldSpec
from ..state import Metrics, NodeState, TaskState, UserState, WorldState
from ..telemetry.health import latency_hist_delta
from ..telemetry.journeys import journey_tick_tp
from ..telemetry.metrics import (
    PHASE_INDEX,
    PHASES,
    accumulate_exchange,
    accumulate_tick,
    init_exchange_leaves,
    tick_activity,
)
from .mesh import replica_sharding
from .tp import shard_map

NODE_AXIS = "node"

#: Collectives the compiled TP tick is ALLOWED to contain, keyed by the
#: op_name scope they must attribute to — the contract ``tools/hloaudit``
#: enforces on the compiled artifact (audit rule A3).  The sharded tick
#: emits exactly two families inside the shard_map body: the ``psum``
#: combines (fan-out counts, completion-gathers, fast-drop sums, the
#: end-of-tick counter fold → ``all-reduce``) and the arrival-exchange
#: ring (``lax.ppermute`` neighbor hops → ``collective-permute``).
#: Anything else (a GSPMD resharding all-to-all, an accidental
#: all-gather from a leaked annotation) is a fatal CI finding.  Extend
#: this table in the same change that adds a collective.
DECLARED_COLLECTIVES = {
    "shmap_body": {"all-reduce", "collective-permute"},
}

_METRIC_FIELDS = tuple(f.name for f in dataclasses.fields(Metrics))


class ExgStats(NamedTuple):
    """One shard's per-tick exchange-plane scalars (ISSUE 11).

    Computed by :func:`_tp_fog_arrivals` when the telemetry plane is
    on; the end-of-tick telemetry fold assembles the per-shard vectors
    (one-hot columns + one ``psum``) and
    :func:`telemetry.metrics.accumulate_exchange` books them.
    """

    occ: jax.Array  # () f32 window occupancy fraction (n_set / K; > 1
    #   means overflow -> deferral)
    util: jax.Array  # () f32 ppermute payload utilization (seated / K)
    age: jax.Array  # () f32 max tick-age of a deferred candidate
    cand: jax.Array  # () f32 integer-valued candidate-production count
    defer: jax.Array  # () f32 integer-valued deferred-at-window count
    seated: jax.Array  # () i32 slots seated in the exchange window


# ----------------------------------------------------------------------
# population padding (arbitrary user counts on a fixed mesh)
# ----------------------------------------------------------------------

def pad_users_to_multiple(
    spec: WorldSpec, state: WorldState, net: NetParams, n: int
) -> Tuple[WorldSpec, WorldState, NetParams]:
    """Pad the user population up to a multiple of ``n`` with INERT rows.

    Padded users are unregistered ghosts: never started (``start_t`` =
    +inf), non-publishers, unconnected, with all their task rows
    ``Stage.UNUSED``/``NO_TASK`` — no phase can ever touch them, so the
    real users' dynamics are exactly those of the same spec at the
    padded population (tests/test_tp.py pins the inertness).  The net
    gains matching unattached node rows (attach = -1).

    Spawn-stream note: PRNG draws are shaped ``(n_users,)``, so padding
    changes the per-user random stream vs the unpadded world — the same
    (documented) caveat as ``max_sends_per_tick > 1``.  Scenario anchors
    pinned to committed traces use divisible populations.
    """
    U = spec.n_users
    pad = (-U) % n
    if pad == 0:
        return spec, state, net
    if spec.learn_active:
        raise ValueError(
            "pad_users_to_multiple does not extend per-task learner "
            "state; pick a divisible population for learned policies"
        )
    S = spec.max_sends_per_user
    U2 = U + pad
    spec2 = dataclasses.replace(spec, n_users=U2).validate()
    f32, i32 = jnp.float32, jnp.int32

    def ins_nodes(x, fill):
        blk = jnp.full((pad,) + x.shape[1:], fill, x.dtype)
        return jnp.concatenate([x[:U], blk, x[U:]], axis=0)

    nd = state.nodes
    nodes = NodeState(
        kind=ins_nodes(nd.kind, 0),  # NodeKind.USER
        pos=ins_nodes(nd.pos, 0.0),
        alive=ins_nodes(nd.alive, True),
        mobility=ins_nodes(nd.mobility, 0),
        vel=ins_nodes(nd.vel, 0.0),
        circle_center=ins_nodes(nd.circle_center, 0.0),
        circle_radius=ins_nodes(nd.circle_radius, 0.0),
        circle_omega=ins_nodes(nd.circle_omega, 0.0),
        circle_phase=ins_nodes(nd.circle_phase, 0.0),
        energy=ins_nodes(nd.energy, spec.energy_capacity_j),
        energy_capacity=ins_nodes(nd.energy_capacity, spec.energy_capacity_j),
        has_energy=ins_nodes(nd.has_energy, False),
        link_backlog=ins_nodes(nd.link_backlog, 0.0),
        link_drop_p=ins_nodes(nd.link_drop_p, 0.0),
        tx_count=ins_nodes(nd.tx_count, 0),
        rx_count=ins_nodes(nd.rx_count, 0),
        assoc_sum=ins_nodes(nd.assoc_sum, 0),
    )

    def app_users(x, fill):
        blk = jnp.full((pad,) + x.shape[1:], fill, x.dtype)
        return jnp.concatenate([x, blk], axis=0)

    us = state.users
    users = UserState(
        next_send=app_users(us.next_send, jnp.inf),
        send_count=app_users(us.send_count, 0),
        send_interval=app_users(us.send_interval, spec.send_interval),
        connected=app_users(us.connected, False),
        start_t=app_users(us.start_t, jnp.inf),
        connack_at=app_users(us.connack_at, jnp.inf),
        publisher=app_users(us.publisher, False),
        pub_topic=app_users(us.pub_topic, 0),
        sub_mask=app_users(us.sub_mask, False),
        n_delivered=app_users(us.n_delivered, 0),
    )

    def app_tasks(x, fill):
        blk = jnp.full((pad * S,) + x.shape[1:], fill, x.dtype)
        return jnp.concatenate([x, blk], axis=0)

    tk = state.tasks
    tasks = TaskState(
        stage=app_tasks(tk.stage, 0),  # Stage.UNUSED
        user=jnp.repeat(jnp.arange(U2, dtype=i32), S),
        fog=app_tasks(tk.fog, NO_TASK),
        mips_req=app_tasks(tk.mips_req, 0.0),
        t_create=app_tasks(tk.t_create, jnp.inf),
        t_at_broker=app_tasks(tk.t_at_broker, jnp.inf),
        t_at_fog=app_tasks(tk.t_at_fog, jnp.inf),
        t_service_start=app_tasks(tk.t_service_start, jnp.inf),
        t_complete=app_tasks(tk.t_complete, jnp.inf),
        t_q_enter=app_tasks(tk.t_q_enter, jnp.inf),
        t_ack3=app_tasks(tk.t_ack3, jnp.inf),
        t_ack4_fwd=app_tasks(tk.t_ack4_fwd, jnp.inf),
        t_ack4_queued=app_tasks(tk.t_ack4_queued, jnp.inf),
        t_ack5=app_tasks(tk.t_ack5, jnp.inf),
        t_ack6=app_tasks(tk.t_ack6, jnp.inf),
        queue_time_ms=app_tasks(tk.queue_time_ms, jnp.inf),
        req_open=app_tasks(tk.req_open, 0),
    )

    net2 = net.replace(
        node_attach=ins_nodes(net.node_attach, -1),  # unattached ghosts
        node_acc=ins_nodes(net.node_acc, 0.0),
        is_wireless=ins_nodes(net.is_wireless, False),
        ap_nodes=jnp.where(
            net.ap_nodes >= U, net.ap_nodes + pad, net.ap_nodes
        ),
    )
    state2 = state.replace(
        nodes=nodes, users=users, tasks=tasks,
    )
    if spec.telemetry and spec.telemetry_hist:
        # the per-task exactly-once flag grows with the task table:
        # ghost rows stay UNUSED forever, so their flags stay 0 and the
        # histogram never sees them (tests/test_tp_telemetry.py)
        state2 = state2.replace(
            telem=state2.telem.replace(
                lat_seen=jnp.concatenate(
                    [
                        state.telem.lat_seen,
                        jnp.zeros((pad * S,), jnp.int8),
                    ]
                )
            )
        )
    # journey rings (ISSUE 15) survive padding UNCHANGED by design:
    # the leaves are J-sized (never task-capacity-sized), the sampled
    # task ids keep addressing the same (user, send) slots because
    # ghost task rows append at the END of the table, and ghost rows
    # stay UNUSED forever so the per-tick diff can never fire on them.
    # dynspec.bucket_spec relies on this — a bucketed journey world
    # keeps its original sample (tests/test_journeys.py pins it).  The
    # TP runner tiles these J-sized leaves per shard (_tp_setup) and
    # each shard diffs only its owned slots (journey_tick_tp), so the
    # padded sample shards exactly like the unpadded one.
    _ = f32  # (dtype alias kept for symmetry with init_state)
    return spec2, state2, net2


def stamp_tp_telemetry(
    spec: WorldSpec, state: WorldState, n: int
) -> Tuple[WorldSpec, WorldState]:
    """Stamp the shard axis on a telemetry-on world (ISSUE 11).

    Sets ``spec.tp_shards`` and sizes the per-shard exchange-plane
    telemetry leaves (:func:`telemetry.metrics.init_exchange_leaves`)
    so the stamped spec describes the stamped state.  Idempotent — a
    chained call with an already-stamped pair changes nothing — and a
    no-op with the telemetry plane off.  The ONE stamping sequence
    shared by :func:`_tp_setup` and ``telemetry.live.serve_tp_run``;
    the population must already divide over ``n``
    (:func:`pad_users_to_multiple`).
    """
    if not spec.telemetry:
        return spec, state
    if spec.tp_shards != n:
        spec = dataclasses.replace(spec, tp_shards=n).validate()
    if state.telem.exg_cand_sum.shape[0] != n:
        state = state.replace(
            telem=state.telem.replace(**init_exchange_leaves(spec))
        )
    R = min(spec.arrival_cands, spec.max_sends_per_user)
    cap = (spec.n_users // n) * R
    if cap >= 2 ** 24:
        # the exchange gauges ride an f32 one-hot psum: per-tick
        # candidate counts must stay exact integers in f32 (the
        # engine._fused_mips_exact discipline, simlint R10)
        raise ValueError(
            f"per-shard candidate capacity {cap} >= 2^24: the "
            "telemetry exchange fold loses f32 integer exactness — "
            "run telemetry off at this shape or raise the shard count"
        )
    return spec, state


def unstamp_tp_carry(
    spec: WorldSpec, state: WorldState
) -> Tuple[WorldSpec, WorldState]:
    """Gather a row-sharded TP chunk-boundary carry onto the default
    device and re-describe it with the UNSHARDED spec — the fork point
    of the TP what-if rail (ISSUE 20).

    The what-if grid vmaps ONE device-resident carry over the knob rows
    (:func:`fognetsimpp_tpu.parallel.sweep.fork_state`), so the TP
    carry must leave the mesh: one host gather, ``tp_shards`` back to
    0, and the per-shard exchange-plane telemetry leaves re-initialized
    at the unsharded (zero-row) shape — the exchange gauges describe
    the sharded execution substrate, not the forked world, and the
    what-if report reads counter DELTAS that never cross the fork.
    Padded users stay: they are inert rows, and keeping them means the
    forked population equals the population the session actually ran.
    """
    state = jax.tree.map(jnp.asarray, jax.device_get(state))
    if spec.tp_shards:
        spec = dataclasses.replace(spec, tp_shards=0).validate()
        if spec.telemetry:
            state = state.replace(
                telem=state.telem.replace(**init_exchange_leaves(spec))
            )
    return spec, state


# ----------------------------------------------------------------------
# ring arrival exchange
# ----------------------------------------------------------------------

def ring_all_gather(x: jax.Array, axis_name: str, n_shards: int) -> jax.Array:
    """Assemble every shard's block along axis 0, in GLOBAL shard order,
    via ``n-1`` nearest-neighbor ``lax.ppermute`` hops (ring all-gather).

    The portable default for the TP arrival exchange (SNIPPETS [2] is
    the Pallas remote-DMA rendition of this exact pattern —
    ``ops/pallas_kernels.ring_all_gather_pallas`` is the opt-in TPU
    kernel; ``FNS_PALLAS_RING=1``).  Each step sends the block received
    last step to the right neighbor, so after ``n-1`` hops every shard
    has written block ``j`` of shard ``j`` at offset ``j * K`` — the
    concatenation order is shard-major, which for row-sharded user
    blocks IS the global user-major order the reference window uses.
    """
    if n_shards == 1:
        return x
    from ..ops.pallas_kernels import (
        pallas_ring_applicable,
        ring_all_gather_pallas,
    )

    if pallas_ring_applicable(x.ndim, n_shards):
        return ring_all_gather_pallas(x, axis_name, n_shards)
    K = x.shape[0]
    me = jax.lax.axis_index(axis_name)
    out = jnp.zeros((n_shards * K,) + x.shape[1:], x.dtype)
    out = jax.lax.dynamic_update_slice_in_dim(out, x, me * K, axis=0)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    blk = x
    for s in range(1, n_shards):
        blk = jax.lax.ppermute(blk, axis_name, perm)
        src = (me - s) % n_shards  # the block is s hops from home
        out = jax.lax.dynamic_update_slice_in_dim(out, blk, src * K, axis=0)
    return out


def ring_topk_merge(win: jax.Array, axis_name: str, n_shards: int) -> jax.Array:
    """Distributed top-K selection over the exchange ring.

    ``win`` is this shard's ``(K, W)`` i32 payload window, sorted
    ascending on its LAST column (the globally-unique scan-order
    position key; padding rows are bit-identical max-key sentinels).
    Each of the ``n-1`` ``lax.ppermute`` hops forwards the block
    RECEIVED last hop (the original shard windows circulate — never the
    accumulator, which would double-merge) and folds it into the running
    window via :func:`ops.queues.topk_merge_sorted`, truncating back to
    K rows — so the per-hop payload stays O(K) packed slots where
    :func:`ring_all_gather` ships O(n*K).  After ``n-1`` hops every
    shard has merged all ``n`` windows; unique keys make the merged
    K-set order-independent, so the result replicates bit-coherently
    without a final broadcast, and it equals the best-K prefix of
    sorting the full gather (tests/test_tp.py A/Bs it against
    ``ring_all_gather`` + sort).
    """
    if n_shards == 1:
        return win
    from ..ops.pallas_kernels import pallas_ring_applicable

    # the remote-DMA ring kernel gathers; it has no per-hop merge stage,
    # so FNS_PALLAS_RING=1 must visibly decline here rather than hand
    # back an (n*K, W) block where the caller expects (K, W)
    assert not pallas_ring_applicable(win.ndim, n_shards, merged=True)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    acc = win
    blk = win
    for _ in range(1, n_shards):
        blk = jax.lax.ppermute(blk, axis_name, perm)
        acc = topk_merge_sorted(acc, blk)
    return acc


def _bits(x: jax.Array) -> jax.Array:
    """f32 -> i32 bit pattern (pack floats into the one exchange array)."""
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def _floats(x: jax.Array) -> jax.Array:
    return jax.lax.bitcast_convert_type(x, jnp.float32)


# ----------------------------------------------------------------------
# TP fog megaphases (replicated fog state, shard-owned task rows)
# ----------------------------------------------------------------------

def _loc_idx(idx_g: jax.Array, tp: TpCtx, t_loc: int) -> jax.Array:
    """Global task ids -> local scatter targets (sentinel = ``t_loc``).

    Rows owned by another shard map to the one-past-the-end sentinel so
    drop-mode scatters discard them — each global row is written by
    exactly its owner, and every shard computes the identical values,
    so the union of the shards' writes is the reference's single write.
    """
    loc = idx_g - tp.t_off
    owned = (loc >= 0) & (loc < t_loc)
    return jnp.where(owned, loc, t_loc)


def _gather_psum(tp: TpCtx, rows: list, idx_g: jax.Array, t_loc: int):
    """Gather task-table columns at GLOBAL ids across the mesh.

    ``rows`` is a list of (T_loc,) local columns; returns the stacked
    (len(rows), F) gathered values.  Each id is owned by exactly one
    shard: the owner contributes the value, everyone else contributes
    0, and one ``psum`` is the gather (x + 0 = x in f32: exact).
    """
    loc = idx_g - tp.t_off
    owned = (loc >= 0) & (loc < t_loc)
    locc = jnp.clip(loc, 0, t_loc - 1)
    vals = jnp.stack([jnp.where(owned, r[locc], 0.0) for r in rows])
    return jax.lax.psum(vals, tp.axis_name)


def _tp_completions(
    spec: WorldSpec, tp: TpCtx, state: WorldState, cache: LinkCache,
    buf_p: TickBuf, buf_r: TickBuf, m_rep: Metrics, t1: jax.Array,
):
    """TP rendition of ``engine._phase_completions`` (FIFO release).

    Same formulas, same masks; the two task-table reads the replicated
    fog state needs (the finished/promoted tasks' MIPS and the head's
    queue-entry time) come through ONE ``psum`` gather, and the task
    writes land on the owning shard only.  ``buf_p``/``buf_r`` split
    the reference's counters into shard-partial (per-user acks) and
    replicated (fog/broker totals) halves.
    """
    tasks, fogs, b = state.tasks, state.fogs, state.broker
    F, U = spec.n_fogs, spec.n_users
    S = spec.max_sends_per_user
    T_loc = spec.task_capacity
    T_g = tp.n_users_global * S
    i32 = jnp.int32
    fog_alive = state.nodes.alive[U : U + F]

    comp = (fogs.current_task != NO_TASK) & (fogs.busy_until <= t1) & fog_alive
    done_task = jnp.where(comp, fogs.current_task, T_g)  # global ids
    t_done = fogs.busy_until

    # FIFO head (pure function of the replicated ring) hoisted before
    # the busy bookkeeping so both gathers share one psum
    head, q_head, q_len = batched_pop(fogs.queue, fogs.q_head, fogs.q_len, comp)
    head_s = jnp.where(head == NO_TASK, T_g, head)

    gathered = _gather_psum(
        tp,
        [tasks.mips_req, tasks.t_q_enter],
        jnp.concatenate([done_task, head_s]),
        T_loc,
    )  # (2, 2F): both columns gathered at the [done | head] id vector
    mips_done = gathered[0, :F]
    mips_head = gathered[0, F:]
    tq_head = gathered[1, F:]

    user_of = jnp.clip(done_task, 0, T_g - 1) // S  # global users
    d_fb = cache.d2b[U : U + F]
    d_bu = tp.d2b_full[user_of]
    t_ack6 = t_done + d_fb + d_bu

    svc_done = _svc_time(spec, mips_done, fogs.mips)

    done_loc = _loc_idx(done_task, tp, T_loc)
    tasks = tasks.replace(
        t_complete=tasks.t_complete.at[done_loc].set(
            jnp.where(comp, t_done, 0), mode="drop"
        ),
    )
    if not spec.derive_acks:
        tasks = tasks.replace(
            t_ack6=tasks.t_ack6.at[done_loc].set(
                jnp.where(comp, t_ack6, 0), mode="drop"
            ),
        )
    busy_time = jnp.where(comp, fogs.busy_time - svc_done, fogs.busy_time)

    promoted = comp & (head != NO_TASK)
    svc_new = _svc_time(spec, mips_head, fogs.mips)
    prom_loc = _loc_idx(jnp.where(promoted, head_s, T_g), tp, T_loc)
    # ONE stage scatter for completed + promoted rows (disjoint sets)
    scat_stage = jnp.concatenate([done_loc, prom_loc])
    stage_vals = jnp.concatenate(
        [jnp.full((F,), _ST_DONE), jnp.full((F,), _ST_RUNNING)]
    )
    tasks = tasks.replace(
        stage=tasks.stage.at[scat_stage].set(stage_vals, mode="drop"),
        t_service_start=tasks.t_service_start.at[prom_loc].set(
            jnp.where(comp, t_done, 0), mode="drop"
        ),
    )
    if not spec.derive_acks:
        tasks = tasks.replace(
            queue_time_ms=tasks.queue_time_ms.at[prom_loc].set(
                jnp.where(promoted, (t_done - tq_head) * 1e3, 0),
                mode="drop",
            ),
        )
    fogs = fogs.replace(
        busy_time=busy_time,
        current_task=jnp.where(
            comp, jnp.where(promoted, head, NO_TASK), fogs.current_task
        ),
        busy_until=jnp.where(
            comp, jnp.where(promoted, t_done + svc_new, jnp.inf),
            fogs.busy_until,
        ),
        free_since=jnp.where(comp & ~promoted, t_done, fogs.free_since),
        q_head=q_head,
        q_len=q_len,
    )
    if spec.adv_on_completion:
        b = b.replace(
            adv_val_mips=jnp.where(comp, fogs.mips, b.adv_val_mips),
            adv_val_busy=jnp.where(comp, busy_time, b.adv_val_busy),
            adv_arrive_t=jnp.where(comp, t_done + d_fb, b.adv_arrive_t),
        )
    n_comp = jnp.sum(comp.astype(i32))  # replicated total
    m_rep = m_rep.replace(n_completed=m_rep.n_completed + n_comp)
    n_adv = n_comp if spec.adv_on_completion else 0
    buf_r = buf_r._replace(
        tx_f=buf_r.tx_f
        + comp.astype(i32) * (2 if spec.adv_on_completion else 1),
        tx_b=buf_r.tx_b + n_comp,
        rx_b=buf_r.rx_b + n_comp + n_adv,
    )
    # per-user ack relay: only this shard's users land in its rx_u
    u_loc = user_of - tp.u_off
    u_ok = (u_loc >= 0) & (u_loc < U)
    buf_p = buf_p._replace(
        rx_u=buf_p.rx_u.at[jnp.where(u_ok, u_loc, U)].add(
            (comp & u_ok).astype(i32), mode="drop"
        )
    )
    state = state.replace(tasks=tasks, fogs=fogs, broker=b)
    return state, buf_p, buf_r, m_rep


def _tp_fog_arrivals(
    spec: WorldSpec, tp: TpCtx, state: WorldState, cache: LinkCache,
    buf_p: TickBuf, buf_r: TickBuf, m_part: Metrics, m_rep: Metrics,
    t1: jax.Array, k_exchange: int, window_k: Optional[int],
):
    """TP rendition of the two-stage fog-arrival megaphase.

    Front (shard-local): the per-user candidate reduction
    (``engine._arrival_candidates`` — literally the reference code on
    the local user block), the saturated-fog tail-drop decision against
    the replicated fog state (per-fog busy/count sums combined with one
    ``psum``), and compaction of the surviving candidates into the
    fixed ``k_exchange`` window (overflow defers a tick, counted in
    ``n_deferred`` — the engine's established windowed contract).

    Exchange: the packed (slot, fog, time, MIPS) columns ride the ring
    (:func:`ring_all_gather`) into a replicated global window whose
    valid rows sit in global candidate order (shard-major blocks of
    ascending local order = ascending global order), so every relative
    tie-break matches the reference window exactly.

    ``window_k`` (a WINDOWED spec: ``spec.window < task_capacity``)
    replaces that full gather with distributed top-K selection: every
    candidate gets the engine's rotated scan-order position as an
    explicit integer key (``pos`` below — the rank ``_compact`` would
    assign it in the GLOBAL candidate list, tick-keyed rotation
    included), each shard ``lax.top_k``-selects its best ``K`` rows,
    and :func:`ring_topk_merge` folds the ``n`` shard windows into the
    globally-best K with an O(K) per-hop payload.  Position keys are
    globally unique, so the merged window is bit-identical to the
    reference's ``_compact`` over the full candidate list — same rows,
    same order, same tie-breaks — and window overflow defers exactly
    like the single-device K-window (``n_deferred``; seating is decided
    by ``pos <=`` the merged window's max key, which needs no second
    collective).

    Tail (replicated): the reference assignment/FIFO logic verbatim on
    the assembled window — identical on every shard, which is what
    keeps the fog/queue state coherent — with task-table writes mapped
    to the owning shard and per-user acks to the owning shard's bucket.
    """
    tasks, fogs = state.tasks, state.fogs
    F = spec.n_fogs
    U, S = spec.n_users, spec.max_sends_per_user
    T_loc = spec.task_capacity
    T_g = tp.n_users_global * S
    R = min(spec.arrival_cands, S)
    i32 = jnp.int32
    f32 = jnp.float32
    fog_alive = state.nodes.alive[U : U + F]

    st2 = tasks.stage.reshape(U, S)
    taf2 = tasks.t_at_fog.reshape(U, S)
    fog2 = tasks.fog.reshape(U, S)
    mip2 = tasks.mips_req.reshape(U, S)
    kk = jnp.arange(S, dtype=i32)[None, :]

    cks, cts, cfs, cms, cvs, n_left = _arrival_candidates(
        st2, taf2, fog2, mip2, t1, R
    )
    telem_on = spec.telemetry
    UR = U * R
    cand_k = jnp.stack(cks, axis=1).reshape(UR)
    cand_t = jnp.stack(cts, axis=1).reshape(UR)
    cand_f = jnp.stack(cfs, axis=1).reshape(UR)
    cand_m = jnp.stack(cms, axis=1).reshape(UR)
    cand_v = jnp.stack(cvs, axis=1).reshape(UR)
    cand_u = jnp.repeat(jnp.arange(U, dtype=i32), R)
    cand_slot_g = cand_u * S + cand_k + tp.t_off  # GLOBAL task ids
    # exchange-plane telemetry: this shard's candidate production,
    # counted BEFORE the saturated-fog fast drop (the drop is part of
    # what the gauge should make visible)
    n_cand = jnp.sum(cand_v.astype(i32)) if telem_on else None

    # ---- saturated-fog fast drop (local decision, psum'd fog sums) ----
    droppy = (
        (fogs.q_len >= spec.queue_capacity)
        & (fogs.current_task != NO_TASK)
        & fog_alive
    )
    memb = (
        cand_f[None, :] == jnp.arange(F, dtype=i32)[:, None]
    ) & cand_v[None, :]  # (F, UR)
    memb_f = memb.astype(f32)
    droppy_c = droppy.astype(f32) @ memb_f > 0.5
    fast_drop = cand_v & droppy_c
    rhs = jnp.stack(
        [fast_drop.astype(f32), jnp.where(fast_drop, cand_m, 0.0)], axis=1
    )  # (UR, 2)
    sums_fd = jax.lax.psum(memb_f @ rhs, tp.axis_name)  # (F, 2): the
    #   global tail-drop count/MIPS sums (exact f32 integers < 2^24, so
    #   the cross-shard add order cannot change a bit)
    n_fast_f = sums_fd[:, 0].astype(i32)
    svc_fast_f = sums_fd[:, 1] / jnp.maximum(fogs.mips, 1e-9)
    fogs = fogs.replace(
        busy_time=fogs.busy_time + svc_fast_f,
        q_drops=fogs.q_drops + n_fast_f,
    )
    n_fast = jnp.sum(n_fast_f)
    # stage -> DROPPED densely over the local (U, S) view
    fast2 = fast_drop.reshape(U, R)
    sel_fast = jnp.zeros((U, S), bool)
    for r in range(R):
        sel_fast = sel_fast | ((kk == cks[r][:, None]) & fast2[:, r : r + 1])
    tasks = tasks.replace(
        stage=jnp.where(sel_fast, _ST_DROPPED, st2).reshape(T_loc)
    )
    cand_v = cand_v & ~fast_drop

    m_part = m_part.replace(n_deferred=m_part.n_deferred + n_left)
    n_set = jnp.sum(cand_v.astype(i32))

    if window_k is not None:
        # ---- distributed K-window selection (windowed spec) ------------
        # Every candidate's GLOBAL scan-order position under the
        # engine's windowed compaction, as an explicit integer key:
        # ``_compact(cand_v_global, K, UR_g, rot)`` scans blocks in
        # rotated order (rot_b first) and each block's columns from the
        # decorrelated origin c0, so the rank it would assign global
        # candidate g is exactly ``pos`` below — elementwise over the
        # local block, no global materialization.  rot reproduces
        # ``engine._rot_and_defer`` (modulus = GLOBAL task capacity;
        # state.tick is replicated, so every shard keys identically).
        K_w = window_k
        UR_g = tp.n_users_global * R
        C_g = _compact_lane_width(UR_g)
        B_g = -(-UR_g // C_g)
        maxpos = B_g * C_g
        rot = (
            (state.tick.astype(jnp.uint32) * jnp.uint32(2654435761))
            % jnp.uint32(T_g)
        ).astype(i32)
        rot_b = rot % B_g
        c0 = (
            (rot.astype(jnp.uint32) * jnp.uint32(7919)) % jnp.uint32(C_g)
        ).astype(i32)
        g = jnp.arange(UR, dtype=i32) + tp.u_off * R
        pos = ((g // C_g - rot_b) % B_g) * C_g + ((g % C_g - c0) % C_g)
        # local best-K in ascending pos: top_k on the flipped key (valid
        # keys >= 1; invalid rows sink to -1 and become sentinels)
        k_loc = min(K_w, UR)
        vals, sel = jax.lax.top_k(jnp.where(cand_v, maxpos - pos, -1), k_loc)
        valid_w = vals > 0
        win = jnp.stack(
            [
                jnp.where(valid_w, cand_slot_g[sel], T_g),
                jnp.where(valid_w, cand_f[sel], 0),
                _bits(jnp.where(valid_w, cand_t[sel], jnp.inf)),
                _bits(jnp.where(valid_w, cand_m[sel], 0.0)),
                jnp.where(valid_w, pos[sel], maxpos),
            ],
            axis=1,
        )  # (k_loc, 5) i32 — the O(K) per-hop ring payload
        if k_loc < K_w:
            sent = jnp.stack(
                [
                    jnp.int32(T_g),
                    jnp.int32(0),
                    _bits(jnp.float32(jnp.inf)),
                    _bits(jnp.float32(0.0)),
                    jnp.int32(maxpos),
                ]
            )
            win = jnp.concatenate(
                [win, jnp.broadcast_to(sent, (K_w - k_loc, 5))]
            )
        with jax.named_scope("phase_tp_exchange"):
            full = ring_topk_merge(win, tp.axis_name, tp.n_shards)
        # window-overflow deferral, the engine's _rot_and_defer contract:
        # the merged window holds the K globally-smallest pos keys, so a
        # local candidate seats iff its pos <= the window's max valid
        # key — summed over shards this books exactly
        # max(n_set_global - K, 0), with no extra collective
        w_max = jnp.max(jnp.where(full[:, 0] < T_g, full[:, 4], -1))
        seat_mask = cand_v & (pos <= w_max)
        seated = jnp.sum(seat_mask.astype(i32))
        n_defer_exg = n_set - seated
        m_part = m_part.replace(
            n_deferred=m_part.n_deferred + n_defer_exg
        )
        exg = None
        if telem_on:
            f32_ = jnp.float32
            waiting = cand_v & ~seat_mask
            age_t = jnp.max(jnp.where(waiting, t1 - cand_t, -jnp.inf))
            age_ticks = jnp.maximum(age_t / spec.dt, 0.0).astype(f32_)
            exg = ExgStats(
                occ=n_set.astype(f32_) / K_w,
                util=seated.astype(f32_) / K_w,
                age=jnp.where(jnp.any(waiting), age_ticks, 0.0),
                cand=n_cand.astype(f32_),
                defer=n_defer_exg.astype(f32_),
                seated=seated,
            )
        return _tp_arrivals_tail(
            spec, tp, state, cache, buf_p, buf_r, m_part, m_rep, t1,
            tasks, fogs, full, exg, n_fast, n_fast_f, fog_alive,
        )

    # ---- exchange-window compaction (no-window regime) ----------------
    n_defer_exg = jnp.maximum(n_set - k_exchange, 0)
    m_part = m_part.replace(n_deferred=m_part.n_deferred + n_defer_exg)
    if k_exchange >= UR:
        # overflow impossible: plain ascending order, which keeps the
        # assembled window in exact global candidate order (the
        # bit-exact-vs-reference regime)
        rot = None
    else:
        # bounded window: the engine's tick-keyed scan-origin rotation
        # (_rot_and_defer) — a fixed origin would systematically seat
        # low-index users first and starve the rest under sustained
        # overflow.  state.tick is replicated, so every shard rotates
        # identically and deferral spreads evenly across its users.
        rot = (
            (state.tick.astype(jnp.uint32) * jnp.uint32(2654435761))
            % jnp.uint32(UR)
        ).astype(i32)
    _, idxc_l, valid_l = _compact(cand_v, k_exchange, UR, rot)
    slot_w = jnp.where(valid_l, cand_slot_g[idxc_l], T_g)
    packed = jnp.stack(
        [
            slot_w,
            jnp.where(valid_l, cand_f[idxc_l], 0),
            _bits(jnp.where(valid_l, cand_t[idxc_l], jnp.inf)),
            _bits(jnp.where(valid_l, cand_m[idxc_l], 0.0)),
        ],
        axis=1,
    )  # (K_ex, 4) i32 — ONE array around the ring per hop

    exg = None
    if telem_on:
        # shard-local exchange-plane scalars (ISSUE 11): window
        # occupancy/utilization, the overflow backlog, and the age of
        # the oldest candidate the window could not seat this tick
        f32_ = jnp.float32
        seated = jnp.minimum(n_set, k_exchange)
        seat_mask = (
            jnp.zeros((UR + 1,), bool)
            .at[jnp.where(valid_l, idxc_l, UR)]
            .set(True)[:UR]
        )
        waiting = cand_v & ~seat_mask
        age_t = jnp.max(
            jnp.where(waiting, t1 - cand_t, -jnp.inf)
        )
        age_ticks = jnp.maximum(age_t / spec.dt, 0.0).astype(f32_)
        exg = ExgStats(
            occ=n_set.astype(f32_) / k_exchange,
            util=seated.astype(f32_) / k_exchange,
            age=jnp.where(jnp.any(waiting), age_ticks, 0.0),
            cand=n_cand.astype(f32_),
            defer=n_defer_exg.astype(f32_),
            seated=seated,
        )

    with jax.named_scope("phase_tp_exchange"):
        full = ring_all_gather(packed, tp.axis_name, tp.n_shards)
    return _tp_arrivals_tail(
        spec, tp, state, cache, buf_p, buf_r, m_part, m_rep, t1,
        tasks, fogs, full, exg, n_fast, n_fast_f, fog_alive,
    )


def _tp_arrivals_tail(
    spec: WorldSpec, tp: TpCtx, state: WorldState, cache: LinkCache,
    buf_p: TickBuf, buf_r: TickBuf, m_part: Metrics, m_rep: Metrics,
    t1: jax.Array, tasks, fogs, full: jax.Array,
    exg: Optional[ExgStats], n_fast: jax.Array, n_fast_f: jax.Array,
    fog_alive: jax.Array,
):
    """Reference assignment/FIFO tail on the assembled exchange window.

    Shared by both exchange regimes — ``full`` is either the
    :func:`ring_all_gather` concatenation (no-window) or the
    :func:`ring_topk_merge` K-window (windowed; its extra ``pos``
    column rides along unread).  Identical on every shard, which is
    what keeps the replicated fog/queue state coherent; every use of a
    window column is masked by ``valid``, so invalid-row payloads
    (sentinels here, garbage gathers in the reference) can never leak
    into state.
    """
    F = spec.n_fogs
    U, S = spec.n_users, spec.max_sends_per_user
    T_loc = spec.task_capacity
    T_g = tp.n_users_global * S
    i32 = jnp.int32
    idx = full[:, 0]  # global ids, sentinel T_g
    valid = idx < T_g
    fog_g = full[:, 1]
    t_af_g = _floats(full[:, 2])
    mips_g = _floats(full[:, 3])
    user_g = jnp.clip(idx, 0, T_g - 1) // S  # global users
    W = idx.shape[0]

    # ---- reference assignment/queueing tail on the assembled window ---
    fog_gc = jnp.clip(fog_g, 0, F - 1)
    idle = fogs.current_task == NO_TASK
    alive_g = fog_alive[fog_gc]
    dead_dst = valid & ~alive_g
    arr = valid & ~dead_dst

    per_fog_arr = _per_fog(arr, fog_g, F)  # (F, W)
    mips_sum = jnp.sum(jnp.where(per_fog_arr, mips_g[None, :], 0.0), axis=1)

    plan = plan_arrivals(arr, fog_g, t_af_g, F, idle, per_fog=per_fog_arr)

    a_pos = plan.assign_task
    assigned = a_pos != NO_TASK
    a_posc = jnp.clip(a_pos, 0, W - 1)
    a_task = jnp.where(assigned, idx[a_posc], NO_TASK)  # global task id
    a_taskc = jnp.clip(a_task, 0, T_g - 1)
    # the assigned head's (arrival time, MIPS) ARE window columns (the
    # same values the broker wrote this tick), one stacked gather
    tm = jnp.stack([t_af_g, mips_g], axis=1)[a_posc]  # (F, 2)
    taf_a, mips_a = tm[:, 0], tm[:, 1]
    t_start = jnp.maximum(taf_a, fogs.free_since)
    svc_a = _svc_time(spec, mips_a, fogs.mips)
    d_fb = cache.d2b[U : U + F]
    d_bu_a = tp.d2b_full[a_taskc // S]
    t_ack5 = t_start + d_fb + d_bu_a

    scat_a = _loc_idx(jnp.where(assigned, a_task, T_g), tp, T_loc)
    tasks = tasks.replace(
        t_service_start=tasks.t_service_start.at[scat_a].set(
            jnp.where(assigned, t_start, 0), mode="drop"
        ),
    )
    if not spec.derive_acks:
        tasks = tasks.replace(
            t_ack5=tasks.t_ack5.at[scat_a].set(
                jnp.where(assigned, t_ack5, 0), mode="drop"
            ),
        )
    fogs = fogs.replace(
        current_task=jnp.where(assigned, a_task, fogs.current_task),
        busy_until=jnp.where(assigned, t_start + svc_a, fogs.busy_until),
    )

    # queue the rest (rank shifts by 1 where the head got assigned)
    assigned_g = assigned[fog_gc]
    a_task_g = a_task[fog_gc]
    got_head = assigned_g & idle[fog_gc]
    eff_rank = jnp.where(arr, plan.rank - got_head.astype(i32), -1)
    to_queue = arr & (eff_rank >= 0) & (idx != a_task_g)
    queue, q_len, enq_ok, dropped = batched_enqueue(
        fogs.queue, fogs.q_head, fogs.q_len, to_queue, fog_g, eff_rank, idx
    )
    d_bu_q = tp.d2b_full[user_g]
    d_fb_q = d_fb[fog_gc]
    assigned_row = arr & (idx == a_task_g)
    stage_k = jnp.where(
        enq_ok,
        _ST_QUEUED,
        jnp.where(
            (to_queue & ~enq_ok) | dead_dst,
            _ST_DROPPED,
            jnp.where(assigned_row, _ST_RUNNING, _ST_TASK_INFLIGHT),
        ),
    )
    idx_loc = _loc_idx(idx, tp, T_loc)
    tasks = tasks.replace(
        stage=tasks.stage.at[idx_loc].set(stage_k, mode="drop"),
        t_q_enter=tasks.t_q_enter.at[idx_loc].set(
            jnp.where(enq_ok, t_af_g, jnp.inf), mode="drop"
        ),
    )
    if not spec.derive_acks:
        tasks = tasks.replace(
            t_ack4_queued=tasks.t_ack4_queued.at[idx_loc].set(
                jnp.where(enq_ok, t_af_g + d_fb_q + d_bu_q, jnp.inf),
                mode="drop",
            ),
        )
    acked = (assigned_g & (idx == a_task_g)) | enq_ok
    sums = jnp.sum(
        jnp.stack([to_queue & ~enq_ok, dead_dst, acked]).astype(i32), axis=1
    )
    arr_per_fog = jnp.sum(per_fog_arr, axis=1, dtype=i32) + n_fast_f
    add_busy = mips_sum / jnp.maximum(fogs.mips, 1e-9)
    fogs = fogs.replace(
        queue=queue,
        q_len=q_len,
        q_drops=fogs.q_drops + dropped,
        busy_time=fogs.busy_time + add_busy,
    )
    m_rep = m_rep.replace(
        n_dropped=m_rep.n_dropped + sums[0] + sums[1] + n_fast
    )
    buf_r = buf_r._replace(
        tx_f=buf_r.tx_f + arr_per_fog,
        rx_f=buf_r.rx_f + arr_per_fog,
        tx_b=buf_r.tx_b + sums[2],
        rx_b=buf_r.rx_b + sums[2],
    )
    u_loc = user_g - tp.u_off
    u_ok = (u_loc >= 0) & (u_loc < U)
    buf_p = buf_p._replace(
        rx_u=buf_p.rx_u.at[jnp.where(u_ok, u_loc, U)].add(
            (acked & u_ok).astype(i32), mode="drop"
        )
    )
    state = state.replace(tasks=tasks, fogs=fogs)
    return state, buf_p, buf_r, m_part, m_rep, exg


# ----------------------------------------------------------------------
# the sharded tick + runner
# ----------------------------------------------------------------------

def _zero_metrics(m: Metrics) -> Metrics:
    return jax.tree.map(jnp.zeros_like, m)


def _zero_buf(U: int, F: int) -> TickBuf:
    i32 = jnp.int32
    return TickBuf(
        tx_u=jnp.zeros((U,), i32),
        rx_u=jnp.zeros((U,), i32),
        tx_f=jnp.zeros((F,), i32),
        rx_f=jnp.zeros((F,), i32),
        tx_b=jnp.zeros((), i32),
        rx_b=jnp.zeros((), i32),
    )


def _tp_tick(
    spec: WorldSpec, tp: TpCtx, state: WorldState, net: NetParams,
    cache: LinkCache, k_exchange: int, window_k: Optional[int] = None,
    dyn: Optional[DynSpec] = None,
) -> WorldState:
    """One sharded tick over the LOCAL world view.

    Phase order mirrors ``engine.make_step`` for the TP-admitted family
    (dense broker, FIFO fogs, static topology): connect -> adverts ->
    spawn -> dense decide -> completions xN -> arrivals -> counters ->
    telemetry.  Every shard-partial counter rides ONE end-of-tick psum;
    with the telemetry plane on, two more psums (one i32, one f32) fold
    the per-phase work deltas, the exchange-plane gauges and the
    latency-histogram deltas (ISSUE 11) — the telemetry-OFF tick
    compiles to exactly the PR 8 program (bit-exact, per-tick
    collective count unchanged).

    ``dyn`` (ISSUE 20): the promoted-knob operand, replicated across
    the mesh axis.  On the TP-admitted family the only phases that
    consume promoted values are the spawn pair (send/link scalars,
    uplink loss — the chaos/hier/learn/energy subsystems are gated off
    this tick), so the operand threads there and ``spec`` may be the
    bucket's shape key; ``None`` keeps the spec's own values as trace
    constants (the ``FNS_SPEC_PROMOTE=0`` reference path).
    """
    t0 = state.tick.astype(jnp.float32) * spec.dt
    t1 = (state.tick + 1).astype(jnp.float32) * spec.dt
    i32 = jnp.int32
    U, F = spec.n_users, spec.n_fogs
    telem_on = spec.telemetry
    hist_on = spec.telemetry and spec.telemetry_hist
    jour_on = spec.journey_active

    m_carry = state.metrics
    m_rep = _zero_metrics(m_carry)
    buf_p = _zero_buf(U, F)
    buf_r = _zero_buf(U, F)
    state = state.replace(metrics=_zero_metrics(m_carry))  # partial acc

    # ---- per-phase work brackets (ISSUE 11) ---------------------------
    # The single-device engine brackets every phase with the
    # metrics+TickBuf activity sum (telemetry/metrics.tick_activity).
    # Under TP that sum splits into a shard-PARTIAL half (per-user
    # counters and buffers) and a REPLICATED half (fog/broker totals,
    # identical on every shard by construction).  Each shard books its
    # partial delta; only shard 0 books the replicated delta — so the
    # end-of-tick psum over shards reproduces the single-device bracket
    # EXACTLY (integer adds commute), and phase_work under TP equals
    # the single-device profile bit-for-bit
    # (tests/test_tp_telemetry.py pins it per phase).
    ph_work: dict = {}
    gate = tp.shard == 0

    def _act(m_part_v, m_rep_v):
        # THE single-device bracket measure (telemetry.metrics
        # .tick_activity) applied to each half; closes over the
        # CURRENT buf_p/buf_r bindings at call time
        return tick_activity(m_part_v, buf_p) + jnp.where(
            gate, tick_activity(m_rep_v, buf_r), 0
        )

    def _book(name, a0, a1):
        i = PHASE_INDEX[name]
        d = a1 - a0
        ph_work[i] = ph_work[i] + d if i in ph_work else d

    # 1-2. static world: the hoisted cache stands in for mobility +
    # association (spec.assume_static is part of the TP gate)

    # 3. connect handshake (user-partial counters; replicated broker regs)
    if spec.connect_gating:
        a0 = _act(state.metrics, m_rep) if telem_on else None
        with jax.named_scope("phase_connect"):
            state, buf_p = _phase_connect(
                spec, state, net, cache, buf_p, t0, t1
            )
        if telem_on:
            _book("connect", a0, _act(state.metrics, m_rep))
    # 4. advert delivery — its counter is an F-sum, identical on every
    # shard: route it to the REPLICATED accumulator
    m_part = state.metrics
    state = state.replace(metrics=m_rep)
    a0 = _act(m_part, state.metrics) if telem_on else None
    with jax.named_scope("phase_adverts"):
        state = _phase_adverts(state, t1)
    m_rep, state = state.metrics, state.replace(metrics=m_part)
    if spec.adv_periodic:
        with jax.named_scope("phase_adverts"):
            state = _phase_periodic_adverts(spec, state, net, cache, t0, t1)
    if telem_on:
        _book("adverts", a0, _act(state.metrics, m_rep))

    # 5. spawn (full-width PRNG draws sliced per shard — engine._tp_user_draw)
    a0 = _act(state.metrics, m_rep) if telem_on else None
    with jax.named_scope("phase_spawn"):
        if spec.max_sends_per_tick > 1:
            state, buf_p = _phase_spawn_multi(
                spec, state, net, cache, buf_p, t0, t1, tp=tp, dyn=dyn
            )
        else:
            state, buf_p = _phase_spawn(
                spec, state, net, cache, buf_p, t0, t1, tp=tp, dyn=dyn
            )
    if telem_on:
        _book("spawn", a0, _act(state.metrics, m_rep))

    # 6. dense broker decide (replicated scalar winner; one psum for the
    # global fan-out counts)
    a0 = _act(state.metrics, m_rep) if telem_on else None
    with jax.named_scope("phase_broker"):
        state, buf_p = _phase_broker_dense(
            spec, state, net, cache, buf_p, t1, tp=tp
        )
    if telem_on:
        _book("broker", a0, _act(state.metrics, m_rep))
    m_part = state.metrics

    # 7. fog completions + arrivals (replicated fog state)
    a0 = _act(m_part, m_rep) if telem_on else None
    for _ in range(spec.completions_per_tick):
        with jax.named_scope("phase_completions"):
            state, buf_p, buf_r, m_rep = _tp_completions(
                spec, tp, state, cache, buf_p, buf_r, m_rep, t1
            )
    if telem_on:
        _book("completions", a0, _act(m_part, m_rep))
    a0 = _act(m_part, m_rep) if telem_on else None
    with jax.named_scope("phase_fog_arrivals"):
        state, buf_p, buf_r, m_part, m_rep, exg = _tp_fog_arrivals(
            spec, tp, state, cache, buf_p, buf_r, m_part, m_rep, t1,
            k_exchange, window_k,
        )
    if telem_on:
        _book("fog_arrivals", a0, _act(m_part, m_rep))

    # 7b. streaming latency histogram (spec.telemetry_hist under TP,
    # ISSUE 11): shard-local deltas over the owned task rows; the fold
    # below psums them into the replicated histogram.  The per-task
    # exactly-once flag stays shard-local (each task has one owner).
    hist_d = sum_d = None
    if hist_on:
        with jax.named_scope("phase_latency_hist"):
            hist_d, sum_d, seen = latency_hist_delta(
                spec, state.telem, state.tasks, t1
            )
        state = state.replace(
            telem=state.telem.replace(lat_seen=seen)
        )

    # 7c. journey rings (spec.journey_active under TP, ISSUE 19):
    # shard-local diff over the owned sampled slots with GLOBAL slot
    # ids (journeys.journey_tick_tp); non-owned slots hold their
    # previous snapshot, so their rings never advance.  Only the scalar
    # drop-oldest census crosses shards — it rides the end-of-tick
    # psum below; the rings themselves stay shard-local until
    # run_tp_sharded stitches them by owner.
    j_over = None
    if jour_on:
        with jax.named_scope("phase_journeys"):
            telem_j, j_over = journey_tick_tp(
                spec, state.telem, state.tasks, t1, tp.t_off
            )
        state = state.replace(telem=telem_j)

    # 8. THE end-of-tick combine: every shard-partial scalar in one psum
    part_vec = jnp.stack(
        [getattr(m_part, f) for f in _METRIC_FIELDS]
        + [buf_p.tx_b, buf_p.rx_b]
        + ([j_over] if jour_on else [])
    )
    tot = jax.lax.psum(part_vec, tp.axis_name)
    delta = {
        f: tot[i] + getattr(m_rep, f)
        for i, f in enumerate(_METRIC_FIELDS)
    }
    n_def = delta["n_deferred"]
    vals = {
        f: getattr(m_carry, f) + delta[f]
        for f in _METRIC_FIELDS
        if f not in ("n_deferred", "n_deferred_max")
    }
    vals["n_deferred"] = n_def  # per-tick gauge (reference resets it)
    vals["n_deferred_max"] = jnp.maximum(m_carry.n_deferred_max, n_def)
    metrics = Metrics(**vals)
    tx_b = tot[len(_METRIC_FIELDS)] + buf_r.tx_b
    rx_b = tot[len(_METRIC_FIELDS) + 1] + buf_r.rx_b
    if jour_on:
        # the psum'd drop-oldest census is identical on every shard, so
        # the replicated j_dropped scalar stays replicated
        state = state.replace(
            telem=state.telem.replace(
                j_dropped=state.telem.j_dropped
                + tot[len(_METRIC_FIELDS) + 2]
            )
        )

    # per-node message counters: user segment shard-local, the rest
    # replicated totals (identical on every shard by construction)
    n_rest_q = spec.n_aps + spec.n_routers
    rest_zeros = jnp.zeros((n_rest_q,), i32)
    tx_all = jnp.concatenate(
        [buf_p.tx_u, buf_r.tx_f, tx_b[None], rest_zeros]
    )
    rx_all = jnp.concatenate(
        [buf_p.rx_u, buf_r.rx_f, rx_b[None], rest_zeros]
    )
    nodes2 = state.nodes.replace(
        tx_count=state.nodes.tx_count + tx_all,
        rx_count=state.nodes.rx_count + rx_all,
    )
    if spec.n_aps > 0:
        a0, a1 = spec.ap_slice
        nodes2 = nodes2.replace(
            assoc_sum=nodes2.assoc_sum.at[a0:a1].add(cache.n_assoc)
        )
    state = state.replace(nodes=nodes2, metrics=metrics)

    if telem_on:
        # 9a. the telemetry fold (ISSUE 11): ONE i32 psum for the
        # per-phase work deltas + latency-histogram bucket deltas, ONE
        # f32 psum for the exchange-plane one-hot columns + latency
        # sums.  The one-hot layout makes the psum a gather: shard s
        # fills only column s, so the summed result is the full
        # replicated per-shard view and every shard folds identical
        # values into the replicated TelemetryState.  The per-shard
        # exchange LEAVES exist only on a stamped world view
        # (spec.tp_shards, run_tp_sharded's default; run_node_sharded
        # dispatches unstamped to keep its single-return API) — the
        # phase slots and histogram fold book either way.
        exg_on = spec.telemetry_tp_shards > 0
        with jax.named_scope("phase_tp_fold"):
            n_ph = len(PHASES)
            ph_work[PHASE_INDEX["tp_exchange"]] = exg.seated
            ph_work[PHASE_INDEX["tp_defer"]] = exg.defer.astype(i32)
            ph_vec = jnp.zeros((n_ph,), i32)
            for i in sorted(ph_work):
                ph_vec = ph_vec.at[i].set(ph_work[i])
            S_n = tp.n_shards
            ints = [ph_vec]
            flts = []
            if exg_on:
                col = jnp.stack(
                    [exg.occ, exg.util, exg.age, exg.cand, exg.defer]
                )
                flts.append(
                    jnp.zeros((5, S_n), jnp.float32)
                    .at[:, tp.shard].set(col).reshape(-1)
                )
            if hist_on:
                ints.append(hist_d.reshape(-1))
                flts.append(sum_d)
            int_tot = jax.lax.psum(jnp.concatenate(ints), tp.axis_name)
            flt_tot = (
                jax.lax.psum(jnp.concatenate(flts), tp.axis_name)
                if flts else None
            )
            ph_tot = int_tot[:n_ph]
            telem = state.telem
            if hist_on:
                telem = telem.replace(
                    lat_hist=telem.lat_hist
                    + int_tot[n_ph:].reshape(F, spec.telemetry_hist_bins),
                    lat_sum=telem.lat_sum + flt_tot[-F:],
                )
            if exg_on:
                exg_g = flt_tot[: 5 * S_n].reshape(5, S_n)
                telem = accumulate_exchange(
                    spec, telem, exg_g[0], exg_g[1], exg_g[2], exg_g[3],
                    exg_g[4], state.tick,
                )
            state = state.replace(telem=telem)
        # 9b. plane-1 gauges on the replicated fog state + psum'd
        # totals, with the folded per-phase work vector booked exactly
        # like the single-device harness books its bracket deltas
        with jax.named_scope("phase_telemetry"):
            state = state.replace(
                telem=accumulate_tick(
                    spec, state.telem, state.fogs, state.learn,
                    state.metrics, state.tick, t1,
                    {i: ph_tot[i] for i in range(n_ph)},
                )
            )

    return state.replace(t=t1, tick=state.tick + 1)


@functools.lru_cache(maxsize=32)
def _tp_program(
    spec: WorldSpec, n_ticks: int, mesh: Mesh, axis_name: str,
    k_exchange: int, donate: bool, window_k: Optional[int] = None,
    promoted: bool = False,
):
    """Build (and cache) the jitted sharded-horizon program for ``spec``.

    ``promoted`` (ISSUE 20): ``spec`` is then a shape key
    (``dynspec.shape_key``) and the program takes a trailing
    :class:`~fognetsimpp_tpu.dynspec.DynSpec` operand, replicated
    across the mesh axis — every world in the bucket (and every warm
    knob retune) reuses this one cache entry, the ``run_jit`` contract
    extended to the sharded runner.
    """
    n = mesh.shape[axis_name]
    U_g, S = spec.n_users, spec.max_sends_per_user
    U_loc = U_g // n
    T_loc = U_loc * S
    spec_l = dataclasses.replace(spec, n_users=U_loc)
    hist_on = spec.telemetry and spec.telemetry_hist
    jour_on = spec.journey_active

    def run_shard(users, tasks, nodes_u, lat_seen, jour, rep, net, cache,
                  dyn):
        shard = jax.lax.axis_index(axis_name)
        u_off = shard * U_loc
        tp = TpCtx(
            axis_name=axis_name,
            n_shards=n,
            shard=shard,
            n_users_global=U_g,
            u_off=u_off,
            t_off=u_off * S,
            d2b_full=cache.d2b,
        )

        def cut(x):
            return jnp.concatenate(
                [
                    jax.lax.dynamic_slice_in_dim(x, u_off, U_loc, axis=0),
                    x[U_g:],
                ],
                axis=0,
            )

        cache_l = cache.replace(
            assoc=cut(cache.assoc),
            attach_now=cut(cache.attach_now),
            acc_delay=cut(cache.acc_delay),
            reachable=cut(cache.reachable),
            d2b=cut(cache.d2b),
            mac_loss_p=cut(cache.mac_loss_p),
        )
        net_l = net.replace(
            node_attach=cut(net.node_attach),
            node_acc=cut(net.node_acc),
            is_wireless=cut(net.is_wireless),
        )
        nodes_l = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b], axis=0),
            nodes_u, rep["nodes_rest"],
        )
        telem_l = rep["telem"]
        if hist_on:
            # the per-task exactly-once flag travels with the SHARDED
            # tree (each task row has exactly one owner); the rest of
            # the telemetry state stays replicated
            telem_l = telem_l.replace(lat_seen=lat_seen)
        if jour_on:
            # each shard carries a FULL copy of the journey sample
            # (global slot ids) in the sharded tree; only the owner's
            # copy of a slot ever diffs (journeys.journey_tick_tp)
            telem_l = telem_l.replace(
                j_task=jour[0], j_prev=jour[1],
                j_ring=jour[2], j_cursor=jour[3],
            )
        state_l = WorldState(
            t=rep["t"], tick=rep["tick"], key=rep["key"],
            nodes=nodes_l, users=users, fogs=rep["fogs"],
            broker=rep["broker"], tasks=tasks, metrics=rep["metrics"],
            learn=rep["learn"], chaos=rep["chaos"], hier=rep["hier"],
            telem=telem_l,
        )

        def tick(st, _):
            return (
                _tp_tick(spec_l, tp, st, net_l, cache_l, k_exchange,
                         window_k, dyn=dyn),
                None,
            )

        final, _ = jax.lax.scan(tick, state_l, None, length=n_ticks)
        if spec.derive_acks:
            final = _finalize_derived_acks(spec_l, final, cache_l)
        telem_out = final.telem
        lat_seen_out = None
        if hist_on:
            lat_seen_out = telem_out.lat_seen
            telem_out = telem_out.replace(
                lat_seen=jnp.zeros((0,), jnp.int8)
            )
        jour_out = None
        if jour_on:
            jour_out = (
                telem_out.j_task, telem_out.j_prev,
                telem_out.j_ring, telem_out.j_cursor,
            )
            telem_out = telem_out.replace(
                j_task=jnp.zeros((0,), jnp.int32),
                j_prev=jnp.zeros((0,) + telem_out.j_prev.shape[1:],
                                 jnp.int32),
                j_ring=jnp.zeros((0,) + telem_out.j_ring.shape[1:],
                                 jnp.int32),
                j_cursor=jnp.zeros((0,), jnp.int32),
            )
        rep_out = {
            "t": final.t, "tick": final.tick, "key": final.key,
            "fogs": final.fogs, "broker": final.broker,
            "metrics": final.metrics, "learn": final.learn,
            "chaos": final.chaos, "hier": final.hier,
            "telem": telem_out,
            "nodes_rest": jax.tree.map(lambda x: x[U_loc:], final.nodes),
        }
        nodes_u_out = jax.tree.map(lambda x: x[:U_loc], final.nodes)
        return (final.users, final.tasks, nodes_u_out, lat_seen_out,
                jour_out, rep_out)

    # check_vma=False on every variant: outputs mix sharded task rows
    # and replicated fog/broker state; the fog-side replication
    # invariant is by construction (every shard runs the identical tail
    # on the identical exchanged window), not statically provable.
    # The sharded positional args grow with the optional planes
    # (lat_seen under telemetry_hist, the journey-leaf tuple under
    # journey_active) — a plane that is OFF contributes no argument, so
    # its variants trace to exactly the established program.
    k_sh = 3 + int(hist_on) + int(jour_on)

    def body(*args):
        users, tasks, nodes_u = args[:3]
        rest = list(args[3:k_sh])
        rep, net, cache = args[k_sh:k_sh + 3]
        dyn = args[k_sh + 3] if promoted else None
        lat_seen = rest.pop(0) if hist_on else None
        jour = rest.pop(0) if jour_on else None
        u, t, nu, ls, jo, r = run_shard(
            users, tasks, nodes_u, lat_seen, jour, rep, net, cache, dyn
        )
        out = [u, t, nu]
        if hist_on:
            out.append(ls)
        if jour_on:
            out.append(jo)
        out.append(r)
        return tuple(out)

    # the DynSpec operand (promoted) is replicated like the rep tree:
    # every shard reads identical knob values, so the traced tick is
    # the static program with loads where the constants were
    in_specs = (P(axis_name),) * k_sh + (P(), P(), P()) + (
        (P(),) if promoted else ()
    )
    out_specs = (P(axis_name),) * k_sh + (P(),)

    shmapped = shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )

    # donation covers the SHARDED trees only — the memory that scales
    # with world size (task/user/user-node rows, T/n per device).  The
    # replicated tree is KBs of fog/broker state whose donation saves
    # nothing and whose builder-aliased zero/full leaves (smoke seeds
    # pool_avail with the mips array itself) XLA's allocation-level
    # donation tracking rejects even after pointer-level dealiasing.
    if promoted:
        @functools.partial(
            jax.jit, donate_argnums=(0,) if donate else ()
        )
        def go(sharded, rep, net, cache, dyn):
            return shmapped(*sharded, rep, net, cache, dyn)
    else:
        @functools.partial(
            jax.jit, donate_argnums=(0,) if donate else ()
        )
        def go(sharded, rep, net, cache):
            return shmapped(*sharded, rep, net, cache)

    return go


@contextlib.contextmanager
def _donation_safe_compile(donate: bool):
    """Bypass the persistent compilation cache while compiling a
    DONATED TP program.

    jaxlib 0.4.36's CPU executable serialization drops the
    input-output donation aliasing on the way back in: a TP program
    DESERIALIZED from ``jax_compilation_cache_dir`` silently corrupts
    its donated carry when re-invoked (whole-state nondeterministic
    divergence — reproduced on the chunked runner, where chunk N+1
    consumes chunk N's donated output; a cold-compiled executable of
    the same program is bit-exact).  Donated TP programs therefore
    always compile fresh: the in-memory jit cache still dedups within
    the process, only the cross-process executable reuse is given up.
    Non-donated programs keep the persistent cache — they never alias.

    Toggling ``jax_compilation_cache_dir`` alone is NOT enough: jax
    memoizes the is-the-cache-usable decision once per process
    (``compilation_cache.is_cache_used``) and initializes the
    module-global cache object at most once, so a mid-process config
    flip is silently ignored.  ``reset_cache()`` is the documented way
    to drop that memoized state — we reset on entry (so the compile
    under the guard re-evaluates the now-None dir) and again on exit
    (so later non-donated compiles re-attach the restored dir).
    """
    cache_dir = jax.config.jax_compilation_cache_dir
    if not donate or not cache_dir:
        yield
        return
    try:
        from jax._src import compilation_cache as _cc
        _reset = _cc.reset_cache
    except Exception:  # future-jax drift: fail open, keep the cache
        yield
        return
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        _reset()
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        _reset()


def run_tp_sharded(
    spec: WorldSpec,
    state: WorldState,
    net: NetParams,
    bounds: Optional[MobilityBounds] = None,
    mesh: Optional[Mesh] = None,
    n_ticks: Optional[int] = None,
    axis_name: str = NODE_AXIS,
    exchange_window: Optional[int] = None,
    donate: bool = False,
    pad: bool = True,
    stamp: bool = True,
    promote: Optional[bool] = None,
) -> Tuple[WorldSpec, WorldState]:
    """Advance ONE world whose user/task axis spans the mesh.

    The explicit shard_map TP tick (module docstring); requires a
    TP-admissible spec (:func:`engine.tp_ok` — a one-line ``ValueError``
    otherwise).  Returns ``(spec, final_state)``: the spec comes back
    because ``pad=True`` (default) pads a non-divisible population with
    inert users (:func:`pad_users_to_multiple`) and — telemetry on —
    the shard axis is stamped (``spec.tp_shards``, sizing the
    per-shard exchange-plane telemetry leaves the returned state now
    carries); the returned spec describes the returned state either
    way.  Task/user outputs stay row-sharded on the mesh, so chained
    calls never gather the table.

    ``exchange_window`` bounds the per-shard arrival candidates
    exchanged per tick (default: the full per-shard candidate list —
    never defers, bit-exact vs the single-device engine); smaller
    windows defer overflow arrivals a tick, visible in
    ``Metrics.n_deferred`` exactly like the engine's K-window.

    A WINDOWED spec (``arrival_window=K < task_capacity``) instead runs
    the distributed K-window selection: the exchange ring merges shard
    windows hop by hop (O(K) payload — :func:`ring_topk_merge`) into
    exactly the window the single-device windowed engine compacts, so
    results stay bit-exact vs ``run()`` on the same spec
    (tests/test_tp.py), overflow defers with the engine's tick-keyed
    rotation fairness, and ``exchange_window`` must stay ``None`` (the
    spec's own K already bounds the exchange; a ``ValueError`` says so).

    ``donate=True`` donates the (sharded) input state's buffers to the
    run — the memory discipline of ``run_jit`` (simlint R6); do not
    reuse ``state`` after calling.  Bit-exactness is independent of
    donation (tests/test_tp.py).

    ``stamp=False`` skips the telemetry shard-axis stamping — the
    caller's spec keeps describing the returned state (no per-shard
    exchange leaves; phase attribution and the latency histogram still
    book).  :func:`run_node_sharded` uses it to keep its
    single-return dispatch API consistent.

    ``promote`` (ISSUE 20, default on; ``FNS_SPEC_PROMOTE=0`` flips the
    default): the sharded program takes the promoted knobs as a
    replicated DynSpec operand, keyed on the spec's shape key — a warm
    retune of any promoted knob (loss probabilities, send/link
    scalars...) re-uses the compiled program with ZERO compile events,
    exactly the ``run_jit`` contract.  ``promote=False`` is the
    bit-exact static reference path (tests/test_sharded_dynspec.py
    A/Bs the two).
    """
    del bounds  # static worlds only (tp gate): mobility never runs
    go, parts, net_r, cache_r, spec, dyn = _tp_setup(
        spec, state, net, mesh, n_ticks, axis_name, exchange_window,
        donate, pad, stamp, promote,
    )
    with _donation_safe_compile(donate):
        if dyn is not None:
            out = go(*parts, net_r, cache_r, dyn)
        else:
            out = go(*parts, net_r, cache_r)
    users, tasks, nodes_u_f, rep = out[0], out[1], out[2], out[-1]
    telem = rep["telem"]
    i = 3
    if spec.telemetry and spec.telemetry_hist:
        telem = telem.replace(lat_seen=out[i])
        i += 1
    if spec.journey_active:
        # stitch the per-shard ring copies by owner: shard s's block is
        # authoritative exactly for the slots whose global task row
        # falls in its [s*T_loc, (s+1)*T_loc) range — everyone else's
        # copy of that slot never advanced (journeys.journey_tick_tp)
        jt, jp, jr, jc = out[i]
        n_sh = mesh.shape[axis_name]  # _tp_setup required the mesh
        J = jt.shape[0] // n_sh  # leaf-derived: padding may grow the
        t_loc = spec.task_capacity // n_sh  # spec's clamped slot count
        ids = jt[:J]  # the replicated sample: identical in every block
        idx = (ids // t_loc) * J + jnp.arange(J, dtype=ids.dtype)
        telem = telem.replace(
            j_task=ids, j_prev=jp[idx], j_ring=jr[idx], j_cursor=jc[idx]
        )
    nodes = jax.tree.map(
        lambda a, b: jnp.concatenate([a, b], axis=0),
        nodes_u_f, rep["nodes_rest"],
    )
    final = WorldState(
        t=rep["t"], tick=rep["tick"], key=rep["key"], nodes=nodes,
        users=users, fogs=rep["fogs"], broker=rep["broker"], tasks=tasks,
        metrics=rep["metrics"], learn=rep["learn"], chaos=rep["chaos"],
        hier=rep["hier"], telem=telem,
    )
    return spec, final


def run_tp_chunked(
    spec: WorldSpec,
    state: WorldState,
    net: NetParams,
    bounds: Optional[MobilityBounds] = None,
    mesh: Optional[Mesh] = None,
    chunk_ticks: int = 1000,
    callback: Optional[Callable[[WorldState, int], None]] = None,
    n_ticks: Optional[int] = None,
    axis_name: str = NODE_AXIS,
    exchange_window: Optional[int] = None,
    donate: bool = True,
    promote: Optional[bool] = None,
    reconfigure: Optional[Callable[[int], Optional[dict]]] = None,
) -> Tuple[WorldSpec, WorldState]:
    """TP analog of ``engine.run_chunked``: the sharded horizon in
    fixed-size chunks, ``callback(state, ticks_done)`` between chunks.

    The serving substrate of the sharded health plane (ISSUE 11):
    ``telemetry.live.serve_tp_run`` runs its watchdog/exposition loop
    on these chunk boundaries, exactly like ``serve_run`` does on
    ``run_chunked``'s.  Each chunk is one :func:`run_tp_sharded` call,
    so equal-size chunks share ONE cached program (plus one for a
    ragged tail) and the carry stays row-sharded on the mesh between
    chunks — the table is never gathered.  The first chunk pads and
    (telemetry on) stamps the spec; the returned spec describes the
    returned state.  Bit-identical to one full-horizon TP call — the
    carry is the same pytree either way (tests/test_tp_telemetry.py).

    ``donate=True`` (default) donates each chunk's input carry; the
    callback may read the PASSED state freely (the fetch completes
    before the next chunk consumes it) but must not retain device
    references across chunks.

    ``promote`` / ``reconfigure`` (ISSUE 20, the sharded what-if door):
    with promotion on (the default), ``reconfigure(ticks_done)`` —
    called at every INTERIOR chunk boundary, after ``callback`` — may
    return a ``{field: value}`` dict of promoted WorldSpec knobs to
    apply to the remaining horizon with ZERO recompiles: the knobs
    land in the spec (``dynspec.apply_knobs`` rejects shape-key
    changes with a one-line error), the next chunk's ``_tp_setup``
    re-splits it onto the SAME shape bucket, and the cached sharded
    program re-runs with new operand values only
    (``compile_stats()``-delta-provable, gated in ``bench_trend``).
    """
    if promote is None:
        promote = promote_default()
    if reconfigure is not None and not promote:
        raise ValueError(
            "reconfigure re-configures the DynSpec operand between "
            "chunks; it needs the promoted path (promote=True)"
        )
    total = spec.n_ticks if n_ticks is None else n_ticks
    chunk = max(1, min(chunk_ticks, total))
    done = 0
    while done < total:
        ticks = min(chunk, total - done)
        spec, state = run_tp_sharded(
            spec, state, net, bounds, mesh, n_ticks=ticks,
            axis_name=axis_name, exchange_window=exchange_window,
            donate=donate, promote=promote,
        )
        done += ticks
        if callback is not None:
            callback(state, done)
        if reconfigure is not None and done < total:
            knobs = reconfigure(done)
            if knobs:
                # compile-free by construction: apply_knobs rejects any
                # change that would leave the shape bucket, and the next
                # chunk re-uses the cached sharded program with the new
                # operand values only
                spec = apply_knobs(spec, knobs)
    return spec, state


def _tp_setup(
    spec: WorldSpec,
    state: WorldState,
    net: NetParams,
    mesh: Mesh,
    n_ticks: Optional[int],
    axis_name: str,
    exchange_window: Optional[int],
    donate: bool,
    pad: bool,
    stamp: bool = True,
    promote: Optional[bool] = None,
):
    """Shared front half of :func:`run_tp_sharded`: gate, pad, place,
    build the jitted program.  ``tools/hloaudit``/``tools/op_budget``
    call this too and ``.lower(...).compile()`` the returned program —
    so the audited artifact IS the production program, never a twin.

    Returns ``(go, (sharded, rep), net_r, cache_r, spec, dyn)`` where
    ``dyn`` is the replicated DynSpec operand under promotion (append
    it to the call: ``go(*parts, net_r, cache_r, dyn)``) and ``None``
    on the static path (``FNS_SPEC_PROMOTE=0`` or ``promote=False``).
    Under promotion the program is keyed on the padded/stamped spec's
    SHAPE KEY (``dynspec.split_spec``), so every world in the bucket —
    and every warm knob retune — lands on one ``_tp_program`` entry.
    """
    spec.validate()
    reason = tp_reject_reason(spec)
    if reason is not None:
        raise ValueError(f"run_tp_sharded: {reason}")
    if mesh is None:
        raise ValueError("run_tp_sharded needs a Mesh (parallel.make_mesh)")
    if net.mac_loss_tab.shape[0] > 0:
        raise ValueError(_STATIC_MAC_ERR)
    n = mesh.shape[axis_name]
    if spec.n_users % n:
        if not pad:
            raise ValueError(
                f"the {n}-device mesh axis must divide n_users "
                f"({spec.n_users}) — pad_users_to_multiple(spec, state, "
                "net, n) pads with inert users (pad=True does it for you)"
            )
        spec, state, net = pad_users_to_multiple(spec, state, net, n)
    U_loc = spec.n_users // n
    R = min(spec.arrival_cands, spec.max_sends_per_user)
    cap = U_loc * R
    if spec.window < spec.task_capacity:
        # windowed spec: the spec's OWN global K-window bounds the
        # exchange (distributed top-K over the ring — _tp_fog_arrivals);
        # an exchange_window on top would change which candidates even
        # reach the merge and break the bit-exact window contract
        if exchange_window is not None:
            raise ValueError(
                "exchange_window tunes the no-window exchange ring; a "
                f"windowed spec (arrival_window={spec.arrival_window}) "
                "already bounds the hop-pruned exchange to its global "
                "K-window — drop exchange_window or the arrival window"
            )
        window_k = spec.window
        k_ex = cap
    else:
        window_k = None
        k_ex = (
            cap if exchange_window is None
            else max(1, min(exchange_window, cap))
        )
    ticks = spec.n_ticks if n_ticks is None else n_ticks

    if stamp:
        spec, state = stamp_tp_telemetry(spec, state, n)

    # ---- DynSpec operand promotion (ISSUE 20) -------------------------
    # Split AFTER pad/stamp so the shape key describes the world the
    # program actually runs (padded population, stamped shard axis);
    # dyn leaves are population-independent, so one host-side dyn_of
    # covers every shard's local view.
    if promote is None:
        promote = promote_default()
    if promote:
        run_spec, dyn = split_spec(spec)
        registry_note(run_spec, jax.default_backend(), donated=donate)
    else:
        run_spec, dyn = spec, None

    # the run-constant association/delay cache (assume_static is part of
    # the TP gate), computed once OUTSIDE the audited sharded program
    cache = associate(
        net, state.nodes.pos, state.nodes.alive, broker=spec.broker_index
    )

    leaf = replica_sharding(mesh, axis_name)  # leading-axis row sharding
    repl = NamedSharding(mesh, P())

    def rows(tree):
        return jax.tree.map(lambda x: jax.device_put(x, leaf(x)), tree)

    def replicated(tree):
        return jax.tree.map(lambda x: jax.device_put(x, repl), tree)

    nodes_u = jax.tree.map(lambda x: x[: spec.n_users], state.nodes)
    nodes_rest = jax.tree.map(lambda x: x[spec.n_users :], state.nodes)
    hist_on = spec.telemetry and spec.telemetry_hist
    telem_rep = state.telem
    sharded = [
        rows(state.users),
        rows(state.tasks),
        rows(nodes_u),
    ]
    if hist_on:
        # the per-task exactly-once flag rides the sharded tree; the
        # replicated telemetry copy carries a zero-row stand-in
        sharded.append(rows(state.telem.lat_seen))
        telem_rep = telem_rep.replace(
            lat_seen=jnp.zeros((0,), jnp.int8)
        )
    if spec.journey_active:
        # journey leaves ride the sharded tree TILED n× — every shard
        # gets a full copy of the sample (global slot ids), diffs only
        # its owned slots, and run_tp_sharded stitches the blocks back
        # by owner.  O(n·J·R) rows total: the sample is tiny by design
        # (J ≤ telemetry_journeys), so the tiling never dominates.
        tl = state.telem

        def tile(x):
            return jnp.tile(x, (n,) + (1,) * (x.ndim - 1))

        sharded.append(tuple(
            rows(tile(x))
            for x in (tl.j_task, tl.j_prev, tl.j_ring, tl.j_cursor)
        ))
        telem_rep = telem_rep.replace(
            j_task=jnp.zeros((0,), jnp.int32),
            j_prev=jnp.zeros((0,) + tl.j_prev.shape[1:], jnp.int32),
            j_ring=jnp.zeros((0,) + tl.j_ring.shape[1:], jnp.int32),
            j_cursor=jnp.zeros((0,), jnp.int32),
        )
    sharded = tuple(sharded)
    rep = replicated(
        {
            "t": state.t, "tick": state.tick, "key": state.key,
            "fogs": state.fogs, "broker": state.broker,
            "metrics": state.metrics, "learn": state.learn,
            # inert by construction: tp_reject_reason gates chaos-on
            # and multi-broker specs off the TP tick, so every chaos
            # and hier leaf is zero-row
            "chaos": state.chaos, "hier": state.hier,
            "telem": telem_rep, "nodes_rest": nodes_rest,
        }
    )
    net_r = replicated(net)
    cache_r = replicated(cache)
    if dyn is not None:
        dyn = replicated(dyn)
    if donate:
        from ..core.engine import _dealias_for_donation

        sharded = _dealias_for_donation(sharded)
    go = _tp_program(
        run_spec, ticks, mesh, axis_name, k_ex, donate, window_k,
        promoted=promote,
    )
    return go, (sharded, rep), net_r, cache_r, spec, dyn


# ----------------------------------------------------------------------
# GSPMD fallback (the original capacity path) + dispatch
# ----------------------------------------------------------------------

def shard_state_by_node(
    spec: WorldSpec, state: WorldState, mesh: Mesh,
    axis_name: str = NODE_AXIS,
) -> WorldState:
    """Place the world on the mesh: big per-row arrays sharded, rest
    replicated.

    The task/user arrays (the memory that scales with world size) split
    row-wise; the small pytrees (node platform state, fogs, broker view,
    metrics) are committed replicated to every device — they are KBs.
    """
    n = mesh.shape[axis_name]
    if spec.n_users % n or spec.task_capacity % n:
        raise ValueError(
            f"the {n}-device mesh axis must divide n_users "
            f"({spec.n_users}) and task capacity ({spec.task_capacity}) — "
            "pad_users_to_multiple(spec, state, net, n) pads with inert "
            "users"
        )
    leaf = replica_sharding(mesh, axis_name)  # leading-axis row sharding
    repl = NamedSharding(mesh, P())

    def rows(tree):
        return jax.tree.map(lambda x: jax.device_put(x, leaf(x)), tree)

    def replicated(tree):
        return jax.tree.map(lambda x: jax.device_put(x, repl), tree)

    return state.replace(
        tasks=rows(state.tasks),
        users=rows(state.users),
        nodes=replicated(state.nodes),
        fogs=replicated(state.fogs),
        broker=replicated(state.broker),
        metrics=replicated(state.metrics),
    )


# simlint: disable=R6 -- a chained run_node_sharded call can pass an
# already-sharded state whose device_put is a no-op aliasing the caller's
# buffers; donating here would invalidate them behind the caller's back
@functools.partial(jax.jit, static_argnums=(0, 1))
def _advance(
    spec: WorldSpec, n_ticks: Optional[int], state: WorldState,
    net: NetParams, bounds: MobilityBounds,
) -> WorldState:
    final, _ = run(spec, state, net, bounds, n_ticks=n_ticks)
    return final


def run_node_sharded(
    spec: WorldSpec,
    state: WorldState,
    net: NetParams,
    bounds: MobilityBounds,
    mesh: Mesh,
    n_ticks: Optional[int] = None,
    axis_name: str = NODE_AXIS,
) -> WorldState:
    """Advance a node-sharded world over the horizon.

    Dispatch: TP-admissible specs (:func:`engine.tp_ok`) take the
    explicit shard_map tick (:func:`run_tp_sharded` — hand-placed
    collectives, audited and budgeted in CI); everything else keeps the
    GSPMD fallback, where the *unmodified* engine step runs under XLA's
    SPMD partitioner and GSPMD inserts the collectives (correct for
    every engine world, communication chosen by the compiler).  Both
    paths are bit-identical to the single-device engine (tested), and
    input shardings propagate to the outputs, so chained calls keep the
    table distributed.
    """
    if tp_ok(spec):
        # stamp=False: this entry returns only the state, so the
        # CALLER's spec must keep describing it — no per-shard
        # exchange leaves (use run_tp_sharded directly for the
        # exchange plane); phase attribution and the latency
        # histogram still book
        _, final = run_tp_sharded(
            spec, state, net, bounds, mesh, n_ticks=n_ticks,
            axis_name=axis_name, pad=False, stamp=False,
        )
        return final
    state = shard_state_by_node(spec, state, mesh, axis_name)
    return _advance(spec, n_ticks, state, net, bounds)
