"""Node-axis (TP) sharding of the live engine: the HBM-scaling path.

When the task table outgrows one chip (it dominates world memory:
``T = n_users * max_sends`` rows × ~17 columns), the per-task and per-user
arrays shard across the mesh with ``NamedSharding(P("node"))`` and the
*unmodified* engine step runs under XLA's SPMD partitioner: per-shard
phases (spawn, masks, compaction scans) stay local, and GSPMD inserts the
collectives where a phase genuinely needs a global view (the K-sized
compacted windows, fog/broker reductions) — exactly the
"state sharded over mesh axes when node count exceeds one chip's HBM" row
of SURVEY.md §2.3, with zero hand-written communication.

Division of labour with the other axes: replica-DP
(:mod:`fognetsimpp_tpu.parallel.mesh`) is the *throughput* path (zero
collectives); this module is the *capacity* path (per-device task memory
= T / n_devices, paying K-sized gathers per tick).  Results are
bit-identical to the unsharded engine (tested), and input shardings
propagate to the outputs, so chained calls keep the table distributed.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.engine import run
from ..net.mobility import MobilityBounds
from ..net.topology import NetParams
from ..spec import WorldSpec
from ..state import WorldState
from .mesh import replica_sharding

NODE_AXIS = "node"


def shard_state_by_node(
    spec: WorldSpec, state: WorldState, mesh: Mesh,
    axis_name: str = NODE_AXIS,
) -> WorldState:
    """Place the world on the mesh: big per-row arrays sharded, rest
    replicated.

    The task/user arrays (the memory that scales with world size) split
    row-wise; the small pytrees (node platform state, fogs, broker view,
    metrics) are committed replicated to every device — they are KBs.
    """
    n = mesh.shape[axis_name]
    if spec.n_users % n or spec.task_capacity % n:
        raise ValueError(
            f"the {n}-device mesh axis must divide n_users "
            f"({spec.n_users}) and task capacity ({spec.task_capacity}) — "
            "pad users/max_sends_per_user to a multiple"
        )
    leaf = replica_sharding(mesh, axis_name)  # leading-axis row sharding
    repl = NamedSharding(mesh, P())

    def rows(tree):
        return jax.tree.map(lambda x: jax.device_put(x, leaf(x)), tree)

    def replicated(tree):
        return jax.tree.map(lambda x: jax.device_put(x, repl), tree)

    return state.replace(
        tasks=rows(state.tasks),
        users=rows(state.users),
        nodes=replicated(state.nodes),
        fogs=replicated(state.fogs),
        broker=replicated(state.broker),
        metrics=replicated(state.metrics),
    )


# simlint: disable=R6 -- a chained run_node_sharded call can pass an
# already-sharded state whose device_put is a no-op aliasing the caller's
# buffers; donating here would invalidate them behind the caller's back
@functools.partial(jax.jit, static_argnums=(0, 1))
def _advance(
    spec: WorldSpec, n_ticks: Optional[int], state: WorldState,
    net: NetParams, bounds: MobilityBounds,
) -> WorldState:
    final, _ = run(spec, state, net, bounds, n_ticks=n_ticks)
    return final


def run_node_sharded(
    spec: WorldSpec,
    state: WorldState,
    net: NetParams,
    bounds: MobilityBounds,
    mesh: Mesh,
    n_ticks: Optional[int] = None,
    axis_name: str = NODE_AXIS,
) -> WorldState:
    """Advance a node-sharded world over the horizon.

    The jitted program is cached on (spec, n_ticks) — repeat/chained calls
    trace once — and GSPMD propagates the input shardings to the outputs,
    so the table never gathers onto one device between calls.
    """
    state = shard_state_by_node(spec, state, mesh, axis_name)
    return _advance(spec, n_ticks, state, net, bounds)
