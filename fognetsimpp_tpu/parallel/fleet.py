"""Replica-sharded fleet runner: R independent worlds, D devices, one jit.

The production multi-chip throughput path (ISSUE 3).  The existing
entries each solve half the problem: :func:`replicas.run_replicated`
vmaps the replica axis but stays on one device, and
:func:`mesh.run_sharded` lays the batch on a mesh but rebuilds its jit
wrapper (and so recompiles) per call and never donates the dominant
carry.  The fleet runner is the measured-headline composition:

  * the batched world rides a ``NamedSharding(mesh, P('replica', ...))``
    layout (no ``pmap`` — one program, XLA partitions it), so each
    device advances ``R / D`` replicas with zero steady-state
    collectives;
  * the whole horizon runs inside ONE jitted, carry-DONATED
    ``lax.scan`` (simlint R6: the replica-batched task table dominates
    the bytes/tick footprint; donation lets XLA serve the scan carry
    from the input buffers in place);
  * per-replica PRNG keys are folded from one root key
    (:func:`fold_replica_keys`), so a pipeline of fleets draws
    decorrelated streams without host-side key plumbing;
  * metric reduction happens ON DEVICE (:func:`fleet_decisions`): the
    timed section of a benchmark fetches one scalar pair per jitted
    call — the same flat-dispatch discipline ``bench.py`` enforces for
    the single-chip number;
  * per-tick series offload is chunked (:func:`run_fleet_series`):
    within a chunk the vectors stay replica-sharded on device (the scan
    never syncs), each finished chunk offloads to the host, so long
    horizons record in bounded device memory.

Correctness gate: per-replica state hashes equal the vmap
(:func:`replicas.run_replicated`) path bit-for-bit on every world
tested — ``tests/test_fleet.py``, runnable on CPU via the forced
8-virtual-device topology (``conftest.py``).
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.engine import _dealias_for_donation, run
from ..dynspec import DynSpec, promote_default, registry_note, split_spec
from ..net.mobility import MobilityBounds
from ..net.topology import NetParams
from ..spec import WorldSpec
from ..state import WorldState
from .mesh import REPLICA_AXIS, make_mesh, replica_sharding, shard_world


#: The fleet's headline sharding claim, made statically checkable: the
#: replica-DP layout compiles to ZERO steady-state collectives (replicas
#: never communicate).  ``tools/hloaudit`` audits the compiled fleet
#: scan against this empty table (rule A3), so a future engine change
#: that makes GSPMD insert a cross-replica combine fails CI instead of
#: silently taxing every tick.
DECLARED_COLLECTIVES: Dict[str, set] = {}


def fold_replica_keys(key: jax.Array, n_replicas: int) -> jax.Array:
    """(R, 2) per-replica keys: ``fold_in(key, r)`` for each replica id.

    Folding (instead of ``split``) keys each replica's stream on its own
    stable id, so replica ``r`` draws the same trajectory whether the
    fleet runs 8 or 800 replicas around it — sweep grids stay
    comparable across fleet sizes.
    """
    return jax.vmap(lambda r: jax.random.fold_in(key, r))(
        jnp.arange(n_replicas, dtype=jnp.int32)
    )


def _check_fleet_spec(spec: WorldSpec) -> None:
    # chaos worlds run here since the per-replica chaos re-key landed in
    # replicas.replicate_state (fold_in(chaos_key, replica): every
    # replica draws its own fault schedule, so the old share-one-
    # schedule rejection is gone); the federated hierarchy still gates
    from ..hier.federation import hier_reject_reason

    reason = hier_reject_reason(spec, "fleet")
    if reason is not None:
        raise ValueError(reason)


def _check_divisible(n_replicas: int, mesh: Mesh) -> None:
    d = int(mesh.devices.size)
    if n_replicas % d != 0:
        raise ValueError(
            f"fleet replica count {n_replicas} does not divide evenly "
            f"over the {d}-device mesh (fixed shapes: pad the replica "
            "count to a multiple of the mesh size)"
        )


@functools.partial(jax.jit, static_argnums=(0, 1), donate_argnums=(2,))
def _fleet_run(
    spec: WorldSpec, n_ticks: Optional[int], batch: WorldState,
    net: NetParams, bounds: MobilityBounds,
    dyn_rows: Optional[DynSpec] = None,
) -> WorldState:
    def run_one(s, net_, bounds_, dyn_):
        final, _ = run(spec, s, net_, bounds_, n_ticks=n_ticks, dyn=dyn_)
        return final

    return jax.vmap(
        run_one,
        in_axes=(0, None, None, 0 if dyn_rows is not None else None),
    )(batch, net, bounds, dyn_rows)


def _fleet_dyn_rows(
    spec: WorldSpec, R: int, mesh: Mesh, dyn_rows, donate: bool,
):
    """Shared promotion front half of the fleet entries (ISSUE 20):
    split the spec on its shape key, note the program, and return
    ``(run_spec, dyn_rows)`` with ``dyn_rows`` leading-axis ``R`` and
    replica-sharded like the batch.  A ``None`` ``dyn_rows`` broadcasts
    the spec's own promoted leaves to every replica — the plain
    promoted fleet and a ``sweep_dyn`` grid then share ONE compiled
    program (the rows are the only difference, and they are operands).
    """
    run_spec, dyn = split_spec(spec)
    registry_note(run_spec, jax.default_backend(), donated=donate)
    if dyn_rows is None:
        dyn_rows = jax.tree.map(
            lambda x: jnp.broadcast_to(
                jnp.asarray(x)[None, ...], (R,) + jnp.shape(x)
            ),
            dyn,
        )
    else:
        Rd = int(jnp.shape(jax.tree.leaves(dyn_rows)[0])[0])
        if Rd != R:
            raise ValueError(
                f"dyn_rows carries {Rd} replica rows for a {R}-replica "
                "batch — one promoted-knob row per replica"
            )
    leaf = replica_sharding(mesh)
    return run_spec, jax.tree.map(
        lambda x: jax.device_put(x, leaf(x)), dyn_rows
    )


def run_fleet(
    spec: WorldSpec,
    batch: WorldState,
    net: NetParams,
    bounds: MobilityBounds,
    mesh: Optional[Mesh] = None,
    n_ticks: Optional[int] = None,
    donate: bool = True,
    promote: Optional[bool] = None,
    dyn_rows: Optional[DynSpec] = None,
) -> WorldState:
    """Advance every replica of ``batch`` over the mesh; returns the
    sharded final batch.

    ``batch`` is a replicated world (leading replica axis from
    :func:`replicas.replicate_state`); the replica count must divide the
    mesh size.  Identical per-replica semantics to
    :func:`replicas.run_replicated` (``tests/test_fleet.py`` asserts
    per-replica state-hash equality) — but sharded, compile-cached
    across calls (the jit is module-level, keyed on ``(spec,
    n_ticks)``), and carry-donated by default: do not reuse ``batch``
    after calling unless ``donate=False``.

    ``promote`` (default: ``FNS_SPEC_PROMOTE``, on) runs the promoted
    program: the jit keys on the spec's SHAPE KEY and every promoted
    knob rides a replica-sharded DynSpec row operand — so a warm knob
    retune (or a whole ``sweep_dyn`` grid via ``dyn_rows``, one
    promoted-leaf row per replica) re-executes the cached program with
    ZERO compile events.  Bit-exact vs ``promote=False`` and the vmap
    reference (``tests/test_sharded_dynspec.py``).
    """
    if promote is None:
        promote = promote_default()
    if dyn_rows is not None and not promote:
        raise ValueError(
            "dyn_rows carries per-replica promoted knobs; it needs the "
            "promoted path (promote=True)"
        )
    if mesh is None:
        mesh = make_mesh()
    R = int(jnp.shape(jax.tree.leaves(batch)[0])[0])
    _check_fleet_spec(spec)
    _check_divisible(R, mesh)
    batch, net, bounds, _ = shard_world(batch, net, bounds, mesh)
    if promote:
        run_spec, dyn_rows = _fleet_dyn_rows(
            spec, R, mesh, dyn_rows, donate
        )
    else:
        run_spec = spec
    if not donate:
        # one donating jit entry either way (no second compile cache):
        # the keep path hands the donation a private copy, so the
        # caller's batch — typically shared with the vmap path by the
        # equivalence tests — survives
        batch = jax.tree.map(jnp.copy, batch)
    return _fleet_run(run_spec, n_ticks, _dealias_for_donation(batch),
                      net, bounds, dyn_rows)


# simlint: disable=R6 -- donation is semantically wrong here: the batch
# is the pristine TEMPLATE every pipeline iteration re-keys, and timed
# callers (bench.fleet_measurement) reuse it across repeated calls; the
# outputs are two scalars, so donated buffers could never be aliased
# anyway (XLA would warn 'donated buffers were not usable' on every call)
@functools.partial(jax.jit, static_argnums=(0, 1))
def _fleet_pipeline(
    spec: WorldSpec, n_replicas: int, batch: WorldState,
    net: NetParams, bounds: MobilityBounds, keys: jax.Array,
    dyn: Optional[DynSpec] = None,
) -> Tuple[jax.Array, jax.Array]:
    def body(_, k):
        b = batch.replace(key=fold_replica_keys(k, n_replicas))

        def run_one(s, net_, bounds_):
            # dyn (the replicated promoted-knob operand) is closed over:
            # every replica of every pipelined fleet shares one spec, so
            # one scalar set broadcasts through the vmap — and because
            # it is a jit OPERAND, a warm knob retune re-executes this
            # scan instead of re-tracing it
            final, _ = run(spec, s, net_, bounds_, dyn=dyn)
            return final.metrics

        m = jax.vmap(run_one, in_axes=(0, None, None))(b, net, bounds)
        return 0, (jnp.sum(m.n_scheduled), jnp.max(m.n_deferred_max))

    _, (d, dm) = jax.lax.scan(body, 0, keys)
    return jnp.sum(d), jnp.max(dm)


def fleet_decisions(
    spec: WorldSpec,
    batch: WorldState,
    net: NetParams,
    bounds: MobilityBounds,
    keys: jax.Array,
    mesh: Optional[Mesh] = None,
    promote: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Pipelined fleet throughput kernel: ONE jitted call runs
    ``len(keys)`` complete fleets (fresh folded keys each, same compiled
    body) and reduces the metrics on device.

    Returns ``(total decisions, max deferred backlog)`` as two 0-d
    device arrays — the only device->host fetch a timed section needs,
    so the tunnel's flat per-call dispatch cost is paid once per
    measurement instead of once per replica (``bench.py`` methodology).

    ``batch`` is a pristine template (each pipeline iteration re-keys
    it); it is NOT donated — timed callers reuse one batch across
    repeated calls.  Under promotion (the default) the pipeline is
    keyed on the spec's shape key with the promoted knobs riding a
    mesh-replicated DynSpec operand — a retuned rerun is compile-free.
    """
    if promote is None:
        promote = promote_default()
    if mesh is None:
        mesh = make_mesh()
    R = int(jnp.shape(jax.tree.leaves(batch)[0])[0])
    _check_fleet_spec(spec)
    _check_divisible(R, mesh)
    batch, net, bounds, _ = shard_world(batch, net, bounds, mesh)
    if promote:
        run_spec, dyn = split_spec(spec)
        registry_note(run_spec, jax.default_backend(), donated=False)
        dyn = jax.device_put(dyn, NamedSharding(mesh, P()))
    else:
        run_spec, dyn = spec, None
    return _fleet_pipeline(run_spec, R, batch, net, bounds, keys, dyn)


def fleet_busy_fractions_per_replica(
    spec: WorldSpec, final_batch: WorldState
) -> Optional[np.ndarray]:
    """Per-replica per-fog busy fractions, shape ``(R, F)``.

    The second PR-4 follow-up: the fleet's OpenMetrics exposition
    publishes these as one gauge sample per ``(fleet=replica, fog)``
    label pair instead of collapsing the replica axis to its mean — a
    sweep's per-replica behaviour (different policies, loads, seeds) is
    visible to the scrape, not averaged away.  One host gather of the
    (R, F) busy-tick counters; ``None`` when ``spec.telemetry`` was off.
    """
    if not spec.telemetry:
        return None
    busy = np.asarray(final_batch.telem.busy_ticks, np.float64)  # (R, F)
    ticks = np.maximum(
        np.asarray(final_batch.telem.ticks, np.float64), 1.0
    )  # (R,)
    return busy / ticks[:, None]


def fleet_phase_work(
    spec: WorldSpec, final_batch: WorldState
) -> Optional[np.ndarray]:
    """Per-replica per-phase work counters, shape ``(R, P)``.

    The fleet half of the ISSUE 11 phase-attribution story: each
    replica's vmapped tick books its own ``phase_work`` vector, and the
    fleet OpenMetrics exposition publishes one sample per
    ``(fleet=replica, phase)`` label pair
    (``fns_fleet_phase_work{fleet="r",phase="spawn"}``) — so a replica
    whose work profile shifted (a policy sweep cell gone degenerate, a
    replica starving on drops) is visible in the scrape instead of
    averaged away, the ``fleet_busy_fractions_per_replica``
    discipline.  One host gather; ``None`` when ``spec.telemetry`` was
    off.
    """
    if not spec.telemetry:
        return None
    return np.asarray(final_batch.telem.phase_work, np.int64)  # (R, P)


def fleet_latency_hist(
    spec: WorldSpec, final_batch: WorldState
) -> Optional[Dict]:
    """Replica-MERGED streaming latency histogram of a finished fleet
    run (ISSUE 6): one host gather of the ``(R, F, B)`` bucket counts,
    summed over the replica axis into the same summary dict a
    single-world run produces (:func:`telemetry.health.hist_summary`
    detects the leading axis itself) — per-fog counts, ``p50/p95/p99``
    quantiles, sums.  The fleet's OpenMetrics exposition renders this
    as the ``fns_fleet_task_latency`` histogram family
    (``runtime/recorder.record_fleet_run``).  ``None`` when
    ``spec.telemetry_hist`` was off.
    """
    from ..telemetry.health import hist_summary

    return hist_summary(spec, final_batch)


def fleet_busy_fractions(
    spec: WorldSpec, final_batch: WorldState
) -> Optional[np.ndarray]:
    """Replica-mean per-fog busy fraction of a finished fleet run.

    The fleet analog of :func:`telemetry.metrics.busy_fractions` — kept
    for summary readers; the OpenMetrics exposition uses
    :func:`fleet_busy_fractions_per_replica` so replicas stay
    distinguishable.  ``None`` when ``spec.telemetry`` was off.
    """
    per = fleet_busy_fractions_per_replica(spec, final_batch)
    return None if per is None else per.mean(axis=0)


@functools.partial(jax.jit, static_argnums=(0, 1), donate_argnums=(2,))
def _fleet_series_chunk(
    spec: WorldSpec, n_ticks: int, batch: WorldState,
    net: NetParams, bounds: MobilityBounds,
    dyn: Optional[DynSpec] = None,
):
    def run_one(s, net_, bounds_):
        # one replicated DynSpec set shared by every replica (closure
        # capture broadcasts through the vmap, same as _fleet_pipeline)
        return run(spec, s, net_, bounds_, n_ticks=n_ticks, dyn=dyn)

    return jax.vmap(run_one, in_axes=(0, None, None))(batch, net, bounds)


def run_fleet_series(
    spec: WorldSpec,
    batch: WorldState,
    net: NetParams,
    bounds: MobilityBounds,
    mesh: Optional[Mesh] = None,
    chunk_ticks: int = 4096,
    promote: Optional[bool] = None,
) -> Tuple[WorldState, Dict[str, np.ndarray]]:
    """Fleet run with per-tick series recording, chunked for bounded
    device memory.

    Within a chunk the series vectors stay replica-sharded on device
    (they inherit the carry's sharding — the scan never syncs); each
    finished chunk is then offloaded to the host, so the device holds at
    most ONE chunk of series at a time and arbitrarily long horizons
    record in bounded device memory (the ``run_chunked`` discipline,
    extended to series).  Returns ``(final_batch, series)`` where each
    series leaf is a host array of shape ``(R, n_ticks, ...)`` — the
    batched analog of ``run``'s series dict.  The carry is DONATED
    between chunks (do not reuse ``batch``); results are bit-identical
    to one straight ``run_replicated`` with recording
    (``tests/test_fleet.py``).  Promotion (the default) keys the chunk
    program on the shape key with one mesh-replicated DynSpec operand,
    so equal-size chunks AND warm knob retunes share one compile.
    """
    if not spec.record_tick_series:
        raise ValueError(
            "run_fleet_series needs spec.record_tick_series=True; for "
            "counters-only fleets use run_fleet"
        )
    if promote is None:
        promote = promote_default()
    if mesh is None:
        mesh = make_mesh()
    R = int(jnp.shape(jax.tree.leaves(batch)[0])[0])
    _check_fleet_spec(spec)
    _check_divisible(R, mesh)
    batch, net, bounds, _ = shard_world(batch, net, bounds, mesh)
    if promote:
        run_spec, dyn = split_spec(spec)
        registry_note(run_spec, jax.default_backend(), donated=True)
        dyn = jax.device_put(dyn, NamedSharding(mesh, P()))
    else:
        run_spec, dyn = spec, None
    total = spec.n_ticks
    chunk = min(chunk_ticks, total)
    chunks = []
    done = 0
    while done < total:
        n = min(chunk, total - done)
        batch, series = _fleet_series_chunk(
            run_spec, n, _dealias_for_donation(batch), net, bounds, dyn
        )
        # host offload per chunk: frees the chunk's device buffers
        # before the next chunk runs (bounded device memory)
        chunks.append({k: np.asarray(v) for k, v in series.items()})
        done += n
    gathered = {
        k: np.concatenate([c[k] for c in chunks], axis=1)
        for k in chunks[0]
    }
    return batch, gathered
