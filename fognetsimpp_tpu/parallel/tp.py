"""Node-axis (tensor-parallel) sharding of the scheduler kernel.

When the fog population is large enough that the broker's ``(K, F)`` score
matrix should be split across chips, the argmin decision becomes a
two-stage combine: each shard scores its local fog columns and reduces to a
per-task (local-min, global-index) pair, then one ``all_gather`` across the
``fog`` mesh axis picks the global winner.  First-wins tie-breaking (the
``<`` scan of ``src/mqttapp/BrokerBaseApp3.cc:272-279``) is preserved
because both the local argmin and the cross-shard pick prefer the lowest
index.

This is the SURVEY.md §2.3 TP row: state sharded over mesh axes via
``shard_map``, with XLA collectives over ICI doing the combine — the
communication pattern NCCL/MPI would carry in a torch framework.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 exposes shard_map at top level (check_vma kwarg)
    from jax import shard_map as _shard_map

    def shard_map(f, **kw):
        return _shard_map(f, **kw)
except ImportError:  # pragma: no cover - older jax: check_rep, not check_vma
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, **kw):
        kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_old(f, **kw)

FOG_AXIS = "fog"

#: Collectives this module's compiled programs are ALLOWED to contain,
#: keyed by the op_name scope they must attribute to — the contract
#: ``tools/hloaudit`` enforces on the compiled artifact (audit rule A3).
#: The two-stage combine is exactly one all_gather family inside the
#: shard_map body; anything else (an accidental all-reduce from a leaked
#: sharding annotation, a GSPMD resharding all-to-all) is a fatal CI
#: finding.  Extend this table in the same change that adds a collective.
DECLARED_COLLECTIVES = {"shmap_body": {"all-gather"}}


def sharded_min_busy(
    mesh: Mesh,
    mask: jax.Array,  # (K,) bool — tasks being decided (replicated)
    mips_req: jax.Array,  # (K,) f32 (replicated)
    view_busy: jax.Array,  # (F,) f32 — sharded over the fog axis
    view_mips: jax.Array,  # (F,) f32 — sharded over the fog axis
    registered: jax.Array,  # (F,) bool — sharded over the fog axis
    divisor: Optional[jax.Array] = None,  # () f32 — brokers[0] MIPS (the
    #   mips0_divisor bug, BrokerBaseApp3.cc:268); None = per-fog MIPS
    axis_name: str = FOG_AXIS,
) -> jax.Array:
    """MIN_BUSY over a fog axis sharded across the mesh. Returns (K,) i32.

    Matches :func:`fognetsimpp_tpu.ops.sched.schedule_batch` with
    ``policy=MIN_BUSY`` exactly (a test asserts equality), including the
    all-unavailable -> -1 guard.
    """
    n_shards = mesh.shape[axis_name]
    F = view_busy.shape[0]
    assert F % n_shards == 0, "fog count must divide the mesh axis"
    f_local = F // n_shards
    big = jnp.float32(3.4e38)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(axis_name), P(axis_name), P(axis_name)),
        out_specs=P(),
        check_vma=False,  # output is replicated via all_gather; the static
        #                   replication checker can't see through the
        #                   argmin/take combine
    )
    def kernel(mask_, req_, busy_, mips_, reg_):
        shard = jax.lax.axis_index(axis_name)
        if divisor is None:
            est = jnp.where(
                mips_ > 0, req_[:, None] / jnp.maximum(mips_, 1e-30)[None, :],
                jnp.inf,
            )
        else:
            est = jnp.where(
                divisor > 0,
                req_[:, None] / jnp.maximum(divisor, 1e-30),
                jnp.inf,
            ) * jnp.ones((1, f_local), jnp.float32)
        scores = jnp.where(reg_[None, :], busy_[None, :] + est, big)
        scores = jnp.nan_to_num(scores, posinf=big)
        loc_arg = jnp.argmin(scores, axis=1).astype(jnp.int32)  # (K,)
        loc_min = jnp.min(scores, axis=1)  # (K,)
        glob_idx = shard * f_local + loc_arg
        any_avail = jnp.any(reg_)

        mins = jax.lax.all_gather(loc_min, axis_name)  # (S, K)
        idxs = jax.lax.all_gather(glob_idx, axis_name)  # (S, K)
        avails = jax.lax.all_gather(any_avail, axis_name)  # (S,)
        # lowest score wins; ties -> lowest shard (hence lowest global index)
        win_shard = jnp.argmin(mins, axis=0)  # (K,)
        choice = jnp.take_along_axis(idxs, win_shard[None, :], axis=0)[0]
        choice = jnp.where(jnp.any(avails), choice, -1)
        return jnp.where(mask_, choice, -1).astype(jnp.int32)

    return kernel(mask, mips_req, view_busy, view_mips, registered)
