"""Parallel execution: replicas, mesh sharding, policy sweeps.

The reference is a single-threaded sequential DES — OMNeT++ 4.6 executes
one event at a time and the repo never enables parsim (SURVEY.md §2.3).
The TPU-native scale-out axes this package provides instead:

  * **DP** — :func:`replicas.run_replicated`: ``vmap`` over Monte-Carlo
    world replicas, optionally sharded over a device mesh
    (:mod:`mesh`) so each chip advances its own slice of replicas.
    :mod:`fleet` is the production composition (ISSUE 3): the sharded
    batch under one jitted carry-donated scan, device-resident metric
    reduction, chunked sharded series offload — the measured
    multi-chip headline path (``bench.py --fleet`` /
    ``MULTICHIP_r*.json``).
  * **TP** — :mod:`taskshard`: ONE world's user/task axis row-sharded
    over the mesh via an explicit ``shard_map`` tick (hand-placed
    broker↔fog ``psum`` combines + ring ``ppermute`` arrival exchange,
    audited/budgeted in CI; GSPMD fallback for worlds outside the
    dense-broker family) — the HBM-capacity axis, measured at 2^20
    users on the 8-device mesh (``bench.py --tp``).  :mod:`tp` keeps
    the fog-axis shard_map scheduler (cross-shard argmin combines) for
    fog populations exceeding one chip's comfortable tile.
  * **EP** — :func:`sweep.sweep_policies`: the policy axis of the grid
    (the reference's dead ``algo`` parameter made sweepable), and
    :func:`sweep.sweep_explore`: the exploration-rate axis of the
    learned bandit schedulers (``LearnState.explore`` as carry data —
    the whole rate × load grid under one compile).

Collectives ride the mesh (ICI within a slice, DCN across) as XLA
collectives — hand-placed ``psum``/``ppermute`` inside the shard_map
ticks, never raw transports — with one opt-in exception: the TP
arrival exchange's Pallas remote-DMA ring kernel
(``ops/pallas_kernels.ring_all_gather_pallas``, ``FNS_PALLAS_RING=1``).
Every collective a sharded program may emit is declared next to its
module (``DECLARED_COLLECTIVES``) and verified against the compiled
artifact by ``tools/hloaudit``.
"""
from .replicas import replicate_state, run_replicated, replica_counters  # noqa: F401
from .mesh import make_mesh, replica_sharding, shard_replicas, run_sharded  # noqa: F401
from .fleet import (  # noqa: F401
    fleet_decisions,
    fold_replica_keys,
    run_fleet,
    run_fleet_series,
)
from .multihost import global_mesh, initialize  # noqa: F401
from .sweep import sweep_dyn, sweep_explore, sweep_policies  # noqa: F401
from .taskshard import (  # noqa: F401
    pad_users_to_multiple,
    ring_all_gather,
    run_node_sharded,
    run_tp_chunked,
    run_tp_sharded,
    shard_state_by_node,
    unstamp_tp_carry,
)
from .tp import sharded_min_busy  # noqa: F401
