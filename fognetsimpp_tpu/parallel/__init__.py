"""Parallel execution: replicas, mesh sharding, policy sweeps.

The reference is a single-threaded sequential DES — OMNeT++ 4.6 executes
one event at a time and the repo never enables parsim (SURVEY.md §2.3).
The TPU-native scale-out axes this package provides instead:

  * **DP** — :func:`replicas.run_replicated`: ``vmap`` over Monte-Carlo
    world replicas, optionally sharded over a device mesh
    (:mod:`mesh`) so each chip advances its own slice of replicas.
    :mod:`fleet` is the production composition (ISSUE 3): the sharded
    batch under one jitted carry-donated scan, device-resident metric
    reduction, chunked sharded series offload — the measured
    multi-chip headline path (``bench.py --fleet`` /
    ``MULTICHIP_r*.json``).
  * **TP** — :mod:`tp`: node-axis sharding of the scheduler's score
    matrix via ``shard_map`` with cross-shard argmin combines, for worlds
    whose fog population exceeds one chip's comfortable tile.
  * **EP** — :func:`sweep.sweep_policies`: the policy axis of the grid
    (the reference's dead ``algo`` parameter made sweepable), and
    :func:`sweep.sweep_explore`: the exploration-rate axis of the
    learned bandit schedulers (``LearnState.explore`` as carry data —
    the whole rate × load grid under one compile).

Collectives ride the mesh (ICI within a slice, DCN across) through XLA —
``all_gather``/``pmin`` inserted by ``shard_map`` — never hand-written
transports.
"""
from .replicas import replicate_state, run_replicated, replica_counters  # noqa: F401
from .mesh import make_mesh, replica_sharding, shard_replicas, run_sharded  # noqa: F401
from .fleet import (  # noqa: F401
    fleet_decisions,
    fold_replica_keys,
    run_fleet,
    run_fleet_series,
)
from .multihost import global_mesh, initialize  # noqa: F401
from .sweep import sweep_explore, sweep_policies  # noqa: F401
from .taskshard import run_node_sharded, shard_state_by_node  # noqa: F401
from .tp import sharded_min_busy  # noqa: F401
