"""Policy × load sweep driver (the EP axis of SURVEY.md §2.3).

Reproduces the shape of the BASELINE.json sweep configs ("10k nodes × 4
schedulers × 256 load levels"): the *policy* axis is static (each policy is
a different compiled branch — one compile per policy, reused across all
loads), while the *load* axis is dynamic — the per-user publish interval is
a state array (``users.send_interval``, the reference's volatile
``sendInterval`` NED parameter), so every load level × Monte-Carlo replica
runs inside one ``vmap`` and shards over the mesh with zero extra compiles.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .mesh import run_sharded
from .replicas import replica_counters, replicate_state, run_replicated


def sweep_policies(
    build: Callable[..., tuple],
    policies: Sequence[int],
    load_intervals: Sequence[float],
    n_replicas_per_load: int = 1,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    n_ticks: Optional[int] = None,
    **build_kwargs,
) -> Dict[int, Dict[str, np.ndarray]]:
    """Run every (policy, load, replica) combination; return counter grids.

    ``build`` is a scenario builder (e.g. ``scenarios.smoke.build``)
    accepting ``policy=`` and returning ``(spec, state, net, bounds)``.
    ``load_intervals`` are publish intervals in seconds (smaller = heavier).

    Returns ``{policy: {counter: (n_loads, n_replicas) array}}``.
    """
    n_loads = len(load_intervals)
    R = n_loads * n_replicas_per_load
    out: Dict[int, Dict[str, np.ndarray]] = {}
    # Build the world for the HEAVIEST load level so capacity-derived shapes
    # (max_sends_per_user, arrival_window) fit every level; lighter levels
    # just publish less.  Overriding send_interval only post-build would
    # silently cap heavy loads at the light-load send budget.
    build_kwargs.setdefault("send_interval", min(load_intervals))
    for pol in policies:
        spec, state, net, bounds = build(policy=int(pol), **build_kwargs)
        batch = replicate_state(spec, state, R, seed=seed)
        si = jnp.repeat(
            jnp.asarray(load_intervals, jnp.float32), n_replicas_per_load
        )  # (R,)
        batch = batch.replace(
            users=batch.users.replace(
                send_interval=jnp.broadcast_to(
                    si[:, None], (R, spec.n_users)
                )
            )
        )
        if mesh is not None:
            final = run_sharded(spec, batch, net, bounds, mesh, n_ticks=n_ticks)
        else:
            final = run_replicated(spec, batch, net, bounds, n_ticks=n_ticks)
        out[int(pol)] = {
            k: v.reshape(n_loads, n_replicas_per_load)
            for k, v in replica_counters(final).items()
        }
    return out
