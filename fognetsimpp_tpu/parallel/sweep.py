"""Policy × load sweep driver (the EP axis of SURVEY.md §2.3).

Reproduces the shape of the BASELINE.json sweep configs ("10k nodes × 4
schedulers × 256 load levels").  The *load* axis is always dynamic — the
per-user publish interval is a state array (``users.send_interval``, the
reference's volatile ``sendInterval`` NED parameter), so every load level
× Monte-Carlo replica runs inside one ``vmap``.  The *policy* axis has two
modes: static (one compile per policy — any member of ``spec.Policy``,
incl. LOCAL_FIRST/MAX_MIPS and the learned bandits) or ``dynamic=True``
(``Policy.DYNAMIC``: the policy id rides in ``BrokerView.policy_id`` as
traced data, so the ENTIRE grid is one compile; the argmin family
``spec.ARGMIN_FAMILY`` plus — when bandit ids appear in the grid — the
learned ``spec.LEARNED_POLICIES``).  For the learned policies the
*exploration rate* is one more data axis (``LearnState.explore`` is
carry-resident and traced): :func:`sweep_explore` runs a whole
exploration-rate × load grid for one bandit under a single compile.
Either way the grid shards over the mesh.
"""
from __future__ import annotations

import dataclasses
import itertools

from typing import Callable, Dict, List, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..spec import ARGMIN_FAMILY, LEARNED_POLICIES, Policy
from .mesh import run_sharded
from .replicas import replica_counters, replicate_state, run_replicated


def sweep_policies(
    build: Callable[..., tuple],
    policies: Sequence[int],
    load_intervals: Sequence[float],
    n_replicas_per_load: int = 1,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    n_ticks: Optional[int] = None,
    dynamic: bool = False,
    **build_kwargs,
) -> Dict[int, Dict[str, np.ndarray]]:
    """Run every (policy, load, replica) combination; return counter grids.

    ``build`` is a scenario builder (e.g. ``scenarios.smoke.build``)
    accepting ``policy=`` and returning ``(spec, state, net, bounds)``.
    ``load_intervals`` are publish intervals in seconds (smaller = heavier).

    ``dynamic=True`` runs the whole grid under ONE compile: the world is
    built with ``Policy.DYNAMIC`` and each replica carries its policy id
    as data (the argmin family, plus the learned bandit ids when any
    appear in ``policies`` — the build then carries live LearnState via
    ``learn_in_dynamic``).  The static path compiles one program per
    policy — prefer it when a policy outside those families is in the
    grid.

    Returns ``{policy: {counter: (n_loads, n_replicas) array}}``.
    """
    n_loads = len(load_intervals)
    # Build the world for the HEAVIEST load level so capacity-derived shapes
    # (max_sends_per_user, arrival_window) fit every level; lighter levels
    # just publish less.  Overriding send_interval only post-build would
    # silently cap heavy loads at the light-load send budget.
    build_kwargs.setdefault("send_interval", min(load_intervals))

    def load_axis(batch, spec, R):
        si = jnp.tile(
            jnp.repeat(
                jnp.asarray(load_intervals, jnp.float32), n_replicas_per_load
            ),
            R // (n_loads * n_replicas_per_load),
        )  # (R,)
        return batch.replace(
            users=batch.users.replace(
                send_interval=jnp.broadcast_to(si[:, None], (R, spec.n_users))
            )
        )

    def advance(spec, batch, net, bounds):
        if mesh is not None:
            return run_sharded(spec, batch, net, bounds, mesh, n_ticks=n_ticks)
        return run_replicated(spec, batch, net, bounds, n_ticks=n_ticks)

    out: Dict[int, Dict[str, np.ndarray]] = {}
    if dynamic:
        argmin_ids = {int(p) for p in ARGMIN_FAMILY}
        learned_ids = {int(p) for p in LEARNED_POLICIES}
        if any(int(p) not in argmin_ids | learned_ids for p in policies):
            names = ", ".join(
                f"{p.name.lower()}={int(p)}"
                for p in ARGMIN_FAMILY + LEARNED_POLICIES
            )
            raise ValueError(
                f"dynamic sweeps cover the traced-dispatch families "
                f"({names})"
            )
        if any(int(p) in learned_ids for p in policies):
            # carry live bandit state + extend the traced switch
            build_kwargs.setdefault("learn_in_dynamic", True)
        spec, state, net, bounds = build(
            policy=int(Policy.DYNAMIC), **build_kwargs
        )
        P = len(policies)
        nlr = n_loads * n_replicas_per_load
        R = P * nlr
        # one nlr-wide replica block, tiled per policy: every policy sees
        # the SAME per-replica PRNG keys/start times a static per-policy
        # sweep would use, so dynamic == static exactly
        base = replicate_state(spec, state, nlr, seed=seed)
        batch = jax.tree.map(
            lambda x: jnp.concatenate([x] * P, axis=0), base
        )
        # replica order: (policy, load, rep); the load axis tiles per policy
        pol_ids = jnp.repeat(
            jnp.asarray([int(p) for p in policies], jnp.int32), nlr
        )
        batch = batch.replace(
            broker=batch.broker.replace(policy_id=pol_ids)
        )
        batch = load_axis(batch, spec, R)
        final = advance(spec, batch, net, bounds)
        counters = replica_counters(final)
        for i, pol in enumerate(policies):
            sl = slice(i * nlr, (i + 1) * nlr)
            out[int(pol)] = {
                k: v[sl].reshape(n_loads, n_replicas_per_load)
                for k, v in counters.items()
            }
        return out

    R = n_loads * n_replicas_per_load
    for pol in policies:
        spec, state, net, bounds = build(policy=int(pol), **build_kwargs)
        batch = replicate_state(spec, state, R, seed=seed)
        batch = load_axis(batch, spec, R)
        final = advance(spec, batch, net, bounds)
        out[int(pol)] = {
            k: v.reshape(n_loads, n_replicas_per_load)
            for k, v in replica_counters(final).items()
        }
    return out


def sweep_explore(
    build: Callable[..., tuple],
    policy: int,
    explore_rates: Sequence[float],
    load_intervals: Sequence[float],
    n_replicas_per_load: int = 1,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    n_ticks: Optional[int] = None,
    **build_kwargs,
) -> Dict[float, Dict[str, np.ndarray]]:
    """Exploration-rate × load grid for ONE learned policy, one compile.

    The bandit's exploration rate lives in the scan carry
    (``LearnState.explore``, traced) rather than the static spec, so the
    whole grid is a single replica fan-out of one compiled program — no
    ``Policy.DYNAMIC`` switch needed, the policy itself is static.
    Replica order is (explore, load, rep), mirroring
    :func:`sweep_policies`' (policy, load, rep).

    Returns ``{explore_rate: {counter: (n_loads, n_replicas) array}}``;
    each grid additionally carries ``lat_mean_s`` (mean credited task
    latency — the regret harness's raw material) and ``lat_cnt``.
    """
    if int(policy) not in {int(p) for p in LEARNED_POLICIES}:
        names = ", ".join(p.name.lower() for p in LEARNED_POLICIES)
        raise ValueError(
            f"sweep_explore sweeps the learned policies ({names}); got "
            f"policy id {int(policy)}"
        )
    n_loads, n_exp = len(load_intervals), len(explore_rates)
    build_kwargs.setdefault("send_interval", min(load_intervals))
    spec, state, net, bounds = build(policy=int(policy), **build_kwargs)
    nlr = n_loads * n_replicas_per_load
    R = n_exp * nlr
    # one nlr-wide replica block, tiled per exploration rate: every rate
    # sees the same per-replica PRNG keys/start times (grid cells differ
    # only where the experiment says they should)
    base = replicate_state(spec, state, nlr, seed=seed)
    batch = jax.tree.map(lambda x: jnp.concatenate([x] * n_exp, axis=0), base)
    exp_col = jnp.repeat(
        jnp.asarray(explore_rates, jnp.float32), nlr
    )  # (R,)
    batch = batch.replace(learn=batch.learn.replace(explore=exp_col))
    si = jnp.tile(
        jnp.repeat(
            jnp.asarray(load_intervals, jnp.float32), n_replicas_per_load
        ),
        n_exp,
    )
    batch = batch.replace(
        users=batch.users.replace(
            send_interval=jnp.broadcast_to(si[:, None], (R, spec.n_users))
        )
    )
    if mesh is not None:
        final = run_sharded(spec, batch, net, bounds, mesh, n_ticks=n_ticks)
    else:
        final = run_replicated(spec, batch, net, bounds, n_ticks=n_ticks)
    counters = replica_counters(final)
    cnt = np.asarray(final.learn.lat_cnt)
    counters["lat_cnt"] = cnt
    # NaN (not 0.0) for cells where nothing was credited: a zero mean
    # would read as the best possible latency for the emptiest cell
    counters["lat_mean_s"] = np.where(
        cnt > 0, np.asarray(final.learn.lat_sum) / np.maximum(cnt, 1.0),
        np.nan,
    )
    out: Dict[float, Dict[str, np.ndarray]] = {}
    for i, e in enumerate(explore_rates):
        sl = slice(i * nlr, (i + 1) * nlr)
        out[float(e)] = {
            k: v[sl].reshape(n_loads, n_replicas_per_load)
            for k, v in counters.items()
        }
    return out


def sweep_dyn(
    build: Callable[..., tuple],
    knobs: Mapping[str, Sequence],
    n_replicas_per_cell: int = 1,
    seed: int = 0,
    n_ticks: Optional[int] = None,
    mesh=None,
    **build_kwargs,
) -> List[Dict]:
    """Dynamic-knob grid under ONE compile (ISSUE 13).

    ``knobs`` maps promoted WorldSpec fields
    (:data:`~fognetsimpp_tpu.dynspec.DYN_FIELDS`) to value lists; the
    cartesian grid runs as a replica fan-out whose per-replica
    :class:`~fognetsimpp_tpu.dynspec.DynSpec` rows carry the cell's
    values — where ``sweep_policies`` needed Policy.DYNAMIC's traced
    switch and ``sweep_explore`` a carry-resident rate, ANY promoted
    numeric knob now grids for free (a chaos-amplitude × loss-prob grid
    is one compiled program, asserted via ``_run_replicated._cache_
    size()`` in tests).

    ``mesh`` (ISSUE 20) lays the same grid replica-sharded over a
    device mesh via :func:`~fognetsimpp_tpu.parallel.fleet.run_fleet`:
    still ONE compiled program (the per-cell DynSpec rows ride the
    fleet runner's sharded row operand), with cells × replicas spread
    ``R / D`` per device.  The grid size must divide the mesh — pad
    ``n_replicas_per_cell`` to align.

    Every cell must land in the SAME shape bucket: a grid that crosses
    a trace gate (e.g. ``uplink_loss_prob`` values mixing 0 and 0.2)
    raises the one-line shape-key error up front rather than silently
    splitting into per-gate compiles.  The world is built from the
    FIRST cell's values so state init (e.g. the chaos schedule's first
    crash draw) matches that cell's gate class; chaos-knob cells
    re-derive their init-time chaos schedule per row.

    Returns a list of ``{knob values..., counters: {...}}`` dicts in
    grid order (cells × replicas averaged by the caller as needed).
    """
    from ..dynspec import DYN_FIELDS, dyn_of, shape_key

    bad = sorted(set(knobs) - set(DYN_FIELDS))
    if bad:
        raise ValueError(
            f"sweep_dyn grids promoted knobs only; {', '.join(bad)} "
            "is shape-defining (see dynspec.DYN_FIELDS / the README "
            "'one program, many worlds' table)"
        )
    names = sorted(knobs)
    grid = [
        dict(zip(names, vals))
        for vals in itertools.product(*(knobs[k] for k in names))
    ]
    if not grid:
        return []
    # build at the first cell's values: gate classes (zero vs positive)
    # and init-time state derivations then match the whole grid
    spec0, state, net, bounds = build(**{**build_kwargs, **grid[0]})
    cells = [
        dataclasses.replace(spec0, **cell).validate() for cell in grid
    ]
    key0 = shape_key(cells[0])
    for cell, sp in zip(grid, cells):
        if shape_key(sp) != key0:
            raise ValueError(
                f"grid cell {cell} leaves the shape bucket (a knob "
                "crossed a trace gate, e.g. 0 vs positive): split the "
                "sweep per gate class"
            )
    nrc = n_replicas_per_cell
    R = len(cells) * nrc
    base = replicate_state(spec0, state, nrc, seed=seed)
    batch = jax.tree.map(
        lambda x: jnp.concatenate([x] * len(cells), axis=0), base
    )
    if spec0.chaos:
        # the t=0 chaos schedule (first crash gap) is an init-time
        # derivation of the cell's MTBF: re-derive per cell so each
        # row starts exactly where a direct run of its spec would —
        # including the per-REPLICA fold_in(chaos_key, r) re-key
        # replicate_state applies, so each (cell, replica) row equals
        # the direct replicate_state(spec_cell, ...) fan-out
        from ..chaos.faults import init_chaos_state, refold_chaos_state
        from .replicas import fold_replica_chaos_keys

        ch_cells = []
        for sp in cells:
            # keyed on the BUILDER's world key (state.key at t=0):
            # exactly what a direct build of this cell's spec draws
            ch0 = init_chaos_state(sp, state.key)
            ck_r = fold_replica_chaos_keys(ch0.key, nrc)
            ch_cells.append(jax.vmap(
                lambda k, _sp=sp, _c=ch0: refold_chaos_state(_sp, _c, k)
            )(ck_r))
        ch_rows = jax.tree.map(
            lambda *xs: jnp.concatenate(xs, axis=0), *ch_cells
        )
        batch = batch.replace(chaos=ch_rows)
    dyn_rows = jax.tree.map(
        lambda *xs: jnp.repeat(jnp.stack(xs), nrc, axis=0),
        *(dyn_of(sp) for sp in cells),
    )
    if mesh is not None:
        from .fleet import run_fleet

        final = run_fleet(
            key0, batch, net, bounds, mesh=mesh, n_ticks=n_ticks,
            promote=True, dyn_rows=dyn_rows,
        )
    else:
        final = run_replicated(
            key0, batch, net, bounds, n_ticks=n_ticks, dyn_rows=dyn_rows
        )
    counters = replica_counters(final)
    out: List[Dict] = []
    for i, cell in enumerate(grid):
        sl = slice(i * nrc, (i + 1) * nrc)
        out.append({
            **cell,
            "counters": {k: v[sl] for k, v in counters.items()},
        })
    return out


def fork_state(state, n: int):
    """Broadcast ONE live carry identically onto ``n`` replica rows —
    the state-fork half of the what-if door (ISSUE 17).

    Deliberately the opposite of :func:`~fognetsimpp_tpu.parallel.
    replicas.replicate_state`: NO re-keying, NO chaos refold, NO
    start-time resampling.  Every row starts as the bit-identical
    forked carry (same PRNG key, same mid-run chaos schedule, same
    in-flight tasks), so row *i*'s trajectory under cell *i*'s DynSpec
    equals a direct single run of that retuned spec from this exact
    state — the property the what-if rail asserts bit-for-bit.
    """
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), state
    )


def sweep_dyn_from(
    spec,
    state,
    net,
    bounds,
    knobs: Mapping[str, Sequence],
    n_ticks: int,
) -> tuple:
    """Dynamic-knob grid forked from a LIVE carry, under ONE compile.

    The missing half of :func:`sweep_dyn` (which builds each world at
    t=0): here ``state`` is a mid-session chunk-boundary carry and
    every grid cell answers "what do the next ``n_ticks`` ticks look
    like under THIS retuning, starting from NOW".  Knobs must be
    promoted (:data:`~fognetsimpp_tpu.dynspec.DYN_FIELDS`) and every
    cell must stay in the live spec's shape bucket — crossing a trace
    gate raises the one-line shape-key error up front, exactly the
    ``sweep_dyn`` / ``apply_knobs`` discipline, because the fork's
    whole point is answering from the ALREADY-COMPILED program.

    Returns ``(grid, final_batch)``: the cell dicts in grid order and
    the replica-batched final state (row *i* = cell *i*), which
    :func:`fognetsimpp_tpu.twin.whatif.run_whatif` turns into per-cell
    counter/quantile DELTAS against the fork point.  Warm calls on the
    same shape bucket are zero compile events
    (``run_replicated``'s jit cache serves every fork of the session).
    """
    from ..dynspec import DYN_FIELDS, dyn_of, shape_key

    bad = sorted(set(knobs) - set(DYN_FIELDS))
    if bad:
        raise ValueError(
            f"what-if grids promoted knobs only; {', '.join(bad)} "
            "is shape-defining (see dynspec.DYN_FIELDS / the README "
            "'one program, many worlds' table)"
        )
    names = sorted(knobs)
    grid = [
        dict(zip(names, vals))
        for vals in itertools.product(*(knobs[k] for k in names))
    ]
    if not grid:
        return [], None
    cells = [
        dataclasses.replace(spec, **cell).validate() for cell in grid
    ]
    key0 = shape_key(spec)
    for cell, sp in zip(grid, cells):
        if shape_key(sp) != key0:
            raise ValueError(
                f"what-if cell {cell} leaves the live session's shape "
                "bucket (a knob crossed a trace gate, e.g. 0 vs "
                "positive): such a retuning needs a recompile and "
                "cannot be answered from the live program"
            )
    batch = fork_state(state, len(cells))
    dyn_rows = jax.tree.map(
        lambda *xs: jnp.stack(xs), *(dyn_of(sp) for sp in cells)
    )
    final = run_replicated(
        key0, batch, net, bounds, n_ticks=n_ticks, dyn_rows=dyn_rows
    )
    return grid, final
