"""Policy × load sweep driver (the EP axis of SURVEY.md §2.3).

Reproduces the shape of the BASELINE.json sweep configs ("10k nodes × 4
schedulers × 256 load levels").  The *load* axis is always dynamic — the
per-user publish interval is a state array (``users.send_interval``, the
reference's volatile ``sendInterval`` NED parameter), so every load level
× Monte-Carlo replica runs inside one ``vmap``.  The *policy* axis has two
modes: static (one compile per policy — any policy, incl. LOCAL_FIRST/
MAX_MIPS) or ``dynamic=True`` (``Policy.DYNAMIC``: the policy id rides in
``BrokerView.policy_id`` as traced data, so the ENTIRE grid is one
compile; argmin family only).  Either way the grid shards over the mesh.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .mesh import run_sharded
from .replicas import replica_counters, replicate_state, run_replicated


def sweep_policies(
    build: Callable[..., tuple],
    policies: Sequence[int],
    load_intervals: Sequence[float],
    n_replicas_per_load: int = 1,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    n_ticks: Optional[int] = None,
    dynamic: bool = False,
    **build_kwargs,
) -> Dict[int, Dict[str, np.ndarray]]:
    """Run every (policy, load, replica) combination; return counter grids.

    ``build`` is a scenario builder (e.g. ``scenarios.smoke.build``)
    accepting ``policy=`` and returning ``(spec, state, net, bounds)``.
    ``load_intervals`` are publish intervals in seconds (smaller = heavier).

    ``dynamic=True`` runs the whole grid under ONE compile: the world is
    built with ``Policy.DYNAMIC`` and each replica carries its policy id as
    data (argmin-family policies 0-4 only).  The static path compiles one
    program per policy — prefer it when a policy outside that family is in
    the grid.

    Returns ``{policy: {counter: (n_loads, n_replicas) array}}``.
    """
    n_loads = len(load_intervals)
    # Build the world for the HEAVIEST load level so capacity-derived shapes
    # (max_sends_per_user, arrival_window) fit every level; lighter levels
    # just publish less.  Overriding send_interval only post-build would
    # silently cap heavy loads at the light-load send budget.
    build_kwargs.setdefault("send_interval", min(load_intervals))

    def load_axis(batch, spec, R):
        si = jnp.tile(
            jnp.repeat(
                jnp.asarray(load_intervals, jnp.float32), n_replicas_per_load
            ),
            R // (n_loads * n_replicas_per_load),
        )  # (R,)
        return batch.replace(
            users=batch.users.replace(
                send_interval=jnp.broadcast_to(si[:, None], (R, spec.n_users))
            )
        )

    def advance(spec, batch, net, bounds):
        if mesh is not None:
            return run_sharded(spec, batch, net, bounds, mesh, n_ticks=n_ticks)
        return run_replicated(spec, batch, net, bounds, n_ticks=n_ticks)

    out: Dict[int, Dict[str, np.ndarray]] = {}
    if dynamic:
        from ..spec import Policy

        if any(not 0 <= int(p) <= 4 for p in policies):
            raise ValueError(
                "dynamic sweeps cover the argmin family (policy ids 0-4)"
            )
        spec, state, net, bounds = build(
            policy=int(Policy.DYNAMIC), **build_kwargs
        )
        P = len(policies)
        nlr = n_loads * n_replicas_per_load
        R = P * nlr
        # one nlr-wide replica block, tiled per policy: every policy sees
        # the SAME per-replica PRNG keys/start times a static per-policy
        # sweep would use, so dynamic == static exactly
        base = replicate_state(spec, state, nlr, seed=seed)
        batch = jax.tree.map(
            lambda x: jnp.concatenate([x] * P, axis=0), base
        )
        # replica order: (policy, load, rep); the load axis tiles per policy
        pol_ids = jnp.repeat(
            jnp.asarray([int(p) for p in policies], jnp.int32), nlr
        )
        batch = batch.replace(
            broker=batch.broker.replace(policy_id=pol_ids)
        )
        batch = load_axis(batch, spec, R)
        final = advance(spec, batch, net, bounds)
        counters = replica_counters(final)
        for i, pol in enumerate(policies):
            sl = slice(i * nlr, (i + 1) * nlr)
            out[int(pol)] = {
                k: v[sl].reshape(n_loads, n_replicas_per_load)
                for k, v in counters.items()
            }
        return out

    R = n_loads * n_replicas_per_load
    for pol in policies:
        spec, state, net, bounds = build(policy=int(pol), **build_kwargs)
        batch = replicate_state(spec, state, R, seed=seed)
        batch = load_axis(batch, spec, R)
        final = advance(spec, batch, net, bounds)
        out[int(pol)] = {
            k: v.reshape(n_loads, n_replicas_per_load)
            for k, v in replica_counters(final).items()
        }
    return out
