"""Static world specification for the batched tick engine.

A :class:`WorldSpec` is the hashable, static-shape description of one
simulated world: how many nodes of each kind exist, the capacities of the
fixed-shape task/queue arrays, the tick size, the application generation
(v1/v2/v3 of the reference apps) and the scheduling policy.

Everything here is *static* under ``jax.jit`` — the dynamic quantities
(positions, busy times, task tables, energies) live in
:mod:`fognetsimpp_tpu.state`.

Reference parity notes (citations into /root/reference):
  * Node roles mirror the reference's node NED wrappers
    (``src/node/compute/*.ned``, user wrappers in ``fognetsim.zip``) on top
    of INET host types; here a role is just an integer kind plus per-node
    parameter arrays.
  * App generations v1/v2/v3 correspond to
    ``src/mqttapp/{mqttApp,BrokerBaseApp,ComputeBrokerApp}[23]?.cc`` — see
    SURVEY.md Appendix A for the capability matrix.
  * Bug-compatibility switches replicate the reference's quirks listed in
    SURVEY.md Appendix B (e.g. the scheduler dividing by ``brokers[0]``'s
    MIPS, ``src/mqttapp/BrokerBaseApp3.cc:268,273,275``).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Tuple


#: One message for the assume_static x Bianchi-keyed-MAC conflict,
#: shared by every entry point that can hit it: WorldSpec.validate()
#: (spec-level, via spec.mac_keyed), engine.run() (net-level
#: belt-and-braces) and engine.make_step() — the entries must agree
#: (ADVICE r5), so the text lives in exactly one place.
STATIC_MAC_ERR = (
    "[SPEC-STATIC-MAC] assume_static cannot hoist a Bianchi-keyed association: "
    "MAC contention is keyed on per-tick offered load (r5). "
    "Disable assume_static for this world, or build the net "
    "with mac_model='linear'."
)


class NodeKind(enum.IntEnum):
    """Role of a simulated node.

    The reference distinguishes user hosts, compute brokers (fog nodes), the
    base broker, access points and routers at the NED level
    (``src/node/compute/*.ned``, ``simulations/testing/*.ned``).
    """

    USER = 0
    FOG = 1
    BROKER = 2
    AP = 3
    ROUTER = 4


class Stage(enum.IntEnum):
    """Lifecycle stage of an offloaded task.

    Mirrors the status codes of the reference's ack chain
    (``src/mqttapp/BrokerBaseApp3.cc:149`` status 4 = forwarded,
    ``src/mqttapp/ComputeBrokerApp3.cc:287`` status 5 = assigned,
    ``:312`` status 4 = queued, ``:231`` status 6 = performed), plus the
    in-flight hops made explicit by the tick engine.
    """

    UNUSED = 0
    PUB_INFLIGHT = 1  # publish travelling client -> base broker
    TASK_INFLIGHT = 2  # FognetMsgTask travelling broker -> fog node
    QUEUED = 3  # sitting in a fog node's FIFO queue
    RUNNING = 4  # being served by a fog node
    DONE = 5  # completed; status-6 ack recorded
    NO_RESOURCE = 6  # broker had no fog nodes (BrokerBaseApp3.cc:306-319)
    DROPPED = 7  # queue overflow (no reference analog: vectors are unbounded)
    LOCAL_RUN = 8  # executing locally on the base broker (v1 path,
    #                BrokerBaseApp.cc:196-224 sendPubAck(status=true))
    REJECTED = 9  # pool fog rejected (TaskAck status=false,
    #               ComputeBrokerApp2.cc:300-310 — the broker ignores the
    #               TaskAck, BrokerBaseApp2.cc:139-141, so the task dies) or
    #               the v1 offload scan found no fog with MIPS > required
    #               (BrokerBaseApp.cc:244 guard: nothing is sent at all)
    LOST = 10  # publish lost on the wireless uplink (MAC retry exhaustion:
    #            the reference's demo run records only 52 of 67 sent —
    #            simulations/example/results/General-0.sca sentPk vs n)
    HOP_EXHAUSTED = 11  # federated hierarchy (hier/): the task's broker
    #            domain is dead and its broker→broker migration hop
    #            budget (spec.hier_max_hops) ran out — terminal, counted
    #            in HierState.n_hop_exhausted (no reference analog: the
    #            reference has exactly one broker and no failover)


class Policy(enum.IntEnum):
    """Scheduling policy run by the base broker per publish arrival.

    ``MIN_BUSY`` is the exact v3 policy (argmin of busyTime + estimated
    service time, ``src/mqttapp/BrokerBaseApp3.cc:267-281``).  The others
    realise the reference's dead ``algo`` parameter
    (``src/mqttapp/BrokerBaseApp3.ned:26``, read but never branched on —
    SURVEY.md Appendix B item 4) as live policies.
    """

    MIN_BUSY = 0
    ROUND_ROBIN = 1
    MIN_LATENCY = 2
    ENERGY_AWARE = 3
    RANDOM = 4
    LOCAL_FIRST = 5  # v1 hybrid: local if MIPSRequired < broker pool
    #                  (BrokerBaseApp.cc:171-180), else offload via MAX_MIPS
    MAX_MIPS = 6  # v1/v2 offload pick: the buggy "max MIPS" scan that
    #               compares every candidate to brokers[0]
    #               (BrokerBaseApp.cc:228-240; see BugCompat.v1_max_scan)
    DYNAMIC = 7  # policy chosen by the *traced* BrokerView.policy_id
    #              (the argmin family ids 0-4, plus the learned bandit ids
    #              8-10 when spec.learn_in_dynamic): one compile covers a
    #              whole policy x load x replica sweep grid (EP axis as data)
    # --- online bandit schedulers (fognetsimpp_tpu.learn) -------------
    # Each fog node is an arm; the broker learns from observed ack
    # latencies (reward = -latency, credited at status-5/6 ack time to
    # the fog picked at publish time — core/engine._phase_learn_credit).
    UCB = 8  # UCB1 over per-fog reward means + exploration bonus
    DUCB = 9  # discounted UCB (gamma-decayed stats; non-stationary worlds)
    EXP3 = 10  # adversarial EXP3 (softmax log-weights, importance-weighted)


#: The traced-dispatch family Policy.DYNAMIC covers via ``policy_id``.
ARGMIN_FAMILY: Tuple[Policy, ...] = (
    Policy.MIN_BUSY,
    Policy.ROUND_ROBIN,
    Policy.MIN_LATENCY,
    Policy.ENERGY_AWARE,
    Policy.RANDOM,
)

#: The online-learning policies backed by the ``learn/`` subsystem.
LEARNED_POLICIES: Tuple[Policy, ...] = (Policy.UCB, Policy.DUCB, Policy.EXP3)


def policy_from_name(name) -> Policy:
    """Resolve a policy given either its integer id or its enum name.

    Accepts ``"ucb"``, ``"MIN_BUSY"``, ``"3"``, ``3`` ... — the CLI tier
    (``--policy``, ``--sweep 'policies=...'``) goes through here so an
    unknown name becomes one actionable ``ValueError`` listing the valid
    names instead of a traceback.
    """
    if isinstance(name, (int, Policy)):
        try:
            return Policy(int(name))
        except ValueError:
            pass
    else:
        s = str(name).strip()
        try:
            return Policy(int(s))
        except ValueError:
            pass
        try:
            return Policy[s.upper()]
        except KeyError:
            pass
    known = ", ".join(f"{p.name.lower()}={int(p)}" for p in Policy)
    raise ValueError(f"unknown policy {name!r} (have {known})")


class FogModel(enum.IntEnum):
    """Fog-node resource model.

    ``FIFO`` is v3's single-server queue (``ComputeBrokerApp3.cc:258-314``);
    ``POOL`` is v1/v2's MIPS-pool accounting (subtract on accept, reject when
    exhausted — ``ComputeBrokerApp2.cc:272,300``).
    """

    FIFO = 0
    POOL = 1


class ChaosMode(enum.IntEnum):
    """In-flight task handling when a fog node crashes (``chaos/``).

    LOSE: every task sitting on (or in flight to) the crashed fog is
    dropped into :class:`Stage.LOST` and counted in
    ``ChaosState.n_lost_crash`` — the iFogSim-style hard-failure model.
    REOFFLOAD: those tasks bounce back to the base broker as fresh
    ``PUB_INFLIGHT`` arrivals (through the established K-window
    contract) with a bounded per-task retry budget
    (``spec.chaos_max_retries``); tasks whose budget is exhausted are
    lost and counted in ``ChaosState.n_retry_exhausted``.
    """

    LOSE = 0
    REOFFLOAD = 1


class HierPolicy(enum.IntEnum):
    """Broker↔broker task-migration policy of the federated hierarchy
    (``fognetsimpp_tpu.hier``).

    NEVER: domains are isolated — a saturated or dead domain keeps (or
    loses) its own tasks, the FogNetSim++ single-broker behaviour tiled
    B times.  THRESHOLD: a broker whose local busy fraction exceeds
    ``spec.hier_threshold`` (or whose domain has no usable fog at all)
    forwards its matured publishes to the least-loaded peer by its AGED
    view of peer load summaries.  LEAST_LOADED: a broker forwards
    whenever any peer looks strictly less loaded than itself (dead
    domains always forward).  Peer views age by the inter-broker RTT —
    federation sees stale data exactly like the broker→fog view does
    (FogMQ arXiv:1610.00620 brokers-at-internet-scale).
    """

    NEVER = 0
    THRESHOLD = 1
    LEAST_LOADED = 2


def hier_policy_from_name(name) -> HierPolicy:
    """Resolve a hierarchy migration policy from its id or name.

    The ``--hier-policy`` CLI flag goes through here so an unknown name
    becomes one actionable ``ValueError`` listing the valid names.
    """
    if isinstance(name, (int, HierPolicy)):
        try:
            return HierPolicy(int(name))
        except ValueError:
            pass
    else:
        s = str(name).strip()
        try:
            return HierPolicy(int(s))
        except ValueError:
            pass
        try:
            return HierPolicy[s.upper()]
        except KeyError:
            pass
    known = ", ".join(f"{p.name.lower()}={int(p)}" for p in HierPolicy)
    raise ValueError(f"unknown hier policy {name!r} (have {known})")


class Mobility(enum.IntEnum):
    """Per-node mobility model (INET equivalents cited).

    STATIONARY: INET StationaryMobility.  LINEAR: LinearMobility with speed +
    angle + reflective bounds (``testing/wireless5.ini:23-50``).  CIRCLE:
    CircleMobility around (cx, cy) with radius r and speed
    (``example/wirelessNet.ini:13-29``).
    """

    STATIONARY = 0
    LINEAR = 1
    CIRCLE = 2


@dataclasses.dataclass(frozen=True)
class BugCompat:
    """Replicate-or-fix switches for the reference's quirks (SURVEY.md App. B).

    Attributes:
      mips0_divisor: scheduler estimates service time with ``brokers[0]``'s
        MIPS for *every* candidate (``BrokerBaseApp3.cc:268,273,275``).  When
        False, each candidate's own advertised MIPS is used.
      zero_initial_view_mips: fog nodes register with MIPS=0 in the broker's
        table (``BrokerBaseApp3.cc:104``) so estimates are +inf until the
        first advertisement lands.  When False, the true MIPS is known at
        registration.
      v1_max_scan: the v1/v2 offload scan compares every candidate's MIPS to
        ``brokers[0]``'s instead of the running max (``BrokerBaseApp.cc:
        232-236``: ``temp`` is never updated), so the winner is the *last*
        fog whose MIPS exceeds fog 0's.  When False, a true argmax is used.
      local_pool_leak: the v1 local path never records its Request
        (``BrokerBaseApp.cc:208`` is commented out) so ``releaseResource``
        finds nothing and the broker pool is never refunded.  When False,
        the pool is released at task expiry (the evident intent).
    """

    mips0_divisor: bool = True
    zero_initial_view_mips: bool = True
    v1_max_scan: bool = True
    local_pool_leak: bool = False


@dataclasses.dataclass(frozen=True)
class WorldSpec:
    """Hashable static description of a simulated world.

    Array-capacity fields size the fixed-shape state arrays:
      * tasks capacity T = ``n_users * max_sends_per_user`` — task slots are
        statically owned by (user, send-index) pairs so no dynamic allocation
        is ever needed on device.
      * each fog node owns a ring-buffer FIFO of ``queue_capacity`` slots.
    """

    # --- population ---------------------------------------------------
    n_users: int
    n_fogs: int
    n_aps: int = 0
    n_routers: int = 0
    # there is exactly one base broker (single point of failure in the
    # reference too — SURVEY.md §5 "no broker failover logic exists")

    # --- capacities ---------------------------------------------------
    max_sends_per_user: int = 64
    queue_capacity: int = 64
    # Max task arrivals decided per tick at the broker / at the fogs.  The
    # hot phases gather the masked rows into a buffer of this size (sort and
    # score-matrix cost O(K) instead of O(T)); overflowing arrivals simply
    # stay in flight and are picked up next tick.  None = task_capacity
    # (never overflows; right for small worlds and parity tests).
    arrival_window: Optional[int] = None

    # --- time ---------------------------------------------------------
    dt: float = 1e-3  # tick length (s); keep <= min link delay for fidelity
    horizon: float = 3.35  # simulated seconds (example run: BASELINE.md)
    completions_per_tick: int = 2  # inner completion phases per tick

    # --- application behaviour (mqttApp2.cc:353-409) -------------------
    app_gen: int = 3
    send_interval: float = 0.05  # example/wirelessNet.ini publish interval
    send_interval_jitter: float = 0.0  # >0 resamples per send (volatile par)
    start_time_min: float = 0.0
    start_time_max: float = 0.0  # sends start uniform in [min, max]
    send_stop_time: float = float("inf")  # stopTime NED param: publishing
    #   ceases at this sim time (mqttApp2.cc:191-210; the inis set 300-1000 s,
    #   beyond every committed horizon, so inf is the faithful default)
    mips_required_min: int = 200  # mqttApp2.cc:370: 200 + rand() % 701
    mips_required_max: int = 900
    # Static bound on publishes per user per tick.  1 (default) keeps the
    # original one-send-per-tick spawn phase (and its PRNG stream, which
    # the committed-trace anchors pin).  >1 switches to the closed-form
    # multi-send spawn (engine._phase_spawn_multi) so a coarse tick
    # (dt > send_interval) still carries the full publish workload with
    # exact per-send event times; requires send_interval_jitter == 0
    # (the closed form needs deterministic send spacing).  Size it
    # >= ceil(dt / min send_interval) + 1 or late sends defer a tick.
    max_sends_per_tick: int = 1
    # FIFO fog-arrival front-end (r5 perf): reduce the (U, S) task-table
    # view to the R earliest matured arrivals per user before the
    # K-window compaction, instead of compacting the full T-sized mask —
    # same decisions whenever at most R tasks per user mature per tick
    # (always, at dt <= send_interval with R >= max_sends_per_tick);
    # excess matured tasks defer one tick exactly like window overflow
    # (Metrics.n_deferred).  Removes the (F,T) fast-drop matmuls and the
    # T-sized compaction (~100 MB + 200 MFLOP of the tick's cost
    # analysis at the 10k bench shape, and the r4 replica-fan-out crash
    # with them); tests/test_compaction.py A/Bs the paths bit-for-bit.
    two_stage_arrivals: bool = True
    # per-user candidate slots for the two-stage front-end; None derives
    # max_sends_per_tick (+1 slack when mobility can bunch arrivals)
    arrival_cands_per_user: Optional[int] = None
    # Fused per-user slot-window front-end (r6 perf, "kernel-count
    # collapse"): thread the hot task-table columns through
    # spawn -> broker -> completions -> fog-arrivals as (U, S) register
    # views and flush them ONCE per tick — each phase contributes column
    # updates to a shared write set instead of materialising its own
    # scatter chain, so the dt=1 ms tick compiles to measurably fewer
    # HLO fusions/ops (gated by tools/op_budget.py).  Applies statically
    # to the dense-broker policy family over FIFO fogs with the
    # two-stage arrival front-end (engine._fused_ok); other worlds keep
    # the classic per-phase path.  Bit-exact vs the unfused engine
    # (state-hash A/B in tests/test_fused.py), which is why it defaults
    # ON; set False to force the per-phase reference path (bench.py
    # BENCH_FUSED=0 A/Bs the two).
    fused_slots: bool = True
    # r5 perf: skip the per-tick writes of the five ack-timestamp columns
    # and queue_time_ms (each a ~25 us scatter or a full-column select)
    # and reconstruct them ONCE after the scan from the hot columns —
    # t_ack4_fwd = t_at_broker + d_bu, t_ack4_queued = t_q_enter + d_fb
    # + d_bu, t_ack5 = t_service_start + d_fb + d_bu (assigned rows),
    # t_ack6 = t_complete + d_fb + d_bu, queue_time = service_start -
    # q_enter: identical float arithmetic in the same order, so the
    # reconstruction is bit-exact (tests/test_runtime.py A/Bs it).
    # Requires delays the decision tick and the end of the run agree on:
    # assume_static (constant cache), no DropTail backpressure, FIFO fog
    # model, and no broker-local branch (t_ack3 is v1-only).
    derive_acks: bool = False
    required_time: float = 0.01  # mqttApp2.cc:372
    task_bytes: int = 128  # mqttApp2.cc:379
    fixed_mips_required: Optional[int] = None  # v1: 100 (mqttApp.cc:330)

    # --- scheduling / fog model ---------------------------------------
    policy: int = int(Policy.MIN_BUSY)
    # RANDOM policy: the per-task unit draw is a pure function of the task
    # id keyed on this seed (threefry fold_in), NOT of the tick batching —
    # so the native DES consumes the identical stream and the RANDOM
    # policy is exact-parity-gated like the deterministic ones (r3).
    policy_seed: int = 0
    fog_model: int = int(FogModel.FIFO)
    adv_interval: float = 0.01  # v1/v2 periodic re-advertise
    adv_on_completion: bool = True  # v3 (ComputeBrokerApp3.cc:254)
    adv_periodic: bool = False  # v1/v2 (ComputeBrokerApp2.cc:219)
    broker_mips: float = 0.0  # broker's own pool for LOCAL_FIRST (v1)
    # v2 base broker (BrokerBaseApp2.cc:176-270): a LOCAL_FIRST hybrid —
    # MIPSRequired < pool runs locally — whose releaseResource runs off
    # ONE shared self-message: every accept cancels the pending release
    # and reschedules it (+requiredTime), and each firing releases at most
    # one stored request (SURVEY App. B item 8, live in v2).  Offloaded
    # publishes are ALSO stored in requests[] (BrokerBaseApp2.cc:244-252),
    # so their release refunds pool MIPS that was never debited and sends
    # a duplicate status-6.  Requires policy == LOCAL_FIRST.
    v2_local_broker: bool = False
    # POOL fog model: how many arrival ranks are pool-checked per pass
    # (the sequential accept/reject chain is exact up to this depth;
    # deeper arrivals re-rank next pass, keeping their exact arrival
    # times — tests/test_v1v2.py::test_pool_same_tick_depth_beyond_
    # phases_is_benign).  With adv_periodic the advert-boundary
    # sub-phasing runs TWO passes per tick, so the per-tick depth is
    # effectively 2x this.  See _phase_pool_arrivals.
    pool_phases: int = 4

    # --- online learning (fognetsimpp_tpu.learn) ------------------------
    # Exploration rate: UCB/DUCB confidence-bonus coefficient c, or the
    # EXP3 uniform-mixing weight gamma.  Only the INITIAL value: the live
    # rate rides the carry (LearnState.explore, traced) so a replica fan-
    # out can sweep exploration rates under one compile (parallel/sweep
    # .sweep_explore).
    learn_explore: float = 0.5
    # Per-tick decay of the discounted-UCB statistics (gamma of arxiv
    # 0805.3415's D-UCB); 1.0 degenerates to plain UCB accounting.
    learn_discount: float = 0.995
    # Latency scale (s) of the bounded reward map r = exp(-latency/scale)
    # (learn/rewards.py): the ack latency at which a credit is worth 1/e.
    learn_reward_scale: float = 0.25
    # Policy.DYNAMIC normally dispatches the argmin family (ids 0-4) only;
    # True extends the traced switch with the bandit ids 8-10 AND carries
    # live LearnState, so a single-compile grid can mix static and learned
    # schedulers per replica.
    learn_in_dynamic: bool = False

    # --- wireless uplink loss ------------------------------------------
    # Probability a publish is lost before reaching the broker (802.11 MAC
    # retry exhaustion, emergent in INET; e.g. the committed demo run loses
    # 15 of 67 publishes).  Applied per publish via the kernel PRNG; lost
    # tasks enter Stage.LOST and are counted in metrics.n_lost.
    uplink_loss_prob: float = 0.0

    # --- wired-link queueing (DropTailQueue, wireless5.ini:72-73) ------
    # The reference runs a frameCapacity=40 DropTailQueue on every eth
    # interface; under load wired links delay and drop.  When enabled,
    # each node's access link carries a serialization backlog: per tick
    # backlog += message_bytes - rate*dt, added delay = backlog/rate, and
    # overflow beyond 40 frames becomes a DropTail loss probability
    # applied to next-tick publishes (acks are delayed, not dropped — the
    # batched analog of tail-dropping a full queue).  Off by default: no
    # committed reference scenario drives links near saturation
    # (tests/test_link_queue.py validates that claim).
    wired_queue_enabled: bool = False
    link_rate_bps: float = 100e6  # DatarateChannel 100 Mbps
    link_queue_frames: int = 40  # frameCapacity

    # --- link warm-up (INET ARP/802.11-association transient) ----------
    # In every committed reference wireless run the first ~1 s of uplink
    # packets buffer below the app while ARP + association resolve, then
    # drain as a burst (example/results/General-0.vec vector 1093: first
    # sample's delay is exactly link_up - app_start).  When link_up_s > 0,
    # a publish whose normal arrival would precede it instead arrives at
    # ``link_up_s + send_index * link_drain_s``.
    link_up_s: float = 0.0  # 0 = disabled
    link_drain_s: float = 0.02  # backlog drain spacing once the link is up
    # Two-phase drain (committed demo trace, example/results/General-0.vec
    # vector 1093: the first ~7 buffered packets pour out with 4-10 ms
    # gaps, the rest of the backlog trickles at tens of ms): sends with
    # in-backlog index k < link_burst_n drain at link_drain_s, the rest at
    # link_drain2_s.  link_burst_n = 0 keeps the single-gap model.
    link_burst_n: int = 0
    link_drain2_s: float = 0.0
    # Mechanistic warm-up buffer (r5, VERDICT r4 "what's weak" 6): the
    # committed demo trace's losses are DETERMINISTIC, not stochastic —
    # creations k=0..13 all drain (burst + trickle), the LAST SIX
    # pre-link-up creations (k=14..19) are all dropped, and post-link-up
    # packets never lose (General-0.vec vector 1093: creation indices
    # 0..13 and 20..57 present, exactly 14..19 absent).  That is INET's
    # bounded ARP/MAC pending queue overflowing while the link
    # establishes.  When > 0: publishes *created* before ``link_up_s``
    # are buffered if their send index < link_buffer_frames and
    # deterministically LOST otherwise; creations after link-up transmit
    # directly.  0 keeps the legacy arrival-time gating with unlimited
    # buffering (plus whatever ``uplink_loss_prob`` models residually).
    link_buffer_frames: int = 0

    # --- MQTT control plane (BrokerBaseApp3.cc:86-121, 201-218) --------
    # When True, users/fogs start unconnected: a Connect must round-trip to
    # the broker before the first publish / advertisement (mqttApp2.cc:
    # 165-233, ComputeBrokerApp3.cc:261-267).  False = born connected (the
    # round-1 shortcut, kept for micro-tests).
    connect_gating: bool = True
    n_topics: int = 1  # topic id space for subscriptions / fan-out
    fanout_enabled: bool = True  # publishAll as a live feature (SURVEY §3.4)

    # --- energy (testing/wireless5.ini:150-166) ------------------------
    energy_enabled: bool = False
    energy_capacity_j: float = 0.05
    idle_power_w: float = 2e-3
    tx_energy_j: float = 2e-4
    rx_energy_j: float = 1e-4
    compute_power_w: float = 5e-3  # fog drain while serving
    harvest_power_w: float = 5e-3
    harvest_period_s: float = 1.0  # generation/sleep alternation period
    harvest_duty: float = 0.5
    shutdown_frac: float = 0.10  # nodeShutdownCapacity = 10% (ini:160)
    start_frac: float = 0.50  # nodeStartCapacity = 50% (ini:161)

    # --- static-world fast path ----------------------------------------
    # Builder promise that node positions and liveness never change over
    # the run (every node STATIONARY, no energy lifecycle): the engine
    # then computes the association/delay cache ONCE before the scan and
    # skips the per-tick mobility + association kernels entirely.
    # Results are bit-identical to the unhoisted path (the cache is a
    # pure function of (pos, alive), both constant); validate() rejects
    # the combination with the energy model, and run() re-derives the
    # cache whenever the promise cannot be checked.
    assume_static: bool = False
    # Builder declaration that the world's MAC contention is keyed on
    # per-tick offered load (the Bianchi DCF tables of
    # net/topology.py::make_net_params with mac_model="bianchi" and APs
    # present).  Such an association can never be hoisted out of the
    # scan, so validate() rejects assume_static + mac_keyed at SPEC
    # CONSTRUCTION (ADVICE r5: previously only run() raised, at run
    # time, and make_step() silently disagreed).  The engine still
    # belt-and-braces checks the net's actual mac table at both
    # entries (core/engine.py::_STATIC_MAC_ERR) in case a hand-built
    # spec under-declares.
    mac_keyed: bool = False

    # --- deterministic fault injection (fognetsimpp_tpu.chaos) ----------
    # Master gate: carry a ChaosState pytree in the scan (fog-node
    # crash/recover schedules, per-task re-offload retry counters,
    # broker->fog RTT degradation) and trace the chaos lifecycle phase.
    # Off (the default) keeps every chaos array leaf zero-row and the
    # run bit-exact vs the chaos-less engine — the inert-LearnState /
    # TelemetryState gate discipline (tests/test_chaos.py A/Bs it).
    chaos: bool = False
    # Seed of the chaos PRNG stream.  The stream is threefry-folded
    # from the WORLD key at init (never split from it), so enabling
    # chaos perturbs no draw of the main simulation stream, and two
    # chaos seeds on one world seed give independent fault schedules.
    chaos_seed: int = 0
    # ChaosMode: what happens to tasks on a crashed fog (LOSE/REOFFLOAD).
    chaos_mode: int = int(ChaosMode.LOSE)
    # Random fog lifecycle: mean up-time between crashes and mean repair
    # time, both in simulated seconds (exponential draws per fog per
    # outage, keyed fold_in(fold_in(chaos_key, fog), outage_index) so
    # host tooling can replay the exact schedule — chaos/faults.py
    # outage_timeline).  mtbf <= 0 disables random crashes (scripted
    # schedules and link degradation still apply).
    chaos_mtbf_s: float = 0.0
    chaos_mttr_s: float = 0.0
    # REOFFLOAD retry budget: a task may bounce back to the broker at
    # most this many times; the next crash loses it (retry-exhausted).
    chaos_max_retries: int = 2
    # Scripted outages: ((fog, t_down, t_up), ...) absolute-time
    # intervals for reproducible scenarios; composes with the random
    # schedule (a fog is down while ANY source holds it down).
    chaos_script: Tuple[Tuple[int, float, float], ...] = ()
    # Link degradation: time-varying broker->fog RTT perturbation over
    # the tick's delay cache.  The periodic term multiplies each fog
    # row of d2b by 1 + amp * (1 + sin(2*pi*t/period + phase_f)) / 2
    # (phase_f a per-fog draw from the chaos stream, so fogs do not
    # degrade in lockstep); the burst term multiplies by burst_mult on
    # per-fog per-tick Bernoulli(burst_prob) draws keyed on the tick
    # index (deterministic across run/run_jit/run_chunked).  Stale
    # view_busy and latency estimates actually go stale under it.
    chaos_rtt_amp: float = 0.0
    chaos_rtt_period_s: float = 1.0
    chaos_rtt_burst_prob: float = 0.0
    chaos_rtt_burst_mult: float = 5.0

    # --- federated multi-broker hierarchy (fognetsimpp_tpu.hier) --------
    # Broker count B: 1 (the default) is the reference's single base
    # broker and traces NONE of the hierarchy machinery (bit-exact vs
    # the pre-hier engine — tests/test_hier.py A/Bs it).  B > 1
    # partitions users and fogs into B broker domains via the
    # assembler-stamped ownership vectors (HierState.user_broker /
    # fog_broker, default block-contiguous): each logical broker runs
    # the established decide phase over its LOCAL fog set with its own
    # stale view slice, and the contract-registered
    # ``_phase_broker_migrate`` moves matured publishes between brokers
    # when a domain is saturated or dead.  All B logical brokers share
    # the one physical broker node's link delays; the inter-broker hop
    # cost is the ``hier_rtt_*`` matrix below.
    n_brokers: int = 1
    # HierPolicy: NEVER / THRESHOLD (on local busy fraction) /
    # LEAST_LOADED (over aged peer load summaries).  Static: selects
    # whether the migrate phase is traced at all.
    hier_policy: int = 0  # int(HierPolicy.NEVER)
    # THRESHOLD trigger: migrate when the local busy fraction (busy
    # usable fogs / usable fogs of the domain) exceeds this.  inf = the
    # phase traces but can only fire on dead domains.  Rides the
    # DynSpec operand: retunable with zero recompiles.
    hier_threshold: float = 0.75
    # Migration hop budget per task: a task that still cannot be served
    # after this many broker→broker hops (its domain dead, or nowhere
    # left to go) becomes Stage.HOP_EXHAUSTED and is counted in
    # HierState.n_hop_exhausted — the conservation invariant's new
    # terminal bucket.  Rides the DynSpec operand (int, like
    # chaos_max_retries).
    hier_max_hops: int = 2
    # Uniform inter-broker RTT (seconds) used when no explicit matrix
    # is given: a migrated task's t_at_broker advances by the src→dst
    # entry, re-offering it through the established K-window arrival
    # contract at the new broker.  Rides the DynSpec operand.
    hier_rtt_s: float = 0.005
    # Explicit B×B inter-broker RTT matrix (tuple-of-tuples, hashable);
    # None derives the uniform matrix (hier_rtt_s off-diagonal, zero
    # diagonal).  Rides the DynSpec operand as a (B, B) f32 leaf.
    hier_rtt_matrix: Optional[Tuple[Tuple[float, ...], ...]] = None

    # --- telemetry (fognetsimpp_tpu.telemetry) --------------------------
    # Plane-1 observability gate: carry a TelemetryState pytree in the
    # scan (per-fog queue-depth min/max/sum, busy fractions, pool
    # occupancy, bandit pick histogram, per-phase work counters, and a
    # bounded strided reservoir of per-tick rows), accumulated entirely
    # on device.  Off (the default) keeps every telemetry leaf zero-row
    # and the run bit-exact vs the untelemetered engine
    # (tests/test_telemetry.py state-hash A/B, the inert-LearnState
    # discipline of PR 2).
    telemetry: bool = False
    # Reservoir rows for the whole horizon (strided sampling: row k
    # holds tick k * ceil(n_ticks / rows)); bounds device memory at any
    # horizon, the run_fleet_series discipline without per-chunk host
    # offload.
    telemetry_reservoir: int = 256
    # --- live health plane (telemetry/health.py, ISSUE 6) --------------
    # Device-resident streaming latency histogram: per-fog log-spaced
    # bucket counts of the task_time signal (publish -> status-6 ack),
    # accumulated inside the scan carry by core/engine._phase_latency_
    # hist and folded into p50/p95/p99 + SLO-breach counters on host.
    # Off (the default) keeps every histogram leaf zero-row and the run
    # bit-exact vs the histogram-less engine — the same gate discipline
    # as spec.telemetry itself (tests/test_health.py A/Bs it).
    # Requires spec.telemetry (the leaves ride TelemetryState).
    telemetry_hist: bool = False
    # Log-spaced bucket count: bucket b covers (edge[b-1], edge[b]] with
    # edges geometric between the min/max bounds below; the last bucket
    # is the +Inf overflow.  Fixed at trace time, so the carry shape
    # never depends on data.
    telemetry_hist_bins: int = 24
    telemetry_hist_min_ms: float = 0.1  # lowest finite bucket edge
    telemetry_hist_max_ms: float = 10_000.0  # highest finite bucket edge
    # --- causal task-journey tracing (telemetry/journeys.py) -----------
    # Sample J task slots (a deterministic hash-select from the WORLD
    # key — folded, never split, so enabling journeys perturbs no draw
    # of the main simulation stream) and carry one bounded event ring
    # per sampled task in TelemetryState: every lifecycle edge an
    # engine phase produces for a sampled task (spawn, broker decide,
    # broker→broker migration hop, chaos re-offload / crash loss, fog
    # enqueue, service start, terminal) appends one packed
    # ``(t_bits, code, a, b)`` i32 row.  0 (the default) keeps every
    # journey leaf zero-row and the run bit-exact vs the journey-less
    # engine — the inert-LearnState gate discipline
    # (tests/test_journeys.py A/Bs it).  Requires spec.telemetry.
    telemetry_journeys: int = 0
    # Ring rows per sampled task.  Overflow keeps drop-OLDEST
    # semantics: the append cursor wraps, so the ring always holds the
    # LAST `telemetry_journey_ring` events of the task's journey (the
    # flight-recorder question is "what was it doing most recently"),
    # and overwritten rows are counted in the ``journeys_dropped``
    # scalar.
    telemetry_journey_ring: int = 64
    # --- distributed observability (ISSUE 11) --------------------------
    # Shard count of the TP (task-table-sharded) world view this spec
    # describes: 0 for unsharded worlds; run_tp_sharded stamps the mesh
    # size here (telemetry-on runs only) so the per-shard exchange-plane
    # telemetry leaves (TelemetryState.exg_*) carry real dimensions and
    # host readers (.sca.json rows, fns_tp_exchange_* OpenMetrics
    # families, Perfetto shard lanes) know the shard axis.  Static under
    # jit; the single-device engine never reads it.
    tp_shards: int = 0

    # --- digital-twin live ingestion (fognetsimpp_tpu.twin, ISSUE 17) --
    # Master gate for queue-fed arrivals: with it on, the serve loop may
    # drain an external ingestion queue into next-chunk arrival state at
    # each chunk boundary via core/engine._phase_inject (contract-
    # registered, chunk-boundary-only — the compiled tick itself never
    # hosts a transfer, hloaudit's tick_ingest variant proves it).  Off
    # (the default) the injection phase never runs and every run is
    # bit-exact vs the pre-twin engine (tests/test_twin.py state-hash
    # A/B) — the inert-LearnState gate discipline.
    ingest: bool = False
    # Fixed injection batch width: the per-boundary drain hands the
    # compiled injector at most this many arrival rows (padded with
    # user=-1 sentinels), so the injector's shape never depends on queue
    # depth and one compiled program serves every boundary.
    ingest_batch: int = 64

    # --- misc ----------------------------------------------------------
    bug_compat: BugCompat = BugCompat()
    record_tick_series: bool = False  # emit per-tick vectors from the scan
    record_trails: bool = False  # also record per-tick node positions in
    #   the series (the Tkenv movement-trail analog; O(ticks*N) memory —
    #   meant for demo-scale worlds).  Requires record_tick_series.

    # ------------------------------------------------------------------
    @property
    def n_nodes(self) -> int:
        return self.n_users + self.n_fogs + 1 + self.n_aps + self.n_routers

    @property
    def task_capacity(self) -> int:
        return self.n_users * self.max_sends_per_user

    @property
    def n_ticks(self) -> int:
        return int(round(self.horizon / self.dt))

    # node index layout: [users | fogs | broker | aps | routers]
    @property
    def user_slice(self) -> Tuple[int, int]:
        return (0, self.n_users)

    @property
    def fog_slice(self) -> Tuple[int, int]:
        return (self.n_users, self.n_users + self.n_fogs)

    @property
    def broker_index(self) -> int:
        return self.n_users + self.n_fogs

    @property
    def ap_slice(self) -> Tuple[int, int]:
        a = self.n_users + self.n_fogs + 1
        return (a, a + self.n_aps)

    def user_index(self, u: int) -> int:
        return u

    def fog_index(self, f: int) -> int:
        return self.n_users + f

    @property
    def window(self) -> int:
        """Effective arrival-compaction buffer size K."""
        if self.arrival_window is None:
            return self.task_capacity
        return min(self.arrival_window, self.task_capacity)

    @property
    def arrival_cands(self) -> int:
        """Per-user candidate slots for the two-stage arrival front-end.

        Defaults to ``max_sends_per_tick`` plus one slack slot when the
        world is mobile (varying broker->fog legs can bunch two sends'
        fog arrivals into one tick); explicit
        ``arrival_cands_per_user`` overrides.
        """
        if self.arrival_cands_per_user is not None:
            return max(1, self.arrival_cands_per_user)
        return self.max_sends_per_tick + (0 if self.assume_static else 1)

    @property
    def learn_active(self) -> bool:
        """Whether the ``learn/`` bandit subsystem is live for this spec.

        True for the learned policies themselves and for DYNAMIC grids
        that opted the bandit ids into the traced switch.  Static under
        jit: it gates whether the engine traces the decision bookkeeping
        and the delayed-reward credit phase at all, so worlds running the
        pre-existing policies stay bit-exact (and allocation-identical up
        to the empty provenance columns).
        """
        if self.policy in tuple(int(p) for p in LEARNED_POLICIES):
            return True
        return self.policy == int(Policy.DYNAMIC) and self.learn_in_dynamic

    @property
    def learn_capacity(self) -> int:
        """Rows of the per-task decision-provenance columns (0 when the
        learn subsystem is off, so inert worlds pay no task-table-sized
        memory for it)."""
        return self.task_capacity if self.learn_active else 0

    # --- chaos sizing (zero-row when the subsystem is off) -------------
    @property
    def chaos_fogs(self) -> int:
        """Rows of the per-fog chaos schedule/accumulator leaves."""
        return self.n_fogs if self.chaos else 0

    @property
    def chaos_tasks(self) -> int:
        """Rows of the per-task re-offload retry column (0 when chaos
        is off, so inert worlds pay no task-table-sized memory)."""
        return self.task_capacity if self.chaos else 0

    # --- hierarchy sizing (zero-row when the subsystem is off) ---------
    @property
    def hier_active(self) -> bool:
        """Whether the federated multi-broker hierarchy is live.

        Static under jit: ``n_brokers == 1`` traces none of the
        hierarchy machinery (domain masks, migrate phase, HierState
        updates), which is the bit-exactness argument of the single-
        broker gate (tests/test_hier.py)."""
        return self.n_brokers > 1

    @property
    def hier_brokers(self) -> int:
        """Rows of the per-broker hierarchy leaves (peer views,
        migration counters)."""
        return self.n_brokers if self.hier_active else 0

    @property
    def hier_users(self) -> int:
        """Rows of the user-ownership vector."""
        return self.n_users if self.hier_active else 0

    @property
    def hier_fogs(self) -> int:
        """Rows of the fog-ownership vector."""
        return self.n_fogs if self.hier_active else 0

    @property
    def hier_tasks(self) -> int:
        """Rows of the per-task broker/hop columns (0 when the
        hierarchy is off, so single-broker worlds pay no
        task-table-sized memory)."""
        return self.task_capacity if self.hier_active else 0

    @property
    def telemetry_hier_brokers(self) -> int:
        """Rows of the per-broker telemetry load accumulators: the
        broker count when BOTH the telemetry plane and the hierarchy
        are on, zero otherwise — the zero-row inert discipline of every
        other telemetry dimension."""
        return self.n_brokers if (self.telemetry and self.hier_active) else 0

    # --- telemetry sizing (zero-row when the plane is off) -------------
    @property
    def telemetry_fogs(self) -> int:
        """Rows of the per-fog telemetry accumulators."""
        return self.n_fogs if self.telemetry else 0

    @property
    def telemetry_phases(self) -> int:
        """Rows of the per-phase work-counter vector."""
        from .telemetry.metrics import PHASES

        return len(PHASES) if self.telemetry else 0

    @property
    def telemetry_slots(self) -> int:
        """Rows of the strided per-tick reservoir."""
        if not self.telemetry:
            return 0
        return max(1, min(self.telemetry_reservoir, self.n_ticks))

    @property
    def telemetry_hist_fogs(self) -> int:
        """Rows of the per-fog latency-histogram leaves (0 when off)."""
        return self.n_fogs if (self.telemetry and self.telemetry_hist) else 0

    @property
    def telemetry_hist_nbins(self) -> int:
        """Columns of the latency histogram (0 when off; the last
        column is the +Inf overflow bucket)."""
        if not (self.telemetry and self.telemetry_hist):
            return 0
        return self.telemetry_hist_bins

    @property
    def telemetry_hist_tasks(self) -> int:
        """Rows of the per-task counted flag that makes the streaming
        histogram exactly-once (0 when off).  A completion backlog can
        ack a task whose ``t_ack6`` already lies behind the tick window
        (the learn-credit problem, PR 2), so the trigger is a persistent
        flag, not a time-interval test."""
        return (
            self.task_capacity
            if (self.telemetry and self.telemetry_hist)
            else 0
        )

    # --- journey sizing (zero-row when the plane is off) ---------------
    @property
    def journey_active(self) -> bool:
        """Whether the task-journey event rings are live.  Static under
        jit: it gates whether the engine traces the per-tick journey
        tap at all, so journey-off worlds stay bit-exact (the
        inert-LearnState discipline, tests/test_journeys.py)."""
        return self.telemetry and self.telemetry_journeys > 0

    @property
    def journey_slots(self) -> int:
        """Rows of the per-sampled-task journey leaves (ring, cursor,
        previous-snapshot): J when the plane is on, zero otherwise."""
        if not self.journey_active:
            return 0
        return min(self.telemetry_journeys, self.task_capacity)

    @property
    def journey_ring(self) -> int:
        """Event rows of each sampled task's ring (0 when off)."""
        return self.telemetry_journey_ring if self.journey_active else 0

    @property
    def telemetry_tp_shards(self) -> int:
        """Rows of the per-shard TP exchange-plane telemetry leaves
        (``TelemetryState.exg_*``): the stamped shard count when the
        telemetry plane is on, zero otherwise — the same zero-row inert
        discipline as every other telemetry dimension."""
        return self.tp_shards if self.telemetry else 0

    @property
    def auto_arrival_window(self) -> int:
        """Window sized from the spec's own arrival rate (VERDICT r3 #4).

        Steady-state publishes per tick = ``n_users * dt / send_interval``;
        30% slack plus a start-up pad absorbs jitter and the connect
        transient, so window overflow (``Metrics.n_deferred``) stays at
        zero in steady state without hand tuning.  Pass as
        ``arrival_window=spec_args -> build(..., arrival_window=None)``
        replacement for large worlds: e.g. the 100k/1M-user benchmark
        rows (``benchmarks.py``).
        """
        rate = self.n_users * self.dt / max(self.send_interval, 1e-12)
        return int(
            min(self.task_capacity, max(1024, int(1.3 * rate) + 256))
        )

    def validate(self) -> "WorldSpec":
        assert self.n_users >= 0 and self.n_fogs >= 0
        assert self.max_sends_per_user > 0 and self.queue_capacity > 0
        assert self.dt > 0 and self.horizon > 0
        assert self.n_topics >= 1 and self.pool_phases >= 1
        assert 0.0 <= self.uplink_loss_prob <= 1.0, (
            f"uplink_loss_prob is a probability, got {self.uplink_loss_prob}"
        )
        if self.arrival_window is not None:
            assert self.arrival_window > 0
        assert self.telemetry_reservoir >= 1, (
            "telemetry_reservoir sizes the per-tick sample reservoir "
            "(>= 1 row)"
        )
        assert self.tp_shards >= 0, (
            "tp_shards is a shard count (0 = unsharded world view)"
        )
        if self.telemetry_hist:
            assert self.telemetry, (
                "telemetry_hist rides TelemetryState in the scan carry: "
                "set spec.telemetry=True as well"
            )
            assert self.telemetry_hist_bins >= 2, (
                "the latency histogram needs >= 2 buckets (the last is "
                "the +Inf overflow)"
            )
            assert (
                0.0 < self.telemetry_hist_min_ms < self.telemetry_hist_max_ms
            ), "histogram bounds must satisfy 0 < min_ms < max_ms"
            assert not self.derive_acks, (
                "telemetry_hist streams latencies at status-6 ack time "
                "inside the tick; derive_acks reconstructs the ack "
                "columns only after the scan"
            )
        # --- journey tracing (ValueError: user-reachable knobs) --------
        if self.telemetry_journeys < 0:
            raise ValueError(
                f"telemetry_journeys is a sampled-task count (>= 0), "
                f"got {self.telemetry_journeys}"
            )
        if self.telemetry_journeys > 0:
            if not self.telemetry:
                raise ValueError(
                    "[SPEC-JOURNEYS-TELEM] telemetry_journeys rides "
                    "TelemetryState in the scan carry: set "
                    "spec.telemetry=True as well"
                )
            if self.telemetry_journeys > self.task_capacity:
                raise ValueError(
                    f"telemetry_journeys={self.telemetry_journeys} "
                    f"exceeds the task capacity "
                    f"{self.task_capacity}: there are not that many "
                    "task slots to sample"
                )
            if self.telemetry_journey_ring < 8:
                raise ValueError(
                    "telemetry_journey_ring needs >= 8 event rows per "
                    "sampled task: one tick can append up to 8 edges "
                    "(spawn, re-offload, migrate, decide, local, "
                    "enqueue, service start, terminal), and a ring "
                    "smaller than one tick's worth would wrap WITHIN "
                    "the tick's scatter (duplicate-index order is "
                    "undefined)"
                )
        if self.chaos:
            # ValueError (not assert) on the user-reachable knobs: the
            # CLI/config tier surfaces these as one actionable line
            if self.assume_static:
                raise ValueError(
                    "[SPEC-CHAOS-STATIC] chaos cannot run under "
                    "assume_static: crash/recover "
                    "schedules mutate fog liveness per tick (the energy-"
                    "lifecycle restriction); build with assume_static="
                    "False"
                )
            if self.energy_enabled:
                raise ValueError(
                    "[SPEC-CHAOS-ENERGY] chaos and the energy lifecycle "
                    "both drive node liveness; enable one failure "
                    "source per world"
                )
            if self.chaos_mode not in tuple(int(m) for m in ChaosMode):
                raise ValueError(
                    f"unknown chaos_mode {self.chaos_mode} (have "
                    + ", ".join(
                        f"{m.name.lower()}={int(m)}" for m in ChaosMode
                    )
                    + ")"
                )
            if self.chaos_mtbf_s > 0 and not (self.chaos_mttr_s > 0):
                raise ValueError(
                    "random crash schedules need a repair time: set "
                    "chaos_mttr_s > 0 alongside chaos_mtbf_s"
                )
            if not (0 <= self.chaos_max_retries < 127):
                raise ValueError(
                    "chaos_max_retries must be in [0, 127) (the "
                    "per-task retry column is int8)"
                )
            for ent in self.chaos_script:
                if len(ent) != 3:
                    raise ValueError(
                        f"chaos_script entries are (fog, t_down, t_up), "
                        f"got {ent!r}"
                    )
                f, td, tu = ent
                if not (0 <= int(f) < self.n_fogs):
                    raise ValueError(
                        f"chaos_script fog index {f} out of range "
                        f"[0, {self.n_fogs})"
                    )
                if not (0.0 <= float(td) < float(tu)):
                    raise ValueError(
                        f"chaos_script interval ({td}, {tu}) needs "
                        "0 <= t_down < t_up"
                    )
                if float(tu) - float(td) < self.dt:
                    raise ValueError(
                        f"chaos_script interval ({td}, {tu}) is shorter "
                        f"than one tick (dt={self.dt}): the engine "
                        "observes liveness at tick boundaries, so a "
                        "sub-tick outage would silently never fire — "
                        "widen it to at least dt"
                    )
            if self.chaos_rtt_amp < 0 or self.chaos_rtt_period_s <= 0:
                raise ValueError(
                    "chaos_rtt_amp must be >= 0 with chaos_rtt_period_s "
                    "> 0"
                )
            if not (0.0 <= self.chaos_rtt_burst_prob <= 1.0):
                raise ValueError(
                    "chaos_rtt_burst_prob is a probability, got "
                    f"{self.chaos_rtt_burst_prob}"
                )
            if self.chaos_rtt_burst_prob > 0 and (
                self.chaos_rtt_burst_mult <= 0
            ):
                raise ValueError(
                    "chaos_rtt_burst_mult must be > 0 when bursts are on"
                )
        # --- digital-twin ingestion (ValueError: user-reachable knobs) -
        if self.ingest:
            if self.ingest_batch < 1:
                raise ValueError(
                    f"ingest_batch sizes the fixed injection batch "
                    f"(>= 1 row), got {self.ingest_batch}"
                )
            if self.ingest_batch > self.task_capacity:
                raise ValueError(
                    f"ingest_batch={self.ingest_batch} exceeds the task "
                    f"capacity {self.task_capacity}: one boundary could "
                    "never land that many publishes"
                )
        # --- federated hierarchy (ValueError: user-reachable knobs) ----
        if self.n_brokers < 1:
            raise ValueError(
                f"n_brokers must be >= 1 (got {self.n_brokers}); 1 is "
                "the single base broker, B > 1 federates"
            )
        if self.n_brokers == 1 and self.hier_rtt_matrix is not None:
            # the DynSpec hier_rtt leaf is (1, 1) on single-broker
            # worlds by contract (dynspec._hier_rtt_of); an orphan
            # matrix would silently change the operand's shape inside
            # one shape bucket
            raise ValueError(
                "hier_rtt_matrix needs a federated world: set "
                "n_brokers > 1 (or drop the matrix)"
            )
        if self.n_brokers > 1:
            if self.n_brokers > self.n_fogs:
                raise ValueError(
                    f"n_brokers={self.n_brokers} exceeds n_fogs="
                    f"{self.n_fogs}: every broker domain needs at least "
                    "one fog node — reduce the broker count or add fogs"
                )
            if self.hier_policy not in tuple(int(p) for p in HierPolicy):
                raise ValueError(
                    f"unknown hier_policy {self.hier_policy} (have "
                    + ", ".join(
                        f"{p.name.lower()}={int(p)}" for p in HierPolicy
                    )
                    + ")"
                )
            if self.policy in (
                int(Policy.ROUND_ROBIN),
                int(Policy.LOCAL_FIRST),
                int(Policy.DYNAMIC),
            ):
                raise ValueError(
                    f"[SPEC-HIER-POLICY] policy "
                    f"{Policy(self.policy).name.lower()} does not "
                    "federate (n_brokers > 1): round_robin needs a "
                    "per-domain cursor, local_first/dynamic are single-"
                    "broker constructs — use the argmin family "
                    "(min_busy/min_latency/energy_aware/random/max_mips) "
                    "or a learned policy (ucb/ducb/exp3)"
                )
            if not (0 <= self.hier_max_hops < 127):
                raise ValueError(
                    "hier_max_hops must be in [0, 127) (the per-task "
                    "hop column is int8)"
                )
            if not (self.hier_threshold >= 0.0):
                raise ValueError(
                    "hier_threshold is a busy fraction (>= 0; inf "
                    "disables the saturation trigger)"
                )
            if self.hier_rtt_s < 0:
                raise ValueError("hier_rtt_s must be >= 0 seconds")
            if self.hier_rtt_matrix is not None:
                B = self.n_brokers
                if len(self.hier_rtt_matrix) != B or any(
                    len(row) != B for row in self.hier_rtt_matrix
                ):
                    raise ValueError(
                        f"hier_rtt_matrix must be {B}x{B} for "
                        f"n_brokers={B}"
                    )
                if any(
                    float(x) < 0 for row in self.hier_rtt_matrix
                    for x in row
                ):
                    raise ValueError(
                        "hier_rtt_matrix entries are RTTs (>= 0 s)"
                    )
        if self.assume_static:
            assert not self.energy_enabled, (
                "assume_static promises constant (pos, alive); the energy "
                "model's lifecycle shutdown/restart mutates alive"
            )
            if self.mac_keyed:
                raise ValueError(STATIC_MAC_ERR)
        assert self.max_sends_per_tick >= 1
        if self.arrival_cands_per_user is not None:
            assert self.arrival_cands_per_user >= 1
        if self.derive_acks:
            assert (
                self.assume_static
                and not self.wired_queue_enabled
                and self.fog_model == int(FogModel.FIFO)
                and self.policy != int(Policy.LOCAL_FIRST)
            ), (
                "derive_acks reconstructs ack columns from one static "
                "delay cache: needs assume_static, no DropTail, FIFO "
                "fogs and no broker-local branch"
            )
        if self.max_sends_per_tick > 1:
            assert self.send_interval_jitter == 0.0, (
                "the closed-form multi-send spawn needs deterministic "
                "send spacing (send_interval_jitter == 0)"
            )
        if self.learn_active:
            assert self.n_fogs >= 1, (
                "learned policies need at least one fog node (arm)"
            )
            assert not self.derive_acks, (
                "learned policies credit rewards at ack time inside the "
                "tick; derive_acks reconstructs the ack columns only "
                "after the scan"
            )
            assert self.app_gen >= 2, (
                "learned policies need the status-6 ack chain (app_gen "
                ">= 2): the v1 broker drops TaskAcks, so no reward "
                "signal ever reaches the learner"
            )
            assert 0.0 < self.learn_discount <= 1.0
            assert self.learn_reward_scale > 0.0
            assert self.learn_explore >= 0.0
        if self.learn_in_dynamic:
            assert self.policy == int(Policy.DYNAMIC), (
                "learn_in_dynamic extends the DYNAMIC traced switch: set "
                "policy=Policy.DYNAMIC (a static learned policy needs no "
                "switch)"
            )
        if self.policy == int(Policy.LOCAL_FIRST):
            assert self.broker_mips > 0, (
                "LOCAL_FIRST needs a broker-side MIPS pool (broker_mips)"
            )
        if self.v2_local_broker:
            assert self.policy == int(Policy.LOCAL_FIRST), (
                "v2_local_broker models BrokerBaseApp2's hybrid broker: "
                "set policy=Policy.LOCAL_FIRST (+ broker_mips)"
            )
            assert self.required_time >= self.dt, (
                "v2_local_broker needs required_time >= dt: the broker "
                "scan's in-tick release pre-selection assumes a request "
                "stored this tick cannot expire before a same-tick fire "
                "(core/engine.py LOCAL_FIRST v2 scan)"
            )
        return self
