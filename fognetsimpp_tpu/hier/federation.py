"""Federated multi-broker hierarchy: state, ownership stamping, readers.

The reference models ONE central base broker every end device publishes
to (SURVEY.md §5 "no broker failover logic exists"); internet-scale
deployments federate brokers instead — FogMQ (arXiv:1610.00620) argues
brokers must be distributed and migrate subscriber state, and iFogSim
(arXiv:1606.02007) structures placement across tiers with inter-tier
forwarding cost.  This module is the batched engine's rendition:

* **Domains**: ``spec.n_brokers = B`` partitions users and fogs into B
  broker domains via assembler-stamped ownership vectors
  (:class:`HierState.user_broker` / ``fog_broker``, default
  block-contiguous — :func:`default_ownership`; scenario builders and
  tests restamp with :func:`stamp_ownership`).  Each logical broker
  runs the established decide phase over its LOCAL fog set with its
  own stale view slice (the (F,)-wide BrokerView columns partition
  naturally, since domains partition fogs).
* **Migration**: the contract-registered engine phase
  ``core/engine._phase_broker_migrate`` moves matured publishes between
  brokers when the owning domain is saturated or dead
  (:class:`~fognetsimpp_tpu.spec.HierPolicy`), re-offering them through
  the established K-window arrival contract with the inter-broker hop's
  RTT added to ``t_at_broker`` and a bounded per-task hop budget
  (``spec.hier_max_hops``; exhausted tasks become
  ``Stage.HOP_EXHAUSTED`` and join the conservation identity).
* **Staleness**: broker b's view of peer p's load refreshes only every
  ``rtt[b, p]`` seconds (:class:`HierState.peer_load` / ``peer_t``) —
  federation sees stale data exactly like fogs do through in-flight
  advertisements.

Everything rides :class:`HierState` in the scan carry with the
inert-LearnState gate discipline: every array leaf is zero-row when
``n_brokers == 1``, and no hierarchy code is traced at all, so the
single-broker world is bit-exact vs the pre-hier engine
(tests/test_hier.py A/Bs it across run/run_jit/run_chunked).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..spec import HierPolicy, WorldSpec


@struct.dataclass
class HierState:
    """Carry-resident federation state (one per world / replica).

    Ownership / per-task leaves are sized ``spec.hier_users`` /
    ``spec.hier_fogs`` / ``spec.hier_tasks`` and the per-broker leaves
    ``spec.hier_brokers`` — the real dimensions when ``n_brokers > 1``,
    zero rows otherwise.  The scalar counters are always present and
    stay exactly zero on single-broker worlds.
    """

    user_broker: jax.Array  # (Uh,) i32 broker owning each user's uplink
    fog_broker: jax.Array  # (Fh,) i32 broker owning each fog node
    task_broker: jax.Array  # (Th,) i32 broker currently holding each
    #   task: stamped user_broker[user] at init, restamped by the
    #   migrate phase on every broker→broker hop
    hops: jax.Array  # (Th,) i8 migration hop count per task
    peer_load: jax.Array  # (Bh, Bh) f32 — entry (b, p): broker b's AGED
    #   view of peer p's busy fraction (+inf = dead domain); refreshed
    #   only when the rtt[b, p] exchange period elapses
    peer_t: jax.Array  # (Bh, Bh) f32 next view-refresh time per pair
    mig_out: jax.Array  # (Bh,) i32 tasks migrated AWAY from each broker
    mig_in: jax.Array  # (Bh,) i32 tasks migrated INTO each broker
    n_migrated: jax.Array  # () i32 total broker→broker migrations
    n_hop_exhausted: jax.Array  # () i32 tasks terminal after the hop
    #   budget ran out in a dead domain (conservation bucket)


def default_ownership(spec: WorldSpec):
    """Block-contiguous default domains: user u → broker ``u*B // U``,
    fog f → broker ``f*B // F``.  Host numpy (stamped at init, before
    any tracing); every broker owns at least one fog because
    ``validate()`` requires ``n_brokers <= n_fogs``."""
    B = spec.n_brokers
    ub = (np.arange(spec.n_users, dtype=np.int64) * B) // max(spec.n_users, 1)
    fb = (np.arange(spec.n_fogs, dtype=np.int64) * B) // max(spec.n_fogs, 1)
    return ub.astype(np.int32), fb.astype(np.int32)


def _task_broker_of(spec: WorldSpec, user_broker) -> jnp.ndarray:
    """Per-task owning broker from the static slot layout u*S + k."""
    return jnp.repeat(
        jnp.asarray(user_broker, jnp.int32), spec.max_sends_per_user
    )


def init_hier_state(spec: WorldSpec) -> HierState:
    """The t=0 federation state for ``spec`` (inert zero-row when
    ``n_brokers == 1``)."""
    B = spec.hier_brokers
    f32, i32 = jnp.float32, jnp.int32
    if spec.hier_active:
        ub, fb = default_ownership(spec)
        user_broker = jnp.asarray(ub)
        fog_broker = jnp.asarray(fb)
        task_broker = _task_broker_of(spec, ub)
    else:
        user_broker = jnp.zeros((0,), i32)
        fog_broker = jnp.zeros((0,), i32)
        task_broker = jnp.zeros((0,), i32)
    return HierState(
        user_broker=user_broker,
        fog_broker=fog_broker,
        task_broker=task_broker,
        hops=jnp.zeros((spec.hier_tasks,), jnp.int8),
        # peer_t starts at 0: the first tick refreshes every pair from
        # the live loads, after which each entry ages by its RTT
        peer_load=jnp.zeros((B, B), f32),
        peer_t=jnp.zeros((B, B), f32),
        mig_out=jnp.zeros((B,), i32),
        mig_in=jnp.zeros((B,), i32),
        n_migrated=jnp.zeros((), i32),
        n_hop_exhausted=jnp.zeros((), i32),
    )


def stamp_ownership(
    spec: WorldSpec,
    state,
    user_broker: Optional[Sequence[int]] = None,
    fog_broker: Optional[Sequence[int]] = None,
):
    """Assembler hook: restamp the domain ownership vectors of a built
    world (and rebuild the per-task broker column from the new user
    ownership).  ``None`` keeps the current stamping for that axis.
    Must run BEFORE the first tick — the engine never re-derives
    ``task_broker`` from ``user_broker``."""
    if not spec.hier_active:
        raise ValueError(
            "stamp_ownership needs a federated world (n_brokers > 1)"
        )
    h = state.hier
    B = spec.n_brokers
    if user_broker is not None:
        ub = np.asarray(user_broker, np.int32)
        if ub.shape != (spec.n_users,) or ub.min(initial=0) < 0 or (
            ub.max(initial=0) >= B
        ):
            raise ValueError(
                f"user_broker must be ({spec.n_users},) ints in [0, {B})"
            )
        h = h.replace(
            user_broker=jnp.asarray(ub),
            task_broker=_task_broker_of(spec, ub),
        )
    if fog_broker is not None:
        fb = np.asarray(fog_broker, np.int32)
        if fb.shape != (spec.n_fogs,) or fb.min(initial=0) < 0 or (
            fb.max(initial=0) >= B
        ):
            raise ValueError(
                f"fog_broker must be ({spec.n_fogs},) ints in [0, {B})"
            )
        h = h.replace(fog_broker=jnp.asarray(fb))
    return state.replace(hier=h)


def hier_reject_reason(spec: WorldSpec, runner: str) -> Optional[str]:
    """Why a federated spec cannot run on a sharded runner (None = it
    can — i.e. the hierarchy is off).  ONE message source for the
    TP-tick gate (``core/engine.tp_reject_reason``) and the fleet
    runner (``parallel/fleet._check_fleet_spec``), so the entries can
    never drift apart.  The leading ``[{RUNNER}-HIER]`` clause ID is
    the machine-parseable key (``[TP-HIER]`` / ``[FLEET-HIER]``) that
    ``tools/featmat`` extraction and the ID-asserting tests hang on."""
    if not spec.hier_active:
        return None
    return (
        f"[{runner.upper()}-HIER] the {runner} runner does not carry the "
        "multi-broker hierarchy yet (per-domain decide masks and the "
        "migrate phase need cross-shard load summaries); run "
        f"n_brokers={spec.n_brokers} worlds on single-device "
        "run/run_jit/run_chunked"
    )


# ----------------------------------------------------------------------
# host-side readers (post-run / per chunk; one fetch each)
# ----------------------------------------------------------------------

def hier_summary(spec: WorldSpec, final) -> Optional[dict]:
    """Host roll-up of a finished federated run (None when the
    hierarchy is off).  THE values every exposition publishes — the
    recorder's ``.sca.json`` hier section, the ``fns_hier_*``
    OpenMetrics families and the Perfetto broker lanes all read this
    one dict (the ``busy_fractions`` single-source discipline)."""
    if not spec.hier_active:
        return None
    h = final.hier
    B = spec.n_brokers
    fb = np.asarray(h.fog_broker, np.int64)
    ub = np.asarray(h.user_broker, np.int64)
    out = {
        "n_brokers": B,
        "policy": HierPolicy(spec.hier_policy).name.lower(),
        "max_hops": int(spec.hier_max_hops),
        "migrated": int(np.asarray(h.n_migrated)),
        "hop_exhausted": int(np.asarray(h.n_hop_exhausted)),
        # plain ints: every consumer JSON-serializes this dict verbatim
        "mig_out": [int(x) for x in np.asarray(h.mig_out)],
        "mig_in": [int(x) for x in np.asarray(h.mig_in)],
        "fogs_per_broker": [int((fb == b).sum()) for b in range(B)],
        "users_per_broker": [int((ub == b).sum()) for b in range(B)],
    }
    # per-broker mean load + strided per-tick lanes when the telemetry
    # plane carried the hier accumulators (telemetry_hier_brokers > 0)
    t = getattr(final, "telem", None)
    if t is not None and t.hier_load_sum.shape[0] == B:
        ticks = max(int(np.asarray(t.ticks)), 1)
        res = np.asarray(t.res, np.float64)
        rows = np.asarray(t.hier_load_res, np.float64)
        Rm = rows.shape[0]
        stride = max(1, -(-spec.n_ticks // Rm)) if Rm else 1
        n_rows = min(Rm, -(-ticks // stride)) if Rm else 0
        out["load_mean"] = [
            float(x) / ticks for x in np.asarray(t.hier_load_sum)
        ]
        out["load_rows"] = rows[:n_rows]
        out["load_rows_t"] = (
            res[:n_rows, 0] if n_rows else np.zeros((0,))
        )
    return out


def hier_counters(final) -> dict:
    """Tiny per-chunk counter fetch for the live health plane: two
    scalars, no per-broker or per-task leaves — safe at any serving
    cadence."""
    h = final.hier
    return {
        "migrated": int(np.asarray(h.n_migrated)),
        "hop_exhausted": int(np.asarray(h.n_hop_exhausted)),
    }
