"""Federated multi-broker hierarchy with broker↔broker task migration."""
from .federation import (  # noqa: F401
    HierState,
    default_ownership,
    hier_counters,
    hier_reject_reason,
    hier_summary,
    init_hier_state,
    stamp_ownership,
)
