"""Mobility kernels: INET LinearMobility / CircleMobility equivalents.

The reference configures mobility declaratively per node
(``simulations/testing/wireless5.ini:23-50`` LinearMobility with speed/angle,
``simulations/example/wirelessNet.ini:13-29`` CircleMobility r=250 m at
40 mps).  Here all nodes advance in one vectorized update per tick; circle
motion is closed-form in time (exact, no integration drift), linear motion
integrates with reflective bounds like INET's constraint area.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ..spec import Mobility
from .topology import NetParams  # noqa: F401  (re-export convenience)


@struct.dataclass
class MobilityBounds:
    lo: jax.Array  # (2,) f32 constraint area min (x, y)
    hi: jax.Array  # (2,) f32 constraint area max


def default_bounds(extent: float = 1000.0) -> MobilityBounds:
    return MobilityBounds(
        lo=jnp.zeros((2,), jnp.float32),
        hi=jnp.full((2,), extent, jnp.float32),
    )


def step_mobility(nodes, bounds: MobilityBounds, t_next: jax.Array, dt: float):
    """Advance every node one tick. Returns (pos, vel) updated arrays.

    LINEAR: pos += vel*dt with reflective bounce (INET LinearMobility's
    constraint-area reflection).  CIRCLE: closed-form
    ``center + r*(cos, sin)(phase + omega*t)`` — evaluated at absolute time
    so long scans accumulate no error.
    """
    mob = nodes.mobility
    pos, vel = nodes.pos, nodes.vel

    # linear + bounce
    p_lin = pos + vel * dt
    lo, hi = bounds.lo[None, :], bounds.hi[None, :]
    over_hi = p_lin > hi
    under_lo = p_lin < lo
    p_lin = jnp.where(over_hi, 2 * hi - p_lin, p_lin)
    p_lin = jnp.where(under_lo, 2 * lo - p_lin, p_lin)
    v_lin = jnp.where(over_hi | under_lo, -vel, vel)

    # circle, closed-form at absolute time t_next
    ang = nodes.circle_phase + nodes.circle_omega * t_next
    p_circ = nodes.circle_center + nodes.circle_radius[:, None] * jnp.stack(
        [jnp.cos(ang), jnp.sin(ang)], axis=-1
    )

    is_lin = (mob == int(Mobility.LINEAR))[:, None]
    is_circ = (mob == int(Mobility.CIRCLE))[:, None]
    new_pos = jnp.where(is_circ, p_circ, jnp.where(is_lin, p_lin, pos))
    new_vel = jnp.where(is_lin, v_lin, vel)
    return new_pos.astype(jnp.float32), new_vel.astype(jnp.float32)
