"""Network model: the simulated UDP/IP/802.11 stack collapsed into data.

The reference gets its network effects (link delay, queueing, 802.11
contention, AP handover) emergently from INET's per-packet stack traversal
(SURVEY.md §2.2).  The TPU-native design replaces packet traversal with a
*delay model*: every message's travel time is a pure function of (src, dst,
time), composed of

  ``delay(a, b, t) = wacc(a, t) + core[attach(a, t), attach(b, t)] + wacc(b, t)``

where ``core`` is a small all-pairs base-delay matrix over *infrastructure
attach points* (wired hosts, APs, routers — shortest path over link
propagation + serialization, Floyd–Warshall at build time; the
DropTailQueue on every eth interface — ``wireless5.ini:72-73`` — has a
batched analog in the engine, ``spec.wired_queue_enabled``: per-node
egress backlog with serialization backpressure and frameCapacity tail
drops, off by default since no reference scenario drives its 100 Mbps
links near saturation, a claim ``tests/test_link_queue.py`` now tests),
``attach`` maps a node to its attach point (itself if wired, its associated
AP if wireless — association is argmin distance within range, recomputed
every tick so handover is emergent, mirroring INET's 802.11 mgmt), and
``wacc`` is the wireless access delay (base MAC+serialization plus a
contention term linear in the AP's current station count — the calibrated
approximation of 802.11 EDCA noted in SURVEY.md §7 "hard parts").

Scales to 10k+ nodes because the dense matrix is only over the ~dozens of
infrastructure nodes; per-node state is O(N).
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class NetParams:
    """Static-per-scenario network data (device arrays, shapes fixed)."""

    core_delay: jax.Array  # (I, I) f32 — base path delay between attach pts
    node_attach: jax.Array  # (N,) i32 — wired attach point per node (or -1)
    node_acc: jax.Array  # (N,) f32 — wired access-link delay to the attach
    #   point (lets many hosts share one infra entry: a 10k-host star is one
    #   switch + per-node access cost, O(N) instead of an O(N^2) matrix)
    is_wireless: jax.Array  # (N,) bool
    ap_nodes: jax.Array  # (A,) i32 node indices of APs (A >= 1 if any wireless)
    ap_attach: jax.Array  # (A,) i32 infra index of each AP
    ap_range: jax.Array  # (A,) f32 metres
    w_base: jax.Array  # () f32 wireless per-hop base delay (s)
    w_prop: jax.Array  # () f32 propagation s/m
    w_contention: jax.Array  # () f32 single-station MAC airtime anchor (s):
    #   occupancy-n access delay = w_contention * mac_delay_tab[n] /
    #   mac_delay_tab[1] (Bianchi shape, calibrated scale) — or the legacy
    #   linear w_contention * n when mac_delay_tab is empty
    # --- load-dependent 802.11 DCF model (r4, VERDICT item 3) ----------
    # Bianchi saturation tables indexed by per-AP station count, built
    # host-side from the reference's MAC configuration (wireless5.ini:
    # 56-68: DCF, cwMinData 31, retryLimit 7, 54/6 Mbps): delay rises
    # superlinearly and loss = p_collision^(retryLimit+1) rises from ~0
    # as the cell saturates.  Empty (0,) tables = legacy linear model.
    mac_delay_tab: jax.Array  # (n_max+1,) f32 expected MAC access delay
    mac_loss_tab: jax.Array  # (n_max+1,) f32 retry-exhaustion loss prob


@struct.dataclass
class LinkCache:
    """Per-tick derived connectivity (recomputed after mobility)."""

    assoc: jax.Array  # (N,) i32 — associated AP slot per node (-1 = none)
    n_assoc: jax.Array  # (A,) i32 — stations per AP
    attach_now: jax.Array  # (N,) i32 — current infra attach point per node
    acc_delay: jax.Array  # (N,) f32 — current wireless access delay per node
    reachable: jax.Array  # (N,) bool — node currently has connectivity
    d2b: jax.Array  # (N,) f32 — delay(node, broker) this tick (+inf when
    #   unreachable).  Every message in the protocol has the base broker at
    #   one end (SURVEY.md §3.2-3.3), so this one vector serves all phases.
    mac_loss_p: jax.Array  # (N,) f32 — this tick's per-node 802.11 retry-
    #   exhaustion loss probability from the sender's cell occupancy
    #   (0 for wired nodes / the legacy linear model)


def _delay_between(
    net: NetParams, attach_a, acc_a, attach_b, acc_b
) -> jax.Array:
    """The delay model: ``acc_a + core[attach_a, attach_b] + acc_b``.

    Single implementation shared by :func:`pair_delay` and the per-tick
    broker-delay cache; unattached endpoints (attach < 0) yield +inf.
    """
    I = net.core_delay.shape[0]
    core = net.core_delay[
        jnp.clip(attach_a, 0, I - 1), jnp.clip(attach_b, 0, I - 1)
    ]
    d = acc_a + core + acc_b
    return jnp.where((attach_a >= 0) & (attach_b >= 0), d, jnp.inf)


def _delay_to(
    net: NetParams, attach_now: jax.Array, acc_delay: jax.Array, dst: int
) -> jax.Array:
    """Per-node delay to one fixed destination node (the base broker)."""
    return _delay_between(
        net, attach_now, acc_delay, attach_now[dst], acc_delay[dst]
    )


def associate(
    net: NetParams, pos: jax.Array, alive: jax.Array,
    broker: int | None = None, offered_rate: jax.Array | None = None,
) -> LinkCache:
    """Recompute AP association + access delays for the current positions.

    Association = nearest alive AP within range (INET's 802.11 mgmt
    association, made explicit).  Handover between APs as a node moves is
    emergent, as in the reference's wireless4/wireless5 scenarios
    (``simulations/testing/wireless4.ini``).

    ``broker`` must be the base-broker node index (``spec.broker_index``) —
    required because a wrong-but-plausible default (node 0 is always a
    *user* under the [users | fogs | broker] layout) would silently compute
    every protocol delay to the wrong node.

    ``offered_rate`` (r5, VERDICT r4 item 2): per-node offered frame rate
    (frames/s; 0 = idle).  INET's DCF contends only among stations with
    queued frames, not among associated-but-idle ones — with this given,
    the Bianchi lookup is keyed on each cell's EFFECTIVE backlogged
    station count via the Little's-law fixed point

        n_eff = clip(lambda_cell * D(n_eff), 1, occupancy)

    (lambda = summed offered rate in the cell, D = the Bianchi per-frame
    MAC delay at n contenders): a cell at 20% utilisation keys near the
    n=1 baseline however many stations are merely associated, and an
    overloaded cell climbs to its occupancy ceiling — saturation delay
    and retry-exhaustion loss.  The map is monotone, so 8 damped
    iterations pin the fixed point to table resolution.  ``None`` keeps
    the legacy occupancy keying (all associated stations count).
    """
    if broker is None:
        raise ValueError(
            "associate() needs broker=spec.broker_index to build the "
            "delay-to-broker cache"
        )
    N = pos.shape[0]
    A = net.ap_nodes.shape[0]
    if A == 0:
        attach_now = net.node_attach
        return LinkCache(
            assoc=jnp.full((N,), -1, jnp.int32),
            n_assoc=jnp.zeros((0,), jnp.int32),
            attach_now=attach_now,
            acc_delay=net.node_acc,
            reachable=attach_now >= 0,
            d2b=_delay_to(net, attach_now, net.node_acc, broker),
            mac_loss_p=jnp.zeros((N,), jnp.float32),
        )
    ap_pos = pos[net.ap_nodes]  # (A, 2)
    ap_ok = alive[net.ap_nodes]  # (A,)
    d2 = jnp.sum((pos[:, None, :] - ap_pos[None, :, :]) ** 2, axis=-1)  # (N, A)
    d2 = jnp.where(ap_ok[None, :], d2, jnp.inf)
    nearest = jnp.argmin(d2, axis=1).astype(jnp.int32)  # (N,)
    ndist = jnp.sqrt(jnp.take_along_axis(d2, nearest[:, None], axis=1)[:, 0])
    in_range = ndist <= net.ap_range[nearest]
    assoc = jnp.where(net.is_wireless & in_range & alive, nearest, -1)

    n_assoc = jnp.zeros((A + 1,), jnp.int32).at[
        jnp.where(assoc >= 0, assoc, A)
    ].add(1, mode="drop")[:A]

    attach_now = jnp.where(
        net.is_wireless,
        jnp.where(assoc >= 0, net.ap_attach[jnp.clip(assoc, 0, A - 1)], -1),
        net.node_attach,
    )
    assoc_c = jnp.clip(assoc, 0, A - 1)
    if net.mac_delay_tab.shape[0] > 0:
        # Bianchi DCF: access delay follows the saturation curve, scale
        # anchored at n=1 to the calibrated w_contention (the committed
        # single-station demo trace is numerically unchanged); loss is
        # the retry-exhaustion probability of the same fixed point
        tab_n = net.mac_delay_tab.shape[0]
        occ_f = jnp.maximum(n_assoc.astype(jnp.float32), 1.0)  # (A,)
        if offered_rate is not None:
            # Little's-law effective contenders (docstring above):
            # n_eff = clip(lambda * D(n_eff), 1, occupancy), solved by
            # 8 iterations of the monotone map over the (A,) cells
            src_ok = net.is_wireless & (assoc >= 0)
            lam = jnp.zeros((A + 1,), jnp.float32).at[
                jnp.where(src_ok, assoc, A)
            ].add(
                jnp.where(src_ok, offered_rate, 0.0), mode="drop"
            )[:A]

            def _interp(tab, x):
                i0 = jnp.clip(
                    jnp.floor(x).astype(jnp.int32), 0, tab_n - 2
                )
                fr = jnp.clip(x - i0.astype(jnp.float32), 0.0, 1.0)
                return tab[i0] * (1.0 - fr) + tab[i0 + 1] * fr

            n_eff = jnp.ones((A,), jnp.float32)
            for _ in range(8):
                n_eff = jnp.clip(
                    lam * _interp(net.mac_delay_tab, n_eff), 1.0, occ_f
                )
            n_here_f = n_eff[assoc_c]  # (N,) continuous contender count
            mac_d = (
                net.w_contention
                * _interp(net.mac_delay_tab, n_here_f)
                / net.mac_delay_tab[1]
            )
            mac_loss = _interp(net.mac_loss_tab, n_here_f)
        else:
            n_here = n_assoc[assoc_c]  # legacy: own-cell occupancy
            n_c = jnp.clip(n_here, 0, tab_n - 1)
            mac_d = (
                net.w_contention
                * net.mac_delay_tab[n_c]
                / net.mac_delay_tab[1]
            )
            mac_loss = net.mac_loss_tab[n_c]
    else:
        n_here = n_assoc[assoc_c]
        mac_d = net.w_contention * n_here.astype(jnp.float32)
        mac_loss = jnp.zeros((N,), jnp.float32)
    on_air = net.is_wireless & (assoc >= 0)
    acc = jnp.where(
        on_air,
        net.w_base + net.w_prop * ndist + mac_d,
        net.node_acc,
    )
    acc = acc.astype(jnp.float32)
    return LinkCache(
        assoc=assoc,
        n_assoc=n_assoc,
        attach_now=attach_now,
        acc_delay=acc,
        reachable=attach_now >= 0,
        d2b=_delay_to(net, attach_now, acc, broker),
        mac_loss_p=jnp.where(on_air, mac_loss, 0.0).astype(jnp.float32),
    )


def pair_delay(
    net: NetParams, cache: LinkCache, src: jax.Array, dst: jax.Array
) -> jax.Array:
    """Vectorized message delay between node index arrays src/dst.

    Unreachable endpoints (wireless node out of AP range, dead AP) yield
    +inf — the message is lost, like a packet that never associates in INET.
    """
    return _delay_between(
        net,
        cache.attach_now[src],
        cache.acc_delay[src],
        cache.attach_now[dst],
        cache.acc_delay[dst],
    )


# ----------------------------------------------------------------------
# Host-side builders (numpy; run once per scenario)
# ----------------------------------------------------------------------

def bianchi_fixed_point(
    n: int, cw_min: int = 31, n_stages: int = 5
) -> Tuple[float, float]:
    """Solve Bianchi's two-equation DCF fixed point for n stations.

    Returns (tau, p): per-slot transmission probability and conditional
    collision probability satisfying (Bianchi 2000, eqs. 7 and 9)

        tau = 2(1-2p) / ((1-2p)(W+1) + pW(1-(2p)^m)),
        p   = 1 - (1-tau)^(n-1)

    with W = cw_min+1 and m = n_stages backoff doublings.  Exposed
    separately from :func:`bianchi_tables` so tests can verify the
    solved point against the defining equations (a correctness check
    independent of the damped iteration used to find it).
    """
    W = cw_min + 1
    tau = 2.0 / (W + 1)
    for _ in range(200):
        p = 1.0 - (1.0 - tau) ** (n - 1)
        denom = (1 - 2 * p) * (W + 1) + p * W * (1 - (2 * p) ** n_stages)
        tau_new = 2 * (1 - 2 * p) / denom if abs(denom) > 1e-12 else 1e-6
        tau_new = min(max(tau_new, 1e-7), 1.0)
        prev = tau
        tau = 0.5 * tau + 0.5 * tau_new  # damped: stable for large n
        if abs(tau - prev) < 1e-12:
            break
    return tau, 1.0 - (1.0 - tau) ** (n - 1)


def bianchi_tables(
    n_max: int,
    cw_min: int = 31,  # wireless5.ini:67 cwMinData
    n_stages: int = 5,  # CWmax 1023 = 31 doubled 5 times (802.11g DCF)
    retry_limit: int = 7,  # wireless5.ini:66
    slot_s: float = 9e-6,  # 802.11g ERP slot
    sifs_s: float = 10e-6,
    difs_s: float = 28e-6,
    rate_bps: float = 54e6,  # wireless5.ini:64 mac.bitrate
    basic_bps: float = 6e6,  # :65 basicBitrate (ACKs)
    payload_bytes: int = 128,
    mac_header_bytes: int = 34,
    phy_preamble_s: float = 20e-6,
    ack_bytes: int = 14,
) -> Tuple[np.ndarray, np.ndarray]:
    """Bianchi DCF saturation tables for 0..n_max contending stations.

    Solves the standard two-equation fixed point (tau = transmission
    probability per slot, p = conditional collision probability) for each
    station count, then derives
      * expected per-packet MAC access delay  D(n) = E[backoff slots over
        the retry ladder] * E[slot length] + T_success, and
      * retry-exhaustion loss  L(n) = p^(retryLimit+1)
    — the emergent quantities of INET's Ieee80211Mac that the reference
    configures at ``wireless5.ini:56-68`` (DCF: EDCA false, cwMinData 31,
    retryLimit 7, 54 Mbps data / 6 Mbps basic).  Both are monotone in n
    and saturate the way a real cell does; the engine anchors the SCALE
    at n=1 to the calibrated ``w_contention`` so single-station worlds
    (the committed demo trace) are numerically unchanged.
    """
    W = cw_min + 1
    t_s = (
        phy_preamble_s
        + (mac_header_bytes + payload_bytes) * 8.0 / rate_bps
        + sifs_s
        + phy_preamble_s
        + ack_bytes * 8.0 / basic_bps
        + difs_s
    )
    t_c = (
        phy_preamble_s
        + (mac_header_bytes + payload_bytes) * 8.0 / rate_bps
        + difs_s
    )
    delays = np.zeros((n_max + 1,), np.float64)
    losses = np.zeros((n_max + 1,), np.float64)
    for n in range(1, n_max + 1):
        tau, p = bianchi_fixed_point(n, cw_min=cw_min, n_stages=n_stages)
        p_tr = 1.0 - (1.0 - tau) ** n
        p_s = n * tau * (1.0 - tau) ** (n - 1) / max(p_tr, 1e-12)
        e_slot = (
            (1 - p_tr) * slot_s + p_tr * p_s * t_s + p_tr * (1 - p_s) * t_c
        )
        # expected backoff slots summed over the retry ladder (stage j's
        # window doubles up to CWmax), weighted by reaching stage j
        ex, reach = 0.0, 1.0
        for j in range(retry_limit + 1):
            w_j = min(W * 2 ** min(j, n_stages), 1024)
            ex += reach * (w_j - 1) / 2.0
            reach *= p
        delays[n] = ex * e_slot + t_s
        losses[n] = p ** (retry_limit + 1)
    delays[0] = delays[1] if n_max >= 1 else 0.0
    return delays.astype(np.float32), losses.astype(np.float32)

def build_core_delay(
    n_infra: int,
    links: Sequence[Tuple[int, int, float, float]],
    packet_bytes: int = 128,
) -> np.ndarray:
    """All-pairs base delay over infrastructure attach points.

    ``links`` entries are (i, j, datarate_bps, prop_delay_s) — the NED
    channel parameters (e.g. 100 Mbps / 0.1 us links,
    ``testing/wireless5.ned:37-42``).  Per-hop cost = prop +
    serialization(packet_bytes).  Floyd–Warshall shortest path stands in for
    IPv4NetworkConfigurator's static routing (SURVEY.md §2.2).
    """
    d = np.full((n_infra, n_infra), np.inf, np.float64)
    np.fill_diagonal(d, 0.0)
    for i, j, rate, prop in links:
        cost = prop + (packet_bytes * 8.0) / rate
        d[i, j] = min(d[i, j], cost)
        d[j, i] = min(d[j, i], cost)
    for k in range(n_infra):
        d = np.minimum(d, d[:, k : k + 1] + d[k : k + 1, :])
    return d.astype(np.float32)


def make_net_params(
    n_nodes: int,
    core_delay: np.ndarray,
    node_attach: np.ndarray,
    is_wireless: np.ndarray,
    ap_nodes: Sequence[int] = (),
    ap_attach: Sequence[int] = (),
    ap_range: float | Sequence[float] = 400.0,
    w_base: float = 2e-3,
    w_prop: float = 3.336e-9,
    w_contention: float = 1.5e-3,
    node_acc: np.ndarray | None = None,
    mac_model: str = "bianchi",
) -> NetParams:
    """Assemble a :class:`NetParams` pytree from host-side arrays.

    ``mac_model="bianchi"`` (default, wireless worlds) attaches the DCF
    saturation tables so access delay AND uplink loss respond to per-AP
    occupancy; ``"linear"`` keeps the legacy constant-per-station model
    (e.g. benchmark worlds whose AP density is a deliberate abstraction).
    """
    A = len(ap_nodes)
    ap_range_arr = (
        np.full((A,), ap_range, np.float32)
        if np.isscalar(ap_range)
        else np.asarray(ap_range, np.float32)
    )
    if node_acc is None:
        node_acc = np.zeros((n_nodes,), np.float32)
    if A > 0 and mac_model == "bianchi":
        mac_delay, mac_loss = bianchi_tables(n_nodes)
    elif mac_model in ("bianchi", "linear"):
        mac_delay = np.zeros((0,), np.float32)
        mac_loss = np.zeros((0,), np.float32)
    else:
        raise ValueError(f"unknown mac_model {mac_model!r}")
    return NetParams(
        core_delay=jnp.asarray(core_delay, jnp.float32),
        node_attach=jnp.asarray(node_attach, jnp.int32),
        node_acc=jnp.asarray(node_acc, jnp.float32),
        is_wireless=jnp.asarray(is_wireless, bool),
        ap_nodes=jnp.asarray(np.asarray(ap_nodes, np.int32)),
        ap_attach=jnp.asarray(np.asarray(ap_attach, np.int32)),
        ap_range=jnp.asarray(ap_range_arr),
        w_base=jnp.asarray(w_base, jnp.float32),
        w_prop=jnp.asarray(w_prop, jnp.float32),
        w_contention=jnp.asarray(w_contention, jnp.float32),
        mac_delay_tab=jnp.asarray(mac_delay),
        mac_loss_tab=jnp.asarray(mac_loss),
    )


def wired_star(n_nodes: int, link_delay: float = 1e-4, rate: float = 100e6,
               packet_bytes: int = 128) -> NetParams:
    """Convenience: all nodes wired to one switch (the smoke-test shape).

    Approximates ``simulations/testing/network.ned:27-69`` where users, fog
    nodes and the broker hang off one router with identical channels.

    Built as ONE infra point (the switch) with per-node access-link delays,
    so construction and memory are O(N) — a 10k-host star needs no 10k²
    delay matrix.  ``delay(a, b) = acc_a + acc_b`` for distinct nodes,
    identical to the two-hop path through the switch.
    """
    cost = link_delay + (packet_bytes * 8.0) / rate
    core = np.zeros((1, 1), np.float32)
    return make_net_params(
        n_nodes=n_nodes,
        core_delay=core,
        node_attach=np.zeros((n_nodes,), np.int32),
        is_wireless=np.zeros((n_nodes,), bool),
        node_acc=np.full((n_nodes,), cost, np.float32),
    )
