"""Energy + lifecycle: INET's battery/management framework, batched.

The reference wires SimpleEpEnergyStorage + StateBasedEpEnergyConsumer +
SimpleEpEnergyManagement + AlternatingEpEnergyGenerator per node in the ini
(``simulations/testing/wireless5.ini:150-166``): radios drain the battery,
the management module shuts a node down below ``nodeShutdownCapacity`` (10%)
and restarts it above ``nodeStartCapacity`` (50%), a generator alternates
harvesting and sleeping.  This *is* the reference's fault-injection mechanism
(SURVEY.md §5) — energy-driven churn of nodes.

Here the whole framework is one masked vector update per tick: idle drain +
per-message tx/rx energy + compute drain for busy fog nodes, square-wave
harvesting, and hysteresis thresholds flipping the ``alive`` mask.  Apps
react exactly like ``handleNodeShutdown``/``handleNodeStart``
(``mqttApp2.cc:471-492``): dead users stop publishing (their send timer is
effectively cancelled), dead fog nodes stop advertising and serving.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..spec import WorldSpec


def step_energy(
    spec: WorldSpec,
    energy: jax.Array,  # (N,) f32 joules
    capacity: jax.Array,  # (N,) f32
    has_energy: jax.Array,  # (N,) bool — node participates in the model
    alive: jax.Array,  # (N,) bool
    t: jax.Array,  # () f32 tick start
    tx_count: jax.Array,  # (N,) i32 messages sent by node this tick
    rx_count: jax.Array,  # (N,) i32 messages received this tick
    computing: jax.Array,  # (N,) bool — fog node actively serving
    dyn=None,  # Optional[DynSpec] (ISSUE 13): promoted power/threshold
    #   operands; None folds the spec's values as the same f32 constants
) -> Tuple[jax.Array, jax.Array]:
    """One energy tick. Returns (energy', alive').

    Nodes outside the model (``has_energy`` False) are always alive-eligible;
    the alive mask for them is left untouched.  Every power/threshold
    scalar reads through the DynSpec view (the per-tick products
    ``idle_power_w*dt`` etc. are host-precomputed leaves), so a what-if
    re-configuration of the energy budget reuses the compiled program.
    """
    if dyn is None:
        from ..dynspec import dyn_of

        dyn = dyn_of(spec)
    drain = (
        dyn.energy_idle_dt
        + dyn.energy_tx_j * tx_count.astype(jnp.float32)
        + dyn.energy_rx_j * rx_count.astype(jnp.float32)
        + jnp.where(computing, dyn.energy_compute_dt, 0.0)
    )
    # AlternatingEpEnergyGenerator: square wave, harvest for `duty` fraction
    # of each period (wireless5.ini:163-166).
    phase = jnp.mod(t, dyn.harvest_period_s) / dyn.harvest_period_s
    harvesting = phase < dyn.harvest_duty
    gain = jnp.where(harvesting, dyn.energy_harvest_dt, 0.0)

    e = jnp.where(
        has_energy,
        jnp.clip(energy - jnp.where(alive, drain, 0.0) + gain, 0.0, capacity),
        energy,
    )
    frac = e / jnp.maximum(capacity, 1e-12)
    # SimpleEpEnergyManagement hysteresis (wireless5.ini:159-161)
    shut = has_energy & alive & (frac <= dyn.shutdown_frac)
    boot = has_energy & ~alive & (frac >= dyn.start_frac)
    alive2 = jnp.where(shut, False, jnp.where(boot, True, alive))
    return e.astype(jnp.float32), alive2
