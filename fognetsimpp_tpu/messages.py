"""The typed protocol-message layer: all 12 reference schemas, mapped.

The reference declares 9 MQTT + 3 fognet packet types as OMNeT++ ``.msg``
schemas (``src/mqttapp/{mqttMessages,fognetMessages}/*.msg``) compiled by
nedtool into ~5.5k LoC of serialization code (SURVEY.md §2.1).  The batched
engine carries the same information as *columns of dense arrays* — a
message "in flight" is a set of per-task/per-node timestamps and payload
fields rather than a heap object.  This module is the explicit schema map:
for every reference message type, which array fields realise its payload
and which engine phase plays each side of the exchange.  It exists so
parity auditing is a table lookup, and so message-level accounting
(:func:`message_counts`) has one authoritative source.

Schema notes mirrored from the reference:
  * Publish **carries the task** (``MqttMsgPublish.msg:21-29``): clientID,
    topic, MIPSRequired, requiredTime, messageID.
  * PingRequest/PingResponse are declared but never sent by any app (no
    references in any ``.cc``) — they exist here as DEAD entries for
    inventory completeness.
  * TaskAck (``FognetMsgTaskAck.msg:17-20``) is v1/v2 only, and every
    broker generation ignores it (``BrokerBaseApp2.cc:139-141``) — realised
    as :class:`~fognetsimpp_tpu.spec.Stage` REJECTED with no client ack.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Tuple

import numpy as np

from .spec import WorldSpec
from .state import WorldState


class Direction(enum.Enum):
    USER_TO_BROKER = "user->broker"
    BROKER_TO_USER = "broker->user"
    FOG_TO_BROKER = "fog->broker"
    BROKER_TO_FOG = "broker->fog"


@dataclasses.dataclass(frozen=True)
class MessageSchema:
    """One reference ``.msg`` type and its array realisation."""

    name: str  # reference class name
    msg_file: str  # schema file under src/mqttapp/
    direction: Direction
    payload: Tuple[str, ...]  # reference payload fields
    realised_by: str  # engine state/phase that carries it
    live: bool = True  # False = declared but never sent in the reference


SCHEMAS: Dict[str, MessageSchema] = {
    s.name: s
    for s in [
        MessageSchema(
            "MqttMsgConnect", "mqttMessages/MqttMsgConnect.msg:28-67",
            Direction.USER_TO_BROKER,
            ("clientID", "qos", "isBroker", "will", "cleanSession",
             "keepAlive"),
            "users.start_t -> _phase_connect (pending mask); fog Connects "
            "are broker.register_t (prime_initial_advertisements)",
        ),
        MessageSchema(
            "MqttMsgConnack", "mqttMessages/MqttMsgConnack.msg",
            Direction.BROKER_TO_USER, ("returnCode",),
            "users.connack_at; first publish fires on arrival "
            "(_phase_connect)",
        ),
        MessageSchema(
            "MqttMsgSubscribe", "mqttMessages/MqttMsgSubscribe.msg:21-25",
            Direction.USER_TO_BROKER, ("clientID", "topic", "qos"),
            "users.sub_mask rows (the broker's subscriptions[] transposed); "
            "counted on Connack in _phase_connect",
        ),
        MessageSchema(
            "MqttMsgSuback", "mqttMessages/MqttMsgSuback.msg",
            Direction.BROKER_TO_USER, ("returnCode",),
            "metrics.n_subscribed increment in _phase_connect",
        ),
        MessageSchema(
            "MqttMsgPublish", "mqttMessages/MqttMsgPublish.msg:21-29",
            Direction.USER_TO_BROKER,
            ("clientID", "topic", "mqttMessage", "qoS", "MIPSRequired",
             "requiredTime", "messageID"),
            "TaskState row (slot = user * max_sends + send_idx): topic, "
            "mips_req, t_create, t_at_broker (_phase_spawn)",
        ),
        MessageSchema(
            "MqttMsgPuback", "mqttMessages/MqttMsgPuback.msg:24-28",
            Direction.BROKER_TO_USER, ("qos", "messageID", "status"),
            "the ack-time columns: t_ack3 (v1 local accept), t_ack4_fwd "
            "(forwarded), t_ack4_queued, t_ack5 (assigned), t_ack6 "
            "(performed) — statuses 3/4/5/6 of the reference chain",
        ),
        MessageSchema(
            "MqttMsgPingRequest", "mqttMessages/MqttMsgPingRequest.msg",
            Direction.USER_TO_BROKER, (), "none — dead in the reference",
            live=False,
        ),
        MessageSchema(
            "MqttMsgPingResponse", "mqttMessages/MqttMsgPingResponse.msg",
            Direction.BROKER_TO_USER, (), "none — dead in the reference",
            live=False,
        ),
        MessageSchema(
            "MqttMsgBase", "mqttMessages/MqttMsgBase.msg",
            Direction.USER_TO_BROKER, ("messageType", "qos"),
            "abstract base — the Stage/ack-column encodings stand in for "
            "messageType",
        ),
        MessageSchema(
            "FognetMsgAdvertiseMIPS",
            "fognetMessages/FognetMsgAdvertiseMIPS.msg:22-26",
            Direction.FOG_TO_BROKER, ("MIPS", "computeBrokerID", "busyTime"),
            "BrokerView.adv_val_mips/adv_val_busy/adv_arrive_t (latest-wins "
            "in-flight slot); applied by _phase_adverts",
        ),
        MessageSchema(
            "FognetMsgTask", "fognetMessages/FognetMsgTask.msg:22-27",
            Direction.BROKER_TO_FOG,
            ("requestID", "requiredTime", "clientID", "requiredMIPS"),
            "TaskState.fog + t_at_fog set by _phase_broker; consumed by "
            "_phase_fog_arrivals / _phase_pool_arrivals",
        ),
        MessageSchema(
            "FognetMsgTaskAck", "fognetMessages/FognetMsgTaskAck.msg:17-20",
            Direction.FOG_TO_BROKER, ("requestID", "status"),
            "v1/v2 pool reject -> Stage.REJECTED (broker ignores it, so no "
            "client ack column)",
        ),
    ]
}


def live_schemas() -> Dict[str, MessageSchema]:
    return {k: v for k, v in SCHEMAS.items() if v.live}


def message_counts(spec: WorldSpec, final: WorldState) -> Dict[str, int]:
    """Per-type message totals reconstructed from a finished run.

    The authoritative wire-level accounting (what the reference's
    ``sentPk``/``rcvdPk`` scalars count per app) derived from the task
    table and control-plane state.
    """
    t = final.tasks
    fin = lambda col: int(np.isfinite(np.asarray(col)).sum())  # noqa: E731
    n_connect = int(np.asarray(final.users.connected).sum()) + spec.n_fogs
    n_sub = int(np.asarray(final.metrics.n_subscribed))
    pubacks = sum(
        fin(c) for c in (t.t_ack3, t.t_ack4_fwd, t.t_ack4_queued, t.t_ack5,
                         t.t_ack6)
    )
    return {
        "MqttMsgConnect": n_connect,
        "MqttMsgConnack": n_connect,
        "MqttMsgSubscribe": n_sub,
        "MqttMsgSuback": n_sub,
        "MqttMsgPublish": int(np.asarray(final.metrics.n_published)),
        "MqttMsgPuback": pubacks,
        "FognetMsgAdvertiseMIPS": int(np.asarray(final.metrics.n_adverts)),
        "FognetMsgTask": int(np.asarray(final.metrics.n_scheduled)),
        "FognetMsgTaskAck": int(np.asarray(final.metrics.n_rejected)),
        "MqttMsgPingRequest": 0,
        "MqttMsgPingResponse": 0,
    }
