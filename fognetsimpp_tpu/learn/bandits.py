"""Bandit arm state + index kernels for the in-loop learned schedulers.

Each fog node is an arm; the base broker is the learner.  The whole
learner lives in :class:`LearnState` — a small pytree carried inside
:class:`~fognetsimpp_tpu.state.WorldState` so the optimizer state is
scan-carry-resident (compiled once, donated with the rest of the world,
checkpointable, replicable under ``vmap``).  Decisions ride the existing
``ops/sched.py`` argmin machinery: UCB/discounted-UCB are one masked
argmax over a per-fog index vector (task-independent, like the
reference's own scan between two advertisement arrivals), EXP3 samples
per task from the softmax weights via the task-id-keyed uniform stream.

Batched-decision semantics: every arrival decided in one tick window
sees the SAME arm statistics snapshot — the exact analog of the broker
view staleness the reference already has (``BrokerBaseApp3.cc:123-136``)
— and the pick counts advance at the end of the window.  Rewards arrive
*later* (status-5/6 ack time) and are credited by
``core/engine._phase_learn_credit`` to the fog recorded at publish time.

References: UCB node selection under delayed feedback follows "Learn and
Pick Right Nodes to Offload" (arxiv 1804.08416); the discounted variant
is D-UCB (arxiv 0805.3415); EXP3 is Auer et al.'s adversarial bandit.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..spec import WorldSpec

# Score assigned to a never-picked arm: forces one exploratory pull per
# arm before any index comparison matters (hoisted, simlint R7).
_UNTRIED = np.float32(3.4e38)
# Floor for discounted counts: an abandoned arm's decayed count tends to
# zero, which would blow the confidence bonus to inf; the floor caps the
# bonus while still making stale arms maximally attractive to re-probe.
_DISC_FLOOR = np.float32(1e-3)
_NEG_BIG = np.float32(-3.4e38)


@struct.dataclass
class LearnState:
    """Carry-resident bandit learner (one per world / replica).

    The (F,)-sized arm statistics are always allocated (a few hundred
    bytes); the per-task decision-provenance columns are sized
    ``spec.learn_capacity`` — the full task capacity when the learn
    subsystem is active, zero rows otherwise.
    """

    pick_count: jax.Array  # (F,) f32 decisions routed to each fog
    reward_cnt: jax.Array  # (F,) f32 rewards credited so far
    reward_sum: jax.Array  # (F,) f32 sum of bounded rewards r in [0, 1]
    disc_cnt: jax.Array  # (F,) f32 gamma-discounted credit count (D-UCB)
    disc_sum: jax.Array  # (F,) f32 gamma-discounted reward sum
    logw: jax.Array  # (F,) f32 EXP3 log-weights (kept mean-centred)
    explore: jax.Array  # () f32 live exploration rate — TRACED, so a
    #   replica fan-out sweeps exploration rates under one compile
    lat_sum: jax.Array  # () f32 cumulative credited raw latency (s) —
    #   feeds the regret harness (learn/eval.py) without re-reading the
    #   task table per tick
    lat_cnt: jax.Array  # () f32 number of credited tasks
    # --- per-task decision provenance (learn_capacity rows) -----------
    pick_p: jax.Array  # (Tl,) f32 probability the picked arm had at
    #   decision time (1.0 for the deterministic UCB family); EXP3's
    #   importance weights divide by this at credit time
    credited: jax.Array  # (Tl,) i8 1 once the task's reward was credited


def init_learn_state(spec: WorldSpec) -> LearnState:
    """The t=0 learner for ``spec`` (inert zero-row provenance when the
    learn subsystem is off)."""
    F, Tl = spec.n_fogs, spec.learn_capacity
    f32 = jnp.float32
    return LearnState(
        pick_count=jnp.zeros((F,), f32),
        reward_cnt=jnp.zeros((F,), f32),
        reward_sum=jnp.zeros((F,), f32),
        disc_cnt=jnp.zeros((F,), f32),
        disc_sum=jnp.zeros((F,), f32),
        logw=jnp.zeros((F,), f32),
        explore=jnp.asarray(spec.learn_explore, f32),
        lat_sum=jnp.zeros((), f32),
        lat_cnt=jnp.zeros((), f32),
        pick_p=jnp.ones((Tl,), f32),
        credited=jnp.zeros((Tl,), jnp.int8),
    )


class BanditArms(NamedTuple):
    """The read-only arm view ``ops/sched.py`` scores against.

    A plain NamedTuple (not the full LearnState) so the scheduler kernel
    signature stays a flat list of arrays — the same convention as the
    broker-view columns it sits next to.
    """

    pick_count: jax.Array  # (F,) f32
    reward_cnt: jax.Array  # (F,) f32
    reward_sum: jax.Array  # (F,) f32
    disc_cnt: jax.Array  # (F,) f32
    disc_sum: jax.Array  # (F,) f32
    logw: jax.Array  # (F,) f32
    explore: jax.Array  # () f32 traced


def arms_view(learn: LearnState) -> BanditArms:
    """The scheduler-facing slice of a :class:`LearnState`."""
    return BanditArms(
        pick_count=learn.pick_count,
        reward_cnt=learn.reward_cnt,
        reward_sum=learn.reward_sum,
        disc_cnt=learn.disc_cnt,
        disc_sum=learn.disc_sum,
        logw=learn.logw,
        explore=learn.explore,
    )


def ucb_scores(arms: BanditArms, avail: jax.Array) -> jax.Array:
    """UCB1 index per arm (higher = better): mean + c*sqrt(ln t / n).

    ``n`` is the PLAY count (decisions), the mean is over CREDITED
    rewards only — under delayed feedback an arm with outstanding picks
    keeps its exploration bonus shrinking while its mean lags, which is
    exactly the optimism the delayed-ack setting needs (arxiv
    1804.08416 §III).  Never-picked available arms score ``_UNTRIED``.
    """
    n = arms.pick_count
    total = jnp.sum(jnp.where(avail, n, 0.0))
    mean = arms.reward_sum / jnp.maximum(arms.reward_cnt, 1.0)
    bonus = arms.explore * jnp.sqrt(jnp.log1p(total) / jnp.maximum(n, 1.0))
    return jnp.where(n > 0, mean + bonus, _UNTRIED)


def ducb_scores(arms: BanditArms, avail: jax.Array) -> jax.Array:
    """Discounted-UCB index (D-UCB): UCB over gamma-decayed statistics.

    The credit phase decays ``disc_cnt``/``disc_sum`` every tick, so an
    arm unvisited for a while sees its effective count shrink and its
    bonus regrow — the forgetting that tracks non-stationary fog load.
    """
    n = jnp.maximum(arms.disc_cnt, _DISC_FLOOR)
    total = jnp.sum(jnp.where(avail, n, 0.0))
    mean = arms.disc_sum / n
    bonus = arms.explore * jnp.sqrt(jnp.log1p(total) / n)
    return jnp.where(arms.pick_count > 0, mean + bonus, _UNTRIED)


def exp3_probs(
    logw: jax.Array, avail: jax.Array, gamma: jax.Array
) -> jax.Array:
    """EXP3 arm distribution over the available fogs.

    ``p = (1-gamma) * softmax(logw | avail) + gamma/|avail|`` — the
    uniform mixing floor bounds every importance weight by
    ``|avail|/gamma``, which (with the mean-centring applied at credit
    time) keeps the log-weights finite under adversarial rewards.
    Unavailable arms get exactly 0.  All-unavailable returns the zero
    vector; callers route those decisions to NO_RESOURCE like every
    other policy.
    """
    z = jnp.where(avail, logw, _NEG_BIG)
    z = z - jnp.max(z)
    w = jnp.where(avail, jnp.exp(z), 0.0)
    sm = w / jnp.maximum(jnp.sum(w), 1e-30)
    n_avail = jnp.sum(avail.astype(jnp.float32))
    mix = jnp.clip(gamma, 0.0, 1.0)
    p = (1.0 - mix) * sm + mix * avail.astype(jnp.float32) / jnp.maximum(
        n_avail, 1.0
    )
    # exact renormalisation over the available set (mix mass on
    # unavailable arms was dropped by the mask above)
    return p / jnp.maximum(jnp.sum(p), 1e-30)


def exp3_sample(p: jax.Array, u: jax.Array) -> jax.Array:
    """Inverse-CDF sample per task, guaranteed inside the support of p.

    ``u`` is the task-id-keyed uniform stream (``ops.sched.task_uniform``)
    — a pure function of the global task id, so the draw is independent
    of tick batching, exactly like Policy.RANDOM's stream.

    The target is ``clip(u, eps, 1) * cdf[-1]``, not ``u`` itself: a raw
    ``u == 0.0`` draw (jax uniforms are [0, 1)) or a float32 cumsum that
    tops out below 1 would otherwise let the first-True argmax land on a
    zero-probability (unavailable) arm or fall off the end.  With a
    strictly positive target bounded by the actual cumsum total, the
    first bin reaching it always carries p > 0: either it is bin 0 (then
    cdf[0] = p[0] >= target > 0) or its predecessor was below the target
    (so this bin added mass).  The eps floor redistributes only the
    bottom 1e-7 of mass.
    """
    cdf = jnp.cumsum(p)
    total = cdf[-1]
    target = jnp.clip(u, 1e-7, 1.0)[:, None] * total
    arm = jnp.argmax(cdf[None, :] >= target, axis=1).astype(jnp.int32)
    # degenerate all-zero p (no available arm): signal -1
    return jnp.where(total > 0, arm, -1)
