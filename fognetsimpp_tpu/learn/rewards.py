"""Delayed-reward credit assignment for the bandit schedulers.

The reward of an offload decision is the *observed* end-to-end latency:
nothing is credited when the broker picks a fog, only when the status-6
"performed" ack reaches the client (``core/engine._phase_learn_credit``
finds those arrivals each tick).  The raw reward is ``-latency``; the
bandit statistics store the bounded monotone transform

    r = exp(-latency / learn_reward_scale)  in (0, 1]

so UCB confidence bonuses have a fixed scale and EXP3's importance
weights stay bounded.  The raw latency is accumulated separately
(``lat_sum``/``lat_cnt``) for the regret harness, which reports regret
in latency units, not reward units.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .bandits import LearnState


def reward_from_latency(lat: jax.Array, scale) -> jax.Array:
    """Bounded reward in (0, 1] from an observed ack latency (seconds).

    ``scale`` may be a host float OR a traced f32 scalar (the promoted
    ``DynSpec.learn_reward_scale`` operand, ISSUE 13) — ``asarray``
    handles both with the same f32 value.
    """
    return jnp.exp(
        -jnp.maximum(lat, 0.0) / jnp.asarray(scale, jnp.float32)
    )


def _credit_counts_exact(k_rows: int) -> None:
    """Static guard for the integer-valued f32 credit counters (simlint
    R10, the ``engine._fused_mips_exact`` pattern).

    ``credit_batch`` counts credit rows by summing booleans in f32
    (``cnt_f``/``lat_cnt``): each per-tick increment is an exact integer
    — and therefore reduction-order/backend independent — only while the
    summed width stays below 2^24.  ``k_rows`` is the static credit
    window (a trace-time shape), so this raises at trace time, never on
    device.  The CUMULATIVE counters stay exact while total credits per
    fog stay below 2^24 (~16.7M acks); ``tools/hloaudit`` audit rule A4
    pins that end via ``spec.task_capacity`` on learn-active specs.
    """
    if k_rows >= 2 ** 24:
        raise ValueError(
            f"credit window of {k_rows} rows >= 2^24: the f32 credit "
            "count sums lose integer exactness — shrink the compaction "
            "window or switch the counters to int32"
        )


def penalize_counts(learn: LearnState, cnt_f: jax.Array) -> LearnState:
    """Zero-reward resolution of crashed picks (the ``chaos/`` hook).

    ``cnt_f`` is the per-fog count of decisions whose task was swept by
    a crash this tick (lost outright, bounced for re-offload, or
    retry-exhausted).  Each such PICK resolves exactly once, here, as
    the infimum of the bounded reward map (r = 0): the credit counters
    grow with zero reward mass, dragging the arm's empirical mean down
    — while ``reward_sum``/``disc_sum`` and the EXP3 log-weights are
    untouched because a zero reward contributes zero importance-
    weighted gain (EXP3's native treatment of a zero-reward round).
    The observed-latency accumulators (``lat_sum``/``lat_cnt``) are
    deliberately NOT touched: they feed the regret harness's
    mean-credited-latency curve, which is defined over tasks that
    actually acked.

    No discount decay here — the D-UCB clock is time and lives in
    :func:`credit_batch`, which runs once per tick regardless.
    """
    return learn.replace(
        reward_cnt=learn.reward_cnt + cnt_f,
        disc_cnt=learn.disc_cnt + cnt_f,
    )


def credit_batch(
    learn: LearnState,
    valid: jax.Array,  # (K,) bool — rows of this tick's credit window
    memb: jax.Array,  # (F, K) bool — row f marks credits bound for fog f
    lat: jax.Array,  # (K,) f32 observed latency (t_ack6 - t_create)
    pick_p_g: jax.Array,  # (K,) f32 decision-time pick probability
    n_fogs: int,
    discount,  # host float or traced f32 (DynSpec.learn_discount)
    reward_scale,  # host float or traced f32 (DynSpec.learn_reward_scale)
) -> LearnState:
    """Fold one tick's credit window into the arm statistics.

    All per-fog reductions are membership selects over the (F, K)
    matrix — the same vmap-collapse-safe shape every engine phase uses
    instead of scatter-adds.  The per-task ``credited`` flags are the
    caller's to set (it owns the compaction indices).
    """
    f32 = jnp.float32
    _credit_counts_exact(int(valid.shape[0]))
    r01 = jnp.where(valid, reward_from_latency(lat, reward_scale), 0.0)
    cnt_f = jnp.sum(memb, axis=1, dtype=f32)  # (F,)
    sum_f = jnp.sum(jnp.where(memb, r01[None, :], 0.0), axis=1)

    # EXP3 importance-weighted gain: eta * r / p(pick), eta = gamma/F.
    # pick_p is 1.0 for the UCB family, so the update is a bounded
    # spectator there; its floor mirrors exp3_probs' mixing floor.
    eta = learn.explore / f32(max(n_fogs, 1))
    gain = r01 / jnp.maximum(pick_p_g, 1e-6)
    gain_f = eta * jnp.sum(jnp.where(memb, gain[None, :], 0.0), axis=1)
    logw = learn.logw + gain_f
    # mean-centring is a softmax invariant; it pins the weight drift so
    # adversarial reward sequences cannot walk the weights to +/-inf
    logw = logw - jnp.mean(logw)

    g = jnp.asarray(discount, f32)
    return learn.replace(
        reward_cnt=learn.reward_cnt + cnt_f,
        reward_sum=learn.reward_sum + sum_f,
        disc_cnt=learn.disc_cnt * g + cnt_f,
        disc_sum=learn.disc_sum * g + sum_f,
        logw=logw,
        lat_sum=learn.lat_sum + jnp.sum(jnp.where(valid, lat, 0.0)),
        lat_cnt=learn.lat_cnt + jnp.sum(valid, dtype=f32),
    )
