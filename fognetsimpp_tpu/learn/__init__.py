"""learn/ — online bandit schedulers inside the jitted tick loop.

The subsystem has three layers:

  * :mod:`.bandits` — the :class:`LearnState` pytree (carried in
    ``WorldState``) plus the UCB1 / discounted-UCB index kernels and the
    EXP3 distribution/sampling helpers that ``ops/sched.py`` dispatches
    as ``Policy.UCB`` / ``Policy.DUCB`` / ``Policy.EXP3``;
  * :mod:`.rewards` — delayed-reward credit assignment: reward =
    ``-latency`` observed at status-5/6 ack time, credited to the fog
    picked at publish time (``core/engine._phase_learn_credit``);
  * :mod:`.eval` — the regret harness: replays one world under each
    learned policy vs. the static per-world oracle and emits
    ``learnRegret`` / ``learnPicks`` curves through the recorder.

``.eval`` imports the engine, so it is NOT imported here (the engine's
scheduler imports this package); reach it explicitly::

    from fognetsimpp_tpu.learn import eval as learn_eval
"""
from .bandits import (  # noqa: F401
    BanditArms,
    LearnState,
    arms_view,
    ducb_scores,
    exp3_probs,
    exp3_sample,
    init_learn_state,
    ucb_scores,
)
from .rewards import credit_batch, reward_from_latency  # noqa: F401
