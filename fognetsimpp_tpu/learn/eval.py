"""Regret harness: replay one world under learned vs. static policies.

The bandit literature's regret — cumulative reward shortfall against the
best fixed arm — becomes, in this simulator, the *latency* shortfall
against the best static scheduling policy for the same world: every
policy replays the identical scenario (same seed, same topology, same
publish schedule up to policy-dependent feedback), the static runs
establish the per-world oracle, and the learned run's per-tick credited
latency accumulators (``LearnState.lat_sum``/``lat_cnt``, recorded in
the tick series) yield a regret-vs-tick curve without re-reading the
task table.

Curves are emitted through the recorder as the ``learnRegret`` (per-tick
regret, seconds) and ``learnPicks`` (per-tick cumulative per-fog pick
counts) signal vectors next to the reference-derived signals in the
``.vec.npz``.

Host-side module: nothing here traces; it drives :func:`engine.run`.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..spec import LEARNED_POLICIES, Policy

#: Static policies the oracle is taken over (the argmin family sans
#: ENERGY_AWARE, which only differs in energy-enabled worlds).
DEFAULT_STATICS: Tuple[Policy, ...] = (
    Policy.MIN_BUSY,
    Policy.ROUND_ROBIN,
    Policy.MIN_LATENCY,
    Policy.RANDOM,
)


def mean_task_latency_s(final) -> float:
    """Mean publish → status-6 ack latency (s) over completed tasks."""
    from ..runtime.signals import extract_signals

    v = extract_signals(final)["task_time"]
    return float(v.mean() / 1e3) if v.size else float("nan")


def run_policy(build, policy: int, record_series: bool = False, **kw):
    """Replay the world under ``policy``; returns (spec, final, series)."""
    from ..core.engine import run

    if record_series:
        kw = dict(kw, record_tick_series=True)
    spec, state, net, bounds = build(policy=int(policy), **kw)
    final, series = run(spec, state, net, bounds)
    return spec, final, series


def static_oracle(
    build, statics: Sequence[Policy] = DEFAULT_STATICS, **kw
) -> Tuple[int, Dict[int, float]]:
    """Mean latency of each static policy on this world; returns
    (best_policy_id, {policy_id: mean_latency_s}).  NaN means (a policy
    that completed nothing) lose against any finite mean."""
    means: Dict[int, float] = {}
    for pol in statics:
        _, final, _ = run_policy(build, int(pol), **kw)
        means[int(pol)] = mean_task_latency_s(final)
    finite = {p: m for p, m in means.items() if np.isfinite(m)}
    if not finite:
        raise ValueError(
            "no static policy completed any task on this world — the "
            "regret baseline is undefined (grow the horizon or lower "
            "the load)"
        )
    best = min(finite, key=finite.get)
    return best, means


def regret_curves(series, oracle_mean_s: float) -> Dict[str, np.ndarray]:
    """Per-tick regret + pick curves from a learned run's tick series.

    ``learnRegret[i]`` = (mean credited latency up to tick i) − (oracle
    mean latency); ticks before the first credit carry 0 regret (no
    evidence either way yet).
    """
    lat_sum = np.asarray(series["learn_lat_sum"], np.float64)
    lat_cnt = np.asarray(series["learn_lat_cnt"], np.float64)
    mean = lat_sum / np.maximum(lat_cnt, 1.0)
    regret = np.where(lat_cnt > 0, mean - oracle_mean_s, 0.0)
    return {
        "learnRegret": regret.astype(np.float64),
        "learnPicks": np.asarray(series["learn_picks"], np.float64),
    }


def evaluate(
    build,
    learned: Sequence[Policy] = LEARNED_POLICIES,
    statics: Sequence[Policy] = DEFAULT_STATICS,
    outdir: Optional[str] = None,
    run_id_prefix: str = "learn",
    **kw,
) -> Dict:
    """The full harness: oracle + one recorded run per learned policy.

    Returns a summary dict::

        {"oracle": {"policy": id, "mean_latency_s": m,
                    "statics": {id: mean}},
         "learned": {"ucb": {"mean_latency_s": ..., "final_regret_s":
                     ..., "picks": [...], "paths": {...}?}, ...}}

    With ``outdir`` each learned run is persisted through the recorder
    (``<prefix>-<name>.sca.json`` / ``.vec.npz``) with the
    ``learnRegret``/``learnPicks`` curves as extra signal vectors.
    """
    best, static_means = static_oracle(build, statics=statics, **kw)
    oracle_mean = static_means[best]
    out: Dict = {
        "oracle": {
            "policy": int(best),
            "policy_name": Policy(best).name.lower(),
            "mean_latency_s": oracle_mean,
            "statics": static_means,
        },
        "learned": {},
    }
    for pol in learned:
        name = Policy(int(pol)).name.lower()
        spec, final, series = run_policy(
            build, int(pol), record_series=True, **kw
        )
        curves = regret_curves(series, oracle_mean)
        entry = {
            "mean_latency_s": mean_task_latency_s(final),
            "final_regret_s": float(curves["learnRegret"][-1]),
            "picks": np.asarray(final.learn.pick_count).tolist(),
            "credited": float(np.asarray(final.learn.lat_cnt)),
        }
        if outdir is not None:
            from ..runtime.recorder import record_run

            entry["paths"] = record_run(
                outdir, spec, final, series=series,
                run_id=f"{run_id_prefix}-{name}",
                attrs={"policy": name, "oracle": out["oracle"]},
                extra_vectors=curves,
            )
        out["learned"][name] = entry
    return out
