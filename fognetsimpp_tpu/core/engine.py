"""The batched tick engine: one `lax.scan` step advances the whole world.

This is the TPU-native replacement for OMNeT++'s sequential event loop
(SURVEY.md §7 "guiding translation").  Per tick ``[t0, t1)`` the engine runs
a fixed phase pipeline — mobility → association → advertisement delivery →
publish spawning → broker scheduling → fog completions → fog arrivals →
energy/lifecycle — each phase a masked, batched array update over the task
table and per-node state.

Event-time fidelity: all task timestamps are *exact* (sums of link delays and
service times, chained through ``busy_until``), never tick-quantised.  The
tick size only bounds how stale a decision's *view* can be (which fog a task
goes to, whether a server looked idle), exactly the staleness the reference
already has through in-flight advertisement packets.  With
``dt <= min link delay`` the decision ordering matches the event-driven
execution (SURVEY.md §7 "hard parts" item 1).

The hot path per reference trace §3.2:
  client publish (``mqttApp2.cc:353-409``) → broker schedule
  (``BrokerBaseApp3.cc:231-319``) → fog assign/queue
  (``ComputeBrokerApp3.cc:269-320``) → fog release
  (``ComputeBrokerApp3.cc:224-256``) → ack relay to client
  (``BrokerBaseApp3.cc:164-198`` + ``mqttApp2.cc:252-296``).
"""
from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..net.mobility import MobilityBounds, step_mobility
from ..net.energy import step_energy
from ..net.topology import LinkCache, NetParams, associate, pair_delay
from ..ops.queues import NO_TASK, batched_enqueue, batched_pop, plan_arrivals
from ..ops.sched import schedule_batch
from ..spec import Policy, Stage, WorldSpec
from ..state import WorldState


def _fog_node_idx(spec: WorldSpec, fog: jax.Array) -> jax.Array:
    """Map fog slot -> global node index (layout: users | fogs | broker)."""
    return spec.n_users + jnp.clip(fog, 0, spec.n_fogs - 1)


def _svc_time(spec: WorldSpec, mips_req: jax.Array, fog_mips: jax.Array) -> jax.Array:
    """Fog-side service time: requiredMIPS / MIPS (ComputeBrokerApp3.cc:276)."""
    return mips_req / jnp.maximum(fog_mips, 1e-9)


# ----------------------------------------------------------------------
# phases
# ----------------------------------------------------------------------

def _phase_adverts(state: WorldState, t1: jax.Array) -> WorldState:
    """Deliver in-flight MIPS advertisements whose arrival time has passed.

    Mirrors the broker's AdvertiseMIPS branch updating ``brokers[j]``
    (``BrokerBaseApp3.cc:123-136``) — latest-wins overwrite.
    """
    b = state.broker
    arrived = b.adv_arrive_t <= t1
    broker = b.replace(
        view_mips=jnp.where(arrived, b.adv_val_mips, b.view_mips),
        view_busy=jnp.where(arrived, b.adv_val_busy, b.view_busy),
        adv_arrive_t=jnp.where(arrived, jnp.inf, b.adv_arrive_t),
    )
    return state.replace(broker=broker)


def _phase_spawn(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    t0: jax.Array, t1: jax.Array,
) -> WorldState:
    """Users whose send timer fired publish one task (mqttApp2.cc:353-409).

    Task slot ``u * max_sends + send_count[u]`` is claimed; MIPSRequired ~
    U[200, 900] via the kernel PRNG (fixing the reference's wall-clock
    ``rand()`` nondeterminism, SURVEY.md App. B item 5).  The publish's
    arrival at the broker is stamped immediately:
    ``t_at_broker = t_create + delay(user, broker)``.
    """
    U, T, S = spec.n_users, spec.task_capacity, spec.max_sends_per_user
    users, tasks = state.users, state.tasks
    uidx = jnp.arange(U, dtype=jnp.int32)
    alive_u = state.nodes.alive[uidx]

    due = alive_u & users.connected & (users.next_send < t1) & (users.send_count < S)
    t_create = jnp.maximum(users.next_send, t0)  # missed-while-dead resume

    key, k_mips, k_jit = jax.random.split(state.key, 3)
    if spec.fixed_mips_required is not None:
        mips_req = jnp.full((U,), float(spec.fixed_mips_required), jnp.float32)
    else:
        mips_req = jax.random.randint(
            k_mips, (U,), spec.mips_required_min, spec.mips_required_max + 1
        ).astype(jnp.float32)

    broker_node = jnp.full((U,), spec.broker_index, jnp.int32)
    d_ub = pair_delay(net, cache, uidx, broker_node)  # (U,)
    slot = jnp.where(due, uidx * S + users.send_count, T)

    def scat(col, val):
        return col.at[slot].set(jnp.where(due, val, col[jnp.clip(slot, 0, T - 1)]), mode="drop")

    tasks = tasks.replace(
        stage=tasks.stage.at[slot].set(jnp.int8(int(Stage.PUB_INFLIGHT)), mode="drop"),
        mips_req=scat(tasks.mips_req, mips_req),
        t_create=scat(tasks.t_create, t_create),
        t_at_broker=scat(tasks.t_at_broker, t_create + d_ub),
    )
    interval = users.send_interval
    if spec.send_interval_jitter > 0:
        interval = interval * jax.random.uniform(
            k_jit, (U,), minval=1.0 - spec.send_interval_jitter,
            maxval=1.0 + spec.send_interval_jitter,
        )
    users = users.replace(
        next_send=jnp.where(due, t_create + interval, users.next_send),
        send_count=jnp.where(due, users.send_count + 1, users.send_count),
    )
    metrics = state.metrics.replace(
        n_published=state.metrics.n_published + jnp.sum(due.astype(jnp.int32))
    )
    return state.replace(users=users, tasks=tasks, metrics=metrics, key=key)


def _phase_broker(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    t1: jax.Array,
) -> WorldState:
    """Broker decides every publish that has arrived (BrokerBaseApp3.cc:231-319).

    All arrivals in the window see the same view snapshot — faithful, since
    the reference's view is only refreshed by advertisement arrivals, never
    by its own assignments.  Emits the forwarded status-4 ack
    (``BrokerBaseApp3.cc:146-150``) whose client-side arrival becomes the
    latencyH1 signal (``mqttApp2.cc:269-277``).
    """
    tasks, b = state.tasks, state.broker
    T = spec.task_capacity
    mask = (tasks.stage == int(Stage.PUB_INFLIGHT)) & (tasks.t_at_broker <= t1)

    any_fog = jnp.any(b.registered)
    key, k_sched = jax.random.split(state.key)
    fog_nodes = jnp.arange(spec.n_fogs, dtype=jnp.int32) + spec.n_users
    broker_node_f = jnp.full((spec.n_fogs,), spec.broker_index, jnp.int32)
    rtt_bf = 2.0 * pair_delay(net, cache, broker_node_f, fog_nodes)
    fog_alive = state.nodes.alive[fog_nodes]
    fog_efrac = state.nodes.energy[fog_nodes] / jnp.maximum(
        state.nodes.energy_capacity[fog_nodes], 1e-12
    )

    choice, rr_new = schedule_batch(
        spec.policy, mask, tasks.mips_req, b.view_busy, b.view_mips,
        b.registered, fog_alive, fog_efrac, rtt_bf, b.rr_next, k_sched,
        spec.bug_compat.mips0_divisor,
    )

    fog_node = _fog_node_idx(spec, choice)
    broker_node = jnp.full((T,), spec.broker_index, jnp.int32)
    user_node = tasks.user
    d_bf = pair_delay(net, cache, broker_node, fog_node)
    d_bu = pair_delay(net, cache, broker_node, user_node)

    sched = mask & any_fog
    no_res = mask & ~any_fog  # "no compute resource available" (:306-319)
    tasks = tasks.replace(
        stage=jnp.where(
            sched, jnp.int8(int(Stage.TASK_INFLIGHT)),
            jnp.where(no_res, jnp.int8(int(Stage.NO_RESOURCE)), tasks.stage),
        ),
        fog=jnp.where(sched, choice, tasks.fog),
        t_at_fog=jnp.where(sched, tasks.t_at_broker + d_bf, tasks.t_at_fog),
        t_ack4_fwd=jnp.where(mask, tasks.t_at_broker + d_bu, tasks.t_ack4_fwd),
    )
    metrics = state.metrics.replace(
        n_scheduled=state.metrics.n_scheduled + jnp.sum(sched.astype(jnp.int32)),
        n_no_resource=state.metrics.n_no_resource + jnp.sum(no_res.astype(jnp.int32)),
    )
    return state.replace(
        tasks=tasks, broker=b.replace(rr_next=rr_new), metrics=metrics, key=key
    )


def _phase_completions(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    t1: jax.Array,
) -> WorldState:
    """Fog nodes whose in-service task finished release it (releaseResource,
    ``ComputeBrokerApp3.cc:224-256``): status-6 ack relayed to the client
    (taskTime signal), busyTime decremented by the task's service time, FIFO
    head promoted (queueTime signal), next release scheduled exactly at
    ``busy_until + svc``, and a fresh advertisement put in flight.
    """
    tasks, fogs, b = state.tasks, state.fogs, state.broker
    F = spec.n_fogs
    fog_nodes = jnp.arange(F, dtype=jnp.int32) + spec.n_users
    fog_alive = state.nodes.alive[fog_nodes]

    comp = (fogs.current_task != NO_TASK) & (fogs.busy_until <= t1) & fog_alive
    done_task = jnp.where(comp, fogs.current_task, T_SENTINEL := spec.task_capacity)
    t_done = fogs.busy_until  # exact completion times per fog

    # ack6 path: fog -> broker -> client (relay, BrokerBaseApp3.cc:164-175)
    user_of = tasks.user[jnp.clip(done_task, 0, spec.task_capacity - 1)]
    broker_node_f = jnp.full((F,), spec.broker_index, jnp.int32)
    d_fb = pair_delay(net, cache, fog_nodes, broker_node_f)
    d_bu = pair_delay(net, cache, broker_node_f, user_of)
    t_ack6 = t_done + d_fb + d_bu

    svc_done = _svc_time(
        spec, tasks.mips_req[jnp.clip(done_task, 0, spec.task_capacity - 1)], fogs.mips
    )

    tasks = tasks.replace(
        stage=tasks.stage.at[done_task].set(jnp.int8(int(Stage.DONE)), mode="drop"),
        t_complete=tasks.t_complete.at[done_task].set(
            jnp.where(comp, t_done, 0), mode="drop"
        ),
        t_ack6=tasks.t_ack6.at[done_task].set(jnp.where(comp, t_ack6, 0), mode="drop"),
    )
    # busyTime -= currentTask.requiredTime (== its tskTime, set at accept:
    # ComputeBrokerApp3.cc:296,232)
    busy_time = jnp.where(comp, fogs.busy_time - svc_done, fogs.busy_time)

    # promote FIFO head (ComputeBrokerApp3.cc:236-252)
    head, q_head, q_len = batched_pop(fogs.queue, fogs.q_head, fogs.q_len, comp)
    promoted = comp & (head != NO_TASK)
    head_c = jnp.clip(head, 0, spec.task_capacity - 1)
    svc_new = _svc_time(spec, tasks.mips_req[head_c], fogs.mips)
    tasks = tasks.replace(
        stage=tasks.stage.at[jnp.where(promoted, head, spec.task_capacity)].set(
            jnp.int8(int(Stage.RUNNING)), mode="drop"
        ),
        t_service_start=tasks.t_service_start.at[
            jnp.where(promoted, head, spec.task_capacity)
        ].set(jnp.where(comp, t_done, 0), mode="drop"),
        queue_time_ms=tasks.queue_time_ms.at[
            jnp.where(promoted, head, spec.task_capacity)
        ].set(
            jnp.where(promoted, (t_done - tasks.t_q_enter[head_c]) * 1e3, 0),
            mode="drop",
        ),
    )
    fogs = fogs.replace(
        busy_time=busy_time,
        current_task=jnp.where(comp, jnp.where(promoted, head, NO_TASK), fogs.current_task),
        busy_until=jnp.where(
            comp, jnp.where(promoted, t_done + svc_new, jnp.inf), fogs.busy_until
        ),
        queue=fogs.queue,
        q_head=q_head,
        q_len=q_len,
    )
    # advertisement in flight: advertiseMIPS() at end of releaseResource
    # (ComputeBrokerApp3.cc:254); latest-wins single slot per fog.
    b = b.replace(
        adv_val_mips=jnp.where(comp, fogs.mips, b.adv_val_mips),
        adv_val_busy=jnp.where(comp, busy_time, b.adv_val_busy),
        adv_arrive_t=jnp.where(comp, t_done + d_fb, b.adv_arrive_t),
    )
    metrics = state.metrics.replace(
        n_completed=state.metrics.n_completed + jnp.sum(comp.astype(jnp.int32))
    )
    return state.replace(tasks=tasks, fogs=fogs, broker=b, metrics=metrics)


def _phase_fog_arrivals(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    t1: jax.Array,
) -> WorldState:
    """Tasks reaching their fog node are assigned or queued
    (``ComputeBrokerApp3.cc:269-320``).

    busyTime += tskTime for *every* arrival (accepted or queued, ``:279``);
    an idle fog takes the earliest arrival (status-5 "assigned" ack → the
    client's latency signal); the rest enter the FIFO in arrival order
    (status-4 "queued" ack → a second latencyH1 sample at the client).
    """
    tasks, fogs = state.tasks, state.fogs
    T, F = spec.task_capacity, spec.n_fogs
    fog_nodes_all = jnp.arange(F, dtype=jnp.int32) + spec.n_users
    fog_alive = state.nodes.alive[fog_nodes_all]

    arr = (tasks.stage == int(Stage.TASK_INFLIGHT)) & (tasks.t_at_fog <= t1)
    dead_dst = arr & ~fog_alive[jnp.clip(tasks.fog, 0, F - 1)]
    arr = arr & ~dead_dst  # packets to a dead node are lost

    svc = _svc_time(spec, tasks.mips_req, fogs.mips[jnp.clip(tasks.fog, 0, F - 1)])
    add_busy = jnp.zeros((F + 1,), jnp.float32).at[
        jnp.where(arr, tasks.fog, F)
    ].add(jnp.where(arr, svc, 0.0), mode="drop")[:F]

    idle = fogs.current_task == NO_TASK
    plan = plan_arrivals(arr, tasks.fog, tasks.t_at_fog, F, idle)

    # --- immediate assignment on idle fogs ---
    a_task = plan.assign_task  # (F,) task id or NO_TASK
    assigned = a_task != NO_TASK
    a_c = jnp.clip(a_task, 0, T - 1)
    t_start = tasks.t_at_fog[a_c]
    svc_a = _svc_time(spec, tasks.mips_req[a_c], fogs.mips)
    broker_node_f = jnp.full((F,), spec.broker_index, jnp.int32)
    d_fb = pair_delay(net, cache, fog_nodes_all, broker_node_f)
    d_bu_a = pair_delay(net, cache, broker_node_f, tasks.user[a_c])
    t_ack5 = t_start + d_fb + d_bu_a

    scat_a = jnp.where(assigned, a_task, T)
    tasks = tasks.replace(
        stage=tasks.stage.at[scat_a].set(jnp.int8(int(Stage.RUNNING)), mode="drop"),
        t_service_start=tasks.t_service_start.at[scat_a].set(
            jnp.where(assigned, t_start, 0), mode="drop"
        ),
        t_ack5=tasks.t_ack5.at[scat_a].set(jnp.where(assigned, t_ack5, 0), mode="drop"),
    )
    fogs = fogs.replace(
        current_task=jnp.where(assigned, a_task, fogs.current_task),
        busy_until=jnp.where(assigned, t_start + svc_a, fogs.busy_until),
        busy_time=fogs.busy_time + add_busy,
    )

    # --- queue the rest (rank shifts by 1 where the head got assigned) ---
    got_head = assigned[jnp.clip(tasks.fog, 0, F - 1)] & idle[jnp.clip(tasks.fog, 0, F - 1)]
    eff_rank = jnp.where(arr, plan.rank - got_head.astype(jnp.int32), -1)
    to_queue = arr & (eff_rank >= 0) & (
        jnp.arange(T, dtype=jnp.int32) != a_task[jnp.clip(tasks.fog, 0, F - 1)]
    )
    queue, q_len, enq_ok, dropped = batched_enqueue(
        fogs.queue, fogs.q_head, fogs.q_len, to_queue, tasks.fog, eff_rank
    )
    d_bu_q = pair_delay(
        net, cache, jnp.full((T,), spec.broker_index, jnp.int32), tasks.user
    )
    d_fb_q = d_fb[jnp.clip(tasks.fog, 0, F - 1)]
    tasks = tasks.replace(
        stage=jnp.where(
            enq_ok, jnp.int8(int(Stage.QUEUED)),
            jnp.where(
                to_queue & ~enq_ok, jnp.int8(int(Stage.DROPPED)),
                jnp.where(dead_dst, jnp.int8(int(Stage.DROPPED)), tasks.stage),
            ),
        ),
        t_q_enter=jnp.where(enq_ok, tasks.t_at_fog, tasks.t_q_enter),
        t_ack4_queued=jnp.where(
            enq_ok, tasks.t_at_fog + d_fb_q + d_bu_q, tasks.t_ack4_queued
        ),
    )
    fogs = fogs.replace(queue=queue, q_len=q_len, q_drops=fogs.q_drops + dropped)
    metrics = state.metrics.replace(
        n_dropped=state.metrics.n_dropped
        + jnp.sum((to_queue & ~enq_ok).astype(jnp.int32))
        + jnp.sum(dead_dst.astype(jnp.int32))
    )
    return state.replace(tasks=tasks, fogs=fogs, metrics=metrics)


def _phase_periodic_adverts(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    t0: jax.Array, t1: jax.Array,
) -> WorldState:
    """v1/v2 fogs re-advertise every ``adv_interval`` (ComputeBrokerApp2.cc:219).

    Fired on the tick containing each multiple of the interval; the
    advertisement carries the fog's *current* (MIPS, busyTime) and lands at
    the broker after the fog->broker delay.
    """
    F = spec.n_fogs
    fog_nodes = jnp.arange(F, dtype=jnp.int32) + spec.n_users
    alive = state.nodes.alive[fog_nodes]
    k0 = jnp.floor(t0 / spec.adv_interval)
    k1 = jnp.floor(t1 / spec.adv_interval)
    fire = (k1 > k0) & alive
    t_fire = (k0 + 1.0) * spec.adv_interval
    d_fb = pair_delay(
        net, cache, fog_nodes, jnp.full((F,), spec.broker_index, jnp.int32)
    )
    b = state.broker
    b = b.replace(
        adv_val_mips=jnp.where(fire, state.fogs.mips, b.adv_val_mips),
        adv_val_busy=jnp.where(fire, state.fogs.busy_time, b.adv_val_busy),
        adv_arrive_t=jnp.where(fire, t_fire + d_fb, b.adv_arrive_t),
    )
    return state.replace(broker=b)


def prime_initial_advertisements(
    spec: WorldSpec, state: WorldState, net: NetParams, t_adv: float = 0.01
) -> WorldState:
    """Put each fog's first advertisement in flight at t=t_adv.

    Mirrors the connack handler scheduling ADVERTISEMIPS at +0.01 s
    (``ComputeBrokerApp3.cc:261-267``); until it lands the broker's view has
    MIPS=0 (registration default, ``BrokerBaseApp3.cc:104``) and the
    scheduler's estimates are +inf, exactly like the reference's first
    decisions.  Scenario builders call this after placing nodes.
    """
    cache = associate(net, state.nodes.pos, state.nodes.alive)
    F = spec.n_fogs
    fog_nodes = jnp.arange(F, dtype=jnp.int32) + spec.n_users
    d_fb = pair_delay(
        net, cache, fog_nodes, jnp.full((F,), spec.broker_index, jnp.int32)
    )
    b = state.broker.replace(
        adv_val_mips=state.fogs.mips,
        adv_val_busy=state.fogs.busy_time,
        adv_arrive_t=jnp.asarray(t_adv, jnp.float32) + d_fb,
    )
    return state.replace(broker=b)


# ----------------------------------------------------------------------
# the tick
# ----------------------------------------------------------------------

def make_step(
    spec: WorldSpec,
) -> Callable[[WorldState, NetParams, MobilityBounds], WorldState]:
    """Build the jit-compiled single-tick transition for ``spec``."""
    spec.validate()

    def step(state: WorldState, net: NetParams, bounds: MobilityBounds) -> WorldState:
        t0 = state.tick.astype(jnp.float32) * spec.dt
        t1 = (state.tick + 1).astype(jnp.float32) * spec.dt

        # 1. mobility (positions at end-of-tick; delays in this tick use them)
        pos, vel = step_mobility(state.nodes, bounds, t1, spec.dt)
        nodes = state.nodes.replace(pos=pos, vel=vel)
        state = state.replace(nodes=nodes)

        # 2. connectivity / association snapshot for this tick
        cache = associate(net, pos, nodes.alive)

        # 3-7. protocol phases
        state = _phase_adverts(state, t1)
        if spec.adv_periodic:
            state = _phase_periodic_adverts(spec, state, net, cache, t0, t1)
        state = _phase_spawn(spec, state, net, cache, t0, t1)
        state = _phase_broker(spec, state, net, cache, t1)
        if spec.n_fogs > 0:  # a fog-less world exercises only the
            # "no compute resource available" branch (BrokerBaseApp3.cc:306)
            for _ in range(spec.completions_per_tick):
                state = _phase_completions(spec, state, net, cache, t1)
            state = _phase_fog_arrivals(spec, state, net, cache, t1)

        # 8. energy + lifecycle
        if spec.energy_enabled:
            N = spec.n_nodes
            fog_nodes = jnp.arange(spec.n_fogs, dtype=jnp.int32) + spec.n_users
            computing = jnp.zeros((N,), bool).at[fog_nodes].set(
                state.fogs.current_task != NO_TASK
            )
            tx = jnp.zeros((N,), jnp.int32)
            rx = jnp.zeros((N,), jnp.int32)
            energy, alive = step_energy(
                spec, state.nodes.energy, state.nodes.energy_capacity,
                state.nodes.has_energy, state.nodes.alive, t1, tx, rx, computing,
            )
            state = state.replace(
                nodes=state.nodes.replace(energy=energy, alive=alive)
            )

        return state.replace(
            t=t1, tick=state.tick + 1
        )

    return step


def run(
    spec: WorldSpec,
    state: WorldState,
    net: NetParams,
    bounds: Optional[MobilityBounds] = None,
    n_ticks: Optional[int] = None,
) -> Tuple[WorldState, Optional[dict]]:
    """Run ``n_ticks`` (default: spec horizon) under one `lax.scan`.

    Returns (final_state, series) where ``series`` holds per-tick vectors
    (queue lengths, busy times, alive count) when
    ``spec.record_tick_series`` — the ``.vec``-file analog (SURVEY.md §5
    tracing).
    """
    if bounds is None:
        from ..net.mobility import default_bounds

        bounds = default_bounds()
    n = spec.n_ticks if n_ticks is None else n_ticks
    step = make_step(spec)

    def body(carry, _):
        s = step(carry, net, bounds)
        if spec.record_tick_series:
            out = {
                "t": s.t,
                "busy_time": s.fogs.busy_time,
                "q_len": s.fogs.q_len,
                "n_alive": jnp.sum(s.nodes.alive.astype(jnp.int32)),
                "energy_mean": jnp.mean(s.nodes.energy),
            }
        else:
            out = None
        return s, out

    final, series = jax.lax.scan(body, state, None, length=n)
    return final, series


@functools.partial(jax.jit, static_argnums=0)
def run_jit(
    spec: WorldSpec, state: WorldState, net: NetParams, bounds: MobilityBounds
) -> WorldState:
    """Whole-run jit entry (spec static): scan over the full horizon."""
    final, _ = run(spec, state, net, bounds)
    return final
