"""The batched tick engine: one `lax.scan` step advances the whole world.

This is the TPU-native replacement for OMNeT++'s sequential event loop
(SURVEY.md §7 "guiding translation").  Per tick ``[t0, t1)`` the engine runs
a fixed phase pipeline — mobility → association → connect/registration →
advertisement delivery → publish spawning → broker scheduling (+ topic
fan-out) → fog completions → fog arrivals → energy/lifecycle — each phase a
masked, batched array update over the task table and per-node state.

Event-time fidelity: all task timestamps are *exact* (sums of link delays and
service times, chained through ``busy_until``/``free_since``), never
tick-quantised.  The tick size only bounds how stale a decision's *view* can
be (which fog a task goes to, whether a server looked idle), exactly the
staleness the reference already has through in-flight advertisement packets.
With ``dt <= min link delay`` the decision ordering matches the event-driven
execution (SURVEY.md §7 "hard parts" item 1).

Compaction: the two hot phases (broker scheduling, fog arrivals) gather the
masked task rows into a fixed ``spec.window``-sized buffer before sorting /
scoring, so their cost is O(K log K + K·F) instead of O(T log T + T·F).
When more than K tasks mature in one tick the excess rows simply keep their
in-flight stage and are picked up next tick (conservation holds; ordering
degrades only under that overflow, and the selection is by task id, not
arrival time — size K at the expected per-tick arrival rate plus slack).

The hot path per reference trace §3.2:
  client publish (``mqttApp2.cc:353-409``) → broker schedule
  (``BrokerBaseApp3.cc:231-319``) → fog assign/queue
  (``ComputeBrokerApp3.cc:269-320``) → fog release
  (``ComputeBrokerApp3.cc:224-256``) → ack relay to client
  (``BrokerBaseApp3.cc:164-198`` + ``mqttApp2.cc:252-296``).

v1/v2 semantics (POOL fog model, LOCAL_FIRST/MAX_MIPS policies) follow
``BrokerBaseApp.cc:160-260`` and ``ComputeBrokerApp2.cc:246-320``; see
:class:`~fognetsimpp_tpu.spec.FogModel` and the phase docstrings.
"""
from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..chaos.faults import rtt_factor, step_lifecycle
from ..dynspec import (
    DynSpec,
    dyn_of,
    promote_default,
    registry_note,
    split_spec,
)
from ..learn.bandits import arms_view, exp3_probs
from ..learn.rewards import credit_batch, penalize_counts
from ..net.mobility import MobilityBounds, step_mobility
from ..net.energy import step_energy
from ..net.topology import LinkCache, NetParams, associate
from ..ops.queues import (
    NO_TASK,
    batched_enqueue,
    batched_pop,
    enqueue_scatter,
    plan_arrivals,
    row_lexmin,
)
from ..ops.sched import scalar_winner, schedule_batch, task_uniform
from ..hier.federation import hier_reject_reason
from ..spec import (
    STATIC_MAC_ERR,
    ChaosMode,
    FogModel,
    HierPolicy,
    Policy,
    Stage,
    WorldSpec,
)
from ..state import WorldState
from ..telemetry.health import accumulate_latency
from ..telemetry.metrics import PHASE_INDEX, accumulate_tick, tick_activity

# Stage tags as hoisted int8 scalar constants (simlint R7): the hot phases
# previously rebuilt `jnp.int8(int(Stage.X))` per use (~15x per trace in
# this module).  numpy scalars carry the same int8 dtype through every jnp
# op (selects, fills, scatters, compares) with zero per-trace constant
# construction and no device-array creation at import time.
_ST_UNUSED = np.int8(int(Stage.UNUSED))
_ST_PUB_INFLIGHT = np.int8(int(Stage.PUB_INFLIGHT))
_ST_TASK_INFLIGHT = np.int8(int(Stage.TASK_INFLIGHT))
_ST_QUEUED = np.int8(int(Stage.QUEUED))
_ST_RUNNING = np.int8(int(Stage.RUNNING))
_ST_DONE = np.int8(int(Stage.DONE))
_ST_NO_RESOURCE = np.int8(int(Stage.NO_RESOURCE))
_ST_DROPPED = np.int8(int(Stage.DROPPED))
_ST_LOCAL_RUN = np.int8(int(Stage.LOCAL_RUN))
_ST_REJECTED = np.int8(int(Stage.REJECTED))
_ST_LOST = np.int8(int(Stage.LOST))
_ST_HOP_EXHAUSTED = np.int8(int(Stage.HOP_EXHAUSTED))


# The assume_static x Bianchi-keyed-MAC conflict message: defined ONCE
# in spec.py (WorldSpec.validate() raises it too) so the entry points
# can never drift apart (ADVICE r5: the entries must agree).
_STATIC_MAC_ERR = STATIC_MAC_ERR


class TpCtx(NamedTuple):
    """Shard context threaded through the TP-aware phase entry points.

    Built by :mod:`fognetsimpp_tpu.parallel.taskshard` inside its
    ``shard_map`` body; ``None`` everywhere else (the single-device
    engine never constructs one).  The per-user/per-task phases run on
    the LOCAL world view (a spec with ``n_users = U/n_shards`` and
    locally sliced user/task arrays), and this context carries what a
    shard-local view cannot: the global population (PRNG draws must
    keep the reference's full-width shapes to stay bit-exact), the
    shard's row offsets, and the full broker-delay vector for
    global-id gathers in the fog-side phases.
    """

    axis_name: str  # mesh axis the task table is sharded over
    n_shards: int  # static shard count
    shard: jax.Array  # () i32 — this shard's index (lax.axis_index)
    n_users_global: int  # U of the UNsharded world
    u_off: jax.Array  # () i32 — first global user owned by this shard
    t_off: jax.Array  # () i32 — first global task row owned
    d2b_full: jax.Array  # (N_global,) f32 — full broker-delay vector


def _tp_user_draw(tp: Optional[TpCtx], draw, n_local: int, *trailing):
    """Run a per-user PRNG draw at the REFERENCE width, slice the shard.

    Under TP each shard holds ``U/n`` users, but a shard-local draw of
    shape ``(U_loc, ...)`` would consume a different threefry counter
    layout than the reference's ``(U, ...)`` draw — so every shard
    draws the full-width array (cheap: O(U) bits once per tick) and
    dynamic-slices its own block.  Bit-exact by construction: the
    local lanes ARE the reference lanes.
    """
    if tp is None:
        return draw((n_local,) + tuple(trailing))
    full = draw((tp.n_users_global,) + tuple(trailing))
    return jax.lax.dynamic_slice_in_dim(full, tp.u_off, n_local, axis=0)


def tp_reject_reason(spec: WorldSpec) -> Optional[str]:
    """Why ``spec`` cannot run on the shard_map'd TP tick (None = it can).

    The TP tick covers the dense-broker production family — the same
    static family as the fused front-end (:func:`_broker_dense_ok` over
    FIFO fogs with the two-stage arrival front-end) — windowed or not
    (a windowed spec runs the distributed K-window selection over the
    exchange ring), on a static topology.  Everything else keeps the
    GSPMD fallback
    (:func:`fognetsimpp_tpu.parallel.taskshard.run_node_sharded`
    dispatches) or the single-device engine.

    Every clause leads with a stable machine-parseable ID (``[TP-*]``):
    the featmat tier (``tools/featmat``) extracts the composition matrix
    from these clauses, the CLI one-liners key on the IDs, and
    ``tests/test_cli_errors.py`` asserts IDs rather than prose — the
    prose can be reworded freely, the bracketed ID cannot.
    """
    if spec.n_fogs <= 0:
        return "[TP-NOFOGS] TP tick needs fog nodes (n_fogs >= 1)"
    if spec.chaos:
        # checked FIRST among the feature gates: a chaos spec also
        # fails the assume_static hoist below (chaos mutates liveness),
        # and the actionable reason is the subsystem, not the symptom
        return (
            "[TP-CHAOS] TP tick does not carry the chaos fault-injection "
            "subsystem yet (run chaos worlds on single-device run/run_jit/"
            "run_chunked)"
        )
    if spec.hier_active:
        # same subsystem-first ordering as chaos: ONE message source
        # (hier/federation.hier_reject_reason) shared with the fleet gate
        return hier_reject_reason(spec, "TP")
    # journeys (spec.journey_active) run INSIDE the sharded tick since
    # ISSUE 19: shard-local rings over the owned row block, scalar drop
    # census in the end-of-tick psum (parallel/taskshard.py)
    if spec.fog_model != int(FogModel.FIFO):
        return (
            "[TP-POOL] TP tick covers FIFO fogs only (POOL pools are "
            "sequential)"
        )
    if not _broker_dense_ok(spec):
        return (
            "[TP-POLICY] TP tick covers the dense-broker policy family "
            "(MIN_BUSY/MIN_LATENCY/ENERGY_AWARE with bug_compat."
            "mips0_divisor, or MAX_MIPS); sequential-pool and learned "
            "policies keep the single-device / GSPMD paths"
        )
    if not spec.two_stage_arrivals:
        return "[TP-ARRIVALS] TP tick needs the two-stage arrival front-end"
    if not spec.assume_static:
        return (
            "[TP-DYNTOPO] TP tick hoists one association/delay cache for "
            "the whole run: needs assume_static"
        )
    if spec.energy_enabled:
        return (
            "[TP-ENERGY] TP tick does not carry the energy/lifecycle "
            "model yet"
        )
    if spec.wired_queue_enabled:
        return "[TP-WIRED] TP tick does not carry DropTail backpressure yet"
    if spec.learn_active:
        return "[TP-LEARN] TP tick does not carry bandit learner state yet"
    if spec.record_tick_series:
        return (
            "[TP-SERIES] TP tick records no per-tick series (record via "
            "summary)"
        )
    return None


def tp_ok(spec: WorldSpec) -> bool:
    """Static gate for the shard_map'd TP tick (see tp_reject_reason)."""
    return tp_reject_reason(spec) is None


class TickBuf(NamedTuple):
    """Per-tick message-count accumulators feeding the energy model.

    The radio tx/rx energy of INET's StateBasedEpEnergyConsumer
    (``testing/wireless5.ini:156-157``) becomes per-message joule costs
    multiplied by these counts (ADVICE r1: previously hardwired zeros).
    Counts are booked in the tick where the send/receive is *decided*; the
    at-most-one-tick skew vs the exact event time is far below the energy
    model's own granularity.

    Segmented by node role ([users | fogs | broker] of the node layout)
    so the per-phase updates are elementwise adds and scalar adds that XLA
    fuses into the surrounding kernels — a flat (N,) buffer would force a
    ~25 us scatter kernel per phase per counter (profiled r3).  The energy
    phase reassembles the flat view once per tick.
    """

    tx_u: jax.Array  # (U,) i32
    rx_u: jax.Array  # (U,) i32
    tx_f: jax.Array  # (F,) i32
    rx_f: jax.Array  # (F,) i32
    tx_b: jax.Array  # () i32 — the single base broker
    rx_b: jax.Array  # () i32


def _per_fog(
    mask: jax.Array, fog: jax.Array, n_fogs: int
) -> jax.Array:
    """(F, K) membership matrix: row f marks masked tasks bound for fog f.

    One comparison kernel replaces per-counter scatter-adds (a TPU scatter
    costs ~6 ns/element serialized + ~25 us fixed; the (F, K) reduces over
    this matrix vectorise on the VPU instead).
    """
    F = n_fogs
    return (fog[None, :] == jnp.arange(F, dtype=jnp.int32)[:, None]) & mask[None, :]


def _fog_node_idx(spec: WorldSpec, fog: jax.Array) -> jax.Array:
    """Map fog slot -> global node index (layout: users | fogs | broker)."""
    return spec.n_users + jnp.clip(fog, 0, spec.n_fogs - 1)


def _svc_time(spec: WorldSpec, mips_req: jax.Array, fog_mips: jax.Array) -> jax.Array:
    """Fog-side service time: requiredMIPS / MIPS (ComputeBrokerApp3.cc:276)."""
    return mips_req / jnp.maximum(fog_mips, 1e-9)


def _compact_lane_width(T: int) -> int:
    """Power-of-two in-block lane width minimising B + C (B = T/C)."""
    return min((128, 256, 512, 1024), key=lambda c: -(-T // c) + c)


def _compact(
    mask: jax.Array, K: int, T: int, rot: Optional[jax.Array] = None
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Gather the indices of up to K set bits of ``mask`` (length T).

    Returns (idx, idx_clipped, valid): ``idx`` is (K,) int32 padded with T,
    ``valid`` marks real entries.  Scatters back with ``.at[idx]`` +
    ``mode='drop'``; gathers with ``idx_clipped``.

    ``rot`` (traced scalar): start the selection scan at block
    ``rot % n_blocks``, wrapping — under sustained window overflow a
    fixed scan origin would systematically decide low-id (= low-user-
    index) tasks first and starve the rest (VERDICT r3 weak item 3); the
    engine rotates the origin every tick so deferral spreads evenly.
    The rotation permutes only the (B,)-sized BLOCK prefix order (one
    430-element roll at the bench shape), never the T-sized data — a
    whole-mask `jnp.roll` with a traced shift lowers to a per-element
    gather under `vmap` and collapsed replica fan-out (r4 measured:
    config3 893k -> 222k decisions/s before this formulation).  With
    ``rot=None`` (or K == T, where overflow is impossible) selection is
    plain ascending id order.

    Implemented as a two-level prefix sum + dense first-True argmax.
    ``jnp.nonzero(size=K)`` lowers to a serialized scan that profiled at
    ~2 ms/tick per call at T=240k (the hottest op in the engine), and
    binary searches lower to sequential while-loops whose per-iteration
    overhead (~30 us) dominates; the (K,B) / (K,C) one-shot comparisons
    here are single fused kernels instead.

    Block size: the dominant intermediates are (K, B) and (K, C) with
    B = T/C, minimised at C ~ sqrt(T) (r5; the fixed C=1024 of r1-r4
    streamed a 19 MB (K, C) gather at the bench shape where sqrt(T)=663
    would stream 12 MB).  Rounded to a power of two in [128, 1024] so
    the lane dimension stays tiled.
    """
    C = _compact_lane_width(T)
    B = -(-T // C)
    m2 = jnp.zeros((B * C,), jnp.int32).at[:T].set(mask.astype(jnp.int32))
    wcs = jnp.cumsum(m2.reshape(B, C), axis=1)  # (B, C) within-block prefix
    bsum = wcs[:, -1]  # (B,)
    k = jnp.arange(K, dtype=jnp.int32)
    if rot is not None:
        # (block rotation) x (in-block rotation): block order starts at
        # rot % B and every block's internal scan starts at a decorrelated
        # column offset — over ticks each slot's priority sweeps the whole
        # range, so no user is systematically favoured even when K is far
        # smaller than a block
        rot_b = (rot % B).astype(jnp.int32)
        c0 = ((rot.astype(jnp.uint32) * jnp.uint32(7919)) % jnp.uint32(C)
              ).astype(jnp.int32)
        bsum_sel = jnp.roll(bsum, -rot_b)  # (B,) only — cheap under vmap
    else:
        rot_b = None
        bsum_sel = bsum
    bcs = jnp.cumsum(bsum_sel)  # (B,) block-offset prefix (selection order)
    # block of the k-th set bit: first b with bcs[b] >= k+1 (argmax = first
    # True over bool), then its within-block rank and position the same way
    blk = jnp.argmax(bcs[None, :] >= (k + 1)[:, None], axis=1).astype(jnp.int32)
    base = bcs[blk] - bsum_sel[blk]  # set bits before this block
    rank = k + 1 - base  # 1-based rank within the block
    if rot_b is not None:
        blk = (blk + rot_b) % B  # back to the original block id
    rows = wcs[blk]  # (K, C)
    if rot_b is None:
        inb = jnp.argmax(rows >= rank[:, None], axis=1).astype(jnp.int32)
    else:
        # in-block scan order c0..C-1, 0..c0-1 via index arithmetic on the
        # SAME gathered rows (no T-sized roll): prefix count in that order
        # at original column j, then first satisfying j by rotated position
        cols = jnp.arange(C, dtype=jnp.int32)[None, :]  # (1, C)
        off = jnp.where(
            c0 > 0, rows[:, jnp.maximum(c0 - 1, 0)], 0
        )[:, None]  # set bits before column c0
        tail = cols >= c0
        prefix_rot = jnp.where(
            tail, rows - off, rows + (rows[:, -1:] - off)
        )
        pos_rot = jnp.where(tail, cols - c0, cols + (C - c0))
        ok = prefix_rot >= rank[:, None]
        inb_pos = jnp.min(jnp.where(ok, pos_rot, C), axis=1)
        inb = ((inb_pos + c0) % C).astype(jnp.int32)
    idx = blk * C + inb
    valid = k < bcs[-1]
    idx = jnp.where(valid, jnp.minimum(idx, T - 1), T)
    return idx, jnp.minimum(idx, T - 1), valid



def _rot_and_defer(
    spec: WorldSpec, state: WorldState, mask: jax.Array, K: int
) -> Tuple[Optional[jax.Array], WorldState]:
    """Per-tick compaction-origin rotation + deferred-backlog accounting.

    Returns (rot, state'): ``rot`` is the tick-keyed scan origin for
    :func:`_compact` (None when K == T — overflow impossible), and the
    state's ``n_deferred`` gauge grows by the matured rows this window
    cannot seat (they stay in flight and are decided in later ticks).
    """
    T = spec.task_capacity
    if K >= T:
        return None, state
    rot = (
        (state.tick.astype(jnp.uint32) * jnp.uint32(2654435761))
        % jnp.uint32(T)
    ).astype(jnp.int32)
    n_set = jnp.sum(mask.astype(jnp.int32))
    deferred = jnp.maximum(n_set - K, 0)
    state = state.replace(
        metrics=state.metrics.replace(
            n_deferred=state.metrics.n_deferred + deferred
        )
    )
    return rot, state



def offered_rate_vector(
    spec: WorldSpec, alive_u, users, t0, dyn: Optional[DynSpec] = None
) -> jax.Array:
    """Per-node offered frame rate (frames/s) for the Bianchi contention
    keying: a user's publish rate while it is actively publishing, zero
    otherwise.  SHARED between the engine's tick (below) and the native
    DES's delay-table chain (native/bridge.py) — the two must stay
    bit-identical or wireless parity silently breaks."""
    dv = dyn if dyn is not None else dyn_of(spec)
    publishing = (
        alive_u
        & users.connected
        & users.publisher
        & (users.send_count < spec.max_sends_per_user)
        & jnp.isfinite(users.next_send)
    )
    if spec.send_stop_time != float("inf"):
        publishing = publishing & (t0 < dv.send_stop_time)
    return jnp.concatenate(
        [
            jnp.where(publishing, 1.0 / users.send_interval, 0.0).astype(
                jnp.float32
            ),
            jnp.zeros((spec.n_nodes - spec.n_users,), jnp.float32),
        ]
    )


# ----------------------------------------------------------------------
# phases
# ----------------------------------------------------------------------

def _phase_connect(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t0: jax.Array, t1: jax.Array,
    views: Optional[dict] = None,
):
    """MQTT connect handshake: Connect → broker registration → Connack.

    Users: ``processStart`` sends MqttMsgConnect at the app start time
    (``mqttApp2.cc:165-233``); the broker registers the client and replies
    Connack (``BrokerBaseApp3.cc:109-120``); on Connack the client issues its
    first publish and its subscriptions (``processConSubAck``,
    ``mqttApp2.cc:319-351``).  Fog registrations (``isBroker`` connects,
    ``BrokerBaseApp3.cc:102-107``) were stamped ahead of time by
    :func:`prime_initial_advertisements`; here they just mature.
    """
    users, b = state.users, state.broker
    U = spec.n_users
    # (a) fog registrations mature (brokers.push_back at Connect arrival)
    b = b.replace(registered=b.register_t <= t1)

    # (b) users whose start fired send Connect; stamp the Connack round-trip
    pending = (
        state.nodes.alive[:U]
        & ~users.connected
        & jnp.isinf(users.connack_at)
        & (users.start_t < t1)
    )
    d_ub = cache.d2b[:U]
    t_send = jnp.maximum(users.start_t, t0)
    connack_at = jnp.where(pending, t_send + 2.0 * d_ub, users.connack_at)

    # (c) Connacks that arrived: connected; first publish fires immediately
    #     (processConSubAck publishes then subscribes, mqttApp2.cc:319-351)
    acked = ~users.connected & (connack_at <= t1)
    n_subs = jnp.sum(users.sub_mask.astype(jnp.int32), axis=1)  # (U,)
    users = users.replace(
        connected=users.connected | acked,
        connack_at=connack_at,
        next_send=jnp.where(acked, connack_at, users.next_send),
    )
    # message accounting: Connect + per-topic Subscribe from the user;
    # Connack + per-topic Suback from the broker
    acked_subs = jnp.where(acked, n_subs, 0)
    up_msgs = pending.astype(jnp.int32) + acked_subs
    down_msgs = acked.astype(jnp.int32) * (1 + n_subs)
    buf = buf._replace(
        tx_u=buf.tx_u + up_msgs,
        rx_u=buf.rx_u + down_msgs,
    )
    metrics = state.metrics
    defer_counts = views is not None and views.get(
        "defer_host_counts", False
    )
    if defer_counts:
        # fused telemetry-off tick: the four scalar sums join the
        # flush's one merged U-wide reduction (exact integer rows)
        views["def_u"] = list(views.get("def_u", ()))
        views["def_u"] += [
            (down_msgs, (("tx_b", 1),)),
            (up_msgs, (("rx_b", 1),)),
            (acked.astype(jnp.int32), (("n_connected", 1),)),
            (acked_subs, (("n_subscribed", 1),)),
        ]
    else:
        # one stacked reduction for all the scalar sums of this phase
        sums = jnp.sum(
            jnp.stack(
                [down_msgs, up_msgs, acked.astype(jnp.int32), acked_subs]
            ),
            axis=1,
        )
        buf = buf._replace(
            tx_b=buf.tx_b + sums[0],
            rx_b=buf.rx_b + sums[1],
        )
        metrics = metrics.replace(
            n_connected=metrics.n_connected + sums[2],
            n_subscribed=metrics.n_subscribed + sums[3],
        )
    state = state.replace(users=users, broker=b, metrics=metrics)
    if views is not None:
        return state, buf, views
    return state, buf


def _phase_adverts(
    state: WorldState, t1: jax.Array,
    buf: Optional[TickBuf] = None, views: Optional[dict] = None,
):
    """Deliver in-flight MIPS advertisements whose arrival time has passed.

    Mirrors the broker's AdvertiseMIPS branch updating ``brokers[j]``
    (``BrokerBaseApp3.cc:123-136``) — latest-wins overwrite.  In fused
    telemetry-off mode (``views`` + ``buf`` passed) the advert counter
    joins the flush's merged F-wide reduction.
    """
    b = state.broker
    arrived = b.adv_arrive_t <= t1
    broker = b.replace(
        view_mips=jnp.where(arrived, b.adv_val_mips, b.view_mips),
        view_busy=jnp.where(arrived, b.adv_val_busy, b.view_busy),
        adv_arrive_t=jnp.where(arrived, jnp.inf, b.adv_arrive_t),
    )
    metrics = state.metrics
    defer_counts = views is not None and views.get(
        "defer_host_counts", False
    )
    if defer_counts:
        views = dict(views)
        views["def_f"] = list(views.get("def_f", ()))
        views["def_f"].append((arrived, (("n_adverts", 1),)))
    else:
        metrics = metrics.replace(
            n_adverts=metrics.n_adverts + jnp.sum(arrived.astype(jnp.int32))
        )
    state = state.replace(broker=broker, metrics=metrics)
    if views is not None:
        return state, buf, views
    return state


def _phase_spawn(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t0: jax.Array, t1: jax.Array,
    views: Optional[dict] = None, tp: Optional[TpCtx] = None,
    dyn: Optional[DynSpec] = None,
):
    """Users whose send timer fired publish one task (mqttApp2.cc:353-409).

    Task slot ``u * max_sends + send_count[u]`` is claimed; MIPSRequired ~
    U[200, 900] via the kernel PRNG (fixing the reference's wall-clock
    ``rand()`` nondeterminism, SURVEY.md App. B item 5).  The publish's
    arrival at the broker is stamped immediately:
    ``t_at_broker = t_create + delay(user, broker)``.

    Written as *elementwise* updates over the ``(U, S)`` view of the task
    table: the claimed slot is where the send-index axis equals the user's
    ``send_count``, so the whole phase is masked vector selects — per-user
    values broadcast along the send axis — with zero scatter kernels
    (a TPU scatter serializes at ~6-10 ns/element; these selects run at
    HBM bandwidth, profiled r3).
    """
    U, T, S = spec.n_users, spec.task_capacity, spec.max_sends_per_user
    users, tasks = state.users, state.tasks
    alive_u = state.nodes.alive[:U]
    dv = dyn if dyn is not None else dyn_of(spec)

    due = (
        alive_u
        & users.connected
        & users.publisher
        & (users.next_send < t1)
        & (users.send_count < S)
    )
    t_create = jnp.maximum(users.next_send, t0)  # missed-while-dead resume
    if spec.send_stop_time != float("inf"):
        # stopTime: the app cancels its send timer at stopTime and a
        # restarted node reschedules sends only before it (mqttApp2.cc:
        # 191-210); gate the actual creation time so a node resuming
        # after stopTime cannot publish
        due = due & (t_create < dv.send_stop_time)

    if spec.wired_queue_enabled:
        key, k_mips, k_jit, k_loss, k_dtail = jax.random.split(state.key, 5)
    else:
        key, k_mips, k_jit, k_loss = jax.random.split(state.key, 4)
    if spec.fixed_mips_required is not None:
        mips_req = jnp.full((U,), float(spec.fixed_mips_required), jnp.float32)
    else:
        mips_req = _tp_user_draw(
            tp,
            lambda s: jax.random.randint(
                k_mips, s, spec.mips_required_min,
                spec.mips_required_max + 1,
            ),
            U,
        ).astype(jnp.float32)

    d_ub = cache.d2b[:U]  # (U,)

    t_arrive = t_create + d_ub
    if spec.link_up_s > 0:
        # ARP/association warm-up: a publish that would arrive before the
        # link is up instead arrives at its drain slot (spec.link_up_s).
        # Two-phase drain when link_burst_n > 0: the first burst pours at
        # link_drain_s gaps, the rest of the backlog at link_drain2_s
        # (committed demo trace, General-0.vec vector 1093)
        k = users.send_count.astype(jnp.float32)
        if spec.link_burst_n > 0:
            nb = float(spec.link_burst_n - 1)
            pos = jnp.where(
                k <= nb,
                k * dv.link_drain_s,
                dv.link_burst_base + (k - nb) * dv.link_drain2_s,
            )
        else:
            pos = k * dv.link_drain_s
        drained = dv.link_up_s + pos
        if spec.link_buffer_frames > 0:
            # mechanistic pre-link-up buffer (see spec.link_buffer_frames):
            # creations while the link is down either sit in the bounded
            # pending queue (send index < capacity -> drain schedule) or
            # overflow deterministically; post-link-up sends go direct
            pre = t_create < dv.link_up_s
            buffered = pre & (users.send_count < spec.link_buffer_frames)
            t_arrive = jnp.where(buffered, drained, t_arrive)
            warm_lost = pre & ~buffered
        else:
            t_arrive = jnp.where(t_arrive < dv.link_up_s, drained, t_arrive)
            buffered = None
            warm_lost = None
    else:
        buffered = None
        warm_lost = None
    # wireless uplink loss (MAC retry exhaustion): the publish is sent and
    # costs tx energy, but never reaches the broker.  Two components,
    # independently combined: the calibrated residual probability
    # (spec.uplink_loss_prob: fading/mobility effects fitted to the
    # committed trace) and the load-dependent Bianchi retry-exhaustion
    # term from the sender's cell occupancy (cache.mac_loss_p, r4) —
    # loss now RISES with offered load (VERDICT r3 item 3).  Packets
    # buffered during the link warm-up deliver reliably once the link is
    # up (the committed demo trace loses only steady-state packets).
    lost = jnp.zeros((U,), bool)
    has_mac = net.mac_loss_tab.shape[0] > 0
    if spec.uplink_loss_prob > 0 or has_mac:
        p_eff = jnp.full((U,), dv.uplink_loss_prob, jnp.float32)
        if has_mac:
            p_eff = 1.0 - (1.0 - p_eff) * (1.0 - cache.mac_loss_p[:U])
        lost = (
            (_tp_user_draw(
                tp, lambda s: jax.random.uniform(k_loss, s), U
            ) < p_eff)
            & net.is_wireless[:U]
        )
        if buffered is not None:
            # mechanistic buffer: frames the pending queue kept deliver
            # reliably at their drain slot (code-review r5 fix: the
            # legacy arrival-time gate left late-created buffered frames
            # in the random-loss draw)
            lost = lost & ~buffered
        elif spec.link_up_s > 0:
            lost = lost & (t_create + d_ub >= dv.link_up_s)
    if spec.wired_queue_enabled:
        # DropTail: a publish entering a full egress queue (its own link
        # or the broker's) is tail-dropped with last tick's overflow
        # fraction.  Acks/adverts are delayed, not dropped (batched
        # approximation; drops are counted in metrics.n_link_drops).
        p_u = state.nodes.link_drop_p[:U]
        p_b = state.nodes.link_drop_p[spec.broker_index]
        p_eff = 1.0 - (1.0 - p_u) * (1.0 - p_b)
        lost = lost | (
            _tp_user_draw(tp, lambda s: jax.random.uniform(k_dtail, s), U)
            < p_eff
        )
    if warm_lost is not None:
        lost = lost | (warm_lost & net.is_wireless[:U])
    stage_new = jnp.where(
        lost, _ST_LOST, _ST_PUB_INFLIGHT
    )
    # claimed slot per user: send-index k == send_count, as an (U, S) mask
    sel = due[:, None] & (
        jnp.arange(S, dtype=jnp.int32)[None, :] == users.send_count[:, None]
    )

    if views is not None:
        # fused front-end: same selects, written into the threaded
        # (U, S) register views instead of the task table
        views = dict(views)

        def put2(col2, val_u):
            return jnp.where(sel, val_u[:, None], col2)

        views["stage2"] = put2(views["stage2"], stage_new)
        views["mips2"] = put2(views["mips2"], mips_req)
        views["t_create2"] = put2(views["t_create2"], t_create)
        views["t_at_broker2"] = put2(
            views["t_at_broker2"], jnp.where(lost, jnp.inf, t_arrive)
        )
    else:

        def put(col, val_u):
            return jnp.where(sel, val_u[:, None], col.reshape(U, S)).reshape(T)

        tasks = tasks.replace(
            stage=put(tasks.stage, stage_new),
            mips_req=put(tasks.mips_req, mips_req),
            t_create=put(tasks.t_create, t_create),
            t_at_broker=put(
                tasks.t_at_broker, jnp.where(lost, jnp.inf, t_arrive)
            ),
        )
    interval = users.send_interval
    if spec.send_interval_jitter > 0:
        interval = interval * _tp_user_draw(
            tp,
            lambda s: jax.random.uniform(
                k_jit, s, minval=1.0 - spec.send_interval_jitter,
                maxval=1.0 + spec.send_interval_jitter,
            ),
            U,
        )
    users = users.replace(
        next_send=jnp.where(due, t_create + interval, users.next_send),
        send_count=jnp.where(due, users.send_count + 1, users.send_count),
    )
    metrics = state.metrics
    defer_counts = views is not None and views.get(
        "defer_host_counts", False
    )
    if defer_counts:
        views["def_u"] = list(views.get("def_u", ()))
        views["def_u"] += [
            (due.astype(jnp.int32), (("n_published", 1),)),
            ((due & lost).astype(jnp.int32), (("n_lost", 1),)),
        ]
    else:
        sums = jnp.sum(
            jnp.stack(
                [due.astype(jnp.int32), (due & lost).astype(jnp.int32)]
            ),
            axis=1,
        )
        metrics = metrics.replace(
            n_published=metrics.n_published + sums[0],
            n_lost=metrics.n_lost + sums[1],
        )
    buf = buf._replace(tx_u=buf.tx_u + due.astype(jnp.int32))
    state = state.replace(users=users, tasks=tasks, metrics=metrics, key=key)
    if views is not None:
        return state, buf, views
    return state, buf


def _phase_inject(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t0: jax.Array, t1: jax.Array,
    batch: Optional[dict] = None,
):
    """Chunk-boundary arrival injection: the digital twin's input phase
    (twin/ingest, ISSUE 17).

    Lands one fixed-width batch of EXTERNAL publish requests — ``batch``
    maps ``user`` (i32 ``(spec.ingest_batch,)``, -1 = padding row) and
    ``mips`` (f32, MIPSRequired per request) — into the task table
    through the same slot contract as :func:`_phase_spawn`: row *j*
    targeting user *u* claims slot ``u * S + send_count[u] + rank``
    where ``rank`` counts earlier batch rows for the same user, so a
    batch may carry several requests per user and the claimed slots
    stay distinct.  The publish is stamped at the CURRENT sim time
    (``state.t``) and arrives at the broker through the established
    K-window contract at ``state.t + delay(user, broker)``.

    Deliberately draw-free: no PRNG key is split and no loss draw is
    taken (the request already reached the service's front door; the
    simulated user is a stand-in for an external client, so uplink loss
    and user tx energy are not re-modelled), which is what makes a
    recorded arrival log replay bit-exactly — injection is a pure
    function of (state, batch).  Rows for dead/disconnected users or
    users whose ``S`` send slots are exhausted are REJECTED, not
    queued: the count comes back in ``extra["n_rejected"]`` and the
    host-side queue (twin/ingest.IngestQueue) owns the drop policy.

    This phase never runs inside the compiled tick — it is applied
    between chunks by :func:`inject_arrivals` (run_chunked's ``inject``
    hook), so the tick program stays host-transfer-free (hloaudit's
    ``tick_ingest`` variant pins exactly that).
    """
    U, T, S = spec.n_users, spec.task_capacity, spec.max_sends_per_user
    B = spec.ingest_batch
    users, tasks = state.users, state.tasks
    alive_u = state.nodes.alive[:U]
    if batch is None:  # contract trace / gate-off: all-padding batch
        uid = jnp.full((B,), -1, jnp.int32)
        mips = jnp.zeros((B,), jnp.float32)
    else:
        uid = batch["user"].astype(jnp.int32)
        mips = batch["mips"].astype(jnp.float32)
    ok0 = (uid >= 0) & (uid < U)
    ui = jnp.clip(uid, 0, max(U - 1, 0))
    # rank of row j among earlier same-user rows: the (B, B) triangle is
    # tiny (B = spec.ingest_batch), so this stays a vector compare, not
    # a serializing scatter
    same = (uid[:, None] == uid[None, :]) & ok0[:, None] & ok0[None, :]
    rank = jnp.sum(jnp.tril(same, k=-1), axis=1).astype(jnp.int32)
    slot_k = users.send_count[ui] + rank
    ok = ok0 & alive_u[ui] & users.connected[ui] & (slot_k < S)

    t_now = state.t
    t_arrive = t_now + cache.d2b[:U][ui]
    # out-of-bounds sentinel slot + mode="drop": rejected rows write
    # nothing (the established .at[] drop idiom, no branching)
    slot = jnp.where(ok, ui * S + jnp.clip(slot_k, 0, S - 1), T)
    tasks = tasks.replace(
        stage=tasks.stage.at[slot].set(_ST_PUB_INFLIGHT, mode="drop"),
        mips_req=tasks.mips_req.at[slot].set(mips, mode="drop"),
        t_create=tasks.t_create.at[slot].set(
            jnp.broadcast_to(t_now, (B,)), mode="drop"
        ),
        t_at_broker=tasks.t_at_broker.at[slot].set(t_arrive, mode="drop"),
    )
    usafe = jnp.where(ok, ui, U)
    users = users.replace(
        send_count=users.send_count.at[usafe].add(1, mode="drop"),
    )
    n_inj = jnp.sum(ok.astype(jnp.int32))
    metrics = state.metrics.replace(
        n_published=state.metrics.n_published + n_inj
    )
    cnt_u = jnp.zeros((U,), jnp.int32).at[usafe].add(1, mode="drop")
    buf = buf._replace(tx_u=buf.tx_u + cnt_u)
    state = state.replace(users=users, tasks=tasks, metrics=metrics)
    extra = {
        "n_injected": n_inj,
        "n_rejected": jnp.sum((ok0 & ~ok).astype(jnp.int32)),
    }
    return state, buf, extra


# simlint: disable=R6 -- the boundary injector must NOT donate: the serve
# callback path retains chunk-boundary states (flight recorder /
# checkpoint streaming), and donating here would delete those buffers
# behind the recorder's back
@functools.partial(jax.jit, static_argnums=0)
def _inject_jit(
    spec: WorldSpec, state: WorldState, net: NetParams,
    user: jax.Array, mips: jax.Array,
):
    cache = associate(
        net, state.nodes.pos, state.nodes.alive, broker=spec.broker_index
    )
    zero_u = jnp.zeros((spec.n_users,), jnp.int32)
    buf = TickBuf(
        tx_u=zero_u, rx_u=zero_u,
        tx_f=jnp.zeros((spec.n_fogs,), jnp.int32),
        rx_f=jnp.zeros((spec.n_fogs,), jnp.int32),
        tx_b=jnp.zeros((), jnp.int32), rx_b=jnp.zeros((), jnp.int32),
    )
    state, _buf, extra = _phase_inject(
        spec, state, net, cache, buf,
        jnp.float32(0.0), jnp.float32(0.0),
        batch={"user": user, "mips": mips},
    )
    return state, extra["n_injected"], extra["n_rejected"]


def inject_arrivals(
    spec: WorldSpec, state: WorldState, net: NetParams,
    user, mips,
) -> Tuple[WorldState, int, int]:
    """Host entry for the chunk-boundary injector (twin/ingest drain).

    Pads ``user``/``mips`` (any length <= ``spec.ingest_batch``) to the
    fixed batch width and applies :func:`_phase_inject` under one
    compiled program per shape key — every boundary of a live session
    reuses the same executable regardless of how many requests arrived.
    Returns ``(state, n_injected, n_rejected)`` with the counts as
    Python ints (the boundary is already a host sync point).
    """
    if not spec.ingest:
        raise ValueError(
            "inject_arrivals needs the ingestion gate: build the world "
            "with spec.ingest=True (the injection phase is compiled "
            "out otherwise)"
        )
    B = spec.ingest_batch
    u = np.full((B,), -1, np.int32)
    m = np.zeros((B,), np.float32)
    n = len(user)
    if n > B:
        raise ValueError(
            f"injection batch of {n} rows exceeds spec.ingest_batch="
            f"{B}: drain at most ingest_batch rows per boundary"
        )
    # simlint: disable=R1 -- host boundary by design: the drain hands in
    # plain Python/numpy rows (never traced values); padding happens
    # before the jit entry
    u[:n] = np.asarray(user, np.int32)
    # simlint: disable=R1 -- same host boundary
    m[:n] = np.asarray(mips, np.float32)
    state, n_inj, n_rej = _inject_jit(spec, state, net, u, m)
    return state, int(n_inj), int(n_rej)


def _phase_spawn_multi(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t0: jax.Array, t1: jax.Array,
    views: Optional[dict] = None, tp: Optional[TpCtx] = None,
    dyn: Optional[DynSpec] = None,
):
    """Closed-form multi-send spawn: up to ``spec.max_sends_per_tick``
    publishes per user per tick, each with its exact event time.

    With a coarse tick (``dt > send_interval``) the one-send-per-tick
    phase would silently throttle the workload; here send ``j`` of the
    window fires at ``max(next_send, t0) + j * interval`` — exactly the
    sequence the per-tick phase produces one tick at a time (the resume
    shift applies to the whole chain, as sequential unrolling would).
    Everything is an elementwise select over the ``(U, S)`` task-table
    view; per-send randomness (MIPSRequired, uplink loss, DropTail) draws
    ``(U, R)`` lanes mapped onto slots by the send offset ``j``.

    Spawn-stream note: the draw shapes differ from the R=1 phase, so this
    path produces a different (equally valid) MIPS/loss sample sequence —
    scenario anchors pinned to committed traces keep ``max_sends_per_tick
    == 1``.  Requires ``send_interval_jitter == 0`` (validate()).
    """
    U, T, S = spec.n_users, spec.task_capacity, spec.max_sends_per_user
    R = spec.max_sends_per_tick
    users, tasks = state.users, state.tasks
    alive_u = state.nodes.alive[:U]
    i32 = jnp.int32
    dv = dyn if dyn is not None else dyn_of(spec)

    can = alive_u & users.connected & users.publisher
    base = jnp.maximum(users.next_send, t0)  # (U,) chain start this window
    interval = users.send_interval

    k = jnp.arange(S, dtype=i32)[None, :]  # (1, S) send index
    j = k - users.send_count[:, None]  # (U, S) window offset
    jc = jnp.clip(j, 0, R - 1)
    fire = base[:, None] + j.astype(jnp.float32) * interval[:, None]
    due2 = (
        can[:, None]
        & (j >= 0)
        & (j < R)
        & (fire < t1)
    )
    if spec.send_stop_time != float("inf"):
        due2 = due2 & (fire < dv.send_stop_time)

    if spec.wired_queue_enabled:
        key, k_mips, k_loss, k_dtail = jax.random.split(state.key, 4)
    else:
        key, k_mips, k_loss = jax.random.split(state.key, 3)
    def lane_select(draws, fill):
        # draws: (U, R) per-window lanes -> (U, S) by the send offset j.
        # A take_along_axis over (U, S) lowers to a serialized ~6 ns/elem
        # gather (2.6 ms at the bench shape); R fused compare-selects run
        # at HBM bandwidth instead.
        out = jnp.full((U, S), fill, draws.dtype)
        for r in range(R):
            out = jnp.where(jc == r, draws[:, r : r + 1], out)
        return out

    if spec.fixed_mips_required is not None:
        mips2 = jnp.full((U, S), float(spec.fixed_mips_required), jnp.float32)
    else:
        draws = _tp_user_draw(
            tp,
            lambda s: jax.random.randint(
                k_mips, s, spec.mips_required_min,
                spec.mips_required_max + 1,
            ),
            U, R,
        ).astype(jnp.float32)
        mips2 = lane_select(draws, 0.0)

    d_ub = cache.d2b[:U]  # (U,)
    t_arrive = fire + d_ub[:, None]
    if spec.link_up_s > 0:
        kf = k.astype(jnp.float32)
        if spec.link_burst_n > 0:
            nb = float(spec.link_burst_n - 1)
            pos = jnp.where(
                kf <= nb,
                kf * dv.link_drain_s,
                dv.link_burst_base + (kf - nb) * dv.link_drain2_s,
            )
        else:
            pos = kf * dv.link_drain_s
        drained = dv.link_up_s + pos
        if spec.link_buffer_frames > 0:
            # mechanistic pre-link-up buffer (see _phase_spawn)
            pre2 = fire < dv.link_up_s
            buffered2 = pre2 & (k < spec.link_buffer_frames)
            t_arrive = jnp.where(buffered2, drained, t_arrive)
            warm_lost2 = pre2 & ~buffered2
        else:
            t_arrive = jnp.where(t_arrive < dv.link_up_s, drained, t_arrive)
            buffered2 = None
            warm_lost2 = None
    else:
        buffered2 = None
        warm_lost2 = None
    lost2 = jnp.zeros((U, S), bool)
    has_mac = net.mac_loss_tab.shape[0] > 0
    if spec.uplink_loss_prob > 0 or has_mac:
        # residual fitted loss + load-dependent Bianchi retry exhaustion
        # (see _phase_spawn); one uniform lane per window send
        p_eff = jnp.full((U,), dv.uplink_loss_prob, jnp.float32)
        if has_mac:
            p_eff = 1.0 - (1.0 - p_eff) * (1.0 - cache.mac_loss_p[:U])
        draws_l = _tp_user_draw(
            tp, lambda s: jax.random.uniform(k_loss, s), U, R
        ) < p_eff[:, None]
        lost2 = lane_select(draws_l, False) & net.is_wireless[:U, None]
        if buffered2 is not None:
            lost2 = lost2 & ~buffered2  # buffered frames deliver reliably
        elif spec.link_up_s > 0:
            lost2 = lost2 & (fire + d_ub[:, None] >= dv.link_up_s)
    if spec.wired_queue_enabled:
        p_u = state.nodes.link_drop_p[:U]
        p_b = state.nodes.link_drop_p[spec.broker_index]
        p_eff = 1.0 - (1.0 - p_u) * (1.0 - p_b)
        draws_d = _tp_user_draw(
            tp, lambda s: jax.random.uniform(k_dtail, s), U, R
        )
        lost2 = lost2 | (lane_select(draws_d, 1.0) < p_eff[:, None])
    if warm_lost2 is not None:
        lost2 = lost2 | (warm_lost2 & net.is_wireless[:U, None])

    stage_new = jnp.where(
        lost2, _ST_LOST, _ST_PUB_INFLIGHT
    )
    if views is not None:
        views = dict(views)
        views["stage2"] = jnp.where(due2, stage_new, views["stage2"])
        views["mips2"] = jnp.where(due2, mips2, views["mips2"])
        views["t_create2"] = jnp.where(due2, fire, views["t_create2"])
        views["t_at_broker2"] = jnp.where(
            due2, jnp.where(lost2, jnp.inf, t_arrive),
            views["t_at_broker2"],
        )
    else:
        st2 = tasks.stage.reshape(U, S)
        tasks = tasks.replace(
            stage=jnp.where(due2, stage_new, st2).reshape(T),
            mips_req=jnp.where(
                due2, mips2, tasks.mips_req.reshape(U, S)
            ).reshape(T),
            t_create=jnp.where(
                due2, fire, tasks.t_create.reshape(U, S)
            ).reshape(T),
            t_at_broker=jnp.where(
                due2,
                jnp.where(lost2, jnp.inf, t_arrive),
                tasks.t_at_broker.reshape(U, S),
            ).reshape(T),
        )
    if views is not None:
        # one stacked (2, U, S) reduce for the fired/lost per-user
        # counts (exact integers, same values as the standalone forms)
        nl = jnp.sum(jnp.stack([due2, due2 & lost2]).astype(i32), axis=2)
        n_fired, lost_u = nl[0], nl[1]
    else:
        n_fired = jnp.sum(due2, axis=1, dtype=i32)  # (U,)
        lost_u = None
    users = users.replace(
        next_send=jnp.where(
            n_fired > 0,
            base + n_fired.astype(jnp.float32) * interval,
            users.next_send,
        ),
        send_count=users.send_count + n_fired,
    )
    metrics = state.metrics
    defer_counts = views is not None and views.get(
        "defer_host_counts", False
    )
    if defer_counts:
        views["def_u"] = list(views.get("def_u", ()))
        views["def_u"] += [
            (n_fired, (("n_published", 1),)),
            (lost_u, (("n_lost", 1),)),
        ]
    else:
        if lost_u is None:
            lost_u = jnp.sum(due2 & lost2, axis=1, dtype=i32)
        sums = jnp.sum(jnp.stack([n_fired, lost_u]), axis=1)
        metrics = metrics.replace(
            n_published=metrics.n_published + sums[0],
            n_lost=metrics.n_lost + sums[1],
        )
    buf = buf._replace(tx_u=buf.tx_u + n_fired)
    state = state.replace(users=users, tasks=tasks, metrics=metrics, key=key)
    if views is not None:
        return state, buf, views
    return state, buf


def _phase_v2_release(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array, before_broker: bool,
    resched_t: Optional[jax.Array] = None,
    prerefunded: Optional[jax.Array] = None,
) -> Tuple[WorldState, TickBuf]:
    """The v2 broker's shared-timer releaseResource (BrokerBaseApp2.cc:
    284-312 via the selfMsg dance at :221-224).

    One pending RELEASERESOURCE self-message exists at a time; each local
    accept cancels and reschedules it, so only the LAST accepted task's
    expiry ever fires, and each firing releases exactly ONE stored request
    — the first in insertion (decision) order whose requiredTime passed:
    pool += its MIPS (offloaded requests were stored without a debit,
    BrokerBaseApp2.cc:244-252, so their release *inflates* the pool) and a
    status-6 Puback goes straight to the client.  Local tasks complete
    only here — a cancelled timer leaves them (and the pool) hanging,
    which is exactly the leak that drains the pool during sub-requiredTime
    publish bursts and forces the offloads observed in the committed demo
    run (ComputeBroker1 received every forwarded task).

    Called twice per tick: before the broker phase for fire times that
    precede this tick's first publish arrival (the event-order case
    "timer < arrival"), and after it for fire times the tick's decisions
    did not cancel.
    """
    tasks, b = state.tasks, state.broker
    T, S = spec.task_capacity, spec.max_sends_per_user
    U = spec.n_users
    i32 = jnp.int32
    fire_t = b.release_timer_t
    if before_broker:
        # cancelEvent semantics: a local accept earlier than the fire time
        # would cancel it, and any arrival must be *decided* first if it
        # precedes the fire — so this pass only fires timers that precede
        # every pending arrival
        arr2 = (
            tasks.stage.reshape(U, S) == _ST_PUB_INFLIGHT
        ) & (tasks.t_at_broker.reshape(U, S) <= t1)
        t_first_arr = jnp.min(
            jnp.where(arr2, tasks.t_at_broker.reshape(U, S), jnp.inf)
        )
        fire = (fire_t <= t1) & (fire_t <= t_first_arr)
    else:
        fire = fire_t <= t1

    # first stored request in insertion (= decision-time, ties by slot id)
    # order whose requiredTime expired before the fire
    expiry = tasks.t_at_broker + spec.required_time
    open_m = (tasks.req_open > 0) & (expiry < fire_t)
    key1 = jnp.where(open_m, tasks.t_at_broker, jnp.inf)
    tmin = jnp.min(key1)
    cand = open_m & (key1 == tmin)
    sel = jnp.min(jnp.where(cand, jnp.arange(T, dtype=i32), T))
    have = fire & (sel < T)
    selc = jnp.clip(sel, 0, T - 1)
    user_sel = selc // S
    ack_t = fire_t + cache.d2b[user_sel]
    was_local = tasks.stage[selc] == _ST_LOCAL_RUN

    # the self-message is spent whether or not a request matched; when the
    # broker phase deferred a reschedule behind an already-due fire (ADVICE
    # r3: an accept cannot cancel a timer that fired before it in event
    # order), the consumed timer is replaced by that reschedule, and the
    # pool refund is skipped if the broker scan already applied it
    next_t = jnp.inf if resched_t is None else resched_t
    pre = jnp.zeros((), bool) if prerefunded is None else prerefunded
    b = b.replace(
        local_pool=b.local_pool
        + jnp.where(have & ~pre, tasks.mips_req[selc], 0.0),
        release_timer_t=jnp.where(fire, next_t, fire_t),
    )
    scat = jnp.where(have, sel, T)
    scat_local = jnp.where(have & was_local, sel, T)
    tasks = tasks.replace(
        req_open=tasks.req_open.at[scat].set(0, mode="drop"),
        # duplicate status-6 for offloaded requests: the client acts on
        # whichever lands first (mqttApp2.cc:279-291 erases the entry)
        t_ack6=tasks.t_ack6.at[scat].min(
            jnp.where(have, ack_t, jnp.inf), mode="drop"
        ),
        stage=tasks.stage.at[scat_local].set(
            _ST_DONE, mode="drop"
        ),
        t_complete=tasks.t_complete.at[scat_local].set(
            jnp.where(have, fire_t, 0.0), mode="drop"
        ),
    )
    n_done = (have & was_local).astype(i32)
    metrics = state.metrics.replace(
        n_completed=state.metrics.n_completed + n_done
    )
    buf = buf._replace(
        tx_b=buf.tx_b + have.astype(i32),
        rx_u=buf.rx_u.at[user_sel].add(have.astype(i32), mode="drop"),
    )
    return state.replace(tasks=tasks, broker=b, metrics=metrics), buf


# full-fog fast-drop gate: the dense per-fog reduction is an (F, T)
# row-sum, so very wide fog axes keep the purely-compacted path (results
# are identical either way; tests A/B it by zeroing this)
_FAST_DROP_MAX_F = 256


def _broker_dense_ok(spec: WorldSpec) -> bool:
    """Static gate for the elementwise broker phase.

    With the faithful ``mips0_divisor`` quirk (``BrokerBaseApp3.cc:268``:
    every candidate's service estimate divides by brokers[0]'s MIPS), the
    estimate term is constant *across fog nodes*, so the argmin winner is
    task-independent — one scalar decision per tick window, exactly like
    the sequential broker between two advertisement arrivals.  The same
    holds for MIN_LATENCY / ENERGY_AWARE (their extra terms are per-fog,
    not per-task) and for the v1/v2 MAX_MIPS scan (batch-global winner by
    construction, ``BrokerBaseApp.cc:228-240``).  Task-dependent policies
    (ROUND_ROBIN slots, RANDOM draws, DYNAMIC's traced id, LOCAL_FIRST's
    sequential pool) stay on the compacted path.
    """
    if spec.policy == int(Policy.MAX_MIPS):
        return True
    return spec.policy in (
        int(Policy.MIN_BUSY),
        int(Policy.MIN_LATENCY),
        int(Policy.ENERGY_AWARE),
    ) and spec.bug_compat.mips0_divisor


def _fused_ok(spec: WorldSpec) -> bool:
    """Static gate for the fused per-user slot-window front-end (r6).

    ``spec.fused_slots`` threads the hot task-table columns through
    spawn -> broker -> completions -> fog-arrivals as ``(U, S)``
    register views plus a shared deferred-scatter write set
    (:func:`_task_views` / :func:`_flush_task_views`), flushed ONCE per
    tick.  It applies exactly where every participating phase is already
    elementwise over the per-user view: the dense-broker policy family
    (:func:`_broker_dense_ok`) on FIFO fogs with the two-stage arrival
    front-end.  The sequential-pool policies (LOCAL_FIRST / v2 broker),
    the POOL fog model and the learned policies keep the classic
    per-phase path — their broker is compacted, not dense, so there is
    no (U, S) pipeline to fuse.
    """
    return (
        spec.fused_slots
        and spec.n_fogs > 0
        and spec.fog_model == int(FogModel.FIFO)
        and spec.two_stage_arrivals
        and _broker_dense_ok(spec)
        and not spec.learn_active
        and spec.policy != int(Policy.LOCAL_FIRST)
        and _fused_mips_exact(spec)
    )


def _fused_mips_exact(spec: WorldSpec) -> bool:
    """Whether the tail's per-fog busy-MIPS sum is guaranteed an exact
    f32 integer under the fused path.

    The fused tail folds that sum into one merged (C, W) row reduction;
    exact-integer rows make the merge provably bit-identical to the
    unfused standalone reduce on EVERY backend (beyond 2^24 a different
    reduction tiling could round differently).  Bound: at most
    ``min(window, U*R)`` candidates can land on one fog in a tick, each
    contributing at most ``mips_required_max``.  Specs beyond the bound
    (e.g. a 1M-user auto-window world with 900-MIPS tasks) simply keep
    the unfused reference path.
    """
    mips_max = (
        spec.fixed_mips_required
        if spec.fixed_mips_required is not None
        else spec.mips_required_max
    )
    R = min(spec.arrival_cands, spec.max_sends_per_user)
    width = min(spec.window, spec.n_users * R)
    return width * max(int(mips_max), 1) < 2 ** 24


def _fused_skip_compact(spec: WorldSpec) -> bool:
    """Whether the fused arrival front-end may skip the K-window
    compaction and run the shared tail directly on the ``(U*R,)``
    candidate list.

    Legal only when the window can never overflow (``K >= T`` — the
    regime where :func:`_rot_and_defer` returns ``rot=None``, so the
    packed window order is plain ascending candidate order and the
    candidate list preserves every relative-order tie-break).  The
    exact-integer busy-MIPS bound that makes the tail's reduction
    independent of the reduction shape is already part of
    :func:`_fused_ok` (via :func:`_fused_mips_exact`: with K >= T the
    bound width IS ``U*R``), so only the window condition lives here.
    """
    return spec.window >= spec.task_capacity


def _task_views(spec: WorldSpec, tasks) -> dict:
    """Build the fused front-end's register-view pack from the task table.

    ``(U, S)`` views of the columns the fused phases read AND write
    elementwise, plus ``scat`` — the shared deferred-scatter write set
    (column name -> list of ``(idx, vals)`` T-space contributions, all
    pairwise disjoint by construction) — and ``pending_promote``, the
    one completions RUNNING entry a later completion pass may still
    retire (see :func:`_phase_completions`).  :func:`_flush_task_views`
    folds the whole pack back with one write per column.
    """
    U, S = spec.n_users, spec.max_sends_per_user
    v = {
        "stage2": tasks.stage.reshape(U, S),
        "fog2": tasks.fog.reshape(U, S),
        "mips2": tasks.mips_req.reshape(U, S),
        "t_create2": tasks.t_create.reshape(U, S),
        "t_at_broker2": tasks.t_at_broker.reshape(U, S),
        "t_at_fog2": tasks.t_at_fog.reshape(U, S),
        "t_q_enter2": tasks.t_q_enter.reshape(U, S),
        "scat": {},
        "pending_promote": None,
        # deferred host-facing counters (telemetry-off ticks only; with
        # telemetry on they stay eager so the per-phase work brackets
        # book identically to the unfused pipeline).  def_u / def_f are
        # (row, ((target, scale), ...)) entries whose row sums ride ONE
        # merged flush reduction per width (U-wide and F-wide); targets
        # name Metrics fields or the scalar TickBuf counters.
        "defer_host_counts": False,
        "rx_u": [],
        "def_u": [],
        "def_f": [],
    }
    if not spec.derive_acks:
        v["t_ack4_fwd2"] = tasks.t_ack4_fwd.reshape(U, S)
        v["t_ack4_queued2"] = tasks.t_ack4_queued.reshape(U, S)
    return v


def _defer_scatter(v: dict, col: str, idx: jax.Array, vals: jax.Array) -> None:
    """Append one deferred task-table scatter to the shared write set.

    Contributors guarantee their index sets are disjoint from every
    earlier entry on the same column (sentinel ``T`` rows aside), so the
    flush may concatenate them into ONE ``.at[idx].set`` per column.
    """
    v["scat"].setdefault(col, []).append((idx, vals))


def _flush_task_views(spec: WorldSpec, tasks, v: dict):
    """Fold the fused front-end's write set back into the task table.

    One dense column write per threaded view plus one concatenated
    scatter per deferred column — the per-phase scatter chains of the
    unfused path collapse to a single ``.at[idx].set`` each (the r5
    "scatter merge" extended across phase boundaries).  Bit-exact: the
    dense views carry exactly the per-phase select results, and every
    scatter group is pairwise disjoint, so flush order cannot differ
    from the sequential per-phase writes.
    """
    T = spec.task_capacity
    rep = dict(
        stage=v["stage2"].reshape(T),
        fog=v["fog2"].reshape(T),
        mips_req=v["mips2"].reshape(T),
        t_create=v["t_create2"].reshape(T),
        t_at_broker=v["t_at_broker2"].reshape(T),
        t_at_fog=v["t_at_fog2"].reshape(T),
    )
    rep["t_q_enter"] = v["t_q_enter2"].reshape(T)
    ack4 = v.get("t_ack4_fwd2")
    if ack4 is not None:
        rep["t_ack4_fwd"] = ack4.reshape(T)
        rep["t_ack4_queued"] = v["t_ack4_queued2"].reshape(T)
    tasks = tasks.replace(**rep)
    scat = dict(v["scat"])
    if v["pending_promote"] is not None:
        p_idx = v["pending_promote"]
        scat.setdefault("stage", []).append(
            (p_idx, jnp.full(p_idx.shape, _ST_RUNNING))
        )
    for col, entries in scat.items():
        if len(entries) == 1:
            idxs, vals = entries[0]
        else:
            idxs = jnp.concatenate([e[0] for e in entries])
            vals = jnp.concatenate([e[1] for e in entries])
        tasks = tasks.replace(
            **{col: getattr(tasks, col).at[idxs].set(vals, mode="drop")}
        )
    return tasks


def _phase_broker_dense(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array, views: Optional[dict] = None,
    tp: Optional[TpCtx] = None,
):
    """Elementwise broker phase over the ``(U, S)`` task-table view.

    Semantics identical to :func:`_phase_broker` (same formulas, same
    status partition) for the policies admitted by :func:`_broker_dense_ok`;
    the scheduling decision collapses to one scalar argmin over the fog
    view, and every per-task update is a masked vector select — no
    compaction, no gathers, no scatters (the compacted path costs ~0.6
    ms/tick at the 10k-user bench shape; this runs at HBM bandwidth).
    Unlike the compacted path there is no K-window: every matured publish
    decides this tick (strictly closer to the event-driven execution).

    ``views`` (the fused front-end, :func:`_fused_ok`): read the (U, S)
    columns from the threaded register pack instead of the task table
    and write the decisions back into it — identical arithmetic, zero
    task-table materialisation until the per-tick flush.
    """
    tasks, b = state.tasks, state.broker
    U, S, F = spec.n_users, spec.max_sends_per_user, spec.n_fogs
    T = spec.task_capacity
    i32 = jnp.int32
    if views is not None:
        st2 = views["stage2"]
        tab2 = views["t_at_broker2"]
    else:
        st2 = tasks.stage.reshape(U, S)
        tab2 = tasks.t_at_broker.reshape(U, S)
    mask2 = (st2 == _ST_PUB_INFLIGHT) & (tab2 <= t1)

    metrics = state.metrics
    users = state.users
    n_del = jnp.zeros((), i32)
    if views is None:
        cnt_u = jnp.sum(mask2, axis=1, dtype=i32)  # (U,) decided per user
        if spec.fanout_enabled:
            per_topic = jnp.sum(
                jnp.where(
                    users.pub_topic[None, :]
                    == jnp.arange(spec.n_topics, dtype=i32)[:, None],
                    cnt_u[None, :].astype(jnp.float32),
                    0.0,
                ),
                axis=1,
            )
            if tp is not None:
                # fan-out needs the GLOBAL per-topic publish counts: the
                # one broker-side combine of the decide megaphase (exact
                # f32 integers, so the psum total is order-independent)
                per_topic = jax.lax.psum(per_topic, tp.axis_name)
            deliveries = (
                users.sub_mask.astype(jnp.float32) @ per_topic
            ).astype(i32)
            n_del = jnp.sum(deliveries)
            users = users.replace(n_delivered=users.n_delivered + deliveries)
            metrics = metrics.replace(n_fanout=metrics.n_fanout + n_del)
            buf = buf._replace(rx_u=buf.rx_u + deliveries)
    # fused mode: cnt_u / the fan-out topic sums / the decision counters
    # all come from ONE two-stage merged reduction after the partition
    # (below) — same integers, three fewer standalone reduces

    # key split kept for PRNG-stream alignment with the compacted path
    key, _ = jax.random.split(state.key)

    # ---- scalar winner (shared formulas: ops/sched.py) ----------------
    fog_alive = state.nodes.alive[U : U + F]
    # chaos worlds mask crashed fogs out of EVERY policy's candidate
    # set (the broker observes liveness; the reference never evicts
    # dead fogs — bug_compat — so this is gated on spec.chaos to keep
    # chaos-off worlds bit-exact)
    reg_eff = b.registered & fog_alive if spec.chaos else b.registered
    fog_efrac = state.nodes.energy[U : U + F] / jnp.maximum(
        state.nodes.energy_capacity[U : U + F], 1e-12
    )
    if spec.hier_active:
        # federated hierarchy: one scalar winner PER BROKER DOMAIN
        # (vmap of the same reference-faithful scan over each domain's
        # availability slice), selected per task by its owning broker —
        # the decide stays elementwise over the (U, S) view, with two
        # tiny (B,)-table gathers replacing the scalar broadcast
        B = spec.n_brokers
        owned_bf = (
            state.hier.fog_broker[None, :]
            == jnp.arange(B, dtype=i32)[:, None]
        )  # (B, F)
        reg_b = reg_eff[None, :] & owned_bf
        rtt_bf = 2.0 * cache.d2b[U : U + F]
        choice_B = jax.vmap(
            lambda rg: scalar_winner(
                spec.policy, b.view_busy, b.view_mips, rg, fog_alive,
                fog_efrac, rtt_bf, spec.bug_compat.v1_max_scan,
            )
        )(reg_b)  # (B,)
        any_B = jnp.any(reg_b, axis=1)
        tb2 = jnp.clip(state.hier.task_broker, 0, B - 1).reshape(U, S)
        choice_s = choice_B[tb2]  # (U, S) per-task domain winner
        any_fog = any_B[tb2]
    else:
        any_fog = jnp.any(reg_eff)
        choice_s = scalar_winner(
            spec.policy, b.view_busy, b.view_mips, reg_eff, fog_alive,
            fog_efrac, 2.0 * cache.d2b[U : U + F],
            spec.bug_compat.v1_max_scan,
        )

    choice_ok = choice_s >= 0
    if spec.policy == int(Policy.MAX_MIPS) and F > 0:
        win_mips = b.view_mips[jnp.clip(choice_s, 0, F - 1)]
        mips2 = (
            views["mips2"] if views is not None
            else tasks.mips_req.reshape(U, S)
        )
        guard2 = mask2 & choice_ok & ~(mips2 < win_mips)
    else:
        guard2 = jnp.zeros((U, S), bool)

    sched2 = mask2 & any_fog & choice_ok & ~guard2
    rejected2 = mask2 & any_fog & guard2
    no_res2 = mask2 & ~(sched2 | rejected2)

    new_stage2 = jnp.where(
        sched2,
        _ST_TASK_INFLIGHT,
        jnp.where(
            rejected2,
            _ST_REJECTED,
            _ST_NO_RESOURCE,
        ),
    )
    d_bf_c = cache.d2b[U + jnp.clip(choice_s, 0, F - 1)] if F > 0 else 0.0
    d_bu = cache.d2b[:U]
    if views is not None:
        views = dict(views)
        views["stage2"] = jnp.where(mask2, new_stage2, st2)
        views["fog2"] = jnp.where(sched2, choice_s, views["fog2"])
        views["t_at_fog2"] = jnp.where(
            sched2, tab2 + d_bf_c, views["t_at_fog2"]
        )
        if not spec.derive_acks:
            views["t_ack4_fwd2"] = jnp.where(
                mask2, tab2 + d_bu[:, None], views["t_ack4_fwd2"]
            )
    else:
        tasks = tasks.replace(
            stage=jnp.where(mask2, new_stage2, st2).reshape(T),
            fog=jnp.where(
                sched2, choice_s, tasks.fog.reshape(U, S)
            ).reshape(T),
            t_at_fog=jnp.where(
                sched2, tab2 + d_bf_c, tasks.t_at_fog.reshape(U, S)
            ).reshape(T),
        )
        if not spec.derive_acks:  # else reconstructed post-run (run())
            tasks = tasks.replace(
                t_ack4_fwd=jnp.where(
                    mask2, tab2 + d_bu[:, None],
                    tasks.t_ack4_fwd.reshape(U, S),
                ).reshape(T),
            )
    if views is not None:
        # two-stage merged reduction: per-user partials over the send
        # axis feed both the scalar decision counters and the fan-out
        # topic sums (all exact f32 integers -> bit-identical to the
        # unfused standalone reduces)
        part = jnp.sum(
            jnp.stack([sched2, no_res2, rejected2, mask2]).astype(i32),
            axis=2,
        )  # (4, U)
        cnt_u = part[3]
        if spec.fanout_enabled:
            f32 = jnp.float32
            topicrows = jnp.where(
                users.pub_topic[None, :]
                == jnp.arange(spec.n_topics, dtype=i32)[:, None],
                cnt_u[None, :].astype(f32),
                0.0,
            )
            merged = jnp.sum(
                jnp.concatenate([part.astype(f32), topicrows]), axis=1
            )
            sums = merged[:4].astype(i32)
            per_topic = merged[4:]
            deliveries = (
                users.sub_mask.astype(f32) @ per_topic
            ).astype(i32)
            users = users.replace(n_delivered=users.n_delivered + deliveries)
            buf = buf._replace(rx_u=buf.rx_u + deliveries)
            defer_fanout = views.get("defer_host_counts", False)
            if defer_fanout:
                # the fan-out total joins the flush's merged reduction
                views["def_u"] = list(views.get("def_u", ()))
                views["def_u"].append(
                    (deliveries, (("n_fanout", 1), ("tx_b", 1)))
                )
                n_del = jnp.zeros((), i32)  # tx_b add lands at flush
            else:
                n_del = jnp.sum(deliveries)
                metrics = metrics.replace(n_fanout=metrics.n_fanout + n_del)
        else:
            sums = jnp.sum(part, axis=1)
    else:
        sums = jnp.sum(
            jnp.stack([sched2, no_res2, rejected2, mask2]).astype(i32),
            axis=(1, 2),
        )
    metrics = metrics.replace(
        n_scheduled=metrics.n_scheduled + sums[0],
        n_no_resource=metrics.n_no_resource + sums[1],
        n_rejected=metrics.n_rejected + sums[2],
    )
    buf = buf._replace(
        tx_b=buf.tx_b + sums[0] + sums[3] + n_del,
        rx_b=buf.rx_b + sums[3],
        rx_u=buf.rx_u + cnt_u,
    )
    state = state.replace(tasks=tasks, users=users, metrics=metrics, key=key)
    if views is not None:
        return state, buf, views
    return state, buf


def _phase_broker(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array,
) -> Tuple[WorldState, TickBuf]:
    """Broker decides every publish that has arrived (BrokerBaseApp3.cc:231-319).

    All arrivals in the window see the same view snapshot — faithful, since
    the reference's view is only refreshed by advertisement arrivals, never
    by its own assignments.  Emits the forwarded status-4 ack
    (``BrokerBaseApp3.cc:146-150``) whose client-side arrival becomes the
    latencyH1 signal (``mqttApp2.cc:269-277``).

    Additional branches here:
      * topic fan-out (``publishAll``, ``BrokerBaseApp3.cc:365-385``,
        upgraded from dormant to live per SURVEY §3.4): every arrival is
        duplicated to all subscribers of its topic — one (U × topics) @
        (topics,) matmul per tick.
      * LOCAL_FIRST local execution (``BrokerBaseApp.cc:196-224``): tasks
        with ``MIPSRequired < pool`` run on the broker itself; the pool is
        debited sequentially in arrival order (exact, via a tiny lax.scan
        over the compact window).
      * MAX_MIPS / LOCAL_FIRST offload guard (``BrokerBaseApp.cc:244``):
        a task whose MIPSRequired >= the winner's advertised MIPS is never
        sent anywhere → Stage.REJECTED.
    """
    tasks, b = state.tasks, state.broker
    T, F, K = spec.task_capacity, spec.n_fogs, spec.window
    S = spec.max_sends_per_user
    v2_resched = None  # deferred release-timer reschedule (v2 broker only)
    mask = (tasks.stage == _ST_PUB_INFLIGHT) & (
        tasks.t_at_broker <= t1
    )
    rot, state = _rot_and_defer(spec, state, mask, K)
    idx, idxc, valid = _compact(mask, K, T, rot)

    mips_g = tasks.mips_req[idxc]
    user_g = idxc // S  # slot layout u*S+k makes the owner a pure index op
    t_ab_g = tasks.t_at_broker[idxc]

    # ---- topic fan-out (publishAll as a live feature) -----------------
    metrics = state.metrics
    users = state.users
    n_del = jnp.zeros((), jnp.int32)
    if spec.fanout_enabled:
        topic_g = users.pub_topic[user_g]
        # (topics, K) membership reduce instead of a serialized scatter-add
        per_topic = jnp.sum(
            (
                topic_g[None, :]
                == jnp.arange(spec.n_topics, dtype=jnp.int32)[:, None]
            )
            & valid[None, :],
            axis=1,
            dtype=jnp.float32,
        )
        deliveries = (users.sub_mask.astype(jnp.float32) @ per_topic).astype(
            jnp.int32
        )  # (U,)
        n_del = jnp.sum(deliveries)
        users = users.replace(n_delivered=users.n_delivered + deliveries)
        metrics = metrics.replace(n_fanout=metrics.n_fanout + n_del)
        buf = buf._replace(rx_u=buf.rx_u + deliveries)

    # ---- LOCAL_FIRST: debit the broker's own pool in arrival order ----
    local = jnp.zeros((K,), bool)
    local_first = spec.policy == int(Policy.LOCAL_FIRST)
    if local_first:
        order = jnp.lexsort((idx, jnp.where(valid, t_ab_g, jnp.inf)))
        mips_sorted = mips_g[order]
        valid_sorted = valid[order]
        if not spec.v2_local_broker:

            def body(pool, xs):
                m, v = xs
                take = v & (m < pool)  # strict <, BrokerBaseApp.cc:171
                return pool - jnp.where(take, m, 0.0), take

            pool_after, local_sorted = jax.lax.scan(
                body, b.local_pool, (mips_sorted, valid_sorted)
            )
        else:
            # v2: the shared RELEASERESOURCE self-message is interleaved
            # with the accept chain in event order (ADVICE r3 + r4 review):
            #   * every local accept cancels the pending timer
            #     (BrokerBaseApp2.cc:221-224) — the FIRST accept before
            #     the fire time disarms it;
            #   * a still-armed timer pops before any arrival at or after
            #     its fire time, and its pool refund is visible to the
            #     accept checks that follow it in the same tick.
            # The released request is selected on pre-decision state —
            # identical to the after-pass selection, because a request
            # stored this tick can only satisfy ``expiry < fire`` when
            # required_time < dt (excluded by validate()).
            fire_t0 = b.release_timer_t
            expiry0 = tasks.t_at_broker + spec.required_time
            open0 = (tasks.req_open > 0) & (expiry0 < fire_t0)
            key0 = jnp.where(open0, tasks.t_at_broker, jnp.inf)
            cand0 = open0 & (key0 == jnp.min(key0))
            sel0 = jnp.min(
                jnp.where(cand0, jnp.arange(T, dtype=jnp.int32), T)
            )
            refund0 = jnp.where(
                sel0 < T, tasks.mips_req[jnp.clip(sel0, 0, T - 1)], 0.0
            )
            tm_sorted = jnp.where(valid, t_ab_g, jnp.inf)[order]

            def body(carry, xs):
                pool, armed, fired = carry
                m, v, t = xs
                # the timer (heap-pushed earlier) pops before an arrival
                # at the same instant: fire at t >= fire time
                fire_now = armed & v & (t >= fire_t0)
                pool = pool + jnp.where(fire_now, refund0, 0.0)
                fired = fired | fire_now
                armed = armed & ~fire_now
                take = v & (m < pool)  # strict <, BrokerBaseApp2.cc:181
                pool = pool - jnp.where(take, m, 0.0)
                armed = armed & ~take  # cancelEvent at every accept
                return (pool, armed, fired), take

            (pool_after, _, v2_fired), local_sorted = jax.lax.scan(
                body,
                (
                    b.local_pool,
                    jnp.isfinite(fire_t0),
                    jnp.zeros((), bool),
                ),
                (mips_sorted, valid_sorted, tm_sorted),
            )
        local = jnp.zeros((K,), bool).at[order].set(local_sorted)
        b = b.replace(local_pool=pool_after)
        if spec.v2_local_broker:
            # Timer disposition (one shared self-message, App. B item 8):
            #   * in-scan fire  -> leave it armed at the old fire time so
            #     the after pass does the release bookkeeping (its pool
            #     refund already landed in the scan), then installs the
            #     last accept's reschedule via ``v2_resched``;
            #   * accepts only  -> the first accept cancelled it: install
            #     the last accept's reschedule directly;
            #   * neither       -> unchanged (the after pass fires it if
            #     due, with the full refund).
            any_local = jnp.any(local)
            t_last_acc = jnp.max(jnp.where(local, t_ab_g, -jnp.inf))
            resched = jnp.where(
                any_local, t_last_acc + spec.required_time, jnp.inf
            )
            v2_resched = (
                jnp.where(v2_fired, resched, jnp.inf),  # after-pass next
                v2_fired,  # pool already refunded in-scan
            )
            b = b.replace(
                release_timer_t=jnp.where(
                    any_local & ~v2_fired, resched, b.release_timer_t
                )
            )

    # ---- offload scheduling ------------------------------------------
    key, k_sched = jax.random.split(state.key)
    U = spec.n_users
    rtt_bf = 2.0 * cache.d2b[U : U + F]
    fog_alive = state.nodes.alive[U : U + F]
    # chaos worlds mask crashed fogs out of every policy's candidate
    # set (gated on spec.chaos: chaos-off worlds keep the reference's
    # never-evicts-dead-fogs view, bit-exact)
    reg_eff = b.registered & fog_alive if spec.chaos else b.registered
    fog_efrac = state.nodes.energy[U : U + F] / jnp.maximum(
        state.nodes.energy_capacity[U : U + F], 1e-12
    )
    hier_kw = {}
    tb_g = None
    if spec.hier_active:
        # federated hierarchy: the window's tasks carry their owning
        # broker; schedule_batch masks every policy's candidate set to
        # the task's domain (per-domain brokers[0] anchors, bandit
        # slices, RANDOM slot tables — ops/sched.py)
        B_h = spec.n_brokers
        tb_g = jnp.clip(state.hier.task_broker[idxc], 0, B_h - 1)
        hier_kw = dict(
            fog_owner=state.hier.fog_broker,
            task_broker=tb_g,
            n_brokers=B_h,
        )
        any_fog = jnp.any(
            reg_eff[None, :]
            & (
                state.hier.fog_broker[None, :]
                == jnp.arange(B_h, dtype=jnp.int32)[:, None]
            ),
            axis=1,
        )[tb_g]  # (K,) per-task: does MY domain have a candidate?
    else:
        any_fog = jnp.any(reg_eff)

    offl = valid & ~local
    if spec.policy in (
        int(Policy.RANDOM), int(Policy.DYNAMIC), int(Policy.EXP3)
    ):
        # the RANDOM stream is keyed on the global task id (shared with
        # the native DES, see ops/sched.py::task_uniform); EXP3 samples
        # its arm from the same batching-independent stream
        rand_u = task_uniform(
            jax.random.PRNGKey(spec.policy_seed), idxc
        )
    else:
        rand_u = None
    choice, rr_new = schedule_batch(
        spec.policy, offl, mips_g, b.view_busy, b.view_mips,
        reg_eff, fog_alive, fog_efrac, rtt_bf, b.rr_next, k_sched,
        spec.bug_compat.mips0_divisor, spec.bug_compat.v1_max_scan,
        policy_id=b.policy_id, order_t=t_ab_g, rand_u=rand_u,
        learn=arms_view(state.learn) if spec.learn_active else None,
        **hier_kw,
    )
    choice_ok = choice >= 0
    guard_fail = jnp.zeros((K,), bool)
    if spec.policy in (int(Policy.MAX_MIPS), int(Policy.LOCAL_FIRST)) and F > 0:
        # per-task guard: MIPSRequired < winner's advertised MIPS, else the
        # task is silently never sent (BrokerBaseApp.cc:244-252)
        win_mips = b.view_mips[jnp.clip(choice, 0, F - 1)]
        guard_fail = choice_ok & ~(mips_g < win_mips)

    fog_node = _fog_node_idx(spec, choice)
    d_bf = cache.d2b[fog_node]
    d_bu = cache.d2b[user_g]

    # partition the decided arrivals: scheduled / locally run / rejected by
    # the v1 guard / no resource (no registered fog, or a policy-level
    # "no usable fog" -1, e.g. ENERGY_AWARE with every fog dead)
    sched = offl & any_fog & choice_ok & ~guard_fail
    rejected = offl & any_fog & guard_fail
    no_res = offl & (~any_fog | (~choice_ok & ~guard_fail))

    # ---- bandit decision bookkeeping (learn/bandits.py) ---------------
    # Pick counts advance at the END of the window (every same-window
    # arrival scored the same snapshot — the broker-view staleness
    # contract), and the per-task provenance records the probability the
    # picked arm had at decision time so the delayed credit phase can
    # importance-weight EXP3 updates.  Statically gated: worlds on the
    # pre-existing policies trace none of this.
    learn2 = state.learn
    if spec.learn_active:
        picked = _per_fog(sched, choice, F)  # (F, K) membership
        learn2 = learn2.replace(
            pick_count=learn2.pick_count
            + jnp.sum(picked, axis=1, dtype=jnp.float32)
        )
        exp3ish = spec.policy == int(Policy.EXP3) or (
            spec.policy == int(Policy.DYNAMIC) and spec.learn_in_dynamic
        )
        if exp3ish:
            if spec.hier_active:
                # per-domain distributions (the same rows the pick
                # sampled from in ops/sched.py): the stored importance
                # weight is the probability within the task's OWN
                # broker's softmax
                owned_bf = (
                    state.hier.fog_broker[None, :]
                    == jnp.arange(spec.n_brokers, dtype=jnp.int32)[:, None]
                )
                p_bf = jax.vmap(
                    lambda av: exp3_probs(learn2.logw, av, learn2.explore)
                )((b.registered & fog_alive)[None, :] & owned_bf)
                p_row = p_bf[tb_g, jnp.clip(choice, 0, F - 1)]
            else:
                p_vec = exp3_probs(
                    learn2.logw, b.registered & fog_alive, learn2.explore
                )
                # p at the chosen fog per row via the membership matrix
                # (a (K,) gather from an (F,) table serializes under
                # vmap)
                p_row = jnp.sum(
                    jnp.where(picked, p_vec[:, None], 0.0), axis=0
                )
            if spec.policy == int(Policy.DYNAMIC):
                p_row = jnp.where(
                    b.policy_id == int(Policy.EXP3), p_row, 1.0
                )
            # only EXP3-capable specs store provenance: the UCB family's
            # pick_p stays at its all-ones init, so scattering ones per
            # tick would be a dead ~25 us op in the hot broker phase
            learn2 = learn2.replace(
                pick_p=learn2.pick_p.at[idx].set(
                    jnp.where(sched, p_row, 1.0), mode="drop"
                )
            )

    new_stage = jnp.where(
        sched,
        _ST_TASK_INFLIGHT,
        jnp.where(
            local,
            _ST_LOCAL_RUN,
            jnp.where(
                rejected,
                _ST_REJECTED,
                _ST_NO_RESOURCE,
            ),
        ),
    )
    # v3 emits the forwarded status-4 for every QoS-1 publish; v1's local
    # branch instead acks status-3 "processing" (BrokerBaseApp.cc:200-212)
    tasks = tasks.replace(
        stage=tasks.stage.at[idx].set(new_stage, mode="drop"),
        fog=tasks.fog.at[idx].set(jnp.where(sched, choice, NO_TASK), mode="drop"),
        t_at_fog=tasks.t_at_fog.at[idx].set(
            jnp.where(sched, t_ab_g + d_bf, jnp.inf), mode="drop"
        ),
    )
    if not spec.derive_acks:  # else reconstructed post-run (run())
        tasks = tasks.replace(
            t_ack4_fwd=tasks.t_ack4_fwd.at[idx].set(
                jnp.where(~local, t_ab_g + d_bu, jnp.inf), mode="drop"
            ),
            t_ack3=tasks.t_ack3.at[idx].set(
                jnp.where(local, t_ab_g + d_bu, jnp.inf), mode="drop"
            ),
        )
    if local_first:
        tasks = tasks.replace(
            t_service_start=tasks.t_service_start.at[idx].set(
                jnp.where(local, t_ab_g, jnp.inf), mode="drop"
            ),
        )
        if spec.v2_local_broker:
            # v2 stores a Request for local accepts AND for every decided
            # offload-branch publish when fogs exist (BrokerBaseApp2.cc:
            # 212,244 — stored even when the MIPS guard then refuses to
            # send); completion happens only at a release firing
            store = local | (offl & any_fog)
            tasks = tasks.replace(
                req_open=tasks.req_open.at[
                    jnp.where(store, idx, spec.task_capacity)
                ].set(jnp.int8(1), mode="drop"),
            )
        else:
            tasks = tasks.replace(
                t_complete=tasks.t_complete.at[idx].set(
                    jnp.where(local, t_ab_g + spec.required_time, jnp.inf),
                    mode="drop",
                ),
            )
    i32 = jnp.int32
    # one stacked reduction for every scalar count of this phase
    sums = jnp.sum(
        jnp.stack([sched, no_res, rejected, local, valid]).astype(i32), axis=1
    )
    metrics = metrics.replace(
        n_scheduled=metrics.n_scheduled + sums[0],
        n_no_resource=metrics.n_no_resource + sums[1],
        n_rejected=metrics.n_rejected + sums[2],
        n_local=metrics.n_local + sums[3],
    )
    # broker sends: FognetMsgTask per scheduled + one ack per decided task;
    # rx: the decided publishes arrived at the broker this tick
    buf = buf._replace(
        tx_b=buf.tx_b + sums[0] + sums[4] + n_del,
        rx_b=buf.rx_b + sums[4],
        rx_u=buf.rx_u.at[user_g].add(valid.astype(i32), mode="drop"),
    )
    return (
        state.replace(
            tasks=tasks, users=users, broker=b.replace(rr_next=rr_new),
            metrics=metrics, key=key, learn=learn2,
        ),
        buf,
        v2_resched,
    )


def _phase_completions(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array, views: Optional[dict] = None,
):
    """FIFO fogs whose in-service task finished release it (releaseResource,
    ``ComputeBrokerApp3.cc:224-256``): status-6 ack relayed to the client
    (taskTime signal), busyTime decremented by the task's service time, FIFO
    head promoted (queueTime signal), next release scheduled exactly at
    ``busy_until + svc``, and a fresh advertisement put in flight.

    ``views`` (fused front-end): task-table reads come from the threaded
    column views and every task write joins the shared deferred-scatter
    set instead of landing as its own kernel.  One sequencing hazard:
    the promoted head's RUNNING entry may be retired by the NEXT
    completion pass completing that same task within the tick — so the
    entry parks in ``views["pending_promote"]`` and the next pass (or
    the flush) resolves it, keeping the merged scatter groups disjoint.
    """
    tasks, fogs, b = state.tasks, state.fogs, state.broker
    F, U = spec.n_fogs, spec.n_users
    T = spec.task_capacity
    i32 = jnp.int32
    fog_alive = state.nodes.alive[U : U + F]

    comp = (fogs.current_task != NO_TASK) & (fogs.busy_until <= t1) & fog_alive
    done_task = jnp.where(comp, fogs.current_task, spec.task_capacity)
    t_done = fogs.busy_until  # exact completion times per fog

    if views is not None:
        views = dict(views)
        views["scat"] = {k: list(xs) for k, xs in views["scat"].items()}
        if views["pending_promote"] is not None:
            # the previous pass's promoted head completes THIS pass ->
            # its RUNNING entry is superseded by this pass's DONE write
            # (sequential order); retire it so the merged stage scatter
            # stays conflict-free
            _defer_scatter(
                views, "stage",
                jnp.where(comp, T, views["pending_promote"]),
                jnp.full((F,), _ST_RUNNING),
            )
            views["pending_promote"] = None

    # ack6 path: fog -> broker -> client (relay, BrokerBaseApp3.cc:164-175)
    user_of = jnp.clip(done_task, 0, spec.task_capacity - 1) // spec.max_sends_per_user
    d_fb = cache.d2b[U : U + F]
    d_bu = cache.d2b[user_of]
    t_ack6 = t_done + d_fb + d_bu

    mips_flat = views["mips2"].reshape(T) if views is not None else tasks.mips_req
    svc_done = _svc_time(
        spec, mips_flat[jnp.clip(done_task, 0, spec.task_capacity - 1)], fogs.mips
    )

    if views is not None:
        _defer_scatter(
            views, "t_complete", done_task, jnp.where(comp, t_done, 0)
        )
        if not spec.derive_acks:
            _defer_scatter(
                views, "t_ack6", done_task, jnp.where(comp, t_ack6, 0)
            )
    else:
        tasks = tasks.replace(
            t_complete=tasks.t_complete.at[done_task].set(
                jnp.where(comp, t_done, 0), mode="drop"
            ),
        )
        if not spec.derive_acks:
            tasks = tasks.replace(
                t_ack6=tasks.t_ack6.at[done_task].set(
                    jnp.where(comp, t_ack6, 0), mode="drop"
                ),
            )
    # busyTime -= currentTask.requiredTime (== its tskTime, set at accept:
    # ComputeBrokerApp3.cc:296,232)
    busy_time = jnp.where(comp, fogs.busy_time - svc_done, fogs.busy_time)

    # promote FIFO head (ComputeBrokerApp3.cc:236-252)
    head, q_head, q_len = batched_pop(fogs.queue, fogs.q_head, fogs.q_len, comp)
    promoted = comp & (head != NO_TASK)
    head_c = jnp.clip(head, 0, spec.task_capacity - 1)
    svc_new = _svc_time(spec, mips_flat[head_c], fogs.mips)
    if views is not None:
        # stage: DONE entries join the merged scatter now; the promoted
        # RUNNING entry parks as pending (see docstring)
        _defer_scatter(views, "stage", done_task, jnp.full((F,), _ST_DONE))
        views["pending_promote"] = jnp.where(promoted, head, T)
        _defer_scatter(
            views, "t_service_start",
            jnp.where(promoted, head, T), jnp.where(comp, t_done, 0),
        )
        if not spec.derive_acks:
            _defer_scatter(
                views, "queue_time_ms",
                jnp.where(promoted, head, T),
                jnp.where(promoted, (t_done - tasks.t_q_enter[head_c]) * 1e3, 0),
            )
    else:
        # ONE stage scatter for completed + promoted rows (disjoint index
        # sets; two separate scatters cost ~25 us each on the v5e)
        scat_stage = jnp.concatenate(
            [done_task, jnp.where(promoted, head, spec.task_capacity)]
        )
        stage_vals = jnp.concatenate(
            [
                jnp.full((F,), _ST_DONE),
                jnp.full((F,), _ST_RUNNING),
            ]
        )
        tasks = tasks.replace(
            stage=tasks.stage.at[scat_stage].set(stage_vals, mode="drop"),
            t_service_start=tasks.t_service_start.at[
                jnp.where(promoted, head, spec.task_capacity)
            ].set(jnp.where(comp, t_done, 0), mode="drop"),
        )
        if not spec.derive_acks:
            tasks = tasks.replace(
                queue_time_ms=tasks.queue_time_ms.at[
                    jnp.where(promoted, head, spec.task_capacity)
                ].set(
                    jnp.where(
                        promoted, (t_done - tasks.t_q_enter[head_c]) * 1e3, 0
                    ),
                    mode="drop",
                ),
            )
    fogs = fogs.replace(
        busy_time=busy_time,
        current_task=jnp.where(comp, jnp.where(promoted, head, NO_TASK), fogs.current_task),
        busy_until=jnp.where(
            comp, jnp.where(promoted, t_done + svc_new, jnp.inf), fogs.busy_until
        ),
        # an idle server's next arrival cannot start before this completion
        # (ADVICE r1: same-tick arrival-after-completion overlap)
        free_since=jnp.where(comp & ~promoted, t_done, fogs.free_since),
        q_head=q_head,
        q_len=q_len,
    )
    # advertisement in flight: advertiseMIPS() at end of releaseResource
    # (ComputeBrokerApp3.cc:254); latest-wins single slot per fog.
    if spec.adv_on_completion:
        b = b.replace(
            adv_val_mips=jnp.where(comp, fogs.mips, b.adv_val_mips),
            adv_val_busy=jnp.where(comp, busy_time, b.adv_val_busy),
            adv_arrive_t=jnp.where(comp, t_done + d_fb, b.adv_arrive_t),
        )
    defer_counts = views is not None and views.get(
        "defer_host_counts", False
    )
    if defer_counts:
        # telemetry-off fused tick: the scalar completion count, the
        # broker relay counters and the per-user ack scatter-add all
        # fold into the flush's single merged pass (int adds commute,
        # so the deferred totals are bit-identical to the eager ones)
        views["def_f"] = list(views.get("def_f", ()))
        views["def_f"].append((
            comp,
            (
                ("n_completed", 1),
                ("tx_b", 1),
                ("rx_b", 2 if spec.adv_on_completion else 1),
            ),
        ))
        views["rx_u"] = list(views.get("rx_u", ()))
        views["rx_u"].append((user_of, comp.astype(i32)))
        metrics = state.metrics
        buf = buf._replace(
            tx_f=buf.tx_f
            + comp.astype(i32) * (2 if spec.adv_on_completion else 1),
        )
    else:
        n_comp = jnp.sum(comp.astype(i32))
        metrics = state.metrics.replace(
            n_completed=state.metrics.n_completed + n_comp
        )
        # fog sends ack6 (+ advert); broker relays to the user
        n_adv = n_comp if spec.adv_on_completion else 0
        buf = buf._replace(
            tx_f=buf.tx_f
            + comp.astype(i32) * (2 if spec.adv_on_completion else 1),
            tx_b=buf.tx_b + n_comp,
            rx_b=buf.rx_b + n_comp + n_adv,
            rx_u=buf.rx_u.at[user_of].add(comp.astype(i32), mode="drop"),
        )
    state = state.replace(tasks=tasks, fogs=fogs, broker=b, metrics=metrics)
    if views is not None:
        return state, buf, views
    return state, buf


def _phase_fog_arrivals(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array, views: Optional[dict] = None,
):
    """Tasks reaching their FIFO fog node are assigned or queued
    (``ComputeBrokerApp3.cc:269-320``).

    busyTime += tskTime for *every* arrival (accepted or queued, ``:279``);
    an idle fog takes the earliest arrival (status-5 "assigned" ack → the
    client's latency signal); the rest enter the FIFO in arrival order
    (status-4 "queued" ack → a second latencyH1 sample at the client).

    Two front-ends produce the compacted arrival window (r5 perf):
    ``spec.two_stage_arrivals`` selects the per-user candidate reduction
    over the (U, S) task-table view (:func:`_fog_arrivals_front_two_stage`)
    instead of the classic full-table compaction — same decisions with
    the (F,T) matmuls and T-compaction gone; the shared tail does the
    assignment, queueing and ack bookkeeping either way.
    """
    if spec.two_stage_arrivals:
        return _fog_arrivals_front_two_stage(
            spec, state, net, cache, buf, t1, views
        )
    assert views is None  # the fused gate requires two_stage_arrivals
    return _fog_arrivals_front_full(spec, state, net, cache, buf, t1)


def _fog_arrivals_front_full(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array,
) -> Tuple[WorldState, TickBuf]:
    """Classic front-end: full-table mask, dense fast drop, T-compaction."""
    tasks, fogs = state.tasks, state.fogs
    T, F, K = spec.task_capacity, spec.n_fogs, spec.window
    U = spec.n_users
    i32 = jnp.int32
    fog_alive = state.nodes.alive[U : U + F]

    arr_full = (tasks.stage == _ST_TASK_INFLIGHT) & (
        tasks.t_at_fog <= t1
    )
    # ---- full-fog fast drop (dense) -----------------------------------
    # An arrival at a fog whose ring is already full can only be tail-
    # dropped (enqueue would fail for every rank), so it never needs a
    # compaction slot: decide those densely over the task table.  Exact:
    # completions ran first, so q_len here is what the ranked enqueue
    # would have seen; busy_time still grows by the arrival's service
    # estimate (the reference adds busyTime for EVERY arrival,
    # ComputeBrokerApp3.cc:279, and has no drops to skip).  In saturated
    # worlds (the throughput benchmark) this keeps the compacted window
    # K small — the shape-cost of the ranked path no longer scales with
    # the offered load.  Dead-fog arrivals keep their existing compacted
    # handling (different counting: no busy add, no fog rx); an idle
    # server (possible over a stale ring after lifecycle churn) disables
    # the fast path for its fog, since the ranked path would assign
    # there, not drop.  Gated on F <= 256: the dense per-fog reduction
    # is an (F, T) row-sum.
    n_fast = jnp.zeros((), i32)
    n_fast_f = jnp.zeros((F,), i32)
    if 0 < F <= _FAST_DROP_MAX_F:
        fog_dst = jnp.clip(tasks.fog, 0, F - 1)
        droppy = (  # (F,) fog can only tail-drop a live arrival
            (fogs.q_len >= spec.queue_capacity)
            & (fogs.current_task != NO_TASK)
            & fog_alive
        )
        # droppy[fog_dst] as a GEMV over the (F, T) membership compare: a
        # T-sized gather from an (F,) table lowers fine solo but
        # serializes under vmap (the r4 64-replica fan-out collapse)
        eqf = fog_dst[None, :] == jnp.arange(F, dtype=i32)[:, None]  # (F,T)
        droppy_t = (
            droppy.astype(jnp.float32) @ eqf.astype(jnp.float32)
        ) > 0.5
        fast_drop = arr_full & droppy_t
        tasks = tasks.replace(
            stage=jnp.where(
                fast_drop, _ST_DROPPED, tasks.stage
            )
        )
        arr_full = arr_full & ~fast_drop
        # per-fog reduction as ONE (F, T) @ (T, 2) matmul: a broadcast
        # compare + axis-1 reduce lowers fine solo but collapsed under
        # vmap (r4 measured: 64-replica fan-out lost 3.8x); the batched
        # GEMM form rides the MXU in both cases.  f32 exact: counts and
        # integer MIPS sums stay far below 2^24 per fog per tick.
        onehot = eqf & fast_drop[None, :]  # (F, T)
        rhs = jnp.stack(
            [
                jnp.ones((T,), jnp.float32),
                jnp.where(fast_drop, tasks.mips_req, 0.0),
            ],
            axis=1,
        )  # (T, 2)
        sums = onehot.astype(jnp.float32) @ rhs  # (F, 2)
        n_fast_f = sums[:, 0].astype(i32)
        svc_fast_f = sums[:, 1] / jnp.maximum(fogs.mips, 1e-9)
        fogs = fogs.replace(
            busy_time=fogs.busy_time + svc_fast_f,
            q_drops=fogs.q_drops + n_fast_f,
        )
        n_fast = jnp.sum(n_fast_f)

    rot, state = _rot_and_defer(spec, state, arr_full, K)
    idx, idxc, valid = _compact(arr_full, K, T, rot)
    fog_g = tasks.fog[idxc]  # (K,)
    t_af_g = tasks.t_at_fog[idxc]
    mips_g = tasks.mips_req[idxc]
    user_g = idxc // spec.max_sends_per_user
    return _fog_arrivals_tail(
        spec, state, cache, buf, tasks, fogs,
        idx, idxc, valid, fog_g, t_af_g, mips_g, user_g, n_fast, n_fast_f,
    )


def _arrival_candidates(st2, taf2, fog2, mip2, t1, R: int):
    """R earliest matured (TASK_INFLIGHT, ``t_at_fog <= t1``) slots per
    user, reduced from the ``(U, S)`` task-table view.

    The unfused reference formulation of the two-stage front's candidate
    loop, extracted so the TP sharded tick
    (:mod:`fognetsimpp_tpu.parallel.taskshard`) runs the IDENTICAL
    per-pass reductions on its local user block — argmin returns the
    FIRST min, so time ties break by slot id exactly like the classic
    selection.  Returns ``(cks, cts, cfs, cms, cvs, n_left)``: per-pass
    lists of (slot-index, time, fog, MIPS, valid) plus the count of
    matured slots beyond the per-user cap (they defer one tick).
    """
    i32 = jnp.int32
    S = st2.shape[1]
    kk = jnp.arange(S, dtype=i32)[None, :]
    m = (st2 == _ST_TASK_INFLIGHT) & (taf2 <= t1)
    cks, cts, cfs, cms, cvs = [], [], [], [], []
    for _ in range(R):
        key = jnp.where(m, taf2, jnp.inf)
        ck = jnp.argmin(key, axis=1).astype(i32)  # (U,)
        ct = jnp.min(key, axis=1)
        cv = jnp.isfinite(ct)
        sel = m & (kk == ck[:, None])
        cf = jnp.sum(jnp.where(sel, fog2, 0), axis=1)  # one-hot: exact
        cm = jnp.sum(jnp.where(sel, mip2, 0.0), axis=1)
        cks.append(ck); cts.append(ct); cfs.append(cf)
        cms.append(cm); cvs.append(cv)
        m = m & ~sel
    n_left = jnp.sum(m, dtype=i32)  # matured beyond the per-user cap
    return cks, cts, cfs, cms, cvs, n_left


def _fog_arrivals_front_two_stage(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array, views: Optional[dict] = None,
):
    """Per-user candidate front-end (r5).

    At ``dt <= send_interval`` at most one task per user matures at its
    fog per tick (more under coarse windows — bounded by
    ``spec.max_sends_per_tick`` — or after deferral transients), so the
    full-table compaction is overkill: reduce the ``(U, S)`` view to the
    ``R`` earliest matured slots per user (masked argmin passes — pure
    elementwise/reduce work, no T-sized gathers), then compact the
    ``(U*R,)`` candidate list into the K-window.  Matured tasks beyond
    the per-user cap defer exactly like window overflow (counted in
    ``Metrics.n_deferred``, decided next tick; same benign-deferral
    contract as the K-window, tests/test_compaction.py A/Bs the paths).

    The saturated-fog fast drop happens on the candidate list: per-fog
    tail-drop sums become one (F, U*R) membership GEMM instead of the
    classic front-end's (F, T) matmuls (~44x smaller at the bench
    shape; with them went r4's replica-fan-out worker crash).
    """
    tasks, fogs = state.tasks, state.fogs
    T, F, K = spec.task_capacity, spec.n_fogs, spec.window
    U, S = spec.n_users, spec.max_sends_per_user
    R = min(spec.arrival_cands, S)
    i32 = jnp.int32
    f32 = jnp.float32
    fog_alive = state.nodes.alive[U : U + F]

    if views is not None:
        st2 = views["stage2"]
        taf2 = views["t_at_fog2"]
        fog2 = views["fog2"]
        mip2 = views["mips2"]
    else:
        st2 = tasks.stage.reshape(U, S)
        taf2 = tasks.t_at_fog.reshape(U, S)
        fog2 = tasks.fog.reshape(U, S)
        mip2 = tasks.mips_req.reshape(U, S)
    kk = jnp.arange(S, dtype=i32)[None, :]

    # R earliest matured slots per user; argmin returns the FIRST min, so
    # time ties break by slot id exactly like the classic selection.
    # Fused mode halves the reductions per pass: (min, argmin) collapse
    # into one variadic lex-min reduce (ops/queues.row_lexmin — same
    # first-occurrence tie-break) and the two one-hot row sums into one
    # stacked sum (a one-hot sum IS its single element, and fog ids are
    # exact in f32, so both merges are bit-identical).
    if views is None:
        # unfused reference formulation, shared with the TP sharded tick
        cks, cts, cfs, cms, cvs, n_left = _arrival_candidates(
            st2, taf2, fog2, mip2, t1, R
        )
    else:
        m = (st2 == _ST_TASK_INFLIGHT) & (taf2 <= t1)
        cks, cts, cfs, cms, cvs = [], [], [], [], []
        for _ in range(R):
            key = jnp.where(m, taf2, jnp.inf)
            ct, ck = row_lexmin(key)  # (U,), (U,) in ONE reduce
            cv = jnp.isfinite(ct)
            sel = m & (kk == ck[:, None])
            cfm = jnp.sum(
                jnp.where(
                    sel[:, None, :],
                    jnp.stack([fog2.astype(f32), mip2], axis=1),
                    0.0,
                ),
                axis=2,
            )  # (U, 2)
            cf = cfm[:, 0].astype(i32)
            cm = cfm[:, 1]
            cks.append(ck); cts.append(ct); cfs.append(cf)
            cms.append(cm); cvs.append(cv)
            m = m & ~sel
        n_left = jnp.sum(m, dtype=i32)  # matured beyond the per-user cap

    UR = U * R
    cand_k = jnp.stack(cks, axis=1).reshape(UR)  # (UR,) slot index in [0,S)
    cand_t = jnp.stack(cts, axis=1).reshape(UR)
    cand_f = jnp.stack(cfs, axis=1).reshape(UR)
    cand_m = jnp.stack(cms, axis=1).reshape(UR)
    cand_v = jnp.stack(cvs, axis=1).reshape(UR)
    cand_u = jnp.repeat(jnp.arange(U, dtype=i32), R)
    cand_slot = cand_u * S + cand_k  # global task id per candidate

    # ---- saturated-fog fast drop on the candidate list ----------------
    n_fast = jnp.zeros((), i32)
    n_fast_f = jnp.zeros((F,), i32)
    fast_defer = None
    defer_fast = views is not None and _fused_skip_compact(spec)
    if F > 0:
        droppy = (  # (F,) fog can only tail-drop a live arrival
            (fogs.q_len >= spec.queue_capacity)
            & (fogs.current_task != NO_TASK)
            & fog_alive  # so droppy already implies a live destination
        )
        # (F, UR) membership GEMV: droppy per candidate without a
        # serialized (UR,) gather (vmap-collapse-safe, r4)
        memb = (
            cand_f[None, :] == jnp.arange(F, dtype=i32)[:, None]
        ) & cand_v[None, :]  # (F, UR)
        memb_f = memb.astype(f32)
        droppy_c = droppy.astype(f32) @ memb_f > 0.5  # (UR,)
        fast_drop = cand_v & droppy_c
        if defer_fast:
            # fused no-window mode: the tail's merged reduction runs at
            # the candidate width, so the fast-drop count/MIPS sums ride
            # it instead of paying their own (F, UR) @ (UR, 2) GEMM here
            fast_defer = (memb & fast_drop[None, :], fast_drop)
        else:
            # per-fog tail-drop count + busyTime add: one (F, UR) @ (UR, 2)
            rhs = jnp.stack(
                [
                    fast_drop.astype(f32),
                    jnp.where(fast_drop, cand_m, 0.0),
                ],
                axis=1,
            )  # (UR, 2)
            sums = memb_f @ rhs  # (F, 2) f32 exact (counts < 2^24)
            n_fast_f = sums[:, 0].astype(i32)
            svc_fast_f = sums[:, 1] / jnp.maximum(fogs.mips, 1e-9)
            fogs = fogs.replace(
                busy_time=fogs.busy_time + svc_fast_f,
                q_drops=fogs.q_drops + n_fast_f,
            )
            n_fast = jnp.sum(n_fast_f)
        # stage -> DROPPED densely over the (U, S) view (no T-scatter)
        fast2 = fast_drop.reshape(U, R)
        sel_fast = jnp.zeros((U, S), bool)
        for r in range(R):
            sel_fast = sel_fast | (
                (kk == cks[r][:, None]) & fast2[:, r : r + 1]
            )
        st2 = jnp.where(sel_fast, _ST_DROPPED, st2)
        if views is not None:
            views = dict(views)
            views["stage2"] = st2
        else:
            tasks = tasks.replace(stage=st2.reshape(T))
        cand_v = cand_v & ~fast_drop

    # ---- K-window compaction over the candidate list ------------------
    state = state.replace(
        metrics=state.metrics.replace(
            n_deferred=state.metrics.n_deferred + n_left
        )
    )
    if views is not None and _fused_skip_compact(spec):
        # fused no-window mode: with K >= T the window can never
        # overflow and the packed selection order is plain ascending
        # candidate order, so the candidate list IS the window — the
        # whole _compact machinery (two cumsums, first-True argmaxes,
        # the (K, C) row gather) drops out of the tick.  Padding rows
        # keep ``idx = T`` (drop-mode scatters) and every tail
        # reduction is order/shape-independent (integer sums, mins, and
        # the exact-integer busy-MIPS sum of _fused_skip_compact's
        # bound), so results are bit-identical to the compacted path.
        idx = jnp.where(cand_v, cand_slot, T)
        idxc = jnp.minimum(idx, T - 1)
        valid = cand_v
        fog_g, t_af_g, mips_g, user_g = cand_f, cand_t, cand_m, cand_u
        dense_wb = cks  # per-pass slot indices: window row (u, r) owns
        #   slot cks[r][u], so the tail writes back densely, no scatter
    else:
        dense_wb = None
        rot, state = _rot_and_defer(spec, state, cand_v, K)
        idx_c, idxc_c, valid = _compact(cand_v, K, UR, rot)
        if views is not None:
            # one stacked gather per dtype family instead of five
            # (K,)-from-(UR,) gathers; gathers are exact, so this is
            # bit-identical to the per-column form
            fg = jnp.stack([cand_t, cand_m], axis=1)[idxc_c]  # (K, 2)
            ig = jnp.stack(
                [cand_f, cand_u, cand_slot], axis=1
            )[idxc_c]  # (K, 3)
            t_af_g, mips_g = fg[:, 0], fg[:, 1]
            fog_g, user_g, slot_g = ig[:, 0], ig[:, 1], ig[:, 2]
        else:
            fog_g = cand_f[idxc_c]
            t_af_g = cand_t[idxc_c]
            mips_g = cand_m[idxc_c]
            user_g = cand_u[idxc_c]
            slot_g = cand_slot[idxc_c]
        idx = jnp.where(valid, slot_g, T)  # T-space scatter targets
        idxc = jnp.minimum(idx, T - 1)
    return _fog_arrivals_tail(
        spec, state, cache, buf, tasks, fogs,
        idx, idxc, valid, fog_g, t_af_g, mips_g, user_g, n_fast, n_fast_f,
        views=views, fast_defer=fast_defer, dense_wb=dense_wb,
    )


def _fog_arrivals_tail(
    spec: WorldSpec, state: WorldState, cache: LinkCache, buf: TickBuf,
    tasks, fogs, idx: jax.Array, idxc: jax.Array, valid: jax.Array,
    fog_g: jax.Array, t_af_g: jax.Array, mips_g: jax.Array,
    user_g: jax.Array, n_fast: jax.Array, n_fast_f: jax.Array,
    views: Optional[dict] = None,
    fast_defer: Optional[Tuple[jax.Array, jax.Array]] = None,
    dense_wb: Optional[list] = None,
):
    """Shared assignment/queueing tail over the compacted K-window (or,
    in the fused no-window mode, directly over the candidate list —
    ``idx.shape[0]`` is the buffer width either way).

    ``fast_defer``: fused no-window mode only — the front's fast-drop
    ``(membership, drop-mask)`` pair, whose per-fog count/MIPS sums ride
    this tail's one merged reduction instead of their own GEMM."""
    T, F = spec.task_capacity, spec.n_fogs
    W = idx.shape[0]  # window width (spec.window, or U*R when fused)
    U = spec.n_users
    i32 = jnp.int32
    fog_alive = state.nodes.alive[U : U + F]
    fog_gc = jnp.clip(fog_g, 0, F - 1)

    idle = fogs.current_task == NO_TASK
    if views is not None:
        # one stacked (F, 2) gather for the two per-fog predicates the
        # window needs (0/1 integers — exact), instead of two gathers
        ai = jnp.stack(
            [fog_alive.astype(i32), idle.astype(i32)], axis=1
        )[fog_gc]
        alive_g = ai[:, 0] != 0
        idle_g = ai[:, 1] != 0
    else:
        alive_g = fog_alive[fog_gc]
        idle_g = None  # gathered at use (the unfused reference path)
    dead_dst = valid & ~alive_g  # packets to a dead node are lost
    arr = valid & ~dead_dst

    per_fog_arr = _per_fog(arr, fog_g, F)  # (F, W) membership
    # busyTime += this window's service estimates, as (Σ MIPSRequired) /
    # MIPS per fog — the same formulation as the fast-drop path's
    # ``svc_fast_f`` (r6): MIPSRequired values are integers, so the f32
    # sum is EXACT (and reduction-order/shape independent) below 2^24,
    # which is what lets the fused no-window mode reduce over the
    # candidate list instead of the packed window bit-identically.  In
    # fused mode the sum rides the tail's one merged reduction below.
    if views is None:
        mips_sum = jnp.sum(
            jnp.where(per_fog_arr, mips_g[None, :], 0.0), axis=1
        )

    plan = plan_arrivals(
        arr, fog_g, t_af_g, F, idle, per_fog=per_fog_arr,
        fused=views is not None,
    )

    # --- immediate assignment on idle fogs ---
    a_pos = plan.assign_task  # (F,) position in the window buffer or NO_TASK
    assigned = a_pos != NO_TASK
    a_posc = jnp.clip(a_pos, 0, W - 1)
    a_task = jnp.where(assigned, idx[a_posc], NO_TASK)  # global task id
    a_taskc = jnp.clip(a_task, 0, T - 1)
    # service starts when the task arrives — or when the server actually
    # became free, if that was later within this same tick (free_since fix).
    # Fused mode reads the threaded views: the broker wrote t_at_fog THIS
    # tick and the write has not been flushed to the table yet.  In the
    # no-window mode the assigned head's (arrival time, MIPS) are
    # already window columns, so ONE stacked (W, 2) gather at the
    # assigned position replaces the two T-space gathers (the window
    # columns were read from the same views — identical values).
    if dense_wb is not None:
        tm = jnp.stack([t_af_g, mips_g], axis=1)[a_posc]  # (F, 2)
        taf_a, mips_a = tm[:, 0], tm[:, 1]
    elif views is not None:
        taf_a = views["t_at_fog2"].reshape(T)[a_taskc]
        mips_a = views["mips2"].reshape(T)[a_taskc]
    else:
        taf_a = tasks.t_at_fog[a_taskc]
        mips_a = tasks.mips_req[a_taskc]
    t_start = jnp.maximum(taf_a, fogs.free_since)
    svc_a = _svc_time(spec, mips_a, fogs.mips)
    d_fb = cache.d2b[U : U + F]
    d_bu_a = cache.d2b[a_taskc // spec.max_sends_per_user]
    t_ack5 = t_start + d_fb + d_bu_a

    # (no stage scatter here: every assigned head is inside the window,
    # and the window's stage_k write below already maps assigned_row ->
    # RUNNING — the r1-r4 double write was a redundant ~25 us scatter)
    scat_a = jnp.where(assigned, a_task, T)
    if views is not None:
        views = dict(views)
        views["scat"] = {k: list(xs) for k, xs in views["scat"].items()}
        _defer_scatter(
            views, "t_service_start", scat_a, jnp.where(assigned, t_start, 0)
        )
        if not spec.derive_acks:
            _defer_scatter(
                views, "t_ack5", scat_a, jnp.where(assigned, t_ack5, 0)
            )
    else:
        tasks = tasks.replace(
            t_service_start=tasks.t_service_start.at[scat_a].set(
                jnp.where(assigned, t_start, 0), mode="drop"
            ),
        )
        if not spec.derive_acks:
            tasks = tasks.replace(
                t_ack5=tasks.t_ack5.at[scat_a].set(
                    jnp.where(assigned, t_ack5, 0), mode="drop"
                ),
            )
    fogs = fogs.replace(
        current_task=jnp.where(assigned, a_task, fogs.current_task),
        busy_until=jnp.where(assigned, t_start + svc_a, fogs.busy_until),
    )

    # --- queue the rest (rank shifts by 1 where the head got assigned) ---
    if views is not None:
        # stacked (F, 2) gather for the assignment predicates (exact)
        aa = jnp.stack([assigned.astype(i32), a_task], axis=1)[fog_gc]
        assigned_g = aa[:, 0] != 0
        a_task_g = aa[:, 1]
        got_head = assigned_g & idle_g
    else:
        assigned_g = assigned[fog_gc]
        a_task_g = a_task[fog_gc]
        got_head = assigned_g & idle[fog_gc]
    eff_rank = jnp.where(arr, plan.rank - got_head.astype(i32), -1)
    to_queue = arr & (eff_rank >= 0) & (idx != a_task_g)
    if views is not None:
        # scatter half only: added/dropped counts join the merged
        # reduction below (same integers as batched_enqueue's)
        queue, enq_ok = enqueue_scatter(
            fogs.queue, fogs.q_head, fogs.q_len, to_queue, fog_g,
            eff_rank, idx, stacked=True,
        )
        q_len = dropped = None  # from the merged reduction
    else:
        queue, q_len, enq_ok, dropped = batched_enqueue(
            fogs.queue, fogs.q_head, fogs.q_len, to_queue, fog_g,
            eff_rank, idx,
        )
    d_bu_q = cache.d2b[user_g]
    d_fb_q = d_fb[fog_gc]
    # no gather needed for the keep-stage case: every valid row was
    # TASK_INFLIGHT by mask construction; the assigned head gets its
    # RUNNING stage HERE (assigned_row branch) — this is its only write
    assigned_row = arr & (idx == a_task_g)
    stage_k = jnp.where(
        enq_ok,
        _ST_QUEUED,
        jnp.where(
            (to_queue & ~enq_ok) | dead_dst,
            _ST_DROPPED,
            jnp.where(
                assigned_row,
                _ST_RUNNING,
                _ST_TASK_INFLIGHT,
            ),
        ),
    )
    if views is not None and dense_wb is not None:
        # fused no-window mode: window row (u, r) owns slot
        # dense_wb[r][u] of the (U, S) view, so the window's column
        # writes map back as R masked selects — the whole T-space
        # scatter chain of the window disappears.  Same rows (idx !=
        # sentinel ⟺ valid), same values as the scatter form.
        R_wb = len(dense_wb)
        Uw = spec.n_users
        kk_wb = jnp.arange(spec.max_sends_per_user, dtype=i32)[None, :]
        stage_k2 = stage_k.reshape(Uw, R_wb)
        valid2 = valid.reshape(Uw, R_wb)
        tqv2 = jnp.where(enq_ok, t_af_g, jnp.inf).reshape(Uw, R_wb)
        if not spec.derive_acks:
            a4v2 = jnp.where(
                enq_ok, t_af_g + d_fb_q + d_bu_q, jnp.inf
            ).reshape(Uw, R_wb)
        for r, ckr in enumerate(dense_wb):
            wsel = (kk_wb == ckr[:, None]) & valid2[:, r : r + 1]
            views["stage2"] = jnp.where(
                wsel, stage_k2[:, r : r + 1], views["stage2"]
            )
            views["t_q_enter2"] = jnp.where(
                wsel, tqv2[:, r : r + 1], views["t_q_enter2"]
            )
            if not spec.derive_acks:
                views["t_ack4_queued2"] = jnp.where(
                    wsel, a4v2[:, r : r + 1], views["t_ack4_queued2"]
                )
    elif views is not None:
        _defer_scatter(views, "stage", idx, stage_k)
        _defer_scatter(
            views, "t_q_enter", idx, jnp.where(enq_ok, t_af_g, jnp.inf)
        )
        if not spec.derive_acks:
            _defer_scatter(
                views, "t_ack4_queued", idx,
                jnp.where(enq_ok, t_af_g + d_fb_q + d_bu_q, jnp.inf),
            )
    else:
        tasks = tasks.replace(
            stage=tasks.stage.at[idx].set(stage_k, mode="drop"),
            t_q_enter=tasks.t_q_enter.at[idx].set(
                jnp.where(enq_ok, t_af_g, jnp.inf), mode="drop"
            ),
        )
        if not spec.derive_acks:
            tasks = tasks.replace(
                t_ack4_queued=tasks.t_ack4_queued.at[idx].set(
                    jnp.where(enq_ok, t_af_g + d_fb_q + d_bu_q, jnp.inf),
                    mode="drop",
                ),
            )
    # every live arrival is a fog rx + one ack (assigned/queued) relayed
    # through the broker to the user
    acked = (assigned_g & (idx == a_task_g)) | enq_ok
    f32 = jnp.float32
    if views is not None:
        # THE merged tail reduction: every per-fog and scalar sum of the
        # phase — the scalar counters, the busy-MIPS sum, the arrival
        # counts, the enqueue added/dropped counts, and (no-window mode)
        # the front's deferred fast-drop sums — rides ONE (C, W) f32 row
        # reduction.  Rows reduce independently and every count is an
        # exact f32 integer, so each slice is bit-identical to its
        # standalone reduce in the unfused path.
        scalar_rows = [to_queue & ~enq_ok, dead_dst, acked]
        if fast_defer is not None:
            fast_memb, fast_drop = fast_defer
            scalar_rows.append(fast_drop)
        groups = [r.astype(f32)[None, :] for r in scalar_rows] + [
            jnp.where(per_fog_arr, mips_g[None, :], 0.0),
            per_fog_arr.astype(f32),
            (per_fog_arr & enq_ok[None, :]).astype(f32),
            (per_fog_arr & (to_queue & ~enq_ok)[None, :]).astype(f32),
        ]
        if fast_defer is not None:
            groups += [
                fast_memb.astype(f32),
                jnp.where(fast_memb, mips_g[None, :], 0.0),
            ]
        red = jnp.sum(jnp.concatenate(groups, axis=0), axis=1)
        s0 = len(scalar_rows)
        sums = red[:3].astype(i32)
        mips_sum = red[s0 : s0 + F]
        counts = red[s0 + F : s0 + 2 * F].astype(i32)
        added = red[s0 + 2 * F : s0 + 3 * F].astype(i32)
        dropped = red[s0 + 3 * F : s0 + 4 * F].astype(i32)
        if fast_defer is not None:
            n_fast = red[3].astype(i32)
            n_fast_f = red[s0 + 4 * F : s0 + 5 * F].astype(i32)
            svc_fast_f = red[s0 + 5 * F :] / jnp.maximum(fogs.mips, 1e-9)
        q_len = fogs.q_len + added
        arr_per_fog = counts + n_fast_f
    else:
        sums = jnp.sum(
            jnp.stack([to_queue & ~enq_ok, dead_dst, acked]).astype(i32),
            axis=1,
        )
        # fast-dropped arrivals still reached (and were answered by) the
        # fog exactly like a compacted enqueue-failure would have been
        arr_per_fog = jnp.sum(per_fog_arr, axis=1, dtype=i32) + n_fast_f
    add_busy = mips_sum / jnp.maximum(fogs.mips, 1e-9)
    if fast_defer is not None:
        # deferred fast-drop bookkeeping lands here, in the SAME order
        # the unfused path applies it (fast-drop add, then window add —
        # f32 addition order preserved bit-for-bit)
        busy_time = fogs.busy_time + svc_fast_f + add_busy
        q_drops = fogs.q_drops + n_fast_f + dropped
    else:  # front already applied any fast-drop sums
        busy_time = fogs.busy_time + add_busy
        q_drops = fogs.q_drops + dropped
    fogs = fogs.replace(
        queue=queue, q_len=q_len, q_drops=q_drops, busy_time=busy_time,
    )
    metrics = state.metrics.replace(
        n_dropped=state.metrics.n_dropped + sums[0] + sums[1] + n_fast
    )
    buf = buf._replace(
        tx_f=buf.tx_f + arr_per_fog,
        rx_f=buf.rx_f + arr_per_fog,
        tx_b=buf.tx_b + sums[2],
        rx_b=buf.rx_b + sums[2],
    )
    defer_counts = views is not None and views.get(
        "defer_host_counts", False
    )
    if views is not None and dense_wb is not None:
        # no-window mode: window rows are user-major (u, r), so the
        # per-user ack counts are a row sum — no scatter at all
        buf = buf._replace(
            rx_u=buf.rx_u + jnp.sum(
                acked.reshape(spec.n_users, len(dense_wb)), axis=1,
                dtype=i32,
            )
        )
    elif defer_counts:
        # telemetry-off fused tick: the per-user ack scatter-add joins
        # the flush's one merged rx_u scatter (int adds commute)
        views["rx_u"] = list(views.get("rx_u", ()))
        views["rx_u"].append((user_g, acked.astype(i32)))
    else:
        buf = buf._replace(
            rx_u=buf.rx_u.at[user_g].add(acked.astype(i32), mode="drop")
        )
    state = state.replace(tasks=tasks, fogs=fogs, metrics=metrics)
    if views is not None:
        return state, buf, views
    return state, buf


# ----------------------------------------------------------------------
# v1/v2 POOL fog model (ComputeBrokerApp2.cc:246-320)
# ----------------------------------------------------------------------

def _phase_pool_completions(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array,
) -> Tuple[WorldState, TickBuf]:
    """Pool tasks whose requiredTime expired release their MIPS.

    ``releaseResource`` (``ComputeBrokerApp2.cc:222-245``): pool += MIPS,
    status-6 Puback to the broker, which relays it to the client and erases
    the request (``BrokerBaseApp2.cc:143-153``).  The reference releases at
    most one expired task per timer tick (shared-selfMsg quirk, SURVEY App. B
    item 8); the batched engine releases all expired tasks — the exact timer
    dance lives in the C++ parity core, and the deviation is bounded by one
    0.01 s advert period per extra concurrent expiry.

    v1 fogs ack completion with FognetMsgTaskAck, which the v1 broker logs
    and drops (``BrokerBaseApp.cc:142-147``) — the client never learns;
    ``app_gen == 1`` therefore records no t_ack6.
    """
    tasks = state.tasks
    T, F, K = spec.task_capacity, spec.n_fogs, spec.window
    i32 = jnp.int32
    comp_full = (
        (tasks.stage == _ST_RUNNING)
        & (tasks.fog >= 0)
        & (tasks.t_complete <= t1)
    )
    rot, state = _rot_and_defer(spec, state, comp_full, K)
    idx, idxc, valid = _compact(comp_full, K, T, rot)
    fog_g = jnp.clip(tasks.fog[idxc], 0, F - 1)
    mips_g = tasks.mips_req[idxc]
    user_g = idxc // spec.max_sends_per_user
    t_done = tasks.t_complete[idxc]

    per_fog_v = _per_fog(valid, fog_g, F)  # (F, K)
    pool_avail = state.fogs.pool_avail + jnp.sum(
        jnp.where(per_fog_v, mips_g[None, :], 0.0), axis=1
    )

    d_fb = cache.d2b[fog_g + spec.n_users]
    d_bu = cache.d2b[user_g]
    t_ack6 = t_done + d_fb + d_bu

    tasks = tasks.replace(
        stage=tasks.stage.at[idx].set(_ST_DONE, mode="drop"),
    )
    if spec.app_gen >= 2:
        tasks = tasks.replace(
            t_ack6=tasks.t_ack6.at[idx].set(
                jnp.where(valid, t_ack6, jnp.inf), mode="drop"
            ),
        )
    n_comp = jnp.sum(valid.astype(i32))
    metrics = state.metrics.replace(n_completed=state.metrics.n_completed + n_comp)
    buf = buf._replace(
        tx_f=buf.tx_f + jnp.sum(per_fog_v, axis=1, dtype=i32),
        rx_b=buf.rx_b + n_comp,
    )
    if spec.app_gen >= 2:
        buf = buf._replace(
            tx_b=buf.tx_b + n_comp,
            rx_u=buf.rx_u.at[user_g].add(valid.astype(i32), mode="drop"),
        )
    return (
        state.replace(
            tasks=tasks, fogs=state.fogs.replace(pool_avail=pool_avail),
            metrics=metrics,
        ),
        buf,
    )


def _phase_pool_arrivals(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array,
) -> Tuple[WorldState, TickBuf]:
    """Pool fogs accept/reject arriving tasks against their MIPS pool.

    ``ComputeBrokerApp2.cc:258-310``: accept iff ``requiredMIPS < MIPS``
    (strict), pool -= MIPS, expiry at ``now + requiredTime``; else TaskAck
    (status=false) which every broker generation ignores → Stage.REJECTED.

    Same-tick arrivals at one fog are pool-checked strictly in arrival
    order: rank r of each fog's batch is processed in sub-phase r (unrolled
    ``spec.pool_phases`` times — exact up to that depth; deeper arrivals
    stay TASK_INFLIGHT and are re-ranked next tick).
    """
    tasks = state.tasks
    T, F, K = spec.task_capacity, spec.n_fogs, spec.window
    U = spec.n_users
    i32 = jnp.int32
    fog_alive = state.nodes.alive[U : U + F]

    arr_full = (tasks.stage == _ST_TASK_INFLIGHT) & (
        tasks.t_at_fog <= t1
    )
    rot, state = _rot_and_defer(spec, state, arr_full, K)
    idx, idxc, valid = _compact(arr_full, K, T, rot)
    fog_g = tasks.fog[idxc]
    fog_gc = jnp.clip(fog_g, 0, F - 1)
    t_af_g = tasks.t_at_fog[idxc]
    mips_g = tasks.mips_req[idxc]

    dead_dst = valid & ~fog_alive[fog_gc]
    arr = valid & ~dead_dst
    per_fog_arr = _per_fog(arr, fog_g, F)  # (F, K)
    plan = plan_arrivals(
        arr, fog_g, t_af_g, F, jnp.ones((F,), bool), per_fog=per_fog_arr
    )

    pool = state.fogs.pool_avail
    accept = jnp.zeros((K,), bool)
    reject = jnp.zeros((K,), bool)
    for r in range(spec.pool_phases):
        sel = arr & (plan.rank == r)
        sel_f = per_fog_arr & (plan.rank == r)[None, :]  # (F, K)
        req_f = jnp.sum(jnp.where(sel_f, mips_g[None, :], 0.0), axis=1)
        has_f = jnp.any(sel_f, axis=1)
        acc_f = has_f & (req_f < pool)  # strict <, ComputeBrokerApp2.cc:269
        pool = pool - jnp.where(acc_f, req_f, 0.0)
        accept = accept | (sel & acc_f[fog_gc])
        reject = reject | (sel & has_f[fog_gc] & ~acc_f[fog_gc])

    stage_k = jnp.where(
        accept,
        _ST_RUNNING,
        jnp.where(
            reject,
            _ST_REJECTED,
            jnp.where(dead_dst, _ST_DROPPED, tasks.stage[idxc]),
        ),
    )
    tasks = tasks.replace(
        stage=tasks.stage.at[idx].set(stage_k, mode="drop"),
        t_service_start=tasks.t_service_start.at[idx].set(
            jnp.where(accept, t_af_g, jnp.inf), mode="drop"
        ),
        t_complete=tasks.t_complete.at[idx].set(
            jnp.where(accept, t_af_g + spec.required_time, jnp.inf), mode="drop"
        ),
    )
    fogs = state.fogs.replace(pool_avail=pool)
    metrics = state.metrics.replace(
        n_rejected=state.metrics.n_rejected + jnp.sum(reject.astype(i32)),
        n_dropped=state.metrics.n_dropped + jnp.sum(dead_dst.astype(i32)),
    )
    # arrivals are fog rx; each decided arrival sends a TaskAck to the broker
    decided = accept | reject
    buf = buf._replace(
        tx_f=buf.tx_f
        + jnp.sum(per_fog_arr & decided[None, :], axis=1, dtype=i32),
        rx_f=buf.rx_f + jnp.sum(per_fog_arr, axis=1, dtype=i32),
        rx_b=buf.rx_b + jnp.sum(decided.astype(i32)),
    )
    return (
        state.replace(tasks=tasks, fogs=fogs, metrics=metrics),
        buf,
    )


def _phase_local_completions(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array,
) -> Tuple[WorldState, TickBuf]:
    """Broker-local tasks expire: status-6 straight to the client.

    ``BrokerBaseApp.cc:369-394`` releaseResource: pool refund + status-6
    Puback directly to the stored client address.  The refund is gated on
    ``not bug_compat.local_pool_leak`` — the reference never actually stores
    the request (``:208`` commented out), so its pool only ever shrinks.
    """
    tasks = state.tasks
    T, K = spec.task_capacity, spec.window
    i32 = jnp.int32
    comp_full = (tasks.stage == _ST_LOCAL_RUN) & (
        tasks.t_complete <= t1
    )
    rot, state = _rot_and_defer(spec, state, comp_full, K)
    idx, idxc, valid = _compact(comp_full, K, T, rot)
    user_g = idxc // spec.max_sends_per_user
    t_done = tasks.t_complete[idxc]
    d_bu = cache.d2b[user_g]
    tasks = tasks.replace(
        stage=tasks.stage.at[idx].set(_ST_DONE, mode="drop"),
        t_ack6=tasks.t_ack6.at[idx].set(
            jnp.where(valid, t_done + d_bu, jnp.inf), mode="drop"
        ),
    )
    b = state.broker
    if not spec.bug_compat.local_pool_leak:
        b = b.replace(
            local_pool=b.local_pool
            + jnp.sum(jnp.where(valid, tasks.mips_req[idxc], 0.0))
        )
    n_comp = jnp.sum(valid.astype(i32))
    metrics = state.metrics.replace(n_completed=state.metrics.n_completed + n_comp)
    buf = buf._replace(
        tx_b=buf.tx_b + n_comp,
        rx_u=buf.rx_u.at[user_g].add(valid.astype(i32), mode="drop"),
    )
    return (
        state.replace(tasks=tasks, broker=b, metrics=metrics),
        buf,
    )


def _phase_chaos(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t0: jax.Array, t1: jax.Array,
    dyn: Optional[DynSpec] = None,
) -> Tuple[WorldState, TickBuf]:
    """Fault injection: fog crash/recover lifecycle + in-flight sweep.

    Runs FIRST among the protocol phases (after the tick's
    association/delay cache is built, before any dispatch), so an
    outage scheduled inside ``[t0, t1)`` is already reflected in the
    ``nodes.alive`` mask every dispatch/arrival/completion phase of
    this tick respects.  Three jobs:

    * advance the deterministic outage schedules
      (:func:`fognetsimpp_tpu.chaos.faults.step_lifecycle`) and write
      the per-fog up mask into ``nodes.alive``;
    * sweep in-flight work off crashed fogs — ``spec.chaos_mode``
      chooses LOSE (tasks drop into :data:`Stage.LOST`, counted in
      ``ChaosState.n_lost_crash``) or RE-OFFLOAD (tasks bounce back to
      the broker as fresh ``PUB_INFLIGHT`` arrivals at
      ``crash_time + d(fog, broker)``, re-decided through the
      established K-window contract, with a bounded per-task retry
      budget; exhausted tasks are lost and counted separately).  The
      crashed fog's server/queue/pool state is wiped (a restarted node
      boots clean) and a fresh advertisement is put in flight at
      recovery so the broker's view converges;
    * resolve the learn-side credit of every swept decision
      exactly-once as a zero-reward penalty
      (:func:`fognetsimpp_tpu.learn.rewards.penalize_counts`) — lost
      tasks never ack, so without this their picks would dangle as
      unresolved optimism on a dead arm.

    Only traced when ``spec.chaos`` is on; chaos-off worlds stay
    bit-exact (tests/test_chaos.py A/Bs it).
    """
    U, F, T = spec.n_users, spec.n_fogs, spec.task_capacity
    i32 = jnp.int32
    f32 = jnp.float32
    tasks = state.tasks
    dv = dyn if dyn is not None else dyn_of(spec)

    up_prev = state.nodes.alive[U : U + F]
    ch, up_new, crashed, recovered, crash_t, recover_t = step_lifecycle(
        spec, state.chaos, up_prev, t0, t1, dyn=dv
    )
    nodes = state.nodes.replace(
        alive=state.nodes.alive.at[U : U + F].set(up_new)
    )

    # ---- in-flight sweep over this tick's crash edges -----------------
    # (T,)-gathers from (F,) tables: fine on the single-device paths
    # this subsystem covers (the TP/fleet runners gate chaos off — a
    # gather here serializes under collapsed vmap fan-out, r4)
    has_fog = tasks.fog >= 0
    fog_c = jnp.clip(tasks.fog, 0, F - 1)
    st = tasks.stage
    live = (
        (st == _ST_TASK_INFLIGHT)
        | (st == _ST_QUEUED)
        | (st == _ST_RUNNING)
    )
    swept = has_fog & live & crashed[fog_c]
    t_edge = crash_t[fog_c]

    # learn-side exactly-once penalty on the picked (now dead) arms —
    # booked BEFORE the fog column is cleared.  f32 scatter-add counts
    # stay exact integers: learn-active specs bound task_capacity
    # < 2^24 (learn/rewards._credit_counts_exact; hloaudit A4).
    learn = state.learn
    if spec.learn_active:
        cnt_f = jnp.zeros((F,), f32).at[
            jnp.where(swept, fog_c, F)
        ].add(1.0, mode="drop")
        learn = penalize_counts(learn, cnt_f)

    reoffload = spec.chaos_mode == int(ChaosMode.REOFFLOAD)
    if reoffload:
        retry_new = ch.retry + swept.astype(jnp.int8)
        exhausted = swept & (
            retry_new.astype(i32) > dv.chaos_max_retries
        )
        bounce = swept & ~exhausted
        terminal = exhausted
        # bounce: back to the broker as a fresh publish arrival — the
        # fog->broker hop models the orphan-detection round trip
        d_fb_t = cache.d2b[U + fog_c]
        tasks = tasks.replace(
            stage=jnp.where(
                bounce, _ST_PUB_INFLIGHT,
                jnp.where(exhausted, _ST_LOST, st),
            ),
            t_at_broker=jnp.where(
                bounce, t_edge + d_fb_t, tasks.t_at_broker
            ),
            fog=jnp.where(bounce, NO_TASK, tasks.fog),
            t_at_fog=jnp.where(bounce, jnp.inf, tasks.t_at_fog),
            t_q_enter=jnp.where(bounce, jnp.inf, tasks.t_q_enter),
            t_service_start=jnp.where(
                bounce, jnp.inf, tasks.t_service_start
            ),
            t_complete=jnp.where(swept, jnp.inf, tasks.t_complete),
        )
        ch = ch.replace(retry=retry_new)
    else:
        bounce = jnp.zeros((T,), bool)
        exhausted = jnp.zeros((T,), bool)
        terminal = swept
        # LOSE: the fog column is kept as provenance (which arm the
        # task died on — the timeline and the learn penalty both read
        # it); stage LOST is terminal, so no phase ever revives it
        tasks = tasks.replace(
            stage=jnp.where(swept, _ST_LOST, st),
            t_complete=jnp.where(swept, jnp.inf, tasks.t_complete),
        )
    if spec.learn_active:
        # terminal rows resolve here, exactly once; bounced rows keep
        # credited=0 and resolve at their eventual ack on the new arm
        learn = learn.replace(
            credited=jnp.maximum(
                learn.credited, terminal.astype(jnp.int8)
            )
        )

    # ---- crashed fogs reboot clean; recovered fogs re-advertise -------
    fogs = state.fogs
    fogs = fogs.replace(
        current_task=jnp.where(crashed, NO_TASK, fogs.current_task),
        busy_until=jnp.where(crashed, jnp.inf, fogs.busy_until),
        busy_time=jnp.where(crashed, 0.0, fogs.busy_time),
        free_since=jnp.where(recovered, recover_t, fogs.free_since),
        queue=jnp.where(crashed[:, None], NO_TASK, fogs.queue),
        q_head=jnp.where(crashed, 0, fogs.q_head),
        q_len=jnp.where(crashed, 0, fogs.q_len),
        pool_avail=jnp.where(crashed, fogs.mips, fogs.pool_avail),
    )
    b = state.broker
    d_fb = cache.d2b[U : U + F]
    adv_mips = (
        fogs.pool_avail
        if spec.fog_model == int(FogModel.POOL)
        else fogs.mips
    )
    b = b.replace(
        adv_val_mips=jnp.where(recovered, adv_mips, b.adv_val_mips),
        adv_val_busy=jnp.where(recovered, 0.0, b.adv_val_busy),
        adv_arrive_t=jnp.where(
            recovered, recover_t + d_fb, b.adv_arrive_t
        ),
    )

    # one stacked reduction for the sweep counters
    sums = jnp.sum(
        jnp.stack([bounce, exhausted, swept]).astype(i32), axis=1
    )
    if reoffload:
        ch = ch.replace(
            n_reoffloaded=ch.n_reoffloaded + sums[0],
            n_retry_exhausted=ch.n_retry_exhausted + sums[1],
        )
    else:
        ch = ch.replace(n_lost_crash=ch.n_lost_crash + sums[2])
    # message accounting: each bounce is one orphan notice reaching the
    # broker; each recovery puts one advertisement on the wire
    buf = buf._replace(
        rx_b=buf.rx_b + sums[0],
        tx_f=buf.tx_f + recovered.astype(i32),
    )
    return (
        state.replace(
            nodes=nodes, tasks=tasks, fogs=fogs, broker=b,
            learn=learn, chaos=ch,
        ),
        buf,
    )


def _hier_migrate_on(spec: WorldSpec) -> bool:
    """Static gate for the broker↔broker migrate phase: a federated
    world whose migration policy is not NEVER.  NEVER worlds keep the
    domain-masked decide phases but trace no migration machinery (the
    isolated-domains baseline the bench compares against)."""
    return spec.hier_active and spec.hier_policy != int(HierPolicy.NEVER)


def _phase_broker_migrate(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t0: jax.Array, t1: jax.Array,
    views: Optional[dict] = None,
    dyn: Optional[DynSpec] = None,
):
    """Federated hierarchy: broker↔broker task migration (hier/).

    Runs after the spawn phase and BEFORE the decide phase of the same
    tick (threading the fused ``(U, S)`` register views when the fused
    front-end is live), so a publish maturing at a saturated or dead
    domain THIS tick can leave before the local broker decides — or
    NO_RESOURCEs — it.  Three jobs:

    * refresh each broker's AGED view of peer load summaries: entry
      ``(b, p)`` re-reads peer p's live busy fraction only when its
      ``rtt[b, p]`` exchange period has elapsed — federation sees stale
      data exactly like fogs do through in-flight advertisements (a
      freshly-dead peer can still look attractive for one RTT, which is
      the staleness FogMQ's distributed brokers actually pay);
    * fire the migration policy per broker (THRESHOLD on the local busy
      fraction, LEAST_LOADED against the aged peer minimum; dead
      domains — no registered, up fog — always want out) and re-home
      every matured ``PUB_INFLIGHT`` task of a firing broker to the
      least-loaded peer: ``task_broker`` restamps, ``t_at_broker``
      advances by the inter-broker hop's RTT, and the task re-offers
      through the established K-window arrival contract when it
      matures at the new broker;
    * enforce the bounded hop budget: a matured task in a DEAD domain
      that can no longer move (``hops >= hier_max_hops``, or every
      peer domain looks dead/fogless) becomes the terminal
      :data:`Stage.HOP_EXHAUSTED`, counted in
      ``HierState.n_hop_exhausted`` — the conservation identity's new
      bucket.  Saturated-but-alive domains never exhaust: their tasks
      simply stay and decide locally.

    Deterministic (no PRNG consumption: destinations are argmin picks,
    ties to the lowest broker id) and only traced when
    :func:`_hier_migrate_on` — NEVER/single-broker worlds are
    bit-exact without it (tests/test_hier.py).
    """
    U, F, T, B = spec.n_users, spec.n_fogs, spec.task_capacity, spec.n_brokers
    i32, f32 = jnp.int32, jnp.float32
    dv = dyn if dyn is not None else dyn_of(spec)
    hier, tasks, b = state.hier, state.tasks, state.broker

    bid = jnp.arange(B, dtype=i32)
    owned = hier.fog_broker[None, :] == bid[:, None]  # (B, F)
    fog_alive = state.nodes.alive[U : U + F]
    # "usable" mirrors the decide phases' reg_eff exactly: a domain is
    # dead here iff its broker's decide phase would find no candidate
    usable = b.registered & fog_alive if spec.chaos else b.registered
    if spec.fog_model == int(FogModel.POOL):
        busy = state.fogs.pool_avail < state.fogs.mips
    else:
        busy = state.fogs.current_task != NO_TASK
    up_b = owned & usable[None, :]
    n_up = jnp.sum(up_b, axis=1)  # (B,)
    n_busy = jnp.sum(up_b & busy[None, :], axis=1)
    dead = n_up == 0
    load = jnp.where(
        dead, jnp.inf,
        n_busy.astype(f32) / jnp.maximum(n_up.astype(f32), 1.0),
    )  # (B,) live local busy fraction; a dead domain repels peers

    # ---- aged peer-view exchange (staleness = inter-broker RTT) -------
    # (jnp view of the RTT leaf: on the dyn=None static path it is a
    # host np constant, which traced indexing below cannot consume raw)
    rtt_m = jnp.asarray(dv.hier_rtt)
    due = t1 >= hier.peer_t  # (B, B)
    peer_load = jnp.where(due, load[None, :], hier.peer_load)
    peer_t = jnp.where(due, t1 + rtt_m, hier.peer_t)

    # ---- destination: least-loaded peer by the aged view --------------
    has_fog = jnp.sum(owned, axis=1) > 0  # (B,) domains with owned fogs
    cand = (~jnp.eye(B, dtype=bool)) & has_fog[None, :]
    score = jnp.where(cand, peer_load, jnp.inf)  # (B, B)
    dest = jnp.argmin(score, axis=1).astype(i32)  # ties → lowest id
    has_dest = jnp.isfinite(jnp.min(score, axis=1))

    # ---- fire policy per broker ---------------------------------------
    if spec.hier_policy == int(HierPolicy.THRESHOLD):
        fire = dead | (load > dv.hier_threshold)
    else:  # LEAST_LOADED
        fire = dead | (jnp.min(score, axis=1) < load)

    # ---- per-task re-homing (elementwise over the (U, S) view) --------
    S = spec.max_sends_per_user
    if views is not None:
        st2, tab2 = views["stage2"], views["t_at_broker2"]
    else:
        st2 = tasks.stage.reshape(U, S)
        tab2 = tasks.t_at_broker.reshape(U, S)
    matured2 = (st2 == _ST_PUB_INFLIGHT) & (tab2 <= t1)
    tb = jnp.clip(hier.task_broker, 0, B - 1)  # (T,)
    tb2 = tb.reshape(U, S)
    hops_ok2 = (hier.hops.astype(i32) < dv.hier_max_hops).reshape(U, S)
    mig2 = matured2 & fire[tb2] & has_dest[tb2] & hops_ok2
    # exhaustion is a DEAD-domain terminal only: the task can never be
    # served where it sits and cannot move
    exhaust2 = matured2 & dead[tb2] & ~(has_dest[tb2] & hops_ok2)

    dst2 = dest[tb2]  # (U, S)
    rtt_hop2 = rtt_m[tb2, dst2]  # (U, S) src→dst hop latency
    new_st2 = jnp.where(exhaust2, _ST_HOP_EXHAUSTED, st2)
    new_tab2 = jnp.where(mig2, tab2 + rtt_hop2, tab2)
    if views is not None:
        views = dict(views)
        views["stage2"] = new_st2
        views["t_at_broker2"] = new_tab2
    else:
        tasks = tasks.replace(
            stage=new_st2.reshape(T),
            t_at_broker=new_tab2.reshape(T),
        )
    mig = mig2.reshape(T)
    dst_t = dst2.reshape(T)
    # one (B, T) membership reduce per direction instead of scatter-adds
    out_b = jnp.sum(
        (tb[None, :] == bid[:, None]) & mig[None, :], axis=1, dtype=i32
    )
    in_b = jnp.sum(
        (dst_t[None, :] == bid[:, None]) & mig[None, :], axis=1, dtype=i32
    )
    sums = jnp.sum(
        jnp.stack([mig2, exhaust2]).astype(i32), axis=(1, 2)
    )
    hier = hier.replace(
        task_broker=jnp.where(mig, dst_t, hier.task_broker),
        hops=hier.hops + mig.astype(jnp.int8),
        peer_load=peer_load,
        peer_t=peer_t,
        mig_out=hier.mig_out + out_b,
        mig_in=hier.mig_in + in_b,
        n_migrated=hier.n_migrated + sums[0],
        n_hop_exhausted=hier.n_hop_exhausted + sums[1],
    )
    # message accounting: each migration is one broker→broker task
    # forward over the federation link (the one physical broker node
    # carries both ends)
    buf = buf._replace(tx_b=buf.tx_b + sums[0], rx_b=buf.rx_b + sums[0])
    state = state.replace(tasks=tasks, hier=hier)
    if views is not None:
        return state, buf, views
    return state, buf


def _phase_learn_credit(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array, dyn: Optional[DynSpec] = None,
) -> Tuple[WorldState, TickBuf]:
    """Delayed-reward credit assignment for the bandit schedulers.

    A decision earns its reward only when the status-6 "performed" ack
    reaches the client: each tick this phase finds the DONE tasks whose
    ``t_ack6`` has passed and is not yet credited, and folds
    ``reward = -latency`` (bounded via learn/rewards.py) into the arm
    statistics of the fog picked at publish time (``tasks.fog``) — not
    the fog that would be picked now.  The per-task ``credited`` flag
    makes the credit exactly-once; rows beyond this tick's K-window
    simply credit a later tick (the flag persists), so no reward is ever
    lost or double-counted.  The discounted-UCB statistics decay once
    per tick here whether or not anything credits (D-UCB's clock is
    time, not events).
    """
    tasks, learn = state.tasks, state.learn
    T, F, K = spec.task_capacity, spec.n_fogs, spec.window
    i32 = jnp.int32
    dv = dyn if dyn is not None else dyn_of(spec)

    due = (
        (tasks.stage == _ST_DONE)
        & (learn.credited == 0)
        & (tasks.fog >= 0)
        & (tasks.t_ack6 <= t1)
    )
    # same tick-keyed scan-origin rotation as the decision phases (so a
    # sustained overflow cannot starve high-id tasks of credit), but no
    # n_deferred accounting: that gauge tracks *decision* backlog
    if K < T:
        rot = (
            (state.tick.astype(jnp.uint32) * jnp.uint32(2654435761))
            % jnp.uint32(T)
        ).astype(i32)
    else:
        rot = None
    idx, idxc, valid = _compact(due, K, T, rot)
    fog_g = tasks.fog[idxc]  # picked-at-publish-time fog (provenance)
    # Credit-observation origin: publish time — except for a task the
    # chaos subsystem re-offloaded (retry > 0): its t_at_broker was
    # restamped at the bounce, and measuring from broker arrival
    # charges each DECISION only its own leg — the rescue arm is not
    # blamed for the crashed detour (the crashed pick already resolved
    # as a zero-reward penalty in _phase_chaos).  Per-task, keyed on
    # the retry column, so an inert chaos-on world (zero sweeps) stays
    # bit-exact; the regret harness's reported task latency stays
    # publish -> ack either way (runtime/signals.py).
    lat0 = tasks.t_ack6[idxc] - tasks.t_create[idxc]
    if spec.chaos:
        lat0 = jnp.where(
            state.chaos.retry[idxc] > 0,
            tasks.t_ack6[idxc] - tasks.t_at_broker[idxc],
            lat0,
        )
    if spec.hier_active:
        # a MIGRATED task's t_at_broker was restamped at each hop: the
        # rescuing broker's pick is credited with its own leg only, not
        # the federation detour — the chaos-retry restamp discipline,
        # keyed per task on the hop column so hop-free worlds stay
        # bit-exact
        lat0 = jnp.where(
            state.hier.hops[idxc] > 0,
            tasks.t_ack6[idxc] - tasks.t_at_broker[idxc],
            lat0,
        )
    lat = jnp.where(valid, lat0, 0.0)
    pick_p_g = learn.pick_p[idxc]
    memb = _per_fog(valid, fog_g, F)  # (F, K)
    learn = credit_batch(
        learn, valid, memb, lat, pick_p_g,
        spec.n_fogs, dv.learn_discount, dv.learn_reward_scale,
    )
    learn = learn.replace(
        credited=learn.credited.at[idx].set(jnp.int8(1), mode="drop")
    )
    return state.replace(learn=learn), buf


def _phase_latency_hist(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array,
) -> Tuple[WorldState, TickBuf]:
    """Streaming latency-histogram accumulation (telemetry/health.py).

    Folds every task whose status-6 ack has reached the client by
    ``t1`` — and that the persistent ``lat_seen`` flag has not counted
    yet — into the per-fog log-bucket histogram riding
    :class:`TelemetryState`.  Statically gated on
    ``spec.telemetry_hist``: worlds without the health plane trace none
    of this and stay bit-exact (tests/test_health.py).  Pure carry
    endomorphism, so it rides the scan and the fleet ``vmap``
    unchanged.
    """
    telem = accumulate_latency(spec, state.telem, state.tasks, t1)
    return state.replace(telem=telem), buf


def _phase_journeys(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array,
) -> Tuple[WorldState, TickBuf]:
    """Causal task-journey tap (telemetry/journeys.py, ISSUE 15).

    Diffs each sampled task's packed row against the previous tick's
    snapshot and appends one ``(t_bits, code, a, b)`` event per
    lifecycle edge to its bounded ring in :class:`TelemetryState` —
    J-sized gathers plus one drop-scatter, nothing task-capacity-sized.
    Runs LAST among the task-mutating phases (after the fused write
    set has flushed, after learn credit), so one diff observes the
    whole tick's causal chain with each edge stamped from its own
    exact event-time column.  Statically gated on
    ``spec.journey_active``: journey-off worlds trace none of this and
    stay bit-exact (tests/test_journeys.py).  Pure carry endomorphism,
    so it rides the scan and the fleet ``vmap`` unchanged.
    """
    from ..telemetry.journeys import journey_tick

    telem = journey_tick(
        spec, state.telem, state.tasks, t1,
        chaos=state.chaos if spec.chaos else None,
        hier=state.hier if spec.hier_active else None,
    )
    return state.replace(telem=telem), buf


def _phase_telemetry(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    buf: TickBuf, t1: jax.Array,
    phase_work: Optional[dict] = None,
) -> Tuple[WorldState, TickBuf]:
    """Plane-1 telemetry accumulation (telemetry/metrics.py).

    Folds this tick's end-of-tick fog/learn/metrics snapshot — plus the
    per-phase work deltas the step bracketed around each phase call —
    into the carry-resident :class:`TelemetryState`.  Statically gated:
    worlds with ``spec.telemetry`` off trace none of this and stay
    bit-exact (tests/test_telemetry.py).  Pure carry endomorphism, so
    it rides the scan and the fleet's replica ``vmap`` unchanged.
    """
    if spec.chaos:
        U, F = spec.n_users, spec.n_fogs
        chaos = state.chaos
        fogs_down = jnp.sum(
            (~state.nodes.alive[U : U + F]).astype(jnp.int32)
        )
    else:
        chaos, fogs_down = None, None
    hier_load = None
    if spec.telemetry_hier_brokers > 0:
        # per-broker domain load gauge (busy owned fogs / owned fogs):
        # the fns_hier_load family and the Perfetto broker lanes
        B_h, F_h = spec.n_brokers, spec.n_fogs
        owned_bf = (
            state.hier.fog_broker[None, :]
            == jnp.arange(B_h, dtype=jnp.int32)[:, None]
        )
        if spec.fog_model == int(FogModel.POOL):
            busy_f = state.fogs.pool_avail < state.fogs.mips
        else:
            busy_f = state.fogs.current_task != NO_TASK
        n_owned = jnp.sum(owned_bf, axis=1)
        hier_load = jnp.sum(
            owned_bf & busy_f[None, :], axis=1
        ).astype(jnp.float32) / jnp.maximum(
            n_owned.astype(jnp.float32), 1.0
        )
    telem = accumulate_tick(
        spec, state.telem, state.fogs, state.learn, state.metrics,
        state.tick, t1, phase_work, chaos=chaos, fogs_down=fogs_down,
        hier_load=hier_load,
    )
    return state.replace(telem=telem), buf


def _phase_periodic_adverts(
    spec: WorldSpec, state: WorldState, net: NetParams, cache: LinkCache,
    t0: jax.Array, t1: jax.Array,
) -> WorldState:
    """v1/v2 fogs re-advertise every ``adv_interval`` (ComputeBrokerApp2.cc:219).

    Fired on the tick containing each multiple of the interval; the
    advertisement carries the fog's *current* MIPS — which in the POOL model
    is the remaining pool (the reference mutates ``MIPS`` itself,
    ``ComputeBrokerApp2.cc:272``) — and lands after the fog->broker delay.
    """
    F, U = spec.n_fogs, spec.n_users
    alive = state.nodes.alive[U : U + F]
    k0 = jnp.floor(t0 / spec.adv_interval)
    k1 = jnp.floor(t1 / spec.adv_interval)
    fire = (k1 > k0) & alive
    t_fire = (k0 + 1.0) * spec.adv_interval
    d_fb = cache.d2b[U : U + F]
    adv_mips = (
        state.fogs.pool_avail
        if spec.fog_model == int(FogModel.POOL)
        else state.fogs.mips
    )
    b = state.broker
    b = b.replace(
        adv_val_mips=jnp.where(fire, adv_mips, b.adv_val_mips),
        adv_val_busy=jnp.where(fire, state.fogs.busy_time, b.adv_val_busy),
        adv_arrive_t=jnp.where(fire, t_fire + d_fb, b.adv_arrive_t),
    )
    return state.replace(broker=b)


def prime_initial_advertisements(
    spec: WorldSpec, state: WorldState, net: NetParams, t_adv: float = 0.01,
    fog_start_t: float = 0.0,
) -> WorldState:
    """Stamp fog registration + first advertisement arrival times.

    Mirrors the fog boot sequence: Connect at ``fog_start_t`` arrives at the
    broker one hop later (registration, ``BrokerBaseApp3.cc:102-107``,
    MIPS=0 in the view until the first advert); Connack returns; the fog
    schedules ADVERTISEMIPS at +``t_adv`` (``ComputeBrokerApp3.cc:261-267``)
    whose packet lands another hop later.  Scenario builders call this after
    placing nodes.  In the POOL model the advertised value is the pool.
    """
    cache = associate(
        net, state.nodes.pos, state.nodes.alive, broker=spec.broker_index
    )
    F = spec.n_fogs
    fog_nodes = jnp.arange(F, dtype=jnp.int32) + spec.n_users
    d_fb = cache.d2b[fog_nodes]
    adv_mips = (
        state.fogs.pool_avail
        if spec.fog_model == int(FogModel.POOL)
        else state.fogs.mips
    )
    register_t = jnp.asarray(fog_start_t, jnp.float32) + d_fb
    connack_at_fog = jnp.asarray(fog_start_t, jnp.float32) + 2.0 * d_fb
    b = state.broker.replace(
        register_t=register_t if spec.connect_gating else state.broker.register_t,
        registered=(
            jnp.zeros((F,), bool) if spec.connect_gating else state.broker.registered
        ),
        adv_val_mips=adv_mips,
        adv_val_busy=state.fogs.busy_time,
        adv_arrive_t=(
            (connack_at_fog if spec.connect_gating else 0.0)
            + jnp.asarray(t_adv, jnp.float32)
            + d_fb
        ),
    )
    return state.replace(broker=b)


# ----------------------------------------------------------------------
# the tick
# ----------------------------------------------------------------------

def make_step(
    spec: WorldSpec, with_aux: bool = False
) -> Callable[[WorldState, NetParams, MobilityBounds], WorldState]:
    """Build the jit-compiled single-tick transition for ``spec``.

    ``with_aux=True`` returns ``(state, aux)`` where ``aux`` carries the
    tick's per-AP association counts — used by the series recorder so the
    trace reuses the association ``step`` already computed instead of
    recomputing it per tick.

    ``static_cache``: with ``spec.assume_static`` the caller (``run``)
    associates once before the scan and passes the constant
    :class:`LinkCache` here — the per-tick mobility + association kernels
    are then skipped entirely (bit-identical: the cache is a pure
    function of the constant ``(pos, alive)``).

    ``dyn`` (ISSUE 13): the promoted numeric knobs as a device operand.
    ``None`` (the static path) folds :func:`~fognetsimpp_tpu.dynspec.
    dyn_of` at trace time, embedding the same host f32 constants the
    pre-promotion engine used — so the two paths execute identical
    arithmetic and the promoted entry points can be state-hash A/B'd
    against this one.  With a :class:`DynSpec` operand, ``spec`` should
    be the world's SHAPE KEY (``dynspec.shape_key``) so every world in
    the bucket hits one compiled program.
    """
    spec.validate()

    def step(
        state: WorldState, net: NetParams, bounds: MobilityBounds,
        static_cache: Optional[LinkCache] = None,
        dyn: Optional[DynSpec] = None,
    ):
        t0 = state.tick.astype(jnp.float32) * spec.dt
        t1 = (state.tick + 1).astype(jnp.float32) * spec.dt
        i32 = jnp.int32
        dv = dyn if dyn is not None else dyn_of(spec)
        buf = TickBuf(
            tx_u=jnp.zeros((spec.n_users,), i32),
            rx_u=jnp.zeros((spec.n_users,), i32),
            tx_f=jnp.zeros((spec.n_fogs,), i32),
            rx_f=jnp.zeros((spec.n_fogs,), i32),
            tx_b=jnp.zeros((), i32),
            rx_b=jnp.zeros((), i32),
        )

        # 0. the deferred-backlog gauge restarts every tick (each window
        # compaction adds what it could not seat; see _rot_and_defer)
        state = state.replace(
            metrics=state.metrics.replace(
                n_deferred=jnp.zeros((), jnp.int32)
            )
        )

        # phase harness: every phase call runs under a jax.named_scope
        # (XLA profiles attribute cost per phase — telemetry plane 3)
        # and, when spec.telemetry, is bracketed by the metrics-activity
        # scalar so its work delta lands in TelemetryState.phase_work.
        # The thunk reads the CURRENT state/buf bindings at call time;
        # _ph rebinds them from the phase's return.
        telem_on = spec.telemetry
        ph_work: dict = {}

        def _ph(name, thunk):
            nonlocal state, buf
            m0 = tick_activity(state.metrics, buf) if telem_on else None
            with jax.named_scope("phase_" + name):
                out = thunk()
            extra = None
            if isinstance(out, tuple):
                if len(out) == 3:
                    state, buf, extra = out
                else:
                    state, buf = out
            else:
                state = out
            if telem_on:
                i = PHASE_INDEX[name]
                d = tick_activity(state.metrics, buf) - m0
                ph_work[i] = ph_work[i] + d if i in ph_work else d
            return extra

        # 1. mobility (positions at end-of-tick; delays in this tick use them)
        # 2. connectivity / association snapshot for this tick
        if spec.assume_static and static_cache is not None:
            cache = static_cache
        else:
            if spec.assume_static and net.mac_loss_tab.shape[0] > 0:
                # trace-time (shape is static): a direct make_step caller
                # without a static cache must not silently diverge from
                # run(), which rejects this combination outright
                raise ValueError(_STATIC_MAC_ERR)
            with jax.named_scope("phase_mobility_association"):
                pos, vel = step_mobility(state.nodes, bounds, t1, spec.dt)
                nodes = state.nodes.replace(pos=pos, vel=vel)
                state = state.replace(nodes=nodes)
                # Bianchi worlds key MAC contention on each cell's OFFERED
                # LOAD (DCF contends among stations with queued frames, not
                # associated-but-idle ones — VERDICT r4 item 2), solved to
                # an effective contender count inside associate()
                offered = None
                if net.mac_loss_tab.shape[0] > 0:
                    offered = offered_rate_vector(
                        spec, state.nodes.alive[: spec.n_users],
                        state.users, t0, dyn=dv,
                    )
                cache = associate(
                    net, state.nodes.pos, state.nodes.alive,
                    broker=spec.broker_index, offered_rate=offered,
                )
        if spec.wired_queue_enabled:
            # DropTailQueue backpressure (wireless5.ini:72-73): last
            # tick's egress backlog serializes ahead of new messages.
            # SYMMETRIC simplification (PARITY.md deviation ledger): both
            # endpoints' egress backlogs delay the shared d2b vector, so
            # a broker->user ack is also delayed by the user's uplink
            # backlog — directionally wrong under asymmetric congestion;
            # exact in aggregate for the symmetric request/ack traffic of
            # the committed scenarios.
            qdelay = state.nodes.link_backlog * dv.link_inv_rate
            cache = cache.replace(
                d2b=cache.d2b + qdelay + qdelay[spec.broker_index]
            )

        # chaos fault injection (spec.chaos, ISSUE 12): degrade the
        # broker->fog delay rows for this tick (periodic + PRNG-burst
        # terms keyed on the tick index — deterministic across every
        # entry point), then run the lifecycle phase so crash/recover
        # edges land in nodes.alive BEFORE any dispatch decision of
        # this tick (and before the fused register views snapshot the
        # task table below).
        if spec.chaos:
            if spec.chaos_rtt_amp > 0 or spec.chaos_rtt_burst_prob > 0:
                with jax.named_scope("chaos_rtt"):
                    fac = rtt_factor(
                        spec, state.chaos, state.tick, t0, dyn=dv
                    )
                    n_rest_c = spec.n_nodes - spec.n_users - spec.n_fogs
                    full_fac = jnp.concatenate([
                        jnp.ones((spec.n_users,), jnp.float32),
                        fac,
                        jnp.ones((n_rest_c,), jnp.float32),
                    ])
                    cache = cache.replace(d2b=cache.d2b * full_fac)
            _ph("chaos", lambda: _phase_chaos(
                spec, state, net, cache, buf, t0, t1, dyn=dv))

        # fused per-user slot-window front-end (spec.fused_slots, r6):
        # spawn/broker/completions/arrivals thread the hot task-table
        # columns as (U, S) register views plus a shared deferred-
        # scatter write set; the table is written ONCE, after the last
        # contributing phase.  Metrics/TickBuf/fog updates stay eager
        # and per-phase (so the _ph work brackets book identically to
        # the unfused pipeline) EXCEPT on telemetry-off ticks, where
        # the scalar counter sums ride two merged flush reductions.
        fused = _fused_ok(spec)
        fv = _task_views(spec, state.tasks) if fused else None
        if fused:
            fv["defer_host_counts"] = not telem_on

        # 3-7. protocol phases
        if spec.connect_gating:
            out = _ph("connect", lambda: _phase_connect(
                spec, state, net, cache, buf, t0, t1, views=fv))
            if fused:
                fv = out
        out = _ph("adverts", lambda: _phase_adverts(
            state, t1, buf=buf, views=fv))
        if fused:
            fv = out
        if spec.adv_periodic and spec.fog_model != int(FogModel.POOL):
            _ph("adverts", lambda: _phase_periodic_adverts(
                spec, state, net, cache, t0, t1))
        if spec.max_sends_per_tick > 1:
            out = _ph("spawn", lambda: _phase_spawn_multi(
                spec, state, net, cache, buf, t0, t1, views=fv, dyn=dv))
        else:
            out = _ph("spawn", lambda: _phase_spawn(
                spec, state, net, cache, buf, t0, t1, views=fv, dyn=dv))
        if fused:
            fv = out
        # federated hierarchy (spec.n_brokers > 1, hier/): migrate the
        # publishes maturing at saturated/dead broker domains THIS tick
        # out before the decide phase sees them — a chaos-killed
        # domain's re-offloaded tasks leave the same tick they bounce
        if _hier_migrate_on(spec):
            out = _ph("broker_migrate", lambda: _phase_broker_migrate(
                spec, state, net, cache, buf, t0, t1, views=fv, dyn=dv))
            if fused:
                fv = out
        v2_local = (
            spec.policy == int(Policy.LOCAL_FIRST) and spec.v2_local_broker
        )
        if v2_local:  # shared-timer fires that precede every arrival
            _ph("v2_release_pre", lambda: _phase_v2_release(
                spec, state, net, cache, buf, t1, before_broker=True))
        v2_resched = None
        if _broker_dense_ok(spec):
            out = _ph("broker", lambda: _phase_broker_dense(
                spec, state, net, cache, buf, t1, views=fv))
            if fused:
                fv = out
        else:
            v2_resched = _ph("broker", lambda: _phase_broker(
                spec, state, net, cache, buf, t1))
        if v2_local:  # fires this tick's decisions did not cancel
            rs, pre = (None, None) if v2_resched is None else v2_resched
            _ph("v2_release_post", lambda: _phase_v2_release(
                spec, state, net, cache, buf, t1, before_broker=False,
                resched_t=rs, prerefunded=pre))
        if spec.n_fogs > 0:  # a fog-less world exercises only the
            # "no compute resource available" branch (BrokerBaseApp3.cc:306)
            if spec.fog_model == int(FogModel.POOL):
                if spec.adv_periodic:
                    # sub-tick advert-boundary phasing: the periodic
                    # advertisement's payload is the pool *at the fire
                    # time* (the reference reads this->MIPS when the timer
                    # fires, ComputeBrokerApp2.cc:202-220), so fog events
                    # up to the boundary must settle first, then the
                    # capture, then the rest of the tick.  Exactness r3:
                    # the r2 gate tolerated 5% choice divergence from the
                    # start-of-tick capture.
                    t_fire = (
                        jnp.floor(t0 / spec.adv_interval) + 1.0
                    ) * spec.adv_interval
                    t_a = jnp.minimum(t_fire, t1)
                    _ph("pool_completions", lambda: _phase_pool_completions(
                        spec, state, net, cache, buf, t_a))
                    _ph("pool_arrivals", lambda: _phase_pool_arrivals(
                        spec, state, net, cache, buf, t_a))
                    _ph("adverts", lambda: _phase_periodic_adverts(
                        spec, state, net, cache, t0, t1))
                _ph("pool_completions", lambda: _phase_pool_completions(
                    spec, state, net, cache, buf, t1))
                _ph("pool_arrivals", lambda: _phase_pool_arrivals(
                    spec, state, net, cache, buf, t1))
            else:
                for _ in range(spec.completions_per_tick):
                    out = _ph("completions", lambda: _phase_completions(
                        spec, state, net, cache, buf, t1, views=fv))
                    if fused:
                        fv = out
                out = _ph("fog_arrivals", lambda: _phase_fog_arrivals(
                    spec, state, net, cache, buf, t1, views=fv))
                if fused:
                    fv = out
        if fused:
            # the one task-table writeback of the tick: each threaded
            # column lands as a single dense write, each deferred
            # column as a single concatenated scatter — plus the
            # deferred host-facing counters (telemetry-off only)
            with jax.named_scope("phase_flush"):
                state = state.replace(
                    tasks=_flush_task_views(spec, state.tasks, fv)
                )
                if fv["rx_u"]:
                    buf = buf._replace(
                        rx_u=buf.rx_u.at[
                            jnp.concatenate([i for i, _ in fv["rx_u"]])
                        ].add(
                            jnp.concatenate([a for _, a in fv["rx_u"]]),
                            mode="drop",
                        )
                    )
                # deferred scalar counters: ONE stacked reduction per
                # row width, then integer adds to their targets (exact,
                # and commutative, so totals equal the eager per-phase
                # adds bit-for-bit)
                m_adds: dict = {}
                b_adds: dict = {}
                for pool in ("def_u", "def_f"):
                    entries = fv[pool]
                    if not entries:
                        continue
                    red = jnp.sum(
                        jnp.stack(
                            [r for r, _ in entries]
                        ).astype(jnp.int32),
                        axis=1,
                    )
                    for i, (_, targets) in enumerate(entries):
                        for name, scale in targets:
                            d = m_adds if name.startswith("n_") else b_adds
                            add = red[i] * scale if scale != 1 else red[i]
                            d[name] = d.get(name, 0) + add
                if m_adds:
                    state = state.replace(
                        metrics=state.metrics.replace(**{
                            k: getattr(state.metrics, k) + v
                            for k, v in m_adds.items()
                        })
                    )
                if b_adds:
                    buf = buf._replace(**{
                        k: getattr(buf, k) + v for k, v in b_adds.items()
                    })
        if spec.policy == int(Policy.LOCAL_FIRST) and not spec.v2_local_broker:
            _ph("local_completions", lambda: _phase_local_completions(
                spec, state, net, cache, buf, t1))
        if spec.learn_active:
            # delayed-reward credit: after completions/arrivals so a
            # status-6 ack that lands inside this tick credits this tick
            _ph("learn_credit", lambda: _phase_learn_credit(
                spec, state, net, cache, buf, t1, dyn=dv))
        if spec.telemetry_hist:
            # streaming latency histogram: after completions/acks so a
            # status-6 ack landing inside this tick streams this tick
            _ph("latency_hist", lambda: _phase_latency_hist(
                spec, state, net, cache, buf, t1))

        # 7b. flat per-node views of this tick's message counts, feeding
        # the cumulative per-module counters, the DropTail queues and the
        # energy model
        n_rest_q = spec.n_aps + spec.n_routers
        rest_zeros = jnp.zeros((n_rest_q,), i32)
        tx_all = jnp.concatenate(
            [buf.tx_u, buf.tx_f, buf.tx_b[None], rest_zeros]
        )
        rx_all = jnp.concatenate(
            [buf.rx_u, buf.rx_f, buf.rx_b[None], rest_zeros]
        )
        nodes2 = state.nodes.replace(
            tx_count=state.nodes.tx_count + tx_all,
            rx_count=state.nodes.rx_count + rx_all,
        )
        if spec.n_aps > 0:
            a0, a1 = spec.ap_slice
            nodes2 = nodes2.replace(
                assoc_sum=nodes2.assoc_sum.at[a0:a1].add(cache.n_assoc)
            )
        state = state.replace(nodes=nodes2)

        # wired-link DropTail queues: integrate this tick's egress
        # traffic into each wired node's serialization backlog; overflow
        # beyond frameCapacity becomes next tick's tail-drop probability
        if spec.wired_queue_enabled:
            add_bytes = tx_all.astype(jnp.float32) * float(spec.task_bytes)
            drain = dv.link_drain_bytes
            raw = state.nodes.link_backlog + add_bytes - drain
            cap_bytes = float(spec.link_queue_frames * spec.task_bytes)
            wired = ~net.is_wireless
            backlog = jnp.where(
                wired, jnp.clip(raw, 0.0, cap_bytes), 0.0
            )
            overflow = jnp.where(wired, jnp.maximum(raw - cap_bytes, 0.0), 0.0)
            drop_p = jnp.clip(
                overflow / jnp.maximum(add_bytes, 1.0), 0.0, 1.0
            )
            n_drops = jnp.sum(overflow).astype(jnp.float32) / float(
                spec.task_bytes
            )
            state = state.replace(
                nodes=state.nodes.replace(
                    link_backlog=backlog, link_drop_p=drop_p
                ),
                metrics=state.metrics.replace(
                    n_link_drops=state.metrics.n_link_drops
                    + n_drops.astype(i32)
                ),
            )

        # 8. energy + lifecycle
        if spec.energy_enabled:
            n_rest = spec.n_aps + spec.n_routers
            if spec.fog_model == int(FogModel.POOL):
                fog_busy = state.fogs.pool_avail < state.fogs.mips
            else:
                fog_busy = state.fogs.current_task != NO_TASK
            computing = jnp.concatenate(
                [
                    jnp.zeros((spec.n_users,), bool),
                    fog_busy,
                    jnp.zeros((1 + n_rest,), bool),
                ]
            )
            with jax.named_scope("phase_energy"):
                energy, alive = step_energy(
                    spec, state.nodes.energy, state.nodes.energy_capacity,
                    state.nodes.has_energy, state.nodes.alive, t1,
                    tx_all, rx_all, computing, dyn=dv,
                )
            state = state.replace(
                nodes=state.nodes.replace(energy=energy, alive=alive)
            )

        # 8b. journey tap (spec.telemetry_journeys): diff the sampled
        # tasks' rows against last tick's snapshot and append this
        # tick's lifecycle edges to the per-task rings — after every
        # task-mutating phase (and the fused flush), before the
        # telemetry fold
        if spec.journey_active:
            with jax.named_scope("phase_journeys"):
                state, buf = _phase_journeys(
                    spec, state, net, cache, buf, t1
                )

        # 9. plane-1 telemetry accumulation (after every phase booked
        # its work; before the tick counter advances so the reservoir
        # slot is keyed on THIS tick's index)
        if telem_on:
            with jax.named_scope("phase_telemetry"):
                state, buf = _phase_telemetry(
                    spec, state, net, cache, buf, t1, ph_work
                )

        state = state.replace(
            t=t1,
            tick=state.tick + 1,
            metrics=state.metrics.replace(
                n_deferred_max=jnp.maximum(
                    state.metrics.n_deferred_max,
                    state.metrics.n_deferred,
                )
            ),
        )
        if with_aux:
            return state, {"n_assoc": cache.n_assoc}
        return state

    return step


def _finalize_derived_acks(
    spec: WorldSpec, state: WorldState, cache: LinkCache
) -> WorldState:
    """Reconstruct the ack columns skipped under ``spec.derive_acks``.

    One dense pass after the scan, with the SAME float32 arithmetic (and
    operand order) the per-tick phases use, over the same static delay
    cache — bit-exact vs the eager writes (tests/test_runtime.py).
    """
    t = state.tasks
    U, S, F, T = (
        spec.n_users, spec.max_sends_per_user, spec.n_fogs,
        spec.task_capacity,
    )
    d_bu = cache.d2b[:U][:, None]  # (U, 1) broadcast over the send axis
    d_bf = (
        cache.d2b[U + jnp.clip(t.fog, 0, F - 1)].reshape(U, S)
        if F > 0
        else jnp.zeros((U, S), jnp.float32)
    )
    st2 = t.stage.reshape(U, S)
    qe2 = t.t_q_enter.reshape(U, S)
    ss2 = t.t_service_start.reshape(U, S)
    decided = (
        (st2 != _ST_UNUSED)
        & (st2 != _ST_PUB_INFLIGHT)
        & (st2 != _ST_LOST)
    )
    if spec.n_brokers > 1:
        # hop-exhausted tasks never reached a decide phase: no ack was
        # ever sent (gated so single-broker worlds keep the exact
        # pre-hier reconstruction trace)
        decided = decided & (st2 != _ST_HOP_EXHAUSTED)
    queued = jnp.isfinite(qe2)
    assigned = jnp.isfinite(ss2) & ~queued
    done = st2 == _ST_DONE
    inf = jnp.inf
    return state.replace(
        tasks=t.replace(
            t_ack4_fwd=jnp.where(
                decided, t.t_at_broker.reshape(U, S) + d_bu, inf
            ).reshape(T),
            t_ack4_queued=jnp.where(
                queued, qe2 + d_bf + d_bu, inf
            ).reshape(T),
            t_ack5=jnp.where(assigned, ss2 + d_bf + d_bu, inf).reshape(T),
            t_ack6=jnp.where(
                done, t.t_complete.reshape(U, S) + d_bf + d_bu, inf
            ).reshape(T),
            queue_time_ms=jnp.where(
                queued & jnp.isfinite(ss2), (ss2 - qe2) * 1e3, inf
            ).reshape(T),
        )
    )


def run(
    spec: WorldSpec,
    state: WorldState,
    net: NetParams,
    bounds: Optional[MobilityBounds] = None,
    n_ticks: Optional[int] = None,
    dyn: Optional[DynSpec] = None,
) -> Tuple[WorldState, Optional[dict]]:
    """Run ``n_ticks`` (default: spec horizon) under one `lax.scan`.

    Returns (final_state, series) where ``series`` holds per-tick vectors
    (queue lengths, busy times, alive count) when
    ``spec.record_tick_series`` — the ``.vec``-file analog (SURVEY.md §5
    tracing).

    ``dyn`` (ISSUE 13): promoted numeric knobs as a device operand —
    pass ``dynspec.split_spec(world)``'s parts as ``(spec, dyn)`` so
    every world in the shape bucket traces to one program.  ``None``
    keeps the spec's own values as trace constants (bit-identical).
    """
    if bounds is None:
        from ..net.mobility import default_bounds

        bounds = default_bounds()
    n = spec.n_ticks if n_ticks is None else n_ticks
    record = spec.record_tick_series
    step = make_step(spec, with_aux=record)
    static_cache = None
    if spec.assume_static:
        if net.mac_loss_tab.shape[0] > 0:
            raise ValueError(_STATIC_MAC_ERR)
        # one association for the whole run (spec promise: constant
        # positions + liveness); the scan then runs zero mobility kernels
        static_cache = associate(
            net, state.nodes.pos, state.nodes.alive,
            broker=spec.broker_index,
        )

    def body(carry, _):
        if record:
            s, aux = step(carry, net, bounds, static_cache, dyn)
            out = {
                "t": s.t,
                "busy_time": s.fogs.busy_time,
                "q_len": s.fogs.q_len,
                "pool_avail": s.fogs.pool_avail,
                "n_alive": jnp.sum(s.nodes.alive.astype(jnp.int32)),
                "energy_mean": jnp.mean(s.nodes.energy),
                # per-AP station counts: the handover/association trace
                # (INET's per-NIC association statistics analog), reusing
                # the tick's own association instead of recomputing it
                "n_assoc": aux["n_assoc"],
            }
            if spec.learn_active:
                # bandit trajectory: per-fog cumulative picks + credited
                # raw-latency accumulators — the regret harness
                # (learn/eval.py) turns these into learnRegret /
                # learnPicks curves without re-reading the task table
                out["learn_picks"] = s.learn.pick_count
                out["learn_lat_sum"] = s.learn.lat_sum
                out["learn_lat_cnt"] = s.learn.lat_cnt
            if spec.record_trails:
                # Tkenv movement-trail analog (runtime/trails.py)
                out["pos"] = s.nodes.pos
        else:
            s = step(carry, net, bounds, static_cache, dyn)
            out = None
        return s, out

    final, series = jax.lax.scan(body, state, None, length=n)
    if spec.derive_acks:
        final = _finalize_derived_acks(spec, final, static_cache)
    return final, series


def _dealias_for_donation(state: WorldState) -> WorldState:
    """Buffer donation requires every donated leaf to own its buffer.

    World builders may alias one array into several fields (e.g.
    ``smoke.build`` seeds ``fogs.pool_avail`` with the ``mips`` array
    itself), and XLA's Execute() rejects donating the same buffer twice.
    Copy the second and later references; unaliased states pass through
    untouched, so this never changes results.

    Sharding-aware (ISSUE 3): a mesh-sharded leaf has no single
    ``unsafe_buffer_pointer`` — its identity is the tuple of per-shard
    buffer pointers, so two fleet-batch leaves serving the same device
    buffers are still caught before the donating fleet entries
    (:mod:`fognetsimpp_tpu.parallel.fleet`) hand them to Execute().
    """
    seen = set()

    def one(x):
        try:
            key = x.unsafe_buffer_pointer()
        except Exception:
            try:  # sharded leaves: identity = the per-shard buffers
                key = tuple(
                    s.data.unsafe_buffer_pointer()
                    for s in x.addressable_shards
                )
            except Exception:  # numpy / non-addressable leaves
                key = id(x)
        if key in seen:
            return jnp.copy(x)
        seen.add(key)
        return x

    return jax.tree.map(one, state)


def run_chunked(
    spec: WorldSpec,
    state: WorldState,
    net: NetParams,
    bounds: Optional[MobilityBounds] = None,
    chunk_ticks: int = 10_000,
    callback: Optional[Callable[[WorldState, int], None]] = None,
    telemetry_stream: Optional[Callable[[dict, int], None]] = None,
    promote: Optional[bool] = None,
    reconfigure: Optional[Callable[[int], Optional[dict]]] = None,
    inject: Optional[Callable[["WorldState", int], "WorldState"]] = None,
) -> WorldState:
    """Advance an arbitrarily long horizon in fixed-size scan chunks.

    The long axis of this workload is simulated *time* (the SP analog,
    SURVEY.md §2.3): a compiled ``chunk_ticks``-long scan is reused across
    chunks (one extra compile for a ragged tail when the horizon is not a
    multiple; the persistent compilation cache covers repeat calls), so
    ultra-long horizons run in bounded device memory;
    ``callback(state, tick)`` runs between chunks for checkpointing or
    streaming metrics (pairs with
    :mod:`fognetsimpp_tpu.runtime.checkpoint`).  Bit-identical to one
    straight scan — the carry is the same pytree either way.

    Per-tick series recording is not supported here (the chunks' series
    would be silently dropped): record via the callback instead.

    Buffer donation (simlint R6): without a ``callback``, each chunk
    DONATES its input carry, so XLA serves the next chunk's state from
    the previous chunk's buffers in place instead of holding two copies
    of the dominant task-table footprint — the ``state`` argument itself
    feeds the first chunk, so do not reuse it after calling (platforms
    without donation support just ignore the hint).  WITH a callback the
    chunks do not donate: the callback may retain each chunk-boundary
    state (checkpoint streaming), and donating it to the next chunk
    would delete those buffers behind the callback's back.

    ``telemetry_stream`` (the PR-4 live-dashboard follow-up): with
    ``spec.telemetry`` on, called after every chunk as
    ``telemetry_stream(rows, ticks_done)`` where ``rows`` maps each
    :data:`~fognetsimpp_tpu.telemetry.metrics.RES_FIELDS` name to the
    HOST copy of the reservoir rows this chunk completed (strictly
    in tick order, no row delivered twice).  Unlike ``callback`` it
    does NOT disable donation: the rows are fetched to host before the
    next chunk consumes the state, and nothing device-resident is
    retained.

    ``promote`` / ``reconfigure`` (ISSUE 13, the what-if door): with
    promotion on (the default), the chunk program takes the promoted
    knobs as a DynSpec operand, and ``reconfigure(ticks_done)`` — called
    at every chunk boundary — may return a ``{field: value}`` dict of
    promoted WorldSpec knobs to apply to the REMAINING horizon with
    ZERO recompiles (``compile_stats()`` delta-provable).  Returning
    ``None``/``{}`` keeps the current knobs.  A dict naming a
    shape-defining field (or flipping a trace gate, e.g. turning chaos
    bursts on for a world compiled without them) raises the one-line
    ``dynspec.apply_knobs`` error instead of silently recompiling.

    ``inject`` (ISSUE 17, the digital-twin input door): called at every
    INTERIOR chunk boundary as ``inject(state, ticks_done)`` and must
    return the (possibly updated) state the next chunk consumes —
    the twin/ingest drain hands queued external arrivals to
    :func:`inject_arrivals` here, so injection lands between compiled
    chunks and the tick program itself never hosts a transfer.  Runs
    AFTER ``callback``/``telemetry_stream`` observe the chunk's own
    result and after ``reconfigure`` (observability sees what the sim
    produced; injection feeds what the next chunk starts from).
    Requires ``spec.ingest`` when used with the twin drain (the phase
    is compiled out otherwise).
    """
    if promote is None:
        promote = promote_default()
    if reconfigure is not None and not promote:
        raise ValueError(
            "reconfigure re-configures the DynSpec operand between "
            "chunks; it needs the promoted path (promote=True)"
        )
    if spec.record_tick_series:
        raise ValueError(
            "run_chunked does not collect per-tick series; run() per chunk "
            "or record snapshots via the callback"
        )
    if bounds is None:
        from ..net.mobility import default_bounds

        bounds = default_bounds()

    total = spec.n_ticks
    chunk = min(chunk_ticks, total)

    if promote:
        from ..dynspec import apply_knobs

        live_spec = spec
        run_spec, dyn = split_spec(spec)
        # the callback path runs the NON-donating go_keep executable —
        # a distinct donation layout, hence a distinct registry program
        registry_note(
            run_spec, jax.default_backend(), donated=callback is None
        )
    else:
        run_spec, dyn = spec, None

    @functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
    def go(
        n: int, s: WorldState, net_: NetParams, bounds_: MobilityBounds,
        dyn_: Optional[DynSpec],
    ) -> WorldState:
        final, _ = run(run_spec, s, net_, bounds_, n_ticks=n, dyn=dyn_)
        return final

    # simlint: disable=R6 -- the callback path must NOT donate: callbacks
    # may retain each chunk-boundary state (checkpoint streaming), and the
    # next chunk would delete those buffers behind the callback's back
    @functools.partial(jax.jit, static_argnums=0)
    def go_keep(
        n: int, s: WorldState, net_: NetParams, bounds_: MobilityBounds,
        dyn_: Optional[DynSpec],
    ) -> WorldState:
        final, _ = run(run_spec, s, net_, bounds_, n_ticks=n, dyn=dyn_)
        return final

    if telemetry_stream is not None and not spec.telemetry:
        raise ValueError(
            "telemetry_stream needs spec.telemetry=True (the reservoir "
            "is zero-row when the plane is off)"
        )
    donating = callback is None
    done = 0
    next_row = 0
    while done < total:
        n = min(chunk, total - done)
        if donating:
            state = go(n, _dealias_for_donation(state), net, bounds, dyn)
        else:
            state = go_keep(n, state, net, bounds, dyn)
        done += n
        if telemetry_stream is not None:
            from ..telemetry.metrics import reservoir_progress

            rows, next_row = reservoir_progress(
                spec, state.telem, done, next_row
            )
            telemetry_stream(rows, done)
        if callback is not None:
            callback(state, done)
        if reconfigure is not None and done < total:
            knobs = reconfigure(done)
            if knobs:
                # compile-free by construction: apply_knobs rejects any
                # change that would alter the shape key, and the chunk
                # program re-runs with the new operand values only
                live_spec = apply_knobs(live_spec, knobs)
                dyn = dyn_of(live_spec)
        if inject is not None and done < total:
            state = inject(state, done)
    return state


def run_jit(
    spec: WorldSpec, state: WorldState, net: NetParams,
    bounds: MobilityBounds, promote: Optional[bool] = None,
) -> WorldState:
    """Whole-run jit entry: scan over the full horizon.

    ``state`` is DONATED (simlint R6): the carry dominates the bytes/tick
    footprint, and donation lets XLA alias the initial state's buffers
    into the scan carry instead of copying them.  Do not reuse ``state``
    after calling; rebuild (or ``jax.tree.map(jnp.copy, ...)``) if the
    initial world is needed again.

    ``promote`` (ISSUE 13, default on; ``FNS_SPEC_PROMOTE=0`` flips the
    default): split the spec into its shape key (static) and DynSpec
    operand, so re-configuring any promoted numeric knob — a chaos
    MTBF, an RTT burst amplitude, an energy power budget — re-uses the
    compiled program instead of paying the 8-56 s compile wall.
    ``promote=False`` is the bit-exact static reference path
    (tests/test_dynspec.py A/Bs the two).
    """
    if promote is None:
        promote = promote_default()
    if promote:
        key_spec, dyn = split_spec(spec)
        registry_note(key_spec, jax.default_backend(), donated=True)
        return _run_jit_dyn(
            key_spec, _dealias_for_donation(state), net, bounds, dyn
        )
    # simlint: disable=R12 -- exclusive branch: the promoted return above
    # already left the function, so `state` cannot be a donated alias here
    return _run_jit(spec, _dealias_for_donation(state), net, bounds)


@functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
def _run_jit(
    spec: WorldSpec, state: WorldState, net: NetParams, bounds: MobilityBounds
) -> WorldState:
    final, _ = run(spec, state, net, bounds)
    return final


@functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
def _run_jit_dyn(
    spec: WorldSpec, state: WorldState, net: NetParams,
    bounds: MobilityBounds, dyn: DynSpec,
) -> WorldState:
    """The promoted whole-run program: ``spec`` is a SHAPE KEY
    (``dynspec.shape_key``), every numeric knob rides ``dyn`` — one
    jit-cache entry serves the whole shape bucket."""
    final, _ = run(spec, state, net, bounds, dyn=dyn)
    return final


#: ``FNS_CHECKIFY`` / ``--checkify`` error-set names.  ``div`` is the
#: default: the other two sets page on two DELIBERATE engine idioms —
#: ``nan`` fires on inf-sentinel arithmetic in masked lanes (checkify
#: instruments the untaken side of every ``jnp.where``, and the ack
#: columns subtract ``inf - inf`` there by design) and ``oob`` fires on
#: the sentinel drop-scatter idiom (``NO_TASK`` rows index one past the
#: table so the scatter drops them — well-defined JAX semantics the
#: phases rely on).  Both stay available for targeted debugging; their
#: known-benign findings on the stock engine are exactly those two
#: classes.
CHECKIFY_SETS = ("nan", "div", "oob")


def _checkify_errors(names: Optional[str]):
    from jax.experimental import checkify

    table = {
        "nan": checkify.nan_checks,
        "div": checkify.div_checks,
        "oob": checkify.index_checks,
    }
    # "1"/"on"/"true" are the FNS_CHECKIFY boolean-enable spellings; a
    # "0" reaching here is a CLI `--checkify 0` that MEANT "off" — the
    # env layer already treats 0 as disabled, so reject it loudly
    # rather than silently taking the slow path with the default set
    if names is None or names in ("", "1", "on", "true", "div"):
        picked = ["div"]
    elif names == "all":
        picked = list(CHECKIFY_SETS)
    else:
        picked = [t.strip() for t in names.split(",") if t.strip()]
        bad = sorted(set(picked) - set(CHECKIFY_SETS))
        if bad:
            raise ValueError(
                f"unknown checkify set(s) {bad} "
                f"(have {list(CHECKIFY_SETS)} or 'all')"
            )
    errs = checkify.user_checks
    for t in picked:
        errs = errs | table[t]
    return errs


def run_checkified(
    spec: WorldSpec,
    state: WorldState,
    net: NetParams,
    bounds: Optional[MobilityBounds] = None,
    n_ticks: Optional[int] = None,
    errors: Optional[str] = None,
) -> Tuple[WorldState, Optional[dict]]:
    """Opt-in runtime sanitizer: the full-horizon run under
    ``jax.experimental.checkify`` (ISSUE 7 satellite).

    SLOW PATH, debug runs only: checkify threads a functionalized error
    carry through every instrumented primitive in the scan body, so the
    compiled program is materially slower and allocates extra carry
    state — never benchmark or gate on it.  Enabled via ``FNS_CHECKIFY=1``
    or CLI ``--checkify``; ``errors`` picks the instrumented sets
    (comma-joined names from :data:`CHECKIFY_SETS`, or ``"all"`` —
    default ``div``; see the :data:`CHECKIFY_SETS` note for why ``nan``/
    ``oob`` page on two deliberate engine idioms).  Raises
    ``checkify.JaxRuntimeError`` (via ``err.throw()``) on the first
    check that trips, with the offending primitive in the message.
    """
    if bounds is None:
        from ..net.mobility import default_bounds

        bounds = default_bounds()
    errs = _checkify_errors(errors)
    from jax.experimental import checkify

    def go(s, net_, bounds_):
        return run(spec, s, net_, bounds_, n_ticks=n_ticks)

    err, (final, series) = jax.jit(checkify.checkify(go, errors=errs))(
        state, net, bounds
    )
    err.throw()
    return final, series
