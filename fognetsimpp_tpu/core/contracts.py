"""Trace-time shape/dtype contracts on the engine's phase pipeline.

The OMNeT++ reference leans on its type system and nedtool codegen to keep
message schemas and scheduler state honest; the batched engine's analog is
the *carry contract*: every phase, and the whole tick step, must be an
endomorphism over the :class:`~fognetsimpp_tpu.state.WorldState` /
:class:`~fognetsimpp_tpu.core.engine.TickBuf` pytrees — same tree
structure, same shapes, same dtypes.  A phase that silently promotes a
carry leaf (int8 stage -> int32, f32 timestamp -> f64) would not crash:
under `lax.scan` it triggers a carry-mismatch error at best and a silent
recompile-per-tick on TPU at worst.  Checking the contract via
:func:`jax.eval_shape` costs a CPU trace (no FLOPs, no device buffers), so
promotion bugs fail in seconds in tier-1 instead of minutes into a TPU
run.

This is the trace-time half of the ``simlint`` static pass (rule R8,
``tools/simlint/RULES.md``): the AST side checks that every
``_phase_*`` function in the engine is registered in
:data:`PHASE_CONTRACTS`; the functions here actually trace them.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..spec import FogModel, WorldSpec


class ContractError(AssertionError):
    """A pytree violated its declared shape/dtype contract."""


def _leaf_struct(x) -> Tuple[tuple, str]:
    return (tuple(x.shape), np.dtype(x.dtype).name)


def assert_same_struct(expected, got, what: str = "pytree") -> None:
    """Raise :class:`ContractError` unless ``got`` has exactly the tree
    structure, shapes and dtypes of ``expected`` (weak-type flags are
    ignored: weak f32 and strong f32 lower identically)."""
    exp_paths, exp_def = jax.tree_util.tree_flatten_with_path(expected)
    got_paths, got_def = jax.tree_util.tree_flatten_with_path(got)
    # simlint: disable=R2 -- treedefs are host metadata: tree_flatten
    # returns (traced leaves, HOST treedef) and this branch compares
    # only the latter; the flow layer cannot split the tuple's halves
    if exp_def != got_def:
        raise ContractError(
            f"{what}: tree structure changed\n"
            f"  expected: {exp_def}\n  got:      {got_def}"
        )
    errs = []
    for (path, e), (_, g) in zip(exp_paths, got_paths):
        se, sg = _leaf_struct(e), _leaf_struct(g)
        if se != sg:
            errs.append(
                f"  {jax.tree_util.keystr(path)}: expected "
                f"{se[1]}{list(se[0])}, got {sg[1]}{list(sg[0])}"
            )
    if errs:
        raise ContractError(
            f"{what}: {len(errs)} leaf contract violation(s)\n"
            + "\n".join(errs)
        )


def _zero_buf(spec: WorldSpec):
    from .engine import TickBuf

    i32 = jnp.int32
    return TickBuf(
        tx_u=jnp.zeros((spec.n_users,), i32),
        rx_u=jnp.zeros((spec.n_users,), i32),
        tx_f=jnp.zeros((spec.n_fogs,), i32),
        rx_f=jnp.zeros((spec.n_fogs,), i32),
        tx_b=jnp.zeros((), i32),
        rx_b=jnp.zeros((), i32),
    )


@dataclasses.dataclass(frozen=True)
class PhaseContract:
    """One engine phase + how to invoke it for a shape-only trace.

    ``call(spec, state, net, cache, buf, t0, t1)`` returns the phase's
    raw result (``state`` or ``(state, buf[, extra])``); ``when`` gates
    phases that only trace under certain static specs (e.g. the dense
    broker path).
    """

    name: str
    call: Callable
    when: Callable[[WorldSpec], bool] = lambda spec: True


def _contracts() -> Tuple[PhaseContract, ...]:
    from . import engine as E

    fifo = lambda sp: sp.n_fogs > 0 and sp.fog_model == int(FogModel.FIFO)

    def fused_call(phase, with_t0):
        """Contract-trace a phase in fused (register-view) mode: build
        the view pack, run the phase, flush the write set — so the whole
        deferred-scatter dataflow is covered by the eval_shape trace,
        not just the classic per-phase path."""

        def call(sp, s, n, c, b, t0, t1):
            v = E._task_views(sp, s.tasks)
            args = (sp, s, n, c, b) + ((t0, t1) if with_t0 else (t1,))
            s2, b2, v2 = phase(*args, views=v)
            s2 = s2.replace(tasks=E._flush_task_views(sp, s2.tasks, v2))
            return s2, b2

        return call

    return (
        PhaseContract(
            "_phase_connect",
            lambda sp, s, n, c, b, t0, t1: E._phase_connect(
                sp, s, n, c, b, t0, t1
            ),
        ),
        PhaseContract(
            "_phase_adverts",
            lambda sp, s, n, c, b, t0, t1: E._phase_adverts(s, t1),
        ),
        PhaseContract(
            "_phase_spawn",
            lambda sp, s, n, c, b, t0, t1: E._phase_spawn(
                sp, s, n, c, b, t0, t1
            ),
        ),
        PhaseContract(
            "_phase_spawn_multi",
            lambda sp, s, n, c, b, t0, t1: E._phase_spawn_multi(
                sp, s, n, c, b, t0, t1
            ),
            when=lambda sp: sp.max_sends_per_tick > 1,
        ),
        PhaseContract(
            # chunk-boundary arrival injection (twin/ingest, ISSUE 17):
            # traced with its default all-padding batch — the contract
            # covers the full write dataflow (the padded rows take the
            # same masked-scatter path as real ones)
            "_phase_inject",
            lambda sp, s, n, c, b, t0, t1: E._phase_inject(
                sp, s, n, c, b, t0, t1
            )[:2],
            when=lambda sp: sp.ingest,
        ),
        PhaseContract(
            "_phase_v2_release",
            lambda sp, s, n, c, b, t0, t1: E._phase_v2_release(
                sp, s, n, c, b, t1, before_broker=True
            ),
        ),
        PhaseContract(
            "_phase_broker",
            lambda sp, s, n, c, b, t0, t1: E._phase_broker(
                sp, s, n, c, b, t1
            )[:2],
        ),
        PhaseContract(
            "_phase_broker_dense",
            lambda sp, s, n, c, b, t0, t1: E._phase_broker_dense(
                sp, s, n, c, b, t1
            ),
            when=E._broker_dense_ok,
        ),
        PhaseContract(
            "_phase_completions",
            lambda sp, s, n, c, b, t0, t1: E._phase_completions(
                sp, s, n, c, b, t1
            ),
            when=fifo,
        ),
        PhaseContract(
            "_phase_fog_arrivals",
            lambda sp, s, n, c, b, t0, t1: E._phase_fog_arrivals(
                sp, s, n, c, b, t1
            ),
            when=fifo,
        ),
        PhaseContract(
            "_phase_pool_completions",
            lambda sp, s, n, c, b, t0, t1: E._phase_pool_completions(
                sp, s, n, c, b, t1
            ),
            when=lambda sp: sp.n_fogs > 0,
        ),
        PhaseContract(
            "_phase_pool_arrivals",
            lambda sp, s, n, c, b, t0, t1: E._phase_pool_arrivals(
                sp, s, n, c, b, t1
            ),
            when=lambda sp: sp.n_fogs > 0,
        ),
        PhaseContract(
            "_phase_chaos",
            lambda sp, s, n, c, b, t0, t1: E._phase_chaos(
                sp, s, n, c, b, t0, t1
            ),
            when=lambda sp: sp.chaos,
        ),
        PhaseContract(
            "_phase_broker_migrate",
            lambda sp, s, n, c, b, t0, t1: E._phase_broker_migrate(
                sp, s, n, c, b, t0, t1
            ),
            when=lambda sp: E._hier_migrate_on(sp),
        ),
        PhaseContract(
            "_phase_learn_credit",
            lambda sp, s, n, c, b, t0, t1: E._phase_learn_credit(
                sp, s, n, c, b, t1
            ),
            when=lambda sp: sp.learn_active,
        ),
        PhaseContract(
            "_phase_journeys",
            lambda sp, s, n, c, b, t0, t1: E._phase_journeys(
                sp, s, n, c, b, t1
            ),
            when=lambda sp: sp.journey_active,
        ),
        PhaseContract(
            "_phase_telemetry",
            lambda sp, s, n, c, b, t0, t1: E._phase_telemetry(
                sp, s, n, c, b, t1
            ),
            when=lambda sp: sp.telemetry,
        ),
        PhaseContract(
            "_phase_latency_hist",
            lambda sp, s, n, c, b, t0, t1: E._phase_latency_hist(
                sp, s, n, c, b, t1
            ),
            when=lambda sp: sp.telemetry_hist,
        ),
        PhaseContract(
            "_phase_local_completions",
            lambda sp, s, n, c, b, t0, t1: E._phase_local_completions(
                sp, s, n, c, b, t1
            ),
        ),
        # ---- fused per-user slot-window front-end (spec.fused_slots) --
        # The same phase functions, traced in register-view mode with
        # the write-set flush included: the tick's fused dataflow is
        # contract-covered end to end (tests/test_contracts.py).
        PhaseContract(
            "_phase_spawn",
            fused_call(E._phase_spawn, with_t0=True),
            when=lambda sp: E._fused_ok(sp) and sp.max_sends_per_tick == 1,
        ),
        PhaseContract(
            "_phase_spawn_multi",
            fused_call(E._phase_spawn_multi, with_t0=True),
            when=lambda sp: E._fused_ok(sp) and sp.max_sends_per_tick > 1,
        ),
        PhaseContract(
            "_phase_broker_migrate",
            fused_call(E._phase_broker_migrate, with_t0=True),
            when=lambda sp: E._hier_migrate_on(sp) and E._fused_ok(sp),
        ),
        PhaseContract(
            "_phase_broker_dense",
            fused_call(E._phase_broker_dense, with_t0=False),
            when=E._fused_ok,
        ),
        PhaseContract(
            "_phase_completions",
            fused_call(E._phase_completions, with_t0=False),
            when=E._fused_ok,
        ),
        PhaseContract(
            "_phase_fog_arrivals",
            fused_call(E._phase_fog_arrivals, with_t0=False),
            when=E._fused_ok,
        ),
        PhaseContract(
            "_phase_periodic_adverts",
            lambda sp, s, n, c, b, t0, t1: E._phase_periodic_adverts(
                sp, s, n, c, t0, t1
            ),
        ),
    )


# The registry simlint R8 checks engine `_phase_*` definitions against.
# Adding a phase to core/engine.py without registering it here is a lint
# failure; registering it without a passing eval_shape trace is a tier-1
# test failure (tests/test_contracts.py).
PHASE_CONTRACTS: Tuple[PhaseContract, ...] = _contracts()


def check_phase_contracts(spec: WorldSpec, state, net) -> Tuple[str, ...]:
    """eval_shape every phase applicable under ``spec``; raise
    :class:`ContractError` on any carry-structure change.  Returns the
    names of the phases actually checked."""
    from ..net.topology import associate

    checked = []
    for pc in PHASE_CONTRACTS:
        if not pc.when(spec):
            continue

        def trace(s, _call=pc.call):
            cache = associate(
                net, s.nodes.pos, s.nodes.alive, broker=spec.broker_index
            )
            buf = _zero_buf(spec)
            t0 = jnp.float32(0.0)
            t1 = jnp.float32(spec.dt)
            return _call(spec, s, net, cache, buf, t0, t1)

        out = jax.eval_shape(trace, state)
        new_state = out[0] if isinstance(out, tuple) else out
        assert_same_struct(state, new_state, what=f"{pc.name}: WorldState")
        if isinstance(out, tuple) and len(out) >= 2:
            assert_same_struct(
                _zero_buf(spec), out[1], what=f"{pc.name}: TickBuf"
            )
        checked.append(pc.name)
    return tuple(checked)


def check_step_contract(
    spec: WorldSpec, state, net, bounds=None, step: Optional[Callable] = None
) -> None:
    """The whole-tick contract: ``step`` must be a `lax.scan`-safe carry
    endomorphism.  ``step`` defaults to :func:`engine.make_step`; pass a
    wrapper to test instrumented steps."""
    from ..net.mobility import default_bounds
    from .engine import make_step

    if bounds is None:
        bounds = default_bounds()
    if step is None:
        step = make_step(spec)
    got = jax.eval_shape(lambda s: step(s, net, bounds), state)
    assert_same_struct(state, got, what="tick carry (lax.scan endomorphism)")


def check_telemetry_contract(spec: WorldSpec, state) -> None:
    """The TelemetryState carry contract (ISSUE 4).

    Two halves: (a) the sizing gate — with ``spec.telemetry`` off every
    telemetry array leaf must have zero rows (the inert-LearnState
    discipline: untelemetered worlds pay no memory and stay bit-exact),
    with it on the leaves carry the real per-fog / per-phase /
    reservoir dimensions; (b) the accumulation endomorphism — one
    eval_shape trace of the engine's ``_phase_telemetry`` must preserve
    the whole WorldState structure, or the scan carry would mismatch /
    silently recompile mid-run.
    """
    from ..telemetry.journeys import J_COLS
    from ..telemetry.metrics import EXG_OCC_BINS, PHASES, RES_FIELDS

    t = state.telem
    F = spec.n_fogs if spec.telemetry else 0
    P = len(PHASES) if spec.telemetry else 0
    R = spec.telemetry_slots
    # TP exchange-plane leaves (ISSUE 11): zero-row unless the spec is a
    # stamped TP world view (spec.tp_shards, set by run_tp_sharded) with
    # telemetry on — nested inside spec.telemetry like the hist gate
    S = spec.telemetry_tp_shards
    Rs = R if S else 0
    expect = {
        "q_len_sum": (F,), "q_len_max": (F,), "q_len_min": (F,),
        "busy_ticks": (F,), "pool_occ_sum": (F,), "pick_hist": (F,),
        "phase_work": (P,), "res": (R, len(RES_FIELDS)),
        "ticks": (), "defer_sum": (),
        # streaming latency histogram (ISSUE 6): zero-row unless the
        # spec.telemetry_hist gate is on — its OWN gate, nested inside
        # spec.telemetry, so plain-telemetry worlds stay unchanged
        "lat_hist": (spec.telemetry_hist_fogs, spec.telemetry_hist_nbins),
        "lat_sum": (spec.telemetry_hist_fogs,),
        "lat_seen": (spec.telemetry_hist_tasks,),
        "exg_occ_hist": (S, EXG_OCC_BINS),
        "exg_occ_sum": (S,), "exg_cand_sum": (S,),
        "exg_defer_sum": (S,), "exg_defer_max": (S,),
        "exg_util_sum": (S,), "exg_age_max": (S,),
        "exg_occ_res": (Rs, S),
        # federated hierarchy (hier/): zero-row unless the spec is a
        # telemetry-on multi-broker world — nested inside
        # spec.telemetry like the hist/TP gates
        "hier_load_sum": (spec.telemetry_hier_brokers,),
        "hier_load_res": (
            R if spec.telemetry_hier_brokers else 0,
            spec.telemetry_hier_brokers,
        ),
        # causal task-journey rings (ISSUE 15): zero-row unless the
        # spec.telemetry_journeys gate is on — its OWN gate, nested
        # inside spec.telemetry like the hist/TP/hier gates
        "j_task": (spec.journey_slots,),
        "j_prev": (spec.journey_slots, len(J_COLS)),
        "j_ring": (spec.journey_slots, spec.journey_ring, 4),
        "j_cursor": (spec.journey_slots,),
        "j_dropped": (),
    }
    for name, shape in expect.items():
        got = tuple(getattr(t, name).shape)
        if got != shape:
            raise ContractError(
                f"TelemetryState.{name}: expected shape {shape} under "
                f"telemetry={spec.telemetry}, got {got}"
            )
    if spec.telemetry:
        from . import engine as E

        def trace(s):
            buf = _zero_buf(spec)
            return E._phase_telemetry(
                spec, s, None, None, buf, jnp.float32(spec.dt)
            )

        out = jax.eval_shape(trace, state)
        assert_same_struct(
            state, out[0], what="_phase_telemetry: WorldState"
        )


def check_fleet_contract(spec: WorldSpec, batch, net, bounds=None) -> None:
    """The fleet carry contract (ISSUE 3): the *replica-batched* tick
    step must also be a carry endomorphism — ``vmap(step)`` over the
    leading replica axis preserves every leaf's shape and dtype, so the
    sharded fleet scan (:mod:`fognetsimpp_tpu.parallel.fleet`) never
    recompiles mid-run or silently promotes the batched carry.

    ``batch`` is a replicated world from
    :func:`fognetsimpp_tpu.parallel.replicas.replicate_state`.  A plain
    eval_shape trace: no FLOPs, no device buffers, mesh-independent
    (sharding never changes shapes/dtypes, so one unsharded trace
    covers every mesh layout).
    """
    from ..net.mobility import default_bounds
    from .engine import make_step

    if bounds is None:
        bounds = default_bounds()
    step = make_step(spec)
    got = jax.eval_shape(
        lambda b: jax.vmap(lambda s: step(s, net, bounds))(b), batch
    )
    assert_same_struct(
        batch, got, what="fleet carry (vmap(step) endomorphism)"
    )
