"""Persistent XLA compilation cache.

A full-horizon scan compiles in ~10-50 s per world shape (TPU or CPU);
the persistent cache brings warm-process compiles down to tracing cost
(measured 49.5 s -> 18.3 s across processes on the v5e for a 2k-user
world).  Enabled by the CLI, bench entry points, and the test harness;
set ``FNS_JIT_CACHE`` to relocate or ``FNS_JIT_CACHE=off`` to disable.

The cache directory is keyed by the host CPU model: XLA:CPU stores AOT
results compiled for the build host's exact feature set, and loading
them on a host without those features is a documented SIGILL risk (it
intermittently segfaulted the test suite when the cache travelled
between heterogeneous machines, r4).
"""
from __future__ import annotations

import hashlib
import os
import platform
from typing import Optional


def _host_tag() -> str:
    """Short stable tag for this host's CPU capability set."""
    bits = [platform.machine(), platform.processor()]
    try:
        with open("/proc/cpuinfo") as f:
            for ln in f:
                if ln.startswith("flags") or ln.startswith("Features"):
                    bits.append(ln.strip())
                    break
    except OSError:
        pass
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:12]


def enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    env = os.environ.get("FNS_JIT_CACHE")
    if env is not None and env.strip().lower() in ("off", "0", "false", ""):
        return None
    path = path or env or os.path.expanduser(
        f"~/.cache/fognetsimpp_tpu/jit-{_host_tag()}"
    )
    try:
        import jax

        if jax.default_backend() == "cpu":
            # Serializing certain XLA:CPU executables segfaults inside
            # jaxlib's compilation-cache write path (reproduced r4 with
            # faulthandler on the policy-grid program); accelerator
            # executables are unaffected.  The cache's payoff is on the
            # accelerator anyway — skip it on CPU.
            return None
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except OSError:
        # pure optimization: an unwritable cache dir degrades to no cache
        return None
    return path
