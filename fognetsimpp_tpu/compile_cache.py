"""Persistent XLA compilation cache.

A full-horizon scan compiles in ~10-50 s per world shape (TPU or CPU);
the persistent cache brings warm-process compiles down to tracing cost
(measured 49.5 s -> 18.3 s across processes on the v5e for a 2k-user
world).  Enabled by the CLI, bench entry points, and the test harness;
set ``FNS_JIT_CACHE`` to relocate or ``FNS_JIT_CACHE=off`` to disable.

The cache directory is keyed by the host CPU model: XLA:CPU stores AOT
results compiled for the build host's exact feature set, and loading
them on a host without those features is a documented SIGILL risk (it
intermittently segfaulted the test suite when the cache travelled
between heterogeneous machines, r4).
"""
from __future__ import annotations

import hashlib
import os
import platform
import threading
from typing import Callable, Dict, Optional

# ----------------------------------------------------------------------
# compile-latency observability (ISSUE 6): the ROADMAP's streaming
# serving mode is blocked on 8-56 s compiles vs sub-second run walls, so
# hit/miss/compile-seconds become first-class metrics — surfaced in the
# bench JSON, the OpenMetrics exposition and the flight recorder.
# ----------------------------------------------------------------------

_LOCK = threading.Lock()
_STATS: Dict[str, float] = {
    "cache_hits": 0,  # persistent-cache executable loads
    "cache_misses": 0,  # compiles the cache could not serve
    "compiles": 0,  # backend compile events observed
    "compile_s_total": 0.0,  # wall seconds spent compiling
    "compile_s_max": 0.0,  # worst single compile
}
_CACHE_DIR: Optional[str] = None
_LISTENING = False
#: Extra stat sections merged into :func:`compile_stats` output under
#: their registered name (e.g. the dynspec program registry) — callers
#: get ONE dict for the bench JSON / OpenMetrics / flight recorder.
_PROVIDERS: Dict[str, Callable[[], Dict]] = {}


def _on_event(event: str, **kw) -> None:
    with _LOCK:
        if event.endswith("cache_hits") or event.endswith("cache_hit"):
            _STATS["cache_hits"] += 1
        elif event.endswith("cache_misses") or event.endswith("cache_miss"):
            _STATS["cache_misses"] += 1


def _on_duration(event: str, duration: float, **kw) -> None:
    if "compile" not in event or "saved" in event:
        return
    with _LOCK:
        _STATS["compiles"] += 1
        _STATS["compile_s_total"] += float(duration)
        _STATS["compile_s_max"] = max(
            _STATS["compile_s_max"], float(duration)
        )


def _ensure_listeners() -> None:
    """Register the jax.monitoring listeners once (idempotent; a jax
    without the monitoring API degrades to manual :func:`note_compile`
    accounting only)."""
    global _LISTENING
    if _LISTENING:
        return
    try:
        from jax._src import monitoring

        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
        _LISTENING = True
    except Exception:
        pass


def note_compile(seconds: float, cache_hit: Optional[bool] = None) -> None:
    """Manual accounting entry for callers that time their own cold
    calls (``bench.py`` ``compile_s``, the live loop's first chunk) —
    the fallback when the monitoring listeners are unavailable, and the
    place wall-clock truth (trace + compile + dispatch) is recorded
    next to the listener's pure-compile seconds."""
    with _LOCK:
        _STATS.setdefault("noted_compiles", 0)
        _STATS.setdefault("noted_compile_s_total", 0.0)
        _STATS["noted_compiles"] += 1
        _STATS["noted_compile_s_total"] += float(seconds)
        if cache_hit is not None:
            key = "cache_hits" if cache_hit else "cache_misses"
            _STATS[key] += 1


def compile_stats() -> Dict:
    """Snapshot of the process's compile-latency counters.

    Keys: ``cache_hits`` / ``cache_misses`` (persistent-cache events),
    ``compiles`` / ``compile_s_total`` / ``compile_s_max`` (backend
    compile durations from jax.monitoring), the ``noted_*`` manual
    entries, plus ``cache_dir`` (None when the cache is disabled) and
    one section per registered stats provider (ISSUE 13: the
    ``program_registry`` shape-bucket accounting rides here).
    """
    with _LOCK:
        out: Dict = dict(_STATS)
        providers = dict(_PROVIDERS)
    out["cache_dir"] = _CACHE_DIR
    for name, fn in providers.items():
        try:
            out[name] = fn()
        except Exception:
            # observability must never take down the serving loop
            out[name] = None
    return out


def register_stats_provider(name: str, fn: Callable[[], Dict]) -> None:
    """Attach an extra stats section to :func:`compile_stats` output.

    Idempotent per name (last registration wins); the provider must be
    cheap and exception-safe — it runs on every stats snapshot, which
    the ``--serve`` loop takes per chunk.
    """
    with _LOCK:
        _PROVIDERS[name] = fn


def snapshot() -> Dict[str, float]:
    """Point-in-time copy of the NUMERIC compile counters.

    ``compile_stats()`` is cumulative process-wide, so bench rounds and
    serve chunks could never attribute compile seconds to themselves
    (ISSUE 13 satellite); pair this with :func:`delta_since` to scope
    an interval:

        before = compile_cache.snapshot()
        ...  # the warm re-configure / bench round / serve chunk
        d = compile_cache.delta_since(before)
        assert d["compiles"] == 0
    """
    with _LOCK:
        return {
            k: float(v) for k, v in _STATS.items()
            if isinstance(v, (int, float))
        }


def delta_since(before: Dict[str, float]) -> Dict[str, float]:
    """Numeric counter deltas since a :func:`snapshot`.

    Counters that appeared after the snapshot (e.g. the first
    ``noted_*`` entry) delta from zero; ``compile_s_max`` is a running
    maximum, not a counter, so its delta is the NEW max when it grew
    (0.0 otherwise) — a zero means no compile observed since the
    snapshot beat the prior worst.
    """
    now = snapshot()
    out: Dict[str, float] = {}
    for k, v in now.items():
        prev = float(before.get(k, 0.0))
        if k == "compile_s_max":
            out[k] = v if v > prev else 0.0
        else:
            out[k] = v - prev
    return out


def _host_tag() -> str:
    """Short stable tag for this host's CPU capability set."""
    bits = [platform.machine(), platform.processor()]
    try:
        with open("/proc/cpuinfo") as f:
            for ln in f:
                if ln.startswith("flags") or ln.startswith("Features"):
                    bits.append(ln.strip())
                    break
    except OSError:
        pass
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:12]


def enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    global _CACHE_DIR
    _ensure_listeners()  # compile stats flow even when the cache is off
    env = os.environ.get("FNS_JIT_CACHE")
    if env is not None and env.strip().lower() in ("off", "0", "false", ""):
        return None
    path = path or env or os.path.expanduser(
        f"~/.cache/fognetsimpp_tpu/jit-{_host_tag()}"
    )
    try:
        import jax

        if jax.default_backend() == "cpu":
            # Serializing certain XLA:CPU executables segfaults inside
            # jaxlib's compilation-cache write path (reproduced r4 with
            # faulthandler on the policy-grid program); accelerator
            # executables are unaffected.  The cache's payoff is on the
            # accelerator anyway — skip it on CPU.
            return None
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except OSError:
        # pure optimization: an unwritable cache dir degrades to no cache
        return None
    _CACHE_DIR = path
    return path
