"""Persistent XLA compilation cache.

A full-horizon scan compiles in ~10-50 s per world shape (TPU or CPU);
the persistent cache brings warm-process compiles down to tracing cost
(measured 49.5 s -> 18.3 s across processes on the v5e for a 2k-user
world).  Enabled by the CLI, bench entry points, and the test harness;
set ``FNS_JIT_CACHE`` to relocate or ``FNS_JIT_CACHE=off`` to disable.
"""
from __future__ import annotations

import os
from typing import Optional


def enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    env = os.environ.get("FNS_JIT_CACHE")
    if env is not None and env.strip().lower() in ("off", "0", "false", ""):
        return None
    path = path or env or os.path.expanduser("~/.cache/fognetsimpp_tpu/jit")
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except OSError:
        # pure optimization: an unwritable cache dir degrades to no cache
        return None
    return path
