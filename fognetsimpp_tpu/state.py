"""Device-resident world state: one pytree of fixed-shape arrays.

This is the TPU-native reformulation of the reference's heap-allocated
bookkeeping: the broker's ``clients[] / brokers[] / requests[]`` vectors
(``src/mqttapp/BrokerBaseApp3.h:26-63``), each fog node's ``requests[]``
FIFO + ``currentTask`` (``src/mqttapp/ComputeBrokerApp3.h:26-88``) and each
client's ``uploadedTasks[]`` table (``src/mqttapp/mqttApp2.h``) all become
columns of dense arrays indexed by integer ids.

Checkpoint/resume — absent from the reference (SURVEY.md §5) — is trivial
here: the whole world is this one pytree; snapshot = save it plus the spec.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from .chaos.faults import ChaosState, init_chaos_state
from .hier.federation import HierState, init_hier_state
from .learn.bandits import LearnState, init_learn_state
from .spec import NodeKind, Policy, Stage, WorldSpec
from .telemetry.metrics import TelemetryState, init_telemetry_state

# Sentinel for "no task": valid task ids are [0, T).
NO_TASK = -1
INF = jnp.inf


@struct.dataclass
class NodeState:
    """Per-node physical/platform state, length ``spec.n_nodes``.

    Layout along the node axis: [users | fogs | broker | aps | routers]
    (see :class:`~fognetsimpp_tpu.spec.WorldSpec` index helpers).
    """

    kind: jax.Array  # (N,) int8 NodeKind
    pos: jax.Array  # (N, 2) f32 metres
    alive: jax.Array  # (N,) bool — lifecycle status (wireless5.ini:153)
    # mobility (net/mobility.py)
    mobility: jax.Array  # (N,) int8 Mobility enum
    vel: jax.Array  # (N, 2) f32 m/s (LINEAR)
    circle_center: jax.Array  # (N, 2) f32 (CIRCLE)
    circle_radius: jax.Array  # (N,) f32
    circle_omega: jax.Array  # (N,) f32 rad/s (speed / radius)
    circle_phase: jax.Array  # (N,) f32 rad
    # energy (net/energy.py; SimpleEpEnergyStorage per wireless5.ini:156)
    energy: jax.Array  # (N,) f32 joules
    energy_capacity: jax.Array  # (N,) f32 joules
    has_energy: jax.Array  # (N,) bool — node participates in energy model
    # wired-link DropTailQueue analog (spec.wired_queue_enabled):
    link_backlog: jax.Array  # (N,) f32 bytes queued on the access link
    link_drop_p: jax.Array  # (N,) f32 next-tick DropTail loss probability
    # cumulative per-node message counters (the reference's per-module
    # "packets sent"/"packets received" .sca rows; INET's per-NIC
    # statistics analog — persisted by runtime/recorder.py)
    tx_count: jax.Array  # (N,) i32 messages sent over the whole run
    rx_count: jax.Array  # (N,) i32 messages received
    assoc_sum: jax.Array  # (N,) i32 — AP slots: summed per-tick station
    #   counts (mean occupancy = assoc_sum / ticks); zero elsewhere


@struct.dataclass
class UserState:
    """Per-user application state (mqttApp2 equivalents), length U."""

    next_send: jax.Array  # (U,) f32 next publish time (selfMsg MQTTDATA)
    send_count: jax.Array  # (U,) i32 messageCount (mqttApp2.cc:355)
    send_interval: jax.Array  # (U,) f32 per-user interval (volatile par)
    connected: jax.Array  # (U,) bool got Connack (mqttApp2.cc:244-251)
    # --- MQTT control plane (spec.connect_gating) ----------------------
    start_t: jax.Array  # (U,) f32 app start time (processStart sends Connect)
    connack_at: jax.Array  # (U,) f32 Connack arrival at the user (+inf until
    #                         the connect phase stamps it)
    publisher: jax.Array  # (U,) bool role mask: publishes tasks (the pub/sub
    #                        split of testing/omnetpp.ini:18-21)
    pub_topic: jax.Array  # (U,) i32 topic id this user publishes on
    sub_mask: jax.Array  # (U, n_topics) bool subscription table (the
    #                       broker's subscriptions[] vector, BrokerBaseApp3
    #                       .cc:201-218, transposed to per-user rows)
    n_delivered: jax.Array  # (U,) i32 publishes fanned out to this user
    #                          (publishAll, BrokerBaseApp3.cc:365-385)


@struct.dataclass
class FogState:
    """Per-fog-node (compute broker) state, length F.

    v3 single-server FIFO semantics (``ComputeBrokerApp3.cc:258-314``):
    ``current_task``/``busy_until`` model the in-service task, ``queue`` the
    ``requests[]`` vector as a ring buffer, ``busy_time`` the advertised
    backlog scalar.
    """

    mips: jax.Array  # (F,) f32 par("MIPS")
    busy_time: jax.Array  # (F,) f32 fog's own busyTime accumulator
    current_task: jax.Array  # (F,) i32 task id or NO_TASK
    busy_until: jax.Array  # (F,) f32 absolute finish time of current task
    free_since: jax.Array  # (F,) f32 when an idle fog last became idle (an
    #                         arrival earlier than this still starts service
    #                         here — the event-driven server was busy then)
    queue: jax.Array  # (F, Q) i32 task ids (ring buffer)
    q_head: jax.Array  # (F,) i32
    q_len: jax.Array  # (F,) i32
    q_drops: jax.Array  # (F,) i32 overflow counter (no reference analog)
    # v1/v2 MIPS-pool model (ComputeBrokerApp2.cc:272-310)
    pool_avail: jax.Array  # (F,) f32 remaining MIPS in the pool


@struct.dataclass
class BrokerView:
    """The base broker's (possibly stale) table of fog nodes.

    Mirrors ``brokers[]`` (``BrokerBaseApp3.cc:104,123-136``): entries are
    refreshed only when a ``FognetMsgAdvertiseMIPS`` *arrives*; between
    advertisements the scheduler argmin runs on stale data.  In-flight
    advertisements are modelled as one pending (value, arrival-time) slot per
    fog node: latest-wins, matching the overwrite-on-arrival semantics.
    """

    view_mips: jax.Array  # (F,) f32 broker's last-seen MIPS per fog
    view_busy: jax.Array  # (F,) f32 broker's last-seen busyTime per fog
    registered: jax.Array  # (F,) bool fog's Connect has arrived
    register_t: jax.Array  # (F,) f32 when the fog's Connect arrives at the
    #                         broker (brokers.push_back, BrokerBaseApp3.cc:
    #                         102-107); +inf = never (connect_gating off
    #                         initialises it to 0: born registered)
    adv_val_mips: jax.Array  # (F,) f32 in-flight advertisement payload
    adv_val_busy: jax.Array  # (F,) f32
    adv_arrive_t: jax.Array  # (F,) f32 arrival time (+inf = none in flight)
    rr_next: jax.Array  # () i32 round-robin cursor (Policy.ROUND_ROBIN)
    local_pool: jax.Array  # () f32 broker's own MIPS pool (v1 LOCAL_FIRST)
    release_timer_t: jax.Array  # () f32 — the v2 broker's single shared
    #   RELEASERESOURCE self-message (spec.v2_local_broker): +inf = none
    #   pending; every accept overwrites it (cancelEvent + scheduleAt)
    policy_id: jax.Array  # () i32 — the live policy under Policy.DYNAMIC
    #   (ids 0-4; ignored otherwise).  Traced, so replicas in one vmap can
    #   each run a different scheduler (single-compile EP sweeps).


@struct.dataclass
class TaskState:
    """Task lifecycle table, capacity T = U * max_sends_per_user.

    Slot ``u * max_sends_per_user + k`` is statically owned by user ``u``'s
    ``k``-th publish, so allocation is a pure index computation.  The time
    columns hold *exact* event times (sums of link delays and service times),
    not tick-quantised values; the tick only controls when state transitions
    are observed.  Ack-time columns become the reference's client signals:
    latencyH1/latency/taskTime in milliseconds (``mqttApp2.cc:256-291``),
    queueTime at the fog (``ComputeBrokerApp3.cc:238``), and the broker's
    ``delay`` signal (``BrokerBaseApp3.cc:143``).
    """

    stage: jax.Array  # (T,) int8 Stage
    user: jax.Array  # (T,) i32 originating user index — static (slot layout
    #   u*S+k); kept as a materialised column for host-side readers
    #   (recorder, parity replay); the engine derives it as idx // S instead
    #   of gathering.  The publish topic is likewise derived
    #   (users.pub_topic[user], MqttMsgPublish.msg:22), not stored.
    fog: jax.Array  # (T,) i32 assigned fog index (NO_TASK before)
    mips_req: jax.Array  # (T,) f32 MIPSRequired
    t_create: jax.Array  # (T,) f32 publish creation time
    t_at_broker: jax.Array  # (T,) f32 publish arrival at base broker
    t_at_fog: jax.Array  # (T,) f32 FognetMsgTask arrival at fog
    t_service_start: jax.Array  # (T,) f32
    t_complete: jax.Array  # (T,) f32
    t_q_enter: jax.Array  # (T,) f32 queueStartTime (ComputeBrokerApp3.cc:306)
    # client-side ack arrival times (absolute seconds; +inf = not received)
    t_ack3: jax.Array  # (T,) v1 local-accept "processing" status-3
    #                     (BrokerBaseApp.cc:212)
    t_ack4_fwd: jax.Array  # (T,) broker's own "forwarded" status-4
    t_ack4_queued: jax.Array  # (T,) relayed fog "queued" status-4
    t_ack5: jax.Array  # (T,) relayed "assigned" status-5
    t_ack6: jax.Array  # (T,) relayed "performed" status-6
    queue_time_ms: jax.Array  # (T,) f32 fog queueTime signal (ms)
    req_open: jax.Array  # (T,) i8 — task sits in the v2 broker's
    #   requests[] table awaiting its releaseResource (local accepts AND
    #   offloaded publishes, BrokerBaseApp2.cc:212/:244); always 0 when
    #   spec.v2_local_broker is off


@struct.dataclass
class Metrics:
    """Running counters (the reference's WATCH/numSent/numEchoed analogs)."""

    n_published: jax.Array  # () i32 total publishes sent
    n_scheduled: jax.Array  # () i32 broker scheduling decisions
    n_completed: jax.Array  # () i32 tasks completed
    n_dropped: jax.Array  # () i32 queue overflows
    n_no_resource: jax.Array  # () i32 publishes with no fog registered
    n_connected: jax.Array  # () i32 users whose Connack arrived (numClients)
    n_subscribed: jax.Array  # () i32 subscriptions acked (numSubscribed)
    n_fanout: jax.Array  # () i32 publishAll deliveries to subscribers
    n_rejected: jax.Array  # () i32 pool rejections / v1 unsendable offloads
    n_local: jax.Array  # () i32 tasks run locally on the broker (v1)
    n_adverts: jax.Array  # () i32 FognetMsgAdvertiseMIPS delivered to the
    #                        broker (latest-wins slot: superseded in-flight
    #                        adverts are merged, as in BrokerView)
    n_lost: jax.Array  # () i32 publishes lost on the wireless uplink or
    #                      to a DropTail wired-queue overflow
    n_link_drops: jax.Array  # () i32 frames dropped by full wired queues
    #                           (spec.wired_queue_enabled)
    n_deferred: jax.Array  # () i32 — matured-but-undecided tasks left
    #   behind by this tick's arrival-window compactions (gauge, reset
    #   each tick; conservation holds — they are decided in later ticks)
    n_deferred_max: jax.Array  # () i32 — running max of that backlog
    #   over the run: 0 means the window never overflowed (the engine
    #   was "current" every tick)


@struct.dataclass
class WorldState:
    """The full world: one pytree. ``t`` is the tick-boundary clock."""

    t: jax.Array  # () f32 current time (start of tick)
    tick: jax.Array  # () i32
    key: jax.Array  # PRNG key
    nodes: NodeState
    users: UserState
    fogs: FogState
    broker: BrokerView
    tasks: TaskState
    metrics: Metrics
    learn: LearnState  # bandit-scheduler state (learn/bandits.py);
    #   inert zero-row provenance when spec.learn_active is False
    chaos: ChaosState  # fault-injection schedules/counters
    #   (chaos/faults.py); zero-row when spec.chaos is off
    hier: HierState  # federated multi-broker ownership/migration state
    #   (hier/federation.py); zero-row when spec.n_brokers == 1
    telem: TelemetryState  # device-resident observability accumulators
    #   (telemetry/metrics.py); zero-row when spec.telemetry is off


def init_state(spec: WorldSpec, key: Optional[jax.Array] = None) -> WorldState:
    """Build the t=0 world for ``spec`` with default placements.

    Scenario builders (:mod:`fognetsimpp_tpu.scenarios`) refine positions,
    mobility, MIPS and energy after calling this.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    N, U, F, T, Q = (
        spec.n_nodes,
        spec.n_users,
        spec.n_fogs,
        spec.task_capacity,
        spec.queue_capacity,
    )
    f32 = jnp.float32

    kind = jnp.zeros((N,), jnp.int8)
    kind = kind.at[spec.fog_slice[0] : spec.fog_slice[1]].set(int(NodeKind.FOG))
    kind = kind.at[spec.broker_index].set(int(NodeKind.BROKER))
    if spec.n_aps:
        kind = kind.at[spec.ap_slice[0] : spec.ap_slice[1]].set(int(NodeKind.AP))
    if spec.n_routers:
        kind = kind.at[spec.ap_slice[1] :].set(int(NodeKind.ROUTER))

    nodes = NodeState(
        kind=kind,
        pos=jnp.zeros((N, 2), f32),
        alive=jnp.ones((N,), bool),
        mobility=jnp.zeros((N,), jnp.int8),
        vel=jnp.zeros((N, 2), f32),
        circle_center=jnp.zeros((N, 2), f32),
        circle_radius=jnp.zeros((N,), f32),
        circle_omega=jnp.zeros((N,), f32),
        circle_phase=jnp.zeros((N,), f32),
        energy=jnp.full((N,), spec.energy_capacity_j, f32),
        energy_capacity=jnp.full((N,), spec.energy_capacity_j, f32),
        has_energy=jnp.zeros((N,), bool),
        link_backlog=jnp.zeros((N,), f32),
        link_drop_p=jnp.zeros((N,), f32),
        tx_count=jnp.zeros((N,), jnp.int32),
        rx_count=jnp.zeros((N,), jnp.int32),
        assoc_sum=jnp.zeros((N,), jnp.int32),
    )

    key, k_start = jax.random.split(key)
    start = jax.random.uniform(
        k_start,
        (U,),
        f32,
        minval=spec.start_time_min,
        maxval=max(spec.start_time_max, spec.start_time_min + 1e-9),
    )
    gating = spec.connect_gating
    users = UserState(
        next_send=jnp.full((U,), jnp.inf, f32) if gating else start,
        send_count=jnp.zeros((U,), jnp.int32),
        send_interval=jnp.full((U,), spec.send_interval, f32),
        connected=jnp.full((U,), not gating, bool),
        start_t=start,
        connack_at=jnp.full((U,), jnp.inf, f32),
        publisher=jnp.ones((U,), bool),
        pub_topic=jnp.zeros((U,), jnp.int32),
        sub_mask=jnp.zeros((U, spec.n_topics), bool),
        n_delivered=jnp.zeros((U,), jnp.int32),
    )

    fogs = FogState(
        mips=jnp.full((F,), 1000.0, f32),
        busy_time=jnp.zeros((F,), f32),
        current_task=jnp.full((F,), NO_TASK, jnp.int32),
        busy_until=jnp.full((F,), jnp.inf, f32),
        free_since=jnp.full((F,), -jnp.inf, f32),
        queue=jnp.full((F, Q), NO_TASK, jnp.int32),
        q_head=jnp.zeros((F,), jnp.int32),
        q_len=jnp.zeros((F,), jnp.int32),
        q_drops=jnp.zeros((F,), jnp.int32),
        pool_avail=jnp.full((F,), 1000.0, f32),
    )

    view_mips0 = 0.0 if spec.bug_compat.zero_initial_view_mips else 1000.0
    broker = BrokerView(
        view_mips=jnp.full((F,), view_mips0, f32),
        view_busy=jnp.zeros((F,), f32),
        registered=jnp.full((F,), not gating, bool),
        register_t=jnp.full((F,), jnp.inf if gating else 0.0, f32),
        adv_val_mips=jnp.zeros((F,), f32),
        adv_val_busy=jnp.zeros((F,), f32),
        adv_arrive_t=jnp.full((F,), jnp.inf, f32),
        rr_next=jnp.zeros((), jnp.int32),
        local_pool=jnp.asarray(spec.broker_mips, f32),
        release_timer_t=jnp.asarray(jnp.inf, f32),
        policy_id=jnp.asarray(
            0 if spec.policy == int(Policy.DYNAMIC) else spec.policy,
            jnp.int32,
        ),
    )

    tasks = TaskState(
        stage=jnp.zeros((T,), jnp.int8),
        user=jnp.repeat(jnp.arange(U, dtype=jnp.int32), spec.max_sends_per_user),
        fog=jnp.full((T,), NO_TASK, jnp.int32),
        mips_req=jnp.zeros((T,), f32),
        t_create=jnp.full((T,), jnp.inf, f32),
        t_at_broker=jnp.full((T,), jnp.inf, f32),
        t_at_fog=jnp.full((T,), jnp.inf, f32),
        t_service_start=jnp.full((T,), jnp.inf, f32),
        t_complete=jnp.full((T,), jnp.inf, f32),
        t_q_enter=jnp.full((T,), jnp.inf, f32),
        t_ack3=jnp.full((T,), jnp.inf, f32),
        t_ack4_fwd=jnp.full((T,), jnp.inf, f32),
        t_ack4_queued=jnp.full((T,), jnp.inf, f32),
        t_ack5=jnp.full((T,), jnp.inf, f32),
        t_ack6=jnp.full((T,), jnp.inf, f32),
        queue_time_ms=jnp.full((T,), jnp.inf, f32),  # inf (not NaN): NaN != NaN
        #   breaks cross-process equality checks in multihost device_put
        req_open=jnp.zeros((T,), jnp.int8),
    )

    metrics = Metrics(
        n_published=jnp.zeros((), jnp.int32),
        n_scheduled=jnp.zeros((), jnp.int32),
        n_completed=jnp.zeros((), jnp.int32),
        n_dropped=jnp.zeros((), jnp.int32),
        n_no_resource=jnp.zeros((), jnp.int32),
        n_connected=jnp.zeros((), jnp.int32),
        n_subscribed=jnp.zeros((), jnp.int32),
        n_fanout=jnp.zeros((), jnp.int32),
        n_rejected=jnp.zeros((), jnp.int32),
        n_local=jnp.zeros((), jnp.int32),
        n_adverts=jnp.zeros((), jnp.int32),
        n_lost=jnp.zeros((), jnp.int32),
        n_link_drops=jnp.zeros((), jnp.int32),
        n_deferred=jnp.zeros((), jnp.int32),
        n_deferred_max=jnp.zeros((), jnp.int32),
    )

    return WorldState(
        t=jnp.zeros((), f32),
        tick=jnp.zeros((), jnp.int32),
        key=key,
        nodes=nodes,
        users=users,
        fogs=fogs,
        broker=broker,
        tasks=tasks,
        metrics=metrics,
        learn=init_learn_state(spec),
        # the chaos stream is FOLDED from the world key (never split):
        # enabling it perturbs no draw of the main simulation stream
        chaos=init_chaos_state(spec, key),
        hier=init_hier_state(spec),
        # the journey sample is FOLDED from the world key (never
        # split), the chaos-stream discipline: enabling journeys
        # perturbs no draw of the main simulation stream
        telem=init_telemetry_state(spec, key),
    )
