"""ISSUE 13 — dynamic-operand spec promotion + shape-bucketed reuse.

The correctness rail of "one program, many worlds": promoted-operand
runs must be BIT-EXACT vs the static-spec path over the three
policy-family worlds (argmin/chaos, learned bandit, POOL-v2/energy)
across every entry point; warm re-configuration of a promoted knob must
trigger ZERO compile events; and two same-bucket user counts must share
one compiled program.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fognetsimpp_tpu import compile_cache, dynspec
from fognetsimpp_tpu.core.engine import (
    _run_jit_dyn,
    run,
    run_chunked,
    run_jit,
)
from fognetsimpp_tpu.scenarios import smoke
from fognetsimpp_tpu.telemetry.health import state_hash


def _hash(s) -> str:
    return state_hash(jax.device_get(s))


def _copy(s):
    return jax.tree.map(jnp.copy, s)


def _build(**kw):
    kw.setdefault("n_users", 32)
    kw.setdefault("n_fogs", 4)
    kw.setdefault("horizon", 0.05)
    kw.setdefault("send_interval", 5e-3)
    return smoke.build(**kw)


#: The three policy-family worlds of the acceptance gate, each reading
#: a different slice of the promoted knobs inside the tick.
FAMILIES = {
    "argmin_chaos": dict(
        chaos=True, chaos_mtbf_s=0.01, chaos_mttr_s=0.005,
        chaos_mode=1, chaos_rtt_amp=0.5, chaos_rtt_period_s=0.7,
        chaos_rtt_burst_prob=0.1, chaos_rtt_burst_mult=3.0,
        chaos_max_retries=2, uplink_loss_prob=0.05,
    ),
    "learned_ducb": dict(
        policy=9, learn_discount=0.99, learn_reward_scale=0.3,
    ),
    "pool_v2_energy": dict(
        policy=5, app_gen=2, fog_model=1, broker_mips=3000.0,
        v2_local_broker=True, required_time=0.01, energy_enabled=True,
        idle_power_w=3e-3, harvest_duty=0.4,
    ),
}


# ----------------------------------------------------------------------
# catalogue consistency
# ----------------------------------------------------------------------

def test_dyn_fields_synced_with_simlint_r13():
    """simlint R13's literal field copy cannot drift from the real
    promotion catalogue."""
    from tools.simlint.rules import DYN_PROMOTED_FIELDS

    assert set(dynspec.DYN_FIELDS) == set(DYN_PROMOTED_FIELDS)


def test_dyn_fields_are_spec_fields_and_disjoint_from_static():
    names = {f.name for f in dataclasses.fields(dynspec.WorldSpec)}
    assert set(dynspec.DYN_FIELDS) <= names
    overlap = set(dynspec.DYN_FIELDS) & set(dynspec.STATIC_REASONS)
    assert not overlap, f"fields both promoted and static: {overlap}"
    assert set(dynspec.STATIC_REASONS) <= names


def test_classify_field():
    rec, why = dynspec.classify_field("chaos_rtt_amp")
    assert rec is False and "operand" in why
    rec, why = dynspec.classify_field("horizon")
    assert rec is True and "scan length" in why
    rec, _ = dynspec.classify_field("n_users")
    assert rec is True
    with pytest.raises(ValueError, match="unknown WorldSpec field"):
        dynspec.classify_field("bogus_knob")


# ----------------------------------------------------------------------
# shape keys and buckets
# ----------------------------------------------------------------------

def test_shape_key_merges_knob_values_preserves_gates():
    spec, *_ = _build(**FAMILIES["argmin_chaos"])
    tweaked = dataclasses.replace(
        spec, chaos_rtt_amp=1.75, uplink_loss_prob=0.3,
        learn_reward_scale=0.9,
    ).validate()
    assert dynspec.same_program(spec, tweaked)
    # crossing a gate (positive -> zero) leaves the bucket
    gate_flip = dataclasses.replace(spec, chaos_rtt_amp=0.0).validate()
    assert not dynspec.same_program(spec, gate_flip)
    # shape fields leave the bucket
    bigger = dataclasses.replace(spec, n_users=64).validate()
    assert not dynspec.same_program(spec, bigger)


def test_shape_key_passes_validate():
    for kw in FAMILIES.values():
        spec, *_ = _build(**kw)
        dynspec.shape_key(spec).validate()


def test_dyn_of_matches_static_fold():
    """Each DynSpec leaf equals the f32 the static path folds in."""
    spec, *_ = _build(
        chaos=True, chaos_rtt_period_s=0.7, chaos_mttr_s=-1.0,
        chaos_mtbf_s=0.0, link_rate_bps=10e6,
    )
    d = dynspec.dyn_of(spec)
    assert d.chaos_rtt_omega == np.float32(2.0 * np.pi / 0.7)
    assert d.chaos_mttr_s == np.float32(0.0)  # host clamp
    assert d.link_inv_rate == np.float32(8.0 / 10e6)
    assert d.chaos_max_retries.dtype == np.int32


def test_bucket_users_ladder():
    assert dynspec.bucket_users(500) == 500  # below the floor: untouched
    assert dynspec.bucket_users(1024) == 1024
    assert dynspec.bucket_users(1025) == 1536
    assert dynspec.bucket_users(1537) == 2048
    assert dynspec.bucket_users(5000) == 6144
    # monotone and idempotent on bucket boundaries
    for n in (1100, 2049, 7000):
        b = dynspec.bucket_users(n)
        assert b >= n and dynspec.bucket_users(b) == b


def test_apply_knobs():
    spec, *_ = _build(**FAMILIES["argmin_chaos"])
    spec2 = dynspec.apply_knobs(spec, {"chaos_rtt_amp": 1.25})
    assert spec2.chaos_rtt_amp == 1.25
    assert dynspec.same_program(spec, spec2)
    with pytest.raises(ValueError, match="shape-defining"):
        dynspec.apply_knobs(spec, {"horizon": 1.0})
    with pytest.raises(ValueError, match="unknown dynamic knob"):
        dynspec.apply_knobs(spec, {"bogus": 1.0})
    with pytest.raises(ValueError, match="trace gate"):
        dynspec.apply_knobs(spec, {"uplink_loss_prob": 0.0})


# ----------------------------------------------------------------------
# the bit-exactness rail
# ----------------------------------------------------------------------

@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_promoted_bitexact_vs_static(family):
    """State-hash A/B: the promoted (shape key + DynSpec operand) run
    equals the static-spec run bit-for-bit — any constant-folding
    difference is a finding."""
    spec, state, net, bounds = _build(**FAMILIES[family])
    f_static, _ = run(spec, state, net, bounds)
    key_spec, dyn = dynspec.split_spec(spec)
    f_dyn, _ = run(key_spec, state, net, bounds, dyn=dyn)
    assert _hash(f_static) == _hash(f_dyn)


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_entry_points_bitexact(family):
    """run_jit (promoted vs static) and run_chunked (promoted) all land
    on the same final state."""
    spec, state, net, bounds = _build(**FAMILIES[family])
    ref, _ = run(spec, state, net, bounds)
    h = _hash(ref)
    assert _hash(
        run_jit(spec, _copy(state), net, bounds, promote=False)
    ) == h
    assert _hash(
        run_jit(spec, _copy(state), net, bounds, promote=True)
    ) == h
    assert _hash(run_chunked(
        spec, _copy(state), net, bounds, chunk_ticks=13, promote=True
    )) == h


# ----------------------------------------------------------------------
# the compile-reuse rail
# ----------------------------------------------------------------------

def test_warm_reconfig_zero_compile_events():
    """Re-configuring promoted knobs re-uses the compiled program:
    zero jit-cache growth, zero compile events (compile_stats delta),
    and a warm wall far below the cold one."""
    import time

    # a shape no other test compiles, so the cold wall is genuinely cold
    spec, state, net, bounds = _build(
        n_users=40, horizon=0.06, **FAMILIES["argmin_chaos"]
    )
    t0 = time.perf_counter()
    jax.block_until_ready(
        run_jit(spec, _copy(state), net, bounds, promote=True)
    )
    cold = time.perf_counter() - t0
    base = _run_jit_dyn._cache_size()
    snap = compile_cache.snapshot()
    spec2 = dataclasses.replace(
        spec, chaos_rtt_amp=1.75, chaos_mtbf_s=0.02,
        uplink_loss_prob=0.11, chaos_rtt_burst_mult=5.5,
    ).validate()
    t0 = time.perf_counter()
    jax.block_until_ready(
        run_jit(spec2, _copy(state), net, bounds, promote=True)
    )
    warm = time.perf_counter() - t0
    delta = compile_cache.delta_since(snap)
    assert _run_jit_dyn._cache_size() == base, "jit cache grew"
    assert delta["compiles"] == 0, f"compile events on warm tweak: {delta}"
    # generous bar (the pinned-shape >=10x gate lives in bench_trend):
    # a recompile would cost seconds, a reuse costs milliseconds
    assert warm < cold / 5


def test_same_bucket_user_counts_share_one_program():
    """Two nearby populations pad to one bucket and hit one jit entry."""
    results = {}
    base = None
    for n in (20, 24):
        spec, state, net, bounds = _build(n_users=n)
        spec_b, state_b, net_b = dynspec.bucket_spec(
            spec, state, net, floor=16
        )
        assert spec_b.n_users == 24  # both land on the 16*1.5 bucket
        if base is None:
            jax.block_until_ready(
                run_jit(spec_b, state_b, net_b, bounds, promote=True)
            )
            base = _run_jit_dyn._cache_size()
        else:
            final = run_jit(spec_b, state_b, net_b, bounds, promote=True)
            jax.block_until_ready(final)
            assert _run_jit_dyn._cache_size() == base, (
                "same-bucket world recompiled"
            )
            results["n24"] = final
    # bucket_spec is a no-op on a boundary population
    spec, state, net, bounds = _build(n_users=24)
    s2, st2, n2 = dynspec.bucket_spec(spec, state, net, floor=16)
    assert s2 is spec and st2 is state and n2 is net


def test_bucketed_ghosts_are_inert():
    """The padded world's real users behave exactly like the same spec
    at the padded population built directly (the pad_users contract
    generalized to buckets)."""
    spec, state, net, bounds = _build(n_users=20)
    spec_b, state_b, net_b = dynspec.bucket_spec(
        spec, state, net, floor=16
    )
    final, _ = run(spec_b, state_b, net_b, bounds)
    pub = np.asarray(final.users.send_count)
    assert pub[: spec.n_users].sum() > 0  # real users ran
    assert pub[spec.n_users:].sum() == 0  # ghosts never published
    assert not np.asarray(final.users.connected)[spec.n_users:].any()


def test_program_registry_accounting():
    dynspec.registry_reset()
    spec, *_ = _build()
    key = dynspec.shape_key(spec)
    assert dynspec.registry_note(key, "cpu", True) is True
    assert dynspec.registry_note(key, "cpu", True) is False
    # a different donation layout or backend is a different program
    assert dynspec.registry_note(key, "cpu", False) is True
    st = dynspec.registry_stats()
    assert st["buckets"] == 2 and st["reuses"] == 1
    assert st["programs"] == 2
    # bounded: the LRU cap evicts accounting, never grows unbounded
    for i in range(dynspec._REGISTRY_CAP + 8):
        sp = dataclasses.replace(spec, n_users=8 + i).validate()
        dynspec.registry_note(dynspec.shape_key(sp), "cpu", True)
    assert dynspec.registry_stats()["buckets"] <= dynspec._REGISTRY_CAP
    assert dynspec.registry_stats()["evictions"] >= 8
    # the registry feeds compile_stats() (the satellite accounting)
    assert "program_registry" in compile_cache.compile_stats()
    dynspec.registry_reset()


# ----------------------------------------------------------------------
# the what-if door: knob changes at chunk boundaries
# ----------------------------------------------------------------------

def test_run_chunked_reconfigure_matches_manual_composition():
    """A knob change at a chunk boundary equals running the first half
    with the old DynSpec and the second half with the new one."""
    spec, state, net, bounds = _build(**FAMILIES["argmin_chaos"])
    seen = []

    def reconfig(ticks_done):
        seen.append(ticks_done)
        if ticks_done == 5:
            return {"chaos_rtt_amp": 1.5, "uplink_loss_prob": 0.15}
        return None

    got = run_chunked(
        spec, _copy(state), net, bounds, chunk_ticks=5,
        promote=True, reconfigure=reconfig,
    )
    assert seen and seen[0] == 5
    key_spec, dyn1 = dynspec.split_spec(spec)
    spec2 = dynspec.apply_knobs(
        spec, {"chaos_rtt_amp": 1.5, "uplink_loss_prob": 0.15}
    )
    dyn2 = dynspec.dyn_of(spec2)
    mid, _ = run(key_spec, state, net, bounds, n_ticks=5, dyn=dyn1)
    want, _ = run(
        key_spec, mid, net, bounds, n_ticks=spec.n_ticks - 5, dyn=dyn2
    )
    assert _hash(got) == _hash(want)


def test_run_chunked_reconfigure_rejects_gate_flip_and_static_path():
    spec, state, net, bounds = _build(**FAMILIES["argmin_chaos"])
    with pytest.raises(ValueError, match="promoted path"):
        run_chunked(
            spec, _copy(state), net, bounds, chunk_ticks=5,
            promote=False, reconfigure=lambda t: None,
        )
    with pytest.raises(ValueError, match="shape-defining"):
        run_chunked(
            spec, _copy(state), net, bounds, chunk_ticks=5,
            promote=True, reconfigure=lambda t: {"horizon": 9.0},
        )


# ----------------------------------------------------------------------
# one-compile dynamic-knob grids (the sweep satellite)
# ----------------------------------------------------------------------

def test_sweep_dyn_one_compile_and_cell_equivalence():
    """A chaos-amplitude grid is ONE compile (jit-cache-size assertion,
    not wall clock), and each cell's counters equal a direct
    run_replicated of that cell's spec."""
    from fognetsimpp_tpu.parallel import sweep_dyn
    from fognetsimpp_tpu.parallel.replicas import (
        _run_replicated,
        replica_counters,
        replicate_state,
        run_replicated,
    )

    build_kw = dict(
        n_users=24, n_fogs=3, horizon=0.04, send_interval=4e-3,
        chaos=True, chaos_mtbf_s=0.01, chaos_mttr_s=0.005,
    )
    grid = {"chaos_rtt_amp": [0.25, 1.0], "chaos_rtt_burst_prob": [0.05]}
    base = _run_replicated._cache_size()
    cells = sweep_dyn(
        smoke.build, grid, n_replicas_per_cell=2, **build_kw
    )
    assert _run_replicated._cache_size() == base + 1, (
        "the dynamic-knob grid must be one compile"
    )
    assert len(cells) == 2
    # warm: a NEW grid over the same bucket is a pure jit-cache hit
    # AND zero backend compile events (the compile_stats delta is the
    # accounting the bench/serve loops gate on — not wall clock)
    snap = compile_cache.snapshot()
    sweep_dyn(
        smoke.build,
        {"chaos_rtt_amp": [0.4, 0.8], "chaos_rtt_burst_prob": [0.02]},
        n_replicas_per_cell=2, **build_kw,
    )
    assert _run_replicated._cache_size() == base + 1, (
        "second dynamic-knob grid must be a jit-cache hit"
    )
    assert compile_cache.delta_since(snap)["compiles"] == 0
    # cell equivalence: grid row == direct run of that spec
    spec_a, state_a, net_a, bounds_a = smoke.build(
        **{**build_kw, "chaos_rtt_amp": 0.25,
           "chaos_rtt_burst_prob": 0.05}
    )
    key_a, dyn_a = dynspec.split_spec(spec_a)
    batch = replicate_state(spec_a, state_a, 2, seed=0)
    rows = jax.tree.map(
        lambda x: jnp.broadcast_to(
            jnp.asarray(x), (2,) + jnp.shape(jnp.asarray(x))
        ),
        dyn_a,
    )
    direct = replica_counters(run_replicated(
        key_a, batch, net_a, bounds_a, dyn_rows=rows
    ))
    got = cells[0]["counters"]
    for k, v in direct.items():
        np.testing.assert_array_equal(np.asarray(got[k]), v, err_msg=k)


def test_sweep_dyn_rejects_static_fields_and_gate_crossings():
    from fognetsimpp_tpu.parallel import sweep_dyn

    with pytest.raises(ValueError, match="shape-defining"):
        sweep_dyn(smoke.build, {"horizon": [0.1, 0.2]}, n_users=8)
    with pytest.raises(ValueError, match="shape bucket"):
        sweep_dyn(
            smoke.build, {"uplink_loss_prob": [0.0, 0.2]},
            n_users=8, n_fogs=2, horizon=0.02,
        )


def test_serve_run_forwards_reconfigure():
    """The --serve loop's what-if door: knob changes land between
    chunks with zero compile events; custom run_fn runners reject the
    kwarg with a one-line error."""
    from fognetsimpp_tpu.telemetry.live import serve_run

    spec, state, net, bounds = _build(
        telemetry=True, **FAMILIES["argmin_chaos"]
    )
    calls = []

    def reconfig(ticks_done):
        calls.append(ticks_done)
        return {"chaos_rtt_amp": 1.25} if ticks_done == 10 else None

    # warm the chunk program once so the serve loop's own compile does
    # not pollute the interval delta below
    final, status = serve_run(
        spec, _copy(state), net, bounds, chunk_ticks=10, port=None,
        hash_every_chunk=False, reconfigure=reconfig,
    )
    assert calls and calls[0] == 10
    assert status["chunks"] == spec.n_ticks // 10 + (
        1 if spec.n_ticks % 10 else 0
    )
    with pytest.raises(ValueError, match="run_fn"):
        serve_run(
            spec, _copy(state), net, bounds, port=None,
            run_fn=lambda *a, **k: None, reconfigure=reconfig,
        )
