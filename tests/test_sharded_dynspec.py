"""ISSUE 20 — zero-recompile sharded runners: DynSpec promotion on the
TP and fleet paths.

The acceptance contract: the shard_map'd TP tick and the fleet scan
take ``(shape_key static, DynSpec operand)`` exactly like ``run_jit``
— bit-exact by construction vs the ``FNS_SPEC_PROMOTE=0`` static path
AND vs the single-device reference, warm knob retunes compile ZERO
programs (asserted on the runners' own program caches, with
``compile_cache.delta_since`` as belt-and-suspenders), chunk-boundary
``reconfigure=`` composes exactly like manual ``apply_knobs`` between
``run_tp_sharded`` calls, a ``sweep_dyn(mesh=)`` grid is ONE compiled
fleet program, and a TP chunk-boundary carry leaves the mesh through
``unstamp_tp_carry`` and forks onto a what-if grid like any
single-device carry (the deleted ``[TWIN-WHATIF-TP]`` wall).

Donated TP dyn-operand programs route through
``_donation_safe_compile`` (the PR 17 persistent-cache aliasing bomb):
the regression here re-chunks a promoted donated carry after dropping
the in-memory program cache, the exact shape that corrupted when a
deserialized executable lost its donation aliasing.
"""
import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fognetsimpp_tpu import Policy, compile_cache, run
from fognetsimpp_tpu.core.engine import run_chunked, run_jit
from fognetsimpp_tpu.dynspec import apply_knobs, split_spec
from fognetsimpp_tpu.parallel import (
    make_mesh,
    replicate_state,
    run_fleet,
    run_tp_chunked,
    run_tp_sharded,
    sweep_dyn,
    unstamp_tp_carry,
)
from fognetsimpp_tpu.parallel import taskshard
from fognetsimpp_tpu.parallel.fleet import _fleet_run
from fognetsimpp_tpu.scenarios import smoke
from fognetsimpp_tpu.telemetry.health import state_hash
from fognetsimpp_tpu.telemetry.live import ReconfigDoor
from fognetsimpp_tpu.twin.whatif import run_whatif


def _hash(s) -> str:
    return state_hash(jax.device_get(s))


def _copy(s):
    return jax.tree.map(jnp.copy, s)


#: TP worlds are built with ``send_stop_time`` FINITE (gate on): the
#: retune tests then stay inside the finite-vs-inf trace gate, and the
#: knob demonstrably changes results (cutting sends mid-horizon).
SMALL = dict(
    n_users=16, n_fogs=3, send_interval=0.01, horizon=0.2,
    start_time_max=0.05, send_stop_time=0.12,
)

#: The three dense-broker policy-family worlds the TP tick admits
#: (test_tp.py's acceptance families).
TP_WORLDS = [
    dict(policy=int(Policy.MIN_BUSY)),
    dict(policy=int(Policy.MIN_LATENCY), send_interval_jitter=0.1),
    dict(policy=int(Policy.MAX_MIPS)),
]

#: The three policy-family worlds of the ISSUE 13 acceptance gate
#: (test_dynspec.py's FAMILIES) — the fleet admits all of them.
FLEET_FAMILIES = {
    "argmin_chaos": dict(
        chaos=True, chaos_mtbf_s=0.01, chaos_mttr_s=0.005,
        chaos_mode=1, chaos_rtt_amp=0.5, chaos_rtt_period_s=0.7,
        chaos_rtt_burst_prob=0.1, chaos_rtt_burst_mult=3.0,
        chaos_max_retries=2, uplink_loss_prob=0.05,
    ),
    "learned_ducb": dict(
        policy=9, learn_discount=0.99, learn_reward_scale=0.3,
    ),
    "pool_v2_energy": dict(
        policy=5, app_gen=2, fog_model=1, broker_mips=3000.0,
        v2_local_broker=True, required_time=0.01, energy_enabled=True,
        idle_power_w=3e-3, harvest_duty=0.4,
    ),
}


def _build(**kw):
    args = dict(SMALL)
    args.update(kw)
    return smoke.build(**args)


def _build_fleet(**kw):
    kw.setdefault("n_users", 32)
    kw.setdefault("n_fogs", 4)
    kw.setdefault("horizon", 0.05)
    kw.setdefault("send_interval", 5e-3)
    return smoke.build(**kw)


@pytest.fixture(scope="module")
def node_mesh():
    assert len(jax.devices()) == 8, "conftest must provision 8 devices"
    return make_mesh(8, axis_name="node")


@pytest.fixture(scope="module")
def replica_mesh():
    return make_mesh(8)


def _tp(spec, state, net, bounds, mesh, **kw):
    kw.setdefault("donate", True)
    return run_tp_sharded(spec, _copy(state), net, bounds, mesh, **kw)


# ----------------------------------------------------------------------
# TP: promoted == static == single-device reference
# ----------------------------------------------------------------------

def test_tp_promoted_bitexact_vs_static(node_mesh):
    """State-hash A/B over the three dense policy-family worlds: the
    promoted TP tick == the FNS_SPEC_PROMOTE=0 static TP tick == the
    single-device reference; the first world also pins run_jit and
    run_chunked (the remaining single-device entries)."""
    for i, kw in enumerate(TP_WORLDS):
        spec, state, net, bounds = _build(**kw)
        ref, _ = run(spec, _copy(state), net, bounds)
        spec_p, prom = _tp(spec, state, net, bounds, node_mesh,
                           promote=True)
        _, stat = _tp(spec, state, net, bounds, node_mesh,
                      promote=False)
        assert _hash(ref) == _hash(prom), kw
        assert _hash(prom) == _hash(stat), kw
        assert spec_p == spec
        if i == 0:
            jit_ref = run_jit(spec, _copy(state), net, bounds)
            assert _hash(jit_ref) == _hash(prom)
            chunk_ref = run_chunked(
                spec, _copy(state), net, bounds,
                chunk_ticks=spec.n_ticks // 2,
            )
            assert _hash(chunk_ref) == _hash(prom)


def test_tp_env_optout_matches_promoted(monkeypatch, node_mesh):
    """FNS_SPEC_PROMOTE=0 reverts the TP runner (promote=None resolves
    to the static path) with identical results."""
    spec, state, net, bounds = _build(**TP_WORLDS[0])
    _, prom = _tp(spec, state, net, bounds, node_mesh, promote=True)
    monkeypatch.setenv("FNS_SPEC_PROMOTE", "0")
    _, off = _tp(spec, state, net, bounds, node_mesh)  # promote=None
    assert _hash(prom) == _hash(off)


# ----------------------------------------------------------------------
# TP: warm retune = zero compiles, and the retune has effect
# ----------------------------------------------------------------------

def test_tp_warm_retune_zero_compiles(node_mesh):
    """Retuning a promoted knob on the warm TP program compiles ZERO
    programs (the lru program cache does not miss), changes the result,
    and matches the static path's fresh recompile bit-for-bit."""
    spec, state, net, bounds = _build(**TP_WORLDS[0])
    _, base = _tp(spec, state, net, bounds, node_mesh, promote=True)
    spec2 = apply_knobs(spec, {"send_stop_time": 0.04})
    info0 = taskshard._tp_program.cache_info()
    before = compile_cache.snapshot()
    _, got = _tp(spec2, state, net, bounds, node_mesh, promote=True)
    assert taskshard._tp_program.cache_info().misses == info0.misses, (
        "warm promoted retune recompiled the TP program"
    )
    assert compile_cache.delta_since(before)["compiles"] == 0
    # the retuned knob is not decorative: cutting send_stop_time
    # mid-horizon changes the trajectory
    assert _hash(got) != _hash(base)
    # and the promoted retune equals a static-path recompile
    _, ref = _tp(spec2, state, net, bounds, node_mesh, promote=False)
    assert _hash(got) == _hash(ref)


def test_tp_chunked_reconfigure_composes(node_mesh):
    """``run_tp_chunked(reconfigure=)`` retunes at the chunk boundary
    with zero compile events, equals the manual apply_knobs-between-
    run_tp_sharded-calls composition, and refuses the static path."""
    spec, state, net, bounds = _build(**TP_WORLDS[0])
    n = spec.n_ticks
    assert n % 2 == 0
    calls = []

    def reconf(done):
        calls.append(done)
        return {"send_stop_time": 0.04}

    info0 = taskshard._tp_program.cache_info()
    sp_f, got = run_tp_chunked(
        spec, _copy(state), net, bounds, node_mesh,
        chunk_ticks=n // 2, promote=True, reconfigure=reconf,
    )
    # interior boundary only: the final boundary retunes nothing
    assert calls == [n // 2]
    assert float(sp_f.send_stop_time) == pytest.approx(0.04)
    # both chunks (and the retuned second chunk) reuse ONE program
    assert taskshard._tp_program.cache_info().misses \
        <= info0.misses + 1
    spec_a, half = _tp(spec, state, net, bounds, node_mesh,
                       n_ticks=n // 2, promote=True)
    spec_b = apply_knobs(spec_a, {"send_stop_time": 0.04})
    _, full = run_tp_sharded(
        spec_b, half, net, bounds, node_mesh, n_ticks=n // 2,
        donate=True, promote=True,
    )
    assert _hash(got) == _hash(full)
    with pytest.raises(ValueError, match="promote"):
        run_tp_chunked(
            spec, _copy(state), net, bounds, node_mesh,
            chunk_ticks=n // 2, promote=False, reconfigure=reconf,
        )


def test_tp_donated_promoted_program_keeps_aliases(node_mesh):
    """PR 17 regression, promoted edition: a donated TP dyn-operand
    program must compile through ``_donation_safe_compile`` — after
    dropping the in-memory program cache (so a persistent-cache hit
    would otherwise deserialize an alias-stripped executable), the
    re-chunked promoted run still aliases its donated carry and stays
    bit-exact."""
    spec, state, net, bounds = _build(**TP_WORLDS[0])
    ref, _ = run(spec, _copy(state), net, bounds)
    _, first = run_tp_chunked(
        spec, _copy(state), net, bounds, node_mesh,
        chunk_ticks=spec.n_ticks // 2, promote=True,
    )
    taskshard._tp_program.cache_clear()
    _, again = run_tp_chunked(
        spec, _copy(state), net, bounds, node_mesh,
        chunk_ticks=spec.n_ticks // 2, promote=True,
    )
    assert _hash(ref) == _hash(first) == _hash(again)
    # the compiled promoted program really does alias the donated carry
    go, parts, net_r, cache_r, _, dyn = taskshard._tp_setup(
        spec, _copy(state), net, node_mesh, spec.n_ticks, "node",
        None, True, True, promote=True,
    )
    assert dyn is not None
    txt = go.lower(*parts, net_r, cache_r, dyn).compile().as_text()
    assert "input_output_alias" in txt


# ----------------------------------------------------------------------
# fleet: promoted == static, warm retune = zero compiles
# ----------------------------------------------------------------------

def test_fleet_promoted_bitexact_vs_static(replica_mesh):
    """State-hash A/B over the three policy-family worlds: the
    promoted fleet scan (per-replica DynSpec rows) == the
    FNS_SPEC_PROMOTE=0 static fleet scan."""
    for name, kw in FLEET_FAMILIES.items():
        spec, state, net, bounds = _build_fleet(**kw)
        batch = replicate_state(spec, state, 8, seed=3)
        ref = run_fleet(spec, _copy(batch), net, bounds, replica_mesh,
                        promote=False)
        got = run_fleet(spec, _copy(batch), net, bounds, replica_mesh,
                        promote=True)
        assert _hash(ref) == _hash(got), name


def test_fleet_env_optout_matches_promoted(monkeypatch, replica_mesh):
    spec, state, net, bounds = _build_fleet(
        **FLEET_FAMILIES["argmin_chaos"]
    )
    batch = replicate_state(spec, state, 8, seed=3)
    prom = run_fleet(spec, _copy(batch), net, bounds, replica_mesh,
                     promote=True)
    monkeypatch.setenv("FNS_SPEC_PROMOTE", "0")
    off = run_fleet(spec, _copy(batch), net, bounds, replica_mesh)
    assert _hash(prom) == _hash(off)


def test_fleet_warm_retune_zero_compiles(replica_mesh):
    """A same-bucket knob retune on the warm promoted fleet program
    compiles nothing (the jit cache does not grow) and matches the
    static path's fresh recompile."""
    spec, state, net, bounds = _build_fleet(
        **FLEET_FAMILIES["argmin_chaos"]
    )
    batch = replicate_state(spec, state, 8, seed=3)
    run_fleet(spec, _copy(batch), net, bounds, replica_mesh,
              promote=True)
    size0 = _fleet_run._cache_size()
    before = compile_cache.snapshot()
    spec2 = apply_knobs(
        spec, {"uplink_loss_prob": 0.4, "chaos_rtt_amp": 0.25}
    )
    got = run_fleet(spec2, _copy(batch), net, bounds, replica_mesh,
                    promote=True)
    assert _fleet_run._cache_size() == size0, (
        "warm promoted fleet retune compiled a new program"
    )
    assert compile_cache.delta_since(before)["compiles"] == 0
    ref = run_fleet(spec2, _copy(batch), net, bounds, replica_mesh,
                    promote=False)
    assert _hash(got) == _hash(ref)


def test_fleet_dyn_rows_require_promote(replica_mesh):
    spec, state, net, bounds = _build_fleet()
    batch = replicate_state(spec, state, 8, seed=3)
    _, dyn = split_spec(spec)
    rows = jax.tree.map(
        lambda x: jnp.broadcast_to(
            jnp.asarray(x)[None, ...], (8,) + jnp.shape(x)
        ),
        dyn,
    )
    with pytest.raises(ValueError, match="promote"):
        run_fleet(spec, _copy(batch), net, bounds, replica_mesh,
                  promote=False, dyn_rows=rows)


# ----------------------------------------------------------------------
# sweep_dyn(mesh=): one sharded compile, vmap-identical cells
# ----------------------------------------------------------------------

def test_sweep_dyn_mesh_single_compile(replica_mesh):
    """A ``sweep_dyn`` grid laid over the mesh is ONE fleet compile,
    and every cell's counters equal the unsharded vmap grid's."""
    kw = dict(
        n_users=16, n_fogs=4, horizon=0.02, send_interval=2.5e-3,
        **FLEET_FAMILIES["argmin_chaos"],
    )
    knobs = {
        "chaos_rtt_amp": [0.25, 0.5],
        "uplink_loss_prob": [0.05, 0.1],
    }
    size0 = _fleet_run._cache_size()
    grid = sweep_dyn(
        smoke.build, knobs, n_replicas_per_cell=2,
        mesh=replica_mesh, **kw,
    )
    assert len(grid) == 4
    assert _fleet_run._cache_size() == size0 + 1, (
        "the sharded grid must be ONE compiled fleet program"
    )
    # warm re-ask: zero compiles
    before = compile_cache.snapshot()
    sweep_dyn(
        smoke.build, knobs, n_replicas_per_cell=2,
        mesh=replica_mesh, **kw,
    )
    assert _fleet_run._cache_size() == size0 + 1
    assert compile_cache.delta_since(before)["compiles"] == 0
    ref = sweep_dyn(smoke.build, knobs, n_replicas_per_cell=2, **kw)
    for cell_s, cell_r in zip(grid, ref):
        for k in knobs:
            assert cell_s[k] == cell_r[k]
        for k, v in cell_r["counters"].items():
            assert np.array_equal(
                np.asarray(cell_s["counters"][k]), np.asarray(v)
            ), k


# ----------------------------------------------------------------------
# TP what-if: the deleted [TWIN-WHATIF-TP] wall
# ----------------------------------------------------------------------

def test_tp_whatif_fork_matches_cold_runs(node_mesh):
    """A promoted TP chunk-boundary carry leaves the mesh through
    ``unstamp_tp_carry`` and answers a what-if grid whose every cell is
    bit-identical to a direct single-device run of the retuned spec
    from the same carry."""
    spec, state, net, bounds = _build(**TP_WORLDS[0])
    n = spec.n_ticks
    spec_tp, carry_sh = _tp(
        spec, state, net, bounds, node_mesh, n_ticks=n // 2,
        promote=True,
    )
    sp_w, carry = unstamp_tp_carry(spec_tp, carry_sh)
    assert sp_w.tp_shards == 0
    values = [0.04, 0.08]
    report, batch = run_whatif(
        sp_w, carry, net, bounds, {"send_stop_time": values}, n // 2,
        return_state=True,
    )
    assert report["n_cells"] == 2
    assert json.loads(json.dumps(report))
    key_spec, _ = split_spec(sp_w)
    for i, v in enumerate(values):
        _, dyn_v = split_spec(
            dataclasses.replace(sp_w, send_stop_time=v)
        )
        ref, _ = run(key_spec, carry, net, bounds, n_ticks=n // 2,
                     dyn=dyn_v)
        row = jax.tree.map(lambda a, _i=i: a[_i], batch)
        assert _hash(ref) == _hash(row), v


# ----------------------------------------------------------------------
# the live retune door
# ----------------------------------------------------------------------

def _door_spec():
    spec, *_ = smoke.build(
        n_users=8, n_fogs=2, horizon=0.01, send_interval=2.5e-3,
        send_stop_time=0.008, uplink_loss_prob=0.05,
    )
    return spec


def test_reconfig_door_accepts_promoted_knobs():
    door = ReconfigDoor(_door_spec())
    status, ctype, body = door.handle_http(
        "POST", "/reconfigure",
        json.dumps({"set": ["spec.send_stop_time=0.004"]}).encode(),
    )
    assert status == 200 and ctype == "application/json"
    payload = json.loads(body)
    assert payload["recompile"] == "no"
    assert payload["accepted"] == {"send_stop_time": 0.004}
    assert "dynamic operand" in payload["why"]["send_stop_time"]
    assert door.accepted == 1
    # the chunk hook pops the queue exactly once
    hook = door.as_reconfigure()
    assert hook(100) == {"send_stop_time": 0.004}
    assert hook(200) is None
    assert door.applied_batches == 1


def test_reconfig_door_rejects_gate_flips_eagerly():
    door = ReconfigDoor(_door_spec())
    # crossing the 0-vs-positive trace gate: 400 before the loop sees it
    status, _, body = door.handle_http(
        "POST", "/reconfigure",
        json.dumps({"knobs": {"uplink_loss_prob": 0.0}}).encode(),
    )
    assert status == 400
    assert "gate" in json.loads(body)["error"]
    # shape-defining fields are refused too
    status, _, body = door.handle_http(
        "POST", "/reconfigure",
        json.dumps({"knobs": {"n_users": 64}}).encode(),
    )
    assert status == 400
    assert door.rejected == 2
    assert door.as_reconfigure()(10) is None  # nothing queued


def test_reconfig_door_validates_payloads():
    door = ReconfigDoor(_door_spec())
    assert door.handle_http("POST", "/metrics", b"{}") is None
    status, _, body = door.handle_http("GET", "/reconfigure", b"")
    assert status == 200 and "usage" in json.loads(body)
    for bad in (b"not json", b"[]", b"{}",
                json.dumps({"set": ["no-equals"]}).encode(),
                json.dumps({"knobs": {"send_stop_time": "x"}}).encode()):
        status, _, _ = door.handle_http("POST", "/reconfigure", bad)
        assert status == 400, bad
