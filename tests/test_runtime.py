"""Config tier, recorder, checkpoint/resume, CLI."""
import json

import numpy as np
import pytest

from fognetsimpp_tpu import run
from fognetsimpp_tpu.__main__ import main as cli_main
from fognetsimpp_tpu.config import Config, build_from_config, parse_value
from fognetsimpp_tpu.runtime import checkpoint, load_scalars, load_vectors, record_run
from fognetsimpp_tpu.scenarios import smoke


def test_parse_values():
    assert parse_value("50ms") == pytest.approx(0.05)
    assert parse_value("2s") == 2.0
    assert parse_value("100Mbps") == 100e6
    assert parse_value("true") is True
    assert parse_value("3") == 3 and isinstance(parse_value("3"), int)
    assert parse_value("1.5") == 1.5
    assert parse_value('"mqttApp2"') == "mqttApp2"


def test_wildcard_first_match_wins():
    cfg = Config.from_str(
        """
        [General]
        fog.2.mips = 4000      # specific first, like omnetpp.ini
        fog.*.mips = 1000
        **.send_interval = 2s
        """
    )
    assert cfg.lookup("fog.2.mips") == 4000
    assert cfg.lookup("fog.0.mips") == 1000
    assert cfg.lookup("user.7.send_interval") == 2.0
    assert cfg.lookup("nothing.here") is None


def test_build_from_config():
    cfg = Config.from_str(
        """
        scenario = smoke
        scenario.horizon = 0.4
        scenario.n_fogs = 3
        spec.queue_capacity = 16
        spec.send_interval = 0.02   # size capacity for the fastest user
        fog.1.mips = 4000
        user.*.send_interval = 0.02
        """
    )
    spec, state, net, bounds = build_from_config(cfg)
    assert spec.horizon == pytest.approx(0.4)
    assert spec.n_fogs == 3
    assert spec.queue_capacity == 16
    mips = np.asarray(state.fogs.mips)
    assert mips[1] == 4000.0
    # re-primed advertisement carries the overridden MIPS
    assert np.asarray(state.broker.adv_val_mips)[1] == 4000.0
    assert (np.asarray(state.users.send_interval) == np.float32(0.02)).all()

    with pytest.raises(ValueError):
        build_from_config(Config.from_str("scenario = nope"))
    with pytest.raises(ValueError):
        build_from_config(
            Config.from_str("scenario = smoke\nspec.not_a_field = 1")
        )
    # a faster per-user rate than the send budget must error, not truncate
    with pytest.raises(ValueError, match="send budget"):
        build_from_config(
            Config.from_str(
                "scenario = smoke\nscenario.horizon = 0.4\n"
                "user.*.send_interval = 0.005"
            )
        )
    # builder-owned structural fields give a clear error
    with pytest.raises(ValueError, match="owns WorldSpec field"):
        build_from_config(
            Config.from_str("scenario = wireless\nspec.n_users = 5")
        )


@pytest.fixture(scope="module")
def tiny_run():
    spec, state, net, bounds = smoke.build(horizon=0.3)
    final, _ = run(spec, state, net, bounds)
    return spec, state, net, bounds, final


def test_recorder_roundtrip(tiny_run, tmp_path):
    spec, _, _, _, final = tiny_run
    paths = record_run(str(tmp_path), spec, final, run_id="r0")
    sca = load_scalars(paths["sca"])
    assert sca["scalars"]["n_published"] > 0
    assert sca["spec"]["n_users"] == spec.n_users
    # per-module rows (the reference's per-host .sca section)
    mods = sca["modules"]
    assert len(mods["user"]) == spec.n_users
    assert len(mods["fog"]) == spec.n_fogs
    assert sum(u["sent"] for u in mods["user"]) == sca["scalars"]["n_published"]
    assert sum(f["assigned"] for f in mods["fog"]) == sca["scalars"]["n_scheduled"]
    # stack-level rows (r3): per-node message counters + broker row
    for u in mods["user"]:
        assert u["tx_msgs"] >= u["sent"]  # Connect + publishes at least
        assert u["rx_msgs"] > 0  # Connack + acks came back
        assert u["link_bytes"] == (u["tx_msgs"] + u["rx_msgs"]) * spec.task_bytes
    assert mods["broker"]["rx_msgs"] > 0  # the echoedPk:count analog
    assert mods["broker"]["tx_msgs"] > 0
    assert sum(f["rx_msgs"] for f in mods["fog"]) >= sum(
        f["assigned"] for f in mods["fog"]
    )
    vec = load_vectors(paths["vec"])
    assert "latency_h1" in vec and vec["latency_h1"].size > 0
    assert "delay" in vec


def test_chunked_bit_identical_v2_wired_queue():
    """run_chunked carries the r3 state additions (v2 release timer,
    req_open, DropTail backlogs, per-node counters) bit-identically."""
    from fognetsimpp_tpu.core.engine import run_chunked

    spec, state, net, bounds = smoke.build(
        horizon=0.4, dt=1e-3, send_interval=0.008, n_users=3, n_fogs=2,
        app_gen=2, fog_model=1, policy=5, broker_mips=2048.0,
        v2_local_broker=True, wired_queue_enabled=True,
    )
    straight, _ = run(spec, state, net, bounds)
    chunked = run_chunked(spec, state, net, bounds, chunk_ticks=77)
    for name in ("stage", "t_ack6", "req_open", "fog"):
        np.testing.assert_array_equal(
            np.asarray(getattr(straight.tasks, name)),
            np.asarray(getattr(chunked.tasks, name)),
            err_msg=name,
        )
    np.testing.assert_array_equal(
        np.asarray(straight.nodes.tx_count), np.asarray(chunked.nodes.tx_count)
    )
    np.testing.assert_array_equal(
        np.asarray(straight.nodes.link_backlog),
        np.asarray(chunked.nodes.link_backlog),
    )
    np.testing.assert_array_equal(
        np.asarray(straight.broker.release_timer_t),
        np.asarray(chunked.broker.release_timer_t),
    )


def test_sweep_cli(capsys):
    """--sweep runs a policy x load grid and prints one line per cell."""
    import json

    from fognetsimpp_tpu.__main__ import main

    rc = main([
        "--scenario", "smoke", "--set", "scenario.horizon=0.2",
        "--sweep", "policies=0,2 loads=0.02,0.05 dynamic=1",
    ])
    assert rc == 0
    lines = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()]
    cells = [ln for ln in lines if "policy" in ln]
    assert len(cells) == 4  # 2 policies x 2 loads
    assert all(c["n_scheduled_mean"] > 0 for c in cells)
    assert lines[-1]["dynamic"] is True


def test_recorder_ap_occupancy(tmp_path):
    """Per-AP association occupancy rows (INET per-NIC stats analog)."""
    from fognetsimpp_tpu.scenarios import wireless

    spec, state, net, bounds = wireless.wireless2(horizon=0.3)
    final, _ = run(spec, state, net, bounds)
    paths = record_run(str(tmp_path), spec, final, run_id="ap0")
    mods = load_scalars(paths["sca"])["modules"]
    assert len(mods["ap"]) == spec.n_aps
    # the stations associate somewhere: total mean occupancy is positive
    assert sum(a["assoc_mean"] for a in mods["ap"]) > 1.0


def test_checkpoint_resume_bit_identical(tiny_run, tmp_path):
    spec, state, net, bounds, _ = tiny_run
    half = spec.n_ticks // 2
    # straight run
    full, _ = run(spec, state, net, bounds)
    # run half, checkpoint, reload, run the rest
    mid, _ = run(spec, state, net, bounds, n_ticks=half)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, spec, mid)
    spec2, mid2 = checkpoint.load(path)
    assert spec2 == spec
    resumed, _ = run(spec2, mid2, net, bounds, n_ticks=spec.n_ticks - half)
    for name in ("t_create", "t_ack6", "mips_req", "stage"):
        np.testing.assert_array_equal(
            np.asarray(getattr(full.tasks, name)),
            np.asarray(getattr(resumed.tasks, name)),
            err_msg=name,
        )
    np.testing.assert_array_equal(
        np.asarray(full.metrics.n_completed),
        np.asarray(resumed.metrics.n_completed),
    )


def test_cli(tmp_path, capsys):
    rc = cli_main(
        [
            "--scenario", "smoke",
            "--set", "spec.horizon=0.3",
            "--out", str(tmp_path),
            "--run-id", "cli-0",
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["n_published"] > 0
    assert (tmp_path / "cli-0.sca.json").exists()
    assert (tmp_path / "cli-0.vec.npz").exists()


def test_assume_static_bit_identical():
    """The static-world fast path (cache hoisted out of the scan, zero
    mobility kernels) is bit-identical to the per-tick path on the same
    world."""
    import dataclasses

    import jax
    import numpy as np

    from fognetsimpp_tpu import run
    from fognetsimpp_tpu.scenarios import smoke

    spec_s, state, net, bounds = smoke.build(
        horizon=0.4, send_interval=0.02, dt=1e-3, n_users=3, n_fogs=2,
        start_time_max=0.01,
    )
    assert spec_s.assume_static  # builder default for the wired star
    spec_d = dataclasses.replace(spec_s, assume_static=False)

    fin_s, _ = run(spec_s, state, net, bounds)
    fin_d, _ = run(spec_d, state, net, bounds)
    for a, b in zip(
        jax.tree_util.tree_leaves(fin_s), jax.tree_util.tree_leaves(fin_d)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_derive_acks_reconstruction_is_bit_exact():
    """spec.derive_acks skips the per-tick ack-column writes and rebuilds
    them once post-run with the same f32 arithmetic: every derived
    column must be BIT-identical to the eagerly-written one (r5)."""
    import numpy as np

    from fognetsimpp_tpu import run
    from fognetsimpp_tpu.scenarios import smoke

    kw = dict(
        horizon=0.6, send_interval=0.004, dt=1e-3, n_users=48, n_fogs=3,
        fog_mips=(800.0, 1600.0, 2400.0), queue_capacity=6,
        start_time_max=0.01,
    )
    spec_e, state_e, net_e, bounds_e = smoke.build(**kw)
    f_eager, _ = run(spec_e, state_e, net_e, bounds_e)
    spec_d, state_d, net_d, bounds_d = smoke.build(derive_acks=True, **kw)
    f_der, _ = run(spec_d, state_d, net_d, bounds_d)
    # drops + queueing + assignment all exercised
    assert int(f_eager.metrics.n_dropped) > 0
    assert np.isfinite(np.asarray(f_eager.tasks.t_q_enter)).any()
    for col in ("t_ack3", "t_ack4_fwd", "t_ack4_queued", "t_ack5",
                "t_ack6", "queue_time_ms"):
        np.testing.assert_array_equal(
            np.asarray(getattr(f_eager.tasks, col)),
            np.asarray(getattr(f_der.tasks, col)),
            err_msg=col,
        )


def test_derive_acks_with_chunked_run_matches_single_scan():
    """run_chunked calls run() per chunk, so the derived ack columns are
    written at every chunk boundary from partial state; the final
    chunk's reconstruction must still equal the single-scan result (the
    derivation is a pure function of the hot columns, so intermediate
    writes are benign overwrites)."""
    import numpy as np

    from fognetsimpp_tpu import run
    from fognetsimpp_tpu.core.engine import run_chunked
    from fognetsimpp_tpu.scenarios import smoke

    kw = dict(
        horizon=0.5, send_interval=0.004, dt=1e-3, n_users=32, n_fogs=3,
        fog_mips=(800.0, 1600.0, 2400.0), queue_capacity=8,
        start_time_max=0.01, derive_acks=True,
    )
    spec, state, net, bounds = smoke.build(**kw)
    f_one, _ = run(spec, state, net, bounds)
    spec2, state2, net2, bounds2 = smoke.build(**kw)
    f_chunk = run_chunked(spec2, state2, net2, bounds2, chunk_ticks=120)
    for col in ("stage", "t_ack4_fwd", "t_ack4_queued", "t_ack5",
                "t_ack6", "queue_time_ms"):
        np.testing.assert_array_equal(
            np.asarray(getattr(f_one.tasks, col)),
            np.asarray(getattr(f_chunk.tasks, col)),
            err_msg=col,
        )
