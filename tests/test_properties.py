"""Property-based invariants (hypothesis) over randomized world data.

Shape-stable by design: hypothesis draws only *data* (seeds, fog MIPS,
publish intervals) so every example reuses one compiled program — the
property layer the reference never had (SURVEY.md §4 implication note).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="hypothesis not installed in this environment"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.core.engine import prime_initial_advertisements
from fognetsimpp_tpu.runtime import summarize
from fognetsimpp_tpu.scenarios import smoke

TERMINAL = (Stage.DONE, Stage.NO_RESOURCE, Stage.DROPPED, Stage.REJECTED,
            Stage.LOST)
IN_FLIGHT = (Stage.PUB_INFLIGHT, Stage.TASK_INFLIGHT, Stage.QUEUED,
             Stage.RUNNING, Stage.LOCAL_RUN)

_WORLD = {}


def _world():
    if not _WORLD:
        _WORLD["w"] = smoke.build(
            horizon=0.4, send_interval=0.02, n_users=4, n_fogs=3,
            queue_capacity=8, start_time_max=0.05,
        )
    return _WORLD["w"]


@settings(max_examples=12, deadline=None)
@given(
    seed=st.integers(0, 2**31 - 1),
    mips=st.lists(
        st.sampled_from([200.0, 800.0, 2000.0, 20000.0]),
        min_size=3, max_size=3,
    ),
    interval=st.floats(0.02, 0.2),
)
def test_invariants_hold(seed, mips, interval):
    spec, state0, net, bounds = _world()
    m = jnp.asarray(mips, jnp.float32)
    state = state0.replace(
        key=jax.random.PRNGKey(seed),
        fogs=state0.fogs.replace(mips=m, pool_avail=m),
        users=state0.users.replace(
            send_interval=jnp.full((spec.n_users,), interval, jnp.float32)
        ),
    )
    state = prime_initial_advertisements(spec, state, net)
    final, _ = run(spec, state, net, bounds)
    s = summarize(final)

    # 1. conservation: every published task is in exactly one stage bucket
    accounted = sum(
        s[f"stage_{st_.name.lower()}"] for st_ in TERMINAL + IN_FLIGHT
    )
    assert accounted == s["n_published"]

    t = final.tasks
    stage = np.asarray(t.stage)
    used = stage != int(Stage.UNUSED)

    # 2. causal ordering along the offload chain
    def col(name):
        return np.asarray(getattr(t, name))

    sched = np.isfinite(col("t_at_fog"))
    assert (col("t_at_broker")[used] >= col("t_create")[used] - 1e-6).all()
    assert (col("t_at_fog")[sched] >= col("t_at_broker")[sched] - 1e-6).all()
    done = stage == int(Stage.DONE)
    started = done & np.isfinite(col("t_service_start"))
    assert (
        col("t_complete")[started] >= col("t_service_start")[started] - 1e-6
    ).all()
    assert (col("t_ack6")[started] >= col("t_complete")[started] - 1e-6).all()

    # 3. queue bounds and non-negative accumulators
    q_len = np.asarray(final.fogs.q_len)
    assert ((q_len >= 0) & (q_len <= spec.queue_capacity)).all()
    qt = np.asarray(t.queue_time_ms)
    assert (qt[np.isfinite(qt)] >= -1e-3).all()
    assert (np.asarray(final.fogs.busy_time) >= -1e-3).all()

    # 4. a fog's in-service task really is RUNNING
    cur = np.asarray(final.fogs.current_task)
    for c in cur[cur >= 0]:
        assert stage[c] == int(Stage.RUNNING)
