"""Property-based invariants (hypothesis) over randomized world data.

Shape-stable by design: hypothesis draws only *data* (seeds, fog MIPS,
publish intervals) so every example reuses one compiled program — the
property layer the reference never had (SURVEY.md §4 implication note).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="hypothesis not installed in this environment"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.core.engine import prime_initial_advertisements
from fognetsimpp_tpu.runtime import summarize
from fognetsimpp_tpu.scenarios import smoke

TERMINAL = (Stage.DONE, Stage.NO_RESOURCE, Stage.DROPPED, Stage.REJECTED,
            Stage.LOST)
IN_FLIGHT = (Stage.PUB_INFLIGHT, Stage.TASK_INFLIGHT, Stage.QUEUED,
             Stage.RUNNING, Stage.LOCAL_RUN)

_WORLD = {}


def _world():
    if not _WORLD:
        _WORLD["w"] = smoke.build(
            horizon=0.4, send_interval=0.02, n_users=4, n_fogs=3,
            queue_capacity=8, start_time_max=0.05,
        )
    return _WORLD["w"]


@settings(max_examples=12, deadline=None)
@given(
    seed=st.integers(0, 2**31 - 1),
    mips=st.lists(
        st.sampled_from([200.0, 800.0, 2000.0, 20000.0]),
        min_size=3, max_size=3,
    ),
    interval=st.floats(0.02, 0.2),
)
def test_invariants_hold(seed, mips, interval):
    spec, state0, net, bounds = _world()
    m = jnp.asarray(mips, jnp.float32)
    state = state0.replace(
        key=jax.random.PRNGKey(seed),
        fogs=state0.fogs.replace(mips=m, pool_avail=m),
        users=state0.users.replace(
            send_interval=jnp.full((spec.n_users,), interval, jnp.float32)
        ),
    )
    state = prime_initial_advertisements(spec, state, net)
    final, _ = run(spec, state, net, bounds)
    s = summarize(final)

    # 1. conservation: every published task is in exactly one stage bucket
    accounted = sum(
        s[f"stage_{st_.name.lower()}"] for st_ in TERMINAL + IN_FLIGHT
    )
    assert accounted == s["n_published"]

    t = final.tasks
    stage = np.asarray(t.stage)
    used = stage != int(Stage.UNUSED)

    # 2. causal ordering along the offload chain
    def col(name):
        return np.asarray(getattr(t, name))

    sched = np.isfinite(col("t_at_fog"))
    assert (col("t_at_broker")[used] >= col("t_create")[used] - 1e-6).all()
    assert (col("t_at_fog")[sched] >= col("t_at_broker")[sched] - 1e-6).all()
    done = stage == int(Stage.DONE)
    started = done & np.isfinite(col("t_service_start"))
    assert (
        col("t_complete")[started] >= col("t_service_start")[started] - 1e-6
    ).all()
    assert (col("t_ack6")[started] >= col("t_complete")[started] - 1e-6).all()

    # 3. queue bounds and non-negative accumulators
    q_len = np.asarray(final.fogs.q_len)
    assert ((q_len >= 0) & (q_len <= spec.queue_capacity)).all()
    qt = np.asarray(t.queue_time_ms)
    assert (qt[np.isfinite(qt)] >= -1e-3).all()
    assert (np.asarray(final.fogs.busy_time) >= -1e-3).all()

    # 4. a fog's in-service task really is RUNNING
    cur = np.asarray(final.fogs.current_task)
    for c in cur[cur >= 0]:
        assert stage[c] == int(Stage.RUNNING)


# ----------------------------------------------------------------------
# learn/ bandit invariants (driven at the kernel level for speed: the
# full-engine integration lives in tests/test_learn.py)
# ----------------------------------------------------------------------

def _arms(F, explore=0.5):
    from fognetsimpp_tpu.learn.bandits import BanditArms

    f32 = jnp.float32
    z = jnp.zeros((F,), f32)
    return BanditArms(
        pick_count=z, reward_cnt=z, reward_sum=z, disc_cnt=z, disc_sum=z,
        logw=z, explore=jnp.asarray(explore, f32),
    )


@settings(max_examples=8, deadline=None)
@given(
    lat=st.lists(
        st.sampled_from([0.02, 0.1, 0.4, 0.9, 1.5]),
        min_size=5, max_size=5, unique=True,
    ),
    explore=st.floats(0.05, 0.8),
)
def test_ucb_pick_counts_concentrate_on_the_fastest_fog(lat, explore):
    """Stationary heterogeneous arms: after a modest horizon the UCB
    play counts concentrate on the lowest-latency fog."""
    from fognetsimpp_tpu.learn.bandits import ucb_scores
    from fognetsimpp_tpu.learn.rewards import reward_from_latency

    F = len(lat)
    arms = _arms(F, explore)
    avail = jnp.ones((F,), bool)
    lat_j = jnp.asarray(lat, jnp.float32)
    for _ in range(150):
        a = int(np.argmax(np.asarray(ucb_scores(arms, avail))))
        r = reward_from_latency(lat_j[a], 0.5)
        one = jnp.zeros((F,), jnp.float32).at[a].add(1.0)
        arms = arms._replace(
            pick_count=arms.pick_count + one,
            reward_cnt=arms.reward_cnt + one,
            reward_sum=arms.reward_sum + one * r,
        )
    picks = np.asarray(arms.pick_count)
    best = int(np.argmin(lat))
    assert int(np.argmax(picks)) == best
    assert picks[best] > picks.sum() / 2


@settings(max_examples=8, deadline=None)
@given(
    flips=st.lists(st.booleans(), min_size=60, max_size=60),
    gamma=st.floats(0.05, 0.9),
)
def test_exp3_log_weights_stay_finite_under_adversarial_flips(flips, gamma):
    """Adversarial reward sequences (arbitrary 0/1 flips chosen against
    the sampler) cannot walk the EXP3 log-weights to +/-inf: the mixing
    floor bounds each importance weight and the mean-centring pins the
    drift."""
    from fognetsimpp_tpu.learn.bandits import exp3_probs, exp3_sample
    from fognetsimpp_tpu.learn.rewards import credit_batch
    from fognetsimpp_tpu.learn.bandits import init_learn_state
    from fognetsimpp_tpu.spec import Policy, WorldSpec

    F = 3
    spec = WorldSpec(
        n_users=1, n_fogs=F, policy=int(Policy.EXP3), horizon=0.1
    ).validate()
    learn = init_learn_state(spec).replace(
        explore=jnp.asarray(gamma, jnp.float32)
    )
    avail = jnp.ones((F,), bool)
    for i, good in enumerate(flips):
        p = exp3_probs(learn.logw, avail, learn.explore)
        arm = int(exp3_sample(p, jnp.asarray([(i * 0.618) % 1.0]))[0])
        # adversary: latency ~0 (reward 1) or huge (reward ~0)
        lat = jnp.asarray([0.0 if good else 50.0], jnp.float32)
        memb = (
            jnp.arange(F)[:, None] == jnp.asarray([[arm]])
        )  # (F, 1) one-hot
        learn = credit_batch(
            learn, jnp.asarray([True]), memb, lat,
            jnp.asarray([float(p[arm])], jnp.float32),
            F, spec.learn_discount, spec.learn_reward_scale,
        )
    logw = np.asarray(learn.logw)
    assert np.isfinite(logw).all()
    # mean-centred: bounded drift even after 60 adversarial credits
    assert np.abs(logw).max() < 1e3
    p = np.asarray(exp3_probs(learn.logw, avail, learn.explore))
    assert np.isfinite(p).all() and abs(p.sum() - 1.0) < 1e-5


@settings(max_examples=6, deadline=None)
@given(
    seed=st.integers(0, 2**31 - 1),
    mips=st.lists(
        st.sampled_from([500.0, 1000.0, 4000.0]), min_size=3, max_size=3
    ),
)
def test_learn_state_checkpoint_roundtrips_bit_identically(
    seed, mips, tmp_path_factory
):
    """A LearnState-carrying world survives checkpoint.save/load with
    every leaf bit-identical (the struct contract covers the new carry
    field too)."""
    from fognetsimpp_tpu.runtime import checkpoint

    spec, state0, net, bounds = _learn_world()
    m = jnp.asarray(mips, jnp.float32)
    state = state0.replace(
        key=jax.random.PRNGKey(seed),
        fogs=state0.fogs.replace(mips=m, pool_avail=m),
    )
    state = prime_initial_advertisements(spec, state, net)
    mid, _ = run(spec, state, net, bounds, n_ticks=120)
    p = str(tmp_path_factory.mktemp("ck") / "learn.npz")
    checkpoint.save(p, spec, mid)
    spec2, mid2 = checkpoint.load(p)
    for a, b in zip(jax.tree.leaves(mid), jax.tree.leaves(mid2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


_LEARN_WORLD = {}


def _learn_world():
    if not _LEARN_WORLD:
        _LEARN_WORLD["w"] = smoke.build(
            horizon=0.4, send_interval=0.02, n_users=3, n_fogs=3,
            policy=8,  # Policy.UCB
        )
    return _LEARN_WORLD["w"]
