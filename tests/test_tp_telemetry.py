"""Distributed observability gates (ISSUE 11).

Three planes over the sharded execution paths:

* **Per-shard phase attribution** — under TP every established
  ``phase_work`` slot must equal the single-device profile BIT-FOR-BIT
  (shard-partial bracket deltas folded in the end-of-tick psum; the
  replicated half booked once), while the two new exchange slots
  (``tp_exchange``/``tp_defer``) carry the TP-only quantities a single
  device has no analog for.
* **Exchange-plane telemetry** — per-shard occupancy histogram /
  candidate / defer / utilization / age gauges riding
  ``TelemetryState`` (zero-row and bit-exact when off), exposed as
  ``fns_tp_exchange_*{shard=...}`` OpenMetrics families, ``.sca.json``
  ``tp_shard`` rows and Perfetto per-shard counter lanes.
* **Sharded health plane** — ``serve_tp_run`` (``--serve --tp N``)
  serves live OpenMetrics + ``/healthz`` over the TP chunk runner; a
  forced sustained-overflow world trips the defer-RATE watchdog (the
  per-tick gauge is constant under rotation, so only the cumulative
  delta can page) and the flight recorder's per-shard hashes let
  ``tools/postmortem.py --diff`` name the diverging shard.

Compile budget: the quick tier compiles THREE TP programs (telemetry,
hist, the overflow/serve world); the run_jit/run_chunked cross-entry
A/Bs and the CLI composition smoke ride the slow tier.
"""
import dataclasses
import hashlib
import json
import os
import sys
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fognetsimpp_tpu import Policy, run
from fognetsimpp_tpu.core.engine import run_chunked, run_jit
from fognetsimpp_tpu.parallel import (
    make_mesh,
    run_tp_chunked,
    run_tp_sharded,
)
from fognetsimpp_tpu.scenarios import smoke
from fognetsimpp_tpu.telemetry.metrics import (
    EXG_OCC_BINS,
    PHASE_INDEX,
    PHASES,
    RES_FIELDS,
    exchange_summary,
    telemetry_summary,
)

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)

SMALL = dict(
    n_users=16, n_fogs=3, send_interval=0.01, horizon=0.2,
    start_time_max=0.05,
)

#: TP-only phase_work slots: zero on every single-device path.
_TP_SLOTS = (PHASE_INDEX["tp_exchange"], PHASE_INDEX["tp_defer"])
_SHARED = [i for i in range(len(PHASES)) if i not in _TP_SLOTS]


def _hash(state, skip=()) -> str:
    h = hashlib.sha256()
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        if any(s in jax.tree_util.keystr(path) for s in skip):
            continue
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _build(**kw):
    args = dict(SMALL)
    args.update(kw)
    return smoke.build(**args)


def _tp(spec, state, net, bounds, mesh, **kw):
    kw.setdefault("donate", True)
    return run_tp_sharded(
        spec, jax.tree.map(jnp.copy, state), net, bounds, mesh, **kw
    )


@pytest.fixture(scope="module")
def node_mesh():
    assert len(jax.devices()) == 8, "conftest must provision 8 devices"
    return make_mesh(8, axis_name="node")


# ----------------------------------------------------------------------
# per-shard phase attribution
# ----------------------------------------------------------------------

def test_phase_work_books_identically_under_tp(node_mesh):
    """Sum over shards of per-phase work == the single-device profile,
    bit-for-bit, on every established slot; the TP-only exchange slots
    are nonzero under TP and zero on the reference; every OTHER
    telemetry leaf (gauges, reservoir incl. the new defer_total column,
    counters) is bit-equal; the non-telemetry state is bit-exact."""
    spec, state, net, bounds = _build(telemetry=True)
    ref, _ = run(spec, state, net, bounds)
    spec2, got = _tp(spec, state, net, bounds, node_mesh)
    assert spec2.tp_shards == 8

    pw_ref = np.asarray(ref.telem.phase_work)
    pw_tp = np.asarray(got.telem.phase_work)
    np.testing.assert_array_equal(pw_ref[_SHARED], pw_tp[_SHARED])
    assert pw_ref[_SHARED].sum() > 0  # the profile is not trivially zero
    assert (pw_ref[list(_TP_SLOTS)] == 0).all()
    assert pw_tp[PHASE_INDEX["tp_exchange"]] > 0
    # hloaudit attributes the same phases in the compiled tp_tick
    # manifest via the jax.named_scope bracket this booking shares

    # every other telemetry leaf bit-equal (exchange leaves are TP-only)
    for f in dataclasses.fields(ref.telem):
        if f.name in ("phase_work",) or f.name.startswith("exg_"):
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(ref.telem, f.name)),
            np.asarray(getattr(got.telem, f.name)),
            err_msg=f.name,
        )
    assert "defer_total" in RES_FIELDS  # the watchdog's rate column
    # ...and the simulation itself is bit-exact
    assert _hash(ref, skip=("telem",)) == _hash(got, skip=("telem",))

    # exchange-plane roll-up sanity on the same run
    ex = exchange_summary(spec2, got)
    ticks = int(np.asarray(got.telem.ticks))
    assert ex["n_shards"] == 8
    assert ex["occ_hist"].shape == (8, EXG_OCC_BINS)
    np.testing.assert_array_equal(ex["occ_hist"].sum(axis=1), ticks)
    # candidates were produced (a decided task becomes a candidate the
    # tick its broker->fog hop lands, so the total trails n_scheduled
    # only by the in-flight tail at horizon end)
    assert ex["cand"].sum() > 0
    assert (ex["defer_sum"] == 0).all()  # full window never defers
    assert (ex["util_mean"] <= 1.0).all()
    # the strided occupancy rows feed the Perfetto shard lanes
    assert ex["occ_rows"].shape[1] == 8 and ex["occ_rows"].shape[0] > 0
    # single-device worlds have no exchange plane at all
    assert exchange_summary(spec, ref) is None
    assert np.asarray(ref.telem.exg_cand_sum).shape == (0,)


def test_run_node_sharded_keeps_callers_spec_consistent(node_mesh):
    """The single-return dispatch entry runs UNSTAMPED (stamp=False):
    the caller's spec must keep describing the returned state — no
    per-shard exchange leaves materialize behind its back (the
    telemetry contract would reject them), while phase attribution
    still books, tp_exchange slot included."""
    from fognetsimpp_tpu.core.contracts import check_telemetry_contract
    from fognetsimpp_tpu.parallel.taskshard import run_node_sharded

    spec, state, net, bounds = _build(telemetry=True)
    ref, _ = run(spec, state, net, bounds)
    got = run_node_sharded(
        spec, jax.tree.map(jnp.copy, state), net, bounds, node_mesh
    )
    check_telemetry_contract(spec, got)
    assert np.asarray(got.telem.exg_cand_sum).shape == (0,)
    pw_r = np.asarray(ref.telem.phase_work)
    pw_g = np.asarray(got.telem.phase_work)
    np.testing.assert_array_equal(pw_r[_SHARED], pw_g[_SHARED])
    assert pw_g[PHASE_INDEX["tp_exchange"]] > 0


def test_hist_books_identically_under_tp(node_mesh):
    """spec.telemetry_hist under TP: per-fog bucket counts and the
    exactly-once seen flags are BIT-equal to the single-device run
    (integer scatter-adds commute across the psum fold); the f32
    lat_sum agrees to 1e-6 (the cross-shard fold changes the float
    addition grouping — documented, not bit-pinned)."""
    spec, state, net, bounds = _build(
        send_interval=0.25, horizon=2.0,
        telemetry=True, telemetry_hist=True, derive_acks=False,
    )
    ref, _ = run(spec, state, net, bounds)
    spec2, got = _tp(spec, state, net, bounds, node_mesh)
    a = np.asarray(ref.telem.lat_hist)
    b = np.asarray(got.telem.lat_hist)
    np.testing.assert_array_equal(a, b)
    assert a.sum() > 0  # real samples streamed
    np.testing.assert_array_equal(
        np.asarray(ref.telem.lat_seen), np.asarray(got.telem.lat_seen)
    )
    np.testing.assert_allclose(
        np.asarray(ref.telem.lat_sum), np.asarray(got.telem.lat_sum),
        rtol=1e-6,
    )
    # phase profile equality holds with the hist phase traced too
    np.testing.assert_array_equal(
        np.asarray(ref.telem.phase_work)[_SHARED],
        np.asarray(got.telem.phase_work)[_SHARED],
    )
    assert _hash(ref, skip=("telem",)) == _hash(got, skip=("telem",))


@pytest.mark.slow  # extra compiles: full-suite tier
def test_tp_telemetry_across_worlds_and_entries(node_mesh):
    """The 3 dense-family policy worlds x run/run_jit/run_chunked:
    phase_work + hist equality is entry-independent, and a chunked TP
    run bit-matches the one-shot TP run."""
    worlds = [
        dict(policy=int(Policy.MIN_BUSY)),
        dict(policy=int(Policy.MIN_LATENCY), send_interval_jitter=0.1),
        dict(policy=int(Policy.MAX_MIPS)),
    ]
    for kw in worlds:
        spec, state, net, bounds = _build(
            send_interval=0.25, horizon=2.0,
            telemetry=True, telemetry_hist=True, derive_acks=False, **kw
        )
        ref, _ = run(spec, state, net, bounds)
        jit_ref = run_jit(
            spec, jax.tree.map(jnp.copy, state), net, bounds
        )
        chunk_ref = run_chunked(
            spec, jax.tree.map(jnp.copy, state), net, bounds,
            chunk_ticks=spec.n_ticks // 2,
        )
        # the single-device entries agree among themselves...
        assert _hash(ref) == _hash(jit_ref) == _hash(chunk_ref)
        spec2, got = _tp(spec, state, net, bounds, node_mesh)
        np.testing.assert_array_equal(
            np.asarray(ref.telem.lat_hist),
            np.asarray(got.telem.lat_hist), err_msg=str(kw),
        )
        np.testing.assert_array_equal(
            np.asarray(ref.telem.phase_work)[_SHARED],
            np.asarray(got.telem.phase_work)[_SHARED],
            err_msg=str(kw),
        )
        assert _hash(ref, skip=("telem",)) == _hash(
            got, skip=("telem",)
        ), kw
        # chunked TP == one-shot TP, bit-for-bit, telemetry included
        spec3, got_c = run_tp_chunked(
            spec, jax.tree.map(jnp.copy, state), net, bounds, node_mesh,
            chunk_ticks=spec.n_ticks // 4,
        )
        assert spec3 == spec2
        assert _hash(got_c) == _hash(got), kw


# ----------------------------------------------------------------------
# sharded health plane: serve --tp, defer-rate watchdog, postmortem
# ----------------------------------------------------------------------

def test_serve_tp_overflow_pages_and_postmortem_names_the_shard(
    node_mesh, tmp_path
):
    """A forced sustained-overflow world (exchange_window=1 from t=0)
    under serve_tp_run: the defer-RATE floor trips the watchdog (the
    z-score alone cannot — the rate is CONSTANT), a post-mortem bundle
    lands with per-shard hashes, the live endpoint serves per-shard
    exchange families that pass the OpenMetrics lint, and
    tools/postmortem.py --diff bisects the diverging shard."""
    import check_openmetrics as com
    import postmortem

    from fognetsimpp_tpu.telemetry.live import serve_tp_run

    # every user publishes EVERY tick (interval == dt) into a 1-slot
    # exchange window: 2 candidates per shard per tick, 1 deferred —
    # constant overflow from t=0, the regime whose z-score is 0 forever
    spec, state, net, bounds = _build(
        send_interval=0.001, start_time_max=0.0, horizon=0.15,
        telemetry=True,
    )
    dump_dir = str(tmp_path / "pm")
    spec2, final, status = serve_tp_run(
        spec, state, net, bounds, node_mesh,
        exchange_window=1,
        chunk_ticks=30,
        port=0,
        dump_dir=dump_dir,
    )
    # sustained overflow really deferred...
    assert int(np.asarray(final.metrics.n_deferred_max)) > 0
    ex = exchange_summary(spec2, final)
    assert ex["defer_sum"].sum() > 0
    assert ex["age_max_ticks"].max() > 0  # someone waited
    assert (ex["occ_hist"][:, -1] > 0).any()  # overflow bucket hit
    # ...and the defer-rate floor paged (kind='floor', not a z spike)
    wd = status["watchdog"]
    fired = [a for a in wd.anomalies if a["signal"] == "defer_rate"]
    assert fired and any(a.get("kind") == "floor" for a in fired)
    assert status["dumps"], "anomaly must dump a post-mortem bundle"

    # live endpoint: per-shard families + healthz, lint-clean
    port = status["port"]
    om = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics"
    ).read().decode()
    hz = json.load(
        urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz")
    )
    status["server"].close()
    assert com.check_text(om, "tp-serve") == 0
    assert 'fns_tp_exchange_occupancy_bucket{shard="0"' in om
    assert "defer_rate" in hz["signals"]

    # flight recorder carried per-shard hashes each chunk
    ring = status["recorder"].ring
    assert all(len(e.get("shard_hashes") or []) == 8 for e in ring)

    # postmortem --diff: two bundles built from the FULL serving ring
    # (the defer-rate dump fires on chunk 1, so its own ring snapshot
    # is one entry deep), the twin's shard-3 hash flipped at the second
    # chunk; the diff must name tick AND shard.  Writing the bundles
    # minimal also exercises load()'s optional-field defaults.
    assert len(ring) >= 2
    src = str(tmp_path / "run_a.json")
    with open(src, "w") as f:
        json.dump({"reason": "anomaly", "ring": ring}, f)
    b = json.loads(json.dumps({"reason": "anomaly", "ring": ring}))
    t_div = b["ring"][1]["ticks_done"]
    b["ring"][1]["state_hash"] = "deadbeef"
    b["ring"][1]["shard_hashes"][3] = "deadbeef"
    twin = str(tmp_path / "twin.json")
    with open(twin, "w") as f:
        json.dump(b, f)
    lines = postmortem.diff(postmortem.load(src), postmortem.load(twin))
    text = "\n".join(lines)
    assert f"first state-hash divergence at tick {t_div}" in text
    assert "diverging shard(s)" in text and "3" in text


def test_serve_tp_window_overflow_pages(node_mesh, tmp_path):
    """The defer-rate watchdog floor pages under the WINDOWED exchange
    too (ISSUE 18 satellite): a global arrival_window=2 with every user
    publishing every tick keeps the hop-pruned merge ring truncating
    from t=0, the deferral books into the same n_deferred /
    exchange-plane gauges, and the floor fires exactly like the
    exchange_window overflow world above."""
    from fognetsimpp_tpu.telemetry.live import serve_tp_run

    spec, state, net, bounds = _build(
        send_interval=0.001, start_time_max=0.0, horizon=0.15,
        telemetry=True, arrival_window=2,
    )
    spec2, final, status = serve_tp_run(
        spec, state, net, bounds, node_mesh,
        chunk_ticks=30,
        port=0,
        dump_dir=str(tmp_path / "pm"),
    )
    status["server"].close()
    # sustained window overflow really deferred, observably
    assert int(np.asarray(final.metrics.n_deferred_max)) > 0
    ex = exchange_summary(spec2, final)
    assert ex["defer_sum"].sum() > 0
    assert ex["age_max_ticks"].max() > 0
    # ...and the defer-rate floor paged (kind='floor')
    fired = [
        a for a in status["watchdog"].anomalies
        if a["signal"] == "defer_rate"
    ]
    assert fired and any(a.get("kind") == "floor" for a in fired)


def test_postmortem_tolerates_pre_issue6_bundles(tmp_path, capsys):
    """A minimal old-style bundle (no compile_cache, no watchdog, ring
    entries without hashes) summarizes without crashing."""
    import postmortem

    old = {
        "reason": "crash",
        "ring": [{"rows": {"t": [1.0]}}],
        "watchdog": {"anomalies": [{"signal": "q_depth"}]},  # no z
    }
    p = str(tmp_path / "old.json")
    with open(p, "w") as f:
        json.dump(old, f)
    rc = postmortem.main([p])
    out = capsys.readouterr().out
    assert rc == 0
    assert "reason:      crash" in out
    assert "z=?" in out
    # and --diff against itself stays calm
    assert postmortem.main(["--diff", p, p]) == 0


# ----------------------------------------------------------------------
# host-side exposition units (no TP compile)
# ----------------------------------------------------------------------

def test_watchdog_defer_rate_is_per_tick_not_per_row():
    """The defer-rate floor must mean deferred-per-TICK at any horizon:
    the reservoir stride (row_ticks) normalizes the per-row cumulative
    delta, so a long-horizon serve (stride >> 1) does not page on a
    benign trickle while the same physical rate pages at stride 1."""
    from fognetsimpp_tpu.telemetry.live import Watchdog

    def rows(deferred):
        n = len(deferred)
        return {
            "t": np.arange(n, dtype=float),
            "q_len_total": np.zeros(n),
            "n_busy": np.zeros(n),
            "n_deferred": np.zeros(n),
            "n_completed": np.zeros(n),
            "n_dropped": np.zeros(n),
            "defer_total": np.asarray(deferred, float),
        }

    # 0.05 deferrals/tick over 10 rows x 100-tick stride = delta 50
    wd = Watchdog(4, row_ticks=100)
    sig = wd.signals_from_rows(rows(np.arange(10) * 5.0))
    assert sig["defer_rate"] == pytest.approx(45.0 / 1000.0)
    assert not wd.update(sig, 1000)  # benign: well under the floor
    # the same per-row delta at stride 1 is 4.5/tick -> floor trips
    wd1 = Watchdog(4, row_ticks=1)
    sig1 = wd1.signals_from_rows(rows(np.arange(10) * 5.0))
    fired = wd1.update(sig1, 10)
    assert fired and fired[0]["kind"] == "floor"


def test_openmetrics_linter_shard_label_rules():
    """The shard-label contract on fns_tp_exchange_* families: missing
    label, non-integer value and shard gaps are findings; the generic
    duplicate-series rule covers duplicate (family, shard) pairs."""
    import check_openmetrics as com

    head = (
        "# HELP fns_tp_exchange_candidates c\n"
        "# TYPE fns_tp_exchange_candidates counter\n"
    )
    good = (
        head
        + 'fns_tp_exchange_candidates{shard="0"} 5\n'
        + 'fns_tp_exchange_candidates{shard="1"} 7\n# EOF\n'
    )
    assert com.check_text(good, "g") == 0
    assert com.check_text(
        head + "fns_tp_exchange_candidates 5\n# EOF\n", "no-label"
    ) == 1
    assert com.check_text(
        head + 'fns_tp_exchange_candidates{shard="x"} 5\n# EOF\n',
        "non-int",
    ) == 1
    assert com.check_text(
        head + 'fns_tp_exchange_candidates{shard="1"} 5\n# EOF\n',
        "gap",
    ) == 1
    assert com.check_text(
        head
        + 'fns_tp_exchange_candidates{shard="0"} 5\n'
        + 'fns_tp_exchange_candidates{shard="0"} 6\n# EOF\n',
        "dup",
    ) == 1
    # TRAILING gap: the published fns_tp_shards count is the truth —
    # shards 0..1 of a 3-shard run is a finding even with no hole
    shards_head = (
        "# HELP fns_tp_shards s\n# TYPE fns_tp_shards gauge\n"
        "fns_tp_shards 3\n"
    )
    assert com.check_text(shards_head + good[: -len("# EOF\n")]
                          + "# EOF\n", "trailing-gap") == 1
    assert com.check_text(
        shards_head
        + head
        + 'fns_tp_exchange_candidates{shard="0"} 5\n'
        + 'fns_tp_exchange_candidates{shard="1"} 7\n'
        + 'fns_tp_exchange_candidates{shard="2"} 9\n# EOF\n',
        "complete",
    ) == 0


def test_fleet_openmetrics_per_replica_phase_work():
    """render_fleet_openmetrics publishes one sample per
    (fleet=replica, phase) pair and stays lint-clean."""
    import check_openmetrics as com

    from fognetsimpp_tpu.telemetry.openmetrics import (
        render_fleet_openmetrics,
    )

    pw = np.arange(2 * len(PHASES)).reshape(2, len(PHASES))
    scalars = {
        "n_replicas": 2,
        "aggregate": {
            "n_completed": {
                "sum": 4.0, "mean": 2.0, "min": 1.0, "max": 3.0
            }
        },
    }
    text = render_fleet_openmetrics(scalars, phase_work=pw)
    assert com.check_text(text, "fleet") == 0
    assert 'fns_fleet_phase_work{fleet="0",phase="connect"} 0' in text
    # the LAST registered phase slot, whatever it is (phases appended
    # since — e.g. ISSUE 12's "chaos" — must not silently fall off)
    assert (
        f'fns_fleet_phase_work{{fleet="1",phase="{PHASES[-1]}"}} '
        f"{2 * len(PHASES) - 1}" in text
    )
    assert 'phase="tp_defer"' in text


def test_bench_trend_overhead_gate(tmp_path):
    """A capture recording telemetry_overhead above the bar fails
    --check; at/below the bar passes."""
    import bench_trend

    def cap(path, overhead):
        with open(path, "w") as f:
            json.dump(
                {
                    "parsed": {
                        "metric": "m", "value": 100.0, "backend": "cpu",
                        "n_users": 8, "telemetry_overhead": overhead,
                    }
                },
                f,
            )

    cap(tmp_path / "BENCH_r01.json", 1.04)
    rows = bench_trend.load_rounds(str(tmp_path))
    assert bench_trend.check(rows) == []
    cap(tmp_path / "BENCH_r02.json", 1.31)
    rows = bench_trend.load_rounds(str(tmp_path))
    problems = bench_trend.check(rows)
    assert len(problems) == 1 and "overhead" in problems[0]


@pytest.mark.slow  # in-process CLI: its own TP serve program
def test_cli_serve_tp_composes(tmp_path, capsys):
    """--serve --tp N end to end: pads, serves, records — the
    previously rejected composition (ISSUE 11)."""
    from fognetsimpp_tpu.__main__ import main

    rc = main([
        "--scenario", "smoke", "--tp", "8", "--serve", "0",
        "--serve-chunk", "50",
        "--set", "scenario.n_users=16",
        "--set", "scenario.n_fogs=3",
        "--set", "scenario.send_interval=0.01",
        "--set", "scenario.horizon=0.1",
        "--out", str(tmp_path),
    ])
    captured = capsys.readouterr()
    assert rc == 0
    summary = json.loads(captured.out.strip().splitlines()[-1])
    assert summary["tp_shards"] == 8 and summary["chunks"] >= 1
    om = open(
        os.path.join(str(tmp_path), "General-0.om.txt")
    ).read()
    assert "fns_tp_exchange_occupancy_bucket" in om
    sca = json.load(
        open(os.path.join(str(tmp_path), "General-0.sca.json"))
    )
    assert len(sca["modules"]["tp_shard"]) == 8
