"""Scave text-format export: the reference's tooling reads our results.

`runtime/scave.py` renders a finished run in the OMNeT++ 4.x "version 2"
text grammar (`simulations/example/results/General-0.sca` shape: run/attr
header, `scalar <module> <name> <value>` rows, `statistic` blocks with
seven `field` rows; the `.vec` twin declares `vector <id> <module> <name>
ETV` and streams tab-separated id/event/time/value rows).  These tests
parse the emitted files back with a minimal reader and check the numbers
round-trip against the engine's own state.
"""
import os
import re

import numpy as np

from fognetsimpp_tpu import run
from fognetsimpp_tpu.runtime.recorder import record_run
from fognetsimpp_tpu.runtime.scave import export_scave
from fognetsimpp_tpu.scenarios import smoke


def _world():
    return smoke.build(
        horizon=0.6, send_interval=0.02, dt=1e-3, n_users=3, n_fogs=2,
        fog_mips=(20000.0, 30000.0), start_time_max=0.01,
    )


def _parse_sca(path):
    scalars, stats = {}, {}
    cur = None
    with open(path) as f:
        lines = f.read().splitlines()
    assert lines[0] == "version 2"
    assert lines[1].startswith("run ")
    for ln in lines[2:]:
        if ln.startswith("scalar "):
            m = re.match(r'scalar (\S+) \t("[^"]+"|\S+) \t(\S+)', ln)
            assert m, ln
            scalars[(m.group(1), m.group(2).strip('"'))] = float(m.group(3))
            cur = None
        elif ln.startswith("statistic "):
            m = re.match(r'statistic (\S+) \t("[^"]+"|\S+)', ln)
            assert m, ln
            cur = (m.group(1), m.group(2).strip('"'))
            stats[cur] = {}
        elif ln.startswith("field ") and cur is not None:
            _, name, val = ln.split(" ", 2)
            stats[cur][name] = float(val)
    return scalars, stats


def test_sca_roundtrip(tmp_path):
    spec, state, net, bounds = _world()
    final, _ = run(spec, state, net, bounds)
    paths = export_scave(str(tmp_path), spec, final, network="Network")
    scalars, stats = _parse_sca(paths["sca"])

    tx = np.asarray(final.nodes.tx_count)
    rx = np.asarray(final.nodes.rx_count)
    for u in range(spec.n_users):
        mod = f"Network.user[{u}].udpApp[0]"
        assert scalars[(mod, "packets sent")] == tx[u]
        assert scalars[(mod, "packets received")] == rx[u]
    bmod = "Network.BaseBroker.udpApp[0]"
    assert scalars[(bmod, "echoedPk:count")] == rx[spec.broker_index]

    # statistic fields are real statistics of the signal vectors
    from fognetsimpp_tpu.runtime.signals import extract_signals

    sig = extract_signals(final)
    st = stats[(bmod, "delay:stats")]
    assert st["count"] == sig["delay"].size
    np.testing.assert_allclose(st["mean"], sig["delay"].mean(), rtol=1e-6)
    np.testing.assert_allclose(st["max"], sig["delay"].max(), rtol=1e-6)
    # per-user taskTime blocks partition the global vector
    tot = sum(
        stats[(f"Network.user[{u}].udpApp[0]", "taskTime:stats")]["count"]
        for u in range(spec.n_users)
    )
    assert tot == sig["task_time"].size


def test_vec_roundtrip(tmp_path):
    spec, state, net, bounds = _world()
    final, _ = run(spec, state, net, bounds)
    paths = export_scave(str(tmp_path), spec, final, network="Network")
    decls, rows = {}, []
    with open(paths["vec"]) as f:
        for ln in f:
            if ln.startswith("vector "):
                m = re.match(r"vector (\d+)  (\S+)  (\S+)  ETV", ln)
                assert m, ln
                decls[int(m.group(1))] = (m.group(2), m.group(3))
            elif re.match(r"^\d+\t", ln):
                vid, ev, t, v = ln.split("\t")
                rows.append((int(vid), int(ev), float(t), float(v)))
    assert decls and rows
    # every data row references a declared vector; events are monotone
    evs = [r[1] for r in rows]
    assert evs == sorted(evs)
    assert {r[0] for r in rows} <= set(decls)
    # the taskTime samples across users equal the engine's signal vector
    from fognetsimpp_tpu.runtime.signals import extract_signals

    want = np.sort(extract_signals(final)["task_time"])
    got = np.sort(
        [r[3] for r in rows if decls[r[0]][1] == "taskTime:vector"]
    )
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_record_run_emits_scave_twins(tmp_path):
    spec, state, net, bounds = _world()
    final, _ = run(spec, state, net, bounds)
    paths = record_run(str(tmp_path), spec, final)
    for k in ("sca_txt", "vec_txt", "anf"):
        assert os.path.exists(paths[k]), k
    with open(paths["anf"]) as f:
        anf = f.read()
    assert paths["sca_txt"] in anf and paths["vec_txt"] in anf


# ---------------------------------------------------------------------
# the reader against the REFERENCE'S OWN committed artifacts (VERDICT r4
# item 7): grammar compatibility proven on the real files, not only on
# this exporter's output
# ---------------------------------------------------------------------

REF_EXAMPLE_SCA = "/root/reference/simulations/example/results/General-0.sca"
REF_EXAMPLE_VEC = "/root/reference/simulations/example/results/General-0.vec"
REF_TESTING_SCA = "/root/reference/simulations/results/General-0.sca"


def test_reads_reference_example_sca():
    from fognetsimpp_tpu.runtime.scave import read_sca

    s = read_sca(REF_EXAMPLE_SCA)
    assert s["run"].startswith("General-0-20180626")
    assert s["attrs"]["network"] == "WirelessNet"
    # every scalar row parsed (grep -c '^scalar' == 1497)
    assert len(s["scalars"]) == 1497
    # app-level anchors the repo's own modules mirror
    sc = s["scalars"]
    assert sc[("WirelessNet.BaseBroker.udpApp[0]", "echoedPk:count")] == 1744
    # quoted names ("simulated time", "frames/sec sent") parse
    assert (
        sc[("WirelessNet.ComputeBroker1.eth[0].mac", "simulated time")]
        == 3.350067039997
    )
    # statistic blocks with nested attrs + histogram bins
    st = s["statistics"][
        ("WirelessNet.ComputeBroker1.udpApp[0]", "rcvdPkLifetime:stats")
    ]
    assert st["fields"]["count"] >= 0


def test_reads_reference_unused_testing_sca():
    """The 153.906 s testing run — the artifact NOTHING in r1-r4 touched
    (VERDICT r4 missing item 2).  Parse it fully and anchor what it
    pins: the run length (consistent across every MAC module) and the
    802.11 beacon accounting (APs beacon every ~0.1 s, each AP hears its
    two in-range neighbours — the WirelessNet AP layout)."""
    from fognetsimpp_tpu.runtime.scave import read_sca

    s = read_sca(REF_TESTING_SCA)
    assert len(s["scalars"]) == 1073
    sim_times = {
        v for (mod, name), v in s["scalars"].items()
        if name == "simulated time"
    }
    assert sim_times == {153.90571729757}
    sent = {
        mod.split(".")[1]: v
        for (mod, name), v in s["scalars"].items()
        if name == "sentDownPk:count" and ".wlan[0].mac" in mod
    }
    rcvd = {
        mod.split(".")[1]: v
        for (mod, name), v in s["scalars"].items()
        if name == "numReceivedBroadcast" and ".wlan[0].mac" in mod
    }
    aps = [k for k in sent if k.startswith("ap")]
    assert len(aps) >= 2
    for ap in aps:
        beacon_interval = 153.90571729757 / sent[ap]
        assert abs(beacon_interval - 0.1) < 2e-3, (ap, beacon_interval)
        # each AP's received broadcasts ~= 2 neighbours' beacons
        assert abs(rcvd[ap] / sent[ap] - 2.0) < 0.05, ap


def test_reads_reference_example_vec():
    from fognetsimpp_tpu.runtime.scave import read_vec

    v = read_vec(REF_EXAMPLE_VEC, vector_ids={1093})
    d = v["vectors"][1093]
    assert d["module"] == "WirelessNet.user.udpApp[0]"
    assert d["name"] == "delay:vector" and d["columns"] == "ETV"
    ev, tt, val = v["data"][1093]
    assert val.size == 52  # the committed delay vector (BASELINE.md)
    np.testing.assert_allclose(val.mean(), 0.5018811835, rtol=1e-9)
    np.testing.assert_allclose(val.min(), 0.401364501443, rtol=1e-12)
    np.testing.assert_allclose(val.max(), 0.981402934761, rtol=1e-12)
    assert (np.diff(ev) > 0).all()  # event column is monotone


def test_reader_roundtrips_own_exporter(tmp_path):
    """Both directions through the library code: export a run, read it
    back with read_sca/read_vec (not the test-local regex parser)."""
    from fognetsimpp_tpu.runtime.scave import read_sca, read_vec

    spec, state, net, bounds = _world()
    final, _ = run(spec, state, net, bounds)
    paths = export_scave(str(tmp_path), spec, final, network="Network")
    s = read_sca(paths["sca"])
    tx = np.asarray(final.nodes.tx_count)
    for u in range(spec.n_users):
        mod = f"Network.user[{u}].udpApp[0]"
        assert s["scalars"][(mod, "packets sent")] == tx[u]
    v = read_vec(paths["vec"])
    from fognetsimpp_tpu.runtime.signals import extract_signals

    want = np.sort(extract_signals(final)["task_time"])
    got = np.sort(
        np.concatenate(
            [
                v["data"][vid][2]
                for vid, d in v["vectors"].items()
                if d["name"] == "taskTime:vector" and vid in v["data"]
            ]
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-6)
