"""Scave text-format export: the reference's tooling reads our results.

`runtime/scave.py` renders a finished run in the OMNeT++ 4.x "version 2"
text grammar (`simulations/example/results/General-0.sca` shape: run/attr
header, `scalar <module> <name> <value>` rows, `statistic` blocks with
seven `field` rows; the `.vec` twin declares `vector <id> <module> <name>
ETV` and streams tab-separated id/event/time/value rows).  These tests
parse the emitted files back with a minimal reader and check the numbers
round-trip against the engine's own state.
"""
import os
import re

import numpy as np

from fognetsimpp_tpu import run
from fognetsimpp_tpu.runtime.recorder import record_run
from fognetsimpp_tpu.runtime.scave import export_scave
from fognetsimpp_tpu.scenarios import smoke


def _world():
    return smoke.build(
        horizon=0.6, send_interval=0.02, dt=1e-3, n_users=3, n_fogs=2,
        fog_mips=(20000.0, 30000.0), start_time_max=0.01,
    )


def _parse_sca(path):
    scalars, stats = {}, {}
    cur = None
    with open(path) as f:
        lines = f.read().splitlines()
    assert lines[0] == "version 2"
    assert lines[1].startswith("run ")
    for ln in lines[2:]:
        if ln.startswith("scalar "):
            m = re.match(r'scalar (\S+) \t("[^"]+"|\S+) \t(\S+)', ln)
            assert m, ln
            scalars[(m.group(1), m.group(2).strip('"'))] = float(m.group(3))
            cur = None
        elif ln.startswith("statistic "):
            m = re.match(r'statistic (\S+) \t("[^"]+"|\S+)', ln)
            assert m, ln
            cur = (m.group(1), m.group(2).strip('"'))
            stats[cur] = {}
        elif ln.startswith("field ") and cur is not None:
            _, name, val = ln.split(" ", 2)
            stats[cur][name] = float(val)
    return scalars, stats


def test_sca_roundtrip(tmp_path):
    spec, state, net, bounds = _world()
    final, _ = run(spec, state, net, bounds)
    paths = export_scave(str(tmp_path), spec, final, network="Network")
    scalars, stats = _parse_sca(paths["sca"])

    tx = np.asarray(final.nodes.tx_count)
    rx = np.asarray(final.nodes.rx_count)
    for u in range(spec.n_users):
        mod = f"Network.user[{u}].udpApp[0]"
        assert scalars[(mod, "packets sent")] == tx[u]
        assert scalars[(mod, "packets received")] == rx[u]
    bmod = "Network.BaseBroker.udpApp[0]"
    assert scalars[(bmod, "echoedPk:count")] == rx[spec.broker_index]

    # statistic fields are real statistics of the signal vectors
    from fognetsimpp_tpu.runtime.signals import extract_signals

    sig = extract_signals(final)
    st = stats[(bmod, "delay:stats")]
    assert st["count"] == sig["delay"].size
    np.testing.assert_allclose(st["mean"], sig["delay"].mean(), rtol=1e-6)
    np.testing.assert_allclose(st["max"], sig["delay"].max(), rtol=1e-6)
    # per-user taskTime blocks partition the global vector
    tot = sum(
        stats[(f"Network.user[{u}].udpApp[0]", "taskTime:stats")]["count"]
        for u in range(spec.n_users)
    )
    assert tot == sig["task_time"].size


def test_vec_roundtrip(tmp_path):
    spec, state, net, bounds = _world()
    final, _ = run(spec, state, net, bounds)
    paths = export_scave(str(tmp_path), spec, final, network="Network")
    decls, rows = {}, []
    with open(paths["vec"]) as f:
        for ln in f:
            if ln.startswith("vector "):
                m = re.match(r"vector (\d+)  (\S+)  (\S+)  ETV", ln)
                assert m, ln
                decls[int(m.group(1))] = (m.group(2), m.group(3))
            elif re.match(r"^\d+\t", ln):
                vid, ev, t, v = ln.split("\t")
                rows.append((int(vid), int(ev), float(t), float(v)))
    assert decls and rows
    # every data row references a declared vector; events are monotone
    evs = [r[1] for r in rows]
    assert evs == sorted(evs)
    assert {r[0] for r in rows} <= set(decls)
    # the taskTime samples across users equal the engine's signal vector
    from fognetsimpp_tpu.runtime.signals import extract_signals

    want = np.sort(extract_signals(final)["task_time"])
    got = np.sort(
        [r[3] for r in rows if decls[r[0]][1] == "taskTime:vector"]
    )
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_record_run_emits_scave_twins(tmp_path):
    spec, state, net, bounds = _world()
    final, _ = run(spec, state, net, bounds)
    paths = record_run(str(tmp_path), spec, final)
    for k in ("sca_txt", "vec_txt", "anf"):
        assert os.path.exists(paths[k]), k
    with open(paths["anf"]) as f:
        anf = f.read()
    assert paths["sca_txt"] in anf and paths["vec_txt"] in anf
