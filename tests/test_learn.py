"""learn/ — online bandit schedulers: decision provenance, delayed
credit, the regret harness, one-compile exploration sweeps, and the
bit-exactness of every pre-existing policy around the new carry field.

The heterogeneous 8-fog world: two fast fogs (8000 MIPS) among six slow
ones (1000 MIPS), moderately loaded so queueing separates good and bad
arms without saturating the fast pair.  All numbers are deterministic
(fixed seed, CPU backend) — the asserted margins are wide (2x+), not
knife-edge.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fognetsimpp_tpu import Policy, run
from fognetsimpp_tpu.learn import eval as learn_eval
from fognetsimpp_tpu.scenarios import smoke

# the regret world of the acceptance gate: >= 8 heterogeneous fogs
HET = dict(
    n_users=4,
    n_fogs=8,
    fog_mips=(
        8000.0, 1000.0, 1000.0, 1000.0, 1000.0, 1000.0, 1000.0, 8000.0,
    ),
    send_interval=0.25,
    horizon=20.0,  # 2000 ticks: enough for >2x margins on every gate
    #   while keeping the quick tier's wall-clock budget in sight
    dt=0.01,
    learn_discount=0.9995,
    learn_explore=0.3,
    learn_reward_scale=0.5,
)
FAST_FOGS = (0, 7)

_CACHE = {}


def _statics():
    if "statics" not in _CACHE:
        _CACHE["statics"] = learn_eval.static_oracle(
            smoke.build,
            statics=(Policy.MIN_BUSY, Policy.ROUND_ROBIN, Policy.RANDOM),
            **HET,
        )
    return _CACHE["statics"]


def _ducb():
    if "ducb" not in _CACHE:
        _CACHE["ducb"] = learn_eval.run_policy(
            smoke.build, int(Policy.DUCB), record_series=True, **HET
        )
    return _CACHE["ducb"]


def test_regret_harness_ducb_beats_random_and_tracks_oracle():
    """The acceptance gate: on the heterogeneous 8-fog world,
    discounted-UCB's mean task latency beats Policy.RANDOM and lands
    within 15% of the best static policy for that world."""
    best, means = _statics()
    _, final, _ = _ducb()
    ducb_mean = learn_eval.mean_task_latency_s(final)
    assert np.isfinite(ducb_mean)
    assert ducb_mean < means[int(Policy.RANDOM)], (
        f"DUCB {ducb_mean:.3f}s should beat RANDOM "
        f"{means[int(Policy.RANDOM)]:.3f}s"
    )
    assert ducb_mean <= 1.15 * means[best], (
        f"DUCB {ducb_mean:.3f}s vs best static "
        f"({Policy(best).name}) {means[best]:.3f}s"
    )


def test_ducb_picks_concentrate_on_the_fast_fogs():
    _, final, _ = _ducb()
    picks = np.asarray(final.learn.pick_count)
    fast = sum(picks[f] for f in FAST_FOGS)
    assert fast > 0.6 * picks.sum(), picks
    # every arm was explored at least once (the forced-pull bootstrap)
    assert (picks > 0).all()


def test_regret_curve_is_monotone_evidence_and_ends_low():
    """learnRegret: per-tick credited-mean latency minus the oracle's
    mean — it must end at (or below) the 15% band the mean-latency gate
    asserts, and the pick curve must be cumulative."""
    best, means = _statics()
    _, _, series = _ducb()
    curves = learn_eval.regret_curves(series, means[best])
    r = curves["learnRegret"]
    picks = curves["learnPicks"]
    assert r.shape[0] == picks.shape[0]
    assert picks.shape[1] == HET["n_fogs"]
    # cumulative pick counts never decrease
    assert (np.diff(picks, axis=0) >= -1e-6).all()
    assert r[-1] <= 0.15 * means[best]


def test_harness_emits_regret_signals_through_recorder(tmp_path):
    from fognetsimpp_tpu.runtime.recorder import load_scalars, load_vectors

    out = learn_eval.evaluate(
        smoke.build,
        learned=(Policy.UCB,),
        statics=(Policy.RANDOM,),
        outdir=str(tmp_path),
        n_users=2,
        n_fogs=2,
        fog_mips=(4000.0, 500.0),
        send_interval=0.2,
        horizon=3.0,
    )
    entry = out["learned"]["ucb"]
    vec = load_vectors(entry["paths"]["vec"])
    assert "learnRegret" in vec and "learnPicks" in vec
    assert np.isfinite(vec["learnRegret"]).all()
    assert vec["learnPicks"].shape[1] == 2
    sca = load_scalars(entry["paths"]["sca"])
    # per-fog learnPicks scalar rows + the summarize() roll-up
    assert all("learn_picks" in f for f in sca["modules"]["fog"])
    assert sca["scalars"]["learn_credited"] >= 1


def test_explore_load_grid_runs_in_one_compile():
    """The exploration-rate x load grid of a learned policy reuses ONE
    compiled program: explore rides the carry (LearnState.explore), load
    rides users.send_interval — a second grid with different rates (same
    shapes) is a pure jit-cache hit."""
    from fognetsimpp_tpu.parallel.replicas import _run_replicated
    from fognetsimpp_tpu.parallel.sweep import sweep_explore

    kw = dict(
        n_users=2, n_fogs=3, fog_mips=(4000.0, 500.0, 1000.0),
        horizon=0.5,
    )
    base = _run_replicated._cache_size()
    g1 = sweep_explore(
        smoke.build, policy=int(Policy.UCB), explore_rates=[0.1, 0.7],
        load_intervals=[0.05, 0.1], n_replicas_per_load=2, **kw
    )
    assert _run_replicated._cache_size() == base + 1
    # a second grid over different RATES reuses the same program: the
    # rate axis is carry data, not a compile axis (the load axis sizes
    # spec capacity, so changing the load grid legitimately recompiles)
    g2 = sweep_explore(
        smoke.build, policy=int(Policy.UCB), explore_rates=[0.3, 0.9],
        load_intervals=[0.05, 0.1], n_replicas_per_load=2, **kw
    )
    assert _run_replicated._cache_size() == base + 1, (
        "second exploration-rate grid must be a jit-cache hit"
    )
    for g in (g1, g2):
        assert len(g) == 2
        for grid in g.values():
            assert grid["n_scheduled"].shape == (2, 2)
            assert "lat_mean_s" in grid and "lat_cnt" in grid


def test_dynamic_grid_dispatches_bandit_ids():
    """Policy.DYNAMIC + learn_in_dynamic: static and bandit ids mix in
    one traced-switch grid, and the bandit replicas actually learn."""
    from fognetsimpp_tpu.parallel.sweep import sweep_policies

    grids = sweep_policies(
        smoke.build,
        policies=[int(Policy.MIN_BUSY), int(Policy.UCB), int(Policy.EXP3)],
        load_intervals=[0.05],
        dynamic=True,
        n_users=2,
        n_fogs=3,
        fog_mips=(4000.0, 500.0, 1000.0),
        horizon=0.5,
    )
    assert set(grids) == {0, int(Policy.UCB), int(Policy.EXP3)}
    for g in grids.values():
        assert int(g["n_scheduled"].sum()) > 0


def test_dynamic_grid_rejects_undispatchable_policy():
    from fognetsimpp_tpu.parallel.sweep import sweep_policies

    with pytest.raises(ValueError, match="traced-dispatch"):
        sweep_policies(
            smoke.build, policies=[int(Policy.LOCAL_FIRST)],
            load_intervals=[0.05], dynamic=True,
        )


def test_sweep_explore_rejects_static_policy():
    from fognetsimpp_tpu.parallel.sweep import sweep_explore

    with pytest.raises(ValueError, match="learned"):
        sweep_explore(
            smoke.build, policy=int(Policy.MIN_BUSY),
            explore_rates=[0.1], load_intervals=[0.05],
        )


def test_delayed_credit_is_exactly_once_and_latency_exact():
    """Every DONE task whose status-6 ack landed inside the horizon is
    credited exactly once, with the exact ack latency, to the fog picked
    at publish time; play counts equal broker scheduling decisions."""
    spec, state, net, bounds = smoke.build(
        n_users=3, n_fogs=4, fog_mips=(4000.0, 500.0, 1000.0, 2000.0),
        send_interval=0.1, horizon=2.0, policy=int(Policy.UCB),
    )
    final, _ = run(spec, state, net, bounds)
    from fognetsimpp_tpu import Stage

    t = final.tasks
    stage = np.asarray(t.stage)
    ack6 = np.asarray(t.t_ack6)
    done = stage == int(Stage.DONE)
    landed = done & np.isfinite(ack6) & (ack6 <= float(final.t))
    lat = ack6[landed] - np.asarray(t.t_create)[landed]
    assert int(np.asarray(final.learn.lat_cnt)) == int(landed.sum())
    np.testing.assert_allclose(
        float(final.learn.lat_sum), lat.sum(), rtol=1e-5
    )
    credited = np.asarray(final.learn.credited)
    np.testing.assert_array_equal(credited.astype(bool), landed)
    # per-fog credit counts match the task table's provenance column
    fogs = np.asarray(t.fog)[landed]
    want = np.bincount(fogs, minlength=spec.n_fogs)
    np.testing.assert_array_equal(
        np.asarray(final.learn.reward_cnt).astype(int), want
    )
    assert int(np.asarray(final.learn.pick_count).sum()) == int(
        np.asarray(final.metrics.n_scheduled)
    )


def test_checkpoint_roundtrip_carries_learn_state(tmp_path):
    """A LearnState-carrying world round-trips bit-identically through
    the checkpoint struct contract."""
    from fognetsimpp_tpu.runtime import checkpoint

    spec, state, net, bounds = smoke.build(
        n_users=2, n_fogs=3, fog_mips=(4000.0, 500.0, 1000.0),
        send_interval=0.1, horizon=1.0, policy=int(Policy.EXP3),
    )
    mid, _ = run(spec, state, net, bounds, n_ticks=400)
    assert float(np.asarray(mid.learn.pick_count).sum()) > 0
    p = str(tmp_path / "learn.npz")
    checkpoint.save(p, spec, mid)
    spec2, mid2 = checkpoint.load(p)
    assert spec2.policy == spec.policy
    for a, b in zip(jax.tree.leaves(mid), jax.tree.leaves(mid2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the restored world keeps running
    fin, _ = run(spec2, mid2, net, bounds, n_ticks=50)
    assert int(np.asarray(fin.tick)) == 450


def _state_hash(state) -> bytes:
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def test_preexisting_policies_bit_exact_across_run_entries():
    """State-hash A/B over 3 pre-existing-policy worlds: the learn carry
    field flows through run / run_jit / run_chunked without perturbing a
    single bit of the existing columns (and stays inert: zero learn
    state throughout)."""
    from fognetsimpp_tpu.core.engine import run_chunked, run_jit

    worlds = [
        dict(policy=int(Policy.MIN_BUSY)),
        dict(policy=int(Policy.RANDOM)),
        dict(policy=int(Policy.LOCAL_FIRST), broker_mips=2048.0),
    ]
    for kw in worlds:
        spec, state, net, bounds = smoke.build(
            horizon=0.4, n_users=2, n_fogs=2, send_interval=0.05, **kw
        )
        assert not spec.learn_active
        assert spec.learn_capacity == 0
        ref, _ = run(spec, state, net, bounds)
        h_ref = _state_hash(ref)
        assert float(np.asarray(ref.learn.pick_count).sum()) == 0.0
        spec2, state2, net2, bounds2 = smoke.build(
            horizon=0.4, n_users=2, n_fogs=2, send_interval=0.05, **kw
        )
        assert _state_hash(run_jit(spec2, state2, net2, bounds2)) == h_ref
        spec3, state3, net3, bounds3 = smoke.build(
            horizon=0.4, n_users=2, n_fogs=2, send_interval=0.05, **kw
        )
        assert (
            _state_hash(run_chunked(spec3, state3, net3, bounds3, 170))
            == h_ref
        )


def test_ucb_kernel_explores_untried_arms_first():
    from fognetsimpp_tpu.learn.bandits import BanditArms, ucb_scores

    F = 4
    f32 = jnp.float32
    arms = BanditArms(
        pick_count=jnp.asarray([3.0, 0.0, 1.0, 0.0], f32),
        reward_cnt=jnp.asarray([3.0, 0.0, 1.0, 0.0], f32),
        reward_sum=jnp.asarray([2.9, 0.0, 0.2, 0.0], f32),
        disc_cnt=jnp.zeros((F,), f32),
        disc_sum=jnp.zeros((F,), f32),
        logw=jnp.zeros((F,), f32),
        explore=jnp.asarray(0.5, f32),
    )
    avail = jnp.ones((F,), bool)
    s = np.asarray(ucb_scores(arms, avail))
    # untried arms dominate any finite index
    assert s[1] > s[0] and s[3] > s[0]
    # among tried arms, the high-mean one wins
    assert s[0] > s[2]


def test_exp3_probs_mask_and_floor():
    from fognetsimpp_tpu.learn.bandits import exp3_probs

    logw = jnp.asarray([5.0, 0.0, 0.0, -5.0], jnp.float32)
    avail = jnp.asarray([True, True, False, True])
    p = np.asarray(exp3_probs(logw, avail, jnp.float32(0.2)))
    assert p[2] == 0.0
    np.testing.assert_allclose(p.sum(), 1.0, rtol=1e-5)
    # the gamma mixing floor keeps every available arm samplable
    assert (p[[0, 1, 3]] > 0.2 / 3 * 0.9).all()


def test_exp3_sample_stays_inside_the_support():
    """Edge draws cannot select a zero-probability arm: u == 0.0 (jax
    uniforms are [0,1)) must not land on an unavailable arm 0, and u
    near 1 must not fall off a float32 cumsum that tops out below 1."""
    from fognetsimpp_tpu.learn.bandits import exp3_probs, exp3_sample

    avail = jnp.asarray([False, True, True, True])
    p = exp3_probs(jnp.zeros((4,), jnp.float32), avail, jnp.float32(0.2))
    arms = np.asarray(
        exp3_sample(p, jnp.asarray([0.0, 0.5, 0.9999999], jnp.float32))
    )
    assert (arms != 0).all()
    # skewed weights: the sampled arm always carries positive mass
    rng = np.random.default_rng(0)
    for _ in range(200):
        logw = jnp.asarray(rng.normal(0, 10, size=6), jnp.float32)
        av = jnp.asarray(rng.random(6) > 0.3)
        if not bool(av.any()):
            continue
        pv = exp3_probs(logw, av, jnp.float32(0.05))
        got = np.asarray(
            exp3_sample(pv, jnp.asarray(rng.random(16), jnp.float32))
        )
        assert (np.asarray(pv)[got] > 0).all()
    # no available arm at all still signals -1
    p0 = exp3_probs(
        jnp.zeros((3,), jnp.float32), jnp.zeros((3,), bool),
        jnp.float32(0.2),
    )
    assert int(exp3_sample(p0, jnp.asarray([0.3], jnp.float32))[0]) == -1
