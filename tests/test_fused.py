"""The fused per-user slot-window front-end (ISSUE 5, `spec.fused_slots`).

The acceptance contract: with ``fused_slots`` ON (the default) every
world must be BIT-EXACT vs the unfused per-phase engine —
state-hash A/B over the three policy-family worlds (dense broker,
compacted LOCAL_FIRST, learned UCB) across ``run`` / ``run_jit`` /
``run_chunked`` (the same gate discipline telemetry used), plus
fleet-vs-vmap equality on the 8-virtual-device mesh with the fused path
engaged.  The static applicability gate itself is pinned so a spec
change cannot silently widen or narrow the fused family.
"""
import dataclasses
import hashlib

import jax
import numpy as np

from fognetsimpp_tpu import Policy, run
from fognetsimpp_tpu.core.engine import (
    _fused_ok,
    _fused_skip_compact,
    run_chunked,
    run_jit,
)
from fognetsimpp_tpu.scenarios import smoke

SMALL = dict(n_users=3, n_fogs=2, send_interval=0.01, horizon=0.4)

#: The three policy-family worlds of the telemetry gate (ISSUE 4):
#: dense-broker argmin family, sequential-pool LOCAL_FIRST, learned UCB.
WORLDS = [
    dict(policy=int(Policy.MIN_BUSY)),
    dict(policy=int(Policy.LOCAL_FIRST), broker_mips=2048.0),
    dict(policy=int(Policy.UCB)),
]


def _hash(state) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _build(**kw):
    args = dict(SMALL)
    args.update(kw)
    return smoke.build(**args)


def test_fused_gate_is_pinned():
    """The static applicability family: dense-broker policies over FIFO
    fogs with the two-stage front-end fuse; sequential-pool and learned
    policies keep the reference path."""
    on = _build(policy=int(Policy.MIN_BUSY))[0]
    assert on.fused_slots and _fused_ok(on)
    assert _fused_ok(_build(policy=int(Policy.MAX_MIPS))[0])
    assert not _fused_ok(
        _build(policy=int(Policy.LOCAL_FIRST), broker_mips=2048.0)[0]
    )
    assert not _fused_ok(_build(policy=int(Policy.UCB))[0])
    assert not _fused_ok(_build(policy=int(Policy.ROUND_ROBIN))[0])
    assert not _fused_ok(
        dataclasses.replace(on, fused_slots=False)
    )
    assert not _fused_ok(
        dataclasses.replace(on, two_stage_arrivals=False)
    )
    # the no-window tail engages exactly when the window cannot overflow
    assert _fused_skip_compact(on)  # smoke default: window == capacity
    assert not _fused_skip_compact(
        dataclasses.replace(on, arrival_window=8)
    )
    # exact-integer busy-MIPS bound (code-review r6): a spec whose
    # per-fog window MIPS sum could exceed 2^24 keeps the reference
    # path on every backend, windowed or not
    assert not _fused_ok(
        dataclasses.replace(on, mips_required_max=2 ** 24)
    )


def test_fused_bit_exact_across_run_entries():
    """State-hash A/B over the three policy-family worlds across
    run / run_jit / run_chunked: fused_slots on == off, bit for bit.
    (For the non-fusing families the gate keeps the reference path, so
    equality there pins that the flag stays inert for them.)"""
    for kw in WORLDS:
        ref_hashes = []
        for fused in (True, False):
            spec, state, net, bounds = _build(fused_slots=fused, **kw)
            h_run = _hash(run(spec, state, net, bounds)[0])
            spec, state, net, bounds = _build(fused_slots=fused, **kw)
            h_jit = _hash(run_jit(spec, state, net, bounds))
            spec, state, net, bounds = _build(fused_slots=fused, **kw)
            h_chunk = _hash(run_chunked(spec, state, net, bounds, 170))
            assert h_run == h_jit == h_chunk, (kw, fused)
            ref_hashes.append(h_run)
        assert ref_hashes[0] == ref_hashes[1], kw


def test_fused_bit_exact_under_windowed_compaction_and_saturation():
    """The fused path with the K-window retained (rotation active) and
    with saturated queues (fast-drop path exercised) — the two regimes
    beyond the plain no-window tick."""
    for kw in (
        dict(arrival_window=8),  # rotated compaction, sustained overflow
        dict(  # saturated fogs: candidate-list fast drop fires
            n_users=8, send_interval=0.004, dt=1e-3, horizon=0.5,
            n_fogs=3, fog_mips=(400.0, 800.0, 1200.0), queue_capacity=4,
        ),
        dict(derive_acks=False),  # ack columns written in-tick
        dict(telemetry=True),  # phase_work brackets ride the fused tick
        dict(  # coarse window: multi-send spawn + multi-candidate front
            dt=0.2, horizon=0.6, send_interval=0.05,
            max_sends_per_tick=8, n_users=6,
        ),
    ):
        args = dict(SMALL)
        args.update(kw)
        spec, state, net, bounds = smoke.build(**args)
        assert _fused_ok(spec)
        f_on, _ = run(spec, state, net, bounds)
        spec2, state2, net2, bounds2 = smoke.build(
            fused_slots=False, **args
        )
        f_off, _ = run(spec2, state2, net2, bounds2)
        assert _hash(f_on) == _hash(f_off), kw


def test_fused_fleet_matches_vmap_on_the_mesh():
    """Fleet-vs-vmap equality on the 8-virtual-device mesh with
    spec.fused_slots on (the ISSUE 5 acceptance bullet): the fused tick
    must vmap over the replica axis and shard without perturbing a
    bit."""
    from fognetsimpp_tpu.parallel import make_mesh, replicate_state
    from fognetsimpp_tpu.parallel.fleet import run_fleet
    from fognetsimpp_tpu.parallel.replicas import run_replicated

    spec, state, net, bounds = _build(
        policy=int(Policy.MIN_BUSY), horizon=0.2
    )
    assert _fused_ok(spec)
    batch = replicate_state(spec, state, 8, seed=5)
    ref = run_replicated(spec, batch, net, bounds)
    got = run_fleet(spec, batch, net, bounds, make_mesh(8), donate=False)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(ref)[0],
        jax.tree_util.tree_flatten_with_path(got)[0],
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=jax.tree_util.keystr(pa),
        )


def test_fused_composes_with_donation():
    """run_jit donates the carry; the fused tick's flush must not alias
    a donated buffer incorrectly (values already covered above — this
    pins that donation itself stays enabled and clean)."""
    spec, state, net, bounds = _build(policy=int(Policy.MIN_BUSY))
    ref, _ = run(spec, state, net, bounds)
    spec2, state2, net2, bounds2 = _build(policy=int(Policy.MIN_BUSY))
    got = run_jit(spec2, state2, net2, bounds2)
    assert _hash(ref) == _hash(got)
