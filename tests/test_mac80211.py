"""Load-dependent 802.11 DCF model (r4 VERDICT item 3; reworked r5 per
VERDICT r4 item 2).

`net.topology.bianchi_tables` solves the DCF fixed point for the
reference's MAC configuration (``wireless5.ini:56-68``: EDCA off,
cwMinData 31, retryLimit 7, 54/6 Mbps).  r4 keyed the table on
*associated* stations — 60 idle stations got full saturation delay; INET
contends only among stations with queued frames.  r5 keys it on each
cell's OFFERED LOAD via the Little's-law fixed point
``n_eff = clip(lambda * D(n_eff), 1, occupancy)`` (associate's
``offered_rate``): idle cells sit at the n=1 baseline, overloaded cells
climb to the saturation ceiling.
"""
import numpy as np

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.net.topology import (
    associate,
    bianchi_fixed_point,
    bianchi_tables,
)
from fognetsimpp_tpu.scenarios import wireless


def test_tables_monotone_and_anchored():
    d, l = bianchi_tables(200)
    assert np.all(np.diff(d[1:]) > 0)  # delay strictly rises with load
    assert np.all(np.diff(l[1:]) >= 0) and l[200] > l[2] > 0
    assert l[1] == 0.0  # a lone station cannot collide
    # saturation: the marginal cost per station GROWS (superlinear curve,
    # unlike the old constant-coefficient model)
    assert (d[100] - d[99]) > (d[3] - d[2])


def test_fixed_point_satisfies_bianchi_equations():
    """Quantitative anchor (VERDICT r4 item 2): the solved (tau, p)
    satisfies Bianchi's defining equations to 1e-6 — a check independent
    of the damped iteration that found the point — and matches the
    closed-form collision-free slot probability at n=1."""
    W, m = 32, 5
    for n in (2, 5, 10, 50, 200):
        tau, p = bianchi_fixed_point(n)
        assert abs(p - (1.0 - (1.0 - tau) ** (n - 1))) < 1e-9
        rhs = 2 * (1 - 2 * p) / (
            (1 - 2 * p) * (W + 1) + p * W * (1 - (2 * p) ** m)
        )
        assert abs(tau - rhs) < 1e-6, (n, tau, rhs)
    tau1, p1 = bianchi_fixed_point(1)
    assert p1 == 0.0 and abs(tau1 - 2.0 / (W + 1)) < 1e-9


def test_single_station_delay_from_first_principles():
    """The n=1 table entry, recomputed by hand with the reference MAC
    parameters: mean backoff (W-1)/2 = 15.5 empty slots of 9 us plus one
    idle-slot-weighted successful exchange, plus the data+SIFS+ACK+DIFS
    exchange itself.  Pins the table's absolute scale, not just shape."""
    d, _ = bianchi_tables(2)
    t_s = (  # DATA(preamble + 162 B @ 54 Mbps) + SIFS + ACK(preamble +
        #      14 B @ 6 Mbps) + DIFS   (bianchi_tables defaults)
        20e-6 + (34 + 128) * 8.0 / 54e6 + 10e-6 + 20e-6
        + 14 * 8.0 / 6e6 + 28e-6
    )
    tau = 2.0 / 33.0
    e_slot = (1 - tau) * 9e-6 + tau * t_s  # n=1: every tx succeeds
    want = 15.5 * e_slot + t_s
    np.testing.assert_allclose(d[1], want, rtol=1e-6)


def _world(n_users, interval):
    spec, state, net, bounds = wireless.wireless3(
        numb=2, numb_users=n_users, horizon=3.0, dt=1e-3,
        send_interval=interval,
    )
    return spec, state, net, bounds


def _mean_delay_and_loss(n_users, interval):
    """Two-AP chain world via the real engine."""
    spec, state, net, bounds = _world(n_users, interval)
    final, _ = run(spec, state, net, bounds)
    t0 = np.asarray(final.tasks.t_create)
    tb = np.asarray(final.tasks.t_at_broker)
    m = np.isfinite(t0) & np.isfinite(tb) & (tb <= float(final.t))
    stage = np.asarray(final.tasks.stage)
    sent = np.isfinite(t0)
    lost = (stage == int(Stage.LOST)).sum()
    return (tb[m] - t0[m]).mean(), lost / max(sent.sum(), 1), int(sent.sum())


def test_delay_rises_with_offered_load_not_occupancy():
    """End-to-end through associate(): the SAME 60 stations at light
    load (20 fps each, ~20% cell utilisation) transit near the baseline,
    and at heavy load (200 fps each, cells oversubscribed) the transit
    and loss climb — contention responds to traffic, not to how many
    stations merely sit associated."""
    d_lo, p_lo, n_lo = _mean_delay_and_loss(60, 0.05)
    d_hi, p_hi, n_hi = _mean_delay_and_loss(60, 0.005)
    assert n_lo > 600 and n_hi > 6000
    assert d_hi > d_lo * 1.5, (d_lo, d_hi)
    assert p_hi >= p_lo  # loss cannot fall as the cell saturates


def test_idle_cell_keys_at_single_station_baseline():
    """VERDICT r4 item 2's litmus: 60 associated stations of which ONE
    publishes — the active sender's access delay equals the genuinely
    single-station cell's, not the 60-station saturation value."""
    import jax.numpy as jnp

    spec, state, net, bounds = _world(60, 0.05)
    N = spec.n_nodes
    one_active = jnp.zeros((N,), jnp.float32).at[0].set(20.0)
    cache_idle = associate(
        net, state.nodes.pos, state.nodes.alive,
        broker=spec.broker_index, offered_rate=one_active,
    )
    spec1, state1, net1, _ = _world(1, 0.05)
    cache_single = associate(
        net1, state1.nodes.pos, state1.nodes.alive,
        broker=spec1.broker_index,
        offered_rate=jnp.zeros((spec1.n_nodes,), jnp.float32).at[0].set(20.0),
    )
    # same AP layout; user 0's access delay identical in both worlds
    np.testing.assert_allclose(
        float(cache_idle.acc_delay[0]), float(cache_single.acc_delay[0]),
        rtol=1e-6,
    )
    # and equal to the n=1 table anchor through the calibrated scale
    occup = associate(  # legacy keying for contrast: would pay n~30
        net, state.nodes.pos, state.nodes.alive, broker=spec.broker_index
    )
    assert float(cache_idle.acc_delay[0]) < float(occup.acc_delay[0])


def test_single_station_matches_legacy_anchor():
    """n=1 is numerically anchored to the calibrated w_contention, so the
    committed-trace demo calibration is unchanged by the model swap."""
    spec, state, net, bounds = wireless.wireless3(
        numb=2, numb_users=1, horizon=0.2, dt=1e-3, send_interval=0.05,
    )
    cache = associate(
        net, state.nodes.pos, state.nodes.alive, broker=spec.broker_index
    )
    import jax.numpy as jnp

    legacy = net.replace(
        mac_delay_tab=jnp.zeros((0,)), mac_loss_tab=jnp.zeros((0,))
    )
    cache_l = associate(
        legacy, state.nodes.pos, state.nodes.alive,
        broker=spec.broker_index,
    )
    np.testing.assert_allclose(
        np.asarray(cache.acc_delay)[:1], np.asarray(cache_l.acc_delay)[:1],
        rtol=1e-6,
    )
    assert float(cache.mac_loss_p[0]) == 0.0
