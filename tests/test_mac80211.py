"""Load-dependent 802.11 DCF model (r4, VERDICT item 3).

The r3 model was a constant per-station delay coefficient and a FIXED
Bernoulli uplink loss — delay did not saturate and loss did not respond
to load.  Now `net.topology.bianchi_tables` solves the DCF fixed point
for the reference's MAC configuration (``wireless5.ini:56-68``: EDCA off,
cwMinData 31, retryLimit 7, 54/6 Mbps) and `associate` maps per-AP
occupancy through it: delay follows the saturation curve (anchored at
n=1 to the calibrated scale) and loss is the retry-exhaustion
probability of the same fixed point.
"""
import numpy as np

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.net.topology import associate, bianchi_tables
from fognetsimpp_tpu.scenarios import wireless


def test_tables_monotone_and_anchored():
    d, l = bianchi_tables(200)
    assert np.all(np.diff(d[1:]) > 0)  # delay strictly rises with load
    assert np.all(np.diff(l[1:]) >= 0) and l[200] > l[2] > 0
    assert l[1] == 0.0  # a lone station cannot collide
    # saturation: the marginal cost per station GROWS (superlinear curve,
    # unlike the old constant-coefficient model)
    assert (d[100] - d[99]) > (d[3] - d[2])


def _mean_delay_and_loss(n_users):
    """Two-AP chain world at two occupancies via the real engine."""
    spec, state, net, bounds = wireless.wireless3(
        numb=2, numb_users=n_users, horizon=3.0, dt=1e-3,
        send_interval=0.05,
    )
    final, _ = run(spec, state, net, bounds)
    t0 = np.asarray(final.tasks.t_create)
    tb = np.asarray(final.tasks.t_at_broker)
    m = np.isfinite(t0) & np.isfinite(tb)
    stage = np.asarray(final.tasks.stage)
    sent = np.isfinite(t0)
    lost = (stage == int(Stage.LOST)).sum()
    return (tb[m] - t0[m]).mean(), lost / max(sent.sum(), 1), int(sent.sum())


def test_delay_and_loss_rise_with_occupancy():
    """End-to-end through associate(): the same scenario at 2 vs 60
    stations shows higher uplink transit AND a nonzero loss rate —
    qualitatively what INET's contention produces as a cell fills."""
    d_lo, p_lo, n_lo = _mean_delay_and_loss(2)
    d_hi, p_hi, n_hi = _mean_delay_and_loss(60)
    assert n_lo > 20 and n_hi > 600
    assert d_hi > d_lo * 1.5, (d_lo, d_hi)
    assert p_hi >= p_lo  # loss cannot fall as the cell saturates


def test_single_station_matches_legacy_anchor():
    """n=1 is numerically anchored to the calibrated w_contention, so the
    committed-trace demo calibration is unchanged by the model swap."""
    spec, state, net, bounds = wireless.wireless3(
        numb=2, numb_users=1, horizon=0.2, dt=1e-3, send_interval=0.05,
    )
    cache = associate(
        net, state.nodes.pos, state.nodes.alive, broker=spec.broker_index
    )
    import jax.numpy as jnp

    legacy = net.replace(
        mac_delay_tab=jnp.zeros((0,)), mac_loss_tab=jnp.zeros((0,))
    )
    cache_l = associate(
        legacy, state.nodes.pos, state.nodes.alive,
        broker=spec.broker_index,
    )
    np.testing.assert_allclose(
        np.asarray(cache.acc_delay)[:1], np.asarray(cache_l.acc_delay)[:1],
        rtol=1e-6,
    )
    assert float(cache.mac_loss_p[0]) == 0.0
