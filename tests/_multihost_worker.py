"""Worker process for tests/test_multihost.py.

Run as: python tests/_multihost_worker.py <process_id> <port>

Joins a 2-process jax.distributed cluster over localhost (2 virtual CPU
devices per process -> a 4-device global mesh), runs a replica-sharded
world across BOTH processes, and asserts its addressable shards match the
locally-computed unsharded reference bit-for-bit.
"""
import os
import sys

pid, port = int(sys.argv[1]), sys.argv[2]
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# distributed init MUST precede anything that touches the XLA backend —
# importing the framework creates module-level jnp constants, so it comes
# after
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)

import numpy as np  # noqa: E402

from fognetsimpp_tpu.parallel import multihost  # noqa: E402
from fognetsimpp_tpu.parallel.mesh import run_sharded  # noqa: E402
from fognetsimpp_tpu.parallel.replicas import (  # noqa: E402
    replicate_state,
    run_replicated,
)
from fognetsimpp_tpu.scenarios import smoke  # noqa: E402

n = jax.process_count()
assert n == 2, f"expected 2 processes, got {n}"
assert len(jax.local_devices()) == 2, jax.local_devices()
assert jax.device_count() == 4, jax.devices()

mesh = multihost.global_mesh()
assert mesh.devices.size == 4  # spans both processes

R = 4
spec, state, net, bounds = smoke.build(
    horizon=0.1, n_users=2, n_fogs=2, send_interval=0.01
)
batch = replicate_state(spec, state, R, seed=0)

# the distributed run: replica axis sharded over the 2-process mesh
final = run_sharded(spec, batch, net, bounds, mesh)
# the local reference: same batch, plain single-process vmap
ref = run_replicated(spec, batch, net, bounds)

checked = 0
for name, arr in [
    ("n_scheduled", final.metrics.n_scheduled),
    ("n_completed", final.metrics.n_completed),
    ("t_ack6", final.tasks.t_ack6),
    ("stage", final.tasks.stage),
]:
    ref_arr = np.asarray(
        {
            "n_scheduled": ref.metrics.n_scheduled,
            "n_completed": ref.metrics.n_completed,
            "t_ack6": ref.tasks.t_ack6,
            "stage": ref.tasks.stage,
        }[name]
    )
    for shard in arr.addressable_shards:
        got = np.asarray(shard.data)
        want = ref_arr[shard.index]
        np.testing.assert_array_equal(got, want, err_msg=name)
        checked += 1
assert checked >= 8, checked  # 2 local shards x 4 arrays

print(f"MULTIHOST-OK pid={pid} procs={n} devices={jax.device_count()}")
