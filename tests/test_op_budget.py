"""The HLO op-budget gate (ISSUE 5): kernel-count regressions fail CI.

tools/op_budget.py compiles the dt=1 ms tick at one pinned CPU shape,
counts the optimized ENTRY computation's instructions and fusions, and
gates them against the checked-in tools/op_budget.json — the same
fail-fast discipline as simlint.  Here: the budget file exists and is
self-consistent, the live counts sit within it, the fused front-end
keeps its >= 30% kernel-count reduction, and the file is regenerable
via --write.
"""
import json
import os

import pytest

from tools import op_budget

pytestmark = pytest.mark.filterwarnings("ignore")


@pytest.fixture(scope="module")
def measured():
    # tp=False / hier=False / journeys=False: the TP sharded-tick,
    # federated-tick and journey-tap compiles are covered by
    # test_tp.py / test_hier.py / test_journeys.py's own programs in
    # this tier; all three budget gates still run in CI via the
    # op_budget CLI (--check), which measures everything
    return op_budget.measure(tp=False, hier=False, journeys=False)


def test_budget_file_present_and_consistent():
    assert os.path.exists(op_budget.BUDGET_PATH), (
        "tools/op_budget.json missing — regenerate with "
        "`python tools/op_budget.py --write` and commit it"
    )
    with open(op_budget.BUDGET_PATH) as f:
        budget = json.load(f)
    for key in ("shape", "fused", "unfused", "max_ops", "max_fusions",
                "max_fused_ratio"):
        assert key in budget, key
    # the budget was measured at the tool's own pinned shape
    assert budget["shape"] == {
        k: (list(v) if isinstance(v, tuple) else v)
        for k, v in op_budget.PINNED.items()
    }
    # slack caps genuinely cap the recorded counts
    assert budget["fused"]["ops"] <= budget["max_ops"]
    assert budget["fused"]["fusions"] <= budget["max_fusions"]
    # the TP sharded tick's budget (ISSUE 9): present, self-consistent,
    # and the per-tick collective count pins the itemized kinds exactly
    tp = budget["tp_tick"]
    assert tp["ops"] <= tp["max_ops"]
    assert tp["collective_count"] == sum(tp["collectives"].values())
    assert set(tp["collectives"]) == {"all-reduce", "collective-permute"}


def test_live_counts_within_budget(measured):
    with open(op_budget.BUDGET_PATH) as f:
        budget = json.load(f)
    errs = op_budget.check(measured, budget)
    assert not errs, "\n".join(errs)


def test_fused_reduction_meets_the_30_percent_bar(measured):
    """The ISSUE 5 acceptance number: >= 30% fewer HLO ops in the
    compiled dt=1 ms tick with the fused front-end on."""
    ratio = measured["fused"]["ops"] / measured["unfused"]["ops"]
    assert ratio <= op_budget.MAX_FUSED_RATIO, measured


def test_dyn_promotion_costs_no_kernels(measured):
    """ISSUE 13: the promoted tick (tick_dyn — shape key static, knobs
    as DynSpec operands) must stay within the constant-folded twin's
    op budget: losing a constant-fold to an operand would show up here
    as op growth vs tick_chaos."""
    assert "tick_dyn" in measured and "tick_chaos" in measured
    dyn, chaos = measured["tick_dyn"], measured["tick_chaos"]
    assert dyn["ops"] <= chaos["max_ops"], (dyn, chaos)


def test_budget_regenerable_via_write(tmp_path, measured, capsys):
    out = tmp_path / "budget.json"
    rc = op_budget.main(["--write", "--budget", str(out)])
    capsys.readouterr()
    assert rc == 0
    regen = json.loads(out.read_text())
    # same jax/process -> identical counts as the module fixture
    assert regen["fused"] == measured["fused"]
    assert regen["unfused"] == measured["unfused"]
    # and --check against the fresh file passes
    rc = op_budget.main(["--check", "--budget", str(out)])
    capsys.readouterr()
    assert rc == 0


def test_entry_op_counter_parses_hlo():
    txt = """
HloModule m
ENTRY %main (p: f32[4]) -> f32[4] {
  %p = f32[4]{0} parameter(0)
  %c = f32[] constant(1)
  %b = f32[4]{0} broadcast(f32[] %c), dimensions={}
  %f = f32[4]{0} fusion(f32[4]{0} %p), kind=kLoop, calls=%fused
  ROOT %a = f32[4]{0} add(f32[4]{0} %f, f32[4]{0} %b)
}
"""
    got = op_budget.entry_op_counts(txt)
    assert got == {"ops": 3, "fusions": 1}  # broadcast + fusion + add
