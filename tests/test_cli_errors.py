"""CLI error-path regressions: an .ini referencing an unknown scenario/
network name — or a ``--policy``/``--sweep`` naming an unknown policy —
must produce a one-line actionable error, not a traceback.

Composition rejections assert the bracketed clause ID ([TP-CHAOS],
[CLI-SWEEP-*], ...) rather than the prose: the ID is the stable
machine-parseable contract (tools/featmat extracts the composition
matrix from it), the wording may change freely."""
import json

import pytest

from fognetsimpp_tpu.__main__ import main


def test_unknown_scenario_flag_is_clear_error(capsys):
    rc = main(["--scenario", "wirelessnet-42"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "unknown scenario" in captured.err
    assert "Traceback" not in captured.err
    # the known names are listed so the fix is obvious
    assert "wireless5" in captured.err and "smoke" in captured.err


def test_unknown_network_in_ini_is_clear_error(tmp_path, capsys):
    ini = tmp_path / "run.ini"
    ini.write_text("[General]\nscenario = NoSuchNetwork\n")
    rc = main(["--config", str(ini)])
    captured = capsys.readouterr()
    assert rc == 2
    assert "NoSuchNetwork" in captured.err
    assert "Traceback" not in captured.err


def test_unknown_policy_name_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--policy", "warp_speed"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "unknown policy" in captured.err
    assert "Traceback" not in captured.err
    # the valid names are listed so the fix is obvious
    assert "ucb" in captured.err and "min_busy" in captured.err


def test_sweep_unknown_policy_name_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--sweep", "policies=min_busy,warp"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "unknown policy 'warp'" in captured.err
    assert "Traceback" not in captured.err


def test_sweep_explores_without_learned_policy_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--sweep", "explores=0.1,0.5"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "explores=" in captured.err
    assert "Traceback" not in captured.err


def test_sweep_policy_without_explores_is_clear_error(capsys):
    """policy= (singular) selects the exploration sweep; without
    explores= it must error, not silently run the default policy grid."""
    rc = main(["--scenario", "smoke", "--sweep", "policy=ducb loads=0.05"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "explores=" in captured.err and "policies=" in captured.err
    assert "Traceback" not in captured.err


def test_policy_flag_conflicts_with_sweep(capsys):
    rc = main(["--scenario", "smoke", "--policy", "ucb",
               "--sweep", "policies=min_busy loads=0.05"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "[CLI-SWEEP-POLICY]" in captured.err
    assert "Traceback" not in captured.err


def test_replicas_conflicts_with_sweep(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--replicas", "8",
              "--sweep", "policies=min_busy loads=0.05"])
    assert e.value.code == 2
    assert "[CLI-SWEEP-FLEET]" in capsys.readouterr().err


def test_fleet_replicas_not_dividing_mesh_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--set", "scenario.horizon=0.1",
               "--replicas", "3"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "divide" in captured.err
    assert "Traceback" not in captured.err


def test_fleet_mesh_larger_than_devices_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--set", "scenario.horizon=0.1",
               "--mesh", "4096"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "4096" in captured.err
    assert "Traceback" not in captured.err


def test_sweep_accepts_policy_names(capsys):
    """'policies=' tokens resolve by enum name as well as by id."""
    rc = main([
        "--scenario", "smoke",
        "--set", "scenario.horizon=0.2",
        "--sweep", "policies=min_busy,random loads=0.05",
    ])
    captured = capsys.readouterr()
    assert rc == 0
    assert '"policy": 0' in captured.out
    assert '"policy": 4' in captured.out


def test_tp_conflicts_with_replicas(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--tp", "8", "--replicas", "8"])
    assert e.value.code == 2
    err = capsys.readouterr().err
    assert "[CLI-TP-FLEET]" in err


def test_tp_outside_policy_family_is_clear_error(capsys):
    """--tp composes with --policy; a policy outside the dense-broker
    TP family is a one-line error, not a traceback."""
    rc = main(["--scenario", "smoke", "--tp", "8", "--policy", "ucb",
               "--set", "scenario.horizon=0.05"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "[TP-POLICY]" in captured.err and "dense-broker" in captured.err
    assert "Traceback" not in captured.err


# note: --tp --serve and --tp --hist COMPOSE since ISSUE 11 (the
# sharded health plane); their success paths are gated in
# tests/test_tp_telemetry.py.


def test_tp_window_requires_tp(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--tp-window", "4"])
    assert e.value.code == 2
    assert "[CLI-TPWINDOW]" in capsys.readouterr().err


def test_tp_runs_windowed_specs(capsys):
    """--tp × a WINDOWED spec is a SUCCESS path since ISSUE 18: the
    distributed K-window selection runs the arrival window over the
    hop-pruned exchange ring (the former [TP-WINDOW] rejection is
    gone)."""
    rc = main(["--scenario", "smoke", "--tp", "8",
               "--set", "scenario.arrival_window=4",
               "--set", "scenario.horizon=0.05"])
    captured = capsys.readouterr()
    assert rc == 0
    assert '"tp_shards": 8' in captured.out
    assert "Traceback" not in captured.err


def test_tp_window_flag_conflicts_with_windowed_spec(capsys):
    """--tp-window tunes the NO-WINDOW exchange ring; on a spec that
    already carries its own arrival window the combination is a
    one-line error, not a traceback."""
    rc = main(["--scenario", "smoke", "--tp", "8", "--tp-window", "2",
               "--set", "scenario.arrival_window=4",
               "--set", "scenario.horizon=0.05"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "exchange_window" in captured.err
    assert "Traceback" not in captured.err


# ---- chaos CLI surface (ISSUE 12) ------------------------------------

def test_unknown_chaos_profile_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--chaos", "mayhem"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "unknown chaos profile" in captured.err
    assert "Traceback" not in captured.err
    # the catalogue is listed so the fix is obvious
    assert "hostile" in captured.err and "flaky" in captured.err


def test_chaos_seed_requires_chaos(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--chaos-seed", "3"])
    assert e.value.code == 2
    assert "[CLI-CHAOS-KNOBS]" in capsys.readouterr().err


def test_chaos_script_requires_chaos(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--chaos-script", "/tmp/x.json"])
    assert e.value.code == 2
    assert "[CLI-CHAOS-KNOBS]" in capsys.readouterr().err


def test_unknown_chaos_mode_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--chaos", "light",
               "--chaos-mode", "explode"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "unknown chaos mode" in captured.err
    assert "lose" in captured.err and "reoffload" in captured.err
    assert "Traceback" not in captured.err


def test_malformed_chaos_script_file_is_clear_error(tmp_path, capsys):
    bad = tmp_path / "script.json"
    bad.write_text('[[0, 0.5]]')  # a pair, not a triple
    rc = main(["--scenario", "smoke", "--chaos", "scripted",
               "--chaos-script", str(bad)])
    captured = capsys.readouterr()
    assert rc == 2
    assert "t_down" in captured.err
    assert "Traceback" not in captured.err


def test_chaos_script_fog_out_of_range_is_clear_error(tmp_path, capsys):
    bad = tmp_path / "script.json"
    bad.write_text('[[99, 0.1, 0.2]]')
    rc = main(["--scenario", "smoke", "--chaos", "scripted",
               "--chaos-script", str(bad)])
    captured = capsys.readouterr()
    assert rc == 2
    assert "out of range" in captured.err
    assert "Traceback" not in captured.err


def test_chaos_conflicts_with_sweep(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--chaos", "light",
              "--sweep", "policies=min_busy loads=0.05"])
    assert e.value.code == 2
    err = capsys.readouterr().err
    assert "[CLI-SWEEP-CHAOS]" in err


def test_chaos_with_tp_is_clear_error(capsys):
    """--tp rejects chaos worlds with the tp_reject_reason one-liner,
    never a traceback."""
    rc = main(["--scenario", "smoke", "--tp", "8", "--chaos", "light",
               "--set", "scenario.horizon=0.05"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "[TP-CHAOS]" in captured.err
    assert "Traceback" not in captured.err


def test_chaos_with_replicas_now_runs(capsys):
    """The fleet-chaos follow-up landed: --chaos composes with
    --replicas (per-replica fold_in(chaos_key, r) schedules), so the
    old one-line rejection is gone and the fleet reports normally."""
    import json

    rc = main(["--scenario", "smoke", "--chaos", "light",
               "--set", "scenario.horizon=0.04",
               "--set", "scenario.send_interval=0.01", "--replicas", "8"])
    captured = capsys.readouterr()
    assert rc == 0
    out = json.loads(captured.out.strip().splitlines()[-1])
    assert out["n_replicas"] == 8
    assert out["n_published_sum"] > 0


def test_brokers_below_one_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--brokers", "0"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "--brokers must be >= 1" in captured.err
    assert "Traceback" not in captured.err


def test_brokers_above_fog_count_is_clear_error(capsys):
    """smoke has 2 fogs: --brokers 5 must fail at validate() with the
    actionable reduce-or-add-fogs line, never a traceback."""
    rc = main(["--scenario", "smoke", "--brokers", "5"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "exceeds n_fogs" in captured.err
    assert "Traceback" not in captured.err


def test_unknown_hier_policy_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--brokers", "2",
               "--hier-policy", "warp_speed"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "unknown hier policy" in captured.err
    # the valid names are listed so the fix is obvious
    assert "least_loaded" in captured.err
    assert "Traceback" not in captured.err


def test_hier_policy_requires_brokers(capsys):
    rc = main(["--scenario", "smoke", "--hier-policy", "threshold"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "[CLI-HIERPOLICY]" in captured.err
    assert "Traceback" not in captured.err


def test_brokers_with_tp_is_clear_error(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--brokers", "2", "--tp", "8"])
    assert e.value.code == 2
    err = capsys.readouterr().err
    assert "[TP-HIER]" in err


def test_brokers_with_replicas_is_clear_error(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--brokers", "2",
              "--replicas", "8"])
    assert e.value.code == 2
    err = capsys.readouterr().err
    assert "[FLEET-HIER]" in err


def test_hier_unsupported_policy_is_clear_error(capsys):
    """ROUND_ROBIN does not federate: validate() rejects with the
    supported-family line."""
    rc = main(["--scenario", "smoke", "--brokers", "2",
               "--set", "scenario.n_fogs=4", "--policy", "round_robin"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "[SPEC-HIER-POLICY]" in captured.err
    assert "does not federate" in captured.err
    assert "Traceback" not in captured.err


def test_set_prints_recompile_classification(capsys):
    """--set on a spec field prints a one-line recompile: yes|no
    classification (ISSUE 13): dynamic-operand knobs re-use compiled
    programs, shape-defining fields pay a fresh compile."""
    rc = main([
        "--scenario", "smoke",
        "--set", "scenario.n_users=4",
        "--set", "scenario.horizon=0.002",
        "--set", "spec.chaos_rtt_amp=0.0",
        "--set", "spec.horizon=0.002",
    ])
    captured = capsys.readouterr()
    assert rc == 0
    lines = [
        ln for ln in captured.err.splitlines()
        if ln.startswith("recompile:")
    ]
    assert len(lines) == 2  # spec.* keys only; scenario.* stays silent
    assert lines[0].startswith("recompile: no (spec.chaos_rtt_amp:")
    assert "dynamic operand" in lines[0]
    assert lines[1].startswith("recompile: yes (spec.horizon:")
    assert "shape-defining" in lines[1]


def test_set_under_tp_prints_recompile_no(capsys):
    """Promoted knobs keep their 'recompile: no' classification under
    --tp (ISSUE 20): the sharded runner reads them from the DynSpec
    operand, so a --set retune reuses the compiled TP program."""
    rc = main([
        "--scenario", "smoke",
        "--set", "scenario.n_users=16",
        "--set", "scenario.horizon=0.002",
        "--set", "spec.send_stop_time=0.001",
        "--tp", "8",
    ])
    captured = capsys.readouterr()
    assert rc == 0
    lines = [
        ln for ln in captured.err.splitlines()
        if ln.startswith("recompile:")
    ]
    assert len(lines) == 1
    assert lines[0].startswith("recompile: no (spec.send_stop_time:")
    assert "dynamic operand" in lines[0]
    out = json.loads(captured.out.splitlines()[-1])
    assert out["tp_shards"] == 8


def test_set_unknown_spec_field_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--set", "spec.bogus_knob=1"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error: unknown WorldSpec field 'bogus_knob'" in captured.err
    assert "Traceback" not in captured.err
    # classification fails BEFORE any world is built: no recompile line
    assert "recompile:" not in captured.err


# ---------------------------------------------------------------------
# journey guard rails (ISSUE 15)
# ---------------------------------------------------------------------

def test_journeys_below_one_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--telemetry", "--journeys", "0"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "--journeys" in captured.err and ">= 1" in captured.err
    assert "Traceback" not in captured.err


def test_journeys_without_telemetry_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--journeys", "4"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "[SPEC-JOURNEYS-TELEM]" in captured.err
    assert "Traceback" not in captured.err


def test_journeys_above_task_capacity_is_clear_error(capsys):
    rc = main(["--scenario", "smoke", "--telemetry",
               "--journeys", "999999999"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "task capacity" in captured.err
    assert "Traceback" not in captured.err


def test_journeys_compose_with_tp(capsys):
    """--journeys × --tp is a SUCCESS path since ISSUE 19: the journey
    rings shard with the task axis and the decoded chains bit-match the
    single-device tap (the former [TP-JOURNEYS] rejection is gone)."""
    rc = main(["--scenario", "smoke", "--telemetry", "--journeys", "4",
               "--tp", "8", "--set", "scenario.horizon=0.05"])
    captured = capsys.readouterr()
    assert rc == 0
    assert '"tp_shards": 8' in captured.out
    assert "Traceback" not in captured.err


# ---- digital-twin guard rails (twin/, ISSUE 17) -----------------------
# Every [TWIN-*]/[CLI-*TWIN*] rejection clause of the feature matrix is
# asserted here by its literal ID (featmat consistency gate 3).


def test_ingest_with_tp_is_clear_error(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--ingest", "8", "--tp", "8"])
    assert e.value.code == 2
    assert "[TWIN-INGEST-TP]" in capsys.readouterr().err


def test_ingest_with_replicas_is_clear_error(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--ingest", "8", "--replicas", "8"])
    assert e.value.code == 2
    assert "[TWIN-INGEST-FLEET]" in capsys.readouterr().err


def test_ingest_requires_serve(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--ingest", "8"])
    assert e.value.code == 2
    assert "[TWIN-INGEST-SERVE]" in capsys.readouterr().err


def test_replay_arrivals_requires_serve(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--replay-arrivals", "log.json"])
    assert e.value.code == 2
    assert "[TWIN-INGEST-SERVE]" in capsys.readouterr().err


def test_ingest_capacity_below_one_is_clear_error(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--serve", "0", "--ingest", "0"])
    assert e.value.code == 2
    assert "capacity must be >= 1" in capsys.readouterr().err


@pytest.mark.slow  # compiles a (tiny) TP program + the what-if grid:
#   the [TWIN-WHATIF-TP] wall was deleted by ISSUE 20 — the positive
#   path is gated here, the bit-exactness contract in
#   tests/test_sharded_dynspec.py
def test_whatif_with_tp_runs(capsys):
    """--whatif now rides --tp: the chunk-boundary carry leaves the
    mesh through unstamp_tp_carry and answers the grid."""
    rc = main(["--scenario", "smoke",
               "--set", "scenario.n_users=16",
               "--set", "scenario.horizon=0.01",
               "--set", "spec.uplink_loss_prob=0.05",
               "--whatif", "uplink_loss_prob=0.1,0.2 ticks=5",
               "--tp", "8"])
    captured = capsys.readouterr()
    assert rc == 0
    assert "[TWIN-WHATIF-TP]" not in captured.err
    out = json.loads(captured.out.splitlines()[-1])
    assert out["whatif"]["n_cells"] == 2


def test_whatif_with_replicas_is_clear_error(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke",
              "--whatif", "uplink_loss_prob=0.1", "--replicas", "8"])
    assert e.value.code == 2
    assert "[TWIN-WHATIF-FLEET]" in capsys.readouterr().err


def test_whatif_on_static_spec_path_is_clear_error(monkeypatch, capsys):
    monkeypatch.setenv("FNS_SPEC_PROMOTE", "0")
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--whatif", "uplink_loss_prob=0.1"])
    assert e.value.code == 2
    assert "[TWIN-WHATIF-STATIC]" in capsys.readouterr().err


def test_whatif_conflicts_with_sweep(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--whatif", "uplink_loss_prob=0.1",
              "--sweep", "policies=min_busy loads=0.05"])
    assert e.value.code == 2
    assert "[CLI-SWEEP-TWIN]" in capsys.readouterr().err


def test_tenants_with_tp_is_clear_error(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--tenants", "2", "--tp", "8"])
    assert e.value.code == 2
    assert "[TWIN-FRONT-TP]" in capsys.readouterr().err


def test_tenants_with_replicas_is_clear_error(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--tenants", "2", "--replicas", "8"])
    assert e.value.code == 2
    assert "[TWIN-FRONT-FLEET]" in capsys.readouterr().err


def test_tenants_requires_serve(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--tenants", "2"])
    assert e.value.code == 2
    assert "[TWIN-FRONT-SERVE]" in capsys.readouterr().err


def test_tenants_below_one_is_clear_error(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--tenants", "0", "--serve", "0"])
    assert e.value.code == 2
    assert "--tenants must be >= 1" in capsys.readouterr().err


def test_tenants_conflicts_with_whatif_flag(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--tenants", "2", "--serve", "0",
              "--whatif", "uplink_loss_prob=0.1"])
    assert e.value.code == 2
    assert "[CLI-TENANTS-WHATIF]" in capsys.readouterr().err


def test_tenants_conflicts_with_replay(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--tenants", "2", "--serve", "0",
              "--replay-arrivals", "log.json"])
    assert e.value.code == 2
    assert "[CLI-TENANTS-REPLAY]" in capsys.readouterr().err


def test_tenant_cap_requires_tenants(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--scenario", "smoke", "--tenant-cap", "2"])
    assert e.value.code == 2
    assert "[CLI-TENANTCAP]" in capsys.readouterr().err


def test_malformed_ingest_payload_is_one_line_400():
    """Malformed POST /ingest bodies get the [TWIN-PAYLOAD] one-liner,
    never a traceback (the queue parses before touching the device)."""
    from fognetsimpp_tpu.twin.ingest import IngestQueue

    q = IngestQueue(capacity=4)
    for body in (b"not json", b'{"user": -1, "mips": 5.0}',
                 b'{"rows": [[0, "fast"]]}', b'{"mips": 5.0}',
                 b'{"user": true, "mips": 1.0}'):
        status, doc = q.ingest_payload(body)
        assert status == 400
        assert "[TWIN-PAYLOAD]" in doc["error"]
    assert q.depth == 0  # nothing malformed was queued


def test_malformed_whatif_payload_is_one_line_400():
    """Malformed POST /whatif bodies get the [TWIN-WHATIF-PAYLOAD]
    one-liner before any device work (no carry needed to reject)."""
    from fognetsimpp_tpu.twin.whatif import WhatIfDoor

    door = WhatIfDoor(None, None, None)
    for body in (b"not json", b"[]", b'{"knobs": []}',
                 b'{"knobs": {"x": []}}',
                 b'{"knobs": {"x": [1, "a"]}}',
                 b'{"knobs": {"x": [1]}, "ticks": "soon"}'):
        status, doc = door._post(body)
        assert status == 400
        assert "[TWIN-WHATIF-PAYLOAD]" in doc["error"]
