"""CLI error-path regressions: an .ini referencing an unknown scenario/
network name must produce a one-line actionable error, not a traceback."""
from fognetsimpp_tpu.__main__ import main


def test_unknown_scenario_flag_is_clear_error(capsys):
    rc = main(["--scenario", "wirelessnet-42"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "error:" in captured.err
    assert "unknown scenario" in captured.err
    assert "Traceback" not in captured.err
    # the known names are listed so the fix is obvious
    assert "wireless5" in captured.err and "smoke" in captured.err


def test_unknown_network_in_ini_is_clear_error(tmp_path, capsys):
    ini = tmp_path / "run.ini"
    ini.write_text("[General]\nscenario = NoSuchNetwork\n")
    rc = main(["--config", str(ini)])
    captured = capsys.readouterr()
    assert rc == 2
    assert "NoSuchNetwork" in captured.err
    assert "Traceback" not in captured.err
