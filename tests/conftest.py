"""Test harness config: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on
``xla_force_host_platform_device_count=8`` virtual CPU devices, the pattern
the driver's ``dryrun_multichip`` also uses.

The environment may pre-import jax with the platform pinned to the tunneled
TPU (axon sitecustomize), which makes ``JAX_PLATFORMS`` env assignments
moot — so we set the XLA flag (read at first backend init, which has not
happened yet at conftest time) and override the platform via
``jax.config.update``.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The repo's own compile_cache tier stays off in-process (its enable
# path re-routes cache config mid-run; the sweep-CLI test invokes
# __main__ in-process, which would otherwise re-enable it under test
# feet)...
os.environ["FNS_JIT_CACHE"] = "off"

# ...but jax's persistent compilation cache itself is ON, into a
# repo-local gitignored dir: the tier-1 suite is compile-dominated
# (~900 s cold, the 870 s CI budget's whole problem), and a warm cache
# roughly halves the compile-heavy modules.  Keyed on HLO hash +
# compile options + jaxlib version, so a code change can never serve a
# stale executable.  HISTORY: an r4-era note here kept the cache off
# because serializing one CPU executable segfaulted in jaxlib's
# put_executable_and_time; the r6 fused front-end replaced that
# program generation, and the full suite has been re-validated clean
# with the cache on (r13).  FNS_TEST_JIT_CACHE=off restores the old
# behaviour if a future program regresses.
if os.environ.get("FNS_TEST_JIT_CACHE", "") != "off":
    _cache_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_test_cache",
    )
    try:
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
    except Exception:
        pass  # unwritable checkout: cold compiles, same as before

import pytest  # noqa: E402

# Fast developer-loop tier (VERDICT r3 weak item 7: the full suite is a
# ~10-minute CI run; `pytest -m quick` is the <60 s edit loop).  Files
# here compile only small/short worlds; everything else is marked slow.
_QUICK_FILES = {
    "test_sched.py",
    "test_queues.py",
    "test_engine_smoke.py",
    "test_compaction.py",
    "test_pallas.py",
    # simlint static pass + trace-time contracts (PR 1): pure AST walks
    # and eval_shape traces — seconds, and exactly the checks that should
    # gate every edit loop
    "test_simlint.py",
    "test_simlint_rules.py",
    "test_contracts.py",
    "test_donation.py",
    "test_cli_errors.py",
    # digital twin (ISSUE 17): ingestion determinism/replay, what-if
    # fork bit-exactness + zero-warm-compile, front-door shared-program
    # gates — small worlds, the twin's acceptance rails stay in tier-1
    "test_twin.py",
    # learn/ bandit schedulers (ISSUE 2): unit + regret-harness gates on
    # small worlds — the in-loop-learning capability must stay inside the
    # edit loop, not drift behind the slow tier
    "test_learn.py",
    # fleet runner (ISSUE 3): the 8-virtual-device replica-sharded fleet
    # vs vmap equivalence gate — the multi-chip headline's correctness
    # contract belongs in tier-1, exactly like the donation gates above
    "test_fleet.py",
    # telemetry/ (ISSUE 4): the inert-TelemetryState bit-exactness gate,
    # the Perfetto golden and the OpenMetrics/.sca.json agreement — all
    # small worlds, and exactly the checks an engine edit must not break
    "test_telemetry.py",
    # live health plane (ISSUE 6): the inert-histogram bit-exactness
    # gate, watchdog/flight-recorder/live-endpoint units and the
    # bench-trend CI gate — small worlds + pure host logic
    "test_health.py",
    # fused slot-window front-end (ISSUE 5): the fused-vs-unfused
    # state-hash A/B over the policy-family worlds + the HLO op-budget
    # gate — the kernel-count win's correctness and its CI lock
    "test_fused.py",
    "test_op_budget.py",
    # compiled-artifact auditor (ISSUE 7): canned-HLO rule units are
    # milliseconds; the live tier compiles one tick + the TP dryrun —
    # the same correctness rail the TP-sharding promotion runs on
    "test_hloaudit.py",
    # TP sharded tick (ISSUE 9): the shard_map'd million-user capacity
    # path's state-hash A/B vs the single-device reference on the
    # 8-virtual-device mesh + the ring-exchange units — the same
    # tier-1 contract as the fleet runner's equivalence gate
    "test_tp.py",
    # chaos fault injection (ISSUE 12): the inert-ChaosState
    # bit-exactness gate, cross-entry-point schedule determinism,
    # RE-OFFLOAD conservation, the exactly-once learn-credit property
    # and the churn world where the bandits beat every static policy —
    # the hostile-world capability belongs in the edit loop like learn/
    "test_chaos.py",
    # federated multi-broker hierarchy (ISSUE 14): the single-broker /
    # inert-B>1 bit-exactness gates, the forced-migration conservation
    # grid and the per-broker bandit-credit invariant — small worlds;
    # the cross-entry A/Bs, acceptance-world comparisons and CLI smoke
    # carry their own slow marks (the test_tp.py tier discipline)
    "test_hier.py",
    # distributed observability (ISSUE 11): per-shard phase-work /
    # exchange-gauge / hist A/Bs vs the single-device profile, the
    # serve --tp defer-rate watchdog + postmortem shard bisection, and
    # the host-side exposition/linter units — the sharded paths must
    # stay as inspectable as one device, gated in the edit loop
    "test_tp_telemetry.py",
    # causal task-journey rings (ISSUE 15): the inert-journey
    # bit-exactness gate, the device-vs-host-replay chain bit-match,
    # the Perfetto flow-chain acceptance world and the drop-oldest
    # accounting — the inert-subsystem discipline of chaos/hier above
    "test_journeys.py",
    # TP journeys (ISSUE 19): the stitched-ring A/B vs the
    # single-device tap on the windowed defer-heavy world, the
    # per-shard Perfetto lanes, the owning-shard postmortem column and
    # the census-label/bench-gate units — one TP compile shared
    # module-wide; the regime sweep, host replay and CLI smoke carry
    # their own slow marks (the test_tp.py tier discipline)
    "test_tp_journeys.py",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        name = item.fspath.basename
        item.add_marker(
            pytest.mark.quick if name in _QUICK_FILES else pytest.mark.slow
        )


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    # XLA:CPU intermittently segfaults in backend_compile after ~100
    # compiled programs accumulate in one process (reproduced r4 with
    # faulthandler; the same program compiles cleanly solo).  Dropping
    # compiled executables between modules keeps the live-program count
    # bounded; module-internal caching (fixtures reusing worlds) is
    # unaffected.
    yield
    jax.clear_caches()
