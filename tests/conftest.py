"""Test harness config: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on
``xla_force_host_platform_device_count=8`` virtual CPU devices, the pattern
the driver's ``dryrun_multichip`` also uses.

The environment may pre-import jax with the platform pinned to the tunneled
TPU (axon sitecustomize), which makes ``JAX_PLATFORMS`` env assignments
moot — so we set the XLA flag (read at first backend init, which has not
happened yet at conftest time) and override the platform via
``jax.config.update``.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from fognetsimpp_tpu.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache()

import pytest  # noqa: E402

# Fast developer-loop tier (VERDICT r3 weak item 7: the full suite is a
# ~10-minute CI run; `pytest -m quick` is the <60 s edit loop).  Files
# here compile only small/short worlds; everything else is marked slow.
_QUICK_FILES = {
    "test_sched.py",
    "test_queues.py",
    "test_engine_smoke.py",
    "test_compaction.py",
    "test_pallas.py",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        name = item.fspath.basename
        item.add_marker(
            pytest.mark.quick if name in _QUICK_FILES else pytest.mark.slow
        )
