"""Test harness config: force an 8-device virtual CPU mesh before JAX loads.

Multi-chip TPU hardware is not available in CI; sharding tests run on
``xla_force_host_platform_device_count=8`` virtual CPU devices, the pattern
the driver's ``dryrun_multichip`` also uses.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
