"""hier/ — federated multi-broker hierarchy (ISSUE 14).

Gates: the zero-row HierState is inert (single-broker bit-exactness
across every run entry), an inert B>1 world (one real domain, migration
thresholds at ∞) perturbs zero non-hier bits over the three
policy-family worlds, active federation is bit-identical across
run/run_jit/run_chunked, the task-conservation invariant (including
``n_migrated``/``n_hop_exhausted``) holds exactly on a forced-migration
grid, THRESHOLD/LEAST_LOADED migration beats NEVER on the imbalanced
world, a chaos-killed domain's tasks migrate instead of dropping, the
learn credit of a migrated task resolves exactly-once on the rescuing
broker's pick, and the hier knobs ride the DynSpec operand.
"""
import dataclasses

import jax
import numpy as np
import pytest

from fognetsimpp_tpu import Policy, run
from fognetsimpp_tpu.hier import stamp_ownership
from fognetsimpp_tpu.scenarios import smoke
from fognetsimpp_tpu.spec import ChaosMode, HierPolicy, Stage

#: Deliberately IDENTICAL to tests/test_chaos.py's SMALL shape: the
#: single-broker matrix below then re-runs programs that earlier tier-1
#: files already compiled (the jit cache is process-wide), so the
#: 3-world × 3-entry gate costs runs, not compiles.
SMALL = dict(n_users=2, n_fogs=2, send_interval=0.05, horizon=0.3,
             assume_static=False)

#: The three policy-family worlds of the chaos/telemetry A/B
#: discipline (same policies as test_chaos.WORLDS — shared programs):
#: dense/fused broker, sequential compacted broker, learned bandit.
B1_WORLDS = [
    dict(policy=int(Policy.MIN_BUSY)),
    dict(policy=int(Policy.LOCAL_FIRST), broker_mips=2048.0),
    dict(policy=int(Policy.DUCB)),
]

#: Federatable variants for the B>1 worlds (LOCAL_FIRST does not
#: federate): dense, task-id-keyed RANDOM, learned bandit.
WORLDS = [
    dict(policy=int(Policy.MIN_BUSY)),
    dict(policy=int(Policy.RANDOM)),
    dict(policy=int(Policy.DUCB)),
]

#: The imbalanced acceptance world (hot domain, idle domain): every
#: user publishes to broker 0, whose single slow fog saturates within a
#: few sends, while broker 1 owns three fast idle fogs one 5 ms
#: federation hop away.
IMBALANCED = dict(
    n_users=4, n_fogs=4,
    fog_mips=(900.0, 60000.0, 60000.0, 60000.0),
    send_interval=0.02, horizon=0.6, dt=1e-3, seed=0,
    n_brokers=2, hier_threshold=0.5, hier_max_hops=2,
    hier_rtt_s=0.005, assume_static=False,
)
IMB_FOG_OWNER = [0, 1, 1, 1]
IMB_USER_OWNER = [0, 0, 0, 0]


def _state_hash(state) -> str:
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _build(**kw):
    args = dict(SMALL)
    args.update(kw)
    return smoke.build(**args)


def _build_imbalanced(hier_policy, **kw):
    args = dict(IMBALANCED)
    args.update(kw)
    args["hier_policy"] = int(hier_policy)
    spec, state, net, bounds = smoke.build(**args)
    state = stamp_ownership(
        spec, state, user_broker=IMB_USER_OWNER[: spec.n_users],
        fog_broker=IMB_FOG_OWNER,
    )
    return spec, state, net, bounds


#: Memoized plain-run() finals: run() re-traces its scan per call, so
#: tests sharing a world share ONE trace through these instead of
#: paying ~4 s each (tier-1 time budget; results are read-only).
_RUN_CACHE: dict = {}


def _imb_final(hier_policy, policy=int(Policy.MIN_BUSY)):
    key = ("imb", int(hier_policy), int(policy))
    if key not in _RUN_CACHE:
        spec, state, net, bounds = _build_imbalanced(
            hier_policy, policy=policy
        )
        final, _ = run(spec, state, net, bounds)
        _RUN_CACHE[key] = (spec, final)
    return _RUN_CACHE[key]


def _small_final(**kw):
    key = ("small",) + tuple(sorted(kw.items()))
    if key not in _RUN_CACHE:
        spec, state, net, bounds = _build(**kw)
        final, _ = run(spec, state, net, bounds)
        _RUN_CACHE[key] = (spec, final)
    return _RUN_CACHE[key]


def _census(final) -> dict:
    stage = np.asarray(final.tasks.stage)
    return {s.name: int((stage == int(s)).sum()) for s in Stage}


def _assert_conservation(final):
    """spawned = completed + dropped + lost + in-flight +
    hop-exhausted, exactly (the ISSUE 14 acceptance identity)."""
    c = _census(final)
    published = int(np.asarray(final.metrics.n_published))
    terminal = (
        c["DONE"] + c["DROPPED"] + c["LOST"] + c["NO_RESOURCE"]
        + c["REJECTED"] + c["HOP_EXHAUSTED"]
    )
    in_flight = (
        c["PUB_INFLIGHT"] + c["TASK_INFLIGHT"] + c["QUEUED"]
        + c["RUNNING"] + c["LOCAL_RUN"]
    )
    assert published == terminal + in_flight, (published, c)
    assert c["HOP_EXHAUSTED"] == int(
        np.asarray(final.hier.n_hop_exhausted)
    )
    assert c["DONE"] == int(np.asarray(final.metrics.n_completed))


def _task_time_ms(final) -> np.ndarray:
    from fognetsimpp_tpu.runtime.signals import extract_signals

    return extract_signals(final)["task_time"]


# ----------------------------------------------------------------------
# inert gates: single broker, and a degenerate B>1 world
# ----------------------------------------------------------------------

def test_single_broker_hier_state_inert():
    """n_brokers=1 (the default) carries zero-row hier leaves and
    traces none of the hierarchy machinery: every HierState array leaf
    is empty and every counter exactly zero after a full run — over the
    three policy-family worlds (the edit-loop half of the single-broker
    gate; the full cross-entry state-hash matrix rides the slow twin
    below)."""
    for kw in B1_WORLDS:
        spec, ref = _small_final(**kw)
        assert not spec.hier_active
        assert spec.hier_users == 0 and spec.hier_tasks == 0
        assert ref.hier.fog_broker.shape == (0,)
        assert ref.hier.task_broker.shape == (0,)
        assert ref.hier.peer_load.shape == (0, 0)
        assert int(np.asarray(ref.hier.n_migrated)) == 0
        assert int(np.asarray(ref.hier.n_hop_exhausted)) == 0


@pytest.mark.slow  # run_jit + chunked compiles per world: full-suite
#   tier (the quick tier keeps the zero-row gate above; run_chunked
#   compiles its chunk program per call, so this matrix is the file's
#   compile-heavy half)
def test_single_broker_bit_exact_across_run_entries():
    """n_brokers=1 produces bit-identical final states across
    run / run_jit / run_chunked — over the three policy-family
    worlds (the ISSUE 14 acceptance matrix)."""
    from fognetsimpp_tpu.core.engine import run_chunked, run_jit

    for kw in B1_WORLDS:
        spec, ref = _small_final(**kw)
        h_ref = _state_hash(ref)
        spec2, state2, net2, bounds2 = _build(**kw)
        assert _state_hash(run_jit(spec2, state2, net2, bounds2)) == h_ref
        spec3, state3, net3, bounds3 = _build(**kw)
        assert (
            _state_hash(run_chunked(spec3, state3, net3, bounds3, 150))
            == h_ref
        )


def _build_inert_world(**kw):
    sp, st, n, b = _build(
        n_brokers=2, hier_policy=int(HierPolicy.THRESHOLD),
        hier_threshold=float("inf"), **kw
    )
    st = stamp_ownership(
        sp, st, user_broker=[0] * sp.n_users,
        fog_broker=[0] * sp.n_fogs,
    )
    return sp, st, n, b


def test_inert_multi_broker_world_perturbs_nothing():
    """B=2 with every user AND fog stamped into domain 0 and the
    migration threshold at ∞ is read-only: the hier machinery traces
    (domain masks, the migrate phase, peer-view aging) but every
    non-hier leaf of the final state is bit-equal to the single-broker
    run of the same world — over the three federatable policy-family
    worlds via run() (the run_jit/run_chunked entries ride the slow
    twin below — they re-enter the same phase code; their compile
    budget stays out of the edit loop)."""
    for kw in WORLDS:
        _, ref = _small_final(**kw)
        spec_on, s_on, net2, bounds2 = _build_inert_world(**kw)
        assert spec_on.hier_active
        finals = [run(spec_on, s_on, net2, bounds2)[0]]
        for got in finals:
            for f in dataclasses.fields(ref):
                if f.name == "hier":
                    continue
                for a, b in zip(
                    jax.tree.leaves(getattr(ref, f.name)),
                    jax.tree.leaves(getattr(got, f.name)),
                ):
                    np.testing.assert_array_equal(
                        np.asarray(a), np.asarray(b),
                        err_msg=f"{kw} {f.name}",
                    )
            assert int(np.asarray(got.hier.n_migrated)) == 0
            assert int(np.asarray(got.hier.n_hop_exhausted)) == 0


@pytest.mark.slow  # run_jit + chunked compiles of the inert federated
#   program: full-suite tier (the quick twin above covers run())
def test_inert_multi_broker_world_other_entries():
    """The inert-B>1 world through run_jit and run_chunked as well:
    both entries bit-equal the single-broker run on every non-hier
    leaf (dense-family world; the entries re-enter the same phase code
    for every policy family)."""
    from fognetsimpp_tpu.core.engine import run_chunked, run_jit

    kw = WORLDS[0]
    _, ref = _small_final(**kw)
    sp3, st3, n3, b3 = _build_inert_world(**kw)
    sp4, st4, n4, b4 = _build_inert_world(**kw)
    for got in (
        run_jit(sp3, st3, n3, b3),
        run_chunked(sp4, st4, n4, b4, 150),
    ):
        for f in dataclasses.fields(ref):
            if f.name == "hier":
                continue
            for a, b in zip(
                jax.tree.leaves(getattr(ref, f.name)),
                jax.tree.leaves(getattr(got, f.name)),
            ):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b), err_msg=f.name
                )


def test_phase_contract_registered():
    from fognetsimpp_tpu.core.contracts import check_phase_contracts

    spec, state, net, _ = _build_imbalanced(HierPolicy.THRESHOLD)
    checked = check_phase_contracts(spec, state, net)
    assert "_phase_broker_migrate" in checked


# ----------------------------------------------------------------------
# active federation: determinism + conservation on a forced grid
# ----------------------------------------------------------------------

@pytest.mark.slow  # run_jit + chunked compiles of the federated
#   program: full-suite tier (the quick tier keeps the run()-level
#   migration grid below — the test_tp.py cross-entry discipline)
def test_active_federation_bit_identical_across_run_entries():
    from fognetsimpp_tpu.core.engine import run_chunked, run_jit

    _, ref = _imb_final(HierPolicy.THRESHOLD)
    assert int(np.asarray(ref.hier.n_migrated)) > 0
    h_ref = _state_hash(ref)
    spec2, state2, net2, bounds2 = _build_imbalanced(HierPolicy.THRESHOLD)
    assert _state_hash(run_jit(spec2, state2, net2, bounds2)) == h_ref
    spec3, state3, net3, bounds3 = _build_imbalanced(HierPolicy.THRESHOLD)
    assert (
        _state_hash(run_chunked(spec3, state3, net3, bounds3, 300))
        == h_ref
    )


@pytest.mark.parametrize(
    "policy", [int(Policy.MIN_BUSY), int(Policy.DUCB)],
)
@pytest.mark.parametrize(
    "hier_policy", [int(HierPolicy.THRESHOLD), int(HierPolicy.LEAST_LOADED)]
)
def test_forced_migration_conservation_grid(policy, hier_policy):
    """Migration actually fires on the imbalanced world under
    (dense / learned scheduler) × (THRESHOLD / LEAST_LOADED) cells, and
    the conservation identity holds exactly.  (The compacted RANDOM
    family's domain masking is covered by the inert-B>1 gate above;
    keeping it out of this grid saves two tier-1 compiles.)"""
    spec, final = _imb_final(hier_policy, policy)
    assert int(np.asarray(final.hier.n_migrated)) > 0
    h = final.hier
    np.testing.assert_array_equal(
        np.asarray(h.mig_out).sum(), np.asarray(h.n_migrated)
    )
    np.testing.assert_array_equal(
        np.asarray(h.mig_in).sum(), np.asarray(h.n_migrated)
    )
    # migrated tasks live on domain-1 fogs only after the hop
    fog = np.asarray(final.tasks.fog)
    hops = np.asarray(h.hops)
    done = np.asarray(final.tasks.stage) == int(Stage.DONE)
    rescued = done & (hops > 0)
    assert rescued.any()
    assert np.all(np.isin(fog[rescued], [1, 2, 3]))
    _assert_conservation(final)


@pytest.mark.slow  # its own chaos+hier program: full-suite tier
#   (the quick-tier grid covers conservation incl. HOP_EXHAUSTED=0)
def test_hop_budget_exhausts_in_dead_federation():
    """Every domain dead (scripted chaos kills all fogs), REOFFLOAD
    bounces tasks back to brokers: with nowhere to go the migrate phase
    terminates them as HOP_EXHAUSTED, counted exactly."""
    spec, state, net, bounds = _build(
        horizon=0.6,
        n_brokers=2, hier_policy=int(HierPolicy.THRESHOLD),
        hier_threshold=0.5, hier_max_hops=1,
        chaos=True, chaos_mode=int(ChaosMode.REOFFLOAD),
        chaos_max_retries=8,
        chaos_script=((0, 0.1, 0.55), (1, 0.1, 0.55)),
    )
    final, _ = run(spec, state, net, bounds)
    exhausted = int(np.asarray(final.hier.n_hop_exhausted))
    assert exhausted > 0
    _assert_conservation(final)


# ----------------------------------------------------------------------
# the acceptance results: migration beats NEVER
# ----------------------------------------------------------------------

@pytest.mark.slow  # adds the NEVER-policy program: full-suite tier
#   (the measured result of record is the committed bench.py --hier
#   capture, BENCH_r07.json / BENCHMARKS.md)
def test_migration_beats_never_on_imbalanced_world():
    """Hot domain 0 (one slow fog), idle domain 1 (three fast fogs):
    THRESHOLD and LEAST_LOADED migration both beat NEVER on mean AND
    p95 task latency — the BENCHMARKS.md federation-under-imbalance
    result."""
    results = {}
    for pol in (HierPolicy.NEVER, HierPolicy.THRESHOLD,
                HierPolicy.LEAST_LOADED):
        spec, final = _imb_final(pol)
        tt = _task_time_ms(final)
        assert tt.size > 0, pol
        results[pol] = (float(tt.mean()), float(np.percentile(tt, 95)))
        if pol is HierPolicy.NEVER:
            assert int(np.asarray(final.hier.n_migrated)) == 0
        else:
            assert int(np.asarray(final.hier.n_migrated)) > 0
        _assert_conservation(final)
    never_mean, never_p95 = results[HierPolicy.NEVER]
    for pol in (HierPolicy.THRESHOLD, HierPolicy.LEAST_LOADED):
        mean, p95 = results[pol]
        assert mean < never_mean, (pol, results)
        assert p95 < never_p95, (pol, results)


@pytest.mark.slow  # two chaos+hier programs: full-suite tier
def test_chaos_dead_domain_migrates_instead_of_dropping():
    """A whole domain down (scripted outage over every domain-0 fog):
    under NEVER its re-offloaded tasks die (NO_RESOURCE / retry
    exhaustion); under THRESHOLD they migrate to the surviving domain
    and complete — the federation actually buys robustness."""
    kw = dict(
        n_users=4, n_fogs=4,
        fog_mips=(60000.0, 60000.0, 60000.0, 60000.0),
        send_interval=0.02, horizon=1.0, dt=1e-3, seed=0,
        n_brokers=2, hier_threshold=0.5, hier_max_hops=2,
        assume_static=False,
        chaos=True, chaos_mode=int(ChaosMode.REOFFLOAD),
        chaos_max_retries=8,
        chaos_script=((0, 0.1, 0.95), (1, 0.1, 0.95)),
    )

    def run_one(pol):
        spec, state, net, bounds = smoke.build(
            **kw, hier_policy=int(pol)
        )
        state = stamp_ownership(
            spec, state, user_broker=[0, 0, 0, 0],
            fog_broker=[0, 0, 1, 1],
        )
        final, _ = run(spec, state, net, bounds)
        _assert_conservation(final)
        return final

    never = run_one(HierPolicy.NEVER)
    mig = run_one(HierPolicy.THRESHOLD)
    c_never, c_mig = _census(never), _census(mig)
    lost_never = (
        c_never["NO_RESOURCE"] + c_never["LOST"]
        + c_never["HOP_EXHAUSTED"]
    )
    lost_mig = (
        c_mig["NO_RESOURCE"] + c_mig["LOST"] + c_mig["HOP_EXHAUSTED"]
    )
    assert lost_never > 0, c_never
    assert int(np.asarray(mig.hier.n_migrated)) > 0
    assert lost_mig < lost_never, (c_mig, c_never)
    assert c_mig["DONE"] > c_never["DONE"], (c_mig, c_never)


# ----------------------------------------------------------------------
# learn interplay: exactly-once credit on the rescuing broker's pick
# ----------------------------------------------------------------------

def test_learn_credit_exactly_once_survives_migration():
    """Bandit world on the imbalanced federation: every credit resolves
    exactly once (reward_cnt == lat_cnt with no chaos penalties), every
    DONE-and-acked task's credit went to the fog the RESCUING broker
    picked (tasks.fog provenance), and credited rows never exceed
    picks."""
    spec, final = _imb_final(HierPolicy.THRESHOLD, int(Policy.DUCB))
    assert int(np.asarray(final.hier.n_migrated)) > 0
    reward_cnt = float(np.sum(np.asarray(final.learn.reward_cnt)))
    picks = float(np.sum(np.asarray(final.learn.pick_count)))
    lat_cnt = float(np.asarray(final.learn.lat_cnt))
    assert reward_cnt == pytest.approx(lat_cnt)
    assert reward_cnt <= picks + 1e-6
    # rescued tasks were decided (and credited) on domain-1 arms
    hops = np.asarray(final.hier.hops)
    done = np.asarray(final.tasks.stage) == int(Stage.DONE)
    credited = np.asarray(final.learn.credited) == 1
    rescued = done & credited & (hops > 0)
    assert rescued.any()
    assert np.all(np.isin(np.asarray(final.tasks.fog)[rescued], [1, 2, 3]))


# ----------------------------------------------------------------------
# dynspec: migration knobs ride the operand
# ----------------------------------------------------------------------

def test_hier_knobs_ride_the_dynspec_operand():
    """Threshold / RTT / hop-budget changes stay inside one shape
    bucket (zero recompiles via apply_knobs), and the derived (B, B)
    RTT leaf matches the spec's matrix/uniform derivation."""
    from fognetsimpp_tpu import dynspec

    spec, _, _, _ = _build_imbalanced(HierPolicy.THRESHOLD)
    spec2 = dynspec.apply_knobs(
        spec, {"hier_threshold": 0.9, "hier_rtt_s": 0.02,
               "hier_max_hops": 4},
    )
    assert dynspec.same_program(spec, spec2)
    d = dynspec.dyn_of(spec2)
    assert d.hier_rtt.shape == (2, 2)
    assert float(d.hier_rtt[0, 1]) == np.float32(0.02)
    assert float(d.hier_rtt[0, 0]) == 0.0
    assert int(d.hier_max_hops) == 4
    # an explicit matrix rides verbatim
    spec3 = dataclasses.replace(
        spec, hier_rtt_matrix=((0.0, 0.008), (0.012, 0.0))
    ).validate()
    d3 = dynspec.dyn_of(spec3)
    assert float(d3.hier_rtt[1, 0]) == np.float32(0.012)


@pytest.mark.slow  # pays the federated run_jit cold compile
def test_warm_threshold_reconfig_is_zero_compiles():
    """Re-tuning the migration threshold on a live federated world is
    a pure jit-cache hit: zero backend compile events (the ISSUE 13
    warm-reconfig contract extended to the hier knobs)."""
    from fognetsimpp_tpu import compile_cache, dynspec
    from fognetsimpp_tpu.core.engine import run_jit

    spec, state, net, bounds = _build_imbalanced(HierPolicy.THRESHOLD)
    run_jit(spec, state, net, bounds)  # cold
    snap = compile_cache.snapshot()
    spec2 = dynspec.apply_knobs(spec, {"hier_threshold": 0.25})
    _, state2, net2, bounds2 = _build_imbalanced(
        HierPolicy.THRESHOLD, hier_threshold=0.25
    )
    run_jit(spec2, state2, net2, bounds2)
    assert compile_cache.delta_since(snap)["compiles"] == 0


# ----------------------------------------------------------------------
# observability + sharded-runner gates
# ----------------------------------------------------------------------

@pytest.mark.slow  # its own telemetry-on federated program
def test_recorder_exposition_and_timeline_carry_hier(tmp_path):
    """One federated run through the full output layer: .sca.json hier
    section, fns_hier_* OpenMetrics families, and the Perfetto broker
    load lanes — all from the one hier_summary() source."""
    import json

    from fognetsimpp_tpu.runtime.recorder import record_run
    from fognetsimpp_tpu.telemetry.timeline import build_trace

    spec, state, net, bounds = _build_imbalanced(
        HierPolicy.THRESHOLD, telemetry=True, telemetry_reservoir=64
    )
    final, _ = run(spec, state, net, bounds)
    assert final.telem.hier_load_sum.shape == (2,)
    paths = record_run(str(tmp_path), spec, final, run_id="Hier-0")
    sca = json.loads(open(paths["sca"]).read())
    assert sca["hier"]["n_brokers"] == 2
    assert sca["hier"]["policy"] == "threshold"
    assert sca["hier"]["migrated"] == int(
        np.asarray(final.hier.n_migrated)
    )
    assert sca["hier"]["fogs_per_broker"] == [1, 3]
    assert sca["scalars"]["hier_migrated"] == sca["hier"]["migrated"]
    om = open(paths["om"]).read()
    assert "fns_hier_migrated" in om
    assert 'fns_hier_migrations_out{broker="0"}' in om
    assert 'fns_hier_load_mean{broker="1"}' in om
    trace = build_trace(spec, final)
    lanes = [
        e for e in trace["traceEvents"]
        if e.get("name", "").startswith("broker") and e.get("ph") == "C"
    ]
    assert lanes, "per-broker load lanes missing from the trace"


def test_hier_telemetry_leaves_zero_row_when_off():
    spec, _, _, _ = _build_imbalanced(HierPolicy.THRESHOLD)
    assert spec.telemetry_hier_brokers == 0  # telemetry off
    spec2, state2, _, _ = _build(n_brokers=2, telemetry=True)
    assert spec2.telemetry_hier_brokers == 2
    assert state2.telem.hier_load_sum.shape == (2,)
    spec3, state3, _, _ = _build(telemetry=True)
    assert state3.telem.hier_load_sum.shape == (0,)


def test_sharded_runners_reject_hier_with_one_line():
    """The TP tick and the fleet runner gate federated specs off with
    the ONE shared hier_reject_reason message."""
    from fognetsimpp_tpu.core.engine import tp_reject_reason
    from fognetsimpp_tpu.parallel import make_mesh, replicate_state
    from fognetsimpp_tpu.parallel.fleet import run_fleet

    spec, state, net, bounds = _build(
        n_brokers=2, n_fogs=4, assume_static=True
    )
    reason = tp_reject_reason(spec)
    assert reason is not None and "hierarchy" in reason
    batch = replicate_state(spec, state, 8)
    with pytest.raises(ValueError, match="hierarchy"):
        run_fleet(spec, batch, net, bounds, make_mesh(8))


@pytest.mark.slow  # in-process CLI: its own program (test_tp.py
#   CLI-smoke discipline)
def test_cli_hier_composes_with_policy_and_telemetry(tmp_path, capsys):
    """--brokers/--hier-policy compose with --policy/--telemetry and
    the run lands hier counters in every output."""
    import json

    from fognetsimpp_tpu.__main__ import main

    rc = main([
        "--scenario", "smoke",
        "--set", "scenario.horizon=0.3",
        "--set", "scenario.n_fogs=4",
        "--brokers", "2", "--hier-policy", "least_loaded",
        "--policy", "min_busy", "--telemetry",
        "--out", str(tmp_path),
    ])
    captured = capsys.readouterr()
    assert rc == 0
    json.loads(captured.out.splitlines()[-1])
    sca = json.loads((tmp_path / "General-0.sca.json").read_text())
    assert sca["hier"]["n_brokers"] == 2
    assert sca["hier"]["policy"] == "least_loaded"
    assert sca["spec"]["n_brokers"] == 2
