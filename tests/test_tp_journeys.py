"""TP journeys — shard-local event rings under the sharded tick
(ISSUE 19).

The journey plane runs INSIDE the shard_map'd TP tick: each shard
diffs only its OWNED task slots (global slot ids via the TpCtx offset)
into shard-local rings, only the scalar drop census joins the
end-of-tick psum, and the stitcher reassembles the rings in global
slot order.  The gates:

* the decoded TP chains bit-match the single-device tap on a windowed
  defer-heavy world — every journey leaf, the drop census and the
  stage roll-up, with the simulation state itself bit-exact;
* the same chains bit-match a deterministic numpy HOST REPLAY of the
  single-device schedule (the shared ``journey_edges`` rule set, third
  backend);
* Perfetto renders per-shard ``journeys-shard{k}`` lanes with the
  DEFER slices on the waiting entity's lane, chains still connected;
* flight-recorder bundles carry the owning-shard column and
  ``postmortem.py --task`` names the shard (pre-TP bundles stay
  .get-safe);
* the ``fns_journey_tasks{stage=...}`` census label obeys the
  known-stage/no-duplicate lint and ``tp_journey_overhead`` rides the
  bench trend gate.

Compile budget: the quick tier compiles ONE TP program (the windowed
defer-heavy A/B, shared module-wide); the regime x entry sweep, the
host replay and the CLI composition ride the slow tier.
"""
import hashlib
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fognetsimpp_tpu import run
from fognetsimpp_tpu.core.engine import run_chunked, run_jit
from fognetsimpp_tpu.parallel import (
    make_mesh,
    run_tp_chunked,
    run_tp_sharded,
)
from fognetsimpp_tpu.scenarios import smoke
from fognetsimpp_tpu.telemetry import journeys as jn

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)

SMALL = dict(
    n_users=16, n_fogs=3, send_interval=0.01, horizon=0.2,
    start_time_max=0.05,
)

#: The acceptance world: arrival_window=1 with a hot send cadence keeps
#: the K-window selection truncating from early on, so matured sends
#: WAIT — the DEFER edge fires on both broker- and fog-side lanes and
#: the rings carry a windowed schedule no restamped column could
#: reconstruct.
DEFER_HEAVY = dict(
    telemetry=True, telemetry_journeys=8, telemetry_journey_ring=32,
    arrival_window=1, send_interval=0.005,
)

_JOURNEY_LEAVES = ("j_task", "j_prev", "j_ring", "j_cursor", "j_dropped")


def _hash(state, skip=()) -> str:
    h = hashlib.sha256()
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        if any(s in jax.tree_util.keystr(path) for s in skip):
            continue
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _build(**kw):
    args = dict(SMALL)
    args.update(kw)
    return smoke.build(**args)


def _tp(spec, state, net, bounds, mesh, **kw):
    kw.setdefault("donate", True)
    return run_tp_sharded(
        spec, jax.tree.map(jnp.copy, state), net, bounds, mesh, **kw
    )


@pytest.fixture(scope="module")
def node_mesh():
    assert len(jax.devices()) == 8, "conftest must provision 8 devices"
    return make_mesh(8, axis_name="node")


@pytest.fixture(scope="module")
def ab(node_mesh):
    """The shared quick-tier A/B: single-device reference and TP run of
    the windowed defer-heavy world (ONE TP compile for the module)."""
    spec, state, net, bounds = _build(**DEFER_HEAVY)
    ref, _ = run(spec, state, net, bounds)
    spec2, got = _tp(spec, state, net, bounds, node_mesh)
    return spec, ref, spec2, got


# ----------------------------------------------------------------------
# the determinism oracle: TP chains == single-device tap
# ----------------------------------------------------------------------

def test_tp_journey_chains_bit_match_single_device(ab):
    """THE acceptance A/B (featmat evidence for journeys x tp): every
    journey leaf of the stitched TP state — sample ids, packed prev
    rows, rings, cursors AND the psum-folded drop census — bit-matches
    the single-device tap on the windowed defer-heavy world; the
    decoded chains agree event-for-event with DEFER present; the
    simulation state itself is bit-exact."""
    spec, ref, spec2, got = ab
    assert spec2.tp_shards == 8
    for name in _JOURNEY_LEAVES:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref.telem, name)),
            np.asarray(getattr(got.telem, name)),
            err_msg=name,
        )
    assert _hash(ref, skip=("telem",)) == _hash(got, skip=("telem",))

    dec_ref = jn.decode_rings(spec, ref)
    dec_tp = jn.decode_rings(spec2, got)
    assert dec_ref == dec_tp
    n_events = sum(d["events_total"] for d in dec_tp)
    assert n_events > 0
    # the windowed world really deferred, and the tap recorded it
    defers = [
        e for d in dec_tp for e in d["events"] if e["name"] == "defer"
    ]
    assert defers, "defer-heavy world recorded no DEFER edges"
    # the K-window truncation defers on the fog side (b=1: matured
    # arrival not yet seated), booked at the observing tick's time
    assert {e["b"] for e in defers} <= {0, 1}
    assert any(e["b"] == 1 for e in defers)
    # the census roll-up (the ONLY journey quantity that crossed the
    # psum is j_dropped; the stage counts come from the stitched rings)
    s_ref = jn.journey_summary(spec, ref)
    s_tp = jn.journey_summary(spec2, got)
    assert s_ref is not None and s_tp is not None
    assert s_ref["sampled"] == s_tp["sampled"] == 8
    assert s_ref["events_total"] == s_tp["events_total"] == n_events
    assert s_ref["terminal"] == s_tp["terminal"]


@pytest.mark.slow  # extra compiles: full-suite tier
def test_tp_journeys_across_regimes_and_entries(node_mesh):
    """Windowed and NO-window regimes x run/run_jit/run_chunked: the
    journey leaves are entry-independent and TP bit-matches each;
    run_tp_chunked == one-shot TP bit-for-bit (re-tiling the journey
    tuple at a chunk boundary must not invent events — the level-
    triggered DEFER regression); a minimum-depth ring forces
    drop-oldest overflow THROUGH the psum census."""
    regimes = [
        dict(DEFER_HEAVY),                                # windowed
        dict(DEFER_HEAVY, telemetry_journey_ring=8),      # + overflow
        dict(telemetry=True, telemetry_journeys=8,
             telemetry_journey_ring=16),                  # no window
    ]
    for kw in regimes:
        spec, state, net, bounds = _build(**kw)
        ref, _ = run(spec, state, net, bounds)
        jit_ref = run_jit(
            spec, jax.tree.map(jnp.copy, state), net, bounds
        )
        chunk_ref = run_chunked(
            spec, jax.tree.map(jnp.copy, state), net, bounds,
            chunk_ticks=spec.n_ticks // 2,
        )
        assert _hash(ref) == _hash(jit_ref) == _hash(chunk_ref), kw
        spec2, got = _tp(spec, state, net, bounds, node_mesh)
        for name in _JOURNEY_LEAVES:
            np.testing.assert_array_equal(
                np.asarray(getattr(ref.telem, name)),
                np.asarray(getattr(got.telem, name)),
                err_msg=f"{name} {kw}",
            )
        assert _hash(ref, skip=("telem",)) == _hash(
            got, skip=("telem",)
        ), kw
        assert jn.decode_rings(spec, ref) == jn.decode_rings(
            spec2, got
        ), kw
        if kw.get("telemetry_journey_ring") == 8:
            assert int(np.asarray(got.telem.j_dropped)) > 0, kw
        # chunked TP == one-shot TP, journey rings included
        spec3, got_c = run_tp_chunked(
            spec, jax.tree.map(jnp.copy, state), net, bounds,
            node_mesh, chunk_ticks=spec.n_ticks // 4,
        )
        assert spec3 == spec2
        assert _hash(got_c) == _hash(got), kw


@pytest.mark.slow  # eager per-tick stepping: full-suite tier
def test_tp_chains_bit_match_host_replay(node_mesh, ab):
    """The third backend: re-derive every tick's edges on HOST with the
    shared journey_edges rule set over numpy snapshots of the
    single-device schedule, and require the TP-decoded rings to match
    the replay event-for-event, drop-oldest tail included — the
    sharded tap provably records the schedule the engine executed."""
    from fognetsimpp_tpu.core.engine import make_step
    from fognetsimpp_tpu.net.mobility import default_bounds

    spec, _, spec2, got = ab
    _, state, net, _ = _build(**DEFER_HEAVY)
    step = make_step(spec)
    jstep = jax.jit(lambda s: step(s, net, default_bounds()))
    ids = np.asarray(state.telem.j_task)

    def snap(s):
        return np.asarray(
            jn.snapshot_rows(
                spec, s.tasks, s.chaos, s.hier, jnp.asarray(ids)
            )
        )

    expected = [[] for _ in ids]
    prev = snap(state)
    s = state
    for i in range(spec.n_ticks):
        s = jstep(s)
        cur = snap(s)
        t1 = np.float32(np.float32(i + 1) * np.float32(spec.dt))
        for j, evs in enumerate(
            jn.replay_tick(spec, prev, cur, ids, float(t1))
        ):
            expected[j].extend(evs)
        prev = cur
    decoded = jn.decode_rings(spec2, got)
    R = spec.journey_ring
    n_events = 0
    for j, d in enumerate(decoded):
        exp = expected[j]
        n_events += len(exp)
        assert d["events_total"] == len(exp), (j, d, exp)
        want = exp[-R:] if len(exp) > R else exp
        assert d["events"] == want, (j, d["events"], want)
    assert n_events > 0
    assert any("defer" in {e["name"] for e in c} for c in expected)


# ----------------------------------------------------------------------
# Perfetto: per-shard journey lanes
# ----------------------------------------------------------------------

def test_tp_perfetto_renders_per_shard_journey_lanes(ab, tmp_path):
    """On the TP-stamped world each sampled task's chain renders in its
    OWNING shard's ``journeys-shard{k}`` process; chains stay connected
    (one s ... f per flow id, every flow bound to a slice) and the
    DEFER slices land on the waiting entity's lane."""
    from fognetsimpp_tpu.telemetry.timeline import export_trace

    spec, _, spec2, got = ab
    p = export_trace(spec2, got, str(tmp_path / "tp_journeys.json"))
    trace = json.loads(open(p).read())
    events = trace["traceEvents"]
    shard_pids = {
        e["pid"]: e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and e.get("args", {}).get("name", "").startswith(
            "journeys-shard"
        )
    }
    assert shard_pids, "no per-shard journey process rendered"
    # the sample really spans more than one owning shard
    assert len(shard_pids) > 1, shard_pids
    owners = jn.journey_owner_shards(
        spec2, [d["task"] for d in jn.decode_rings(spec2, got)]
    )
    assert set(shard_pids.values()) == {
        f"journeys-shard{k}" for k in set(owners)
    }
    jev = [e for e in events if e.get("cat") == "journey"]
    assert all(e["pid"] in shard_pids for e in jev)
    # defer slices present, and chains connected within each process
    assert [e for e in jev if e.get("ph") == "X" and e["name"] == "defer"]
    slices = {(e["pid"], e["tid"], e["ts"]) for e in jev if e["ph"] == "X"}
    by_id: dict = {}
    for e in jev:
        if e["ph"] in ("s", "t", "f"):
            by_id.setdefault(e["id"], []).append(e)
            assert (e["pid"], e["tid"], e["ts"]) in slices
    assert by_id, "no flow chains rendered"
    for fid, chain in by_id.items():
        # traceEvents are ts-sorted and a restamped terminal can carry
        # an earlier timestamp than the tick-time defer slices, so the
        # chain is checked by phase COUNTS: exactly one s, one f, the
        # rest t, all inside the owning shard's process
        phases = sorted(e["ph"] for e in chain)
        assert phases.count("s") == 1 and phases.count("f") == 1, (
            fid, phases,
        )
        assert set(phases) <= {"s", "t", "f"}, (fid, phases)
        assert len({e["pid"] for e in chain}) == 1, fid


# ----------------------------------------------------------------------
# flight recorder + postmortem: the owning-shard column
# ----------------------------------------------------------------------

def test_tp_bundle_postmortem_names_owning_shard(ab, tmp_path, capsys):
    """A flight-recorder bundle dumped from the TP run carries the
    owning-shard column; ``postmortem.py --task`` prints it in the
    chain header.  A pre-TP bundle (no ``shard`` key) and a
    pre-journey bundle (no ``journeys`` at all) stay .get-safe."""
    import postmortem

    from fognetsimpp_tpu.telemetry.live import FlightRecorder

    spec, _, spec2, got = ab
    rec = FlightRecorder(capacity=4)
    rec.note_chunk(100, rows={"t": np.asarray([0.1])})
    manifest = rec.dump(
        str(tmp_path), "anomaly", spec=spec2, final=got
    )
    d = json.load(open(manifest))
    rings = d["journeys"]["rings"]
    assert len(rings["shard"]) == len(rings["task"])
    t_loc = spec2.task_capacity // spec2.tp_shards
    assert rings["shard"] == [t // t_loc for t in rings["task"]]
    task_id = rings["task"][0]
    assert postmortem.main(["--task", str(task_id), manifest]) == 0
    out = capsys.readouterr().out
    assert f"task {task_id}" in out
    assert f"owned by shard {rings['shard'][0]}" in out

    # pre-TP bundle: same rings, shard column stripped
    old = dict(d)
    old["journeys"] = dict(d["journeys"])
    old["journeys"]["rings"] = {
        k: v for k, v in rings.items() if k != "shard"
    }
    pre_tp = tmp_path / "pre_tp.json"
    pre_tp.write_text(json.dumps(old))
    assert postmortem.main(["--task", str(task_id), str(pre_tp)]) == 0
    out2 = capsys.readouterr().out
    assert f"task {task_id}" in out2 and "owned by shard" not in out2

    # pre-journey bundle still summarizes
    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps({"reason": "nan", "ring": []}))
    assert postmortem.main([str(legacy)]) == 0


# ----------------------------------------------------------------------
# host-side exposition units (no TP compile)
# ----------------------------------------------------------------------

def test_openmetrics_journey_stage_rules():
    """The census-label contract on fns_journey_tasks: a missing stage
    label, an unknown stage name and a duplicated stage are findings;
    the known-stage census passes."""
    import check_openmetrics as com

    head = (
        "# HELP fns_journey_tasks j\n"
        "# TYPE fns_journey_tasks gauge\n"
    )
    good = (
        head
        + 'fns_journey_tasks{stage="done"} 5\n'
        + 'fns_journey_tasks{stage="in_flight"} 2\n'
        + 'fns_journey_tasks{stage="unspawned"} 1\n# EOF\n'
    )
    assert com.check_text(good, "g") == 0
    assert com.check_text(
        head + "fns_journey_tasks 5\n# EOF\n", "no-label"
    ) == 1
    # an event name that is NOT a census stage (defer is an edge, not
    # a terminal) must be rejected — key drift away from dashboards
    assert com.check_text(
        head + 'fns_journey_tasks{stage="defer"} 5\n# EOF\n',
        "unknown",
    ) == 1
    assert com.check_text(
        head
        + 'fns_journey_tasks{stage="done",broker="0"} 5\n'
        + 'fns_journey_tasks{stage="done",broker="1"} 6\n# EOF\n',
        "dup",
    ) == 1


def test_bench_trend_tp_journey_gate(tmp_path):
    """A capture recording tp_journey_overhead above the 1.10 bar fails
    --check; at/below passes; the text table carries the column."""
    import bench_trend

    def cap(path, overhead):
        with open(path, "w") as f:
            json.dump(
                {
                    "parsed": {
                        "metric": "m", "value": 100.0, "backend": "cpu",
                        "n_users": 8, "tp_journey_overhead": overhead,
                    }
                },
                f,
            )

    cap(tmp_path / "BENCH_r01.json", 1.08)
    rows = bench_trend.load_rounds(str(tmp_path))
    assert bench_trend.check(rows) == []
    assert "tp-journeys x1.080" in bench_trend.table(rows)
    cap(tmp_path / "BENCH_r02.json", 1.27)
    rows = bench_trend.load_rounds(str(tmp_path))
    problems = bench_trend.check(rows)
    assert len(problems) == 1
    assert "TP-journey-rings-on" in problems[0]


# ----------------------------------------------------------------------
# CLI composition
# ----------------------------------------------------------------------

@pytest.mark.slow  # in-process CLI: its own TP program
def test_cli_tp_journeys_records_and_traces(tmp_path, capsys):
    """--journeys --tp N end to end: runs sharded, decodes the stitched
    rings into .sca.json, and the Perfetto export carries the
    per-shard journey lanes — the previously rejected composition."""
    from fognetsimpp_tpu.__main__ import main

    trace = tmp_path / "t.json"
    rc = main([
        "--scenario", "smoke", "--telemetry", "--journeys", "8",
        "--tp", "8",
        "--set", "scenario.n_users=16",
        "--set", "scenario.n_fogs=3",
        "--set", "scenario.send_interval=0.005",
        "--set", "scenario.horizon=0.2",
        "--set", "scenario.arrival_window=1",
        "--out", str(tmp_path), "--trace-out", str(trace),
    ])
    captured = capsys.readouterr()
    assert rc == 0
    summary = json.loads(captured.out.strip().splitlines()[-1])
    assert summary["tp_shards"] == 8
    sca = json.load(open(tmp_path / "General-0.sca.json"))
    assert sca["journeys"]["sampled"] == 8
    assert sca["journeys"]["events_total"] > 0
    t = json.loads(trace.read_text())
    assert [
        e for e in t["traceEvents"] if e.get("cat") == "journey"
    ]
    assert any(
        e.get("ph") == "M" and e.get("name") == "process_name"
        and e.get("args", {}).get("name", "").startswith(
            "journeys-shard"
        )
        for e in t["traceEvents"]
    )
