"""Fleet-runner gates (ISSUE 3), on the forced 8-virtual-device CPU mesh.

The replica-sharded fleet path (``parallel/fleet.py``) is the measured
multi-chip headline; its correctness contract is the same one every
prior perf PR carried: per-replica state hashes equal the existing vmap
(``run_replicated``) path bit-for-bit on every world tested, donation
changes nothing, and the chunked sharded series offload matches straight
recording.
"""
import hashlib

import jax
import numpy as np
import pytest

from fognetsimpp_tpu import Policy
from fognetsimpp_tpu.core.contracts import check_fleet_contract
from fognetsimpp_tpu.core.engine import run
from fognetsimpp_tpu.parallel import (
    fleet_decisions,
    make_mesh,
    replicate_state,
    run_fleet,
    run_fleet_series,
    run_replicated,
)
from fognetsimpp_tpu.scenarios import smoke

HORIZON = 0.3

# three worlds spanning the policy families: the dense scalar-winner
# fast path, the task-id-keyed RANDOM stream, and the sequential v1
# local-pool scan
WORLDS = (
    dict(policy=int(Policy.MIN_BUSY)),
    dict(policy=int(Policy.RANDOM)),
    dict(policy=int(Policy.LOCAL_FIRST), broker_mips=2048.0),
)


def _replica_hash(batch, r: int) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(batch):
        h.update(np.asarray(leaf)[r].tobytes())
    return h.hexdigest()


def test_fleet_equals_vmap_per_replica_over_three_worlds():
    n_dev = len(jax.devices())
    assert n_dev == 8, "conftest must provision 8 virtual devices"
    mesh = make_mesh(n_dev)
    for kw in WORLDS:
        spec, state, net, bounds = smoke.build(
            horizon=HORIZON, start_time_max=0.05, **kw
        )
        batch = replicate_state(spec, state, n_dev, seed=3)
        ref = run_replicated(spec, batch, net, bounds)
        got = run_fleet(spec, batch, net, bounds, mesh, donate=False)
        # really distributed: one replica per device
        assert len(got.tasks.t_ack6.sharding.device_set) == n_dev
        for r in range(n_dev):
            assert _replica_hash(ref, r) == _replica_hash(got, r), (kw, r)


def test_fleet_chaos_per_replica_schedules_match_vmap():
    """The fleet-chaos follow-up (ROADMAP): chaos worlds run on the
    fleet with PER-REPLICA fault schedules — replica r's chaos stream
    is fold_in(chaos_key, r), re-derived at replicate time — and the
    fleet path equals the vmap path bit-for-bit.  Replicas must NOT
    share one schedule (the old rejection's failure mode)."""
    from fognetsimpp_tpu.spec import ChaosMode

    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)
    spec, state, net, bounds = smoke.build(
        horizon=0.5, start_time_max=0.05, n_fogs=3,
        assume_static=False,
        chaos=True, chaos_mode=int(ChaosMode.REOFFLOAD),
        chaos_mtbf_s=0.08, chaos_mttr_s=0.04, chaos_max_retries=4,
    )
    batch = replicate_state(spec, state, n_dev, seed=3)
    # the replicas draw decorrelated schedules (folded chaos keys)
    keys = np.asarray(batch.chaos.key)
    assert len({k.tobytes() for k in keys}) == n_dev
    ref = run_replicated(spec, batch, net, bounds)
    crashes = np.asarray(ref.chaos.n_crashes)
    assert crashes.sum() > 0
    assert len(set(np.asarray(ref.chaos.down_ticks).sum(axis=1))) > 1, (
        "replicas shared one fault schedule"
    )
    got = run_fleet(spec, batch, net, bounds, mesh, donate=False)
    for r in range(n_dev):
        assert _replica_hash(ref, r) == _replica_hash(got, r), r


@pytest.mark.slow  # its own 4-replica chaos program: full-suite
#   tier (the quick tier keeps the fleet-vs-vmap chaos A/B above)
def test_fleet_chaos_replica_schedule_replays_on_host():
    """Replica r's schedule is exactly outage_timeline under its folded
    key — the host-replay contract survives the per-replica re-key."""
    from fognetsimpp_tpu.chaos.faults import outage_timeline
    from fognetsimpp_tpu.spec import ChaosMode

    spec, state, net, bounds = smoke.build(
        horizon=0.5, n_fogs=2, assume_static=False,
        chaos=True, chaos_mode=int(ChaosMode.LOSE),
        chaos_mtbf_s=0.1, chaos_mttr_s=0.05,
    )
    batch = replicate_state(spec, state, 4, seed=0)
    final = run_replicated(spec, batch, net, bounds)
    dt = spec.dt
    t1s = (np.arange(spec.n_ticks) + 1).astype(np.float32) * np.float32(dt)
    for r in range(4):
        timeline = outage_timeline(spec, np.asarray(batch.chaos.key)[r])
        expect = np.zeros(spec.n_fogs, np.int64)
        for f, td, tu in timeline:
            expect[f] += int(
                ((np.float32(td) < t1s) & (np.float32(tu) >= t1s)).sum()
            )
        np.testing.assert_array_equal(
            np.asarray(final.chaos.down_ticks, np.int64)[r], expect,
            err_msg=f"replica {r}",
        )


def test_fleet_donated_carry_bit_exact():
    """Donating the sharded carry (the production default) must not
    change a bit vs the keep path — and the dealias pass must survive
    the builder's fogs.mips/pool_avail alias under sharding."""
    spec, state, net, bounds = smoke.build(
        horizon=HORIZON, start_time_max=0.05
    )
    mesh = make_mesh(8)
    batch = replicate_state(spec, state, 8, seed=3)
    ref = run_fleet(spec, batch, net, bounds, mesh, donate=False)
    got = run_fleet(spec, batch, net, bounds, mesh, donate=True)
    # batch is consumed by the donating call above; do not reuse it
    for r in range(8):
        assert _replica_hash(ref, r) == _replica_hash(got, r), r


def test_fleet_replica_count_must_divide_mesh():
    spec, state, net, bounds = smoke.build(horizon=0.1)
    batch = replicate_state(spec, state, 3)
    with pytest.raises(ValueError, match="divide"):
        run_fleet(spec, batch, net, bounds, make_mesh(8))


def test_fleet_decisions_reduction_matches_vmap_counters():
    """The device-resident pipeline reduction (one scalar fetch) equals
    summing the vmap path's per-replica counters on the host."""
    from fognetsimpp_tpu.parallel.fleet import fold_replica_keys

    spec, state, net, bounds = smoke.build(
        horizon=HORIZON, start_time_max=0.05
    )
    mesh = make_mesh(8)
    batch = replicate_state(spec, state, 8, seed=3)
    keys = jax.random.split(jax.random.PRNGKey(5), 2)
    d, dm = fleet_decisions(spec, batch, net, bounds, keys, mesh)
    total = 0
    for i in range(len(keys)):
        b = batch.replace(key=fold_replica_keys(keys[i], 8))
        fin = run_replicated(spec, b, net, bounds)
        total += int(np.asarray(fin.metrics.n_scheduled).sum())
    assert int(np.asarray(d)) == total
    assert int(np.asarray(dm)) >= 0


def test_fleet_series_chunked_matches_straight_recording():
    """run_fleet_series (chunked, sharded, donated between chunks) is
    bit-identical to one straight vmapped recording run."""
    spec, state, net, bounds = smoke.build(
        horizon=HORIZON, start_time_max=0.05, record_tick_series=True
    )
    mesh = make_mesh(8)
    batch = replicate_state(spec, state, 8, seed=3)

    def run_one(s, net_, bounds_):
        return run(spec, s, net_, bounds_)

    ref_final, ref_series = jax.jit(
        jax.vmap(run_one, in_axes=(0, None, None))
    )(batch, net, bounds)

    got_final, got_series = run_fleet_series(
        spec, batch, net, bounds, mesh, chunk_ticks=130
    )
    assert set(got_series) == set(ref_series)
    for k in ref_series:
        np.testing.assert_array_equal(
            np.asarray(ref_series[k]), got_series[k], err_msg=k
        )
    for r in range(8):
        assert _replica_hash(ref_final, r) == _replica_hash(got_final, r)


def test_fleet_series_requires_recording_spec():
    spec, state, net, bounds = smoke.build(horizon=0.1)
    batch = replicate_state(spec, state, 8)
    with pytest.raises(ValueError, match="record_tick_series"):
        run_fleet_series(spec, batch, net, bounds, make_mesh(8))


def test_fleet_carry_contract():
    """The replica-batched tick step is a carry endomorphism (trace-time
    only: no FLOPs), so the fleet scan can never recompile mid-run."""
    spec, state, net, bounds = smoke.build(horizon=HORIZON)
    batch = replicate_state(spec, state, 8)
    check_fleet_contract(spec, batch, net, bounds)


def test_fleet_cli_runs_and_reports(capsys):
    """python -m fognetsimpp_tpu --replicas 8: one JSON line with the
    replica-aggregated counters."""
    import json

    from fognetsimpp_tpu.__main__ import main

    rc = main([
        "--scenario", "smoke",
        "--set", "scenario.horizon=0.1",
        "--set", "scenario.start_time_max=0.02",
        "--replicas", "8",
    ])
    captured = capsys.readouterr()
    assert rc == 0
    out = json.loads(captured.out.strip().splitlines()[-1])
    assert out["n_replicas"] == 8 and out["n_devices"] == 8
    assert out["n_published_sum"] > 0
