"""telemetry/ — the three-plane observability layer (ISSUE 4).

Plane 1 gates: the zero-row TelemetryState is inert (state-hash A/B
across run entries, and telemetry ON perturbs not a single non-telem
bit), and the device-resident accumulators agree with host-side ground
truth.  Plane 2: the Perfetto exporter against a committed golden.
Plane 3: OpenMetrics exposition matching the recorder's ``.sca.json``
to 1e-6 (exactly, in fact — one shared computation).
"""
import dataclasses
import json
from pathlib import Path

import jax
import numpy as np
import pytest

from fognetsimpp_tpu import Policy, run
from fognetsimpp_tpu.scenarios import smoke

GOLDEN = Path(__file__).parent / "data" / "telemetry_smoke_trace.json"

SMALL = dict(n_users=2, n_fogs=2, send_interval=0.05, horizon=0.4)


def _state_hash(state) -> str:
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _build(**kw):
    args = dict(SMALL)
    args.update(kw)
    return smoke.build(**args)


# ----------------------------------------------------------------------
# Plane 1: inert gate + accumulators
# ----------------------------------------------------------------------

WORLDS = [
    dict(policy=int(Policy.MIN_BUSY)),  # dense broker path
    dict(policy=int(Policy.LOCAL_FIRST), broker_mips=2048.0),  # compacted
    dict(policy=int(Policy.UCB)),  # learned (learn + telem carry fields)
]


def test_telemetry_off_bit_exact_across_run_entries():
    """The PR 2 inert-LearnState discipline, replayed for telemetry:
    with spec.telemetry off (the default) every telemetry leaf has zero
    rows, stays zero, and run / run_jit / run_chunked produce
    bit-identical final states."""
    from fognetsimpp_tpu.core.engine import run_chunked, run_jit

    for kw in WORLDS:
        spec, state, net, bounds = _build(**kw)
        assert not spec.telemetry
        assert spec.telemetry_fogs == 0 and spec.telemetry_slots == 0
        ref, _ = run(spec, state, net, bounds)
        assert ref.telem.q_len_sum.shape == (0,)
        assert ref.telem.res.shape[0] == 0
        assert int(np.asarray(ref.telem.ticks)) == 0
        h_ref = _state_hash(ref)
        spec2, state2, net2, bounds2 = _build(**kw)
        assert _state_hash(run_jit(spec2, state2, net2, bounds2)) == h_ref
        spec3, state3, net3, bounds3 = _build(**kw)
        assert (
            _state_hash(run_chunked(spec3, state3, net3, bounds3, 170))
            == h_ref
        )


def test_telemetry_on_never_perturbs_the_simulation():
    """Telemetry ON is read-only: every non-telem leaf of the final
    state is bit-equal to the telemetry-off run of the same world."""
    for kw in WORLDS:
        spec_off, s_off, net, bounds = _build(**kw)
        ref, _ = run(spec_off, s_off, net, bounds)
        spec_on, s_on, net2, bounds2 = _build(telemetry=True, **kw)
        assert spec_on.telemetry_fogs == spec_on.n_fogs
        got, _ = run(spec_on, s_on, net2, bounds2)
        for f in dataclasses.fields(ref):
            if f.name == "telem":
                continue
            for a, b in zip(
                jax.tree.leaves(getattr(ref, f.name)),
                jax.tree.leaves(getattr(got, f.name)),
            ):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b), err_msg=f.name
                )


def test_accumulators_match_host_ground_truth():
    """Busy fraction / queue stats / pick histogram from the carry
    agree with what the final state itself implies."""
    from fognetsimpp_tpu.telemetry.metrics import telemetry_summary

    spec, state, net, bounds = _build(
        telemetry=True, policy=int(Policy.UCB), horizon=1.0
    )
    final, _ = run(spec, state, net, bounds)
    summ = telemetry_summary(spec, final)
    assert summ["ticks"] == spec.n_ticks
    # pick histogram is the live copy of the learner's pick counts
    np.testing.assert_allclose(
        summ["pick_hist"], np.asarray(final.learn.pick_count)
    )
    # queue-depth bounds: min <= mean <= max, max within capacity
    assert (summ["q_len_min"] <= summ["q_len_max"]).all()
    assert (summ["q_len_mean"] <= summ["q_len_max"] + 1e-9).all()
    assert (summ["q_len_max"] <= spec.queue_capacity).all()
    assert ((summ["busy_frac"] >= 0) & (summ["busy_frac"] <= 1)).all()
    # phase work: the broker phase booked at least every decision, and
    # phases this spec never traces booked nothing
    m = final.metrics
    assert summ["phase_work"]["broker"] >= int(np.asarray(m.n_scheduled))
    assert summ["phase_work"]["pool_arrivals"] == 0
    assert summ["phase_work"]["v2_release_pre"] == 0


def test_reservoir_is_bounded_and_monotone():
    spec, state, net, bounds = _build(
        telemetry=True, telemetry_reservoir=16, horizon=1.0
    )
    assert spec.telemetry_slots == 16
    assert spec.n_ticks > 16  # genuinely strided
    final, _ = run(spec, state, net, bounds)
    from fognetsimpp_tpu.telemetry.metrics import telemetry_summary

    res = telemetry_summary(spec, final)["reservoir"]
    t = res["t"]
    assert len(t) == 16
    assert (np.diff(t) > 0).all()  # strided sample times increase
    assert (np.diff(res["n_completed"]) >= 0).all()  # cumulative


def test_run_chunked_streams_reservoir_in_order():
    """The PR-4 follow-up: run_chunked delivers the telemetry reservoir
    rows per chunk, in tick order, no row twice, and their union equals
    the final reservoir — live dashboards see per-tick rows without
    waiting for run end (and without disabling chunk donation)."""
    from fognetsimpp_tpu.core.engine import run_chunked
    from fognetsimpp_tpu.telemetry.metrics import (
        RES_FIELDS,
        telemetry_summary,
    )

    spec, state, net, bounds = _build(
        telemetry=True, telemetry_reservoir=24, horizon=1.2
    )
    chunk = 170  # ragged: several chunks per run, rows split unevenly
    batches = []

    def stream(rows, ticks_done):
        assert set(rows) == set(RES_FIELDS)
        # callback order: every delivered row's tick precedes the chunk
        # boundary that delivered it (t is the row's end-of-tick time)
        assert (rows["t"] <= ticks_done * spec.dt + 1e-6).all()
        batches.append((rows, ticks_done))

    final = run_chunked(
        spec, state, net, bounds, chunk, telemetry_stream=stream
    )
    assert len(batches) == -(-spec.n_ticks // chunk)  # one per chunk
    dones = [d for _, d in batches]
    assert dones == sorted(dones)
    t_all = np.concatenate([r["t"] for r, _ in batches])
    assert (np.diff(t_all) > 0).all()  # in order, no duplicates
    # union == the final reservoir, field by field
    summ = telemetry_summary(spec, final)
    for i, f in enumerate(RES_FIELDS):
        got = np.concatenate([r[f] for r, _ in batches])
        np.testing.assert_array_equal(got, summ["reservoir"][f])


def test_run_chunked_stream_requires_telemetry():
    from fognetsimpp_tpu.core.engine import run_chunked

    spec, state, net, bounds = _build()
    with pytest.raises(ValueError, match="telemetry_stream"):
        run_chunked(
            spec, state, net, bounds, 100,
            telemetry_stream=lambda rows, done: None,
        )


def test_fleet_carries_telemetry_identically_to_vmap():
    """The telemetry carry rides the replica-sharded fleet scan
    bit-identically to the plain vmap path (8-virtual-device mesh)."""
    from fognetsimpp_tpu.parallel import make_mesh, replicate_state
    from fognetsimpp_tpu.parallel.fleet import (
        fleet_busy_fractions,
        run_fleet,
    )
    from fognetsimpp_tpu.parallel.replicas import run_replicated

    spec, state, net, bounds = _build(telemetry=True, horizon=0.2)
    batch = replicate_state(spec, state, 8, seed=3)
    ref = run_replicated(spec, batch, net, bounds)
    got = run_fleet(
        spec, batch, net, bounds, make_mesh(8), donate=False
    )
    for a, b in zip(jax.tree.leaves(ref.telem), jax.tree.leaves(got.telem)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    bf = fleet_busy_fractions(spec, got)
    assert bf.shape == (spec.n_fogs,)
    assert ((bf >= 0) & (bf <= 1)).all()


def test_telemetry_contract_and_phase_registry():
    from fognetsimpp_tpu.core.contracts import (
        PHASE_CONTRACTS,
        check_step_contract,
        check_telemetry_contract,
    )

    assert any(pc.name == "_phase_telemetry" for pc in PHASE_CONTRACTS)
    spec, state, net, bounds = _build(telemetry=True)
    check_telemetry_contract(spec, state)
    check_step_contract(spec, state, net, bounds)
    spec0, state0, _, _ = _build()
    check_telemetry_contract(spec0, state0)


# ----------------------------------------------------------------------
# Plane 2: Perfetto exporter
# ----------------------------------------------------------------------

def _golden_world():
    return smoke.build(
        n_users=2, n_fogs=2, fog_mips=(4000.0, 2000.0),
        send_interval=0.05, horizon=0.4, telemetry=True,
    )


def _no_nonfinite(name):
    raise AssertionError(f"non-RFC-8259 token in trace JSON: {name}")


def test_perfetto_trace_matches_committed_golden(tmp_path):
    from fognetsimpp_tpu.telemetry.timeline import export_trace

    spec, state, net, bounds = _golden_world()
    final, _ = run(spec, state, net, bounds)
    p = export_trace(spec, final, str(tmp_path / "trace.json"))
    # strict round trip: NaN/Infinity tokens are a parse failure here
    got = json.loads(open(p).read(), parse_constant=_no_nonfinite)
    want = json.loads(GOLDEN.read_text(), parse_constant=_no_nonfinite)
    ge, we = got["traceEvents"], want["traceEvents"]
    assert len(ge) == len(we)
    for g, w in zip(ge, we):
        assert (g["name"], g["ph"], g["pid"], g.get("tid")) == (
            w["name"], w["ph"], w["pid"], w.get("tid")
        )
        if g["ph"] == "X":
            assert g["ts"] == pytest.approx(w["ts"], rel=1e-6)
            assert g["dur"] == pytest.approx(w["dur"], rel=1e-6)


def test_perfetto_trace_structure():
    """pid/tid mapping (replica→pid, fog→tid), monotone ts, span
    nesting (queued/service inside the per-fog task span), durations
    finite and non-negative."""
    from fognetsimpp_tpu.telemetry.timeline import build_trace

    spec, state, net, bounds = _golden_world()
    final, _ = run(spec, state, net, bounds)
    trace = build_trace(spec, final)
    ev = trace["traceEvents"]
    spans = [e for e in ev if e["ph"] == "X"]
    assert spans, "no spans exported"
    assert all(e["pid"] == 0 for e in ev)  # single world: one replica
    # fog lanes 0..F-1 plus the broker lane F
    tids = {e["tid"] for e in spans}
    assert tids <= set(range(spec.n_fogs + 1))
    # spans sorted by ts (metadata first)
    ts = [e["ts"] for e in spans]
    assert ts == sorted(ts)
    assert all(np.isfinite(e["dur"]) and e["dur"] >= 0 for e in spans)
    # nesting: every queued/service child lies inside its fog's
    # enclosing task span
    tasks = {}
    for e in spans:
        if e["name"].startswith("task"):
            tasks.setdefault(e["tid"], []).append(e)
    checked = 0
    for e in spans:
        if e["name"] in ("queued", "service"):
            parents = tasks.get(e["tid"], [])
            assert any(
                p["ts"] - 1e-6 <= e["ts"]
                and e["ts"] + e["dur"] <= p["ts"] + p["dur"] + 1e-6
                for p in parents
            ), e
            checked += 1
    assert checked > 0


def test_perfetto_trace_maps_replicas_to_pids():
    from fognetsimpp_tpu.parallel import replicate_state
    from fognetsimpp_tpu.parallel.replicas import run_replicated
    from fognetsimpp_tpu.telemetry.timeline import build_trace

    spec, state, net, bounds = _build(telemetry=True, horizon=0.2)
    batch = replicate_state(spec, state, 2, seed=1)
    final = run_replicated(spec, batch, net, bounds)
    ev = build_trace(spec, final)["traceEvents"]
    assert {e["pid"] for e in ev} == {0, 1}


# ----------------------------------------------------------------------
# Plane 3: OpenMetrics exposition
# ----------------------------------------------------------------------

def test_openmetrics_busy_fraction_matches_sca_json(tmp_path):
    import re

    from fognetsimpp_tpu.runtime.recorder import load_scalars, record_run

    spec, state, net, bounds = _build(telemetry=True, horizon=1.0)
    final, _ = run(spec, state, net, bounds)
    paths = record_run(str(tmp_path), spec, final, scave=False)
    sca = load_scalars(paths["sca"])
    text = open(paths["om"]).read()
    for f in range(spec.n_fogs):
        m = re.search(
            rf'^fns_fog_busy_fraction\{{fog="{f}"\}} (\S+)$',
            text, re.M,
        )
        assert m, f"fog {f} busy fraction missing from OpenMetrics"
        om_val = float(m.group(1))
        sca_val = sca["modules"]["fog"][f]["busy_frac"]
        assert abs(om_val - sca_val) <= 1e-6
    # format lint: the ~20-line checker the CI smoke step runs
    from tools.check_openmetrics import check

    assert check(paths["om"]) == 0


def test_openmetrics_text_is_wellformed_without_telemetry(tmp_path):
    from fognetsimpp_tpu.runtime.recorder import record_run
    from tools.check_openmetrics import check

    spec, state, net, bounds = _build()
    final, _ = run(spec, state, net, bounds)
    paths = record_run(str(tmp_path), spec, final, scave=False)
    text = open(paths["om"]).read()
    assert text.endswith("# EOF\n")
    assert "fns_fog_busy_fraction" not in text  # plane 1 was off
    assert check(paths["om"]) == 0


def test_fleet_openmetrics_written(tmp_path):
    from fognetsimpp_tpu.parallel import make_mesh, replicate_state
    from fognetsimpp_tpu.parallel.fleet import run_fleet
    from fognetsimpp_tpu.runtime.recorder import record_fleet_run
    from tools.check_openmetrics import check

    import re

    spec, state, net, bounds = _build(telemetry=True, horizon=0.2)
    batch = replicate_state(spec, state, 8, seed=0)
    final = run_fleet(spec, batch, net, bounds, make_mesh(8))
    paths = record_fleet_run(str(tmp_path), spec, final)
    text = open(paths["om"]).read()
    # per-replica gauges (second PR-4 follow-up): one sample per
    # (fleet=replica, fog) pair — replicas are NOT averaged away
    for r in range(8):
        for f in range(spec.n_fogs):
            assert re.search(
                rf'^fns_fleet_fog_busy_fraction\{{fleet="{r}",fog="{f}"\}} ',
                text, re.M,
            ), (r, f)
    # ...and they agree with the per-replica host computation
    from fognetsimpp_tpu.parallel.fleet import (
        fleet_busy_fractions_per_replica,
    )

    per = fleet_busy_fractions_per_replica(spec, final)
    assert per.shape == (8, spec.n_fogs)
    m = re.search(
        r'^fns_fleet_fog_busy_fraction\{fleet="3",fog="1"\} (\S+)$',
        text, re.M,
    )
    assert abs(float(m.group(1)) - per[3, 1]) <= 1e-9
    assert check(paths["om"]) == 0


def test_openmetrics_linter_rejects_duplicate_series(tmp_path):
    """The linter extension that came with the labelled fleet gauges:
    two samples sharing (name, label-set) fail the lint."""
    from tools.check_openmetrics import check

    good = tmp_path / "good.om.txt"
    good.write_text(
        '# HELP fns_x x\n# TYPE fns_x gauge\nfns_x{fleet="0",fog="1"} 1\n'
        'fns_x{fleet="1",fog="1"} 2\n# EOF\n'
    )
    assert check(str(good)) == 0
    bad = tmp_path / "bad.om.txt"
    bad.write_text(
        '# HELP fns_x x\n# TYPE fns_x gauge\nfns_x{fleet="0",fog="1"} 1\n'
        'fns_x{fleet="0",fog="1"} 2\n# EOF\n'
    )
    assert check(str(bad)) == 1
    # the r6 metadata requirement: a family without # HELP fails too
    nohelp = tmp_path / "nohelp.om.txt"
    nohelp.write_text("# TYPE fns_x gauge\nfns_x 1\n# EOF\n")
    assert check(str(nohelp)) == 1


def test_cli_telemetry_flags(tmp_path, capsys):
    """--telemetry --trace-out end to end through the launcher."""
    from fognetsimpp_tpu.__main__ import main

    trace = str(tmp_path / "t.json")
    rc = main([
        "--scenario", "smoke", "--telemetry",
        "--set", "spec.horizon=0.3",
        "--trace-out", trace, "--out", str(tmp_path),
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["trace"] == trace
    d = json.loads(open(trace).read(), parse_constant=_no_nonfinite)
    assert d["traceEvents"]
    sca = json.load(open(out["sca"]))
    assert "busy_frac" in sca["modules"]["fog"][0]


def test_profile_helpers_are_safe():
    """profile_trace degrades to a no-op on failure; the dispatch
    histogram measures a warm jitted round trip."""
    from fognetsimpp_tpu.telemetry.profile import (
        measure_dispatch,
        profile_trace,
    )

    with profile_trace(None) as info:
        assert info["active"] is False
    f = jax.jit(lambda x: x + 1)
    hist = measure_dispatch(lambda: int(np.asarray(f(0))), n=4)
    assert hist["n"] == 4
    assert hist["p50_ms"] >= 0
    assert sum(hist["buckets"].values()) == 4
