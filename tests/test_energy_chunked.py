"""Exact energy accounting (the ≤1% energy criterion) + chunked horizons."""
import jax.numpy as jnp
import numpy as np

from fognetsimpp_tpu import run
from fognetsimpp_tpu.core.engine import run_chunked
from fognetsimpp_tpu.runtime import summarize
from fognetsimpp_tpu.scenarios import smoke


def test_energy_matches_exact_message_accounting():
    """Per-node drain == idle·t + tx_J·sent + rx_J·received, exactly.

    The BASELINE criterion is energy within 1% of the event-driven
    baseline; since both models drain per message, agreement reduces to
    message-count accounting, which this pins to machine precision for a
    single-user world (totals == that user's counts).
    """
    spec, state, net, bounds = smoke.build(
        horizon=0.5,
        send_interval=0.05,
        n_users=1,
        n_fogs=2,
        energy_enabled=True,
        energy_capacity_j=1000.0,  # far from both clamps
        idle_power_w=2e-3,
        tx_energy_j=2e-4,
        rx_energy_j=1e-4,
        harvest_power_w=0.0,
        shutdown_frac=0.0,  # never dies
    )
    # only the user participates in the energy model
    has = np.zeros((spec.n_nodes,), bool)
    has[0] = True
    state = state.replace(
        nodes=state.nodes.replace(has_energy=jnp.asarray(has))
    )
    final, _ = run(spec, state, net, bounds)

    t = final.tasks

    def fin(col):
        return int(np.isfinite(np.asarray(col)).sum())

    n_pub = int(np.asarray(final.metrics.n_published))
    n_subs = int(np.asarray(final.users.sub_mask).sum())
    n_tx = 1 + n_subs + n_pub  # Connect + Subscribes + Publishes
    n_rx = (
        1 + n_subs  # Connack + Subacks
        + fin(t.t_ack3) + fin(t.t_ack4_fwd) + fin(t.t_ack4_queued)
        + fin(t.t_ack5) + fin(t.t_ack6)  # every ack is one receive
        + int(np.asarray(final.users.n_delivered).sum())
    )
    expected = (
        1000.0
        - 2e-3 * spec.horizon
        - 2e-4 * n_tx
        - 1e-4 * n_rx
    )
    got = float(np.asarray(final.nodes.energy)[0])
    assert abs(got - expected) < 1e-3, (got, expected, n_tx, n_rx)


def test_run_chunked_bit_identical():
    spec, state, net, bounds = smoke.build(horizon=0.4)
    straight, _ = run(spec, state, net, bounds)
    chunked = run_chunked(spec, state, net, bounds, chunk_ticks=150)
    for name in ("t_create", "t_ack6", "mips_req", "stage"):
        np.testing.assert_array_equal(
            np.asarray(getattr(straight.tasks, name)),
            np.asarray(getattr(chunked.tasks, name)),
            err_msg=name,
        )
    assert int(straight.metrics.n_completed) == int(chunked.metrics.n_completed)


def test_run_chunked_callback_checkpoints(tmp_path):
    from fognetsimpp_tpu.runtime import checkpoint

    spec, state, net, bounds = smoke.build(horizon=0.4)
    saved = []

    def cb(s, tick):
        p = str(tmp_path / f"ck_{tick}.npz")
        checkpoint.save(p, spec, s)
        saved.append((tick, p))

    final = run_chunked(spec, state, net, bounds, chunk_ticks=200, callback=cb)
    assert [t for t, _ in saved] == [200, 400]
    # resuming from the mid-run checkpoint reproduces the final state
    spec2, mid = checkpoint.load(saved[0][1])
    resumed, _ = run(spec2, mid, net, bounds, n_ticks=200)
    np.testing.assert_array_equal(
        np.asarray(final.tasks.t_ack6), np.asarray(resumed.tasks.t_ack6)
    )
    s = summarize(final)
    assert s["n_published"] > 0
