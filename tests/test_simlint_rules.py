"""Golden positive/negative fixture pairs for every simlint rule, plus
the suppression machinery (inline disables + baseline round-trip)."""
from pathlib import Path

import pytest

from tools.simlint.core import lint, write_baseline

FIXTURES = Path(__file__).resolve().parents[1] / "tools" / "simlint" / "fixtures"
ALL_RULES = [f"R{i}" for i in range(1, 15)]


@pytest.mark.parametrize("rid", ALL_RULES)
def test_bad_fixture_detected(rid):
    res = lint([str(FIXTURES / f"{rid.lower()}_bad.py")])
    hits = [f for f in res.findings if f.rule == rid]
    assert hits, f"{rid} did not fire on its bad fixture"


@pytest.mark.parametrize("rid", ALL_RULES)
def test_good_fixture_clean(rid):
    res = lint([str(FIXTURES / f"{rid.lower()}_good.py")])
    assert res.findings == [], "\n".join(f.render() for f in res.findings)


def test_expected_hit_counts():
    """Each deliberately-seeded violation in the bad fixtures is found
    individually (not just 'at least one per file')."""
    expected = {
        # R3: 5 = the two classic captures + the array-static arg + the
        # telemetry-accumulator case (net AND bounds captured: one
        # finding per name)
        "R1": 4, "R2": 2, "R3": 5, "R4": 3, "R5": 2, "R6": 2, "R7": 1,
        "R8": 1,
        # v2 rules (ISSUE 7): each bad fixture seeds exactly two shapes
        # (R9: unbound PartitionSpec axis + unbound collective axis;
        # R10: dtype=f32 count + bool->f32 astype sum; R11: unordered
        # io_callback + ungated debug print; R12: plain reuse + reuse
        # after a known-donating run entry)
        "R9": 2, "R10": 2, "R11": 2, "R12": 2,
        # R13 (ISSUE 13): a direct jnp-flow read + an assignment-alias
        # read of promoted knobs; gate reads in the good fixture stay
        # exempt.  +1 since ISSUE 20: a promoted-knob read inside a
        # shard_map body (the sharded runners' operand-bypass rot)
        "R13": 3,
        # R14 (ISSUE 16): one derived-stream split + one anonymous fold
        # literal; named-constant and index folds in the good fixture
        # stay exempt
        "R14": 2,
    }
    for rid, n in expected.items():
        res = lint([str(FIXTURES / f"{rid.lower()}_bad.py")])
        got = sum(1 for f in res.findings if f.rule == rid)
        assert got == n, f"{rid}: expected {n} findings, got {got}"


def test_dataflow_assignment_tracking(tmp_path):
    """The v2 dataflow layer: tracedness flows through assignments, so
    branching on a DERIVED name fires R2 exactly like branching on the
    parameter would."""
    p = tmp_path / "flow.py"
    p.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    y = x * 2\n"
        "    z = jnp.cumsum(y)\n"
        "    if z[0] > 0:\n"
        "        return z\n"
        "    return -z\n"
    )
    res = lint([str(p)])
    assert [f.rule for f in res.findings] == ["R2"]


def test_dataflow_host_result_stops_flow(tmp_path):
    """Host-materializing calls cut the traced flow: a branch on
    `jax.device_get(...)`'s result is a HOST branch (outside jit), not
    an R2 — the v1 false-positive class the flow layer removes."""
    p = tmp_path / "host.py"
    p.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def drive(x):\n"
        "    total = jax.device_get(jnp.sum(x))\n"
        "    if total > 0:\n"
        "        return total\n"
        "    return 0.0\n"
        "def probe(state):\n"
        "    n = len(state)\n"
        "    if n > 4:\n"
        "        return n\n"
        "    return 0\n"
    )
    res = lint([str(p)])
    assert res.findings == [], "\n".join(f.render() for f in res.findings)


def test_dataflow_container_store_is_not_a_rebind(tmp_path):
    """`views['k'] = jnp...` mutates a container; it must not re-type
    the container's NAME as traced (the fused-views pack idiom)."""
    from tools.simlint.core import ModuleInfo
    import ast as _ast

    src = (
        "import jax.numpy as jnp\n"
        "def pack(spec, views: dict):\n"
        "    views['q'] = jnp.zeros((4,))\n"
        "    if spec.fused:\n"
        "        return views\n"
        "    return None\n"
    )
    mod = ModuleInfo("mem.py", "mem.py", src)
    fn = mod.functions[0]
    assert "views" not in mod.traced_env(fn)


def test_inline_suppression(tmp_path):
    src = (FIXTURES / "r2_bad.py").read_text()
    patched = src.replace(
        "if x > lo:", "if x > lo:  # simlint: disable=R2 -- fixture"
    ).replace(
        "while x < lo:", "while x < lo:  # simlint: disable=all"
    )
    p = tmp_path / "suppressed.py"
    p.write_text(patched)
    res = lint([str(p)])
    assert res.findings == []
    assert res.inline_suppressed == 2


def test_inline_suppression_comment_block_above(tmp_path):
    p = tmp_path / "block.py"
    p.write_text(
        "import jax\n\n\n"
        "# this capture is deliberate: the table is tiny and constant\n"
        "# simlint: disable=R2 -- reviewed\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"
    )
    # the marker sits above the `def`, not above the offending `if`:
    # it must NOT suppress (suppressions anchor to the finding line)
    res = lint([str(p)])
    assert len(res.findings) == 1
    p.write_text(
        "import jax\n\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    # reviewed: host fallback path\n"
        "    # simlint: disable=R2 -- reviewed\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"
    )
    res = lint([str(p)])
    assert res.findings == [] and res.inline_suppressed == 1


def test_device_classification_is_scan_root_independent():
    """core/engine.py must get its blanket device classification no
    matter where the scan was rooted — `.`-rooted or subdir-rooted scans
    must not silently lose R1/R2/R4/R5 coverage of engine helpers."""
    from tools.simlint.core import ModuleInfo

    engine = (
        Path(__file__).resolve().parents[1]
        / "fognetsimpp_tpu" / "core" / "engine.py"
    )
    src = engine.read_text()
    for relpath in (
        "core/engine.py",                    # scanned from the package
        "fognetsimpp_tpu/core/engine.py",    # scanned from the repo root
        "engine.py",                         # scanned from core/ itself
    ):
        mod = ModuleInfo(str(engine), relpath, src)
        assert mod.blanket_device, f"lost blanket device at {relpath!r}"


def test_baseline_counts_do_not_cover_new_copies(tmp_path):
    """A grandfathered finding suppresses exactly its own multiplicity:
    a future textually-identical violation in the same file stays
    fatal."""
    p = tmp_path / "mod.py"
    body = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x.sum())\n"
    )
    p.write_text(body)
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), lint([str(p)]).findings)
    assert lint([str(p)], baseline_path=str(bl)).findings == []
    # paste a second copy of the same offending line into the same file
    p.write_text(
        body + "@jax.jit\ndef g(x):\n    return float(x.sum())\n"
    )
    res = lint([str(p)], baseline_path=str(bl))
    assert len(res.findings) == 1 and len(res.baselined) == 1


def test_baseline_roundtrip(tmp_path):
    bad = FIXTURES / "r1_bad.py"
    res = lint([str(bad)])
    assert res.findings
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), res.findings)
    res2 = lint([str(bad)], baseline_path=str(bl))
    assert res2.findings == []
    assert len(res2.baselined) == len(res.findings)
    # a NEW violation is still fatal with the old baseline in place
    p = tmp_path / "new_violation.py"
    p.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x.sum())\n"
    )
    res3 = lint([str(p)], baseline_path=str(bl))
    assert len(res3.findings) == 1 and res3.findings[0].rule == "R1"
