"""Tier-1 gate: the simlint static pass over the real package must be
clean (zero unsuppressed findings), and the CLI contract holds."""
from pathlib import Path

from tools.simlint.__main__ import main as simlint_main
from tools.simlint.core import lint
from tools.simlint.rules import default_rules

ROOT = Path(__file__).resolve().parents[1]
BASELINE = ROOT / "tools" / "simlint" / "baseline.json"


def test_package_is_lint_clean():
    res = lint(
        [str(ROOT / "fognetsimpp_tpu")], baseline_path=str(BASELINE)
    )
    assert res.findings == [], (
        "simlint found unsuppressed hazards:\n"
        + "\n".join(f.render() for f in res.findings)
    )


def test_cli_exits_zero_on_clean_tree(capsys):
    assert simlint_main([str(ROOT / "fognetsimpp_tpu")]) == 0
    capsys.readouterr()


def test_cli_exits_nonzero_on_findings(capsys):
    bad = ROOT / "tools" / "simlint" / "fixtures" / "r1_bad.py"
    assert simlint_main(["--no-baseline", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "R1" in out


def test_every_rule_documented():
    rules_md = (ROOT / "tools" / "simlint" / "RULES.md").read_text()
    for r in default_rules():
        assert f"## {r.id}" in rules_md, f"{r.id} missing from RULES.md"


def test_engine_phase_registry_matches_contracts():
    """The R8 static check and the runtime registry agree: every
    `_phase_*` def in the engine has a PhaseContract entry (this is what
    keeps a future phase from shipping uncontracted)."""
    import ast

    from fognetsimpp_tpu.core.contracts import PHASE_CONTRACTS

    engine = (ROOT / "fognetsimpp_tpu" / "core" / "engine.py").read_text()
    phase_defs = {
        n.name
        for n in ast.walk(ast.parse(engine))
        if isinstance(n, ast.FunctionDef) and n.name.startswith("_phase_")
    }
    registered = {pc.name for pc in PHASE_CONTRACTS}
    assert phase_defs == registered, (
        f"unregistered: {phase_defs - registered}; "
        f"stale: {registered - phase_defs}"
    )
