"""Wired-link DropTailQueue analog (spec.wired_queue_enabled).

The reference runs a frameCapacity=40 DropTailQueue on every eth
interface (``/root/reference/simulations/testing/wireless5.ini:72-73``) —
under load, wired links delay and drop.  These tests drive the batched
analog past saturation (delays grow, drops counted, publishes lost) and —
validating the deliberate-deviation ledger in PARITY.md — confirm that a
committed-scenario-scale load never touches the queue (backlog stays 0,
delays identical with the feature on or off).
"""
import numpy as np

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.scenarios import smoke


def _build(enabled, n_users, interval, horizon=0.2, rate=100e6):
    return smoke.build(
        n_users=n_users,
        n_fogs=4,
        fog_mips=(20000.0, 30000.0, 25000.0, 35000.0),
        send_interval=interval,
        horizon=horizon,
        dt=1e-3,
        max_sends_per_user=int(horizon / interval) + 4,
        arrival_window=2048,
        queue_capacity=256,
        wired_queue_enabled=enabled,
        link_rate_bps=rate,
    )


def test_saturated_link_delays_and_drops():
    """600 users x 1 ms publishes push ~0.6 Mframe/s through the broker's
    100 Mbps egress (capacity ~97k frames/s): the DropTail queue must
    saturate — backlog pinned at frameCapacity, drops counted, publishes
    lost — and surviving acks must arrive later than in the uncongested
    world."""
    spec, state, net, bounds = _build(True, n_users=600, interval=1e-3)
    final, _ = run(spec, state, net, bounds)
    m = final.metrics
    assert int(m.n_link_drops) > 1000, int(m.n_link_drops)
    # the counter reaches the .sca scalar roll-up too
    from fognetsimpp_tpu.runtime import summarize

    assert summarize(final)["n_link_drops"] == int(m.n_link_drops)
    # tail-dropped publishes enter Stage.LOST (offered ~6x capacity, so a
    # large fraction of the 120k publishes dies at the queue; the backlog
    # itself oscillates — drops collapse traffic, the queue drains, load
    # resumes — so the *counters*, not the end-state backlog, are the
    # saturation witness)
    assert int(m.n_lost) > 10_000, int(m.n_lost)

    # surviving forwarded-acks are measurably delayed vs the same world
    # without queueing
    spec0, state0, net0, bounds0 = _build(False, n_users=600, interval=1e-3)
    base, _ = run(spec0, state0, net0, bounds0)

    def h1(f):
        t0 = np.asarray(f.tasks.t_create, np.float64)
        a4 = np.asarray(f.tasks.t_ack4_fwd, np.float64)
        ok = np.isfinite(t0) & np.isfinite(a4)
        return a4[ok] - t0[ok]

    # DropTail bounds the queueing delay at frameCapacity/rate (~0.41 ms
    # per hop): the mean rises measurably and the worst survivor carries
    # at least half a full-queue serialization delay
    q_full = spec.link_queue_frames * spec.task_bytes * 8 / spec.link_rate_bps
    assert h1(final).mean() > h1(base).mean() * 1.05
    assert h1(final).max() > h1(base).max() + 0.5 * q_full


def test_committed_scenario_loads_never_saturate():
    """PARITY.md's claim, now tested: at the reference scenarios' scale
    (tens of users, 50 ms publish interval) the wired queues stay empty
    and the model is a no-op — same decisions, same ack times."""
    spec, state, net, bounds = _build(True, n_users=10, interval=0.05)
    final, _ = run(spec, state, net, bounds)
    assert int(final.metrics.n_link_drops) == 0
    assert int(final.metrics.n_lost) == 0
    assert float(np.asarray(final.nodes.link_backlog).max()) == 0.0

    spec0, state0, net0, bounds0 = _build(False, n_users=10, interval=0.05)
    base, _ = run(spec0, state0, net0, bounds0)
    np.testing.assert_array_equal(
        np.asarray(final.tasks.fog), np.asarray(base.tasks.fog)
    )
    a_on = np.asarray(final.tasks.t_ack6)
    a_off = np.asarray(base.tasks.t_ack6)
    both = np.isfinite(a_on) & np.isfinite(a_off)
    np.testing.assert_allclose(a_on[both], a_off[both], rtol=1e-6)
