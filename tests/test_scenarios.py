"""Scenario-ladder integration tests (SURVEY.md §4 rebuilt).

Mirrors the reference's validation strategy — a ladder of increasingly
featureful worlds — with the assertions the reference never had: task
conservation, observed handover, energy-driven churn with revival.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.net.topology import associate
from fognetsimpp_tpu.runtime import extract_signals, summarize
from fognetsimpp_tpu.scenarios import example, wireless

TERMINAL = (Stage.DONE, Stage.NO_RESOURCE, Stage.DROPPED, Stage.REJECTED,
            Stage.LOST)
IN_FLIGHT = (Stage.PUB_INFLIGHT, Stage.TASK_INFLIGHT, Stage.QUEUED,
             Stage.RUNNING, Stage.LOCAL_RUN)


def _conserved(final):
    """Every published task is in exactly one live or terminal stage."""
    s = summarize(final)
    accounted = sum(s[f"stage_{st.name.lower()}"] for st in TERMINAL + IN_FLIGHT)
    assert accounted == s["n_published"], s
    return s


def test_wireless_smoke_rung():
    spec, state, net, bounds = wireless.wireless(horizon=1.0)
    final, _ = run(spec, state, net, bounds)
    s = _conserved(final)
    assert s["n_scheduled"] > 0 and s["n_completed"] >= 1


def test_wireless2_circle_users():
    spec, state, net, bounds = wireless.wireless2(horizon=2.0, dt=5e-3)
    final, _ = run(spec, state, net, bounds)
    s = _conserved(final)
    assert s["n_scheduled"] > 0
    # circle users moved along their orbit; linear users moved +x
    p0 = np.asarray(state.nodes.pos)
    p1 = np.asarray(final.nodes.pos)
    assert np.linalg.norm(p1[2] - p0[2]) > 10.0  # circling user 2
    assert (p1[3, 0] - p0[3, 0]) > 10.0  # linear user moved +x


def test_wireless3_parametric_chain():
    # the NED for-loop topology scales with numb (wireless3.ned:81-85)
    spec6, *_ = wireless.wireless3(numb=6, numb_users=3, horizon=1.0)
    assert spec6.n_aps == 6 and spec6.n_users == 3
    spec, state, net, bounds = wireless.wireless3(horizon=2.0, dt=5e-3)
    final, _ = run(spec, state, net, bounds)
    s = _conserved(final)
    assert s["n_scheduled"] > 0


def test_wireless4_handover():
    spec, state, net, bounds = wireless.wireless4(horizon=8.0, dt=5e-3)
    final, _ = run(spec, state, net, bounds)
    s = _conserved(final)
    # users rolled +x at 20 mps for 8 s = 160 m across 100 m-radius cells:
    # their nearest-AP association must have changed (emergent handover)
    a0 = associate(net, state.nodes.pos, state.nodes.alive,
                   broker=spec.broker_index)
    a1 = associate(net, final.nodes.pos, final.nodes.alive,
                   broker=spec.broker_index)
    assoc0 = np.asarray(a0.assoc)[: spec.n_users]
    assoc1 = np.asarray(a1.assoc)[: spec.n_users]
    assert (assoc0 != assoc1).any(), (assoc0, assoc1)
    # and tasks published after the handover still complete
    assert s["n_completed"] >= 1


def test_wireless5_energy_churn():
    spec, state, net, bounds = wireless.wireless5(
        horizon=60.0, dt=0.01, record_tick_series=True
    )
    final, series = run(spec, state, net, bounds)
    s = _conserved(final)
    n_alive = np.asarray(series["n_alive"])
    n_nodes = spec.n_nodes
    # nodes die (battery below 10%) ...
    assert n_alive.min() < n_nodes, "no node ever shut down"
    # ... and revive (harvester refills past 50%)
    died_at = int(np.argmin(n_alive))
    assert n_alive[died_at:].max() > n_alive.min(), "no node ever restarted"
    # dead users stop publishing, the world keeps serving the rest
    assert s["n_completed"] > 0
    # energy stays within [0, capacity]
    e = np.asarray(final.nodes.energy)
    cap = np.asarray(final.nodes.energy_capacity)
    assert (e >= 0).all() and (e <= cap + 1e-9).all()


def test_paper_topology():
    spec, state, net, bounds = wireless.paper(horizon=2.0, dt=5e-3)
    assert spec.n_users == 18 and spec.n_fogs == 4 and spec.n_aps == 7
    # the static sensor is wired: attached and not wireless
    assert not bool(np.asarray(net.is_wireless)[spec.n_users - 1])
    final, _ = run(spec, state, net, bounds)
    s = _conserved(final)
    assert s["n_scheduled"] > 0


def test_example_matches_committed_trace():
    """The shipped demo analog vs simulations/example/results/General-0.vec.

    Committed ground truth: 67 publishes sent, 52 delay samples recorded,
    delay mean 0.502 / min 0.401 / max 0.9814.  r5: mapping each
    committed sample to its creation index shows the run is
    deterministic — creations 0..13 buffered and drained, 14..19 (the
    pre-link-up pending-queue overflow) all lost, 20..57 at a constant
    0.4015 s transit with zero loss, >= 58 still in flight at the 3.35 s
    horizon.  The mechanistic warm-up buffer (spec.link_buffer_frames)
    reproduces all four statistics on EVERY seed — no stochastic loss
    doing the bookkeeping (VERDICT r4 weak item 6 closed).
    """
    spec, state, net, bounds = example.build()
    final, _ = run(spec, state, net, bounds)
    sig = extract_signals(final)
    d = sig["delay"] / 1e3  # ms -> s
    s = summarize(final)
    assert s["n_published"] == 66  # 67 in the 3.35 s reference run
    assert d.size == 52  # exactly the committed sample count
    assert s["n_lost"] == 6  # exactly creations 14..19 (buffer overflow)
    assert abs(d.mean() - 0.502) < 0.005, d.mean()
    assert abs(d.min() - 0.401) < 0.005, d.min()
    assert abs(d.max() - 0.9814) < 0.005, d.max()
    # v2 semantics actually exercised: broker-local releases and pool-fog
    # expiries both completed tasks with status-6 acks (the shared-timer
    # leak leaves a few locals unreleased at the horizon, as in the
    # reference — requests[] grows, App. B item 7)
    assert s["n_completed"] >= 30
    assert s["n_local"] > 0 and s["n_scheduled"] > 0
    assert np.isfinite(sig["task_time"]).all() and sig["task_time"].size >= 30
    # the trace statistics are seed-independent: only the MIPSRequired
    # stream (offload split) varies with the seed
    spec2, state2, net2, bounds2 = example.build(seed=3)
    final2, _ = run(spec2, state2, net2, bounds2)
    d2 = extract_signals(final2)["delay"] / 1e3
    assert d2.size == 52
    np.testing.assert_allclose(np.sort(d2), np.sort(d), rtol=1e-6)


def test_example_per_fog_traffic_split():
    """Second calibration anchor (r3): the committed run's per-fog app
    traffic split — ComputeBroker1 received every forwarded task (5
    "packets received" = 1 Connack + 4 tasks) while ComputeBroker2-5 got
    only their Connack (``example/results/General-0.sca``).

    The mechanism is the v2 hybrid broker (``BrokerBaseApp2.cc:181``):
    publishes run on the broker's own 1000-MIPS pool; the shared
    release-timer leak during the sub-requiredTime warm-up burst exhausts
    the pool and the overflow offloads via the last-wins MAX_MIPS scan —
    with every fog advertising equal MIPS the winner is the FIRST
    registered fog.  Same calibration constants as the delay test (no
    per-test refit).  The committed run's exact count (4) is one draw of
    the reference's wall-clock-seeded MIPS stream — see
    test_example_offload_count_within_reference_mechanism for the
    distributional gate; late overflow diverts to the LAST fog once
    CB1's reduced pool advert lands — the same scan mechanism, so the
    middle fogs stay at exactly zero either way.
    """
    spec, state, net, bounds = example.build()
    final, _ = run(spec, state, net, bounds)
    used = np.isfinite(np.asarray(final.tasks.t_create))
    fog = np.asarray(final.tasks.fog)[used]
    per_fog_tasks = np.bincount(fog[fog >= 0], minlength=5)
    # the committed run's signature: CB1 dominates, CB2-4 receive nothing
    assert per_fog_tasks[0] >= 4, per_fog_tasks
    assert per_fog_tasks[0] == per_fog_tasks.max()
    assert (per_fog_tasks[1:4] == 0).all(), per_fog_tasks
    # overflow is the exception, local execution the rule (48/52 local in
    # the committed run)
    n_local = int(final.metrics.n_local)
    assert n_local > per_fog_tasks.sum(), (n_local, per_fog_tasks)
    # per-fog app "packets received" analog: Connack + delivered tasks
    received = 1 + per_fog_tasks
    assert received[0] > received[1]
    assert (received[1:4] == 1).all()


# The committed demo run's 52 broker-arrival times (delay:vector 1093 of
# simulations/example/results/General-0.vec): the 7-packet warm-up burst
# (gaps 4-10 ms, two interleaved creation streams), a 50 ms backlog
# trickle, then steady 50 ms arrivals.
_COMMITTED_ARRIVALS = [
    1.0414, 1.0455, 1.0519, 1.0555, 1.0616, 1.0655, 1.0755, 1.1115,
    1.1617, 1.2116, 1.2617, 1.3114, 1.3614, 1.4116, 1.4616, 1.5115,
    1.5616, 1.6117, 1.6615, 1.7117, 1.7617, 1.8117, 1.8617, 1.9117,
    1.9614, 2.0114, 2.0615, 2.1115, 2.1615, 2.2116, 2.2615, 2.3116,
    2.3616, 2.4114, 2.4618, 2.5116, 2.5616, 2.6115, 2.6615, 2.7117,
    2.7617, 2.8114, 2.8617, 2.9118, 2.9615, 3.0115, 3.0617, 3.1115,
    3.1615, 3.2115, 3.2614, 3.3114,
]


def _reference_v2_offload_distribution(n_seeds=200, rt=0.01, pool0=1000.0):
    """The reference v2 broker mechanism replayed on the COMMITTED arrival
    times with random MIPSRequired draws (the reference used wall-clock
    ``srand``, so its exact stream is unobservable): shared release timer,
    cancel-on-accept, one insertion-order release per firing, offload
    stores without debit (BrokerBaseApp2.cc:181-312)."""
    import numpy as np

    offs = []
    for seed in range(n_seeds):
        rng = np.random.default_rng(seed)
        m = rng.integers(200, 901, len(_COMMITTED_ARRIVALS)).astype(float)
        pool, timer, reqs, n_off = pool0, None, [], 0
        for i, t in enumerate(_COMMITTED_ARRIVALS):
            if timer is not None and timer <= t:
                ft, timer = timer, None
                for r in reqs:
                    if r[2] and r[0] + rt < ft:
                        pool += r[1]
                        r[2] = False
                        break
            reqs.append([t, m[i], True])  # stored on BOTH branches
            if m[i] < pool:
                pool -= m[i]
                timer = t + rt  # cancelEvent + scheduleAt
            else:
                n_off += 1
        offs.append(n_off)
    return np.asarray(offs)


def test_example_offload_count_within_reference_mechanism():
    """The committed run's "ComputeBroker1 received 4 tasks" is ONE draw
    of the reference's wall-clock-seeded MIPS stream.  Replaying the v2
    mechanism on the committed arrival times across 200 seeds gives the
    distribution that rand() could have produced (min 4 — the committed
    run sits at its lucky edge — median ~12, p95 ~45); the engine's own
    offload count must fall inside it, or the leak dynamics are wrong.
    """
    dist = _reference_v2_offload_distribution()
    assert dist.min() == 4  # the committed run is the distribution's edge
    spec, state, net, bounds = example.build()
    final, _ = run(spec, state, net, bounds)
    fog = np.asarray(final.tasks.fog)
    n_off = int((fog >= 0).sum())
    lo, hi = int(dist.min()), int(np.percentile(dist, 95))
    assert lo <= n_off <= hi, (n_off, lo, hi, np.median(dist))
