"""Unit tests for the batched FIFO ring-buffer ops."""
import jax
import jax.numpy as jnp
import numpy as np

from fognetsimpp_tpu.ops.queues import (
    NO_TASK,
    batched_enqueue,
    batched_pop,
    plan_arrivals,
)


def test_plan_arrivals_ranks_and_assignment():
    # 6 tasks, 2 fogs. tasks 0,2,4 -> fog 0; 1,3 -> fog 1; 5 masked out
    mask = jnp.array([1, 1, 1, 1, 1, 0], bool)
    fog = jnp.array([0, 1, 0, 1, 0, 0], jnp.int32)
    t = jnp.array([0.3, 0.1, 0.1, 0.2, 0.2, 0.0], jnp.float32)
    idle = jnp.array([True, False])
    plan = plan_arrivals(mask, fog, t, 2, idle)
    # fog0 arrival order by time: task2 (0.1), task4 (0.2), task0 (0.3)
    np.testing.assert_array_equal(np.asarray(plan.rank)[[2, 4, 0]], [0, 1, 2])
    # fog1 order: task1 (0.1), task3 (0.2)
    np.testing.assert_array_equal(np.asarray(plan.rank)[[1, 3]], [0, 1])
    # only fog0 is idle -> gets its first arrival, fog1 gets none
    np.testing.assert_array_equal(np.asarray(plan.assign_task), [2, NO_TASK])
    np.testing.assert_array_equal(np.asarray(plan.counts), [3, 2])


def test_plan_arrivals_tie_breaks_by_task_id():
    mask = jnp.ones((3,), bool)
    fog = jnp.zeros((3,), jnp.int32)
    t = jnp.array([0.5, 0.5, 0.5], jnp.float32)  # simultaneous
    plan = plan_arrivals(mask, fog, t, 1, jnp.array([True]))
    assert int(plan.assign_task[0]) == 0  # lowest id wins, like FIFO insert
    np.testing.assert_array_equal(np.asarray(plan.rank), [0, 1, 2])


def test_enqueue_then_pop_fifo_order():
    F, Q, T = 2, 4, 6
    queue = jnp.full((F, Q), NO_TASK, jnp.int32)
    q_head = jnp.zeros((F,), jnp.int32)
    q_len = jnp.zeros((F,), jnp.int32)
    mask = jnp.array([1, 1, 1, 0, 1, 0], bool)
    fog = jnp.array([0, 0, 1, 0, 0, 0], jnp.int32)
    rank = jnp.array([0, 1, 0, -1, 2, -1], jnp.int32)
    queue, q_len, ok, drops = batched_enqueue(queue, q_head, q_len, mask, fog, rank)
    np.testing.assert_array_equal(np.asarray(q_len), [3, 1])
    assert bool(jnp.all(ok == mask))
    assert int(drops.sum()) == 0

    # pop fog0 twice -> tasks 0 then 1
    t1, q_head, q_len = batched_pop(queue, q_head, q_len, jnp.array([True, False]))
    np.testing.assert_array_equal(np.asarray(t1), [0, NO_TASK])
    t2, q_head, q_len = batched_pop(queue, q_head, q_len, jnp.array([True, True]))
    np.testing.assert_array_equal(np.asarray(t2), [1, 2])
    np.testing.assert_array_equal(np.asarray(q_len), [1, 0])
    t3, q_head, q_len = batched_pop(queue, q_head, q_len, jnp.array([True, True]))
    np.testing.assert_array_equal(np.asarray(t3), [4, NO_TASK])


def test_enqueue_overflow_drops():
    F, Q = 1, 2
    queue = jnp.full((F, Q), NO_TASK, jnp.int32)
    q_head = jnp.zeros((F,), jnp.int32)
    q_len = jnp.zeros((F,), jnp.int32)
    mask = jnp.ones((4,), bool)
    fog = jnp.zeros((4,), jnp.int32)
    rank = jnp.arange(4, dtype=jnp.int32)
    queue, q_len, ok, drops = batched_enqueue(queue, q_head, q_len, mask, fog, rank)
    assert int(q_len[0]) == 2
    assert int(drops[0]) == 2
    np.testing.assert_array_equal(np.asarray(ok), [True, True, False, False])


def test_ring_wraparound():
    F, Q = 1, 3
    queue = jnp.full((F, Q), NO_TASK, jnp.int32)
    q_head = jnp.array([2], jnp.int32)  # head mid-ring
    q_len = jnp.array([1], jnp.int32)
    queue = queue.at[0, 2].set(7)
    mask = jnp.array([True, True], bool)
    fog = jnp.zeros((2,), jnp.int32)
    rank = jnp.array([0, 1], jnp.int32)
    queue, q_len, ok, _ = batched_enqueue(queue, q_head, q_len, mask, fog, rank)
    assert int(q_len[0]) == 3
    order = []
    for _ in range(3):
        t, q_head, q_len = batched_pop(queue, q_head, q_len, jnp.array([True]))
        order.append(int(t[0]))
    assert order == [7, 0, 1]


def test_ops_jit_compile():
    f = jax.jit(lambda m, g, t, i: plan_arrivals(m, g, t, 4, i))
    m = jnp.ones((8,), bool)
    g = jnp.arange(8, dtype=jnp.int32) % 4
    t = jnp.arange(8, dtype=jnp.float32)
    f(m, g, t, jnp.ones((4,), bool))  # must trace without error


def test_full_fog_fast_drop_bit_identical():
    """The dense full-ring tail-drop fast path produces bit-identical
    results to the purely compacted path on a saturated world (tiny
    rings force sustained overflow)."""
    import jax
    import numpy as np

    import fognetsimpp_tpu.core.engine as E
    from fognetsimpp_tpu import run
    from fognetsimpp_tpu.scenarios import smoke

    spec, state, net, bounds = smoke.build(
        horizon=0.5, send_interval=0.005, dt=1e-3, n_users=8, n_fogs=2,
        fog_mips=(2000.0, 3000.0), queue_capacity=2, start_time_max=0.01,
    )
    fin_fast, _ = run(spec, state, net, bounds)
    assert int(fin_fast.metrics.n_dropped) > 50  # overflow really happened

    old = E._FAST_DROP_MAX_F
    E._FAST_DROP_MAX_F = 0
    try:
        fin_slow, _ = run(spec, state, net, bounds)
    finally:
        E._FAST_DROP_MAX_F = old

    for a, b in zip(
        jax.tree_util.tree_leaves(fin_fast),
        jax.tree_util.tree_leaves(fin_slow),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
