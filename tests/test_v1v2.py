"""v1/v2 application-generation semantics: POOL fogs, LOCAL_FIRST, MAX_MIPS.

Round-1 exported these enums without implementing them (VERDICT items 5/8/9/
11); these tests pin the now-live semantics to the reference:
``ComputeBrokerApp2.cc:258-310`` (pool accept/reject/release),
``BrokerBaseApp.cc:160-260`` (local-first + the buggy max-MIPS offload scan).
"""
import jax.numpy as jnp
import numpy as np

from fognetsimpp_tpu import BugCompat, FogModel, Policy, Stage, run
from fognetsimpp_tpu.scenarios import smoke


def _pool_world(**kw):
    kw.setdefault("n_users", 2)
    kw.setdefault("n_fogs", 3)
    kw.setdefault("fog_mips", (1000.0, 3000.0, 2000.0))
    kw.setdefault("horizon", 0.4)
    kw.setdefault("send_interval", 0.05)
    kw.setdefault("fog_model", int(FogModel.POOL))
    kw.setdefault("policy", int(Policy.MAX_MIPS))
    kw.setdefault("adv_periodic", True)
    kw.setdefault("adv_on_completion", False)
    kw.setdefault("app_gen", 2)
    return smoke.build(**kw)


def test_pool_accept_and_release():
    """Pool tasks run concurrently for requiredTime then refund the pool."""
    spec, state, net, bounds = _pool_world()
    final, _ = run(spec, state, net, bounds)
    stage = np.asarray(final.tasks.stage)
    done = stage == int(Stage.DONE)
    assert done.sum() > 0
    # service duration is exactly requiredTime (ComputeBrokerApp2.cc:275:
    # expiry = now + requiredTime, independent of MIPS rating)
    svc = (
        np.asarray(final.tasks.t_complete)[done]
        - np.asarray(final.tasks.t_service_start)[done]
    )
    np.testing.assert_allclose(svc, spec.required_time, rtol=1e-4)
    # at quiescence every accepted task has been released: pool == rated MIPS
    in_flight = np.isin(
        stage, [int(Stage.RUNNING), int(Stage.QUEUED), int(Stage.TASK_INFLIGHT)]
    ).sum()
    if in_flight == 0:
        np.testing.assert_allclose(
            np.asarray(final.fogs.pool_avail), np.asarray(final.fogs.mips)
        )
    # v2 completions reach the client through the broker relay
    assert np.isfinite(np.asarray(final.tasks.t_ack6)[done]).all()


def test_pool_rejects_oversized_tasks():
    """A task bigger than the whole pool is rejected (strict <,
    ComputeBrokerApp2.cc:269), and the broker ignores the TaskAck."""
    spec, state, net, bounds = _pool_world(
        fog_mips=(500.0, 500.0, 500.0),
        fixed_mips_required=800,  # > every pool -> every arrival rejected
        bug_compat=BugCompat(v1_max_scan=False),
    )
    final, _ = run(spec, state, net, bounds)
    stage = np.asarray(final.tasks.stage)
    # the broker-side guard (MIPSRequired < winner's advertised MIPS,
    # BrokerBaseApp.cc:244) already refuses to send once adverts arrive;
    # anything sent before the first advert lands is rejected at the fog
    assert (stage[stage != int(Stage.UNUSED)] != int(Stage.DONE)).all()
    assert int(final.metrics.n_rejected) > 0
    assert int(final.metrics.n_completed) == 0


def test_v1_max_scan_bug_compat():
    """The faithful v1 scan picks the LAST fog whose MIPS beats fog 0's
    (BrokerBaseApp.cc:232-236: `temp` is never updated), not the true max."""
    spec, state, net, bounds = _pool_world(
        fog_mips=(1000.0, 3000.0, 2000.0),
        fixed_mips_required=100,
        horizon=0.3,
    )
    final, _ = run(spec, state, net, bounds)
    fog = np.asarray(final.tasks.fog)
    sent = fog >= 0
    assert sent.any()
    # skip decisions made before the first advertisement arrived (view
    # MIPS all zero -> winner falls back to fog 0)
    t_ab = np.asarray(final.tasks.t_at_broker)
    informed = sent & (t_ab > 0.05)
    # buggy scan: last fog with MIPS > 1000 is fog 2 (2000), not fog 1 (3000)
    assert (fog[informed] == 2).all()

    spec2, state2, net2, bounds2 = _pool_world(
        fog_mips=(1000.0, 3000.0, 2000.0),
        fixed_mips_required=100,
        horizon=0.3,
        bug_compat=BugCompat(v1_max_scan=False),
    )
    final2, _ = run(spec2, state2, net2, bounds2)
    fog2 = np.asarray(final2.tasks.fog)
    informed2 = (fog2 >= 0) & (np.asarray(final2.tasks.t_at_broker) > 0.05)
    assert (fog2[informed2] == 1).all()  # true argmax


def test_local_first_runs_small_tasks_on_broker():
    """LOCAL_FIRST (v1): tasks with MIPSRequired < pool run locally with a
    status-3 ack and a direct status-6 on expiry (BrokerBaseApp.cc:196-224,
    369-394); the pool is debited and refunded."""
    spec, state, net, bounds = _pool_world(
        policy=int(Policy.LOCAL_FIRST),
        broker_mips=10000.0,
        fixed_mips_required=400,
        horizon=0.3,
    )
    final, _ = run(spec, state, net, bounds)
    stage = np.asarray(final.tasks.stage)
    created = np.isfinite(np.asarray(final.tasks.t_create))
    # pool 10000 >> 400: everything runs locally
    assert int(final.metrics.n_local) == created.sum() > 0
    done = stage == int(Stage.DONE)
    assert done.sum() > 0
    assert np.isfinite(np.asarray(final.tasks.t_ack3)[done]).all()
    assert np.isfinite(np.asarray(final.tasks.t_ack6)[done]).all()
    # local run takes exactly requiredTime on the broker
    svc = (
        np.asarray(final.tasks.t_complete)[done]
        - np.asarray(final.tasks.t_service_start)[done]
    )
    np.testing.assert_allclose(svc, spec.required_time, rtol=1e-4)
    # pool refunded at quiescence (local_pool_leak defaults False)
    if (stage == int(Stage.LOCAL_RUN)).sum() == 0:
        np.testing.assert_allclose(float(final.broker.local_pool), 10000.0)


def test_local_pool_leak_bug_compat():
    """With the faithful leak (BrokerBaseApp.cc:208 commented out) the
    broker pool only ever shrinks, eventually pushing tasks to offload."""
    spec, state, net, bounds = _pool_world(
        policy=int(Policy.LOCAL_FIRST),
        broker_mips=1000.0,
        fixed_mips_required=400,
        horizon=0.3,
        bug_compat=BugCompat(local_pool_leak=True),
    )
    final, _ = run(spec, state, net, bounds)
    # 1000 -> two local runs (400+400), then pool=200 < 400 forever
    assert int(final.metrics.n_local) == 2
    assert float(final.broker.local_pool) <= 200.0 + 1e-6


def test_v2_release_fire_between_same_tick_arrivals():
    """ADVICE r3: a pending release whose fire time sits BETWEEN two
    same-tick arrivals fires in event order — the later local accept
    cannot cancel an already-fired timer (BrokerBaseApp2.cc:221-224
    cancelEvent only removes a scheduled message).  The engine must both
    consume that firing (one stored request released) and install the
    accept's reschedule."""
    import jax.numpy as jnp

    from fognetsimpp_tpu.core.engine import make_step
    from fognetsimpp_tpu.net.mobility import default_bounds
    from fognetsimpp_tpu.net.topology import wired_star
    from fognetsimpp_tpu.spec import WorldSpec
    from fognetsimpp_tpu.state import init_state

    spec = WorldSpec(
        n_users=2,
        n_fogs=1,
        dt=0.01,
        horizon=0.02,
        policy=int(Policy.LOCAL_FIRST),
        v2_local_broker=True,
        broker_mips=500.0,
        connect_gating=False,
        max_sends_per_user=2,
    ).validate()
    state = init_state(spec)
    S = spec.max_sends_per_user

    # suppress spawning: the workload is hand-placed below
    state = state.replace(
        users=state.users.replace(publisher=jnp.zeros((2,), bool))
    )
    tasks = state.tasks
    inflight = jnp.int8(int(Stage.PUB_INFLIGHT))

    def put(col, i, v):
        return col.at[i].set(v)

    # slot u0s0: arrival at 0.002, 600 MIPS (> pool 500 -> not local)
    # slot u1s0: arrival at 0.008, 400 MIPS (< pool -> local accept)
    # slot u0s1: stored open request from "before": expiry 0.000
    a, b, r = 0 * S + 0, 1 * S + 0, 0 * S + 1
    tasks = tasks.replace(
        stage=put(put(put(tasks.stage, a, inflight), b, inflight),
                  r, jnp.int8(int(Stage.LOCAL_RUN))),
        t_at_broker=put(put(put(tasks.t_at_broker, a, 0.002), b, 0.008),
                        r, -0.01),
        t_create=put(put(put(tasks.t_create, a, 0.002), b, 0.008), r, -0.01),
        mips_req=put(put(put(tasks.mips_req, a, 600.0), b, 400.0), r, 100.0),
        req_open=put(tasks.req_open, r, jnp.int8(1)),
    )
    # pending shared timer fires at 0.005 — between the two arrivals
    state = state.replace(
        tasks=tasks,
        broker=state.broker.replace(release_timer_t=jnp.asarray(0.005)),
    )

    net = wired_star(spec.n_nodes, packet_bytes=spec.task_bytes)
    step = make_step(spec)
    out = step(state, net, default_bounds(1000.0))

    # the 0.005 firing happened: the stored request completed at 0.005
    # and refunded its 100 MIPS; the accept then debited 400
    assert int(np.asarray(out.tasks.stage)[r]) == int(Stage.DONE)
    np.testing.assert_allclose(float(np.asarray(out.tasks.t_complete)[r]),
                               0.005, atol=1e-6)
    np.testing.assert_allclose(
        float(out.broker.local_pool), 500.0 + 100.0 - 400.0, rtol=1e-6
    )
    # and the accept's reschedule was installed, not lost
    np.testing.assert_allclose(
        float(out.broker.release_timer_t), 0.008 + spec.required_time,
        rtol=1e-6,
    )


def _v2_timer_world(pool, a_mips, b_mips, timer=0.005):
    """Two hand-placed same-tick arrivals (0.002 / 0.008) straddling a
    pending shared-timer fire, plus one stored expired request (100 MIPS)."""
    import jax.numpy as jnp

    from fognetsimpp_tpu.core.engine import make_step
    from fognetsimpp_tpu.net.mobility import default_bounds
    from fognetsimpp_tpu.net.topology import wired_star
    from fognetsimpp_tpu.spec import WorldSpec
    from fognetsimpp_tpu.state import init_state

    spec = WorldSpec(
        n_users=2,
        n_fogs=1,
        dt=0.01,
        horizon=0.02,
        policy=int(Policy.LOCAL_FIRST),
        v2_local_broker=True,
        broker_mips=pool,
        connect_gating=False,
        max_sends_per_user=2,
    ).validate()
    state = init_state(spec)
    S = spec.max_sends_per_user
    state = state.replace(
        users=state.users.replace(publisher=jnp.zeros((2,), bool))
    )
    tasks = state.tasks
    inflight = jnp.int8(int(Stage.PUB_INFLIGHT))

    def put(col, i, v):
        return col.at[i].set(v)

    a, b, r = 0 * S + 0, 1 * S + 0, 0 * S + 1
    tasks = tasks.replace(
        stage=put(put(put(tasks.stage, a, inflight), b, inflight),
                  r, jnp.int8(int(Stage.LOCAL_RUN))),
        t_at_broker=put(put(put(tasks.t_at_broker, a, 0.002), b, 0.008),
                        r, -0.01),
        t_create=put(put(put(tasks.t_create, a, 0.002), b, 0.008), r, -0.01),
        mips_req=put(put(put(tasks.mips_req, a, a_mips), b, b_mips),
                     r, 100.0),
        req_open=put(tasks.req_open, r, jnp.int8(1)),
    )
    state = state.replace(
        tasks=tasks,
        broker=state.broker.replace(release_timer_t=jnp.asarray(timer)),
    )
    net = wired_star(spec.n_nodes, packet_bytes=spec.task_bytes)
    out = make_step(spec)(state, net, default_bounds(1000.0))
    return spec, out, r


def test_v2_first_accept_cancels_pending_timer():
    """r4 review finding 1: cancelEvent fires at EVERY local accept — the
    FIRST accept preceding the fire time cancels the pending timer, even
    when a later same-tick accept follows (BrokerBaseApp2.cc:221-224;
    desim.cpp bumps release_gen per accept)."""
    spec, out, r = _v2_timer_world(pool=500.0, a_mips=400.0, b_mips=50.0)
    # accept at 0.002 (400 < 500) cancelled the 0.005 fire: the stored
    # request must NOT have been released
    assert int(np.asarray(out.tasks.stage)[r]) == int(Stage.LOCAL_RUN)
    # both accepts debited; only the last accept's reschedule survives
    np.testing.assert_allclose(float(out.broker.local_pool), 500 - 400 - 50)
    np.testing.assert_allclose(
        float(out.broker.release_timer_t), 0.008 + spec.required_time,
        rtol=1e-6,
    )


def test_v2_fire_refund_visible_to_later_accept():
    """r4 review finding 2: a still-armed timer pops before later arrivals
    and its pool refund is visible to their accept checks — an arrival
    whose MIPS fits only pool+refund runs locally, as in the DES's strict
    event order."""
    spec, out, r = _v2_timer_world(pool=500.0, a_mips=600.0, b_mips=550.0)
    # 0.002 arrival (600 !< 500) does not accept or cancel; fire at 0.005
    # refunds 100 -> pool 600; 0.008 arrival accepts (550 < 600)
    assert int(np.asarray(out.tasks.stage)[r]) == int(Stage.DONE)
    np.testing.assert_allclose(float(np.asarray(out.tasks.t_complete)[r]),
                               0.005, atol=1e-6)
    np.testing.assert_allclose(
        float(out.broker.local_pool), 500 + 100 - 550, rtol=1e-6
    )
    np.testing.assert_allclose(
        float(out.broker.release_timer_t), 0.008 + spec.required_time,
        rtol=1e-6,
    )


def test_pool_same_tick_depth_beyond_phases_is_benign():
    """VERDICT r3 weak item 6: `pool_phases=4` bounds how many same-tick
    arrival ranks a POOL fog checks per tick; deeper arrivals defer one
    tick.  Benign means: they keep their exact arrival times (service
    start = t_at_fog, not the deferring tick's boundary), nothing is
    lost, and with sufficient pool every arrival is accepted."""
    import jax.numpy as jnp

    from fognetsimpp_tpu.core.engine import make_step
    from fognetsimpp_tpu.net.mobility import default_bounds
    from fognetsimpp_tpu.net.topology import wired_star
    from fognetsimpp_tpu.spec import FogModel, WorldSpec
    from fognetsimpp_tpu.state import init_state

    n = 7  # > pool_phases: ranks 4..6 defer a tick
    spec = WorldSpec(
        n_users=n,
        n_fogs=1,
        dt=1e-3,
        horizon=0.01,
        app_gen=2,
        fog_model=int(FogModel.POOL),
        # periodic adverts add a second advert-boundary pool pass per
        # tick (effective depth 2 x pool_phases); disable them so this
        # test pins the single-pass deferral mechanics
        adv_periodic=False,
        adv_on_completion=False,
        connect_gating=False,
        max_sends_per_user=1,
        pool_phases=4,
    ).validate()
    state = init_state(spec)
    state = state.replace(
        users=state.users.replace(publisher=jnp.zeros((n,), bool)),
        fogs=state.fogs.replace(
            mips=jnp.full((1,), 1e5, jnp.float32),
            pool_avail=jnp.full((1,), 1e5, jnp.float32),
        ),
    )
    tasks = state.tasks
    t_arr = 1e-4 + jnp.arange(n, dtype=jnp.float32) * 1e-6  # one tick
    tasks = tasks.replace(
        stage=jnp.full((n,), jnp.int8(int(Stage.TASK_INFLIGHT))),
        fog=jnp.zeros((n,), jnp.int32),
        mips_req=jnp.full((n,), 500.0, jnp.float32),
        t_create=t_arr,
        t_at_broker=t_arr,
        t_at_fog=t_arr,
    )
    state = state.replace(tasks=tasks)
    net = wired_star(spec.n_nodes, packet_bytes=spec.task_bytes)
    step = make_step(spec)

    s1 = step(state, net, default_bounds(1000.0))
    st1 = np.asarray(s1.tasks.stage)
    # exactly pool_phases ranks decided in the arrival tick
    assert (st1 == int(Stage.RUNNING)).sum() == spec.pool_phases
    assert (st1 == int(Stage.TASK_INFLIGHT)).sum() == n - spec.pool_phases

    s2 = step(s1, net, default_bounds(1000.0))
    st2 = np.asarray(s2.tasks.stage)
    assert (st2 == int(Stage.RUNNING)).sum() == n  # depth drained next tick
    # deferred arrivals kept their EXACT event times: service start is the
    # original t_at_fog, so the deferral costs no simulated time at all
    np.testing.assert_allclose(
        np.asarray(s2.tasks.t_service_start), np.asarray(t_arr), atol=1e-7
    )
    # pool accounting saw every arrival exactly once
    np.testing.assert_allclose(
        float(s2.fogs.pool_avail[0]), 1e5 - n * 500.0, rtol=1e-6
    )
