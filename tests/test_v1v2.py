"""v1/v2 application-generation semantics: POOL fogs, LOCAL_FIRST, MAX_MIPS.

Round-1 exported these enums without implementing them (VERDICT items 5/8/9/
11); these tests pin the now-live semantics to the reference:
``ComputeBrokerApp2.cc:258-310`` (pool accept/reject/release),
``BrokerBaseApp.cc:160-260`` (local-first + the buggy max-MIPS offload scan).
"""
import jax.numpy as jnp
import numpy as np

from fognetsimpp_tpu import BugCompat, FogModel, Policy, Stage, run
from fognetsimpp_tpu.scenarios import smoke


def _pool_world(**kw):
    kw.setdefault("n_users", 2)
    kw.setdefault("n_fogs", 3)
    kw.setdefault("fog_mips", (1000.0, 3000.0, 2000.0))
    kw.setdefault("horizon", 0.4)
    kw.setdefault("send_interval", 0.05)
    kw.setdefault("fog_model", int(FogModel.POOL))
    kw.setdefault("policy", int(Policy.MAX_MIPS))
    kw.setdefault("adv_periodic", True)
    kw.setdefault("adv_on_completion", False)
    kw.setdefault("app_gen", 2)
    return smoke.build(**kw)


def test_pool_accept_and_release():
    """Pool tasks run concurrently for requiredTime then refund the pool."""
    spec, state, net, bounds = _pool_world()
    final, _ = run(spec, state, net, bounds)
    stage = np.asarray(final.tasks.stage)
    done = stage == int(Stage.DONE)
    assert done.sum() > 0
    # service duration is exactly requiredTime (ComputeBrokerApp2.cc:275:
    # expiry = now + requiredTime, independent of MIPS rating)
    svc = (
        np.asarray(final.tasks.t_complete)[done]
        - np.asarray(final.tasks.t_service_start)[done]
    )
    np.testing.assert_allclose(svc, spec.required_time, rtol=1e-4)
    # at quiescence every accepted task has been released: pool == rated MIPS
    in_flight = np.isin(
        stage, [int(Stage.RUNNING), int(Stage.QUEUED), int(Stage.TASK_INFLIGHT)]
    ).sum()
    if in_flight == 0:
        np.testing.assert_allclose(
            np.asarray(final.fogs.pool_avail), np.asarray(final.fogs.mips)
        )
    # v2 completions reach the client through the broker relay
    assert np.isfinite(np.asarray(final.tasks.t_ack6)[done]).all()


def test_pool_rejects_oversized_tasks():
    """A task bigger than the whole pool is rejected (strict <,
    ComputeBrokerApp2.cc:269), and the broker ignores the TaskAck."""
    spec, state, net, bounds = _pool_world(
        fog_mips=(500.0, 500.0, 500.0),
        fixed_mips_required=800,  # > every pool -> every arrival rejected
        bug_compat=BugCompat(v1_max_scan=False),
    )
    final, _ = run(spec, state, net, bounds)
    stage = np.asarray(final.tasks.stage)
    # the broker-side guard (MIPSRequired < winner's advertised MIPS,
    # BrokerBaseApp.cc:244) already refuses to send once adverts arrive;
    # anything sent before the first advert lands is rejected at the fog
    assert (stage[stage != int(Stage.UNUSED)] != int(Stage.DONE)).all()
    assert int(final.metrics.n_rejected) > 0
    assert int(final.metrics.n_completed) == 0


def test_v1_max_scan_bug_compat():
    """The faithful v1 scan picks the LAST fog whose MIPS beats fog 0's
    (BrokerBaseApp.cc:232-236: `temp` is never updated), not the true max."""
    spec, state, net, bounds = _pool_world(
        fog_mips=(1000.0, 3000.0, 2000.0),
        fixed_mips_required=100,
        horizon=0.3,
    )
    final, _ = run(spec, state, net, bounds)
    fog = np.asarray(final.tasks.fog)
    sent = fog >= 0
    assert sent.any()
    # skip decisions made before the first advertisement arrived (view
    # MIPS all zero -> winner falls back to fog 0)
    t_ab = np.asarray(final.tasks.t_at_broker)
    informed = sent & (t_ab > 0.05)
    # buggy scan: last fog with MIPS > 1000 is fog 2 (2000), not fog 1 (3000)
    assert (fog[informed] == 2).all()

    spec2, state2, net2, bounds2 = _pool_world(
        fog_mips=(1000.0, 3000.0, 2000.0),
        fixed_mips_required=100,
        horizon=0.3,
        bug_compat=BugCompat(v1_max_scan=False),
    )
    final2, _ = run(spec2, state2, net2, bounds2)
    fog2 = np.asarray(final2.tasks.fog)
    informed2 = (fog2 >= 0) & (np.asarray(final2.tasks.t_at_broker) > 0.05)
    assert (fog2[informed2] == 1).all()  # true argmax


def test_local_first_runs_small_tasks_on_broker():
    """LOCAL_FIRST (v1): tasks with MIPSRequired < pool run locally with a
    status-3 ack and a direct status-6 on expiry (BrokerBaseApp.cc:196-224,
    369-394); the pool is debited and refunded."""
    spec, state, net, bounds = _pool_world(
        policy=int(Policy.LOCAL_FIRST),
        broker_mips=10000.0,
        fixed_mips_required=400,
        horizon=0.3,
    )
    final, _ = run(spec, state, net, bounds)
    stage = np.asarray(final.tasks.stage)
    created = np.isfinite(np.asarray(final.tasks.t_create))
    # pool 10000 >> 400: everything runs locally
    assert int(final.metrics.n_local) == created.sum() > 0
    done = stage == int(Stage.DONE)
    assert done.sum() > 0
    assert np.isfinite(np.asarray(final.tasks.t_ack3)[done]).all()
    assert np.isfinite(np.asarray(final.tasks.t_ack6)[done]).all()
    # local run takes exactly requiredTime on the broker
    svc = (
        np.asarray(final.tasks.t_complete)[done]
        - np.asarray(final.tasks.t_service_start)[done]
    )
    np.testing.assert_allclose(svc, spec.required_time, rtol=1e-4)
    # pool refunded at quiescence (local_pool_leak defaults False)
    if (stage == int(Stage.LOCAL_RUN)).sum() == 0:
        np.testing.assert_allclose(float(final.broker.local_pool), 10000.0)


def test_local_pool_leak_bug_compat():
    """With the faithful leak (BrokerBaseApp.cc:208 commented out) the
    broker pool only ever shrinks, eventually pushing tasks to offload."""
    spec, state, net, bounds = _pool_world(
        policy=int(Policy.LOCAL_FIRST),
        broker_mips=1000.0,
        fixed_mips_required=400,
        horizon=0.3,
        bug_compat=BugCompat(local_pool_leak=True),
    )
    final, _ = run(spec, state, net, bounds)
    # 1000 -> two local runs (400+400), then pool=200 < 400 forever
    assert int(final.metrics.n_local) == 2
    assert float(final.broker.local_pool) <= 200.0 + 1e-6
