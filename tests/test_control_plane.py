"""MQTT control plane: connect gating, subscriptions, topic fan-out.

Covers the reference subsystem the round-1 build skipped (VERDICT item 6):
Connect/Connack registration (``BrokerBaseApp3.cc:86-121``), the
Subscribe/Suback table (``:201-218``) and ``publishAll`` topic fan-out
(``:365-385``) as a live feature.
"""
import jax
import jax.numpy as jnp
import numpy as np

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.scenarios import smoke


def test_connect_gates_first_publish():
    """No user publishes before its Connack round-trip completes
    (mqttApp2.cc:165-233: processStart -> Connect -> Connack -> publish)."""
    spec, state, net, bounds = smoke.build(horizon=0.2, send_interval=0.05)
    assert spec.connect_gating
    final, _ = run(spec, state, net, bounds)
    connack = np.asarray(final.users.connack_at)
    start = np.asarray(final.users.start_t)
    assert np.isfinite(connack).all()
    assert (connack > start).all()  # round-trip takes two link hops
    # first publish of each user is exactly at its Connack arrival
    # (processConSubAck publishes immediately, mqttApp2.cc:319-326)
    t_create = np.asarray(final.tasks.t_create).reshape(spec.n_users, -1)
    np.testing.assert_allclose(t_create[:, 0], connack, rtol=1e-5)
    assert int(final.metrics.n_connected) == spec.n_users


def test_unconnected_world_never_publishes():
    """With gating on and a start time beyond the horizon, nothing happens."""
    spec, state, net, bounds = smoke.build(
        horizon=0.1, start_time_min=5.0, start_time_max=5.0
    )
    final, _ = run(spec, state, net, bounds)
    assert int(final.metrics.n_published) == 0
    assert int(final.metrics.n_connected) == 0


def test_topic_fanout_delivers_to_subscribers():
    """publishAll: each publish is duplicated to every subscriber of its
    topic (BrokerBaseApp3.cc:365-385, live per SURVEY §3.4).

    World: user 0 publishes on topic 1; user 1 subscribes to topics 0 and 1;
    user 2 subscribes to topic 0 only.  Every publish must land on user 1
    and never on user 2 (or the publisher).
    """
    spec, state, net, bounds = smoke.build(
        n_users=3, horizon=0.3, send_interval=0.05, n_topics=2
    )
    users = state.users
    users = users.replace(
        publisher=jnp.asarray([True, False, False]),
        pub_topic=jnp.asarray([1, 0, 0], jnp.int32),
        sub_mask=jnp.asarray(
            [[False, False], [True, True], [True, False]]
        ),
    )
    state = state.replace(users=users)
    final, _ = run(spec, state, net, bounds)
    published = int(final.metrics.n_published)
    delivered = np.asarray(final.users.n_delivered)
    assert published > 0
    assert delivered[0] == 0
    assert delivered[1] == published
    assert delivered[2] == 0
    assert int(final.metrics.n_fanout) == published
    # both subscribers' subscriptions were acked at connect time
    assert int(final.metrics.n_subscribed) == 3
