"""Wireless/mobility parity gate: batched engine vs native DES (r4).

r3's 1%-parity guarantee covered only static wired worlds — the native
core refused wireless/mobility (VERDICT r3 missing item 1).  Now the DES
consumes a per-tick ``delay(node, t)`` table produced by the SAME
mobility + association model the engine runs (``bridge.delay_table``) and
replays the engine's uplink-loss draws, so handover, contention and range
loss reach the sequential baseline as time-varying data while every
event (scheduling, queues, acks, timers) is still executed independently.

Matches the emergent behaviours of the reference's wireless ladder
(``simulations/testing/wireless2.ini`` / ``wireless5.ini:23-68``): AP
association by proximity, handover as users move, per-AP contention in
the access delay.
"""
import numpy as np
import pytest

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.native import bridge
from fognetsimpp_tpu.scenarios import wireless


@pytest.fixture(scope="module")
def wireless2_worlds():
    spec, state, net, bounds = wireless.wireless2(
        horizon=2.0,
        dt=1e-4,
        send_interval=0.1,
    )
    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(
        spec, final, net, state0=state, bounds=bounds
    )
    return spec, state, net, bounds, final, des, used


def _eng(final, used, col):
    return np.asarray(getattr(final.tasks, col), np.float64)[used]


def test_wireless_delay_table_is_time_varying(wireless2_worlds):
    """The parity input really is a moving world: circling users' delays
    change over the run (handover + contention), so the gate is not
    silently reducing to the static case."""
    spec, state, net, bounds, *_ = wireless2_worlds
    tab = bridge.delay_table(spec, state, net, bounds)
    assert tab.shape == (spec.n_ticks, spec.n_nodes)
    moving = np.asarray(state.nodes.mobility) != 0
    var = np.nanstd(np.where(np.isfinite(tab), tab, np.nan), axis=0)
    assert (var[: spec.n_users][moving[: spec.n_users]] > 0).any()


def test_wireless_choices_match(wireless2_worlds):
    spec, _, _, _, final, des, used = wireless2_worlds
    assert used.sum() >= 150  # 11 users publishing every 0.1 s for 2 s
    eng_fog = np.asarray(final.tasks.fog)[used]
    np.testing.assert_array_equal(eng_fog, des["fog"])
    # transit arithmetic agrees wherever the publish arrived
    e = _eng(final, used, "t_at_broker")
    both = np.isfinite(e) & np.isfinite(des["t_at_broker"])
    assert both.sum() >= 150
    np.testing.assert_allclose(e[both], des["t_at_broker"][both], rtol=1e-5)


def test_wireless_latency_within_1pct(wireless2_worlds):
    spec, _, _, _, final, des, used = wireless2_worlds
    t0 = _eng(final, used, "t_create")
    n_checked = 0
    for col in ("t_ack5", "t_ack6", "t_service_start", "t_complete",
                "t_ack4_queued", "t_at_fog"):
        e = _eng(final, used, col)
        d = des[col]
        both = np.isfinite(e) & np.isfinite(d)
        n_checked += int(both.sum())
        lat_e, lat_d = e[both] - t0[both], d[both] - t0[both]
        rel = np.abs(lat_e - lat_d) / np.maximum(np.abs(lat_d), 1e-9)
        assert rel.size == 0 or rel.max() < 0.01, (col, rel.max())
    assert n_checked >= 100


def test_wireless_stage_census_matches(wireless2_worlds):
    """Same decisions AND same fates: the per-stage census of the two
    simulators agrees up to end-of-horizon straddlers."""
    spec, _, _, _, final, des, used = wireless2_worlds
    eng_stage = np.asarray(final.tasks.stage)[used]
    for st in (Stage.DONE, Stage.NO_RESOURCE, Stage.REJECTED, Stage.LOST):
        n_e = int((eng_stage == int(st)).sum())
        n_d = int((des["stage"] == int(st)).sum())
        assert abs(n_e - n_d) <= 2, (st, n_e, n_d)


def test_wireless5_class_world_has_a_baseline():
    """A wireless5-class world (the full-feature topology: heterogeneous
    fog MIPS, 5 APs, circle + linear mobility) passes the exact-choice
    gate with the lifecycle off — the parity-grade configuration; energy
    accounting itself is gated separately on wired worlds."""
    spec, state, net, bounds = wireless.wireless5(
        numb_users=8,
        horizon=2.0,
        dt=1e-4,
        send_interval=0.1,
        energy_enabled=False,
    )
    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(
        spec, final, net, state0=state, bounds=bounds
    )
    assert used.sum() >= 100
    np.testing.assert_array_equal(np.asarray(final.tasks.fog)[used],
                                  des["fog"])
    t0 = _eng(final, used, "t_create")
    e = _eng(final, used, "t_ack6")
    both = np.isfinite(e) & np.isfinite(des["t_ack6"])
    if both.sum():
        lat_e, lat_d = e[both] - t0[both], des["t_ack6"][both] - t0[both]
        rel = np.abs(lat_e - lat_d) / np.maximum(np.abs(lat_d), 1e-9)
        assert rel.max() < 0.01


def test_wireless_replay_requires_state0():
    spec, state, net, bounds = wireless.wireless2(horizon=0.2, dt=1e-3)
    final, _ = run(spec, state, net, bounds)
    with pytest.raises(NotImplementedError):
        bridge.replay_engine_world(spec, final, net)


def test_wireless5_energy_churn_has_a_baseline():
    """The flagship combination the r4 gate still excluded (VERDICT r4
    missing item 1 / next-round item 5): 802.11 users whose batteries
    drain, die and restart (wireless5.ini:150-166, mqttApp2.cc:471-492).

    The DES derives its OWN alive trajectory — tick-quantised f32 energy
    from its own tx/rx bookings, the alive-gated mqttApp2 send chain run
    natively — rather than replaying the engine's; the gate then asserts
    the two simulators independently produce the same publish schedule,
    the same fog choices, the same latencies AND the same final battery/
    lifecycle state.  Contention is held at zero (w_contention=0,
    mac_model="linear") so the delay table stays alive-independent —
    contention-under-churn remains the documented engine-only exclusion.

    Batteries are sized for fast cycling: ~18 mW net drain while
    publishing kills a 12 mJ battery in ~0.7 s; a dead user harvests
    back to the 50% restart threshold in ~1.5 s — several death/revival
    cycles per user inside the 4 s horizon.  ROUND_ROBIN scheduling: the
    gate isolates LIFECYCLE dynamics, and RR choices are view-
    independent, so the advert-boundary staleness races that churn-
    synchronised publish bursts systematically trigger under view-based
    policies (a pre-existing tick-model artifact documented in
    PARITY.md, unrelated to energy) cannot contaminate the comparison.
    """
    from fognetsimpp_tpu import Policy

    spec, state, net, bounds = wireless.wireless5(
        numb_users=8,
        horizon=4.0,
        dt=1e-4,
        send_interval=0.1,
        w_contention=0.0,
        mac_model="linear",
        policy=int(Policy.ROUND_ROBIN),
        energy_capacity_j=0.012,
        tx_energy_j=2e-3,
        rx_energy_j=1e-4,
        idle_power_w=2e-3,
        harvest_power_w=4e-3,
        harvest_period_s=50.0,  # harvesting throughout the horizon
        harvest_duty=0.5,
    )
    final, _ = run(spec, state, net, bounds)
    U = spec.n_users
    alive0 = np.asarray(state.nodes.alive)[:U]
    alive1 = np.asarray(final.nodes.alive)[:U]
    sent = np.asarray(final.users.send_count)
    # the engine world really churns: publishing is battery-gated (every
    # user sends, nobody sends the full uninterrupted schedule)
    assert (sent > 0).all()
    assert (sent < int(spec.horizon / spec.send_interval) - 3).any(), sent

    des, used = bridge.replay_engine_world(
        spec, final, net, state0=state, bounds=bounds
    )
    # independently derived publish schedule matches slot-for-slot
    eng_create = np.asarray(final.tasks.t_create, np.float64)
    eng_used = np.isfinite(eng_create)
    des_used = np.isfinite(des["t_create"])
    np.testing.assert_array_equal(eng_used, des_used)
    np.testing.assert_allclose(
        eng_create[eng_used], des["t_create"][des_used], rtol=1e-6
    )
    # same decisions and same fates
    np.testing.assert_array_equal(
        np.asarray(final.tasks.fog)[eng_used], des["fog"][eng_used]
    )
    eng_stage = np.asarray(final.tasks.stage)[eng_used]
    for st in (Stage.DONE, Stage.NO_RESOURCE, Stage.LOST, Stage.DROPPED):
        n_e = int((eng_stage == int(st)).sum())
        n_d = int((des["stage"][eng_used] == int(st)).sum())
        assert abs(n_e - n_d) <= 2, (st, n_e, n_d)
    # latency parity: completion times cover every DONE task (ack6 is
    # +inf on BOTH sides whenever the publisher died before the relay —
    # churn's signature — so it yields few finite samples here)
    t0c = eng_create[eng_used]
    for col, min_n in (("t_complete", 40), ("t_ack6", 5)):
        e = np.asarray(getattr(final.tasks, col), np.float64)[eng_used]
        d = des[col][eng_used]
        both = np.isfinite(e) & np.isfinite(d)
        assert both.sum() >= min_n, (col, both.sum())
        rel = np.abs(
            (e[both] - t0c[both]) - (d[both] - t0c[both])
        ) / np.maximum(d[both] - t0c[both], 1e-9)
        assert rel.max() < 0.01, (col, rel.max())
        # and inf-ness itself agrees (the ack died with the user on both
        # sides, never on only one)
        np.testing.assert_array_equal(np.isfinite(e), np.isfinite(d))
    # independently integrated batteries agree: same final joules (f32
    # accounting on both sides) and the same final lifecycle state
    np.testing.assert_allclose(
        np.asarray(final.nodes.energy, np.float64)[:U],
        des["user_energy"],
        rtol=1e-5, atol=1e-7,
    )
    np.testing.assert_array_equal(alive1, des["user_alive"].astype(bool))
