"""Unit tests for the scheduler kernels (policy parity + the extra policies)."""
import jax
import jax.numpy as jnp
import numpy as np

from fognetsimpp_tpu.ops.sched import schedule_batch
from fognetsimpp_tpu.spec import Policy


def _call(policy, mask, mips_req, busy, vmips, mips0=True, rr=0, key=None,
          alive=None, efrac=None, rtt=None):
    F = busy.shape[0]
    if alive is None:
        alive = jnp.ones((F,), bool)
    if efrac is None:
        efrac = jnp.ones((F,), jnp.float32)
    if rtt is None:
        rtt = jnp.zeros((F,), jnp.float32)
    if key is None:
        key = jax.random.PRNGKey(0)
    return schedule_batch(
        int(policy), mask, mips_req, busy, vmips, jnp.ones((F,), bool),
        alive, efrac, rtt, jnp.asarray(rr, jnp.int32), key, mips0,
    )


def test_min_busy_matches_reference_argmin():
    """Exact v3 semantics: argmin of busy + req/MIPS[0], first-wins ties
    (BrokerBaseApp3.cc:267-281)."""
    busy = jnp.array([0.5, 0.2, 0.2, 0.9], jnp.float32)
    vmips = jnp.array([1000.0, 2000.0, 500.0, 100.0], jnp.float32)
    mask = jnp.array([True, True], bool)
    req = jnp.array([400.0, 800.0], jnp.float32)
    choice, _ = _call(Policy.MIN_BUSY, mask, req, busy, vmips)
    # with the MIPS[0] bug the estimate term is constant -> pure argmin(busy),
    # tie between fogs 1 and 2 broken toward the lower index
    np.testing.assert_array_equal(np.asarray(choice), [1, 1])


def test_min_busy_without_bug_uses_per_fog_mips():
    busy = jnp.array([0.0, 0.0], jnp.float32)
    vmips = jnp.array([100.0, 10000.0], jnp.float32)
    mask = jnp.array([True], bool)
    req = jnp.array([500.0], jnp.float32)
    choice, _ = _call(Policy.MIN_BUSY, mask, req, busy, vmips, mips0=False)
    assert int(choice[0]) == 1  # 500/10000 << 500/100


def test_min_busy_zero_mips_view_picks_first():
    """Before the first advertisement the broker's view has MIPS=0
    (BrokerBaseApp3.cc:104): estimates are +inf and the C++ `<` scan keeps
    index 0."""
    busy = jnp.zeros((3,), jnp.float32)
    vmips = jnp.zeros((3,), jnp.float32)
    mask = jnp.array([True], bool)
    req = jnp.array([500.0], jnp.float32)
    choice, _ = _call(Policy.MIN_BUSY, mask, req, busy, vmips)
    assert int(choice[0]) == 0


def test_round_robin_cycles():
    busy = jnp.zeros((3,), jnp.float32)
    vmips = jnp.full((3,), 1000.0, jnp.float32)
    mask = jnp.ones((5,), bool)
    req = jnp.full((5,), 100.0, jnp.float32)
    choice, rr = _call(Policy.ROUND_ROBIN, mask, req, busy, vmips, rr=1)
    np.testing.assert_array_equal(np.asarray(choice), [1, 2, 0, 1, 2])
    assert int(rr) == (1 + 5) % 3


def test_energy_aware_avoids_dead_and_drained():
    busy = jnp.zeros((3,), jnp.float32)
    vmips = jnp.full((3,), 1000.0, jnp.float32)
    mask = jnp.array([True], bool)
    req = jnp.array([100.0], jnp.float32)
    alive = jnp.array([False, True, True])
    efrac = jnp.array([1.0, 0.05, 0.9], jnp.float32)
    choice, _ = _call(
        Policy.ENERGY_AWARE, mask, req, busy, vmips, alive=alive, efrac=efrac
    )
    assert int(choice[0]) == 2


def test_min_latency_includes_rtt():
    busy = jnp.array([0.0, 0.0], jnp.float32)
    vmips = jnp.full((2,), 1000.0, jnp.float32)
    rtt = jnp.array([0.5, 0.001], jnp.float32)
    mask = jnp.array([True], bool)
    req = jnp.array([100.0], jnp.float32)
    choice, _ = _call(Policy.MIN_LATENCY, mask, req, busy, vmips, rtt=rtt)
    assert int(choice[0]) == 1


def test_random_only_picks_alive():
    busy = jnp.zeros((4,), jnp.float32)
    vmips = jnp.full((4,), 1000.0, jnp.float32)
    mask = jnp.ones((64,), bool)
    req = jnp.full((64,), 100.0, jnp.float32)
    alive = jnp.array([False, True, False, True])
    choice, _ = _call(Policy.RANDOM, mask, req, busy, vmips, alive=alive,
                      key=jax.random.PRNGKey(3))
    got = set(np.asarray(choice).tolist())
    assert got <= {1, 3} and len(got) == 2


def test_zero_view_anchors_first_registered_not_slot0():
    """ADVICE r3: with fog slot 0 unregistered and every estimate +inf
    (pre-first-advert MIPS=0 view), the C++ strict-< scan keeps its
    initial value brokers[0] = the FIRST REGISTERED fog — not array
    slot 0, which in this window is not even in brokers[]."""
    from fognetsimpp_tpu.ops.sched import scalar_winner

    F = 3
    busy = jnp.zeros((F,), jnp.float32)
    vmips = jnp.zeros((F,), jnp.float32)
    registered = jnp.array([False, True, True])
    mask = jnp.array([True], bool)
    req = jnp.array([500.0], jnp.float32)
    choice, _ = schedule_batch(
        int(Policy.MIN_BUSY), mask, req, busy, vmips, registered,
        jnp.ones((F,), bool), jnp.ones((F,), jnp.float32),
        jnp.zeros((F,), jnp.float32), jnp.asarray(0, jnp.int32),
        jax.random.PRNGKey(0), True,
    )
    assert int(choice[0]) == 1

    win = scalar_winner(
        int(Policy.MIN_BUSY), busy, vmips, registered,
        jnp.ones((F,), bool), jnp.ones((F,), jnp.float32),
        jnp.zeros((F,), jnp.float32), True,
    )
    assert int(win) == 1
