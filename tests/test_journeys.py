"""telemetry/journeys — causal task-journey tracing (ISSUE 15).

The gates, in the established inert-subsystem order: journeys OFF is
bit-exact across every entry point and journeys ON perturbs not a
single non-journey leaf (the inert-LearnState discipline); the
device-decoded event chain of a scripted chaos+hier world bit-matches
a deterministic host replay of the same schedules (ONE shared
journey_edges rule set, two array backends); a sampled task provably
crashes → re-offloads → broker-migrates → completes as one connected
Perfetto flow chain across two broker lanes (strict RFC-8259 JSON);
ring overflow keeps exact drop-oldest accounting.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fognetsimpp_tpu import Policy, run
from fognetsimpp_tpu.hier import stamp_ownership
from fognetsimpp_tpu.scenarios import smoke
from fognetsimpp_tpu.spec import ChaosMode, HierPolicy
from fognetsimpp_tpu.telemetry import journeys as jn

SMALL = dict(n_users=2, n_fogs=2, send_interval=0.05, horizon=0.4)

#: The acceptance world: domain 0 owns every user and two SLOW fogs
#: that a scripted outage kills mid-run; REOFFLOAD bounces their
#: in-flight tasks back to broker 0, whose dead domain migrates them
#: to domain 1's fast fogs — crash → re-offload → migrate → complete,
#: all inside one run.
CHAOS_HIER = dict(
    n_users=4, n_fogs=4,
    fog_mips=(2000.0, 2000.0, 60000.0, 60000.0),
    send_interval=0.02, horizon=0.5, dt=1e-3, seed=0,
    max_sends_per_user=32,
    n_brokers=2, hier_policy=int(HierPolicy.THRESHOLD),
    hier_threshold=0.5, hier_max_hops=2,
    assume_static=False,
    chaos=True, chaos_mode=int(ChaosMode.REOFFLOAD),
    chaos_max_retries=8,
    chaos_script=((0, 0.05, 0.45), (1, 0.05, 0.45)),
    telemetry=True,
)


def _state_hash(state) -> str:
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _build(**kw):
    args = dict(SMALL)
    args.update(kw)
    return smoke.build(**args)


def _build_chaos_hier(**kw):
    args = dict(CHAOS_HIER)
    args.update(kw)
    spec, state, net, bounds = smoke.build(**args)
    state = stamp_ownership(
        spec, state, user_broker=[0] * spec.n_users,
        fog_broker=[0, 0, 1, 1],
    )
    return spec, state, net, bounds


#: The PR-2/PR-4 policy-family triptych: dense broker, compacted
#: LOCAL_FIRST, learned bandit.
WORLDS = [
    dict(policy=int(Policy.MIN_BUSY)),
    dict(policy=int(Policy.LOCAL_FIRST), broker_mips=2048.0),
    dict(policy=int(Policy.UCB)),
]

#: Memoized finals (the test_hier run-cache discipline: run() retraces
#: per call, so tests sharing a world share one trace).
_RUN_CACHE: dict = {}


def _chaos_hier_final(**kw):
    key = ("ch",) + tuple(sorted(kw.items()))
    if key not in _RUN_CACHE:
        spec, state, net, bounds = _build_chaos_hier(**kw)
        final, _ = run(spec, state, net, bounds)
        _RUN_CACHE[key] = (spec, final)
    return _RUN_CACHE[key]


# ----------------------------------------------------------------------
# inert gates
# ----------------------------------------------------------------------

def test_journeys_off_leaves_zero_row_and_bit_exact_entries():
    """Journeys off (the default): every journey leaf has zero rows,
    the dropped counter stays 0, and run / run_jit / run_chunked
    produce bit-identical final states over the three policy-family
    worlds."""
    from fognetsimpp_tpu.core.engine import run_chunked, run_jit

    for kw in WORLDS:
        spec, state, net, bounds = _build(**kw)
        assert not spec.journey_active
        assert spec.journey_slots == 0 and spec.journey_ring == 0
        ref, _ = run(spec, state, net, bounds)
        assert ref.telem.j_task.shape == (0,)
        assert ref.telem.j_ring.shape == (0, 0, 4)
        assert int(np.asarray(ref.telem.j_dropped)) == 0
        h_ref = _state_hash(ref)
        spec2, state2, net2, bounds2 = _build(**kw)
        assert _state_hash(run_jit(spec2, state2, net2, bounds2)) == h_ref
        spec3, state3, net3, bounds3 = _build(**kw)
        assert (
            _state_hash(run_chunked(spec3, state3, net3, bounds3, 170))
            == h_ref
        )


def test_journeys_on_perturbs_zero_non_journey_leaves():
    """Journeys ON is read-only: every non-journey leaf of the final
    state — including every OTHER telemetry leaf — is bit-equal to
    the journeys-off run of the same telemetry-on world."""
    import dataclasses

    J_LEAVES = {"j_task", "j_prev", "j_ring", "j_cursor", "j_dropped"}
    for kw in WORLDS:
        spec0, st0, net0, b0 = _build(telemetry=True, **kw)
        ref, _ = run(spec0, st0, net0, b0)
        spec1, st1, net1, b1 = _build(
            telemetry=True, telemetry_journeys=4, **kw
        )
        on, _ = run(spec1, st1, net1, b1)
        for f in ("nodes", "users", "fogs", "broker", "tasks",
                  "metrics", "learn", "chaos", "hier"):
            assert _state_hash(getattr(ref, f)) == _state_hash(
                getattr(on, f)
            ), (kw, f)
        for fld in dataclasses.fields(ref.telem):
            if fld.name in J_LEAVES:
                continue
            assert np.array_equal(
                np.asarray(getattr(ref.telem, fld.name)),
                np.asarray(getattr(on.telem, fld.name)),
            ), (kw, fld.name)
        # and the journey plane actually recorded something
        assert int(np.asarray(on.telem.j_cursor).sum()) > 0, kw


def test_journeys_on_bit_identical_across_run_entries():
    """Journeys ON: run / run_jit / run_chunked agree bit-for-bit
    (ring contents included) — the chunk boundary carries the rings."""
    from fognetsimpp_tpu.core.engine import run_chunked, run_jit

    kw = dict(telemetry=True, telemetry_journeys=4)
    spec, state, net, bounds = _build(**kw)
    ref, _ = run(spec, state, net, bounds)
    h_ref = _state_hash(ref)
    spec2, state2, net2, bounds2 = _build(**kw)
    assert _state_hash(run_jit(spec2, state2, net2, bounds2)) == h_ref
    spec3, state3, net3, bounds3 = _build(**kw)
    assert (
        _state_hash(run_chunked(spec3, state3, net3, bounds3, 170))
        == h_ref
    )


def test_fleet_vmap_carries_journey_rings():
    """The fleet path is vmap(step): per-replica rings accumulate
    independently and replica 0 of a 2-replica batch bit-matches the
    single-world run with the same key."""
    from fognetsimpp_tpu.core.engine import make_step
    from fognetsimpp_tpu.net.mobility import default_bounds
    from fognetsimpp_tpu.parallel import replicate_state

    kw = dict(telemetry=True, telemetry_journeys=4)
    spec, state, net, _ = _build(**kw)
    bounds = default_bounds()
    step = make_step(spec)
    batch = replicate_state(spec, state, 2, seed=0)
    vstep = jax.jit(
        lambda b: jax.vmap(lambda s: step(s, net, bounds))(b)
    )
    sstep = jax.jit(lambda s: step(s, net, bounds))
    single = jax.tree.map(lambda x: x[0], batch)
    for _ in range(40):
        batch = vstep(batch)
        single = sstep(single)
    for name in ("j_task", "j_prev", "j_ring", "j_cursor"):
        got = np.asarray(getattr(batch.telem, name))[0]
        want = np.asarray(getattr(single.telem, name))
        assert np.array_equal(got, want), name


def test_bucket_padding_preserves_the_journey_sample():
    """dynspec.bucket_spec pads the task table with END-appended ghost
    rows: the J-sized journey leaves ride through untouched and the
    sampled ids keep addressing the same (user, send) slots."""
    from fognetsimpp_tpu.parallel.taskshard import pad_users_to_multiple

    spec, state, net, bounds = _build(
        telemetry=True, telemetry_journeys=4
    )
    ids0 = np.asarray(state.telem.j_task)
    spec2, state2, net2 = pad_users_to_multiple(spec, state, net, 3)
    assert spec2.n_users > spec.n_users
    assert spec2.journey_slots == spec.journey_slots
    assert np.array_equal(np.asarray(state2.telem.j_task), ids0)
    assert np.array_equal(
        np.asarray(state2.telem.j_prev),
        np.asarray(state.telem.j_prev),
    )
    # padded slot layout: old ids still address the same (user, send)
    S = spec.max_sends_per_user
    assert spec2.max_sends_per_user == S
    for t in ids0:
        assert int(t) // S < spec.n_users


def test_phase_contract_registered_and_shapes():
    from fognetsimpp_tpu.core.contracts import (
        check_phase_contracts,
        check_step_contract,
        check_telemetry_contract,
    )

    spec, state, net, bounds = _build(
        telemetry=True, telemetry_journeys=4
    )
    checked = check_phase_contracts(spec, state, net)
    assert "_phase_journeys" in checked
    check_step_contract(spec, state, net, bounds)
    check_telemetry_contract(spec, state)
    # off-world: zero-row shapes also contract-checked
    spec0, state0, _, _ = _build(telemetry=True)
    check_telemetry_contract(spec0, state0)


def test_sharded_runner_admits_journeys():
    # the [TP-JOURNEYS] clause is deleted (ISSUE 19): a TP-admissible
    # journey spec passes the gate; tests/test_tp_journeys.py proves
    # the sharded rings bit-match the single-device tap
    from fognetsimpp_tpu.core.engine import tp_reject_reason

    spec, *_ = _build(
        telemetry=True, telemetry_journeys=4, assume_static=True,
        derive_acks=True,
    )
    assert tp_reject_reason(spec) is None


def test_spec_validation_one_liners():
    with pytest.raises(ValueError, match="rides TelemetryState"):
        _build(telemetry_journeys=4)
    with pytest.raises(ValueError, match="exceeds the task capacity"):
        _build(telemetry=True, telemetry_journeys=10**9)
    with pytest.raises(ValueError, match=">= 8 event rows"):
        _build(
            telemetry=True, telemetry_journeys=4,
            telemetry_journey_ring=4,
        )


def test_sample_is_deterministic_and_key_folded():
    """The sample is a pure function of (world key, J) — re-building
    the same world re-derives it — and enabling journeys consumes
    nothing from the main stream (the spawn draws are untouched, which
    the perturbs-zero-leaves test already proves end-to-end)."""
    spec, state, net, bounds = _build(
        telemetry=True, telemetry_journeys=4
    )
    spec2, state2, *_ = _build(telemetry=True, telemetry_journeys=4)
    ids, ids2 = (
        np.asarray(state.telem.j_task), np.asarray(state2.telem.j_task)
    )
    assert np.array_equal(ids, ids2)
    assert len(set(ids.tolist())) == 4  # distinct slots
    assert np.all(np.diff(ids) > 0)  # sorted
    assert ids.min() >= 0 and ids.max() < spec.task_capacity


# ----------------------------------------------------------------------
# the acceptance chain: crash -> re-offload -> migrate -> complete
# ----------------------------------------------------------------------

def test_chaos_hier_chain_is_recorded():
    """On the scripted domain-death world at full sampling, at least
    one sampled task's decoded ring shows the full causal rescue:
    re-offload off the crashed fog, broker 0 -> broker 1 migration,
    decide at the rescuing broker, completion on a domain-1 fog — in
    that causal order."""
    spec, final = _chaos_hier_final(
        telemetry_journeys=128, telemetry_journey_ring=32
    )
    decoded = jn.decode_rings(spec, final)
    chains = []
    for d in decoded:
        names = [e["name"] for e in d["events"]]
        if {"reoffload", "migrate", "done"} <= set(names):
            chains.append(d)
    assert chains, "no crash->reoffload->migrate->done chain sampled"
    d = chains[0]
    names = [e["name"] for e in d["events"]]
    i_r = names.index("reoffload")
    i_m = names.index("migrate")
    i_d = names.index("done")
    assert i_r < i_m < i_d, names
    mig = d["events"][i_m]
    assert (mig["a"], mig["b"]) == (0, 1)  # broker 0 -> broker 1
    reoff = d["events"][i_r]
    assert reoff["a"] in (0, 1)  # bounced off a domain-0 fog
    assert reoff["b"] >= 1  # retry count stamped
    done = d["events"][i_d]
    assert done["a"] in (2, 3)  # completed on a domain-1 fog
    # the re-decide at the rescuing broker sits between hop and done
    i_d2 = names.index("decide", i_m)
    assert i_m < i_d2 < i_d
    assert d["events"][i_d2]["b"] == 1  # owning broker after the hop


def test_device_chain_bit_matches_host_replay():
    """THE determinism oracle: drive the real compiled step
    tick-by-tick, re-derive every tick's edges on host with the SAME
    journey_edges rule set over numpy, and require the device-decoded
    rings to match the replay event-for-event (drop-oldest tail
    included) — so the in-scan tap provably records the schedule the
    engine actually executed."""
    from fognetsimpp_tpu.core.engine import make_step
    from fognetsimpp_tpu.net.mobility import default_bounds

    spec, state, net, bounds = _build_chaos_hier(
        telemetry_journeys=128, telemetry_journey_ring=16
    )
    step = make_step(spec)
    jstep = jax.jit(lambda s: step(s, net, default_bounds()))
    ids = np.asarray(state.telem.j_task)

    def snap(s):
        return np.asarray(
            jn.snapshot_rows(
                spec, s.tasks, s.chaos, s.hier, jnp.asarray(ids)
            )
        )

    expected = [[] for _ in ids]
    prev = snap(state)
    s = state
    for i in range(spec.n_ticks):
        s = jstep(s)
        cur = snap(s)
        t1 = np.float32(np.float32(i + 1) * np.float32(spec.dt))
        for j, evs in enumerate(
            jn.replay_tick(spec, prev, cur, ids, float(t1))
        ):
            expected[j].extend(evs)
        prev = cur
    decoded = jn.decode_rings(spec, s)
    R = spec.journey_ring
    n_events = 0
    n_dropped = 0
    for j, d in enumerate(decoded):
        exp = expected[j]
        n_events += len(exp)
        n_dropped += max(0, len(exp) - R)
        assert d["events_total"] == len(exp), (j, d, exp)
        want = exp[-R:] if len(exp) > R else exp
        assert d["events"] == want, (j, d["events"], want)
    assert n_events == int(np.asarray(s.telem.j_cursor).sum())
    assert n_dropped == int(np.asarray(s.telem.j_dropped))
    assert n_events > 0


# ----------------------------------------------------------------------
# Perfetto flow chains
# ----------------------------------------------------------------------

def test_perfetto_flow_chain_crosses_broker_lanes(tmp_path):
    """The acceptance render: the chaos+hier world's trace carries one
    connected s->t...->f flow chain per journeyed task; for a rescued
    task the chain's slices span BOTH broker lanes of the dedicated
    "journeys" process.  The export round-trips strict RFC-8259
    json.loads (no NaN/Infinity tokens)."""
    from fognetsimpp_tpu.telemetry.timeline import export_trace

    spec, final = _chaos_hier_final(
        telemetry_journeys=128, telemetry_journey_ring=32
    )
    p = export_trace(spec, final, str(tmp_path / "journeys.json"))

    def _no_nonfinite(name):
        raise AssertionError(f"non-RFC-8259 token in trace JSON: {name}")

    trace = json.loads(open(p).read(), parse_constant=_no_nonfinite)
    events = trace["traceEvents"]
    # the journeys process exists and is labelled
    jpids = {
        e["pid"] for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and e.get("args", {}).get("name") == "journeys"
    }
    assert len(jpids) == 1
    jpid = jpids.pop()
    jev = [e for e in events if e.get("cat") == "journey"]
    flows = [e for e in jev if e["ph"] in ("s", "t", "f")]
    assert flows, "no flow events rendered"
    # every flow id forms one connected chain: exactly one s, one f,
    # and every flow binds to a slice at the same (tid, ts)
    slices = {
        (e["tid"], e["ts"]) for e in jev if e["ph"] == "X"
    }
    by_id: dict = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e)
        assert (e["tid"], e["ts"]) in slices
    rescued = 0
    B = spec.n_brokers
    for fid, chain in by_id.items():
        phases = [e["ph"] for e in chain]
        assert phases[0] == "s" and phases[-1] == "f", (fid, phases)
        assert all(ph == "t" for ph in phases[1:-1]), (fid, phases)
        broker_lanes = {
            e["tid"] for e in chain if e["tid"] < B
        }
        if len(broker_lanes) >= 2:
            rescued += 1
    assert rescued > 0, "no flow chain crosses two broker lanes"
    # broker lane metadata present for both lanes
    lanes = {
        e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("pid") == jpid
        and e.get("name") == "thread_name"
    }
    assert {"broker0", "broker1"} <= lanes, lanes


def test_journey_off_trace_is_unchanged(tmp_path):
    """No journeys => byte-identical Perfetto export vs a build without
    the journey renderer's output (no 'journeys' process, no flow
    events) — existing goldens stay valid."""
    from fognetsimpp_tpu.telemetry.timeline import export_trace

    spec, state, net, bounds = _build(telemetry=True)
    final, _ = run(spec, state, net, bounds)
    p = export_trace(spec, final, str(tmp_path / "plain.json"))
    trace = json.loads(open(p).read())
    assert not [
        e for e in trace["traceEvents"] if e.get("cat") == "journey"
    ]


# ----------------------------------------------------------------------
# ring overflow: exact drop-oldest accounting
# ----------------------------------------------------------------------

def test_ring_overflow_drop_oldest_accounting():
    """Drive journey_tick eagerly with synthetic snapshots that fire
    one enqueue edge per tick: the cursor keeps counting past the ring
    size, the ring holds exactly the LAST R events, and j_dropped
    counts every overwrite."""
    spec, state, net, bounds = _build(
        telemetry=True, telemetry_journeys=2, telemetry_journey_ring=8
    )
    telem = state.telem
    tasks = state.tasks
    ids = np.asarray(telem.j_task)
    R = spec.journey_ring
    n_ticks = 13  # > R: forces wrap on every slot
    for i in range(n_ticks):
        # restamp the sampled tasks' queue-enter time each "tick": the
        # diff rule fires exactly one ENQUEUE per sampled task
        tq = tasks.t_q_enter.at[jnp.asarray(ids)].set(
            jnp.float32(0.001 * (i + 1))
        )
        tasks = tasks.replace(t_q_enter=tq)
        telem = jn.journey_tick(
            spec, telem, tasks, jnp.float32(0.001 * (i + 1)),
        )
    cursor = np.asarray(telem.j_cursor)
    assert np.all(cursor == n_ticks)
    assert int(np.asarray(telem.j_dropped)) == 2 * (n_ticks - R)
    final = state.replace(telem=telem)
    for d in jn.decode_rings(spec, final):
        assert d["events_total"] == n_ticks
        assert d["dropped"] == n_ticks - R
        assert len(d["events"]) == R
        # the retained tail is the LAST R enqueues, oldest first
        ts = [round(e["t"], 6) for e in d["events"]]
        want = [
            round(float(np.float32(0.001 * (k + 1))), 6)
            for k in range(n_ticks - R, n_ticks)
        ]
        assert ts == want
        assert all(e["name"] == "enqueue" for e in d["events"])


# ----------------------------------------------------------------------
# expositions: .sca.json / OpenMetrics / flight recorder / postmortem
# ----------------------------------------------------------------------

def test_recorder_exposition_and_postmortem_carry_journeys(tmp_path):
    import subprocess
    import sys as _sys
    from pathlib import Path

    from fognetsimpp_tpu.runtime.recorder import record_run
    from fognetsimpp_tpu.telemetry.live import FlightRecorder

    spec, final = _chaos_hier_final(
        telemetry_journeys=128, telemetry_journey_ring=32
    )
    paths = record_run(str(tmp_path), spec, final, scave=False)
    sca = json.load(open(paths["sca"]))
    js = sca["journeys"]
    assert js["sampled"] == 128
    assert js["events_total"] > 0
    assert "done" in js["terminal"]
    assert any(
        {"reoffload", "migrate"} <= {e["name"] for e in t["events"]}
        for t in js["tasks"]
    )
    # OpenMetrics: families present and the file passes the lint
    om = open(paths["om"]).read()
    assert "fns_journey_sampled 128" in om
    assert "fns_journey_events_total" in om
    assert 'fns_journey_tasks{stage="done"}' in om
    assert "fns_hier_brokers 2" in om
    import tools.check_openmetrics as lint

    assert lint.check_text(om, "journeys.om") == 0
    # flight-recorder bundle: rings snapshot + postmortem --task
    rec = FlightRecorder(capacity=4)
    rec.note_chunk(100, rows={"t": np.asarray([0.1])})
    manifest = rec.dump(
        str(tmp_path), "anomaly", spec=spec, final=final,
    )
    d = json.load(open(manifest))
    assert d["journeys"]["sampled"] == 128
    task_id = d["journeys"]["rings"]["task"][0]
    repo = Path(__file__).resolve().parents[1]
    out = subprocess.run(
        [_sys.executable, str(repo / "tools" / "postmortem.py"),
         "--task", str(task_id), manifest],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    assert f"task {task_id}" in out.stdout
    # pre-journey bundles still summarize (the .get-safe contract)
    legacy = tmp_path / "old.json"
    legacy.write_text(json.dumps({"reason": "nan", "ring": []}))
    out2 = subprocess.run(
        [_sys.executable, str(repo / "tools" / "postmortem.py"),
         str(legacy)],
        capture_output=True, text=True,
    )
    assert out2.returncode == 0, out2.stderr


def test_openmetrics_lint_broker_label_rule():
    """The PR 9 shard-label rule, replayed for the per-broker
    federation families: a missing trailing broker series — which
    previously passed — now fails the lint, as do a missing or
    non-integer broker label."""
    from tools.check_openmetrics import check_text

    def fam(name, samples):
        lines = [f"# HELP {name} x", f"# TYPE {name} gauge"]
        lines += samples
        return lines

    base = fam("fns_hier_brokers", ["fns_hier_brokers 2"])
    good = base + fam(
        "fns_hier_fogs",
        ['fns_hier_fogs{broker="0"} 2', 'fns_hier_fogs{broker="1"} 2'],
    )
    assert check_text("\n".join(good + ["# EOF"]), "t") == 0
    # missing trailing broker series: the published count exposes it
    truncated = base + fam(
        "fns_hier_fogs", ['fns_hier_fogs{broker="0"} 2']
    )
    assert check_text("\n".join(truncated + ["# EOF"]), "t") == 1
    # no broker label at all on a per-broker family
    unlabeled = fam("fns_hier_users", ["fns_hier_users 4"])
    assert check_text("\n".join(unlabeled + ["# EOF"]), "t") == 1
    # non-integer broker label
    stringy = fam(
        "fns_hier_load_mean", ['fns_hier_load_mean{broker="a"} 0.5']
    )
    assert check_text("\n".join(stringy + ["# EOF"]), "t") == 1
    # gap without a published count: still caught via max+1
    gappy = fam(
        "fns_hier_migrations_in",
        [
            'fns_hier_migrations_in{broker="0"} 1',
            'fns_hier_migrations_in{broker="2"} 1',
        ],
    )
    assert check_text("\n".join(gappy + ["# EOF"]), "t") == 1


def test_cli_journeys_composes_with_trace_and_out(tmp_path, capsys):
    from fognetsimpp_tpu.__main__ import main

    trace = tmp_path / "t.json"
    rc = main([
        "--scenario", "smoke", "--telemetry", "--journeys", "3",
        "--out", str(tmp_path), "--trace-out", str(trace),
    ])
    assert rc == 0 or rc is None
    sca = json.load(open(tmp_path / "General-0.sca.json"))
    assert sca["journeys"]["sampled"] == 3
    t = json.loads(trace.read_text())
    assert [e for e in t["traceEvents"] if e.get("cat") == "journey"]
