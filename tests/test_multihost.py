"""Real 2-process jax.distributed multihost test (r2 weakness #6).

Spawns two local processes that join one jax.distributed cluster over a
localhost coordinator (2 virtual CPU devices each -> a 4-device global
mesh spanning both), runs a replica-sharded world through the unmodified
engine, and asserts every process's addressable shards are bit-identical
to the single-process reference.  This exercises the actual DCN-analog
path — process-spanning mesh + cross-process program launch — that the
in-process tests cannot (``tests/test_parallel.py`` covers the
single-process passthrough).
"""
import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_cluster_matches_single_process():
    port = _free_port()
    env = dict(os.environ)
    # the workers pin their own platform/device-count flags
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"MULTIHOST-OK pid={pid}" in out, out
