"""compile_cache unit coverage (ISSUE 13 satellite): the snapshot/delta
interval accounting, the stats-provider hook, and the previously
untested ``FNS_JIT_CACHE=off`` / ``_host_tag`` paths."""
import re

import pytest

from fognetsimpp_tpu import compile_cache


def test_note_compile_and_stats_keys():
    before = compile_cache.compile_stats()
    compile_cache.note_compile(1.5)
    compile_cache.note_compile(0.25, cache_hit=True)
    compile_cache.note_compile(0.25, cache_hit=False)
    after = compile_cache.compile_stats()
    assert after["noted_compiles"] == before.get("noted_compiles", 0) + 3
    assert after["cache_hits"] >= before.get("cache_hits", 0) + 1
    assert after["cache_misses"] >= before.get("cache_misses", 0) + 1
    assert (
        after["noted_compile_s_total"]
        >= before.get("noted_compile_s_total", 0.0) + 2.0 - 1e-9
    )


def test_snapshot_delta_scopes_an_interval():
    """Bench rounds / serve chunks attribute compile seconds to
    THEMSELVES via snapshot + delta — the cumulative-stats gap the
    satellite closes."""
    snap = compile_cache.snapshot()
    assert all(isinstance(v, float) for v in snap.values())
    compile_cache.note_compile(2.0)
    d = compile_cache.delta_since(snap)
    assert d["noted_compiles"] == 1.0
    assert d["noted_compile_s_total"] == pytest.approx(2.0)
    # untouched counters delta to zero
    assert d["compiles"] == 0.0
    # a second snapshot scopes a fresh (empty) interval
    d2 = compile_cache.delta_since(compile_cache.snapshot())
    assert d2["noted_compiles"] == 0.0


def test_delta_handles_counters_born_after_snapshot():
    """noted_* keys appear lazily on first note_compile; a snapshot
    taken before that must still delta cleanly (from zero)."""
    snap = dict(compile_cache.snapshot())
    snap.pop("noted_compiles", None)
    snap.pop("noted_compile_s_total", None)
    compile_cache.note_compile(0.5)
    d = compile_cache.delta_since(snap)
    assert d["noted_compiles"] >= 1.0


def test_compile_s_max_delta_is_new_max_or_zero():
    snap = compile_cache.snapshot()
    d = compile_cache.delta_since(snap)
    assert d["compile_s_max"] == 0.0  # running max did not grow


def test_stats_provider_sections_merge_and_never_raise():
    compile_cache.register_stats_provider("t_ok", lambda: {"x": 1})
    compile_cache.register_stats_provider(
        "t_boom", lambda: 1 / 0
    )
    out = compile_cache.compile_stats()
    assert out["t_ok"] == {"x": 1}
    assert out["t_boom"] is None  # provider failure degrades, not raises
    # last registration wins (idempotent per name)
    compile_cache.register_stats_provider("t_ok", lambda: {"x": 2})
    assert compile_cache.compile_stats()["t_ok"] == {"x": 2}
    # snapshots stay numeric-only: provider dicts never leak into deltas
    assert "t_ok" not in compile_cache.snapshot()


def test_fns_jit_cache_off_disables(monkeypatch):
    """FNS_JIT_CACHE=off (and friends) return None without touching
    jax config; stats accounting still flows (note_compile works)."""
    for off in ("off", "0", "false", ""):
        monkeypatch.setenv("FNS_JIT_CACHE", off)
        assert compile_cache.enable_compile_cache() is None
    snap = compile_cache.snapshot()
    compile_cache.note_compile(0.1)
    assert compile_cache.delta_since(snap)["noted_compiles"] == 1.0


def test_enable_on_cpu_backend_skips(monkeypatch, tmp_path):
    """XLA:CPU executables are skipped by design (the r4 segfault
    note): enable returns None and never creates the directory."""
    monkeypatch.delenv("FNS_JIT_CACHE", raising=False)
    target = tmp_path / "jitcache"
    import jax

    if jax.default_backend() == "cpu":
        assert compile_cache.enable_compile_cache(str(target)) is None
        assert not target.exists()


def test_host_tag_is_stable_and_wellformed():
    t1 = compile_cache._host_tag()
    t2 = compile_cache._host_tag()
    assert t1 == t2
    assert re.fullmatch(r"[0-9a-f]{12}", t1)
