"""Wired v1 world (omnetpp.ini analog), engine-level policy coverage,
and the L2 message-schema map."""
import numpy as np
import pytest

from fognetsimpp_tpu import Policy, Stage, run
from fognetsimpp_tpu.messages import SCHEMAS, live_schemas, message_counts
from fognetsimpp_tpu.runtime import summarize
from fognetsimpp_tpu.scenarios import smoke, wired_v1


def test_wired_v1_local_then_offload():
    """v1 LOCAL_FIRST with the faithful pool leak: the broker's 1000-MIPS
    pool serves the first ~10 fixed-100-MIPS tasks locally, then drains
    and everything else offloads through the MAX_MIPS scan to pool fogs.
    """
    spec, state, net, bounds = wired_v1.build(horizon=3.0)
    final, _ = run(spec, state, net, bounds)
    s = summarize(final)
    # only user 0 publishes (user 1 is subscribe-only)
    assert s["n_published"] == pytest.approx(3.0 / 0.05, abs=3)
    assert 8 <= s["n_local"] <= 10  # pool 1000 / 100-MIPS tasks, strict <
    assert s["n_scheduled"] > 20  # the rest offloaded
    assert s["n_completed"] > 20
    # v1 quirks: local completions ack the client directly (status 6)...
    t = final.tasks
    local_done = np.isfinite(np.asarray(t.t_ack3))
    assert local_done.sum() == s["n_local"]
    # ...but offloaded v1 completions never reach the client (TaskAck
    # dropped by the broker): every finite ack6 belongs to a local task
    ack6 = np.isfinite(np.asarray(t.t_ack6))
    assert (ack6 == local_done).all()
    # the broker pool leaked down to a remainder the strict-< test can
    # never spend (9 x 100 drained; 100 < 100 fails for the 10th)
    assert float(np.asarray(final.broker.local_pool)) <= 100.0
    # subscriber got every publish fanned out
    n_del = np.asarray(final.users.n_delivered)
    assert n_del[1] >= s["n_published"] - 1 and n_del[0] == 0


def test_wired_v1_fixed_task_size():
    spec, state, net, bounds = wired_v1.build(horizon=1.0)
    final, _ = run(spec, state, net, bounds)
    req = np.asarray(final.tasks.mips_req)
    used = np.asarray(final.tasks.stage) != int(Stage.UNUSED)
    assert (req[used] == 100.0).all()  # mqttApp.cc:330


@pytest.mark.parametrize(
    "policy", [Policy.ROUND_ROBIN, Policy.MIN_LATENCY, Policy.ENERGY_AWARE,
               Policy.RANDOM]
)
def test_policies_end_to_end(policy):
    """Every realised `algo` policy schedules through the full engine."""
    spec, state, net, bounds = smoke.build(
        horizon=0.3, send_interval=0.01, n_users=4, policy=int(policy)
    )
    final, _ = run(spec, state, net, bounds)
    s = summarize(final)
    assert s["n_scheduled"] > 20, s
    fogs_used = np.unique(
        np.asarray(final.tasks.fog)[np.asarray(final.tasks.fog) >= 0]
    )
    if policy == Policy.ROUND_ROBIN:
        assert len(fogs_used) == spec.n_fogs  # spread across all fogs
    assert s["n_completed"] + s["stage_queued"] + s["stage_running"] > 0


def test_schema_inventory():
    # all 12 reference .msg types present; Ping pair dead as in the source
    assert len(SCHEMAS) == 12
    assert not SCHEMAS["MqttMsgPingRequest"].live
    assert not SCHEMAS["MqttMsgPingResponse"].live
    assert len(live_schemas()) == 10
    for s in SCHEMAS.values():
        assert s.msg_file.startswith(("mqttMessages/", "fognetMessages/"))


def test_message_counts():
    spec, state, net, bounds = smoke.build(horizon=0.3)
    final, _ = run(spec, state, net, bounds)
    counts = message_counts(spec, final)
    s = summarize(final)
    assert counts["MqttMsgPublish"] == s["n_published"]
    assert counts["FognetMsgTask"] == s["n_scheduled"]
    assert counts["MqttMsgConnect"] == spec.n_users + spec.n_fogs
    # every decided publish got at least the forwarded status-4 ack
    assert counts["MqttMsgPuback"] >= s["n_scheduled"]
    assert counts["MqttMsgPingRequest"] == 0
    # initial advert per fog, plus one per completion (v3 adv_on_completion)
    assert counts["FognetMsgAdvertiseMIPS"] >= spec.n_fogs
