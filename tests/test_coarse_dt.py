"""Coarse-tick fidelity: dt is a staleness knob, not a workload knob.

The engine keeps exact event times at any tick size; ``dt`` only bounds
how stale a decision's broker view can be (core/engine.py module
docstring).  These tests pin that claim: with the multi-send spawn a
coarse tick carries the identical publish workload (bit-equal event
times), conservation holds, and the decision/latency deviation vs a
fine-tick run of the same world stays within the advertised staleness
envelope — the licence for running the throughput benchmark at
``dt ~ adv_interval`` (BENCHMARKS.md).
"""
import dataclasses

import numpy as np
import pytest

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.scenarios import smoke


def _world(dt, max_sends_per_tick, **kw):
    return smoke.build(
        horizon=0.4,
        send_interval=0.005,
        dt=dt,
        n_users=16,
        n_fogs=4,
        fog_mips=(20000.0, 30000.0, 25000.0, 15000.0),
        start_time_max=0.02,
        max_sends_per_tick=max_sends_per_tick,
        **kw,
    )


def test_multi_send_spawn_same_workload():
    """With fixed MIPS (no draw-stream difference) the coarse tick spawns
    the same publish sequence: per-slot event times equal to f32
    summation-order rounding (the sequential phase accumulates
    ``next_send += interval``, the closed form computes ``base + j *
    interval`` — ~1e-7 s), same counts."""
    spec_f, state_f, net, bounds = _world(1e-3, 1, fixed_mips_required=400)
    spec_c, state_c, _, _ = _world(1e-2, 4, fixed_mips_required=400)

    fin_f, _ = run(spec_f, state_f, net, bounds)
    fin_c, _ = run(spec_c, state_c, net, bounds)

    for col in ("t_create", "t_at_broker", "mips_req"):
        a = np.asarray(getattr(fin_f.tasks, col))
        b = np.asarray(getattr(fin_c.tasks, col))
        np.testing.assert_array_equal(
            np.isfinite(a), np.isfinite(b), err_msg=col
        )
        m = np.isfinite(a)
        np.testing.assert_allclose(
            a[m], b[m], rtol=0, atol=1e-6, err_msg=col
        )
    assert int(fin_f.metrics.n_published) == int(fin_c.metrics.n_published)


def test_coarse_dt_fidelity_envelope():
    """dt=1e-2 (the advert-staleness scale) vs dt=1e-3 ground truth on the
    same world: every publish is decided (conservation), the decision
    count matches exactly, per-fog totals shift only within the staleness
    envelope, and the mean end-to-end latency agrees to ~1%."""
    spec_f, state_f, net, bounds = _world(1e-3, 1)
    spec_c, state_c, _, _ = _world(1e-2, 4)

    fin_f, _ = run(spec_f, state_f, net, bounds)
    fin_c, _ = run(spec_c, state_c, net, bounds)

    n_f = int(fin_f.metrics.n_scheduled)
    n_c = int(fin_c.metrics.n_scheduled)
    assert n_f == n_c  # same workload, every publish decided

    # conservation: nothing vanishes at either tick size
    for fin in (fin_f, fin_c):
        stage = np.asarray(fin.tasks.stage)
        used = stage != int(Stage.UNUSED)
        pub = int(fin.metrics.n_published)
        assert used.sum() == pub

    # per-fog assignment histogram: staleness can shift individual
    # choices, but the load split must stay close (normalized L1).  This
    # world is deliberately saturated — the harshest regime for view
    # staleness — so the bound is the envelope, not a typical deviation.
    fog_f = np.asarray(fin_f.tasks.fog)
    fog_c = np.asarray(fin_c.tasks.fog)
    h_f = np.bincount(fog_f[fog_f >= 0], minlength=4).astype(float)
    h_c = np.bincount(fog_c[fog_c >= 0], minlength=4).astype(float)
    l1 = np.abs(h_f / h_f.sum() - h_c / h_c.sum()).sum()
    assert l1 < 0.10, (h_f, h_c)


def test_coarse_dt_latency_within_1pct():
    """Event-time fidelity: at moderate load with uniform fog MIPS (so a
    staleness-shifted choice cannot change service time) the coarse tick
    reproduces per-task latency to well under 1% — exact event times are
    carried at any dt; only decision staleness varies."""
    # 8 users / 0.1 s interval = 80 tasks/s against ~145 tasks/s of fog
    # capacity: queues stay short, so latency reflects transit + service
    # times (exact at any dt) rather than staleness-shifted queue waits —
    # saturated-regime choice deviation is bounded separately by the
    # histogram test above
    kw = dict(
        horizon=1.6,
        send_interval=0.1,
        n_users=8,
        n_fogs=4,
        fog_mips=(20000.0,),
        start_time_max=0.02,
    )
    spec_f, state_f, net, bounds = smoke.build(
        dt=1e-3, max_sends_per_tick=1, **kw
    )
    spec_c, state_c, _, _ = smoke.build(
        dt=1e-2, max_sends_per_tick=4, **kw
    )
    fin_f, _ = run(spec_f, state_f, net, bounds)
    fin_c, _ = run(spec_c, state_c, net, bounds)

    def mean_task_ms(fin):
        t6 = np.asarray(fin.tasks.t_ack6)
        t0_ = np.asarray(fin.tasks.t_create)
        m = np.isfinite(t6) & np.isfinite(t0_)
        return ((t6[m] - t0_[m]) * 1e3).mean(), int(m.sum())

    m_f, c_f = mean_task_ms(fin_f)
    m_c, c_c = mean_task_ms(fin_c)
    assert c_f >= 100 and abs(c_f - c_c) <= max(3, 0.05 * c_f)
    assert abs(m_f - m_c) / m_f < 0.01, (m_f, m_c)


def test_multi_send_spawn_respects_capacity_and_stop():
    """The closed form honours the table capacity and send_stop_time the
    way the sequential phase does."""
    spec, state, net, bounds = _world(
        1e-2, 4, max_sends_per_user=8, send_stop_time=0.1,
        fixed_mips_required=400,
    )
    fin, _ = run(spec, state, net, bounds)
    sc = np.asarray(fin.users.send_count)
    assert (sc <= 8).all()
    t_create = np.asarray(fin.tasks.t_create)
    assert np.nanmax(np.where(np.isfinite(t_create), t_create, np.nan)) < 0.1


def test_multi_send_requires_no_jitter():
    with pytest.raises(AssertionError):
        _world(1e-2, 4, send_interval_jitter=0.1)
