"""Hold-out validation of the demo calibration (VERDICT r4 items 3 + 6).

Every anchor in tests/test_scenarios.py pins statistics of the SAME
committed trace the ``CALIB_*`` constants were fitted to.  This test
closes the loop the honest way: it re-derives every constant from the
**warm-up half only** (arrivals <= 1.5 s) of
``simulations/example/results/General-0.vec`` — parsed with the repo's
own Scave reader — then runs the engine and PREDICTS the held-out
steady-state half (arrivals > 1.5 s): its sample count, its per-sample
arrival times and delays, and its mean.  None of those held-out numbers
is an input to any fit.

The fit window contains the full warm-up structure (link-up instant,
7-packet burst, backlog trickle, pending-queue capacity via the highest
buffered creation index) plus the FIRST direct post-link-up sample
(creation 20, arrival 1.4616 s), which pins the steady transit.  The
prediction that the whole held-out segment repeats that transit with
zero loss is exactly the mechanistic model's claim — under r1-r4's
fitted 26% uniform steady loss this test would fail with probability
~0.999 (0.74^37 chance of the observed 37/37 arrivals).
"""
import numpy as np
import pytest

from fognetsimpp_tpu import run
from fognetsimpp_tpu.runtime.scave import read_vec
from fognetsimpp_tpu.scenarios import example

REF_VEC = "/root/reference/simulations/example/results/General-0.vec"
SPLIT_T = 1.5  # s: fit on arrivals <= this, predict arrivals beyond it


def _committed_delay_samples():
    v = read_vec(REF_VEC, vector_ids={1093})
    assert v["vectors"][1093]["module"] == "WirelessNet.user.udpApp[0]"
    assert v["vectors"][1093]["name"] == "delay:vector"
    _, tt, dd = v["data"][1093]
    return tt, dd


def _fit_from_warmup(tt, dd):
    """Re-derive the calibration constants from arrivals <= SPLIT_T."""
    fit = tt <= SPLIT_T
    t_f, d_f = tt[fit], dd[fit]
    creates = t_f - d_f
    cs = np.sort(creates)
    interval = float(np.median(np.diff(cs)))
    start = float(cs.min())
    link_up = float(t_f.min())  # first drained arrival = link-up instant
    ks = np.rint((creates - start) / interval).astype(int)
    pre = creates < link_up  # buffered creations (link still down)
    burst = np.sort(t_f[t_f < link_up + interval])
    burst_n = int(burst.size)
    drain = float((burst[-1] - link_up) / (burst_n - 1))
    buffer_frames = int(ks[pre].max()) + 1  # highest drained index + 1
    trickle_last = float(t_f[pre].max())
    drain2 = float((trickle_last - burst[-1]) / (buffer_frames - burst_n))
    w_obs = float(d_f[~pre].min())  # the first direct sample's transit
    return dict(
        start=start, interval=interval, link_up=link_up, burst_n=burst_n,
        drain=drain, drain2=drain2, buffer_frames=buffer_frames,
        w_obs=w_obs,
    )


def _run_engine(fit, w_base):
    spec, state, net, bounds = example.build(
        send_interval=fit["interval"],
        w_base=w_base,
        start_time_min=fit["start"],
        start_time_max=fit["start"] + 1e-6,
        link_up_s=fit["link_up"],
        link_drain_s=fit["drain"],
        link_burst_n=fit["burst_n"],
        link_drain2_s=fit["drain2"],
        link_buffer_frames=fit["buffer_frames"],
    )
    final, _ = run(spec, state, net, bounds)
    t = final.tasks
    tab = np.asarray(t.t_at_broker, np.float64)
    tc = np.asarray(t.t_create, np.float64)
    m = np.isfinite(tab) & np.isfinite(tc) & (tab <= float(final.t))
    return tab[m], tab[m] - tc[m]


def test_warmup_fit_predicts_heldout_steady_state():
    tt, dd = _committed_delay_samples()
    fit = _fit_from_warmup(tt, dd)
    # sanity: the fit window derived the committed constants (documents
    # that scenarios/example.py's CALIB_* are what the warm-up pins)
    assert fit["burst_n"] == 7 and fit["buffer_frames"] == 14
    assert abs(fit["link_up"] - example.CALIB_LINK_UP) < 1e-4

    # the engine adds the wired core hops on top of w_base; calibrate
    # that offset on the FIT window's own direct sample, never on the
    # held-out half
    t1, d1 = _run_engine(fit, fit["w_obs"])
    hops = float(d1[t1 <= SPLIT_T].min()) - fit["w_obs"]
    assert 0.0 <= hops < 0.01
    t_eng, d_eng = _run_engine(fit, fit["w_obs"] - hops)

    # ---- prediction vs the held-out segment -------------------------
    hold = tt > SPLIT_T
    eng_hold = t_eng > SPLIT_T
    # exact count: every held-out creation arrives (zero steady loss)
    assert int(eng_hold.sum()) == int(hold.sum())  # 37 samples
    # per-sample arrival times and delays within 2 ms
    np.testing.assert_allclose(
        np.sort(t_eng[eng_hold]), np.sort(tt[hold]), atol=2e-3
    )
    np.testing.assert_allclose(
        np.sort(d_eng[eng_hold]), np.sort(dd[hold]), atol=2e-3
    )
    # held-out mean within 1 ms
    assert abs(d_eng[eng_hold].mean() - dd[hold].mean()) < 1e-3
