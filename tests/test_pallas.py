"""Pallas pairwise-rank kernel vs the jnp reference path (interpret mode)."""
import jax
import jax.numpy as jnp
import numpy as np

from fognetsimpp_tpu.ops.pallas_kernels import pairwise_rank
from fognetsimpp_tpu.ops.queues import plan_arrivals


def _jnp_rank(mask, f_key, t_key):
    K = mask.shape[0]
    ids = jnp.arange(K, dtype=jnp.int32)
    same = f_key[None, :] == f_key[:, None]
    earlier = (t_key[None, :] < t_key[:, None]) | (
        (t_key[None, :] == t_key[:, None]) & (ids[None, :] < ids[:, None])
    )
    before = same & earlier & mask[None, :]
    return jnp.where(mask, jnp.sum(before, axis=1, dtype=jnp.int32), -1)


import pytest


@pytest.mark.parametrize("K", [512, 1024])  # 1024 exercises the multi-tile
def test_pairwise_rank_matches_reference(K):  # grid (row_id = i*tk + iota)
    F = 7
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    mask = jax.random.bernoulli(k1, 0.7, (K,))
    fog = jax.random.randint(k2, (K,), 0, F)
    # coarse times force plenty of exact ties -> id tie-break exercised
    t = jnp.round(jax.random.uniform(k3, (K,), maxval=0.01), 4)
    f_key = jnp.where(mask, fog, F).astype(jnp.int32)
    t_key = jnp.where(mask, t, jnp.inf)

    got = pairwise_rank(mask, f_key, t_key, interpret=True)
    want = _jnp_rank(mask, f_key, t_key)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("K", [512, 1024])  # 1024 = multi-tile accumulation
def test_fused_arrival_plan_matches_reference(K):
    """The r6 fused decide-and-reduce kernel (rank + per-fog counts +
    earliest (time, position) lex-min in one pass) is EXACTLY equal to
    the jnp reference reductions — int sums and lex-mins, so tile order
    cannot perturb it (interpret mode; opt-in on TPU)."""
    import jax.numpy as jnp

    from fognetsimpp_tpu.ops.pallas_kernels import fused_arrival_plan

    F = 7
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    mask = jax.random.bernoulli(k1, 0.6, (K,))
    fog = jax.random.randint(k2, (K,), 0, F)
    t = jnp.round(jax.random.uniform(k3, (K,), maxval=0.01), 4)
    f_key = jnp.where(mask, fog, F).astype(jnp.int32)
    t_key = jnp.where(mask, t, jnp.inf)

    rank, counts, t_min, first = fused_arrival_plan(
        mask, f_key, t_key, F, interpret=True
    )
    np.testing.assert_array_equal(
        np.asarray(rank), np.asarray(_jnp_rank(mask, f_key, t_key))
    )
    per_fog = (f_key[None, :] == jnp.arange(F)[:, None]) & mask[None, :]
    np.testing.assert_array_equal(
        np.asarray(counts),
        np.asarray(jnp.sum(per_fog, axis=1, dtype=jnp.int32)),
    )
    want_tmin = jnp.min(
        jnp.where(per_fog, t_key[None, :], jnp.inf), axis=1
    )
    np.testing.assert_array_equal(np.asarray(t_min), np.asarray(want_tmin))
    ids = jnp.arange(K, dtype=jnp.int32)
    is_tmin = per_fog & (t_key[None, :] == want_tmin[:, None])
    want_first = jnp.min(jnp.where(is_tmin, ids[None, :], K), axis=1)
    np.testing.assert_array_equal(np.asarray(first), np.asarray(want_first))


def test_optin_disqualification_notes_once(monkeypatch, capsys):
    """FNS_PALLAS_RANK / FNS_PALLAS_ARRIVAL set but disqualified (shape
    or backend) -> ONE stderr line each, not silence (ISSUE 5)."""
    from fognetsimpp_tpu.ops import pallas_kernels as pk

    monkeypatch.setenv("FNS_PALLAS_RANK", "1")
    monkeypatch.setenv("FNS_PALLAS_ARRIVAL", "1")
    monkeypatch.setattr(pk, "_warned", set())
    assert pk.pallas_rank_applicable(100) is False  # non-aligned K
    assert pk.pallas_rank_applicable(100) is False  # second call: silent
    assert pk.pallas_arrival_applicable(100, 4) is False
    err = capsys.readouterr().err
    assert err.count("FNS_PALLAS_RANK=1 requested but") == 1
    assert err.count("FNS_PALLAS_ARRIVAL=1 requested but") == 1
    assert "falling back to the XLA path" in err
    # aligned shape on a CPU backend: the note names the backend
    monkeypatch.setattr(pk, "_warned", set())
    assert pk.pallas_rank_applicable(512) is False
    assert "not tpu" in capsys.readouterr().err


def test_plan_arrivals_unchanged_on_cpu():
    # on CPU the jnp path runs; sanity that the dispatch doesn't break it
    K, F = 64, 3
    key = jax.random.PRNGKey(1)
    mask = jax.random.bernoulli(key, 0.5, (K,))
    fog = jax.random.randint(key, (K,), 0, F)
    t = jax.random.uniform(key, (K,))
    plan = plan_arrivals(mask, fog, t, F, jnp.ones((F,), bool))
    r = np.asarray(plan.rank)
    assert (r[np.asarray(mask)] >= 0).all()
    assert (r[~np.asarray(mask)] == -1).all()
