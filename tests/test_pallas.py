"""Pallas pairwise-rank kernel vs the jnp reference path (interpret mode)."""
import jax
import jax.numpy as jnp
import numpy as np

from fognetsimpp_tpu.ops.pallas_kernels import pairwise_rank
from fognetsimpp_tpu.ops.queues import plan_arrivals


def _jnp_rank(mask, f_key, t_key):
    K = mask.shape[0]
    ids = jnp.arange(K, dtype=jnp.int32)
    same = f_key[None, :] == f_key[:, None]
    earlier = (t_key[None, :] < t_key[:, None]) | (
        (t_key[None, :] == t_key[:, None]) & (ids[None, :] < ids[:, None])
    )
    before = same & earlier & mask[None, :]
    return jnp.where(mask, jnp.sum(before, axis=1, dtype=jnp.int32), -1)


import pytest


@pytest.mark.parametrize("K", [512, 1024])  # 1024 exercises the multi-tile
def test_pairwise_rank_matches_reference(K):  # grid (row_id = i*tk + iota)
    F = 7
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    mask = jax.random.bernoulli(k1, 0.7, (K,))
    fog = jax.random.randint(k2, (K,), 0, F)
    # coarse times force plenty of exact ties -> id tie-break exercised
    t = jnp.round(jax.random.uniform(k3, (K,), maxval=0.01), 4)
    f_key = jnp.where(mask, fog, F).astype(jnp.int32)
    t_key = jnp.where(mask, t, jnp.inf)

    got = pairwise_rank(mask, f_key, t_key, interpret=True)
    want = _jnp_rank(mask, f_key, t_key)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_plan_arrivals_unchanged_on_cpu():
    # on CPU the jnp path runs; sanity that the dispatch doesn't break it
    K, F = 64, 3
    key = jax.random.PRNGKey(1)
    mask = jax.random.bernoulli(key, 0.5, (K,))
    fog = jax.random.randint(key, (K,), 0, F)
    t = jax.random.uniform(key, (K,))
    plan = plan_arrivals(mask, fog, t, F, jnp.ones((F,), bool))
    r = np.asarray(plan.rank)
    assert (r[np.asarray(mask)] >= 0).all()
    assert (r[~np.asarray(mask)] == -1).all()
