"""twin/ acceptance rails (ISSUE 17): live ingestion, what-if forks,
multi-tenant front door.

Three contracts:

* **determinism** — the ingestion gate is inert when idle (ingest=True
  with an empty queue is bit-exact vs ingest=False), and a live session
  replayed from its recorded arrival log reproduces IDENTICAL chunk
  state hashes (``[TWIN-INGEST-OFF]`` guards the compiled-out path);
* **what-if** — ``run_whatif`` forked from a mid-session carry matches
  K independent runs of the retuned specs bit-for-bit per cell, and the
  warm ask costs ZERO compile events (the promoted-operand rail);
* **front door** — N tenants with nearby populations share ONE compiled
  chunk program through the bucketed registry, each exposes a
  lint-clean OpenMetrics page, and admission past capacity is the
  one-line ``[TWIN-CAP]`` rejection.
"""
import dataclasses
import json

import jax
import numpy as np
import pytest

from fognetsimpp_tpu.core.engine import run, run_chunked
from fognetsimpp_tpu.scenarios import smoke
from fognetsimpp_tpu.telemetry.health import state_hash
from fognetsimpp_tpu.twin.ingest import (
    IngestQueue,
    make_inject,
    serve_ingest_run,
)
from fognetsimpp_tpu.twin.whatif import parse_grid, run_whatif


def _leaves_equal(a, b) -> None:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ----------------------------------------------------------------------
# ingestion: inert when off / idle, bounded queue, replay determinism
# ----------------------------------------------------------------------

def test_ingest_gate_inert_when_idle():
    """ingest=True with an empty queue is bit-exact vs ingest=False —
    injection lives at host chunk boundaries, never inside the tick."""
    base = dict(telemetry=True, horizon=0.5)
    spec0, st0, net0, b0 = smoke.build(**base)
    spec1, st1, net1, b1 = smoke.build(**base, ingest=True,
                                       ingest_batch=8)
    f0, _ = run(spec0, st0, net0, b0)
    f1, _ = run(spec1, st1, net1, b1)
    _leaves_equal(f0, f1)
    # chunked path with a live-but-idle drain hook: still bit-exact
    q = IngestQueue(capacity=4)
    f2 = run_chunked(spec1, st1, net1, b1, chunk_ticks=200,
                     inject=make_inject(spec1, net1, q))
    _leaves_equal(f0, f2)
    assert q.stats()["injected"] == 0


def test_ingest_queue_is_bounded_and_drop_counted():
    q = IngestQueue(capacity=2)
    assert q.feed(0, 100.0) and q.feed(1, 200.0)
    assert not q.feed(0, 300.0)  # full: dropped, not blocked
    s = q.stats()
    assert s["depth"] == 2 and s["capacity"] == 2
    assert s["accepted"] == 2 and s["dropped"] == 1
    users, mips, _ = q.drain(8)
    assert users == [0, 1] and mips == [100.0, 200.0]
    assert q.depth == 0
    with pytest.raises(ValueError):
        IngestQueue(capacity=0)


def test_make_inject_requires_ingest_gate():
    spec, _, net, _ = smoke.build(horizon=0.01)
    with pytest.raises(ValueError) as e:
        make_inject(spec, net, IngestQueue(capacity=2))
    assert "[TWIN-INGEST-OFF]" in str(e.value)


def test_replay_from_arrival_log(tmp_path):
    """A live session's recorded arrival log replays bit-exactly:
    identical per-chunk state hashes, identical final state, and a
    clean ``tools/postmortem.py --diff`` across the two bundles."""
    from fognetsimpp_tpu.telemetry.live import FlightRecorder

    base = dict(telemetry=True, ingest=True, ingest_batch=8,
                horizon=1.0)
    spec, st, net, b = smoke.build(**base)
    q = IngestQueue(capacity=8)
    q.feed(0, 500.0)
    q.feed(1, 800.0)
    rec = FlightRecorder()
    final, status = serve_ingest_run(
        spec, st, net, b, queue=q, port=None, whatif=False,
        chunk_ticks=250, recorder=rec,
    )
    assert status["ingest"]["injected"] == 2
    log = status["arrival_log"]
    # one drained batch, landed at the first interior boundary
    assert [e["user"] for e in log] == [[0, 1]]
    assert all(e["ticks_done"] == 250 for e in log)
    live_hashes = [e["state_hash"] for e in rec.ring]
    assert len(live_hashes) == 4 and all(live_hashes)

    spec2, st2, net2, b2 = smoke.build(**base)
    rec2 = FlightRecorder()
    final2, status2 = serve_ingest_run(
        spec2, st2, net2, b2, port=None, whatif=False,
        chunk_ticks=250, recorder=rec2, replay_log=log,
    )
    assert [e["state_hash"] for e in rec2.ring] == live_hashes
    _leaves_equal(final, final2)
    # the replay session re-records the same log (round-trip) and its
    # queue stats count the replayed injections
    assert status2["arrival_log"] == log
    assert status2["ingest"]["injected"] == 2

    # both bundles carry the ingest roll-up; --diff sees no divergence
    from tools.postmortem import diff as pm_diff
    from tools.postmortem import load as pm_load

    pa = pm_load(rec.dump(str(tmp_path / "a"), "probe", spec=spec,
                          final=final))
    pb = pm_load(rec2.dump(str(tmp_path / "b"), "probe", spec=spec2,
                           final=final2))
    assert pa["ingest_summary"]["injected"] == 2
    assert pb["ingest_summary"]["injected"] == 2
    lines = pm_diff(pa, pb)
    assert any("state hashes agree" in ln for ln in lines)
    assert not any("fed different" in ln for ln in lines)


# ----------------------------------------------------------------------
# what-if: bit-exact forks, zero warm compiles, future-only deltas
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def whatif_world():
    # uplink_loss_prob starts POSITIVE: what-if retunings must stay on
    # the carry's side of the 0-vs-positive trace gate (the shape
    # bucket) or the grid correctly refuses to answer from the live
    # program — test_whatif_rejects_bad_grids pins that refusal too
    spec, st, net, b = smoke.build(
        telemetry=True, telemetry_hist=True, derive_acks=False,
        horizon=1.0, uplink_loss_prob=0.01,
    )
    carry, _ = run(spec, st, net, b, n_ticks=600)
    return spec, carry, net, b


def test_whatif_fork_matches_cold_runs(whatif_world):
    """Every grid cell's final state is bit-identical to a direct run
    of the retuned spec from the same carry (fork_state re-keys
    NOTHING), and the warm ask compiles ZERO new programs."""
    from fognetsimpp_tpu import compile_cache
    from fognetsimpp_tpu.dynspec import split_spec

    spec, carry, net, b = whatif_world
    values = [0.05, 0.1, 0.2]
    report, batch = run_whatif(
        spec, carry, net, b, {"uplink_loss_prob": values}, 200,
        return_state=True,
    )
    assert report["n_cells"] == 3
    key_spec, _ = split_spec(spec)
    for i, v in enumerate(values):
        _, dyn_v = split_spec(
            dataclasses.replace(spec, uplink_loss_prob=v)
        )
        ref, _ = run(key_spec, carry, net, b, n_ticks=200, dyn=dyn_v)
        row = jax.tree_util.tree_map(lambda a: a[i], batch)
        _leaves_equal(ref, row)
    before = compile_cache.snapshot()
    run_whatif(spec, carry, net, b, {"uplink_loss_prob": values}, 200)
    delta = compile_cache.delta_since(before)
    assert delta["compiles"] == 0


def test_whatif_reports_future_only_deltas(whatif_world):
    spec, carry, net, b = whatif_world
    report = run_whatif(
        spec, carry, net, b, {"uplink_loss_prob": [0.01, 0.5]}, 200
    )
    base = int(carry.metrics.n_published)
    for cell in report["cells"]:
        assert cell["delta"]["n_published"] == (
            cell["counters"]["n_published"] - base
        )
        assert cell["delta"]["n_published"] >= 0
        assert set(cell["quantiles_ms"]) == {"p50", "p95", "p99"}
    assert json.loads(json.dumps(report))  # JSON-serializable contract


def test_whatif_rejects_bad_grids(whatif_world):
    spec, carry, net, b = whatif_world
    with pytest.raises(ValueError):
        run_whatif(spec, carry, net, b, {"uplink_loss_prob": [0.1]}, 0)
    with pytest.raises(ValueError):
        run_whatif(spec, carry, net, b, {"not_a_knob": [1.0]}, 10)
    # a retuning that crosses the 0-vs-positive trace gate leaves the
    # live session's shape bucket: refused, not silently recompiled
    with pytest.raises(ValueError) as e:
        run_whatif(spec, carry, net, b, {"uplink_loss_prob": [0.0]}, 10)
    assert "shape bucket" in str(e.value)
    knobs, ticks = parse_grid("uplink_loss_prob=0.05,0.1 ticks=32")
    assert knobs == {"uplink_loss_prob": [0.05, 0.1]} and ticks == 32
    with pytest.raises(ValueError):
        parse_grid("uplink_loss_prob")
    with pytest.raises(ValueError):
        parse_grid("ticks=100")


# ----------------------------------------------------------------------
# front door: shared program, lint-clean per-tenant pages, [TWIN-CAP]
# ----------------------------------------------------------------------

def test_front_door_shared_program():
    """3 tenants with nearby populations bucket onto ONE compiled chunk
    program; per-tenant and aggregate expositions lint clean; arrivals
    route per tenant; admission past capacity is [TWIN-CAP]."""
    from tools.check_openmetrics import check_text

    from fognetsimpp_tpu.twin.front import FrontDoor, _tenant_chunk

    door = FrontDoor(capacity=3, chunk_ticks=250, bucket_floor=4,
                     port=None)
    for i, n in enumerate((5, 6, 5)):
        spec, st, net, b = smoke.build(
            n_users=n, telemetry=True, ingest=True, ingest_batch=8,
            horizon=1.0, seed=i,
        )
        door.admit(f"t{i}", spec, st, net, b, ingest_capacity=8)
    with pytest.raises(ValueError) as e:
        door.admit("t3", spec, st, net, b)
    assert "[TWIN-CAP]" in str(e.value)

    cache_before = _tenant_chunk._cache_size()
    door.step()
    # one arrival for t1, landed at the next boundary
    status, _, body = door._route(
        "POST", "/t/t1/ingest", b'{"user": 0, "mips": 250.0}'
    )
    assert status == 200
    door.step()
    # nearby populations bucket to the same shape: ONE new program
    assert _tenant_chunk._cache_size() - cache_before == 1

    rows = {r["label"]: r for r in door.tenant_rows()}
    assert rows["t1"]["ticks"] == 500
    for label in ("t0", "t1", "t2"):
        status, ctype, text = door._route("GET", f"/t/{label}/metrics", b"")
        assert status == 200 and "openmetrics" in ctype
        assert check_text(text, where=label) == 0
        status, _, health = door._route("GET", f"/t/{label}/healthz", b"")
        assert status == 200 and json.loads(health)["chunks"] == 2
    assert check_text(door.render_aggregate(), where="aggregate") == 0

    t1 = door._tenants["t1"]
    assert t1.queue.stats()["injected"] == 1
    assert [e["user"] for e in t1.queue.log] == [[0]]

    # what-if routes per tenant from that tenant's own carry
    # 0.0 stays on the carry's side of the 0-vs-positive trace gate
    # (these worlds were built lossless), so the ask reuses the live
    # shape bucket
    status, _, body = door._route(
        "POST", "/t/t0/whatif",
        json.dumps({"knobs": {"uplink_loss_prob": [0.0]},
                    "ticks": 50}).encode(),
    )
    assert status == 200
    rep = json.loads(body)
    assert rep["n_cells"] == 1 and rep["fork_ticks_done"] == 500
    assert door._route("GET", "/t/nope/metrics", b"")[0] == 404
    door.close()
