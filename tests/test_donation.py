"""Buffer donation on the whole-run jit entries (simlint R6): results
bit-identical to the undonated run, and aliased builder states survive
the donate-twice Execute() restriction via _dealias_for_donation."""
import jax
import numpy as np

from fognetsimpp_tpu.core.engine import (
    _dealias_for_donation,
    run,
    run_chunked,
    run_jit,
)
from fognetsimpp_tpu.scenarios import smoke


def _leaves_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_run_jit_donated_bit_exact():
    spec, state, net, bounds = smoke.build(horizon=0.5)
    ref, _ = run(spec, state, net, bounds)  # before donation consumes state
    final = run_jit(spec, state, net, bounds)
    _leaves_equal(ref, final)


def test_run_chunked_donated_bit_exact():
    spec, state, net, bounds = smoke.build(horizon=0.5)
    ref, _ = run(spec, state, net, bounds)
    final = run_chunked(spec, state, net, bounds, chunk_ticks=170)
    _leaves_equal(ref, final)


def test_run_chunked_callback_states_stay_alive():
    """The callback path must NOT donate: a callback may retain each
    chunk-boundary state (checkpoint streaming), and the next chunk
    would otherwise delete those buffers behind its back."""
    spec, state, net, bounds = smoke.build(horizon=0.5)
    ref, _ = run(spec, state, net, bounds)
    snaps = []
    final = run_chunked(
        spec, state, net, bounds, chunk_ticks=170,
        callback=lambda s, t: snaps.append((t, s)),
    )
    _leaves_equal(ref, final)
    for _, s in snaps:  # every retained state is still readable
        assert int(np.asarray(s.tick)) > 0
    assert snaps[-1][1] is final


def test_dealias_copies_only_shared_buffers():
    # smoke.build seeds fogs.pool_avail with the mips array itself: the
    # donation path must copy exactly the aliased leaf, nothing else
    spec, state, net, bounds = smoke.build(horizon=0.4)
    assert state.fogs.mips is state.fogs.pool_avail  # the builder alias
    clean = _dealias_for_donation(state)
    assert clean.fogs.mips is not clean.fogs.pool_avail
    np.testing.assert_array_equal(
        np.asarray(clean.fogs.pool_avail), np.asarray(state.fogs.mips)
    )
    # unaliased leaves pass through untouched (no gratuitous copies)
    assert clean.tasks.stage is state.tasks.stage
