"""Arrival-window overflow: observable and fair (VERDICT r3 weak item 3).

When more than K tasks mature in one tick the excess stays in flight and
is decided later.  r3 had two problems there: the backlog was invisible
(no metric) and compaction always scanned from slot 0, so low-id users'
tasks were systematically decided first.  Now ``Metrics.n_deferred`` /
``n_deferred_max`` expose the backlog and the compaction origin rotates
every tick (engine._rot_and_defer).
"""
import numpy as np

from fognetsimpp_tpu import Policy, Stage, run
from fognetsimpp_tpu.scenarios import smoke


def _overflow_world(**kw):
    # 64 users publishing every 2 ms at dt=1 ms -> ~32 matured publishes
    # per tick against a K=8 window: sustained overflow at the broker
    # (ROUND_ROBIN keeps the compacted path) and at the fog side.
    args = dict(
        horizon=0.6,
        send_interval=0.002,
        dt=1e-3,
        n_users=64,
        n_fogs=4,
        fog_mips=(50000.0,),
        policy=int(Policy.ROUND_ROBIN),
        arrival_window=8,
        queue_capacity=256,
        start_time_max=0.002,
    )
    args.update(kw)
    return smoke.build(**args)


def test_overflow_is_counted():
    spec, state, net, bounds = _overflow_world()
    final, _ = run(spec, state, net, bounds)
    # the gauge saw real backlog, and its max is at least the final value
    assert int(final.metrics.n_deferred_max) > 0
    assert int(final.metrics.n_deferred_max) >= int(final.metrics.n_deferred)
    # conservation as the exact stage-partition identity (VERDICT r4 item
    # 9): every published task occupies exactly one non-UNUSED stage, and
    # the broker's decision counters partition the publishes that left
    # PUB_INFLIGHT — equalities, not the near-tautological inequality r3-r4
    # asserted here
    stage = np.asarray(final.tasks.stage)
    n_pub = int(final.metrics.n_published)
    cnt = {s: int((stage == int(s)).sum()) for s in Stage}
    assert sum(c for s, c in cnt.items() if s != Stage.UNUSED) == n_pub
    m = final.metrics
    decided = (
        int(m.n_scheduled) + int(m.n_no_resource)
        + int(m.n_rejected) + int(m.n_local)
    )
    assert decided == n_pub - cnt[Stage.PUB_INFLIGHT] - cnt[Stage.LOST]
    # scheduled tasks are exactly the ones on (or past) the fog leg (this
    # world runs no local/v1 branch, so DONE rows are all fog completions)
    assert int(m.n_local) == 0 and cnt[Stage.LOCAL_RUN] == 0
    assert int(m.n_scheduled) == (
        cnt[Stage.TASK_INFLIGHT] + cnt[Stage.QUEUED] + cnt[Stage.RUNNING]
        + cnt[Stage.DONE] + cnt[Stage.DROPPED]
    )


def test_overflow_does_not_starve_high_id_users():
    """With a rotating compaction origin, sustained overflow spreads
    deferral across users instead of starving the high-id tail (a fixed
    origin decided user 0's tasks first, every tick)."""
    spec, state, net, bounds = _overflow_world()
    final, _ = run(spec, state, net, bounds)
    stage = np.asarray(final.tasks.stage)
    user = np.asarray(final.tasks.user)
    decided = (
        (stage != int(Stage.UNUSED))
        & (stage != int(Stage.PUB_INFLIGHT))
        & (stage != int(Stage.LOST))
    )
    per_user = np.bincount(user[decided], minlength=spec.n_users)
    # every user makes progress, and the spread is bounded
    assert per_user.min() > 0, per_user
    assert per_user.min() >= 0.25 * per_user.mean(), (
        per_user.min(), per_user.mean()
    )


def _state_hash(state):
    import hashlib

    import jax

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def test_sustained_overflow_fused_vs_unfused_bit_exact():
    """ISSUE 5: the rotation x slot-window interaction under SUSTAINED
    window overflow is the likeliest fused-path bit-exactness hazard —
    the fused front-end must reproduce the rotated K-window compaction
    (which candidates defer, in which window positions) exactly.
    MIN_BUSY keeps the dense broker (so the fused path actually
    engages); the K=8 arrival window overflows at the fog side every
    tick."""
    kw = dict(policy=int(Policy.MIN_BUSY), arrival_window=8)
    spec_f, state_f, net_f, bounds_f = _overflow_world(**kw)
    from fognetsimpp_tpu.core.engine import _fused_ok

    assert _fused_ok(spec_f), "fused path must engage for this A/B"
    final_f, _ = run(spec_f, state_f, net_f, bounds_f)
    assert int(final_f.metrics.n_deferred_max) > 0  # overflow sustained
    spec_u, state_u, net_u, bounds_u = _overflow_world(
        fused_slots=False, **kw
    )
    final_u, _ = run(spec_u, state_u, net_u, bounds_u)
    assert _state_hash(final_f) == _state_hash(final_u)


def test_no_overflow_when_window_auto_sized():
    spec, state, net, bounds = _overflow_world(arrival_window=None)
    auto = spec.auto_arrival_window
    assert auto >= int(1.3 * spec.n_users * spec.dt / spec.send_interval)
    spec2, state2, net2, bounds2 = _overflow_world(arrival_window=auto)
    final, _ = run(spec2, state2, net2, bounds2)
    assert int(final.metrics.n_deferred_max) == 0
