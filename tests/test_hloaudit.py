"""Compiled-artifact auditor (tools/hloaudit, ISSUE 7).

Unit tier: the shared HLO parser and every audit rule on canned HLO
text (host-transfer, f64-promotion, undeclared/degenerate-collective
and phase-attribution/manifest cases — no compiles, milliseconds).

Live tier: seeded regressions through real `jit(...).lower().compile()`
— an injected host sync (`pure_callback` carrying a `float()`) and a
forced f64 `convert` each produce a fatal finding; the TP dryrun step
compiles with ONLY its declared collectives; and the fused tick at the
CPU budget shape audits clean against its checked-in manifest (the same
gate `python -m tools.hloaudit --check` runs in CI).
"""
import dataclasses
import json

import numpy as np
import pytest

from tools.hloaudit.audit import (
    AuditFinding,
    check_collectives,
    check_exact_integer_bound,
    check_f64,
    check_host_transfers,
    check_manifest,
)
from tools.hloaudit.hlo import entry_op_counts, parse_hlo

# ----------------------------------------------------------------------
# canned HLO (the grammar tools/hloaudit/hlo.py documents)
# ----------------------------------------------------------------------

CANNED = """\
HloModule canned.0, entry_computation_layout={(f32[8]{0})->f32[8]{0}}

%fused_computation (param_0: f32[8]) -> f32[8] {
  %param_0 = f32[8]{0} parameter(0)
  ROOT %mul.1 = f32[8]{0} multiply(%param_0, %param_0), metadata={op_name="jit(f)/jit(main)/phase_spawn/mul" source_file="a.py"}
}

ENTRY %main.4 (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  %c0 = f32[] constant(2)
  %fusion = f32[8]{0} fusion(%p0), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(f)/jit(main)/phase_spawn/mul"}
  ROOT %add.2 = f32[8]{0} add(%fusion, %p0), metadata={op_name="jit(f)/jit(main)/phase_broker/add"}
}
"""


def test_parser_counts_and_attribution():
    mod = parse_hlo(CANNED)
    assert mod.name == "canned.0"
    assert [c.name for c in mod.computations] == [
        "fused_computation", "main.4",
    ]
    assert mod.entry.name == "main.4"
    # parameter/constant are trivial; fusion + add count
    assert mod.entry_op_counts() == {"ops": 2, "fusions": 1}
    assert entry_op_counts(CANNED) == {"ops": 2, "fusions": 1}
    # phase attribution rides op_name: the nested fusion body attributes
    # too (all_instructions), ENTRY-only view stays consistent
    assert mod.phase_op_counts() == {"broker": 1, "spawn": 2}
    assert mod.phase_op_counts(entry_only=True) == {"broker": 1, "spawn": 1}


def test_parser_matches_op_budget_counting():
    """tools/op_budget.entry_op_counts IS the shared parser (the ISSUE 7
    refactor): identical numbers on the same text, by construction."""
    from tools.op_budget import entry_op_counts as budget_counts

    assert budget_counts(CANNED) == entry_op_counts(CANNED)


def test_parser_rejects_garbage():
    with pytest.raises(ValueError):
        parse_hlo("not hlo at all\n")


def _entry(body: str) -> str:
    return (
        "HloModule m.0\n\n"
        "ENTRY %main.9 (p0: f32[8]) -> f32[8] {\n"
        "  %p0 = f32[8]{0} parameter(0)\n"
        f"{body}"
        "}\n"
    )


def _rules(findings):
    return sorted({f.rule for f in findings})


# ----------------------------------------------------------------------
# A1 host transfers
# ----------------------------------------------------------------------

def test_a1_host_transfer_op():
    mod = parse_hlo(_entry(
        "  %out = token[] outfeed(%p0), outfeed_config=\"x\"\n"
        "  ROOT %n = f32[8]{0} negate(%p0)\n"
    ))
    assert _rules(check_host_transfers(mod, "v")) == ["A1"]


def test_a1_host_callback_custom_call():
    mod = parse_hlo(_entry(
        "  %cc = f32[8]{0} custom-call(%p0), "
        "custom_call_target=\"xla_ffi_python_cpu_callback\", "
        "custom_call_has_side_effect=true, "
        "metadata={op_name=\"jit(f)/pure_callback\"}\n"
        "  ROOT %n = f32[8]{0} negate(%cc)\n"
    ))
    out = check_host_transfers(mod, "v")
    assert _rules(out) == ["A1"] and "callback" in out[0].message


def test_a1_clean_compute_custom_call():
    # a backend compute kernel custom-call (no host target, no side
    # effect) is NOT a host round-trip
    mod = parse_hlo(_entry(
        "  %cc = f32[8]{0} custom-call(%p0), "
        "custom_call_target=\"__onednn$matmul\"\n"
        "  ROOT %n = f32[8]{0} negate(%cc)\n"
    ))
    assert check_host_transfers(mod, "v") == []


# ----------------------------------------------------------------------
# A2 64-bit floats
# ----------------------------------------------------------------------

def test_a2_f64_convert_chain():
    mod = parse_hlo(_entry(
        "  %cv = f64[8]{0} convert(%p0), "
        "metadata={op_name=\"jit(f)/phase_spawn/convert\"}\n"
        "  %m = f64[8]{0} multiply(%cv, %cv)\n"
        "  ROOT %dn = f32[8]{0} convert(%m)\n"
    ))
    out = check_f64(mod, "v")
    # the promoting convert AND the f64 multiply both surface (the
    # downcast's own line shows only f32) — the chain is visible
    assert len(out) == 2 and _rules(out) == ["A2"]
    assert any("convert" in f.message for f in out)


def test_a2_ignores_metadata_strings():
    # "f64" inside metadata (a source path) must not trip the dtype scan
    mod = parse_hlo(_entry(
        "  ROOT %n = f32[8]{0} negate(%p0), "
        "metadata={op_name=\"jit(f)/f64_compat/neg\" source_file=\"f64[x].py\"}\n"
    ))
    assert check_f64(mod, "v") == []


# ----------------------------------------------------------------------
# A3 collectives
# ----------------------------------------------------------------------

_AG = (
    "  %ag = f32[64]{0} all-gather(%p0), replica_groups={{0,1,2,3,4,5,6,7}}, "
    "dimensions={0}, metadata={op_name=\"jit(f)/shmap_body/all_gather\"}\n"
    "  ROOT %n = f32[8]{0} negate(%p0)\n"
)


def test_a3_collective_in_single_device_compile():
    mod = parse_hlo(_entry(_AG))
    out = check_collectives(mod, "v", sharded=False)
    assert _rules(out) == ["A3"] and "SINGLE-DEVICE" in out[0].message


def test_a3_declared_collective_is_clean():
    mod = parse_hlo(_entry(_AG))
    assert check_collectives(
        mod, "v", sharded=True, declared={"shmap_body": {"all-gather"}}
    ) == []


def test_a3_undeclared_collective():
    mod = parse_hlo(_entry(_AG))
    out = check_collectives(
        mod, "v", sharded=True, declared={"shmap_body": {"all-reduce"}}
    )
    assert _rules(out) == ["A3"] and "undeclared" in out[0].message


def test_a3_degenerate_collective():
    body = _AG.replace("{{0,1,2,3,4,5,6,7}}", "{{0},{1}}")
    mod = parse_hlo(_entry(body))
    out = check_collectives(
        mod, "v", sharded=True, declared={"shmap_body": {"all-gather"}}
    )
    assert _rules(out) == ["A3"] and "degenerate" in out[0].message


def test_a3_async_start_normalizes():
    body = _AG.replace("all-gather(", "all-gather-start(")
    mod = parse_hlo(_entry(body))
    assert check_collectives(
        mod, "v", sharded=True, declared={"shmap_body": {"all-gather"}}
    ) == []


def test_tuple_typed_results_are_parsed():
    """Async collective starts and send/recv carry TUPLE result types
    (spaces in the type text) — the ops A1/A3 exist for.  A parser that
    requires a space-free type silently drops exactly those lines
    (review-pass regression)."""
    body = (
        "  %ags = (f32[8]{0}, f32[64]{0}) all-gather-start(%p0), "
        "replica_groups={{0},{1}}, dimensions={0}, "
        "metadata={op_name=\"jit(f)/shmap_body/all_gather\"}\n"
        "  %agd = f32[64]{0} all-gather-done(%ags)\n"
        "  %rv = (f32[8]{0}, u32[], token[]) recv(%p0), channel_id=1\n"
        "  ROOT %n = f32[8]{0} negate(%p0)\n"
    )
    mod = parse_hlo(_entry(body))
    assert {i.opcode for i in mod.all_instructions()} >= {
        "all-gather-start", "all-gather-done", "recv",
    }
    # the recv is an A1 host transfer; the start op is flagged in a
    # single-device compile AND as degenerate when sharded — the -done
    # half does not double-report
    assert _rules(check_host_transfers(mod, "v")) == ["A1"]
    single = check_collectives(mod, "v", sharded=False)
    assert _rules(single) == ["A3"] and len(single) == 1
    degen = check_collectives(
        mod, "v", sharded=True, declared={"shmap_body": {"all-gather"}}
    )
    assert len(degen) == 1 and "degenerate" in degen[0].message


# ----------------------------------------------------------------------
# A4 f32 exact-integer bound (drift between engine gate and audit)
# ----------------------------------------------------------------------

def test_a4_detects_gate_drift(monkeypatch):
    from fognetsimpp_tpu.core import engine as E
    from fognetsimpp_tpu.scenarios import smoke
    from tools.op_budget import PINNED

    spec, *_ = smoke.build(**PINNED)
    assert check_exact_integer_bound(spec, "v") == []  # in-bound: clean
    big = dataclasses.replace(spec, fixed_mips_required=float(2 ** 24))
    # simulate the drift A4 exists for: the engine's own gate claims the
    # merged reduction is still exact while the audit's independent
    # derivation says the bound is blown
    monkeypatch.setattr(E, "_fused_ok", lambda s: True)
    out = check_exact_integer_bound(big, "v")
    assert _rules(out) == ["A4"] and "2^24" in out[0].message


# ----------------------------------------------------------------------
# A5 manifests
# ----------------------------------------------------------------------

def _manifest(**over):
    m = {
        "max_ops": 2, "max_fusions": 1,
        "phases": {"broker": 1, "spawn": 2},
    }
    m.update(over)
    return m


def test_a5_missing_manifest():
    mod = parse_hlo(CANNED)
    assert _rules(check_manifest(mod, "v", None)) == ["A5"]


def test_a5_within_caps_and_phases_clean():
    mod = parse_hlo(CANNED)
    assert check_manifest(mod, "v", _manifest()) == []


def test_a5_count_regression():
    mod = parse_hlo(CANNED)
    out = check_manifest(mod, "v", _manifest(max_ops=1))
    assert _rules(out) == ["A5"] and "regressed" in out[0].message


def test_a5_phase_set_drift():
    mod = parse_hlo(CANNED)
    out = check_manifest(
        mod, "v", _manifest(phases={"broker": 1, "spawn": 2, "rank": 4})
    )
    assert _rules(out) == ["A5"] and "rank" in out[0].message


# ----------------------------------------------------------------------
# live compiles: seeded regressions must fail, production must not
# ----------------------------------------------------------------------

def test_seeded_host_sync_is_fatal():
    """An injected host round-trip — a `pure_callback` whose host half
    is a `float()` sync — must surface as a fatal A1 in the compiled
    artifact, whatever the source tier missed."""
    import jax
    import jax.numpy as jnp

    def bad(x):
        y = jax.pure_callback(
            lambda a: np.float32(float(a)),
            jax.ShapeDtypeStruct((), jnp.float32),
            jnp.sum(x),
        )
        return x + y

    text = jax.jit(bad).lower(
        jnp.ones((8,), jnp.float32)
    ).compile().as_text()
    out = check_host_transfers(parse_hlo(text), "seeded")
    assert "A1" in _rules(out), text


def test_seeded_f64_convert_is_fatal():
    """A forced f64 promotion must surface as a fatal A2."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    with enable_x64():
        text = jax.jit(
            lambda x: (x.astype(jnp.float64) * 2.0).sum()
        ).lower(jnp.ones((8,), jnp.float32)).compile().as_text()
    out = check_f64(parse_hlo(text), "seeded")
    assert "A2" in _rules(out), text


def test_tp_dryrun_compiles_with_only_declared_collectives():
    """The ROADMAP's correctness rail: the TP fog-sharded argmin step
    carries EXACTLY its declared collectives — nothing else, nothing
    degenerate — and audits clean end-to-end."""
    from fognetsimpp_tpu.parallel.tp import DECLARED_COLLECTIVES
    from tools.hloaudit.audit import audit_module
    from tools.hloaudit.hlo import COLLECTIVE_OPS
    from tools.hloaudit.variants import _compile_tp

    text = _compile_tp().text
    mod = parse_hlo(text)
    seen = {
        (i.opcode[:-6] if i.opcode.endswith("-start") else i.opcode)
        for i in mod.all_instructions()
        if (i.opcode[:-6] if i.opcode.endswith("-start") else i.opcode)
        in COLLECTIVE_OPS
    }
    declared = set().union(*DECLARED_COLLECTIVES.values())
    assert seen == declared, (seen, declared)
    findings = audit_module(
        mod, "tp_dryrun", sharded=True,
        declared_collectives=DECLARED_COLLECTIVES,
        check_manifest_counts=False,
    )
    assert findings == [], "\n".join(f.render() for f in findings)


def test_budget_shape_audits_clean_against_manifest():
    """The CI gate in miniature: compile the fused tick at the pinned
    CPU budget shape and audit it against its checked-in manifest."""
    from tools.hloaudit.__main__ import (
        audit_variant,
        load_manifest,
        load_peak_budgets,
        measure_variant,
    )
    from tools.hloaudit.variants import variants

    v = next(x for x in variants() if x.name == "tick_fused")
    measured = measure_variant(v)
    manifest = load_manifest(v.name)
    assert manifest is not None, "tick_fused manifest not checked in"
    peak = load_peak_budgets().get(v.name)
    assert peak is not None, "tick_fused peak_bytes budget not pinned"
    findings = audit_variant(measured, manifest, peak)
    assert findings == [], "\n".join(f.render() for f in findings)
    # the manifest's recorded counts are the live counts' caps
    assert measured["entry"]["ops"] <= manifest["max_ops"]


def test_manifest_files_are_valid_json():
    import os

    from tools.hloaudit.__main__ import MANIFEST_DIR
    from tools.hloaudit.variants import variants

    names = {v.name for v in variants()}
    on_disk = {
        f[:-5] for f in os.listdir(MANIFEST_DIR) if f.endswith(".json")
    }
    assert on_disk == names, (on_disk, names)
    for f in sorted(on_disk):
        with open(os.path.join(MANIFEST_DIR, f + ".json")) as fh:
            m = json.load(fh)
        assert {"max_ops", "max_fusions", "phases"} <= set(m), f


def test_render_findings_format():
    from tools.hloaudit.audit import render_findings

    f = AuditFinding("A1", "tick_fused", "msg")
    assert render_findings([f]) == "tick_fused: A1: msg"
