"""Parallel-axis tests: vmap replicas, mesh sharding, TP kernel, sweeps.

Run on the 8-device virtual CPU mesh forced by conftest.py — the same
pattern the driver's ``dryrun_multichip`` uses for multi-chip validation
without TPU hardware.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fognetsimpp_tpu import Policy
from fognetsimpp_tpu.ops.sched import schedule_batch
from fognetsimpp_tpu.parallel import (
    make_mesh,
    replicate_state,
    replica_counters,
    run_replicated,
    run_sharded,
    sharded_min_busy,
    sweep_policies,
)
from fognetsimpp_tpu.scenarios import smoke

HORIZON = 0.3


@pytest.fixture(scope="module")
def world():
    return smoke.build(horizon=HORIZON, start_time_max=0.05)


def test_replicas_run_and_diverge(world):
    spec, state, net, bounds = world
    R = 8
    batch = replicate_state(spec, state, R, seed=7)
    final = run_replicated(spec, batch, net, bounds)
    counters = replica_counters(final)
    assert counters["n_published"].shape == (R,)
    assert (counters["n_published"] > 0).all()
    # per-replica PRNG keys -> different task sizes between replicas
    mips = np.asarray(final.tasks.mips_req)
    assert not np.array_equal(mips[0], mips[1])
    # start-time resampling -> different connect times
    st = np.asarray(final.users.start_t)
    assert not np.array_equal(st[0], st[1])


def test_sharded_equals_unsharded(world):
    spec, state, net, bounds = world
    n_dev = len(jax.devices())
    assert n_dev == 8, "conftest must provision 8 virtual devices"
    batch = replicate_state(spec, state, n_dev, seed=7)
    ref = run_replicated(spec, batch, net, bounds)
    mesh = make_mesh(n_dev)
    got = run_sharded(spec, batch, net, bounds, mesh)
    # replica-axis sharding must not change any result bit
    for name in ("t_create", "t_ack5", "t_ack6", "mips_req"):
        a = np.asarray(getattr(ref.tasks, name))
        b = np.asarray(getattr(got.tasks, name))
        np.testing.assert_array_equal(a, b, err_msg=name)
    np.testing.assert_array_equal(
        np.asarray(ref.metrics.n_completed), np.asarray(got.metrics.n_completed)
    )
    # and the output really is distributed over the mesh
    assert len(got.tasks.t_ack6.sharding.device_set) == n_dev


def test_sharded_min_busy_matches_kernel():
    mesh = make_mesh(8, axis_name="fog")
    F, K = 16, 8
    key = jax.random.PRNGKey(3)
    k1, k2, k3 = jax.random.split(key, 3)
    view_busy = jax.random.uniform(k1, (F,), maxval=2.0)
    view_mips = jax.random.uniform(k2, (F,), minval=100.0, maxval=4000.0)
    registered = jnp.ones((F,), bool).at[3].set(False)
    mask = jnp.ones((K,), bool).at[K - 1].set(False)
    mips_req = jax.random.uniform(k3, (K,), minval=200.0, maxval=900.0)

    want, _ = schedule_batch(
        int(Policy.MIN_BUSY), mask, mips_req, view_busy, view_mips,
        registered, jnp.ones((F,), bool), jnp.ones((F,)),
        jnp.zeros((F,)), jnp.zeros((), jnp.int32), key,
        mips0_divisor=False,
    )
    got = sharded_min_busy(
        mesh, mask, mips_req, view_busy, view_mips, registered, divisor=None,
        axis_name="fog",
    )
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))

    # the mips0_divisor bug path (BrokerBaseApp3.cc:268)
    want_b, _ = schedule_batch(
        int(Policy.MIN_BUSY), mask, mips_req, view_busy, view_mips,
        registered, jnp.ones((F,), bool), jnp.ones((F,)),
        jnp.zeros((F,)), jnp.zeros((), jnp.int32), key,
        mips0_divisor=True,
    )
    got_b = sharded_min_busy(
        mesh, mask, mips_req, view_busy, view_mips, registered,
        divisor=view_mips[0], axis_name="fog",
    )
    np.testing.assert_array_equal(np.asarray(want_b), np.asarray(got_b))

    # all-unregistered -> -1 everywhere
    got_none = sharded_min_busy(
        mesh, mask, mips_req, view_busy, view_mips,
        jnp.zeros((F,), bool), divisor=None, axis_name="fog",
    )
    assert (np.asarray(got_none)[np.asarray(mask)] == -1).all()


def test_node_sharded_engine_bit_identical():
    """TP: task/user arrays sharded over the mesh, engine unmodified.

    GSPMD partitions the per-shard phases and inserts the K-window
    collectives; results must equal the single-device run exactly.
    """
    from fognetsimpp_tpu.parallel import run_node_sharded
    from fognetsimpp_tpu.parallel.mesh import make_mesh

    spec, state, net, bounds = smoke.build(
        n_users=8, n_fogs=2, horizon=0.3, send_interval=0.02,
        max_sends_per_user=24,  # T = 192 -> 24 rows/device
    )
    from fognetsimpp_tpu import run as run_plain

    ref, _ = run_plain(spec, state, net, bounds)
    mesh = make_mesh(8, axis_name="node")
    got = run_node_sharded(spec, state, net, bounds, mesh)
    assert len(got.tasks.t_ack6.sharding.device_set) == 8
    for name in ("t_create", "t_ack6", "stage", "mips_req", "fog"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref.tasks, name)),
            np.asarray(getattr(got.tasks, name)),
            err_msg=name,
        )
    np.testing.assert_array_equal(
        np.asarray(ref.metrics.n_completed), np.asarray(got.metrics.n_completed)
    )
    # shape guard: uneven worlds are rejected, not silently gathered
    spec2, state2, net2, bounds2 = smoke.build(
        n_users=3, n_fogs=2, horizon=0.1, max_sends_per_user=8
    )
    with pytest.raises(ValueError, match="divide"):
        run_node_sharded(spec2, state2, net2, bounds2, mesh)


def test_node_sharded_wireless_world():
    """GSPMD also partitions the wireless machinery (mobility, per-tick AP
    association/handover) with sharded task/user state."""
    from fognetsimpp_tpu.parallel import run_node_sharded
    from fognetsimpp_tpu.parallel.mesh import make_mesh
    from fognetsimpp_tpu.scenarios import wireless

    spec, state, net, bounds = wireless.wireless4(
        numb_users=8, horizon=2.0, dt=5e-3
    )
    from fognetsimpp_tpu import run as run_plain

    ref, _ = run_plain(spec, state, net, bounds)
    mesh = make_mesh(8, axis_name="node")
    got = run_node_sharded(spec, state, net, bounds, mesh)
    for name in ("t_create", "t_ack6", "stage", "fog"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref.tasks, name)),
            np.asarray(getattr(got.tasks, name)),
            err_msg=name,
        )


def test_multihost_single_process_path():
    from fognetsimpp_tpu.parallel import global_mesh, initialize

    assert initialize() == 1  # no cluster env: single-process passthrough
    mesh = global_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert mesh.axis_names == ("replica",)


def test_sweep_policies(world):
    spec, state, net, bounds = world
    del spec, state  # sweep builds its own worlds
    grids = sweep_policies(
        smoke.build,
        policies=[int(Policy.MIN_BUSY), int(Policy.ROUND_ROBIN)],
        load_intervals=[0.05, 0.02],
        n_replicas_per_load=2,
        horizon=HORIZON,
        start_time_max=0.05,
    )
    for pol, grid in grids.items():
        assert grid["n_published"].shape == (2, 2)
        # heavier load (shorter interval) publishes strictly more
        assert (grid["n_published"][1] > grid["n_published"][0]).all(), pol
        assert (grid["n_scheduled"] > 0).all()


def test_dynamic_policy_matches_static():
    """Policy.DYNAMIC (policy as traced data) == the static compile."""
    import jax.numpy as jnp

    from fognetsimpp_tpu.core.engine import run as run_engine

    for pol in (Policy.MIN_BUSY, Policy.ROUND_ROBIN):
        spec_s, state_s, net, bounds = smoke.build(
            horizon=HORIZON, policy=int(pol), start_time_max=0.05
        )
        want, _ = run_engine(spec_s, state_s, net, bounds)
        spec_d, state_d, net_d, bounds_d = smoke.build(
            horizon=HORIZON, policy=int(Policy.DYNAMIC), start_time_max=0.05
        )
        state_d = state_d.replace(
            broker=state_d.broker.replace(
                policy_id=jnp.asarray(int(pol), jnp.int32)
            )
        )
        got, _ = run_engine(spec_d, state_d, net_d, bounds_d)
        np.testing.assert_array_equal(
            np.asarray(want.tasks.fog), np.asarray(got.tasks.fog), err_msg=pol
        )
        np.testing.assert_array_equal(
            np.asarray(want.tasks.t_ack6), np.asarray(got.tasks.t_ack6)
        )


def test_sweep_dynamic_single_compile_matches_static():
    static = sweep_policies(
        smoke.build,
        policies=[int(Policy.MIN_BUSY), int(Policy.MIN_LATENCY)],
        load_intervals=[0.05, 0.02],
        n_replicas_per_load=2,
        horizon=HORIZON,
        start_time_max=0.05,
    )
    dynamic = sweep_policies(
        smoke.build,
        policies=[int(Policy.MIN_BUSY), int(Policy.MIN_LATENCY)],
        load_intervals=[0.05, 0.02],
        n_replicas_per_load=2,
        horizon=HORIZON,
        start_time_max=0.05,
        dynamic=True,
    )
    for pol in static:
        for k in ("n_published", "n_scheduled", "n_completed"):
            np.testing.assert_array_equal(
                static[pol][k], dynamic[pol][k], err_msg=f"{pol}:{k}"
            )
