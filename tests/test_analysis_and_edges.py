"""Analysis report + the edge worlds the verify recipe probes, as tests."""
import numpy as np
import pytest

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.runtime import analyze, record_run, render_report, summarize


def test_trails_svg(tmp_path):
    """The Tkenv movement/communication-trail analog renders headlessly."""
    from fognetsimpp_tpu import run
    from fognetsimpp_tpu.runtime.trails import render_trails_svg
    from fognetsimpp_tpu.scenarios import wireless

    spec, state, net, bounds = wireless.wireless2(
        horizon=0.5, record_tick_series=True, record_trails=True
    )
    final, series = run(spec, state, net, bounds)
    out = str(tmp_path / "trails.svg")
    render_trails_svg(spec, final, series, out, net=net)
    svg = open(out).read()
    assert "<svg" in svg and "</svg>" in svg
    # one trail per user, AP squares + range circles, counters
    assert svg.count("polyline") == spec.n_users
    assert svg.count("<rect") == spec.n_aps
    assert "sent:" in svg and "rcvd:" in svg and "broker" in svg
from fognetsimpp_tpu.scenarios import smoke


def test_analyze_and_report(tmp_path):
    spec, state, net, bounds = smoke.build(horizon=0.3)
    final, _ = run(spec, state, net, bounds)
    record_run(str(tmp_path), spec, final, run_id="a0")
    record_run(str(tmp_path), spec, final, run_id="a1")
    res = analyze(str(tmp_path))
    assert set(res) == {"a0", "a1"}
    sig = res["a0"]["signals"]
    assert sig["latency_h1"]["n"] > 0
    assert sig["latency_h1"]["max"] >= sig["latency_h1"]["p95"]
    report = render_report(res)
    assert "== run a0" in report and "latency_h1" in report
    with pytest.raises(FileNotFoundError):
        analyze(str(tmp_path / "nope"))


def test_no_fogs_world():
    spec, state, net, bounds = smoke.build(
        horizon=0.3, n_fogs=0, fog_mips=(1000.0,)
    )
    final, _ = run(spec, state, net, bounds)
    s = summarize(final)
    # every decided publish hits "no compute resource available"
    assert s["n_no_resource"] > 0 and s["n_scheduled"] == 0
    assert s["n_no_resource"] + s["stage_pub_inflight"] == s["n_published"]


def test_tiny_queue_drops_counted():
    spec, state, net, bounds = smoke.build(
        horizon=0.3, queue_capacity=2, send_interval=0.01
    )
    final, _ = run(spec, state, net, bounds)
    s = summarize(final)
    assert s["n_dropped"] > 0
    assert int(np.asarray(final.fogs.q_drops).sum()) == s["n_dropped"]
    assert (np.asarray(final.fogs.q_len) <= 2).all()


def test_send_stop_time():
    """stopTime NED param: publishing ceases mid-horizon (mqttApp2.cc:191)."""
    spec, state, net, bounds = smoke.build(
        horizon=0.3, send_interval=0.01, send_stop_time=0.1
    )
    final, _ = run(spec, state, net, bounds)
    s = summarize(final)
    expect = spec.n_users * 0.1 / 0.01
    assert s["n_published"] <= expect + spec.n_users
    assert s["n_published"] >= expect - 2 * spec.n_users
    t_create = np.asarray(final.tasks.t_create)
    assert t_create[np.isfinite(t_create)].max() < 0.1 + 1e-6


def test_coarse_dt_degrades_gracefully():
    """dt 50x the link delay: fidelity drops but conservation holds."""
    spec, state, net, bounds = smoke.build(horizon=0.5, dt=5e-2)
    final, _ = run(spec, state, net, bounds)
    s = summarize(final)
    assert s["n_published"] > 0 and s["n_scheduled"] > 0
    live = (s["stage_pub_inflight"] + s["stage_task_inflight"] + s["stage_queued"]
            + s["stage_running"])
    term = (s["stage_done"] + s["n_no_resource"] + s["n_dropped"]
            + s["n_rejected"])
    assert live + term == s["n_published"]
    # exact event times stay causal even under coarse observation
    t = final.tasks
    sched = np.isfinite(np.asarray(t.t_at_fog))
    assert (
        np.asarray(t.t_at_fog)[sched]
        >= np.asarray(t.t_at_broker)[sched] - 1e-6
    ).all()
