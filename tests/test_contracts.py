"""Tier-1 trace-time contract checks (simlint R8's runtime half).

Everything here runs under JAX_PLATFORMS=cpu via jax.eval_shape — no
FLOPs, no device buffers — so a carry-dtype promotion that would silently
recompile every tick on TPU fails in seconds on CPU instead."""
import jax.numpy as jnp
import numpy as np
import pytest

from fognetsimpp_tpu.core import contracts
from fognetsimpp_tpu.core.engine import make_step
from fognetsimpp_tpu.scenarios import smoke


def _worlds():
    # FIFO v3 argmin-family world (dense broker), v2 POOL LOCAL_FIRST
    # world (compacted broker + pool phases + v2 release timer), a
    # coarse-dt multi-send world (spawn_multi), a learned-policy world
    # (compacted broker + the bandit credit phase), and a telemetry
    # world (plane-1 accumulation phase, ISSUE 4)
    return [
        smoke.build(horizon=0.4),
        smoke.build(
            horizon=0.4, dt=1e-3, send_interval=0.008, n_users=3,
            n_fogs=2, app_gen=2, fog_model=1, policy=5,
            broker_mips=2048.0, v2_local_broker=True,
        ),
        smoke.build(
            horizon=0.3, dt=0.2, send_interval=0.05, max_sends_per_tick=8
        ),
        smoke.build(horizon=0.4, policy=8),  # Policy.UCB
        smoke.build(horizon=0.4, telemetry=True, telemetry_hist=True),
        # chaos fault-injection world (ISSUE 12: the lifecycle/sweep
        # phase + retry carry; assume_static off — liveness mutates)
        # composed with the federated hierarchy (ISSUE 14: the migrate
        # phase + domain-masked decide, HierState in the carry) — one
        # world traces both subsystems' phases, keeping the registry
        # sweep inside the tier-1 time budget
        smoke.build(
            horizon=0.4, chaos=True, chaos_mode=1, chaos_mtbf_s=0.1,
            chaos_mttr_s=0.05, chaos_script=((0, 0.1, 0.2),),
            n_brokers=2, hier_policy=1, hier_threshold=0.5,
        ),
        # journey-tap world (ISSUE 15: the end-of-tick snapshot-diff
        # phase + the j_* ring leaves in the TelemetryState carry)
        smoke.build(
            horizon=0.4, telemetry=True, telemetry_journeys=4,
            telemetry_journey_ring=16,
        ),
        # live-ingestion world (ISSUE 17: the chunk-boundary arrival
        # injection phase — draw-free, gated on spec.ingest)
        smoke.build(
            horizon=0.4, telemetry=True, ingest=True, ingest_batch=8,
        ),
    ]


def test_step_contract_holds_for_all_worlds():
    for spec, state, net, bounds in _worlds():
        contracts.check_step_contract(spec, state, net, bounds)


def test_phase_contracts_hold_and_cover_registry():
    checked = set()
    for spec, state, net, _ in _worlds():
        checked.update(contracts.check_phase_contracts(spec, state, net))
    registry = {pc.name for pc in contracts.PHASE_CONTRACTS}
    assert checked == registry, (
        f"phases never traced by any test world: {registry - checked}"
    )


def test_injected_carry_dtype_promotion_fails():
    spec, state, net, bounds = _worlds()[0]
    step = make_step(spec)

    def promoted_step(s, n, b):
        out = step(s, n, b)
        # int8 stage + strong int32 promotes the carry leaf to int32 —
        # exactly the class of bug R8 exists to catch
        return out.replace(
            tasks=out.tasks.replace(stage=out.tasks.stage + jnp.int32(1))
        )

    with pytest.raises(contracts.ContractError, match="stage"):
        contracts.check_step_contract(
            spec, state, net, bounds, step=promoted_step
        )


def test_injected_shape_drift_fails():
    spec, state, net, bounds = _worlds()[0]
    step = make_step(spec)

    def truncated_step(s, n, b):
        out = step(s, n, b)
        return out.replace(
            tasks=out.tasks.replace(mips_req=out.tasks.mips_req[:-1])
        )

    with pytest.raises(contracts.ContractError, match="mips_req"):
        contracts.check_step_contract(
            spec, state, net, bounds, step=truncated_step
        )


def test_checkpoint_load_rejects_drifted_leaf(tmp_path):
    from fognetsimpp_tpu.runtime import checkpoint

    spec, state, net, bounds = _worlds()[0]
    p = str(tmp_path / "ck.npz")
    checkpoint.save(p, spec, state)
    spec2, state2 = checkpoint.load(p)  # clean round-trip still works

    # tamper one int8 leaf into int32 (the promotion a buggy writer or a
    # layout drift would produce) and reload
    with np.load(p) as z:
        data = {k: z[k] for k in z.files}
    victim = next(
        k for k, v in data.items()
        if k.startswith("leaf_") and v.dtype == np.int8
    )
    data[victim] = data[victim].astype(np.int32)
    np.savez_compressed(p, **data)
    with pytest.raises(contracts.ContractError, match="int32"):
        checkpoint.load(p)


def test_assume_static_bianchi_rejected_consistently():
    """ADVICE r5: the assume_static x Bianchi-keyed-MAC conflict must
    fail at SPEC CONSTRUCTION (WorldSpec.validate via mac_keyed), and a
    hand-built under-declared spec must get the SAME error from a direct
    make_step() trace as from run() — the entries may not disagree."""
    import dataclasses

    from fognetsimpp_tpu.core.engine import run
    from fognetsimpp_tpu.scenarios import wireless
    from fognetsimpp_tpu.spec import WorldSpec

    # spec-level: fails at construction
    with pytest.raises(ValueError, match="Bianchi"):
        WorldSpec(
            n_users=2, n_fogs=2, assume_static=True, mac_keyed=True
        ).validate()

    # builders declare the keyed MAC on the spec
    spec, state, net, bounds = wireless.wireless4(
        numb_users=4, horizon=0.2, dt=5e-3
    )
    assert spec.mac_keyed and net.mac_loss_tab.shape[0] > 0

    # net-level belt-and-braces: an under-declared spec gets the same
    # error from both entry points (make_step used to fall silently
    # into the per-tick offered-rate path)
    bad = dataclasses.replace(spec, mac_keyed=False, assume_static=True)
    step = make_step(bad)
    with pytest.raises(ValueError, match="Bianchi"):
        step(state, net, bounds)
    with pytest.raises(ValueError, match="Bianchi"):
        run(bad, state, net, bounds)


def test_delay_table_rejects_keyed_mac_with_energy():
    """ADVICE r5: delay_table itself (not just replay_engine_world) must
    refuse Bianchi-keyed worlds with the energy lifecycle — its send
    chain assumes an always-alive user set."""
    import dataclasses

    from fognetsimpp_tpu.native.bridge import delay_table
    from fognetsimpp_tpu.scenarios import wireless

    spec, state, net, bounds = wireless.wireless4(
        numb_users=4, horizon=0.2, dt=5e-3
    )
    bad = dataclasses.replace(spec, energy_enabled=True)
    with pytest.raises(NotImplementedError, match="energy"):
        delay_table(bad, state, net, bounds, n_ticks=2)
    # the guard does not over-reach: the keyed, energy-free world still
    # produces its table
    assert delay_table(spec, state, net, bounds, n_ticks=2).shape[0] == 2
