"""The C++-DES parity gate: batched engine vs native event-driven core.

The native core (``native/desim.cpp``) executes the v3 hot path one event at
a time on a heap — the sequential execution model of the reference
(OMNeT++'s role).  The batched engine replays the *same publish workload*
(identical task creation times and sizes) through its tick pipeline; this
test asserts the two agree per task — same fog choices, same exact ack/
completion times — within the ≤1% criterion of BASELINE.json.

With ``dt <= min link delay`` the tick engine's decision ordering matches
the event order exactly, so agreement here is near-bitwise (f32 vs f64
rounding only).
"""
import numpy as np
import pytest

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.native import bridge
from fognetsimpp_tpu.scenarios import smoke


@pytest.fixture(scope="module")
def worlds():
    spec, state, net, bounds = smoke.build(
        horizon=2.0,
        send_interval=0.05,
        dt=1e-4,  # <= min link delay: exact decision ordering
        n_users=2,
        n_fogs=2,
        # fast fogs -> steady state: most tasks complete inside the horizon
        # (the overloaded default would leave all but ~5 queued)
        fog_mips=(20000.0, 30000.0),
        start_time_max=0.02,
    )
    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(spec, final, net)
    return spec, final, des, used


def _eng(final, used, col):
    return np.asarray(getattr(final.tasks, col), np.float64)[used]


def test_native_core_builds():
    assert bridge.build().endswith(".so")


def test_workload_and_choices_match(worlds):
    spec, final, des, used = worlds
    assert used.sum() >= 70  # ~80 publishes in 2 s
    # publish transit is delay arithmetic only — must match to f32 eps
    np.testing.assert_allclose(
        _eng(final, used, "t_at_broker"), des["t_at_broker"], rtol=1e-5
    )
    # scheduling decisions are discrete: any divergence is an ordering bug
    eng_fog = np.asarray(final.tasks.fog)[used]
    decided = des["fog"] >= 0
    assert decided.all()
    np.testing.assert_array_equal(eng_fog, des["fog"])


def test_completion_times_within_1pct(worlds):
    spec, final, des, used = worlds
    eng_done = np.asarray(final.tasks.stage)[used] == int(Stage.DONE)
    des_done = des["stage"] == int(Stage.DONE)
    # end-of-horizon straddlers may differ by one in-flight task
    assert abs(int(eng_done.sum()) - int(des_done.sum())) <= 1
    both = eng_done & des_done
    assert both.sum() >= 30

    t0 = _eng(final, used, "t_create")[both]
    for col in ("t_complete", "t_ack6", "t_ack5", "t_service_start"):
        e = _eng(final, used, col)[both]
        d = des[col][both]
        fin = np.isfinite(e) & np.isfinite(d)
        assert (np.isfinite(e) == np.isfinite(d)).all(), col
        # per-task latency (measured from creation) within 1%
        lat_e, lat_d = e[fin] - t0[fin], d[fin] - t0[fin]
        rel = np.abs(lat_e - lat_d) / np.maximum(np.abs(lat_d), 1e-9)
        assert rel.max() < 0.01, (col, rel.max())

    # mean end-to-end task time within 1% (the headline parity number)
    lat_e = _eng(final, used, "t_ack6")[both] - t0
    lat_d = des["t_ack6"][both] - t0
    assert abs(lat_e.mean() - lat_d.mean()) / lat_d.mean() < 0.01


def test_parity_under_queueing():
    """Loaded regime: FIFO queues form, promote, and drain identically."""
    spec, state, net, bounds = smoke.build(
        horizon=1.5,
        send_interval=0.04,
        dt=1e-4,
        n_users=3,
        n_fogs=2,
        fog_mips=(4000.0, 6000.0),
        start_time_max=0.02,
    )
    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(spec, final, net)
    np.testing.assert_array_equal(np.asarray(final.tasks.fog)[used], des["fog"])
    eng_q = _eng(final, used, "queue_time_ms") / 1e3
    both_q = np.isfinite(eng_q) & np.isfinite(des["queue_time"])
    assert both_q.sum() >= 10  # real queueing happened
    np.testing.assert_allclose(
        eng_q[both_q], des["queue_time"][both_q], rtol=1e-2, atol=1e-5
    )
    done = (np.asarray(final.tasks.stage)[used] == int(Stage.DONE)) & (
        des["stage"] == int(Stage.DONE)
    )
    t0 = _eng(final, used, "t_create")[done]
    lat_e = _eng(final, used, "t_ack6")[done] - t0
    lat_d = des["t_ack6"][done] - t0
    rel = np.abs(lat_e - lat_d) / np.maximum(lat_d, 1e-9)
    assert rel.max() < 0.01


@pytest.mark.parametrize("policy", [1, 2])  # ROUND_ROBIN, MIN_LATENCY
def test_parity_other_policies(policy):
    """The realised `algo` policies also match the sequential DES exactly.

    Power-of-two fog MIPS make every service time exactly representable,
    so the engine's f32 busyTime and the DES's f64 carry identical values
    and score ties break identically (non-representable rates leave
    different rounding dust in the two precisions and flip near-ties —
    an arithmetic artefact, not a scheduling divergence).
    """
    import jax.numpy as jnp

    from fognetsimpp_tpu.core.engine import prime_initial_advertisements

    spec, state, net, bounds = smoke.build(
        horizon=1.0,
        send_interval=0.05,
        dt=1e-4,
        n_users=2,
        n_fogs=3,
        fog_mips=(16384.0, 32768.0, 8192.0),
        start_time_max=0.02,
        policy=policy,
    )
    # heterogeneous fog access delays: without these MIN_LATENCY would
    # degenerate to MIN_BUSY + const and its rtt term would go untested
    fog_nodes = np.arange(spec.n_fogs) + spec.n_users
    acc = np.asarray(net.node_acc).copy()
    acc[fog_nodes] += np.asarray([5e-4, 0.0, 1e-3])
    net = net.replace(node_acc=jnp.asarray(acc))
    state = prime_initial_advertisements(spec, state, net)

    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(spec, final, net)
    ef = np.asarray(final.tasks.fog)[used]
    np.testing.assert_array_equal(ef, des["fog"])
    if policy == 2:
        # the rtt term really decided: the cheapest-link fog dominates
        # (a pure min-busy tie-break would prefer fog 0)
        assert (ef == 1).sum() > (ef == 0).sum(), np.bincount(ef[ef >= 0])
    e = _eng(final, used, "t_ack6")
    both = np.isfinite(e) & np.isfinite(des["t_ack6"])
    assert both.sum() >= 20
    np.testing.assert_allclose(e[both], des["t_ack6"][both], rtol=1e-5)


def test_parity_fixed_bug_modes():
    """Both simulators honour the repaired-bug switches identically
    (per-candidate MIPS divisor, true-argmax offload scan)."""
    from fognetsimpp_tpu.spec import BugCompat

    spec, state, net, bounds = smoke.build(
        horizon=1.0,
        send_interval=0.05,
        dt=1e-4,
        n_users=2,
        n_fogs=2,
        fog_mips=(20000.0, 30000.0),
        start_time_max=0.02,
        bug_compat=BugCompat(mips0_divisor=False, v1_max_scan=False),
    )
    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(spec, final, net)
    np.testing.assert_array_equal(np.asarray(final.tasks.fog)[used], des["fog"])
    e = _eng(final, used, "t_ack6")
    both = np.isfinite(e) & np.isfinite(des["t_ack6"])
    assert both.sum() >= 20
    np.testing.assert_allclose(e[both], des["t_ack6"][both], rtol=1e-5)


def test_parity_v1_local_first():
    """v1 generation: LOCAL_FIRST pool debits, the buggy MAX_MIPS offload
    scan, pool fogs, TaskAck-dropped completions — vs the native DES."""
    from fognetsimpp_tpu.scenarios import wired_v1

    spec, state, net, bounds = wired_v1.build(horizon=1.5, dt=2e-4)
    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(spec, final, net)

    eng_stage = np.asarray(final.tasks.stage)[used]
    np.testing.assert_array_equal(eng_stage, des["stage"])
    # local tasks: status-3 ack + direct status-6 completion times
    local3 = _eng(final, used, "t_ack3")
    both3 = np.isfinite(local3) & np.isfinite(des["t_ack3"])
    assert both3.sum() >= 8  # the ~9 pool-funded local tasks
    np.testing.assert_allclose(local3[both3], des["t_ack3"][both3], rtol=1e-5)
    ack6 = _eng(final, used, "t_ack6")
    both6 = np.isfinite(ack6) & np.isfinite(des["t_ack6"])
    assert (np.isfinite(ack6) == np.isfinite(des["t_ack6"])).all()
    np.testing.assert_allclose(ack6[both6], des["t_ack6"][both6], rtol=1e-5)
    # offloaded pool tasks: same fogs, completion times within 1%
    np.testing.assert_array_equal(np.asarray(final.tasks.fog)[used], des["fog"])
    tc = _eng(final, used, "t_complete")
    done = np.isfinite(tc) & np.isfinite(des["t_complete"])
    rel = np.abs(tc[done] - des["t_complete"][done]) / des["t_complete"][done]
    assert rel.max() < 0.01


def test_parity_v2_pool():
    """v2 generation: POOL fogs with periodic adverts + status-6 relay."""
    spec, state, net, bounds = smoke.build(
        horizon=1.5,
        send_interval=0.05,
        dt=2e-4,
        n_users=2,
        n_fogs=2,
        fog_mips=(1000.0, 2000.0),
        start_time_max=0.02,
        app_gen=2,
        fog_model=1,  # POOL
        policy=6,  # MAX_MIPS
        adv_on_completion=False,
        adv_periodic=True,
    )
    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(spec, final, net)
    # exact gate (r3): the engine splits the POOL fog phases at the
    # periodic-advert boundary so the advertised pool is captured at the
    # exact fire time (engine.py make_step) — decisions now agree 100%,
    # like the v3/v1 gates (the r2 gate tolerated 5% divergence)
    np.testing.assert_array_equal(np.asarray(final.tasks.fog)[used], des["fog"])
    np.testing.assert_array_equal(
        np.asarray(final.tasks.stage)[used], des["stage"]
    )
    ack6 = _eng(final, used, "t_ack6")
    both = np.isfinite(ack6) & np.isfinite(des["t_ack6"])
    assert both.sum() >= 40
    t0 = _eng(final, used, "t_create")[both]
    lat_e = ack6[both] - t0
    lat_d = des["t_ack6"][both] - t0
    rel = np.abs(lat_e - lat_d) / np.maximum(lat_d, 1e-9)
    assert rel.max() < 1e-3


def test_parity_v2_hybrid_broker():
    """The v2 hybrid broker (spec.v2_local_broker): local pool accepts,
    the shared single release timer with its cancel-leak, offload-request
    storage and pool-inflating refunds — engine vs native DES, exact.

    Publishes every 4 ms (< requiredTime = 10 ms) keep cancelling the
    release self-message, so the pool leaks, overflow offloads to the
    POOL fogs, and releases only happen when the publish stream pauses
    (send_stop_time) — the exact mechanism behind the committed demo
    run's per-fog traffic split.
    """
    spec, state, net, bounds = smoke.build(
        horizon=1.0,
        send_interval=0.004,
        dt=1e-4,
        n_users=2,
        n_fogs=2,
        fog_mips=(1024.0, 2048.0),
        app_gen=2,
        fog_model=1,  # POOL
        policy=5,  # LOCAL_FIRST (the v2 hybrid)
        broker_mips=2048.0,
        v2_local_broker=True,
        adv_on_completion=False,
        adv_periodic=True,
        send_stop_time=0.5,  # a quiet tail lets queued releases fire
        max_sends_per_user=130,
    )
    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(spec, final, net)
    np.testing.assert_array_equal(
        np.asarray(final.tasks.stage)[used], des["stage"]
    )
    np.testing.assert_array_equal(np.asarray(final.tasks.fog)[used], des["fog"])
    stage = np.asarray(final.tasks.stage)[used]
    # the leak really bit: locals ran, overflow offloaded, and at least
    # one release fired (a DONE local exists)
    assert (stage == int(Stage.LOCAL_RUN)).sum() > 0  # still leaked
    assert (np.asarray(final.tasks.fog)[used] >= 0).sum() > 5
    ack6 = _eng(final, used, "t_ack6")
    both = np.isfinite(ack6) & np.isfinite(des["t_ack6"])
    assert both.sum() >= 10
    np.testing.assert_allclose(ack6[both], des["t_ack6"][both], rtol=1e-5)


def test_parity_random_shared_stream():
    """RANDOM policy: both simulators consume the identical task-id-keyed
    unit-draw stream (ops/sched.py::task_uniform), so choices are exact —
    the r2 gap of 'no shared PRNG in the DES' is closed."""
    spec, state, net, bounds = smoke.build(
        horizon=2.0,
        send_interval=0.05,
        dt=1e-4,
        n_users=2,
        n_fogs=3,
        fog_mips=(16384.0, 32768.0, 8192.0),
        start_time_max=0.02,
        policy=4,  # RANDOM
    )
    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(spec, final, net)
    ef = np.asarray(final.tasks.fog)[used]
    np.testing.assert_array_equal(ef, des["fog"])
    assert len(set(ef.tolist())) == 3  # the stream actually spreads load
    ack6 = _eng(final, used, "t_ack6")
    both = np.isfinite(ack6) & np.isfinite(des["t_ack6"])
    assert both.sum() >= 30
    np.testing.assert_allclose(ack6[both], des["t_ack6"][both], rtol=1e-5)


def test_parity_energy_aware():
    """ENERGY_AWARE: the DES now carries the same per-fog joule model
    (message costs at event times), so the energy-biased argmin has a real
    sequential baseline and the engine's energy accounting is anchored
    against an independent implementation (r2 weaknesses #3/#5)."""
    import jax.numpy as jnp

    spec, state, net, bounds = smoke.build(
        horizon=2.0,
        send_interval=0.05,
        dt=1e-4,
        n_users=2,
        n_fogs=2,
        # power-of-two MIPS and 2^-8 J message quanta: every busyTime and
        # energy value is exactly representable, so the engine's f32 and
        # the DES's f64 carry identical numbers and score ties break
        # identically (same trick as test_parity_other_policies)
        fog_mips=(16384.0, 32768.0),
        # users publish simultaneously (start spread 0): decisions sit on
        # the 50 ms wave grid while fog arrivals land +d_bf off-grid, so
        # no decision races an arrival inside one tick — the engine's
        # <=1-tick energy-booking skew can never flip a choice and the
        # gate is exact by construction
        policy=3,  # ENERGY_AWARE
        energy_enabled=True,
        energy_capacity_j=1.0,
        tx_energy_j=1.0 / 256.0,
        rx_energy_j=1.0 / 256.0,
        idle_power_w=0.0,
        compute_power_w=0.0,
        harvest_power_w=0.0,
    )
    # fogs participate in the energy model; users stay outside it
    has = np.zeros((spec.n_nodes,), bool)
    has[spec.n_users : spec.n_users + spec.n_fogs] = True
    state = state.replace(
        nodes=state.nodes.replace(has_energy=jnp.asarray(has))
    )
    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(spec, final, net)
    ef = np.asarray(final.tasks.fog)[used]
    np.testing.assert_array_equal(ef, des["fog"])
    # the energy term really decided: both fogs serve (pure min-busy with
    # these MIPS would keep returning to the same winner on ties)
    counts = np.bincount(ef[ef >= 0], minlength=2)
    assert counts.min() >= 10, counts
    ack6 = _eng(final, used, "t_ack6")
    both = np.isfinite(ack6) & np.isfinite(des["t_ack6"])
    assert both.sum() >= 30
    np.testing.assert_allclose(ack6[both], des["t_ack6"][both], rtol=1e-5)
    # independent anchor for the joule model: final fog energies agree to
    # within the <= one-tick booking skew
    eng_e = np.asarray(final.nodes.energy, np.float64)[
        spec.n_users : spec.n_users + spec.n_fogs
    ]
    np.testing.assert_allclose(eng_e, des["fog_energy"], rtol=0.01)


def test_queue_times_match(worlds):
    spec, final, des, used = worlds
    eng_q = _eng(final, used, "queue_time_ms") / 1e3
    des_q = des["queue_time"]
    both = np.isfinite(eng_q) & np.isfinite(des_q)
    # queued-vs-assigned classification can differ only for completion/
    # arrival races inside one tick; none at dt <= link delay
    assert (np.isfinite(eng_q) == np.isfinite(des_q)).mean() > 0.95
    if both.any():
        np.testing.assert_allclose(eng_q[both], des_q[both], rtol=1e-2,
                                   atol=1e-5)


def test_parity_fog0_registers_last():
    """ADVICE r3: the zero-view tie anchors the FIRST REGISTERED fog.

    Fog slot 0's access link is slowed so it registers AFTER the first
    publishes are decided: in that window brokers[0] is fog 1, and with
    the MIPS=0 registration view every estimate is +inf — the strict-<
    scan keeps brokers[0].  Both simulators must route those early
    publishes to fog 1, never to the not-yet-registered slot 0.
    """
    import jax.numpy as jnp

    from fognetsimpp_tpu.core.engine import prime_initial_advertisements

    # Slow fogs keep completion adverts spaced far beyond fog 0's 6 ms
    # transit, and the 0.3 s horizon keeps f32-vs-f64 view_busy drift from
    # producing near-tie argmin flips: both are modelling-envelope effects
    # of the pathological 60x-slower link, not the registration-order
    # semantics under test.
    spec, state, net, bounds = smoke.build(
        horizon=0.3,
        send_interval=0.02,
        dt=1e-4,
        n_users=2,
        n_fogs=3,
        fog_mips=(2000.0, 3000.0, 2500.0),
        start_time_max=0.001,
    )
    acc = np.asarray(net.node_acc).copy()
    acc[spec.n_users + 0] = 6e-3  # fog 0 registers at ~6 ms
    net = net.replace(node_acc=jnp.asarray(acc))
    state = prime_initial_advertisements(spec, state, net)

    final, _ = run(spec, state, net, bounds)
    des, used = bridge.replay_engine_world(spec, final, net)

    reg0 = float(np.asarray(state.broker.register_t)[0])
    t_dec = np.asarray(final.tasks.t_at_broker)[used]
    eng_fog = np.asarray(final.tasks.fog)[used]
    early = t_dec < reg0
    assert early.any()  # the divergence-prone window was exercised
    assert (eng_fog[early] != 0).all()  # never the unregistered slot 0
    np.testing.assert_array_equal(eng_fog, des["fog"])
