"""Arrival-window compaction: O(K) hot phases must not change results.

The broker/fog phases gather masked task rows into a ``spec.window`` buffer
(sort + score cost O(K) instead of O(T)).  With K at least the per-tick
arrival count the trajectory must be bit-identical to the uncompacted run;
with K pathologically small, arrivals spill into later ticks but conservation
still holds.
"""
import numpy as np

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.scenarios import smoke


def _run(**kw):
    kw.setdefault("horizon", 0.4)
    kw.setdefault("send_interval", 0.05)
    spec, state, net, bounds = smoke.build(**kw)
    final, _ = run(spec, state, net, bounds)
    return spec, final


def test_small_window_matches_full():
    spec_full, f_full = _run()
    assert spec_full.window == spec_full.task_capacity
    spec_k, f_k = _run(arrival_window=8)
    assert spec_k.window == 8
    for col in ("stage", "fog", "t_at_broker", "t_at_fog", "t_service_start",
                "t_complete", "t_ack5", "t_ack6", "mips_req"):
        np.testing.assert_array_equal(
            np.asarray(getattr(f_full.tasks, col)),
            np.asarray(getattr(f_k.tasks, col)),
            err_msg=col,
        )


def test_overflowing_window_still_conserves():
    """K=1: one decision per tick; everything else waits in flight."""
    spec, final = _run(arrival_window=1, horizon=0.3)
    stage = np.asarray(final.tasks.stage)
    published = int(final.metrics.n_published)
    assert published > 0
    in_system = (stage != int(Stage.UNUSED)).sum()
    assert in_system == published
    # no task is lost: every row is in a legal stage
    assert int(final.metrics.n_scheduled) > 0


def test_rotated_compaction_matches_oracle():
    """The (block x in-block) rotated selection picks exactly the first K
    set bits of the rotated scan order — checked against a pure-python
    oracle over random masks and rotations."""
    import jax.numpy as jnp
    import numpy as np

    from fognetsimpp_tpu.core.engine import _compact, _compact_lane_width

    rng = np.random.default_rng(0)
    T, K = 5000, 16
    C = _compact_lane_width(T)
    B = -(-T // C)
    for trial in range(6):
        mask = rng.random(T) < (0.02 if trial % 2 else 0.5)
        rot = int(rng.integers(0, 10_000))
        idx, idxc, valid = _compact(
            jnp.asarray(mask), K, T, jnp.asarray(rot, jnp.int32)
        )
        idx = np.asarray(idx)
        rot_b = rot % B
        c0 = (rot * 7919) % C
        want = []
        for bpos in range(B):
            b = (rot_b + bpos) % B
            for p in range(C):
                j = (c0 + p) % C
                slot = b * C + j
                if slot < T and mask[slot]:
                    want.append(slot)
                    if len(want) == K:
                        break
            if len(want) == K:
                break
        got = idx[np.asarray(valid)]
        np.testing.assert_array_equal(got, np.asarray(want)[: len(got)])


def test_two_stage_arrivals_matches_full_front_end():
    """The per-user candidate front-end (spec.two_stage_arrivals, r5) is
    bit-identical to the classic full-table compaction whenever at most
    ``spec.arrival_cands`` tasks per user mature per tick — which holds
    by construction at dt <= send_interval.  Exercised with saturated
    queues so the fast-drop path (the (F,T)-GEMM replacement) is hit."""
    kw = dict(
        horizon=0.5, send_interval=0.002, dt=1e-3, n_users=48, n_fogs=3,
        fog_mips=(400.0, 800.0, 1200.0), queue_capacity=4,
        start_time_max=0.004,
    )
    _, f_two = _run(two_stage_arrivals=True, **kw)
    _, f_full = _run(two_stage_arrivals=False, **kw)
    assert int(f_two.metrics.n_dropped) > 0  # fast drop actually exercised
    for col in ("stage", "fog", "t_at_broker", "t_at_fog",
                "t_service_start", "t_complete", "t_q_enter", "t_ack5",
                "t_ack4_queued", "t_ack6", "queue_time_ms", "mips_req"):
        np.testing.assert_array_equal(
            np.asarray(getattr(f_two.tasks, col)),
            np.asarray(getattr(f_full.tasks, col)),
            err_msg=col,
        )
    for m in ("n_scheduled", "n_completed", "n_dropped", "n_published"):
        assert int(getattr(f_two.metrics, m)) == int(
            getattr(f_full.metrics, m)
        ), m


def test_two_stage_arrivals_caps_defer_benignly():
    """More matured arrivals per user per tick than candidate slots
    (forced via arrival_cands_per_user=1 on a coarse window) defer to
    later ticks: conservation holds and the backlog gauge sees them."""
    kw = dict(
        horizon=0.4, send_interval=0.002, dt=8e-3, n_users=16, n_fogs=2,
        fog_mips=(50000.0,), max_sends_per_tick=4, queue_capacity=256,
        start_time_max=0.002,
    )
    spec, final = _run(
        two_stage_arrivals=True, arrival_cands_per_user=1, **kw
    )
    stage = np.asarray(final.tasks.stage)
    assert (stage != int(Stage.UNUSED)).sum() == int(
        final.metrics.n_published
    )
    assert int(final.metrics.n_deferred_max) > 0
    assert int(final.metrics.n_completed) > 0
