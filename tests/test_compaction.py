"""Arrival-window compaction: O(K) hot phases must not change results.

The broker/fog phases gather masked task rows into a ``spec.window`` buffer
(sort + score cost O(K) instead of O(T)).  With K at least the per-tick
arrival count the trajectory must be bit-identical to the uncompacted run;
with K pathologically small, arrivals spill into later ticks but conservation
still holds.
"""
import numpy as np

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.scenarios import smoke


def _run(**kw):
    kw.setdefault("horizon", 0.4)
    kw.setdefault("send_interval", 0.05)
    spec, state, net, bounds = smoke.build(**kw)
    final, _ = run(spec, state, net, bounds)
    return spec, final


def test_small_window_matches_full():
    spec_full, f_full = _run()
    assert spec_full.window == spec_full.task_capacity
    spec_k, f_k = _run(arrival_window=8)
    assert spec_k.window == 8
    for col in ("stage", "fog", "t_at_broker", "t_at_fog", "t_service_start",
                "t_complete", "t_ack5", "t_ack6", "mips_req"):
        np.testing.assert_array_equal(
            np.asarray(getattr(f_full.tasks, col)),
            np.asarray(getattr(f_k.tasks, col)),
            err_msg=col,
        )


def test_overflowing_window_still_conserves():
    """K=1: one decision per tick; everything else waits in flight."""
    spec, final = _run(arrival_window=1, horizon=0.3)
    stage = np.asarray(final.tasks.stage)
    published = int(final.metrics.n_published)
    assert published > 0
    in_system = (stage != int(Stage.UNUSED)).sum()
    assert in_system == published
    # no task is lost: every row is in a legal stage
    assert int(final.metrics.n_scheduled) > 0


def test_rotated_compaction_matches_oracle():
    """The (block x in-block) rotated selection picks exactly the first K
    set bits of the rotated scan order — checked against a pure-python
    oracle over random masks and rotations."""
    import jax.numpy as jnp
    import numpy as np

    from fognetsimpp_tpu.core.engine import _compact

    rng = np.random.default_rng(0)
    T, K, C = 5000, 16, 1024
    B = -(-T // C)
    for trial in range(6):
        mask = rng.random(T) < (0.02 if trial % 2 else 0.5)
        rot = int(rng.integers(0, 10_000))
        idx, idxc, valid = _compact(
            jnp.asarray(mask), K, T, jnp.asarray(rot, jnp.int32)
        )
        idx = np.asarray(idx)
        rot_b = rot % B
        c0 = (rot * 7919) % C
        want = []
        for bpos in range(B):
            b = (rot_b + bpos) % B
            for p in range(C):
                j = (c0 + p) % C
                slot = b * C + j
                if slot < T and mask[slot]:
                    want.append(slot)
                    if len(want) == K:
                        break
            if len(want) == K:
                break
        got = idx[np.asarray(valid)]
        np.testing.assert_array_equal(got, np.asarray(want)[: len(got)])
