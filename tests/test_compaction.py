"""Arrival-window compaction: O(K) hot phases must not change results.

The broker/fog phases gather masked task rows into a ``spec.window`` buffer
(sort + score cost O(K) instead of O(T)).  With K at least the per-tick
arrival count the trajectory must be bit-identical to the uncompacted run;
with K pathologically small, arrivals spill into later ticks but conservation
still holds.
"""
import numpy as np

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.scenarios import smoke


def _run(**kw):
    kw.setdefault("horizon", 0.4)
    kw.setdefault("send_interval", 0.05)
    spec, state, net, bounds = smoke.build(**kw)
    final, _ = run(spec, state, net, bounds)
    return spec, final


def test_small_window_matches_full():
    spec_full, f_full = _run()
    assert spec_full.window == spec_full.task_capacity
    spec_k, f_k = _run(arrival_window=8)
    assert spec_k.window == 8
    for col in ("stage", "fog", "t_at_broker", "t_at_fog", "t_service_start",
                "t_complete", "t_ack5", "t_ack6", "mips_req"):
        np.testing.assert_array_equal(
            np.asarray(getattr(f_full.tasks, col)),
            np.asarray(getattr(f_k.tasks, col)),
            err_msg=col,
        )


def test_overflowing_window_still_conserves():
    """K=1: one decision per tick; everything else waits in flight."""
    spec, final = _run(arrival_window=1, horizon=0.3)
    stage = np.asarray(final.tasks.stage)
    published = int(final.metrics.n_published)
    assert published > 0
    in_system = (stage != int(Stage.UNUSED)).sum()
    assert in_system == published
    # no task is lost: every row is in a legal stage
    assert int(final.metrics.n_scheduled) > 0
