"""Live health plane (ISSUE 6): streaming latency histograms, SLO
watchdog, flight recorder, live endpoint, bench-trend gate.

Gate structure mirrors tests/test_telemetry.py: the zero-row
``telemetry_hist`` leaves are inert (state-hash A/B across run entries
and fleet-vs-vmap; histogram ON perturbs not one non-telem bit), the
device-resident buckets agree with host-side ground truth sample by
sample, and every derived consumer — OpenMetrics quantile gauges,
``.sca.json`` rows, the live endpoint — reads ONE hist_summary() dict,
asserted here to 1e-6.
"""
import dataclasses
import json
import urllib.request
from pathlib import Path

import jax
import numpy as np
import pytest

from fognetsimpp_tpu import Policy, run
from fognetsimpp_tpu.scenarios import smoke

SMALL = dict(n_users=2, n_fogs=2, send_interval=0.05, horizon=0.4)

WORLDS = [
    dict(policy=int(Policy.MIN_BUSY)),  # dense broker path
    dict(policy=int(Policy.LOCAL_FIRST), broker_mips=2048.0),  # compacted
    dict(policy=int(Policy.UCB)),  # learned (learn + telem carry fields)
]


def _state_hash(state) -> str:
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _build(**kw):
    args = dict(SMALL)
    args.update(kw)
    return smoke.build(**args)


# ----------------------------------------------------------------------
# gate: hist off is inert, hist on is read-only
# ----------------------------------------------------------------------

def test_hist_off_leaves_zero_row_and_entries_bit_exact():
    """With telemetry_hist off every histogram leaf has zero rows and
    run / run_jit / run_chunked produce bit-identical final states —
    the spec.telemetry discipline, nested one level deeper."""
    from fognetsimpp_tpu.core.engine import run_chunked, run_jit

    for kw in WORLDS:
        spec, state, net, bounds = _build(telemetry=True, **kw)
        assert not spec.telemetry_hist
        assert spec.telemetry_hist_fogs == 0
        assert spec.telemetry_hist_tasks == 0
        ref, _ = run(spec, state, net, bounds)
        assert ref.telem.lat_hist.shape == (0, 0)
        assert ref.telem.lat_seen.shape == (0,)
        h_ref = _state_hash(ref)
        spec2, state2, net2, bounds2 = _build(telemetry=True, **kw)
        assert _state_hash(run_jit(spec2, state2, net2, bounds2)) == h_ref
        spec3, state3, net3, bounds3 = _build(telemetry=True, **kw)
        assert (
            _state_hash(run_chunked(spec3, state3, net3, bounds3, 170))
            == h_ref
        )


def test_hist_on_never_perturbs_the_simulation():
    """Histogram ON is read-only: every non-telem leaf of the final
    state is bit-equal to the hist-off run of the same world, across
    run / run_jit / run_chunked."""
    from fognetsimpp_tpu.core.engine import run_chunked, run_jit

    for kw in WORLDS:
        spec_off, s_off, net, bounds = _build(telemetry=True, **kw)
        ref, _ = run(spec_off, s_off, net, bounds)
        spec_on, s_on, net2, bounds2 = _build(
            telemetry=True, telemetry_hist=True, **kw
        )
        assert spec_on.telemetry_hist_fogs == spec_on.n_fogs
        got, _ = run(spec_on, s_on, net2, bounds2)
        for f in dataclasses.fields(ref):
            if f.name == "telem":
                continue
            for a, b in zip(
                jax.tree.leaves(getattr(ref, f.name)),
                jax.tree.leaves(getattr(got, f.name)),
            ):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b), err_msg=f.name
                )
        # ...and the hist-on entries agree among themselves bit-for-bit
        h_got = _state_hash(got)
        spec4, s4, net4, bounds4 = _build(
            telemetry=True, telemetry_hist=True, **kw
        )
        assert _state_hash(run_jit(spec4, s4, net4, bounds4)) == h_got
        spec5, s5, net5, bounds5 = _build(
            telemetry=True, telemetry_hist=True, **kw
        )
        assert (
            _state_hash(run_chunked(spec5, s5, net5, bounds5, 170))
            == h_got
        )


def test_fleet_carries_hist_identically_to_vmap():
    from fognetsimpp_tpu.parallel import make_mesh, replicate_state
    from fognetsimpp_tpu.parallel.fleet import fleet_latency_hist, run_fleet
    from fognetsimpp_tpu.parallel.replicas import run_replicated

    spec, state, net, bounds = _build(
        telemetry=True, telemetry_hist=True, horizon=0.2
    )
    batch = replicate_state(spec, state, 8, seed=3)
    ref = run_replicated(spec, batch, net, bounds)
    got = run_fleet(spec, batch, net, bounds, make_mesh(8), donate=False)
    for a, b in zip(jax.tree.leaves(ref.telem), jax.tree.leaves(got.telem)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    merged = fleet_latency_hist(spec, got)
    per_replica = np.asarray(got.telem.lat_hist, np.int64)  # (R, F, B)
    assert per_replica.shape[0] == 8
    np.testing.assert_array_equal(
        merged["counts"], per_replica.sum(axis=0)
    )


# ----------------------------------------------------------------------
# accumulators vs host ground truth
# ----------------------------------------------------------------------

def _ground_world(**kw):
    return _build(
        n_users=4, horizon=2.0, telemetry=True, telemetry_hist=True, **kw
    )


def test_hist_matches_host_ground_truth():
    """Device bucket counts equal a host re-binning of the task_time
    sample vector: same count, same buckets, same sums — and identical
    whether the run went through one scan or ragged chunks (the
    lat_seen exactly-once flag)."""
    from fognetsimpp_tpu.core.engine import run_chunked
    from fognetsimpp_tpu.runtime.signals import extract_signals
    from fognetsimpp_tpu.telemetry.health import hist_edges_s, hist_summary

    spec, state, net, bounds = _ground_world()
    final, _ = run(spec, state, net, bounds)
    summ = hist_summary(spec, final)
    tt = extract_signals(final)["task_time"]  # ms
    assert summ["count"] == tt.size > 0
    assert abs(summ["sum_ms"] - tt.sum()) <= 1e-2
    edges_ms = hist_edges_s(spec).astype(np.float64) * 1e3
    host_bins = np.bincount(
        np.searchsorted(edges_ms, tt),
        minlength=spec.telemetry_hist_bins,
    )
    np.testing.assert_array_equal(summ["counts"].sum(axis=0), host_bins)
    # chunked run streams the identical histogram (exactly-once across
    # chunk boundaries, including acks processed late)
    spec2, state2, net2, bounds2 = _ground_world()
    final2 = run_chunked(spec2, state2, net2, bounds2, 170)
    np.testing.assert_array_equal(
        np.asarray(final.telem.lat_hist), np.asarray(final2.telem.lat_hist)
    )


def test_hist_excludes_broker_local_completions():
    """Broker-local completions keep fog == NO_TASK (-1): they have no
    fog row to land in, so they must not be clipped into fog 0's
    buckets — the per-fog histogram covers fog-executed tasks only."""
    from fognetsimpp_tpu import Stage
    from fognetsimpp_tpu.telemetry.health import hist_summary

    spec, state, net, bounds = _build(
        policy=int(Policy.LOCAL_FIRST), broker_mips=2048.0,
        n_users=4, horizon=2.0, telemetry=True, telemetry_hist=True,
    )
    final, _ = run(spec, state, net, bounds)
    fog = np.asarray(final.tasks.fog)
    ack6 = np.asarray(final.tasks.t_ack6)
    done = (
        (np.asarray(final.tasks.stage) == int(Stage.DONE))
        & np.isfinite(ack6)
        & (ack6 <= float(final.t))
    )
    assert (done & (fog < 0)).any(), (
        "world grew no broker-local completions; the exclusion gate "
        "is untested"
    )
    summ = hist_summary(spec, final)
    want = np.bincount(
        fog[done & (fog >= 0)], minlength=spec.n_fogs
    )
    np.testing.assert_array_equal(summ["per_fog_count"], want)
    assert summ["count"] == int(want.sum())


def test_slo_breach_count_matches_bucket_snap():
    from fognetsimpp_tpu.runtime.signals import extract_signals
    from fognetsimpp_tpu.telemetry.health import (
        hist_edges_s,
        slo_breach_count,
    )

    spec, state, net, bounds = _ground_world()
    final, _ = run(spec, state, net, bounds)
    tt = extract_signals(final)["task_time"]
    edges_ms = hist_edges_s(spec).astype(np.float64) * 1e3
    for slo in (1.0, 20.0, 500.0, 1e6):
        got = slo_breach_count(spec, final, slo)
        snap = edges_ms[min(
            int(np.searchsorted(edges_ms, slo)), len(edges_ms) - 1
        )]
        want = int((tt > snap).sum()) if slo <= edges_ms[-1] else 0
        assert got == want, (slo, got, want)
    # off world -> None
    spec0, s0, n0, b0 = _build()
    f0, _ = run(spec0, s0, n0, b0)
    assert slo_breach_count(spec0, f0, 10.0) is None


def test_openmetrics_hist_quantiles_match_sca_json(tmp_path):
    """The ISSUE 6 acceptance gate: the OpenMetrics quantile gauges and
    the recorder's .sca.json latency rows agree to 1e-6 (one shared
    hist_summary()), and the histogram family passes the extended
    format lint (le monotone, +Inf terminal, cumulative counts)."""
    import re

    from fognetsimpp_tpu.runtime.recorder import load_scalars, record_run
    from tools.check_openmetrics import check

    spec, state, net, bounds = _ground_world()
    final, _ = run(spec, state, net, bounds)
    paths = record_run(str(tmp_path), spec, final, scave=False)
    assert check(paths["om"]) == 0
    sca = load_scalars(paths["sca"])
    text = open(paths["om"]).read()
    assert "# TYPE fns_task_latency histogram" in text
    for f in range(spec.n_fogs):
        for q in ("p50", "p95", "p99"):
            m = re.search(
                rf'^fns_task_latency_quantile_ms\{{fog="{f}",q="{q}"\}}'
                r" (\S+)$",
                text, re.M,
            )
            sca_val = sca["modules"]["fog"][f].get(f"lat_{q}_ms")
            if m is None:
                assert sca_val is None  # empty fog: both sides skip
                continue
            assert abs(float(m.group(1)) - sca_val) <= 1e-6
        # bucket series terminate at +Inf and count matches
        m = re.search(
            rf'^fns_task_latency_bucket\{{fog="{f}",le="\+Inf"\}} (\d+)$',
            text, re.M,
        )
        assert m
        assert int(m.group(1)) == sca["modules"]["fog"][f]["lat_count"]
    # global quantiles mirror sca["hist"]
    for q, v in sca["hist"]["quantiles_ms"].items():
        m = re.search(
            rf'^fns_task_latency_quantile_ms\{{q="{q}"\}} (\S+)$',
            text, re.M,
        )
        assert m and abs(float(m.group(1)) - v) <= 1e-6
    # compile-latency observability rides every exposition + .sca.json
    assert "# TYPE fns_compile_seconds_total counter" in text
    assert "compile_cache" in sca


def test_fleet_openmetrics_histogram(tmp_path):
    from fognetsimpp_tpu.parallel import make_mesh, replicate_state
    from fognetsimpp_tpu.parallel.fleet import run_fleet
    from fognetsimpp_tpu.runtime.recorder import record_fleet_run
    from tools.check_openmetrics import check

    spec, state, net, bounds = _build(
        n_users=4, horizon=1.0, telemetry=True, telemetry_hist=True
    )
    batch = replicate_state(spec, state, 8, seed=0)
    final = run_fleet(spec, batch, net, bounds, make_mesh(8))
    paths = record_fleet_run(str(tmp_path), spec, final)
    text = open(paths["om"]).read()
    assert "# TYPE fns_fleet_task_latency histogram" in text
    assert check(paths["om"]) == 0
    sca = json.load(open(paths["sca"]))
    assert sca["hist"]["count"] == int(
        np.asarray(final.telem.lat_hist, np.int64).sum()
    )


# ----------------------------------------------------------------------
# watchdog + flight recorder + live endpoint
# ----------------------------------------------------------------------

def test_watchdog_fires_on_injected_queue_depth_step():
    from fognetsimpp_tpu.telemetry.live import Watchdog

    wd = Watchdog(n_fogs=4, z_threshold=4.0, warmup=3)

    def rows(q):
        return {
            "t": np.asarray([0.1]),
            "q_len_total": np.asarray([q], float),
            "n_busy": np.asarray([2.0]),
            "n_deferred": np.asarray([0.0]),
            "n_completed": np.asarray([1.0]),
            "n_dropped": np.asarray([0.0]),
        }

    fired = []
    for i in range(8):  # stable regime
        fired += wd.update_from_rows(rows(10.0 + 0.1 * (i % 2)), i)
    assert fired == []
    fired = wd.update_from_rows(rows(80.0), 99)  # injected step
    assert any(a["signal"] == "q_depth" for a in fired)
    assert wd.anomalies and wd.anomalies[-1]["ticks_done"] == 99
    # empty chunk (no reservoir rows) is a no-op, not a crash
    assert wd.update_from_rows({"t": np.zeros((0,))}, 100) == []


def test_watchdog_variance_floor_ignores_infinitesimal_wiggle():
    """A signal that sat exactly constant through warmup has zero EWMA
    variance; the z denominator's rel/abs floor keeps its first tiny
    wiggle (one routine drop, a 0.001 busy_frac dip) from paging while
    a genuine step still scores far past the threshold."""
    from fognetsimpp_tpu.telemetry.live import Ewma

    flat = Ewma(warmup=3)
    for _ in range(6):
        assert abs(flat.update(0.0)) <= 1e-12
    assert abs(flat.update(0.01)) < 4.0  # one routine drop: no page
    pinned = Ewma(warmup=3)
    for _ in range(6):
        pinned.update(1.0)
    assert abs(pinned.update(0.999)) < 4.0  # saturated fleet dip
    assert abs(pinned.update(0.2)) > 4.0  # a real collapse still fires


def test_flight_recorder_dump_load_roundtrip_on_nan(tmp_path):
    """A forced-NaN world trips the recorder: the dump bundle
    round-trips through load() with the ring, reason and nonfinite
    detail intact, plus a strict-JSON Perfetto trace twin."""
    from fognetsimpp_tpu.telemetry.live import FlightRecorder, serve_run

    spec, state, net, bounds = _build(
        telemetry=True, telemetry_hist=True, horizon=0.4
    )
    # poison one float leaf: the NaN detector must catch it at the
    # first chunk boundary regardless of engine propagation
    state = state.replace(
        nodes=state.nodes.replace(
            energy=state.nodes.energy.at[0].set(jnp_nan())
        )
    )
    final, status = serve_run(
        spec, state, net, bounds, chunk_ticks=200, port=None,
        dump_dir=str(tmp_path),
    )
    dumps = [p for p in status["dumps"] if "-nan-" in p]
    assert dumps, status["dumps"]
    m = FlightRecorder.load(dumps[0])
    assert m["reason"] == "nan"
    assert any("energy" in k for k in m["detail"]["nonfinite"])
    assert m["ring"] and m["ring"][-1]["state_hash"]
    assert set(m["ring"][0]["rows"]) >= {"t", "q_len_total", "n_dropped"}
    trace = json.load(open(m["trace"]))
    assert "traceEvents" in trace
    # ring round-trip: a dump of the (final) recorder state loads back
    # exactly — the dump above fired mid-run, so compare a fresh dump
    p2 = status["recorder"].dump(str(tmp_path), "manual", spec=spec)
    m2 = FlightRecorder.load(p2)
    assert len(m2["ring"]) == len(status["recorder"].ring)
    np.testing.assert_array_equal(
        m2["ring"][-1]["rows"]["t"],
        status["recorder"].ring[-1]["rows"]["t"],
    )
    assert (
        m2["ring"][-1]["state_hash"]
        == status["recorder"].ring[-1]["state_hash"]
    )


def jnp_nan():
    import jax.numpy as jnp

    return jnp.float32(float("nan"))


def test_postmortem_cli_summarize_and_diff(tmp_path, capsys):
    from fognetsimpp_tpu.telemetry.live import FlightRecorder
    from tools.postmortem import main as pm_main

    ra, rb = FlightRecorder(), FlightRecorder()
    for ticks, ha, hb in ((100, "aaa", "aaa"), (200, "bbb", "ccc")):
        ra.note_chunk(ticks, rows={"t": np.asarray([ticks * 1.0])},
                      state_hash=ha)
        rb.note_chunk(ticks, rows={"t": np.asarray([ticks * 1.0])},
                      state_hash=hb)
    pa = ra.dump(str(tmp_path / "a"), "anomaly")
    pb = rb.dump(str(tmp_path / "b"), "anomaly")
    assert pm_main([pa]) == 0
    out = capsys.readouterr().out
    assert "reason:      anomaly" in out
    assert pm_main(["--diff", pa, pb]) == 0
    out = capsys.readouterr().out
    assert "first state-hash divergence at tick 200" in out


def test_live_endpoint_smoke():
    """Serve one chunk, GET /metrics + /healthz, lint the exposition."""
    from fognetsimpp_tpu.telemetry.live import serve_run
    from tools.check_openmetrics import check_text

    spec, state, net, bounds = _build(
        n_users=4, telemetry=True, telemetry_hist=True, horizon=1.0
    )
    chunks = []
    final, status = serve_run(
        spec, state, net, bounds,
        chunk_ticks=spec.n_ticks,  # exactly one chunk
        port=0, slo_ms=1e6, on_chunk=chunks.append,
    )
    try:
        assert status["chunks"] == 1 and len(chunks) == 1
        url = f"http://127.0.0.1:{status['port']}"
        text = urllib.request.urlopen(url + "/metrics").read().decode()
        assert check_text(text, "live") == 0
        assert "# TYPE fns_task_latency histogram" in text
        assert "fns_run_live_chunks 1" in text
        hz = json.loads(urllib.request.urlopen(url + "/healthz").read())
        assert hz["status"] == "ok"
        assert hz["ticks_done"] == spec.n_ticks
        assert hz["slo_breaches"] == 0
        assert chunks[0]["signals"]["busy_frac"] <= 1.0
    finally:
        status["server"].close()


def test_serve_run_validates_gates():
    from fognetsimpp_tpu.telemetry.live import serve_run

    spec, state, net, bounds = _build()
    with pytest.raises(ValueError, match="telemetry"):
        serve_run(spec, state, net, bounds, port=None)
    spec2, state2, net2, bounds2 = _build(telemetry=True)
    with pytest.raises(ValueError, match="telemetry_hist"):
        serve_run(
            spec2, state2, net2, bounds2, port=None, slo_ms=10.0
        )


# ----------------------------------------------------------------------
# contracts, spec validation, linter, bench trend
# ----------------------------------------------------------------------

def test_contract_and_phase_registry():
    from fognetsimpp_tpu.core.contracts import (
        PHASE_CONTRACTS,
        check_step_contract,
        check_telemetry_contract,
    )

    assert any(
        pc.name == "_phase_latency_hist" for pc in PHASE_CONTRACTS
    )
    spec, state, net, bounds = _build(
        telemetry=True, telemetry_hist=True
    )
    check_telemetry_contract(spec, state)
    check_step_contract(spec, state, net, bounds)


def test_spec_validation_guards():
    with pytest.raises(AssertionError, match="telemetry_hist rides"):
        _build(telemetry_hist=True)
    with pytest.raises(AssertionError, match="derive_acks"):
        _build(telemetry=True, telemetry_hist=True, derive_acks=True)
    with pytest.raises(AssertionError, match="buckets"):
        _build(telemetry=True, telemetry_hist=True, telemetry_hist_bins=1)


def test_openmetrics_linter_histogram_rules(tmp_path):
    from tools.check_openmetrics import check_text

    head = (
        "# HELP fns_h h\n# TYPE fns_h histogram\n"
    )
    good = (
        head
        + 'fns_h_bucket{le="0.1"} 1\nfns_h_bucket{le="1"} 2\n'
        + 'fns_h_bucket{le="+Inf"} 3\nfns_h_sum 4.2\nfns_h_count 3\n'
        + "# EOF\n"
    )
    assert check_text(good) == 0
    # non-cumulative counts
    bad = good.replace('fns_h_bucket{le="1"} 2', 'fns_h_bucket{le="1"} 0')
    assert check_text(bad) == 1
    # missing +Inf terminal
    bad = (
        head + 'fns_h_bucket{le="0.1"} 1\nfns_h_sum 1\nfns_h_count 1\n'
        + "# EOF\n"
    )
    assert check_text(bad) == 1
    # le values out of order
    bad = (
        head
        + 'fns_h_bucket{le="1"} 1\nfns_h_bucket{le="0.1"} 1\n'
        + 'fns_h_bucket{le="+Inf"} 1\nfns_h_sum 1\nfns_h_count 1\n# EOF\n'
    )
    assert check_text(bad) == 1
    # _count disagreeing with the +Inf bucket
    bad = good.replace("fns_h_count 3", "fns_h_count 5")
    assert check_text(bad) == 1
    # missing _sum
    bad = good.replace("fns_h_sum 4.2\n", "")
    assert check_text(bad) == 1
    # bucket without an le label
    bad = (
        head + "fns_h_bucket 1\nfns_h_sum 1\nfns_h_count 1\n# EOF\n"
    )
    assert check_text(bad) == 1
    # missing _count entirely (not just disagreeing)
    bad = good.replace("fns_h_count 3\n", "")
    assert check_text(bad) == 1
    # non-numeric le label is a finding, not a linter traceback
    bad = good.replace('le="0.1"', 'le="abc"')
    assert check_text(bad) == 1


def test_bench_trend_gate(tmp_path):
    """Green on the checked-in BENCH history; red on a fabricated >10%
    regression at the same shape; silent on shape changes."""
    from tools.bench_trend import check, load_rounds, table

    rows = load_rounds(str(Path(__file__).parent / ".."))
    assert rows, "checked-in BENCH_r*.json history went missing"
    assert check(rows) == []
    assert "BENCH_r05.json" in table(rows)
    assert "| r5 |" in table(rows, markdown=True)

    def cap(n, value, dt=0.005):
        p = tmp_path / f"BENCH_r{n:02d}.json"
        p.write_text(json.dumps({
            "parsed": {
                "metric": "m", "value": value, "unit": "d/s",
                "backend": "tpu", "n_users": 10, "n_fogs": 2, "dt": dt,
                "compile_s": 1.0,
            }
        }))

    cap(1, 100.0)
    cap(2, 85.0)  # -15% at the same shape
    rows = load_rounds(str(tmp_path))
    problems = check(rows)
    assert len(problems) == 1 and "15.0%" in problems[0]
    # a shape change (different dt) is a new trajectory, not a regression
    cap(2, 85.0, dt=0.001)
    assert check(load_rounds(str(tmp_path))) == []


def test_bench_trend_policy_backfill(tmp_path):
    """A capture that predates the 'policy' field compares against a
    new capture recording the bench default — the gate must not lose
    its entire history the first round that records the knob."""
    from tools.bench_trend import check, load_rounds

    base = {
        "metric": "m", "unit": "d/s", "backend": "tpu",
        "n_users": 10, "n_fogs": 2, "dt": 0.005, "compile_s": 1.0,
    }
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"parsed": {**base, "value": 100.0}})  # no 'policy'
    )
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps(
            {"parsed": {**base, "value": 80.0, "policy": "min_busy"}}
        )
    )
    problems = check(load_rounds(str(tmp_path)))
    assert len(problems) == 1 and "20.0%" in problems[0]
    # a genuinely different policy is still its own trajectory
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps({"parsed": {**base, "value": 10.0, "policy": "ucb"}})
    )
    assert check(load_rounds(str(tmp_path))) == problems


def test_compile_stats_accounting():
    from fognetsimpp_tpu.compile_cache import compile_stats, note_compile

    before = compile_stats()
    note_compile(1.5, cache_hit=False)
    after = compile_stats()
    assert after["noted_compiles"] == before.get("noted_compiles", 0) + 1
    assert after["cache_misses"] == before["cache_misses"] + 1
    assert (
        after["noted_compile_s_total"]
        >= before.get("noted_compile_s_total", 0.0) + 1.5 - 1e-9
    )
    assert "cache_dir" in after


def test_timeline_counter_tracks():
    """Per-fog queue-depth / busy-frac counter events ride next to the
    task spans: non-negative, finite, per-fog named, strict JSON."""
    from fognetsimpp_tpu.telemetry.timeline import build_trace

    spec, state, net, bounds = _ground_world()
    final, _ = run(spec, state, net, bounds)
    trace = build_trace(spec, final)
    counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    assert counters
    names = {e["name"] for e in counters}
    assert any("queue_depth" in n for n in names)
    assert any("busy_frac" in n for n in names)
    for e in counters:
        (val,) = e["args"].values()
        assert np.isfinite(val) and val >= 0.0
        if "busy_frac" in e["name"]:
            assert val <= 1.0
    # depth staircase: integral task counts, consistent with the final
    # state's own queue length at the last sample
    depth = [
        e["args"]["tasks"] for e in counters
        if e["name"] == "fog0 queue_depth"
    ]
    assert depth and all(d == int(d) for d in depth)
    assert depth[-1] == float(np.asarray(final.fogs.q_len)[0])
